"""Benchmark: MoE-layer forward latency on the local chip.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

``vs_baseline`` is ``null`` (and the record carries a ``partial`` field,
with exit code 3) when the xla comparison leg never completed — partial
records are machine-distinguishable from genuine no-speedup results.

The headline config mirrors the reference's benchmark setting
(``csrc/flashmoe_config.json``: E=64, top-k=2, H=2048, I=2048, S=8192) run
through the fused Pallas path.  ``vs_baseline`` is the speedup of the fused
path over the naive XLA dense-dispatch implementation measured in the same
run on the same chip — the analogue of the reference's comparisons against
Megatron-style baselines (``README.md:27``).

Usage:
  python bench.py              # headline number (one JSON line)
  python bench.py --config token_scaling --trials 50
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import time

import jax
import jax.numpy as jnp

from flashmoe_tpu.config import BENCH_CONFIGS, MoEConfig
from flashmoe_tpu.models.reference import init_moe_params
from flashmoe_tpu.ops.moe import moe_layer


def _chained(cfg: MoEConfig, use_pallas: bool, iters: int):
    """Jit `iters` dependent MoE-layer applications ending in a scalar
    readback.  On remote-tunneled backends (axon) `block_until_ready` does
    not synchronize, and the host round-trip is ~100x one layer — so the
    per-iteration time comes from differencing two chain lengths."""

    def run(p, x):
        def body(x, _):
            o = moe_layer(p, x, cfg, use_pallas=use_pallas)
            return o.out.astype(x.dtype), None
        x, _ = jax.lax.scan(body, x, None, length=iters)
        return x.astype(jnp.float32).sum()

    return jax.jit(run)


def _time_chain(fn, p, x, trials):
    float(fn(p, x))  # compile + warm
    times = []
    for _ in range(trials):
        t0 = time.perf_counter()
        float(fn(p, x))
        times.append(time.perf_counter() - t0)
    times.sort()
    return times[len(times) // 2]


# Progressive results: filled in as each path finishes so the deadline
# handler can emit a partial (but real) record instead of value: -1.
# Two rounds of driver-captured -1 (BENCH_r01/r02) motivated this.
# Keyed by the measurement's own config/name so a sweep can never mix
# timings from different points into one record.
_PARTIAL: dict = {}


def bench_moe_layer(cfg: MoEConfig, trials: int, chain: int = 16,
                    name: str = "", candidates: bool = True):
    # clear before any slow work so a failure during setup can never
    # re-emit the previous sweep point's (already-printed) timings
    _PARTIAL.clear()
    _PARTIAL.update(cfg=cfg, name=name)
    key = jax.random.PRNGKey(0)
    params = init_moe_params(key, cfg)
    params = jax.tree_util.tree_map(lambda p: p.astype(cfg.dtype), params)
    x = jax.random.normal(
        jax.random.PRNGKey(1), (cfg.tokens, cfg.hidden_size), cfg.dtype
    )
    def per_iter(c, use_pallas):
        """Per-iteration time via two chain lengths (single definition —
        all legs must share the same differencing arithmetic)."""
        t1 = _time_chain(_chained(c, use_pallas, 1), params, x, trials)
        tn = _time_chain(_chained(c, use_pallas, chain), params, x, trials)
        return max(tn - t1, 1e-9) / (chain - 1)

    out = {}
    for pname, use_pallas in (("fused", True), ("xla", False)):
        out[pname] = per_iter(cfg, use_pallas)
        _PARTIAL[pname] = out[pname]
    # third candidate: the gather-fused inference kernel (dispatch built
    # in-kernel, no [E, C, H] HBM buffer).  Proven paths are already in
    # _PARTIAL, so a Mosaic failure or a deadline here costs nothing —
    # and if it wins on silicon, the headline reports the best fused
    # number the framework has (the measured-winner policy of VERDICT
    # r3 #4, applied at bench time).  Gate on the RESOLVED routing (env
    # opt-in included) so the candidate never re-times the kernel the
    # fused leg already ran; sweeps skip it (one shared deadline).
    from flashmoe_tpu.ops.moe import _gather_fused

    if candidates and not cfg.is_training and not _gather_fused(cfg):
        try:
            tg = per_iter(cfg.replace(gather_fused=True), True)
            _PARTIAL["gather_fused"] = tg
            if tg < out["fused"]:
                out["fused"] = tg
                _PARTIAL["fused"] = tg
                _PARTIAL["fused_variant"] = "gather"
        except Exception as e:  # noqa: BLE001 — candidate only
            print(f"# gather-fused candidate skipped: "
                  f"{type(e).__name__}: {str(e)[:200]}",
                  file=sys.stderr, flush=True)
    return out["fused"], out["xla"]


def _layer_flops(cfg: MoEConfig) -> float:
    """Model FLOPs of one MoE layer forward: gate GEMM + routed expert
    FFN (2 or 3 GEMMs per token-slot)."""
    gate = 2.0 * cfg.tokens * cfg.hidden_size * cfg.num_experts
    rows = cfg.tokens * cfg.expert_top_k
    gemms = 3 if cfg.gated_ffn else 2
    ffn = gemms * 2.0 * rows * cfg.hidden_size * cfg.intermediate_size
    return gate + ffn


def _mxu_util(cfg: MoEConfig, seconds: float) -> float | None:
    """Achieved fraction of peak MXU throughput — the TPU analogue of the
    reference's headline SM-utilization metric (``README.md:43-44``,
    ``plots/sm_util.png``), computed from model FLOPs over wall time."""
    from flashmoe_tpu.parallel.topology import _PEAK_TFLOPS, tpu_generation

    peak = _PEAK_TFLOPS.get(tpu_generation(jax.devices()[0]))
    if peak is None or seconds <= 0:
        return None
    return _layer_flops(cfg) / seconds / (peak * 1e12)


def _planner_fields(cfg, t_fused, t_xla) -> dict:
    """Predicted-vs-measured fields for this record: the analytical
    planner's prediction of the measured path, the signed relative
    error, and the planner's predicted winner at this config — every
    bench run doubles as a calibration point for the cost model
    (``docs/PLANNER.md``).  Empty off known generations (the virtual
    CPU backend has no roofline to predict against; pin
    ``FLASHMOE_TPU_GEN`` to force one)."""
    from flashmoe_tpu.parallel.topology import _PEAK_TFLOPS, tpu_generation
    from flashmoe_tpu.planner.model import predict_paths

    gen = tpu_generation(jax.devices()[0])
    if gen not in _PEAK_TFLOPS:
        gen = os.environ.get("FLASHMOE_TPU_GEN", "")
        if gen not in _PEAK_TFLOPS:
            return {}
    preds = {p.path: p for p in predict_paths(cfg, 1, gen)}
    measured_path = ("gather" if _PARTIAL.get("fused_variant") == "gather"
                     else "explicit")
    out = {"planner_gen": gen}
    winner = next((p for p in preds.values() if p.feasible), None)
    if winner is not None:
        out["predicted_winner"] = winner.path
    p = preds.get(measured_path)
    if p is not None:
        out["predicted_path"] = measured_path
        out["predicted_ms"] = round(p.total_ms, 3)
        out["prediction_error"] = round(
            t_fused * 1e3 / p.total_ms - 1.0, 3)
    px = preds.get("xla")
    if t_xla and px is not None:
        out["xla_predicted_ms"] = round(px.total_ms, 3)
        out["xla_prediction_error"] = round(
            t_xla * 1e3 / px.total_ms - 1.0, 3)
    return out


def _wire_fields(cfg: MoEConfig) -> dict:
    """Wire-dtype identity + modeled bytes saved for one bench record.

    ``wire_modeled_comm_mb`` is the byte model's EP-exchange traffic at
    this config's nominal ep width (0 at ep=1 — the single-chip headline
    has no a2a); ``wire_modeled_comm_saved_mb`` is the drop vs the same
    config with the wire off."""
    from flashmoe_tpu.analysis import path_costs
    from flashmoe_tpu.ops import wire as wr

    out = {"wire_dtype": wr.canonical_name(cfg.wire_dtype),
           "wire_dtype_combine": wr.canonical_name(cfg.wire_dtype_combine)}
    if cfg.wire_dtype is None and cfg.wire_dtype_combine is None:
        return out
    d = max(cfg.ep, 1)
    path = "ragged" if cfg.moe_backend == "ragged" else "explicit"
    comm = path_costs(cfg, path, d_world=d).comm_bytes
    raw = path_costs(
        cfg.replace(wire_dtype=None, wire_dtype_combine=None),
        path, d_world=d).comm_bytes
    out["wire_modeled_comm_mb"] = round(comm / 2**20, 3)
    out["wire_modeled_comm_saved_mb"] = round((raw - comm) / 2**20, 3)
    return out


def _quant_fields(cfg: MoEConfig) -> dict:
    """Quantized-expert-store identity + modeled weight bytes saved for
    one bench record.  ``quant_modeled_weight_mb`` is one full stream
    of this rank's expert weights at the store width (scale sidecars
    included); ``quant_modeled_weight_saved_mb`` the drop vs the same
    stream at full precision — the term the fused rowwin race and every
    HBM-bound path move by."""
    from flashmoe_tpu.analysis import expert_weight_stream_bytes
    from flashmoe_tpu.quant import core as qcore

    out = {"expert_quant": qcore.canonical_name(cfg.expert_quant)}
    if cfg.expert_quant is None:
        return out
    nlx = cfg.num_experts // max(cfg.ep, 1)
    on = expert_weight_stream_bytes(cfg, nlx)
    off = expert_weight_stream_bytes(
        cfg.replace(expert_quant=None), nlx)
    out["quant_modeled_weight_mb"] = round(on / 2**20, 3)
    out["quant_modeled_weight_saved_mb"] = round((off - on) / 2**20, 3)
    return out


def _emit(cfg, name, t_fused, t_xla, note: str | None = None):
    """One JSON record.  ``t_xla=None`` marks a partial measurement (the
    xla leg never completed): vs_baseline is ``null`` — not a number a
    driver could mistake for a genuine no-speedup result — and the record
    carries an explicit ``partial`` field (advisor round-3 #4)."""
    try:
        util = _mxu_util(cfg, t_fused)
    except Exception:  # noqa: BLE001 — never lose the record over the label
        util = None
    rec = {
        "metric": f"moe_layer_fwd_ms[{name}:E={cfg.num_experts},"
                  f"k={cfg.expert_top_k},H={cfg.hidden_size},"
                  f"I={cfg.intermediate_size},S={cfg.tokens},"
                  f"{jnp.dtype(cfg.dtype).name}]",
        "value": round(t_fused * 1e3, 3),
        "unit": "ms",
        "vs_baseline": round(t_xla / t_fused, 3) if t_xla else None,
        "tokens_per_sec_per_chip": round(cfg.tokens / t_fused),
        "xla_path_ms": round(t_xla * 1e3, 3) if t_xla else None,
        "mxu_util": round(util, 4) if util is not None else None,
        "backend": jax.default_backend(),
    }
    if "gather_fused" in _PARTIAL:
        rec["gather_fused_ms"] = round(_PARTIAL["gather_fused"] * 1e3, 3)
        rec["fused_variant"] = _PARTIAL.get("fused_variant", "explicit")
    # path/d identify this measurement for the planner's measured-winner
    # override (planner/select.py:_bench_record_latencies): the headline
    # bench times the single-chip (d=1) kernels.  a2a_chunks rides the
    # identity like the wire knobs: a chunk-pipelined timing never
    # overrides a serial selection (and vice versa)
    rec["path"] = ("gather" if _PARTIAL.get("fused_variant") == "gather"
                   else "explicit")
    rec["d"] = 1
    rec["a2a_chunks"] = cfg.a2a_chunks or 1
    # wire-dtype knobs are part of the measurement identity (a
    # compressed timing never overrides an uncompressed selection), and
    # the modeled EP comm bytes at the config's nominal ep width show
    # what the wire saves — drift monitoring then covers the
    # compressed paths with their own keys
    try:
        rec.update(_wire_fields(cfg))
    except Exception as e:  # noqa: BLE001 — never lose the record
        rec["wire_error"] = f"{type(e).__name__}: {str(e)[:120]}"
    try:
        # quantized-store identity rides every record like the wire
        # knobs: an int8-weights timing never overrides a
        # full-precision selection (planner/select.py)
        rec.update(_quant_fields(cfg))
    except Exception as e:  # noqa: BLE001 — never lose the record
        rec["quant_error_field"] = f"{type(e).__name__}: {str(e)[:120]}"
    try:
        rec.update(_planner_fields(cfg, t_fused, t_xla))
    except Exception as e:  # noqa: BLE001 — never lose the record
        rec["planner_error"] = f"{type(e).__name__}: {str(e)[:120]}"
    # drift monitor: every bench measurement is a calibration point —
    # the planner.drift decision (and its warning past the threshold)
    # closes the predict -> measure -> correct loop (docs/OBSERVABILITY.md)
    if rec.get("predicted_ms"):
        try:
            from flashmoe_tpu.planner.drift import record_drift

            dr = record_drift(cfg, rec["path"], t_fused * 1e3,
                              d=rec["d"], gen=rec.get("planner_gen"),
                              predicted_ms=rec["predicted_ms"])
            rec["drift_exceeded"] = dr.exceeded
            if t_xla and rec.get("xla_predicted_ms"):
                record_drift(cfg, "xla", t_xla * 1e3, d=rec["d"],
                             gen=rec.get("planner_gen"),
                             predicted_ms=rec["xla_predicted_ms"],
                             warn=False)
        except Exception as e:  # noqa: BLE001 — never lose the record
            rec["drift_error"] = f"{type(e).__name__}: {str(e)[:120]}"
    if note:
        rec["partial"] = note
    print(json.dumps(rec), flush=True)
    _flush_observability(rec)
    # consumed: a late SIGALRM must not re-emit this record as "partial"
    _PARTIAL.clear()


# Observability artifact dir (--obs-dir / FLASHMOE_OBS_DIR): every
# emitted record appends to bench_records.jsonl and new telemetry
# decisions (planner.path_select, planner.drift) drain into
# decisions.jsonl — both are inputs `python -m flashmoe_tpu.observe`
# summarizes.  [dir, decisions-already-written] so sweep points never
# duplicate decisions.
_OBS: list = [None, 0]

# Perf-sentry collection (--regression): [history path or None, the
# run's emitted records].  Armed in main(); _finish_regression()
# appends ONE run entry to obs/history.jsonl when the mode completes —
# skipped/partial/error records never enter the baseline
# (telemetry_plane/regression.py filters them).
_REG: list = [None, []]


def _finish_regression():
    if not _REG[0] or not _REG[1]:
        return
    try:
        from flashmoe_tpu.telemetry_plane import regression as reg

        points = reg.collect_points(_REG[1])
        entry = reg.append_run(_REG[0], points,
                               meta={"argv": sys.argv[1:]})
        if entry:
            print(f"# perf sentry: appended {len(points)} metric "
                  f"point(s) to {_REG[0]}", file=sys.stderr, flush=True)
    except Exception as e:  # noqa: BLE001 — history is best-effort
        print(f"# regression history write failed: "
              f"{type(e).__name__}: {str(e)[:120]}",
              file=sys.stderr, flush=True)


def _flush_observability(rec: dict):
    if _REG[0] is not None:
        _REG[1].append(rec)
    if not _OBS[0]:
        return
    try:
        from flashmoe_tpu.utils.telemetry import metrics

        os.makedirs(_OBS[0], exist_ok=True)
        with open(os.path.join(_OBS[0], "bench_records.jsonl"), "a") as f:
            f.write(json.dumps(rec) + "\n")
        _OBS[1] = metrics.dump_decisions_jsonl(
            os.path.join(_OBS[0], "decisions.jsonl"), start=_OBS[1])
    except Exception as e:  # noqa: BLE001 — artifacts are best-effort
        print(f"# obs-dir write failed: {type(e).__name__}: "
              f"{str(e)[:120]}", file=sys.stderr, flush=True)


def _bench_checkpoint(trials: int):
    """Step-loop checkpoint overhead: blocking time of a sync save
    (serialize+fsync+rename on the loop) vs an async save (host snapshot
    only; the writer thread pays the rest).  One JSON record whose
    ``vs_baseline`` is the sync/async blocking-time ratio — the
    speedup the drain-safe async path buys the step loop
    (docs/RESILIENCE.md, preemption section)."""
    import shutil
    import tempfile

    from flashmoe_tpu.runtime import checkpoint as ckpt
    from flashmoe_tpu.runtime.trainer import TrainState

    state = TrainState(
        params={"w": jnp.zeros((512, 512), jnp.float32),
                "b": jnp.zeros((512,), jnp.float32)},
        opt_state={"m": jnp.zeros((512, 512), jnp.float32),
                   "v": jnp.zeros((512, 512), jnp.float32)},
        step=jnp.zeros((), jnp.int32))
    tmp = tempfile.mkdtemp(prefix="flashmoe_ckpt_bench_")
    sync_s, async_s = [], []
    try:
        d_sync = os.path.join(tmp, "sync")
        d_async = os.path.join(tmp, "async")
        # one throwaway save per directory: manager construction and
        # tracemetadata warmup must not be billed to either side
        ckpt.save(d_sync, state, step=0)
        ckpt.save(d_async, state, step=0)
        step = 0
        for _ in range(trials):
            step += 1
            t0 = time.perf_counter()
            ckpt.save(d_sync, state, step=step)
            sync_s.append(time.perf_counter() - t0)
            t0 = time.perf_counter()
            ckpt.save(d_async, state, step=step, blocking=False)
            async_s.append(time.perf_counter() - t0)
            ckpt.wait_for_saves()  # drain between points: measure the
            # enqueue cost, not queue-full newest-wins replacement
        errors = ckpt.wait_for_saves()
        sync_ms = sorted(sync_s)[len(sync_s) // 2] * 1e3
        async_ms = sorted(async_s)[len(async_s) // 2] * 1e3
        rec = {
            "metric": f"ckpt_step_block_ms[async,trials={trials}]",
            "value": round(async_ms, 3),
            "unit": "ms",
            "vs_baseline": round(sync_ms / async_ms, 3) if async_ms
            else None,
            "sync_block_ms": round(sync_ms, 3),
            "async_verified": all(
                ckpt.verify(d_async, s) for s in range(1, step + 1)
                if os.path.isdir(ckpt.step_dir(d_async, s))),
            "async_errors": len(errors),
            "backend": jax.default_backend(),
        }
        print(json.dumps(rec), flush=True)
        _flush_observability(rec)
    finally:
        ckpt.close_manager(os.path.join(tmp, "sync"))
        ckpt.close_manager(os.path.join(tmp, "async"))
        shutil.rmtree(tmp, ignore_errors=True)


def _bench_profile(obs_dir: str | None, *, steps: int = 1,
                   quick: bool = False):
    """Phase-level profile + cost ledger (``--profile``): run the
    flat/hierarchical/ragged x {serial, chunked} x {wire off, e4m3}
    matrix with ``profile_phases=True`` on the virtual CPU mesh (or real
    chips when FLASHMOE_OVERLAP_TPU=1), joining every measured phase
    against the planner's per-phase prediction.  One JSON record per
    matrix point; with ``--obs-dir`` the artifacts land there —
    ``ledger.jsonl`` + ``trace.json`` (open in ui.perfetto.dev) +
    ``flight.jsonl`` — and ``python -m flashmoe_tpu.observe --ledger``
    renders the drift table."""
    from flashmoe_tpu.profiler.ledger import run_ledger_matrix

    on_tpu = os.environ.get("FLASHMOE_OVERLAP_TPU") == "1"
    if not on_tpu:
        from __graft_entry__ import _force_cpu_devices
        _force_cpu_devices(8)
        devices = jax.devices("cpu")[:8]
    else:
        devices = jax.devices()
    records = run_ledger_matrix(obs_dir, quick=quick, steps=steps,
                                devices=devices)
    for rec in records:
        print(json.dumps(rec), flush=True)
        _flush_observability(rec)


def _bench_serve(loads, *, requests: int, max_batch: int,
                 telemetry_port: int | None = None,
                 speculate: int | None = None):
    """Offered-load serving sweep (``--serve``): the continuous-
    batching engine (flashmoe_tpu/serving/) driven by a seeded arrival
    trace at each offered-load point, one JSON record per point with
    throughput (tokens/sec), TTFT/TPOT percentiles, queue depth, cache
    occupancy, and evictions — the latency/throughput curve.  CPU-
    sized model; identical procedure on real chips.
    ``telemetry_port`` arms the live scrape plane for the sweep's
    duration; each record then carries a mid-sweep ``/metrics``
    self-scrape (``telemetry_scrape``).  ``speculate`` (``--serve
    --speculate K``) arms speculative decoding at ``draft_tokens=K``:
    each record gains a ``spec=kK`` identity tag, the realized
    ``accept_rate`` / ``spec_tokens_per_step``, an equal-SLO TPOT
    comparison against a per-point non-speculative baseline, and the
    asserted ``bit_equal_to_baseline`` exactness bit."""
    from flashmoe_tpu.serving.loadgen import serve_load_sweep

    for rec in serve_load_sweep(loads, n_requests=requests,
                                max_batch=max_batch,
                                telemetry_port=telemetry_port,
                                speculate=speculate):
        print(json.dumps(rec), flush=True)
        _flush_observability(rec)


def _bench_fabric(loads, *, requests: int, max_batch: int,
                  telemetry_port: int | None = None,
                  vclock: bool = False, wire: str = "inproc"):
    """Disaggregated-fabric offered-load sweep (``--fabric``): the
    :class:`~flashmoe_tpu.fabric.engine.ServingFabric` driven over
    mocked 1/2/4-replica worlds (``FLASHMOE_MOCK_FABRIC``, set per
    point and restored), one JSON record per (replica count, load
    point) with throughput, TTFT/TPOT percentiles, KV-handoff count and
    modeled DCN cost, and the router's placement histogram.  Host+CPU
    like ``--serve``; identical procedure on real multi-host serving.

    ``vclock`` (``--vclock``): step each point on the fabric's virtual
    clock behind the front door — TTFT/TPOT are measured UNDER the
    modeled DCN delay and each record adds the measured-vs-priced
    handoff fields plus the per-request attribution rollup
    (docs/OBSERVABILITY.md 'Virtual clock').

    ``wire`` (``--wire tcp``): every KV handoff crosses a real
    localhost socket; the record identity gains a ``wire=tcp`` tag so
    the sentry baselines socket and in-process throughput apart."""
    from flashmoe_tpu.serving.loadgen import fabric_load_sweep

    for rec in fabric_load_sweep(loads, n_requests=requests,
                                 max_batch=max_batch,
                                 telemetry_port=telemetry_port,
                                 vclock=vclock, wire=wire):
        print(json.dumps(rec), flush=True)
        _flush_observability(rec)


def _bench_fabric_faults():
    """Serving fault-tolerance sweep (``--fabric --faults``): every
    fault on the serving recovery ladder drilled end to end against a
    mocked 2-replica fabric, one JSON record per fault with recovery
    latency, migrated-request count, handoff retry/corrupt totals and
    the trace-contiguity verdict, plus one brownout record whose
    headline value is the shed fraction.  Host+CPU like ``--fabric``;
    identical drills on real multi-host serving."""
    from flashmoe_tpu.serving.loadgen import fabric_fault_sweep

    for rec in fabric_fault_sweep():
        print(json.dumps(rec), flush=True)
        _flush_observability(rec)


def _bench_overlap(ep: int, trials: int, *, path: str | None = None,
                   wire_dtype: str | None = None,
                   wire_combine: str | None = None,
                   a2a_chunks: int | None = None):
    """Overlap efficiency on an ep-way mesh (BASELINE.json metric 3),
    per chunk count: one record for the serial schedule and one per
    chunked-pipeline depth (``MoEConfig.a2a_chunks``), each reporting
    the measured efficiency next to its analytic bound
    (``overlap.chunked_overlap_bound`` for the chunked XLA schedules,
    ``overlap.overlap_bound`` for the fused kernel) with the
    predicted-vs-measured overlap fraction validated through the drift
    monitor (``planner.overlap_drift``).

    Multi-chip hardware is absent in this container, so the mesh is the
    virtual 8-device CPU backend (interpret-mode kernels) unless
    ``FLASHMOE_OVERLAP_TPU=1`` — the procedure is identical on real chips.
    See parallel/overlap.py for the metric definition.
    """
    import os

    from flashmoe_tpu.parallel.mesh import make_mesh
    from flashmoe_tpu.parallel.overlap import measure_overlap

    on_tpu = os.environ.get("FLASHMOE_OVERLAP_TPU") == "1"
    if not on_tpu:
        from __graft_entry__ import _force_cpu_devices
        _force_cpu_devices(ep)
        devices = jax.devices("cpu")[:ep]
    else:
        devices = jax.devices()[:ep]
    cfg = MoEConfig(
        num_experts=2 * ep, expert_top_k=2, hidden_size=256,
        intermediate_size=512, sequence_len=256 * ep, capacity_factor=1.0,
        drop_tokens=True, ep=ep,
        dtype=jnp.float32 if not on_tpu else jnp.bfloat16,
        wire_dtype=wire_dtype, wire_dtype_combine=wire_combine,
    )
    mesh = make_mesh(cfg, dp=1, devices=devices)
    # off-hardware, interpret-mode Pallas is ~100x slower than compiled XLA,
    # which would poison the ratio — the virtual mesh measures the collective
    # path (compiled end to end); real chips measure the fused kernel,
    # UNLESS wire/chunk knobs are set: those are XLA-transport features
    # (the fused kernel rejects wire dtypes and ignores a2a_chunks), so
    # the measurement they ask for is the collective schedule
    if path is None:
        path = "fused" if on_tpu else "collective"
        if path == "fused" and (wire_dtype or wire_combine or a2a_chunks):
            print("# wire/a2a-chunks knobs are XLA-transport only: "
                  "measuring the collective path instead of the fused "
                  "kernel", file=sys.stderr, flush=True)
            path = "collective"
    nlx = cfg.num_experts // ep
    if path == "fused":
        chunk_list = [1]  # the kernel overlaps in-kernel; no chunk knob
    elif a2a_chunks:
        chunk_list = sorted({1} | {n for n in (a2a_chunks,)
                                   if nlx % n == 0})
        if a2a_chunks > 1 and nlx % a2a_chunks:
            print(f"# a2a_chunks={a2a_chunks} does not divide "
                  f"nLx={nlx}; measuring serial only",
                  file=sys.stderr, flush=True)
    else:
        chunk_list = [1] + [n for n in (2, 4) if nlx % n == 0]

    from flashmoe_tpu.parallel.topology import tpu_generation

    gen = tpu_generation(devices[0])
    for n in chunk_list:
        m = measure_overlap(cfg, mesh, path=path, trials=trials,
                            interpret=False,
                            a2a_chunks=n if path != "fused" else None)
        rec = {
            "metric": f"overlap_efficiency[{path},ep={ep},"
                      f"E={cfg.num_experts},chunks={n},"
                      f"{'tpu' if on_tpu else 'virtual_cpu'}]",
            "value": round(m["overlap_efficiency"], 3),
            "unit": "ratio_vs_serialized",
            "vs_baseline": round(m["overlap_efficiency"], 3),
            "t_overlapped_ms": round(m["t_overlapped_ms"], 3),
            "t_compute_ms": round(m["t_compute_ms"], 3),
            "t_comm_ms": round(m["t_comm_ms"], 3),
            # what one pipeline stage occupies (the moe.a2a_dispatch.k /
            # moe.expert.k trace spans, averaged) — the observe phase
            # breakdown then shows per-chunk pipeline occupancy
            "per_chunk_a2a_ms": round(m["t_comm_ms"] / n, 3),
            "per_chunk_expert_ms": round(m["t_compute_ms"] / n, 3),
            "a2a_chunks": n,
            "path": path,
        }
        rec.update(_wire_fields(cfg))
        if n == 1:
            try:
                rec.update(_skew_metrics(cfg, ep, m))
            except Exception as e:  # noqa: BLE001 — stands alone
                rec["skew_error"] = f"{type(e).__name__}: {str(e)[:120]}"
        try:
            if gen in ("v4", "v5e", "v5p", "v6e"):
                if path == "fused":
                    from flashmoe_tpu.parallel.overlap import overlap_bound

                    b = overlap_bound(
                        cfg, ep, gen,
                        fuse_combine=os.environ.get(
                            "FLASHMOE_FUSED_COMBINE") == "1")
                    # the number this measurement is judged against
                    # (BASELINE.md round-5 note) — reported side by
                    # side, never in isolation; resolved for the FFN
                    # schedule that will actually run
                    rec["expected_bound"] = round(
                        b["overlap_efficiency_bound"], 3)
                    rec["expected_bound_schedule"] = b["schedule"]
                else:
                    from flashmoe_tpu.parallel.overlap import (
                        chunked_overlap_bound,
                    )

                    b = chunked_overlap_bound(cfg, ep, gen, n, path=path)
                    rec["expected_bound"] = round(
                        b["overlap_efficiency_bound"], 3)
                # measured-vs-analytic overlap fraction through the
                # drift monitor: the loop that tells us when the
                # pipeline model (and the chunk picks it drives) has
                # drifted from what the hardware delivers
                from flashmoe_tpu.planner.drift import record_overlap_drift

                dr = record_overlap_drift(
                    path, m["overlap_efficiency"],
                    predicted_fraction=rec["expected_bound"],
                    gen=gen, d=ep, chunks=n)
                rec["overlap_drift_exceeded"] = dr.exceeded
        except Exception as e:  # noqa: BLE001 — but record the breakage
            rec["bound_error"] = f"{type(e).__name__}: {str(e)[:120]}"
        print(json.dumps(rec), flush=True)
        _flush_observability(rec)


def _skew_metrics(cfg: MoEConfig, ep: int, m: dict) -> dict:
    """Ring-vs-predicted-order stall of the fused kernel's static slab
    schedule AT THIS BENCH'S CONFIG — the skew_sim discrete-event model
    (scripts/skew_sim.py) keyed to the measured per-slab compute time
    and this config's slab size, reported alongside the overlap number
    instead of living only in a standalone simulation (VERDICT r4 #6).
    Scenario: one source behind an 8x-slow link (the payload-skew case
    of BASELINE config #5)."""
    import sys as _sys

    # insert only if absent: an unconditional insert accumulated one
    # duplicate entry per overlap run and kept scripts/ ahead of every
    # other import root (module-shadowing risk; ADVICE round 5)
    _scripts = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "scripts")
    if _scripts not in _sys.path:
        _sys.path.insert(0, _scripts)
    import skew_sim

    from flashmoe_tpu.parallel.ep import local_capacity

    nlx = cfg.num_experts // ep
    s_loc = max(cfg.tokens // ep, 1)
    slab_mb = (nlx * local_capacity(cfg, s_loc) * cfg.hidden_size
               * jnp.dtype(cfg.dtype).itemsize) / 1e6
    t_c = m["t_compute_ms"] / ep  # per-slab compute share
    adj = skew_sim.torus_adj(ep)
    adj.alpha[0, :] *= 8.0
    adj.beta[0, :] *= 8.0
    adj.alpha[0, 0] = adj.beta[0, 0] = 0.0
    r = skew_sim.simulate(adj, adj, slab_mb, t_c)
    return {
        "skew8_ring_stall_ms": round(r["ring"] - r["oracle"], 4),
        "skew8_pred_stall_ms": round(r["pred"] - r["oracle"], 4),
        "skew8_arrival_spread_ms": round(r["spread"], 4),
        "skew_slab_mb": round(slab_mb, 3),
    }


def _sweep_ep(trials: int, wire_dtype: str | None = None,
              wire_combine: str | None = None,
              a2a_chunks: int | None = None):
    """Weak-scaling sweep over the ep axis: per-rank tokens held constant
    while the mesh grows (the reference's ``scaling_gpus_8`` axis).
    Virtual CPU mesh when multi-chip hardware is absent; identical
    procedure on real chips (FLASHMOE_OVERLAP_TPU=1).  ``wire_dtype`` /
    ``wire_combine`` compress the EP exchange payload (ops/wire.py) and
    ``a2a_chunks`` runs the chunked double-buffered pipeline — the
    workloads those knobs exist for, so the sweep honors them."""
    import os

    from flashmoe_tpu.parallel.mesh import make_mesh
    from flashmoe_tpu.parallel.overlap import _time_chained
    from flashmoe_tpu.parallel.ep import ep_moe_layer
    from flashmoe_tpu.models.reference import init_moe_params

    on_tpu = os.environ.get("FLASHMOE_OVERLAP_TPU") == "1"
    if not on_tpu:
        from __graft_entry__ import _force_cpu_devices
        _force_cpu_devices(8)
        devs = jax.devices("cpu")
    else:
        devs = jax.devices()
    base_t = None
    for ep in (2, 4, 8):
        if len(devs) < ep:
            break
        chunks = (a2a_chunks if a2a_chunks and a2a_chunks > 1
                  and (16 // ep) % a2a_chunks == 0 else None)
        if a2a_chunks and chunks is None and a2a_chunks > 1:
            print(f"# ep={ep}: a2a_chunks={a2a_chunks} does not divide "
                  f"nLx={16 // ep}; measuring serial", file=sys.stderr,
                  flush=True)
        cfg = MoEConfig(
            num_experts=16, expert_top_k=2, hidden_size=256,
            intermediate_size=512, sequence_len=256 * ep,
            capacity_factor=1.0, drop_tokens=True, ep=ep,
            dtype=jnp.bfloat16 if on_tpu else jnp.float32,
            wire_dtype=wire_dtype, wire_dtype_combine=wire_combine,
            a2a_chunks=chunks,
        )
        mesh = make_mesh(cfg, dp=1, devices=devs[:ep])
        params = init_moe_params(jax.random.PRNGKey(0), cfg)
        params = jax.tree_util.tree_map(
            lambda p: p.astype(cfg.dtype), params)
        x = jax.random.normal(
            jax.random.PRNGKey(1), (cfg.tokens, cfg.hidden_size), cfg.dtype)
        fn = lambda c: ep_moe_layer(params, c, cfg, mesh,
                                    use_pallas=on_tpu).out
        t = _time_chained(fn, x, trials=trials, chain=8)
        base_t = base_t or t
        rec = {
            "metric": f"weak_scaling_ms[collective,ep={ep},"
                      f"tokens_per_rank=256,"
                      f"{'tpu' if on_tpu else 'virtual_cpu'}]",
            "value": round(t * 1e3, 3),
            "unit": "ms",
            "vs_baseline": round(base_t / t, 3),  # weak-scaling efficiency
            "a2a_chunks": cfg.a2a_chunks or 1,
        }
        rec.update(_wire_fields(cfg))
        print(json.dumps(rec), flush=True)


def _bench_scaling(trials: int, *, wire_dtype=None, wire_combine=None,
                   wire_dcn=None, a2a_chunks=None):
    """Weak-scaling sweep over mocked 1/2/4/8-slice meshes (ISSUE 13).

    The 8-rank mesh (virtual CPU, or real chips under
    FLASHMOE_OVERLAP_TPU=1) is partitioned into n "slices" per point
    via ``FLASHMOE_MOCK_SLICES`` — the same detection path a real
    multislice bootstrap runs (``topology.slice_structure``) — and the
    collective layer runs the two-stage hierarchical exchange at
    ``dcn_inner = 8 // n`` (flat at n=1, and at n=8 where one rank per
    slice degenerates to flat).  Per point one JSON record carries the
    measured per-step latency, the planner's slices=n prediction
    through the drift monitor (generation pinned by the backend or
    FLASHMOE_TPU_GEN; prediction fields absent otherwise, like the
    headline bench), the modeled per-hop wire bytes (ICI vs DCN row
    sizes — ``wire_dtype_dcn`` shrinks the dcn hop only) and DCN
    message counts (flat vs hierarchical aggregation), and the
    weak-scaling efficiency vs the 1-slice point."""
    from flashmoe_tpu.analysis import a2a_transport_cost
    from flashmoe_tpu.models.reference import init_moe_params
    from flashmoe_tpu.parallel.ep import ep_moe_layer
    from flashmoe_tpu.parallel.mesh import make_mesh
    from flashmoe_tpu.parallel.overlap import _time_chained
    from flashmoe_tpu.parallel.topology import (
        _PEAK_TFLOPS, slice_structure, tpu_generation,
    )
    from flashmoe_tpu.planner.model import predict_paths, slab_bytes

    on_tpu = os.environ.get("FLASHMOE_OVERLAP_TPU") == "1"
    if not on_tpu:
        from __graft_entry__ import _force_cpu_devices
        _force_cpu_devices(8)
        devs = jax.devices("cpu")[:8]
    else:
        devs = jax.devices()[:8]
    d = len(devs)
    gen = tpu_generation(devs[0])
    if gen not in _PEAK_TFLOPS:
        gen = os.environ.get("FLASHMOE_TPU_GEN", "")
    chunks = (a2a_chunks if a2a_chunks and a2a_chunks > 1
              and (16 // d) % a2a_chunks == 0 else None)
    if a2a_chunks and a2a_chunks > 1 and chunks is None:
        # the _sweep_ep convention: a dropped knob is announced, never
        # silently measured serial
        print(f"# --scaling: a2a_chunks={a2a_chunks} does not divide "
              f"nLx={16 // d}; measuring serial", file=sys.stderr,
              flush=True)
    base_t = None
    saved_mock = os.environ.get("FLASHMOE_MOCK_SLICES")
    try:
        for n_slices in (1, 2, 4, 8):
            if d % n_slices:
                continue
            os.environ["FLASHMOE_MOCK_SLICES"] = str(n_slices)
            ss = slice_structure(devs)
            inner = ss[1] if ss else d
            hier = 1 < inner < d
            cfg = MoEConfig(
                num_experts=16, expert_top_k=2, hidden_size=256,
                intermediate_size=512, sequence_len=256 * d,
                capacity_factor=1.0, drop_tokens=True, ep=d,
                dtype=jnp.bfloat16 if on_tpu else jnp.float32,
                wire_dtype=wire_dtype, wire_dtype_combine=wire_combine,
                wire_dtype_dcn=wire_dcn, a2a_chunks=chunks,
            )
            mesh = make_mesh(cfg, dp=1, devices=devs)
            params = init_moe_params(jax.random.PRNGKey(0), cfg)
            params = jax.tree_util.tree_map(
                lambda p: p.astype(cfg.dtype), params)
            x = jax.random.normal(
                jax.random.PRNGKey(1), (cfg.tokens, cfg.hidden_size),
                cfg.dtype)
            fn = lambda c: ep_moe_layer(params, c, cfg, mesh,
                                        use_pallas=on_tpu,
                                        dcn_inner=inner if hier else 0).out
            t = _time_chained(fn, x, trials=trials, chain=8)
            base_t = base_t or t
            path = "hierarchical" if hier else "collective"
            tc = a2a_transport_cost(d, max(inner, 1),
                                    slab_bytes(cfg, d, leg="dispatch"),
                                    gen=gen if gen in _PEAK_TFLOPS
                                    else "v5e",
                                    dcn_slab_bytes=slab_bytes(
                                        cfg, d, leg="dispatch",
                                        hop="dcn"))
            rec = {
                "metric": f"scaling_ms[{path},slices={n_slices},ep={d},"
                          f"tokens_per_rank=256,"
                          f"{'tpu' if on_tpu else 'virtual_cpu'}]",
                "value": round(t * 1e3, 3),
                "unit": "ms",
                # weak-scaling efficiency over the slice axis: per-rank
                # work constant, only the transport topology changes
                "vs_baseline": round(base_t / t, 3),
                "slices": n_slices,
                "dcn_inner": inner if hier else None,
                "path": path,
                "d": d,
                "a2a_chunks": cfg.a2a_chunks or 1,
                # modeled per-hop wire bytes of one dispatch leg slab
                # (the dcn row shrinks under --wire-dcn) + the DCN
                # message aggregation the two-stage exchange buys
                "slab_ici_mb": round(
                    slab_bytes(cfg, d, leg="dispatch") / 2**20, 4),
                "slab_dcn_mb": round(
                    slab_bytes(cfg, d, leg="dispatch", hop="dcn")
                    / 2**20, 4),
                "dcn_messages_flat": tc["flat"]["dcn_messages"],
                "dcn_messages_hier": tc["hierarchical"]["dcn_messages"],
            }
            rec.update(_wire_fields(cfg))
            rec["wire_dtype_dcn"] = wire_dcn or "off"
            if gen in _PEAK_TFLOPS:
                try:
                    preds = {p.path: p for p in predict_paths(
                        cfg, d, gen, slices=n_slices)}
                    p = preds.get(path) or preds["collective"]
                    rec["planner_gen"] = gen
                    rec["predicted_ms"] = round(p.total_ms, 3)
                    rec["prediction_error"] = round(
                        t * 1e3 / p.total_ms - 1.0, 3)
                    rec["predicted_dcn_ms"] = round(p.dcn_ms, 4)
                    from flashmoe_tpu.planner.drift import record_drift

                    dr = record_drift(cfg, path, t * 1e3, d=d, gen=gen,
                                      predicted_ms=p.total_ms,
                                      warn=False)
                    rec["drift_exceeded"] = dr.exceeded
                except Exception as e:  # noqa: BLE001 — keep the record
                    rec["planner_error"] = (f"{type(e).__name__}: "
                                            f"{str(e)[:120]}")
            print(json.dumps(rec), flush=True)
            _flush_observability(rec)
    finally:
        if saved_mock is None:
            os.environ.pop("FLASHMOE_MOCK_SLICES", None)
        else:
            os.environ["FLASHMOE_MOCK_SLICES"] = saved_mock


def _bench_tiles(cfg: MoEConfig, name: str, trials: int, chain: int):
    """Per-tile-choice records of the row-windowed fused schedule
    (ISSUE 12): every feasible K-window of the IO-aware chooser's grid
    (``parallel/fused.py:rowwin_sweep_candidates`` — one point per kw,
    at its widest feasible row tile) is
    forced through a throwaway ``fused_tiles`` table, timed through the
    fused layer on a 1-rank mesh (the geometry being tuned is
    transfer-free), and emitted as its own JSON record through the
    planner drift monitor — each record carries the byte model's
    roofline prediction FOR THAT TILE PAIR, so a tiles sweep doubles as
    a calibration run for the IO model the chooser minimizes.  The
    fastest candidate is what ``tune_sweep.py --stage tiles`` would
    commit."""
    from flashmoe_tpu import tuning
    from flashmoe_tpu.analysis import path_costs
    from flashmoe_tpu.models.reference import init_moe_params as _init
    from flashmoe_tpu.parallel.fused import (
        fused_ep_moe_layer, rowwin_sweep_candidates,
    )
    from flashmoe_tpu.parallel.mesh import make_mesh
    from flashmoe_tpu.parallel.topology import (
        _PEAK_TFLOPS, chip_spec, tpu_generation,
    )

    cfg = cfg.replace(ep=1, tp=1, fused_schedule="rowwin",
                      moe_backend="fused")
    h, i = cfg.hidden_size, cfg.intermediate_size
    dt = jnp.dtype(cfg.dtype).itemsize
    cap_pad = -(-cfg.capacity_for(cfg.tokens) // 32) * 32
    # the kernel's own candidate grid, one point per feasible K-window
    # at its widest feasible row tile — the sweeps and the chooser can
    # never enumerate different pairs (code-review finding)
    cands = rowwin_sweep_candidates(cap_pad, h, i, dt, cfg.gated_ffn,
                                    False, cfg.expert_top_k)
    if len(cands) < 2:
        print(json.dumps({
            "metric": f"fused_tiles_ms[{name}]", "value": None,
            "unit": "ms", "skipped": True,
            "reason": f"{len(cands)} feasible (cm, kw) rowwin "
                      f"candidates at this shape",
        }), flush=True)
        return
    gen = tpu_generation(jax.devices()[0])
    if gen not in _PEAK_TFLOPS:
        gen = os.environ.get("FLASHMOE_TPU_GEN", "")
    peak_hbm = None
    if gen in _PEAK_TFLOPS:
        peak_tf, hbm_gb = chip_spec(gen)
        if dt >= 4:
            peak_tf /= 2.0
        peak_hbm = (peak_tf * 1e12, hbm_gb * 1e9)
    params = _init(jax.random.PRNGKey(0), cfg)
    params = jax.tree_util.tree_map(lambda p: p.astype(cfg.dtype), params)
    x = jax.random.normal(jax.random.PRNGKey(1),
                          (cfg.tokens, cfg.hidden_size), cfg.dtype)
    mesh = make_mesh(cfg, dp=1, devices=jax.devices()[:1])
    tmp = "/tmp/flashmoe_bench_tiles_candidate.json"
    best = None
    try:
        for cm, kw in cands:
            with open(tmp, "w") as f:
                json.dump({"entries": [{
                    "kernel": "fused_tiles",
                    "match": {"h": h, "i": i,
                              "dtype": jnp.dtype(cfg.dtype).name},
                    "set": {"cm": cm, "kw": kw},
                }]}, f)
            os.environ["FLASHMOE_TUNING_FILE"] = tmp
            tuning._load.cache_clear()

            def layer(c):
                return fused_ep_moe_layer(params, c, cfg, mesh).out

            def chained(n):
                def run(p_unused, xx):
                    def body(c, _):
                        return layer(c).astype(c.dtype), None
                    c, _ = jax.lax.scan(body, xx, None, length=n)
                    return c.astype(jnp.float32).sum()
                return jax.jit(run)

            t1 = _time_chain(chained(1), None, x, trials)
            tn = _time_chain(chained(chain), None, x, trials)
            t = max(tn - t1, 1e-9) / (chain - 1)
            rec = {
                "metric": f"fused_tiles_ms[{name}:cm={cm},kw={kw},"
                          f"{jnp.dtype(cfg.dtype).name}]",
                "value": round(t * 1e3, 3), "unit": "ms",
                "cm": cm, "kw": kw, "schedule": "rowwin", "d": 1,
                "backend": jax.default_backend(),
            }
            # byte-model roofline FOR THIS TILE PAIR (the forced table
            # is live, so path_costs prices this candidate's window
            # count), through the drift monitor like every other bench
            # calibration point
            if peak_hbm is not None:
                try:
                    cost = path_costs(cfg, "fused", d_world=1,
                                      schedule="rowwin")
                    pred = max(cost.flops / peak_hbm[0],
                               cost.total_bytes / peak_hbm[1]) * 1e3
                    rec["planner_gen"] = gen
                    rec["predicted_ms"] = round(pred, 3)
                    rec["prediction_error"] = round(
                        t * 1e3 / pred - 1.0, 3)
                    from flashmoe_tpu.planner.drift import record_drift

                    dr = record_drift(cfg, "fused", t * 1e3, d=1,
                                      gen=gen, predicted_ms=pred,
                                      warn=False)
                    rec["drift_exceeded"] = dr.exceeded
                except Exception as e:  # noqa: BLE001 — keep the record
                    rec["planner_error"] = (f"{type(e).__name__}: "
                                            f"{str(e)[:120]}")
            if best is None or t < best[0]:
                best = (t, cm, kw)
            rec["best_so_far"] = best[1:] == (cm, kw)
            print(json.dumps(rec), flush=True)
            _flush_observability(rec)
    finally:
        os.environ.pop("FLASHMOE_TUNING_FILE", None)
        tuning._load.cache_clear()


def _bench_quant(cfg: MoEConfig, name: str, trials: int, chain: int):
    """Per-(store x path) records of the quantized expert store
    (ISSUE 15): the MoE layer timed at full precision and at each
    quant store (int8 / e4m3) on the single-chip explicit path, each
    record carrying the modeled weight bytes saved
    (``analysis.expert_weight_stream_bytes``) and measured-vs-predicted
    drift through the planner drift monitor — a quant sweep doubles as
    a calibration run for the store-width byte model the golden quant
    dimension freezes."""
    from flashmoe_tpu import quant as qtpkg
    from flashmoe_tpu.models.reference import init_moe_params as _init
    from flashmoe_tpu.ops.moe import moe_layer
    from flashmoe_tpu.parallel.topology import (
        _PEAK_TFLOPS, tpu_generation,
    )
    from flashmoe_tpu.planner.model import predict_paths

    cfg = cfg.replace(ep=1, tp=1)
    params = _init(jax.random.PRNGKey(0), cfg)
    x = jax.random.normal(jax.random.PRNGKey(1),
                          (cfg.tokens, cfg.hidden_size), cfg.dtype)
    use_pallas = jax.default_backend() == "tpu"
    gen = tpu_generation(jax.devices()[0])
    if gen not in _PEAK_TFLOPS:
        gen = os.environ.get("FLASHMOE_TPU_GEN", "")

    def timed(p, c):
        # params are TRACED arguments (the headline bench's
        # convention), not closure constants: baked-in weights would
        # let XLA hoist/constant-fold the dequantize out of the
        # scanned chain, and the sweep would time a plain
        # full-precision matmul (code-review finding)
        def chained(n):
            def run(pp, xx):
                def body(cu, _):
                    return moe_layer(pp, cu, c,
                                     use_pallas=use_pallas
                                     ).out.astype(cu.dtype), None
                cu, _ = jax.lax.scan(body, xx, None, length=n)
                return cu.astype(jnp.float32).sum()
            return jax.jit(run)

        t1 = _time_chain(chained(1), p, x, trials)
        tn = _time_chain(chained(chain), p, x, trials)
        return max(tn - t1, 1e-9) / (chain - 1)

    t_base = timed(params, cfg)
    base_rec = {
        "metric": f"quant_ms[{name}:off,explicit,"
                  f"{jnp.dtype(cfg.dtype).name}]",
        "value": round(t_base * 1e3, 3), "unit": "ms",
        "vs_baseline": 1.0, "path": "explicit", "d": 1,
        "expert_quant": "off", "backend": jax.default_backend(),
    }
    print(json.dumps(base_rec), flush=True)
    _flush_observability(base_rec)

    for qname in ("int8", "e4m3"):
        try:
            cq = cfg.replace(expert_quant=qname)
        except ValueError as e:  # e.g. e4m3 on a float8-less jax
            rec = {"metric": f"quant_ms[{name}:{qname},explicit,"
                             f"{jnp.dtype(cfg.dtype).name}]",
                   "value": None, "unit": "ms", "skipped": True,
                   "reason": f"{type(e).__name__}: {str(e)[:160]}"}
            print(json.dumps(rec), flush=True)
            _flush_observability(rec)
            continue
        qparams = qtpkg.quantize_state(params, qname).params
        t_q = timed(qparams, cq)
        rec = {
            "metric": f"quant_ms[{name}:{qname},explicit,"
                      f"{jnp.dtype(cfg.dtype).name}]",
            "value": round(t_q * 1e3, 3), "unit": "ms",
            "vs_baseline": round(t_base / t_q, 3),
            "path": "explicit", "d": 1,
            "backend": jax.default_backend(),
        }
        rec.update(_quant_fields(cq))
        if gen in _PEAK_TFLOPS:
            try:
                preds = {p.path: p for p in predict_paths(cq, 1, gen)}
                p = preds.get("explicit")
                if p is not None:
                    rec["planner_gen"] = gen
                    rec["predicted_ms"] = round(p.total_ms, 3)
                    rec["prediction_error"] = round(
                        t_q * 1e3 / p.total_ms - 1.0, 3)
                    from flashmoe_tpu.planner.drift import record_drift

                    dr = record_drift(cq, "explicit", t_q * 1e3, d=1,
                                      gen=gen,
                                      predicted_ms=rec["predicted_ms"],
                                      warn=False)
                    rec["drift_exceeded"] = dr.exceeded
            except Exception as e:  # noqa: BLE001 — keep the record
                rec["planner_error"] = (f"{type(e).__name__}: "
                                        f"{str(e)[:120]}")
        print(json.dumps(rec), flush=True)
        _flush_observability(rec)


def _probe_backend(timeout_s: int):
    """Run one trivial op on the default backend in a subprocess with a hard
    timeout.  The tunneled TPU backend can wedge so that even ``jax.devices()``
    hangs forever in-process; an expendable child process turns that into a
    fast, bounded diagnostic instead of eating the whole bench deadline.

    Returns ``(ok, info, hung)`` — ``hung`` distinguishes a probe that
    never answered (timeout: the skip case) from one that answered with
    an error (dead backend: the error case)."""
    code = ("import jax, jax.numpy as jnp;"
            "print(jax.default_backend(), float(jnp.ones(8).sum()))")
    try:
        r = subprocess.run([sys.executable, "-c", code], timeout=timeout_s,
                           capture_output=True, text=True)
    except subprocess.TimeoutExpired:
        return (False,
                f"backend probe hung >{timeout_s}s (tunnel wedged?)", True)
    if r.returncode != 0:
        return False, (f"backend probe rc={r.returncode}: "
                       f"{(r.stderr or '').strip()[-300:]}"), False
    return True, r.stdout.strip(), False


def _probe_backend_retry(budget_s: int, each_s: int = 90,
                         max_attempts: int = 0):
    """Retry the backend probe until it succeeds, the budget runs out,
    or ``max_attempts`` probes all failed (0 = budget-bounded only).

    The tunnel wedges transiently; failing the whole bench on one bad probe
    cost two rounds of driver-captured numbers (BENCH_r01/r02 value: -1) —
    but retrying a WEDGED tunnel for the full budget burned 309 s before
    exiting rc=2 (BENCH_r05), so ``FLASHMOE_PROBE_ATTEMPTS`` /
    ``FLASHMOE_PROBE_TIMEOUT`` bound the loop for drivers that prefer a
    fast, well-formed skip.  A wedged probe subprocess already consumed
    ``each_s``; on fast failures sleep a bit so a flapping relay has time
    to come back.  Returns ``(ok, info, hung)``; ``hung`` is True when
    the final failure was a probe that never answered."""
    start = time.monotonic()
    attempt = 0
    while True:
        attempt += 1
        t0 = time.monotonic()
        remaining = budget_s - (time.monotonic() - start)
        # clamp so the final attempt cannot overrun the budget by each_s
        ok, info, hung = _probe_backend(max(10, min(each_s, int(remaining))))
        if ok:
            return True, f"{info} (probe attempt {attempt})", False
        elapsed = time.monotonic() - start
        if elapsed >= budget_s or (max_attempts and attempt >= max_attempts):
            return (False,
                    f"{info} after {attempt} attempts / {elapsed:.0f}s",
                    hung)
        print(f"# probe attempt {attempt} failed ({info}); retrying",
              file=sys.stderr, flush=True)
        if time.monotonic() - t0 < 15:
            time.sleep(min(15, budget_s - elapsed))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", default="reference",
                    choices=sorted(BENCH_CONFIGS.keys()))
    ap.add_argument("--trials", type=int, default=7)
    ap.add_argument("--chain", type=int, default=16)
    ap.add_argument("--sweep", choices=["tokens", "experts", "ep"],
                    default=None,
                    help="emit one JSON line per point instead of the "
                         "single headline number (ep = weak scaling on "
                         "an ep-way mesh)")
    ap.add_argument("--overlap", type=int, default=0, metavar="EP",
                    help="measure overlap efficiency on an EP-way mesh "
                         "instead of the latency bench")
    ap.add_argument("--scaling", action="store_true",
                    help="weak-scaling sweep over mocked 1/2/4/8-slice "
                         "meshes (FLASHMOE_MOCK_SLICES + the two-stage "
                         "hierarchical a2a): one JSON record per slice "
                         "count with measured vs slices=n predicted "
                         "latency through the drift monitor and the "
                         "per-hop wire bytes (see docs/PERF.md "
                         "'Multi-slice scale-out')")
    ap.add_argument("--tiles", action="store_true",
                    help="sweep the row-windowed fused schedule's "
                         "(cm, kw) tile candidates at --config instead "
                         "of the latency bench — one JSON record per "
                         "tile choice through the planner drift "
                         "monitor (the measured counterpart of the "
                         "IO-aware chooser; see docs/PERF.md)")
    ap.add_argument("--quant", action="store_true",
                    help="sweep the quantized expert store "
                         "(MoEConfig.expert_quant int8/e4m3) at "
                         "--config instead of the latency bench — one "
                         "JSON record per (store, path) with modeled "
                         "weight bytes saved and measured-vs-predicted "
                         "drift (see docs/PERF.md 'Quantized expert "
                         "storage')")
    ap.add_argument("--ckpt", action="store_true",
                    help="measure step-loop checkpoint blocking time, "
                         "sync vs async save, instead of the latency "
                         "bench (host-side; no backend probe)")
    ap.add_argument("--profile", action="store_true",
                    help="phase-level profile + predicted-vs-actual "
                         "cost ledger over the path x chunks x wire "
                         "matrix (virtual CPU mesh; artifacts into "
                         "--obs-dir, summarized by "
                         "`observe --ledger`)")
    ap.add_argument("--profile-quick", action="store_true",
                    help="--profile restricted to the first matrix "
                         "point (CI smoke)")
    ap.add_argument("--profile-steps", type=int, default=1,
                    help="profiled steps per matrix point")
    ap.add_argument("--serve", action="store_true",
                    help="offered-load serving sweep through the "
                         "continuous-batching engine (one record per "
                         "load point with tokens/sec + TTFT/TPOT "
                         "percentiles; see docs/SERVING.md)")
    ap.add_argument("--fabric", action="store_true",
                    help="offered-load sweep over mocked 1/2/4-replica "
                         "disaggregated fabrics (FLASHMOE_MOCK_FABRIC "
                         "+ the replica router + DCN-priced KV "
                         "handoff): one record per (replicas, load) "
                         "point (see docs/SERVING.md 'Disaggregated "
                         "fabric')")
    ap.add_argument("--vclock", action="store_true",
                    help="with --fabric: step the sweep on the "
                         "fabric's deterministic virtual clock behind "
                         "the front door — TTFT/TPOT measured under "
                         "the modeled DCN delay, plus measured-vs-"
                         "priced handoff reconciliation and per-"
                         "request latency attribution on every record")
    ap.add_argument("--faults", action="store_true",
                    help="with --fabric: run the serving fault-"
                         "tolerance sweep instead of the load sweep — "
                         "one record per chaos fault (replica_crash / "
                         "handoff_corrupt / handoff_timeout / "
                         "frontdoor_loss / net_partition / "
                         "lease_split_brain / replica_stall / "
                         "lease_torn_write) with recovery latency, "
                         "migrated-request count, retry totals, "
                         "heartbeat detection latency and shed "
                         "fraction (docs/RESILIENCE.md "
                         "'Serving-side ladder')")
    ap.add_argument("--wire", default="inproc",
                    choices=("inproc", "tcp"),
                    help="with --fabric: the KV-handoff wire for the "
                         "load sweep — 'tcp' sends every transfer "
                         "through a real localhost socket (length-"
                         "prefixed frames + per-page CRC verify) and "
                         "tags each record's identity with wire=tcp; "
                         "'inproc' (default) is the byte-identical "
                         "in-process path")
    ap.add_argument("--serve-loads", default="4,2,1",
                    help="comma-separated arrival gaps in engine "
                         "steps, lightest first (smaller = higher "
                         "offered load)")
    ap.add_argument("--serve-requests", type=int, default=8,
                    help="requests per --serve load point")
    ap.add_argument("--serve-batch", type=int, default=4,
                    help="engine decode-batch width for --serve")
    ap.add_argument("--speculate", type=int, default=None, metavar="K",
                    help="with --serve: arm speculative decoding at "
                         "draft_tokens=K — per-record accept_rate / "
                         "spec_tokens_per_step, an equal-SLO TPOT "
                         "comparison against a per-point baseline, "
                         "and the spec=kK metric-identity tag")
    ap.add_argument("--telemetry-port", type=int, default=None,
                    metavar="PORT",
                    help="with --serve: arm the live scrape plane for "
                         "the sweep and self-scrape /metrics mid-sweep "
                         "into each record (0 = ephemeral port)")
    ap.add_argument("--regression", action="store_true",
                    help="append this run's metric points to "
                         "obs/history.jsonl for the perf sentry "
                         "(`observe --regression`); headline, --serve, "
                         "--profile and --scaling modes")
    ap.add_argument("--deadline", type=int, default=720,
                    help="wall-clock watchdog (s) for the measurement "
                         "itself, armed AFTER the backend probe succeeds; "
                         "emits the best partial record instead of hanging "
                         "on a wedged backend (sized for ~6 remote "
                         "compiles at 60-90s each: two chain lengths x "
                         "{fused, xla, gather-fused candidate})")
    ap.add_argument("--probe-budget", type=int,
                    default=int(os.environ.get("FLASHMOE_PROBE_BUDGET", 300)),
                    help="how long to keep retrying the backend probe (s) "
                         "before giving up")
    ap.add_argument("--probe-attempts", type=int,
                    default=int(os.environ.get("FLASHMOE_PROBE_ATTEMPTS",
                                               0)),
                    help="max probe attempts before giving up "
                         "(0 = bounded by --probe-budget alone); a probe "
                         "that never answers then yields a well-formed "
                         "skipped:true record with rc 0")
    ap.add_argument("--probe-timeout", type=int,
                    default=int(os.environ.get("FLASHMOE_PROBE_TIMEOUT",
                                               90)),
                    help="per-attempt probe timeout (s)")
    ap.add_argument("--wire-dtype", default=None,
                    help="EP payload wire dtype for the dispatch leg "
                         "(bf16 / e4m3 / e5m2; default off) — recorded "
                         "on every emitted measurement")
    ap.add_argument("--wire-combine", default=None,
                    help="EP payload wire dtype for the combine leg")
    ap.add_argument("--wire-dcn", default=None,
                    help="per-hop wire dtype for the CROSS-SLICE (DCN) "
                         "stage of the hierarchical a2a "
                         "(MoEConfig.wire_dtype_dcn; --scaling only — "
                         "the other modes have no DCN hop)")
    ap.add_argument("--a2a-chunks", type=int, default=None,
                    help="chunked double-buffered EP pipeline depth "
                         "(MoEConfig.a2a_chunks; default off = serial "
                         "schedule) — honored by the latency bench, "
                         "the ep sweep, and --overlap (which also "
                         "measures the serial baseline for comparison)")
    ap.add_argument("--obs-dir",
                    default=os.environ.get("FLASHMOE_OBS_DIR"),
                    help="directory for observability artifacts "
                         "(bench_records.jsonl + decisions.jsonl, "
                         "summarized by `python -m flashmoe_tpu.observe`)")
    args = ap.parse_args()
    _OBS[0] = args.obs_dir

    # live-plane flag contracts (the --profile/--ckpt fail-fast rule:
    # refuse flags a mode would silently ignore)
    if args.telemetry_port is not None and not (args.serve
                                                or args.fabric):
        ap.error("--telemetry-port applies with --serve/--fabric only "
                 "(the live scrape plane rides the serving sweeps; the "
                 "train CLIs take their own --telemetry-port)")
    if args.vclock and not args.fabric:
        ap.error("--vclock applies with --fabric only (the virtual "
                 "clock is the fabric's measured-latency plane; every "
                 "other mode times real work on the wall clock)")
    if args.faults and not args.fabric:
        ap.error("--faults applies with --fabric only (the fault "
                 "sweep drills the serving fabric's recovery ladder; "
                 "no other mode owns those faults)")
    if args.faults and args.vclock:
        ap.error("--faults already steps every drill on the virtual "
                 "clock; drop --vclock")
    if args.faults and args.telemetry_port is not None:
        ap.error("--faults drives self-contained chaos drills with "
                 "no live scrape window; drop --telemetry-port")
    if args.wire != "inproc" and not args.fabric:
        ap.error("--wire applies with --fabric only (the socket wire "
                 "carries KV handoffs between fabric pools; no other "
                 "mode moves KV pages)")
    if args.faults and args.wire != "inproc":
        ap.error("--faults picks each drill's wire itself "
                 "(net_partition runs tcp, the rest in-process); "
                 "drop --wire")
    if args.regression and (args.ckpt or args.overlap or args.sweep
                            or args.tiles or args.quant):
        ap.error("--regression appends measured runs from the "
                 "headline bench, --serve, --profile, or --scaling; "
                 "drop --ckpt/--overlap/--sweep/--tiles/--quant")
    _REG[0] = (os.path.join(args.obs_dir or "obs", "history.jsonl")
               if args.regression else None)
    _REG[1].clear()

    # the headline record's identity follows the mode, so a tiles-sweep
    # or scaling-sweep skip/error is machine-distinguishable from a
    # latency-bench one
    headline_metric = (f"fused_tiles_ms[{args.config}]" if args.tiles
                       else f"quant_ms[{args.config}]" if args.quant
                       else "scaling_ms[slices]" if args.scaling
                       else "fabric_fault[matrix]"
                       if (args.fabric and args.faults)
                       else "fabric_tokens_per_sec[replicas]"
                       if args.fabric
                       else f"moe_layer_fwd_ms[{args.config}]")

    def emit_error(msg, code=2):
        print(json.dumps({
            "metric": headline_metric,
            "value": -1, "unit": "ms", "vs_baseline": 0,
            "error": msg,
        }), flush=True)
        sys.exit(code)

    def emit_best_partial(reason):
        """Emit whatever full measurement exists for the in-flight config
        (sweeps included: _PARTIAL carries that point's own cfg/name).
        Exit codes are machine-distinguishable: 0 = headline fully
        measured, 1 = interrupted sweep (emitted rows are real), 3 =
        headline partial (xla leg missing; the record also carries
        vs_baseline null), 2 = nothing measured."""
        tf, tx = _PARTIAL.get("fused"), _PARTIAL.get("xla")
        pcfg, pname = _PARTIAL.get("cfg"), _PARTIAL.get("name")
        if tf is not None and pcfg is not None:
            _emit(pcfg, pname, tf, tx,
                  note=f"{reason}; xla path "
                       f"{'measured' if tx else 'missing'}")
            sys.exit(1 if args.sweep else (0 if tx is not None else 3))
        emit_error(reason)

    def on_deadline(signum, frame):
        emit_best_partial(f"deadline {args.deadline}s exceeded "
                          f"(backend hung or compile stalled)")

    if args.deadline > 0:
        signal.signal(signal.SIGALRM, on_deadline)

    if (args.wire_dtype or args.wire_combine or args.a2a_chunks) \
            and args.ckpt:
        # refuse rather than silently measure uncompressed: the ckpt
        # mode is host-side and exchanges no wire payloads.  --overlap
        # now HONORS both knobs: the chunked schedule encodes/decodes
        # per chunk inside the pipeline, so compressed chunked overlap
        # is exactly the workload the knobs exist for.
        ap.error("--wire-dtype/--wire-combine/--a2a-chunks apply to "
                 "the latency bench, --sweep and --overlap runs, "
                 "not --ckpt")
    if args.a2a_chunks is not None and args.a2a_chunks < 1:
        ap.error("--a2a-chunks must be >= 1")
    if args.wire_dcn and not args.scaling:
        # fail-fast contract: the DCN-hop wire only exists on the
        # two-stage multi-slice exchange the scaling sweep runs; every
        # other mode would silently ignore it
        ap.error("--wire-dcn applies to --scaling only (the other "
                 "modes run no cross-slice hop)")
    if args.speculate is not None and not args.serve:
        # checked BEFORE any mode dispatches (--fabric/--scaling
        # return early): a silently-dropped --speculate would report
        # a plain sweep as a speculative one
        ap.error("--speculate applies with --serve only (the "
                 "speculative drill rides the serving engine)")
    if args.speculate is not None and args.speculate < 1:
        ap.error("--speculate must be >= 1 draft token")
    if args.fabric:
        # the --profile/--ckpt fail-fast contract: the fabric sweep
        # drives its own CPU-sized drill model over its own mocked
        # replica matrix — refuse every mode/knob it would silently
        # ignore
        if args.ckpt or args.overlap or args.profile \
                or args.profile_quick or args.quant or args.serve \
                or args.sweep or args.tiles or args.scaling:
            ap.error("--fabric is its own mode; drop "
                     "--ckpt/--overlap/--profile/--quant/--serve/"
                     "--sweep/--tiles/--scaling")
        if args.wire_dtype or args.wire_combine or args.a2a_chunks:
            ap.error("--fabric drives the CPU-sized serving drill "
                     "model; --wire-dtype/--wire-combine/--a2a-chunks "
                     "do not apply")
    if args.quant:
        # the --profile/--ckpt fail-fast contract: the quant sweep pins
        # its own (store x path) matrix at ep=1 — refuse knobs/modes it
        # would silently ignore.  --ckpt and --overlap are the
        # shape-changing combinations the ISSUE names; the rest follow
        # the same rule.
        if args.wire_dtype or args.wire_combine or args.a2a_chunks:
            ap.error("--quant sweeps the expert weight store; "
                     "--wire-dtype/--wire-combine/--a2a-chunks do not "
                     "apply")
        if args.overlap or args.ckpt or args.sweep or args.serve \
                or args.profile or args.profile_quick or args.tiles \
                or args.scaling:
            ap.error("--quant is its own mode; drop "
                     "--overlap/--ckpt/--sweep/--serve/--profile/"
                     "--tiles/--scaling")
    if args.scaling:
        if args.overlap or args.ckpt or args.sweep or args.serve \
                or args.profile or args.profile_quick or args.tiles:
            ap.error("--scaling is its own mode; drop "
                     "--overlap/--ckpt/--sweep/--serve/--profile/"
                     "--tiles")
        if os.environ.get("FLASHMOE_OVERLAP_TPU") == "1":
            # real-hardware runs inherit the probe fail-fast contract:
            # a wedged tunnel yields ONE well-formed skipped:true
            # record and rc 0, never a hang or an ambiguous rc 2
            ok, info, hung = _probe_backend_retry(
                args.probe_budget, each_s=max(args.probe_timeout, 10),
                max_attempts=args.probe_attempts)
            if not ok:
                if hung:
                    print(json.dumps({
                        "metric": headline_metric,
                        "value": None, "unit": "ms",
                        "vs_baseline": None,
                        "skipped": True, "reason": info,
                    }), flush=True)
                    sys.exit(0)
                emit_error(info)
        if args.deadline > 0:
            signal.alarm(args.deadline)
        _bench_scaling(args.trials, wire_dtype=args.wire_dtype,
                       wire_combine=args.wire_combine,
                       wire_dcn=args.wire_dcn,
                       a2a_chunks=args.a2a_chunks)
        _finish_regression()
        return
    if args.fabric:
        if os.environ.get("FLASHMOE_OVERLAP_TPU") == "1":
            # real-hardware runs inherit the probe fail-fast contract
            # (same as --scaling): a wedged tunnel yields ONE
            # well-formed skipped:true record and rc 0
            ok, info, hung = _probe_backend_retry(
                args.probe_budget, each_s=max(args.probe_timeout, 10),
                max_attempts=args.probe_attempts)
            if not ok:
                if hung:
                    print(json.dumps({
                        "metric": headline_metric,
                        "value": None, "unit": "tokens_per_sec",
                        "vs_baseline": None,
                        "skipped": True, "reason": info,
                    }), flush=True)
                    sys.exit(0)
                emit_error(info)
        if args.deadline > 0:
            signal.alarm(args.deadline)  # host+CPU path: no probe leg
        if args.faults:
            _bench_fabric_faults()
        else:
            _bench_fabric([4, 2, 1], requests=8, max_batch=4,
                          telemetry_port=args.telemetry_port,
                          vclock=args.vclock, wire=args.wire)
        _finish_regression()
        return
    if args.tiles:
        # the --profile/--ckpt fail-fast contract: refuse knobs/modes
        # this mode would silently ignore — the tiles sweep pins its
        # own (fused, rowwin, ep=1) execution and the RDMA transport
        # composes with neither wire compression nor chunking
        if args.wire_dtype or args.wire_combine or args.a2a_chunks:
            ap.error("--tiles sweeps the fused rowwin kernel; "
                     "--wire-dtype/--wire-combine/--a2a-chunks do not "
                     "apply")
        if args.overlap or args.ckpt or args.sweep or args.serve \
                or args.profile or args.profile_quick:
            ap.error("--tiles is its own mode; drop "
                     "--overlap/--ckpt/--sweep/--serve/--profile")
    if not args.serve and (args.serve_requests != 8
                           or args.serve_batch != 4
                           or args.serve_loads != "4,2,1"):
        # checked BEFORE any mode dispatches: --profile et al. return
        # early, and a silently-dropped --serve-requests would break
        # the fail-fast contract every other flag combination honors
        ap.error("--serve-loads/--serve-requests/--serve-batch only "
                 "apply with --serve")
    if args.profile or args.profile_quick:
        # --profile runs its own fixed path x chunks x wire matrix;
        # refuse knobs/modes it would silently ignore rather than let
        # the user believe they profiled a shape they named (the same
        # fail-fast contract --ckpt applies to the wire knobs)
        if args.wire_dtype or args.wire_combine or args.a2a_chunks:
            ap.error("--profile ledgers its own path x chunks x wire "
                     "matrix; --wire-dtype/--wire-combine/--a2a-chunks "
                     "do not apply")
        if args.overlap or args.ckpt or args.sweep or args.serve:
            ap.error("--profile is its own mode; drop "
                     "--overlap/--ckpt/--sweep/--serve")
        if args.deadline > 0:
            signal.alarm(args.deadline)  # virtual-mesh path: no probe leg
        _bench_profile(args.obs_dir, steps=args.profile_steps,
                       quick=args.profile_quick)
        _finish_regression()
        return
    if args.profile_steps != 1:
        ap.error("--profile-steps only applies with "
                 "--profile/--profile-quick")
    if args.serve:
        # the --profile/--ckpt contract: refuse knobs/modes this mode
        # would silently ignore rather than let the user believe they
        # swept a shape they named
        if args.wire_dtype or args.wire_combine or args.a2a_chunks:
            ap.error("--serve drives the CPU-sized serving drill "
                     "model; --wire-dtype/--wire-combine/--a2a-chunks "
                     "do not apply")
        if args.overlap or args.ckpt or args.sweep:
            ap.error("--serve is its own mode; drop "
                     "--overlap/--ckpt/--sweep")
        try:
            loads = [int(v) for v in
                     str(args.serve_loads).split(",") if v.strip()]
        except ValueError:
            ap.error(f"--serve-loads must be comma-separated ints, "
                     f"got {args.serve_loads!r}")
        if not loads or any(v < 1 for v in loads):
            ap.error("--serve-loads gaps must be >= 1 engine step")
        if args.deadline > 0:
            signal.alarm(args.deadline)  # host+CPU path: no probe leg
        _bench_serve(loads, requests=args.serve_requests,
                     max_batch=args.serve_batch,
                     telemetry_port=args.telemetry_port,
                     speculate=args.speculate)
        _finish_regression()
        return
    if args.ckpt:
        if args.deadline > 0:
            signal.alarm(args.deadline)  # host-side path: no probe leg
        _bench_checkpoint(args.trials)
        return
    if args.overlap:
        if args.deadline > 0:
            signal.alarm(args.deadline)  # virtual-mesh path: no probe leg
        _bench_overlap(args.overlap, args.trials,
                       wire_dtype=args.wire_dtype,
                       wire_combine=args.wire_combine,
                       a2a_chunks=args.a2a_chunks)
        return
    if args.sweep == "ep":
        if args.deadline > 0:
            signal.alarm(args.deadline)
        _sweep_ep(args.trials, wire_dtype=args.wire_dtype,
                  wire_combine=args.wire_combine,
                  a2a_chunks=args.a2a_chunks)
        return

    ok, info, hung = _probe_backend_retry(args.probe_budget,
                                          each_s=max(args.probe_timeout, 10),
                                          max_attempts=args.probe_attempts)
    if not ok:
        if hung:
            # the backend never answered: a wedged tunnel is an
            # environment condition, not a measurement failure — emit a
            # well-formed skip (rc 0) the driver can file as "no data"
            # instead of an error record (BENCH_r05: 309 s of retries
            # for an rc=2 the driver could not distinguish from a bug)
            print(json.dumps({
                "metric": headline_metric,
                "value": None, "unit": "ms", "vs_baseline": None,
                "skipped": True, "reason": info,
            }), flush=True)
            sys.exit(0)
        emit_error(info)
    print(f"# backend up: {info}", file=sys.stderr, flush=True)

    # Probing may legitimately consume minutes of a flapping tunnel; the
    # measurement deadline starts only now that the backend is known-up.
    if args.deadline > 0:
        signal.alarm(args.deadline)

    cfg = BENCH_CONFIGS[args.config]
    if cfg.ep > 1 and len(jax.devices()) < cfg.ep:
        cfg = cfg.replace(ep=1)
    if args.wire_dtype or args.wire_combine:
        cfg = cfg.replace(wire_dtype=args.wire_dtype,
                          wire_dtype_combine=args.wire_combine)
    if args.a2a_chunks and args.a2a_chunks > 1:
        cfg = cfg.replace(a2a_chunks=args.a2a_chunks)  # ValueError if
        # the count cannot divide this config's local-expert axis

    if args.tiles:
        try:
            _bench_tiles(cfg, args.config, args.trials, args.chain)
        except Exception as e:  # noqa: BLE001 — always leave a record
            emit_error(f"{type(e).__name__}: {str(e)[:300]}")
        return

    if args.quant:
        try:
            _bench_quant(cfg, args.config, args.trials, args.chain)
        except Exception as e:  # noqa: BLE001 — always leave a record
            emit_error(f"{type(e).__name__}: {str(e)[:300]}")
        return

    try:
        if args.sweep == "tokens":
            for s in (1024, 2048, 4096, 8192, 16384):
                c = cfg.replace(sequence_len=s)
                n = f"{args.config}/S={s}"
                tf, tx = bench_moe_layer(c, args.trials, args.chain,
                                         name=n, candidates=False)
                _emit(c, n, tf, tx)
            return
        if args.sweep == "experts":
            for e in (8, 16, 32, 64, 128):
                c = cfg.replace(num_experts=e,
                                expert_top_k=min(cfg.expert_top_k, e))
                n = f"{args.config}/E={e}"
                tf, tx = bench_moe_layer(c, args.trials, args.chain,
                                         name=n, candidates=False)
                _emit(c, n, tf, tx)
            return
        t_fused, t_xla = bench_moe_layer(cfg, args.trials, args.chain,
                                         name=args.config)
    except Exception as e:  # noqa: BLE001 — always leave a JSON record
        emit_best_partial(f"{type(e).__name__}: {str(e)[:300]}")
        return
    _emit(cfg, args.config, t_fused, t_xla)
    _finish_regression()


if __name__ == "__main__":
    main()
