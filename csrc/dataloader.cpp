// flashmoe-tpu native data loader: binary token shards with background
// prefetch.
//
// The training input pipeline component (the reference repo has no data
// loader — its worker feeds random tensors; a complete training framework
// needs real input).  Format: a flat little-endian int32 token stream.
// The loader cuts it into [batch, seq_len + 1] windows (next-token targets
// share the window), optionally shuffling window order per epoch with an
// xorshift PRNG, and a background thread keeps a small ring of batches
// decoded ahead of the consumer so host input never stalls device steps.
//
// C ABI consumed by flashmoe_tpu/runtime/data.py via ctypes; a NumPy
// fallback with identical semantics covers toolchain-less installs.

#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <deque>
#include <mutex>
#include <thread>
#include <vector>

namespace {

struct XorShift {
  uint64_t s;
  explicit XorShift(uint64_t seed) : s(seed ? seed : 0x9e3779b97f4a7c15ull) {}
  uint64_t next() {
    s ^= s << 13;
    s ^= s >> 7;
    s ^= s << 17;
    return s;
  }
};

struct Loader {
  std::vector<int32_t> tokens;
  int64_t seq_len = 0;
  int64_t batch = 0;
  uint64_t seed = 0;
  bool shuffle = false;

  std::vector<int64_t> order;   // window start indices, epoch order
  int64_t cursor = 0;           // next window in `order`
  int64_t epoch = 0;

  std::deque<std::vector<int32_t>> queue;
  size_t depth = 4;
  std::mutex mu;
  std::condition_variable cv_pop, cv_push;
  std::thread worker;
  bool stop = false;

  int64_t window() const { return seq_len + 1; }
  int64_t num_windows() const {
    return (int64_t)tokens.size() / window();
  }

  void reshuffle() {
    int64_t n = num_windows();
    order.resize(n);
    for (int64_t i = 0; i < n; ++i) order[i] = i * window();
    if (shuffle) {
      XorShift rng(seed + 0x51ed270b * (uint64_t)(epoch + 1));
      for (int64_t i = n - 1; i > 0; --i) {
        int64_t j = (int64_t)(rng.next() % (uint64_t)(i + 1));
        std::swap(order[i], order[j]);
      }
    }
  }

  void fill_batch(std::vector<int32_t>& out) {
    out.resize(batch * window());
    for (int64_t b = 0; b < batch; ++b) {
      if (cursor >= (int64_t)order.size()) {
        ++epoch;
        cursor = 0;
        reshuffle();
      }
      std::memcpy(out.data() + b * window(),
                  tokens.data() + order[cursor], window() * sizeof(int32_t));
      ++cursor;
    }
  }

  void run() {
    for (;;) {
      std::vector<int32_t> buf;
      {
        std::unique_lock<std::mutex> lk(mu);
        cv_push.wait(lk, [&] { return stop || queue.size() < depth; });
        if (stop) return;
      }
      fill_batch(buf);
      {
        std::unique_lock<std::mutex> lk(mu);
        queue.push_back(std::move(buf));
      }
      cv_pop.notify_one();
    }
  }
};

}  // namespace

extern "C" {

void* flashmoe_loader_open(const char* path, int64_t seq_len, int64_t batch,
                           uint64_t seed, int shuffle) {
  if (seq_len <= 0 || batch <= 0) return nullptr;
  FILE* f = std::fopen(path, "rb");
  if (!f) return nullptr;
  std::fseek(f, 0, SEEK_END);
  long bytes = std::ftell(f);
  std::fseek(f, 0, SEEK_SET);
  auto* ld = new Loader();
  ld->tokens.resize(bytes / sizeof(int32_t));
  size_t got = std::fread(ld->tokens.data(), sizeof(int32_t),
                          ld->tokens.size(), f);
  std::fclose(f);
  ld->tokens.resize(got);
  ld->seq_len = seq_len;
  ld->batch = batch;
  ld->seed = seed;
  ld->shuffle = shuffle != 0;
  if (ld->num_windows() < 1) {
    delete ld;
    return nullptr;
  }
  ld->reshuffle();
  ld->worker = std::thread([ld] { ld->run(); });
  return ld;
}

// Copies one [batch, seq_len+1] int32 batch into `out`. Returns 0 on
// success.
int flashmoe_loader_next(void* handle, int32_t* out) {
  auto* ld = static_cast<Loader*>(handle);
  if (!ld) return 1;
  std::vector<int32_t> buf;
  {
    std::unique_lock<std::mutex> lk(ld->mu);
    ld->cv_pop.wait(lk, [&] { return ld->stop || !ld->queue.empty(); });
    if (ld->queue.empty()) return 1;
    buf = std::move(ld->queue.front());
    ld->queue.pop_front();
  }
  ld->cv_push.notify_one();
  std::memcpy(out, buf.data(), buf.size() * sizeof(int32_t));
  return 0;
}

int64_t flashmoe_loader_num_windows(void* handle) {
  auto* ld = static_cast<Loader*>(handle);
  return ld ? ld->num_windows() : -1;
}

void flashmoe_loader_close(void* handle) {
  auto* ld = static_cast<Loader*>(handle);
  if (!ld) return;
  {
    std::unique_lock<std::mutex> lk(ld->mu);
    ld->stop = true;
  }
  ld->cv_push.notify_all();
  ld->cv_pop.notify_all();
  if (ld->worker.joinable()) ld->worker.join();
  delete ld;
}

}  // extern "C"
