// flashmoe-tpu native Decider: topology-aware DP x EP group formation and
// expert assignment.
//
// C++ implementation of the placement optimizer described in
// flashmoe_tpu/parallel/decider.py (the Python reference implementation),
// re-designed from the capability of the reference repo's host-side C++
// Decider (csrc/include/flashmoe/os/decider/decider.cuh:34-329 in
// osayamenja/FlashMoE): greedy hierarchical merging over an alpha-beta
// adjacency matrix with a compute+comm+allreduce objective, memory
// feasibility forcing, and rate-proportional expert assignment.
//
// Exposed as a C ABI for ctypes; bit-identical group structure to the
// Python implementation (cross-validated in tests/test_native.py).

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <limits>
#include <numeric>
#include <queue>
#include <vector>

namespace {

struct DSU {
  std::vector<int> parent;
  explicit DSU(int n) : parent(n) { std::iota(parent.begin(), parent.end(), 0); }
  int find(int x) {
    while (parent[x] != x) {
      parent[x] = parent[parent[x]];  // path halving
      x = parent[x];
    }
    return x;
  }
  int unite(int a, int b) {
    int ra = find(a), rb = find(b);
    if (ra != rb) parent[rb] = ra;
    return ra;
  }
};

struct Ctx {
  int n;
  const double* alpha;
  const double* beta;
  const double* rate;
  const double* mem_gb;
  int num_experts;
  double expert_mb, act_mb, grad_mb, gamma;
  bool training;

  double transfer_ms(int i, int j, double mb) const {
    return alpha[i * n + j] + beta[i * n + j] * mb;
  }
  bool can_hold_all(const std::vector<int>& mem) const {
    double cap = 0;
    for (int d : mem) cap += mem_gb[d] * 1024.0;
    return cap >= num_experts * expert_mb;
  }
  // worst pairwise transfer, payload split across the group (the
  // reference's evalP2PTime with p2pBuffer/numNodes)
  double intra_comm_ms(const std::vector<int>& mem) const {
    double worst = 0;
    double mb = act_mb / std::max<size_t>(mem.size(), 1);
    for (int i : mem)
      for (int j : mem)
        if (i != j) worst = std::max(worst, transfer_ms(i, j, mb));
    return worst;
  }
  // memory-infeasible groups price at infinity (the reference's
  // must-merge encoding, functions.cuh obj())
  double objective(const std::vector<int>& mem, double ar_ms) const {
    if (!can_hold_all(mem)) return std::numeric_limits<double>::infinity();
    double r = 0;
    for (int d : mem) r += rate[d];
    double total_cost =
        num_experts / std::max(*std::min_element(rate, rate + n), 1e-9);
    double compute = total_cost / std::max(r, 1e-9);
    return gamma * (compute + 1.0 * intra_comm_ms(mem)) + ar_ms;
  }
};

}  // namespace

extern "C" {

// Returns 0 on success.  group_id_out[d] = group index of device d
// (dense, ordered by smallest member).  expert_counts_out[d] = number of
// experts assigned to device d within its group.
int flashmoe_decide(int n, const double* alpha, const double* beta,
                    const double* throughput, const double* memory_gb,
                    int num_experts, double expert_mb, double act_mb,
                    double grad_mb, double gamma, int is_training,
                    int* group_id_out, int* expert_counts_out) {
  if (n <= 0 || num_experts <= 0) return 1;
  Ctx ctx{n,        alpha,    beta,    throughput, memory_gb, num_experts,
          expert_mb, act_mb,  grad_mb, gamma,      is_training != 0};

  DSU dsu(n);
  std::vector<std::vector<int>> members(n);
  for (int d = 0; d < n; ++d) members[d] = {d};
  auto alive = [&](int r) { return !members[r].empty(); };
  auto num_groups = [&]() {
    int g = 0;
    for (int d = 0; d < n; ++d)
      if (dsu.find(d) == d) ++g;
    return g;
  };

  struct Edge {
    double w; int a, b;
    bool operator<(const Edge& o) const { return w < o.w; }  // PQ: max by w
  };
  std::vector<Edge> edges;
  edges.reserve(n * (n - 1) / 2);
  for (int i = 0; i < n; ++i)
    for (int j = i + 1; j < n; ++j)
      edges.push_back({ctx.transfer_ms(i, j, act_mb), i, j});
  std::stable_sort(edges.begin(), edges.end(),
                   [](const Edge& x, const Edge& y) { return x.w < y.w; });

  // inter-group allreduce bottleneck: max-heap of external edges keyed by
  // per-chunk gradient transfer time, maintained across merges exactly as
  // the reference's externalEdges priority queue (decider.cuh:60,86-158).
  // Inference jobs (training == false) skip the term entirely — the
  // reference's Decider<JobType::inference> specialization.
  const bool use_ar = ctx.training && ctx.grad_mb > 0;
  std::priority_queue<Edge> ext;  // Edge::operator< orders by w: max-heap
  if (use_ar)
    for (int i = 0; i < n; ++i)
      for (int j = 0; j < n; ++j)
        if (i != j) ext.push({ctx.transfer_ms(i, j, ctx.grad_mb / n), i, j});

  for (const Edge& e : edges) {
    int ra = dsu.find(e.a), rb = dsu.find(e.b);
    if (ra == rb) continue;
    auto& ga = members[ra];
    auto& gb = members[rb];
    std::vector<int> merged = ga;
    merged.insert(merged.end(), gb.begin(), gb.end());
    double ar_parts = 0.0, ar_merged = 0.0;
    std::vector<Edge> limbo;  // edges the merge would internalize
    if (use_ar) {
      while (!ext.empty()) {
        Edge t = ext.top();
        int fa = dsu.find(t.a), fb = dsu.find(t.b);
        if (fa == fb) { ext.pop(); continue; }      // intra forever
        if ((fa == ra && fb == rb) || (fa == rb && fb == ra)) {
          limbo.push_back(t);                        // internal iff merged
          ext.pop();
          continue;
        }
        break;
      }
      // Heap ORDER stays keyed at the initial chunk grad_mb/n; the VALUE
      // is repriced with the chunk of the live partition (grad_mb/g now,
      // grad_mb/(g-1) post-merge) — the reference's ARArgs::refresh
      // (args.cuh:37, decider.cuh:96-158).
      int g = num_groups();
      double cur_bot = 0.0;
      if (!ext.empty())
        cur_bot = ctx.transfer_ms(ext.top().a, ext.top().b, ctx.grad_mb / g);
      for (const Edge& l : limbo)
        cur_bot = std::max(cur_bot,
                           ctx.transfer_ms(l.a, l.b, ctx.grad_mb / g));
      ar_parts = g > 1 ? 2.0 * (g - 1) * cur_bot : 0.0;
      ar_merged = (g - 1 > 1 && !ext.empty())
                      ? 2.0 * (g - 2) *
                            ctx.transfer_ms(ext.top().a, ext.top().b,
                                            ctx.grad_mb / (g - 1))
                      : 0.0;
    }
    double o1 = ctx.objective(ga, ar_parts);
    double o2 = ctx.objective(gb, ar_parts);
    double om = ctx.objective(merged, ar_merged);
    bool both_inf = std::isinf(o1) && std::isinf(o2);
    if (both_inf || om <= std::max(o1, o2)) {
      int root = dsu.unite(ra, rb);
      int other = (root == ra) ? rb : ra;
      members[root] = merged;
      members[other].clear();
      // limbo edges became intra-group: stay out of the pool
    } else {
      for (const Edge& l : limbo) ext.push(l);
    }
  }

  // infeasible groups merge into the nearest feasible neighbour until done
  bool changed = true;
  while (changed) {
    changed = false;
    int roots = 0;
    for (int d = 0; d < n; ++d)
      if (alive(d)) ++roots;
    if (roots <= 1) break;
    for (int r = 0; r < n && !changed; ++r) {
      if (!alive(r) || ctx.can_hold_all(members[r])) continue;
      int best = -1;
      double bestc = 1e300;
      for (int r2 = 0; r2 < n; ++r2) {
        if (r2 == r || !alive(r2)) continue;
        for (int x : members[r]) {
          for (int y : members[r2]) {
            double c = ctx.transfer_ms(x, y, act_mb);
            if (c < bestc) { bestc = c; best = r2; }
          }
        }
      }
      if (best >= 0) {
        std::vector<int> merged = members[r];
        merged.insert(merged.end(), members[best].begin(),
                      members[best].end());
        int root = dsu.unite(r, best);
        int other = (root == r) ? best : r;
        members[root] = merged;
        members[other].clear();
        changed = true;
      }
    }
  }

  // dense group ids ordered by smallest member
  std::vector<std::pair<int, int>> order;  // (min member, root)
  for (int d = 0; d < n; ++d)
    if (alive(d))
      order.push_back({*std::min_element(members[d].begin(), members[d].end()),
                       d});
  std::sort(order.begin(), order.end());
  for (size_t g = 0; g < order.size(); ++g)
    for (int d : members[order[g].second]) group_id_out[d] = (int)g;

  // rate-proportional expert assignment within each group
  for (int d = 0; d < n; ++d) expert_counts_out[d] = 0;
  for (auto& [mn, root] : order) {
    auto group = members[root];
    std::sort(group.begin(), group.end());
    double rsum = 0;
    for (int d : group) rsum += throughput[d];
    std::vector<int> budget(group.size());
    int assigned = 0;
    for (size_t i = 0; i < group.size(); ++i) {
      budget[i] = (int)std::floor(num_experts * throughput[group[i]] / rsum);
      assigned += budget[i];
    }
    // remainder to fastest devices
    std::vector<size_t> idx(group.size());
    std::iota(idx.begin(), idx.end(), 0);
    std::stable_sort(idx.begin(), idx.end(), [&](size_t a, size_t b) {
      return throughput[group[a]] > throughput[group[b]];
    });
    for (int k = 0; k < num_experts - assigned; ++k)
      budget[idx[k % group.size()]] += 1;
    for (size_t i = 0; i < group.size(); ++i)
      expert_counts_out[group[i]] = budget[i];
  }
  return 0;
}

// Library version for the ctypes loader's handshake.
int flashmoe_native_abi_version() { return 1; }

}  // extern "C"
