"""flashmoe-tpu: TPU-native distributed Mixture-of-Experts framework.

A ground-up JAX / XLA / Pallas re-design with the capability envelope of
osayamenja/FlashMoE (surveyed in SURVEY.md): fused gate, capacity/ragged
token dispatch, grouped expert FFN kernels, expert-parallel all-to-all over
TPU meshes, topology-aware expert placement, and a transformer model family
on top.
"""

__version__ = "0.1.0"

from flashmoe_tpu.config import Activation, MoEConfig, BENCH_CONFIGS
from flashmoe_tpu.ops.moe import moe_layer, MoEOutput
from flashmoe_tpu.ops.stats import MoEStats
from flashmoe_tpu.api import (
    get_bookkeeping,
    get_compiled_config,
    get_num_local_experts,
    run_moe,
)

__all__ = [
    "Activation",
    "MoEConfig",
    "BENCH_CONFIGS",
    "moe_layer",
    "MoEOutput",
    "MoEStats",
    "run_moe",
    "get_bookkeeping",
    "get_compiled_config",
    "get_num_local_experts",
]
