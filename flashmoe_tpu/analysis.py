"""Hardware-independent performance evidence: HLO cost analysis + an
analytical HBM-byte/FLOP model of every candidate execution path.

Four rounds of this framework shipped kernels whose relative performance
was argued from design notes ("dispatch/combine HBM traffic is the gap",
BASELINE.md roofline note) while the TPU tunnel was down.  This module
converts those arguments into checked numbers two ways:

  * :func:`xla_cost` measures a compiled XLA path's FLOPs / bytes with
    ``jit(...).lower().compile().cost_analysis()`` — real compiler
    numbers, available on any backend (CPU included), no execution.
  * :func:`path_costs` prices each candidate path's HBM traffic from the
    kernels' actual DMA structure (every term cites the code that moves
    those bytes).  Pallas kernels are custom calls the HLO analysis
    cannot see into, so their traffic is modeled, not measured — but
    modeled from the DMA calls in the source, and the orderings the
    model implies are asserted in ``tests/test_cost_model.py``, giving
    every hardware-blind round a perf-regression gate (VERDICT r4 next
    #2).

The reference's analogue of this accounting is the roofline analysis in
the FlashDMoE paper (arXiv:2506.04667 §5) — the repo itself ships only
measured plots (``/root/reference/README.md:29-46``).

Byte conventions: HBM bytes only (VMEM traffic is free at this
granularity); a remote DMA is counted once as a read on the sender and
once as a write on the receiver, which matches per-chip HBM pressure on
a torus where every hop is chip-to-chip.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from flashmoe_tpu.config import MoEConfig


def xla_cost(fn, *abstract_args) -> dict:
    """FLOPs / bytes-accessed of ``fn`` compiled at abstract shapes.

    ``abstract_args`` are ``jax.ShapeDtypeStruct``s (or arrays); nothing
    executes.  Returns ``{"flops": float, "bytes": float}``; either can
    be ``None`` when the backend's cost model omits the key."""
    compiled = jax.jit(fn).lower(*abstract_args).compile()
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    return {
        "flops": ca.get("flops"),
        "bytes": ca.get("bytes accessed"),
    }


def wire_row_bytes(cfg: MoEConfig, leg: str = "dispatch",
                   hop: str = "ici") -> float:
    """Bytes ONE token row occupies on the EP all-to-all wire for
    ``leg`` ('dispatch' | 'combine'): ``H x wire itemsize`` plus the
    4-byte f32 per-row scale sidecar for fp8 wires
    (:mod:`flashmoe_tpu.ops.wire`), or ``H x compute itemsize`` when the
    leg's wire is off.  Every comm term below — and the planner's slab
    serialization (:mod:`flashmoe_tpu.planner.model`) — prices the
    exchange through this one function, so the byte model can never
    disagree with the codec about what actually crosses the wire.

    ``hop`` selects the stage of a two-stage multi-slice exchange being
    priced: ``'ici'`` (default — also the flat exchange, which carries
    the leg wire end to end) or ``'dcn'``, where
    ``MoEConfig.wire_dtype_dcn`` overrides the leg wire when set (None
    inherits — both hops then price identically, matching the codec's
    single-encode path)."""
    from flashmoe_tpu.ops import wire as wr

    if leg not in ("dispatch", "combine"):
        raise ValueError(f"unknown wire leg {leg!r}")
    if hop not in ("ici", "dcn"):
        raise ValueError(f"unknown wire hop {hop!r}")
    name = cfg.wire_dtype if leg == "dispatch" else cfg.wire_dtype_combine
    if hop == "dcn" and cfg.wire_dtype_dcn is not None:
        name = cfg.wire_dtype_dcn
    wd = wr.resolve(name)
    return (wr.payload_row_bytes(wd, cfg.hidden_size, cfg.dtype)
            + wr.scale_bytes(wd))


def expert_weight_stream_bytes(cfg: MoEConfig, nlx: int, *,
                               quantized: bool = True) -> float:
    """HBM bytes ONE stream of ``nlx`` local experts' FFN weights
    costs.  With ``MoEConfig.expert_quant`` set (and ``quantized`` —
    the engine being priced actually streams the narrow store), each
    element moves at the store width (1 B for int8/e4m3,
    :func:`flashmoe_tpu.quant.core.weight_itemsize`) plus the f32
    per-output-channel scale sidecar; otherwise at the compute width.
    Every weight term in :func:`path_costs` prices through this one
    function, so the byte model can never disagree with the store
    about what actually streams.

    ``quantized=False`` is the honesty valve for engines that
    boundary-dequantize (the fused weights-once schedules — see
    ``parallel/fused.py:_fused_shard``): they stream compute-width
    weights even under a quantized store."""
    h, i = cfg.hidden_size, cfg.intermediate_size
    dt = jnp.dtype(cfg.dtype).itemsize
    w_mult = 3 if cfg.gated_ffn else 2
    if cfg.expert_quant is None or not quantized:
        return float(nlx * w_mult * h * i * dt)
    from flashmoe_tpu.quant import core as qcore

    wdt = qcore.weight_itemsize(cfg.expert_quant, cfg.dtype)
    # per-output-channel f32 scales: I channels each for up (+gate),
    # H for down — the tiny sidecar the stream also reads
    chans = (2 if cfg.gated_ffn else 1) * i + h
    return float(nlx * (w_mult * h * i * wdt
                        + qcore.scale_overhead_bytes(cfg.expert_quant,
                                                     chans)))


def layer_flops(cfg: MoEConfig, tokens: int | None = None) -> float:
    """Model FLOPs of one MoE-layer forward: gate GEMM + routed expert
    FFN (2 GEMMs, or 3 with the gated/SwiGLU branch), matching the
    reference config surface (``csrc/flashmoe_config.json``)."""
    s = tokens if tokens is not None else cfg.tokens
    gate = 2.0 * s * cfg.hidden_size * cfg.num_experts
    rows = s * cfg.expert_top_k
    gemms = 3 if cfg.gated_ffn else 2
    ffn = gemms * 2.0 * rows * cfg.hidden_size * cfg.intermediate_size
    return gate + ffn


@dataclasses.dataclass(frozen=True)
class PathCost:
    """HBM traffic decomposition of one candidate path (bytes, per chip).

    ``post_kernel_bytes`` is the subset of ``total_bytes`` that sits on
    the critical path AFTER the compute kernel finishes (an XLA combine
    stage's read+write cannot overlap the kernel; the in-kernel combine's
    traffic can).  ``weight_bytes`` is broken out because the streaming
    schedule multiplies it by ``n_row_tiles`` (VERDICT r4 weak #4)."""

    path: str
    weight_bytes: float
    activation_bytes: float
    dispatch_bytes: float
    comm_bytes: float
    combine_bytes: float
    post_kernel_bytes: float
    flops: float

    @property
    def total_bytes(self) -> float:
        return (self.weight_bytes + self.activation_bytes
                + self.dispatch_bytes + self.comm_bytes
                + self.combine_bytes)


def _geom(cfg: MoEConfig, d_world: int, fuse_combine: bool = False,
          schedule: str | None = None):
    """Shared geometry: local tokens, per-(rank, expert) capacity, row
    tiling, and the fused kernel's FFN schedule — resolved through the
    kernel's own public :func:`flashmoe_tpu.parallel.fused.
    schedule_table` (ISSUE 12 satellite: this module used to import the
    private ``_fused_schedule``/``_resolve_tiles`` helpers directly, so
    analysis/planner/census could drift from the geometry the kernel
    actually launches).  ``fuse_combine`` must mirror the path being
    priced, because the combine chunks claim VMEM the schedule gate
    accounts for (a mismatch here once under-charged the fused_combine
    table 4x; code-review r5 pass 2 finding #2).

    ``schedule`` overrides the kernel's own resolution ('batched',
    'resident', 'stream', 'rowwin') so the planner can price every
    schedule, not just the one the heuristics would pick; None keeps the
    kernel's choice.  For rowwin, ``bi`` is the IO-aware chooser's
    K-window width and ``n_i_chunks`` the window count."""
    from flashmoe_tpu.parallel.fused import schedule_table

    t = schedule_table(cfg, d_world, fuse_combine=fuse_combine,
                       schedule=schedule)
    return dict(s_loc=t["s_loc"], h=t["h"], i=t["i"], dt=t["dt"],
                cap=t["cap"], cap_raw=t["cap_raw"], cm=t["cm"],
                bi=t["bi"], gated=t["gated"], schedule=t["priced"],
                n_row_tiles=t["n_row_tiles"],
                n_i_chunks=t["n_i_chunks"])


def path_costs(cfg: MoEConfig, path: str, d_world: int = 1,
               schedule: str | None = None) -> PathCost:
    """Analytical per-chip HBM bytes for one forward of ``path``.

    Paths (single-chip unless noted):
      xla            dense-dispatch XLA baseline (``ops/moe.py``,
                     ``use_pallas=False``)
      explicit       capacity-buffer dispatch + grouped Pallas FFN
                     (``ops/expert.py:grouped_ffn``)
      gather         gather-fused inference kernel — rows pulled in-kernel,
                     no [E, C, H] dispatch buffer
                     (``ops/expert.py:grouped_ffn_tokens``)
      fused          RDMA kernel + XLA combine, d_world ranks
                     (``parallel/fused.py``, slab returns)
      fused_combine  RDMA kernel with the in-kernel sorted-return combine
                     (``parallel/fused.py`` + ``dispatch.sorted_return_maps``)

    ``schedule`` (fused paths only) forces the FFN schedule being priced;
    None resolves the kernel's actual choice.
    """
    g = _geom(cfg, d_world, fuse_combine=(path == "fused_combine"),
              schedule=schedule if path in ("fused", "fused_combine")
              else None)
    s, h, i, dt, cap = g["s_loc"], g["h"], g["i"], g["dt"], g["cap"]
    k = cfg.expert_top_k
    e = cfg.num_experts
    nlx = e // d_world
    rows = s * k                       # routed rows on this chip's tokens
    slots = d_world * nlx * cap        # slab slots touching this chip
    # EP exchange traffic of the XLA transports (d_world > 1): each a2a
    # leg reads the send buffer and writes the receive buffer — counted
    # once each per the module's remote-DMA convention, at the WIRE
    # row size (= compute row size when wire_dtype is off), so turning
    # compression on shrinks this term by the wire/compute itemsize
    # ratio (plus the fp8 scale sidecar).
    a2a_row = (wire_row_bytes(cfg, "dispatch")
               + wire_row_bytes(cfg, "combine")) if d_world > 1 else 0.0
    # weight bytes of the experts THIS chip computes, once per stream —
    # at the QUANTIZED store width when expert_quant is on.  Modeling
    # assumption (docs/PERF.md): dequant-in-compute reads the payload
    # at 1 B/elem with the convert fused into the matmul's operand
    # stream — exact for the rowwin streamer (in-VMEM dequant) and the
    # XLA einsum arm; the grouped Pallas kernels currently materialize
    # the dequantized copy layer-side, so their realized saving is
    # smaller than modeled until they grow an int8 arm — exactly the
    # class of drift `bench.py --quant` monitors.  The fused
    # weights-once schedules boundary-dequantize and are priced at
    # compute width below.
    w_once = expert_weight_stream_bytes(cfg, nlx)
    # Weight-streaming multiplicity differs per engine:
    #   * the grouped kernels (ops/expert.py) sort rows by expert, so a
    #     weight block is fetched once per consecutive expert run —
    #     explicit/gather/xla read weights ONCE per expert;
    #   * the fused RDMA kernel's multiplicity depends on its FFN
    #     schedule (parallel/fused.py:_fused_schedule): the per-source
    #     schedules re-stream every local expert's weights once per
    #     source rank — d_world x (times n_row_tiles when streaming
    #     per row tile); the round-5 arrival-batched schedule processes
    #     the own slab at step 0 and every remote slab expert-major at
    #     the final step, streaming weights exactly TWICE.  The d_world
    #     factor was this model's headline finding (BASELINE.md round-5
    #     reading #2) and motivated the batched schedule.  The
    #     row-windowed schedule (ISSUE 12) makes the same 2-pass
    #     guarantee WITHOUT holding anything weights-once in VMEM:
    #     window-major / row-minor order streams each K-window once per
    #     pass (own slab at step 0, batched remotes at the final step),
    #     so its weight column matches batched — the d x n_row_tiles
    #     collapse that rescues mixtral-width experts from the 40x
    #     stream column (BASELINE.md's updated caveat).
    fused_streams = {
        "batched": 2 if d_world > 1 else 1,
        "resident": d_world,
        "rowwin": 2 if d_world > 1 else 1,
        "stream": d_world * g["n_row_tiles"],
    }[g["schedule"]]
    gate_bytes = s * h * dt + h * e * dt
    flops = layer_flops(cfg, tokens=s)

    if path == "xla":
        # dense dispatch builds [E, C, H] with a gather, the einsum FFN
        # streams weights once (read buf + write y), the combine gathers
        # k rows per token.  XLA may additionally materialize the
        # [slots, i] hidden when fusion fails — NOT charged, keeping the
        # baseline's modeled bytes a lower bound so beating it
        # analytically means beating its best case.
        dispatch = s * h * dt + slots * h * dt        # read x, write buf
        ffn = slots * h * dt + slots * h * dt         # read buf, write y
        combine = rows * h * dt + s * h * 4
        return PathCost(path, w_once, gate_bytes + ffn, dispatch,
                        0.0, combine, combine, flops)
    if path == "explicit":
        dispatch = s * h * dt + slots * h * dt
        combine = rows * h * dt + s * h * 4
        # both a2a legs move full capacity slabs (ep._ep_moe_shard) —
        # at the layer's UNPADDED capacity: the XLA transport exchanges
        # the [E, C, H] buffer as-is; only the fused kernel RDMAs
        # 32-padded slabs.  This term used to charge the padded
        # capacity, overpricing e.g. deepseek's C=60 exchange by 64/60
        # — caught by the collective census
        # (flashmoe_tpu/staticcheck/census.py) reconciling this model
        # against the planner's slab_bytes and the lowered graph.
        comm = 2 * (d_world * nlx * g["cap_raw"]) * a2a_row
        return PathCost(path, w_once,
                        gate_bytes + slots * h * dt + slots * h * dt,
                        dispatch, comm, combine, combine, flops)
    if path == "gather":
        # no dispatch buffer: the kernel's per-row DMAs read exactly the
        # routed rows (ops/expert.py:grouped_ffn_tokens)
        combine = rows * h * dt + s * h * 4
        return PathCost(path, w_once,
                        gate_bytes + rows * h * dt + rows * h * dt,
                        0.0, 0.0, combine, combine, flops)
    if path == "ragged":
        # dropless ragged EP (parallel/ragged_ep.py): tokens sort into
        # expert-contiguous rows with NO capacity padding — under the
        # uniform-routing expectation exactly the s*k routed rows move
        # (a skewed batch moves more; this prices the expectation, the
        # same stance the capacity paths take on padding).  Build the
        # sorted send rows, FFN reads/writes them, combine gathers k
        # rows per token.
        dispatch = s * h * dt + rows * h * dt
        combine = rows * h * dt + s * h * 4
        # both ragged a2a legs move exactly the routed rows
        comm = 2 * rows * a2a_row
        return PathCost(path, w_once,
                        gate_bytes + rows * h * dt + rows * h * dt,
                        dispatch, comm, combine, combine, flops)
    if path in ("fused", "fused_combine"):
        # dispatch builds x_send; phase-1 RDMAs read x_send and write
        # x_recv on the peers (slots bytes each side); the FFN streams
        # x_recv once (two-pass schedules: n_i_chunks times) + weights;
        # stage to y_stage and return-RDMA to the source (read + write)
        dispatch = s * h * dt + slots * h * dt
        comm = 2 * slots * h * dt                     # x out + x in
        x_refactor = (g["n_i_chunks"] if g["schedule"] != "stream" else 1)
        act_bytes = (gate_bytes + slots * h * dt * x_refactor
                     + slots * h * dt)                # x_recv reads + y_stage
        if g["schedule"] == "rowwin":
            # the honest price of window-major row-windowing: every
            # resident row round-trips its f32 partial sum through the
            # HBM accumulator at each INTERIOR window boundary (the
            # first window starts from zero, the last folds straight
            # into y_stage) — 4 B read + 4 B write per element per
            # boundary.  This is the term BASELINE.md's caveat demanded
            # the model charge before believing the 2x weight column.
            act_bytes += (g["n_i_chunks"] - 1) * slots * h * 8.0
        if path == "fused_combine":
            # sorted per-row returns carry only the rows actually routed
            # (dispatch.sorted_return_maps): rows*h out + rows*h in — the
            # slab path below returns full capacity-padded slabs, which
            # overstated this path's comm at capacity_factor > 1
            # (ADVICE round 5)
            comm += 2 * rows * h * dt                 # y back out + in
        else:
            comm += 2 * slots * h * dt                # y back out + in
        if path == "fused":
            combine = slots * h * dt + s * h * 4      # XLA reads y_recv
            post = combine
        else:
            # drain combine reads the sorted rows + writes out f32 —
            # inside the kernel, off the post-kernel critical path
            combine = rows * h * dt + (rows * 4) + s * h * 4
            post = 0.0
        # only the rowwin streamer fetches the quantized store
        # in-kernel; the weights-once schedules boundary-dequantize
        # (parallel/fused.py:_fused_shard) and stream compute-width
        # weights, so their column must not claim the int8 discount
        w_stream = expert_weight_stream_bytes(
            cfg, nlx, quantized=(g["schedule"] == "rowwin"))
        return PathCost(path, w_stream * fused_streams, act_bytes,
                        dispatch, comm, combine, post, flops)
    raise ValueError(f"unknown path {path!r}")


def a2a_transport_cost(d: int, inner: int, slab_bytes: float,
                       gen: str = "v5e", links: int = 1,
                       chunks: int = 1,
                       dcn_slab_bytes: float | None = None) -> dict:
    """Model the flat vs two-stage (ICI+DCN) all-to-all on a ``d``-rank
    ep axis spanning ``d // inner`` slices, per rank per direction
    (``parallel/ep.py:_hierarchical_a2a``; the reference's per-peer
    P2P-vs-IBGDA transport split, ``bootstrap.cuh:442-446`` /
    ``os/packet.cuh:221-258``).

    ``slab_bytes`` is one (dest-rank) slab.  Flat: one message per peer
    — ``d - inner`` of them cross DCN.  Hierarchical: stage 1 reorders
    within the slice over ICI ((inner-1) messages of outer slabs), stage
    2 sends ONE aggregated message per remote slice ((outer-1) messages
    of inner slabs) — identical cross-slice bytes, ``inner``x fewer DCN
    messages, so the alpha term shrinks by (inner-1)(outer-1) DCN
    latencies at the price of (outer-1) extra in-slice slab transfers.

    ``links``: ICI links per chip striping each in-slice transfer (the
    beta term divides; per-message alpha and the host-NIC DCN path do
    not) — pass the mesh's link count so single-slice and multi-slice
    predictions stay comparable (planner code-review finding).

    ``chunks``: the chunked-pipeline depth (``MoEConfig.a2a_chunks``) —
    each per-peer slab splits into ``chunks`` messages of
    ``slab_bytes / chunks``, so the beta (serialization) terms are
    unchanged while every per-message alpha multiplies by ``chunks``.
    This is the chunking overhead the planner's overlap-adjusted
    makespan (:mod:`flashmoe_tpu.planner.model`) charges against the
    pipeline's hiding: more chunks hide more compute but pay more
    message latencies — the IO-aware tradeoff SonicMoE's tile knob
    makes (arXiv 2512.14080).

    ``dcn_slab_bytes``: the per-dest slab at the CROSS-SLICE hop's own
    wire row size (``MoEConfig.wire_dtype_dcn`` via
    :func:`wire_row_bytes` ``hop='dcn'``; default None = ``slab_bytes``
    — the inherit case).  Only the hierarchical DCN stage re-encodes,
    so only its serialization term uses it; the flat exchange carries
    the leg wire across DCN unchanged — which is exactly the modeled
    gap an fp8 DCN hop opens over flat (docs/PERF.md "Multi-slice
    scale-out").
    """
    from flashmoe_tpu.parallel.topology import _DCN_SPEC, _ICI_SPECS

    if inner < 1 or d % inner:
        raise ValueError(
            f"ep axis d={d} is not divisible into slices of inner={inner} "
            f"ranks; the two-stage decomposition needs d % inner == 0")
    if chunks < 1:
        raise ValueError(f"chunks={chunks} must be >= 1")
    a_ici, bw_ici = _ICI_SPECS.get(gen, _ICI_SPECS["default"])
    a_dcn, bw_dcn = _DCN_SPEC
    a_ici, a_dcn = a_ici / 1e3, a_dcn / 1e3              # ms
    a_ici, a_dcn = a_ici * chunks, a_dcn * chunks        # n msgs/peer
    bw_ici = bw_ici * 1e6 * max(links, 1)                # B/ms, striped
    bw_dcn = bw_dcn * 1e6                                # B/ms
    outer = d // inner
    dcn_slab = slab_bytes if dcn_slab_bytes is None else dcn_slab_bytes
    flat = {
        "dcn_messages": (d - inner) * chunks,
        "dcn_ms": (d - inner) * (a_dcn + slab_bytes / bw_dcn),
        "ici_ms": (inner - 1) * (a_ici + slab_bytes / bw_ici),
    }
    hier = {
        "dcn_messages": (outer - 1) * chunks,
        "dcn_ms": (outer - 1) * (a_dcn + inner * dcn_slab / bw_dcn),
        "ici_ms": (inner - 1) * (a_ici + outer * slab_bytes / bw_ici),
    }
    for c in (flat, hier):
        c["total_ms"] = c["dcn_ms"] + c["ici_ms"]
    return {"flat": flat, "hierarchical": hier}


def comm_census(cfg: MoEConfig, d: int, path: str) -> dict:
    """Expected *lowered-graph* collective census of one XLA-transport
    MoE layer at ``(cfg, d ranks)`` — the statically-checkable
    counterpart of :func:`path_costs`'s HBM comm model, consumed by
    :mod:`flashmoe_tpu.staticcheck.census` which reconciles it against
    the jaxpr the layer actually traces to.

    Two model sources are deliberately combined and cross-checked
    against each other here: per-leg wire bytes come from the planner's
    slab accounting (``planner.model.slab_bytes``, the quantity the
    ici/dcn terms serialize) while the total is asserted against this
    module's :func:`path_costs` ``comm_bytes`` (the read+write HBM
    convention: exactly 2x the one-sided wire bytes).  A change that
    moves one model but not the other — the class of drift that
    once under-charged the fused_combine table 4x — fails here before
    any graph is even traced.

    Paths: ``collective`` (flat a2a), ``hierarchical`` (two-stage
    exchange — each stage moves the full local buffer, so the graph
    carries 2x the flat leg bytes: the documented staging cost of
    aggregating DCN messages), ``ragged`` (dense fallback arm — the CPU
    trace pads every transfer to the worst-case bound, so graph bytes
    are exactly ``d x chunks`` times the uniform-routing expectation
    ``path_costs`` prices; the TPU ``ragged_all_to_all`` arm moves the
    data-dependent exact rows instead).

    Returns per-rank expectations::

        legs          {dispatch: bytes, combine: bytes}  wire payload
                      + fp8 scale sidecar per leg, as traced
        a2a_eqns      all_to_all count (payload + sidecar + metadata)
        gather_eqns   all_gather count (ragged count-matrix machinery)
        meta_bytes    metadata collective bytes per primitive
                      (counts/sizes, not token rows)
        psum_eqns     loss/count reductions (EXPECTED_PSUMS contract)
        bound_factor  graph-bytes / model-expectation per leg (1 for
                      the capacity paths; d x chunks for ragged-dense)
        model_comm_bytes   path_costs(...).comm_bytes, for reference
    """
    from flashmoe_tpu.ops import wire as wr
    from flashmoe_tpu.parallel.ep import EXPECTED_PSUMS
    from flashmoe_tpu.planner.model import slab_bytes

    if path not in ("collective", "hierarchical", "ragged"):
        raise ValueError(
            f"comm_census covers the XLA transports only, not {path!r} "
            f"(the fused RDMA kernel is a custom call the jaxpr census "
            f"cannot see into; its traffic is modeled in path_costs)")
    chunks = cfg.a2a_chunks or 1
    stages = 2 if path == "hierarchical" else 1
    wires = {"dispatch": wr.resolve(cfg.wire_dtype),
             "combine": wr.resolve(cfg.wire_dtype_combine)}
    cost = path_costs(cfg, "ragged" if path == "ragged" else "explicit",
                      d_world=d)

    legs: dict[str, float] = {}
    a2a = 0
    if path == "ragged":
        n_assign = (cfg.tokens // d) * cfg.expert_top_k
        bound_factor = float(d * chunks)
        for leg, wd in wires.items():
            legs[leg] = bound_factor * n_assign * (
                wr.payload_row_bytes(wd, cfg.hidden_size, cfg.dtype)
                + wr.scale_bytes(wd))
            a2a += chunks * (1 + (1 if wr.is_fp8(wd) else 0))
        nlx = cfg.num_experts // d
        if chunks > 1:
            # one all_gather of the [dest, nLx] count matrix
            # (ragged_ep._chunked_ragged_exchange) derives every chunk's
            # offsets; no metadata a2a
            gather_eqns, meta_a2a = 1, 0
            meta_bytes = {"all_gather": float(d * nlx * 4),
                          "all_to_all": 0.0}
        else:
            # serial: all_gather of the [D] send sizes + one
            # count-matrix a2a (ragged_ep._ragged_ep_shard)
            gather_eqns, meta_a2a = 1, 1
            meta_bytes = {"all_gather": float(d * 4),
                          "all_to_all": float(d * nlx * 4)}
        a2a += meta_a2a
    else:
        bound_factor = 1.0
        gather_eqns = 0
        meta_bytes = {"all_gather": 0.0, "all_to_all": 0.0}
        if path == "hierarchical":
            # per-hop staging (ISSUE 13): the inner (ICI) stage moves
            # the leg-wire buffer, the outer (DCN) stage the DCN-wire
            # buffer (wire_dtype_dcn; equal when it inherits — the
            # codec's single-encode path, where this reduces exactly to
            # the old stages x flat formula)
            wd_dcn = wr.resolve(cfg.wire_dtype_dcn)
            for leg, wd in wires.items():
                legs[leg] = d * (slab_bytes(cfg, d, leg=leg, hop="ici")
                                 + slab_bytes(cfg, d, leg=leg,
                                              hop="dcn"))
                hop_dcn = wd_dcn if wd_dcn is not None else wd
                a2a += chunks * ((1 + (1 if wr.is_fp8(wd) else 0))
                                 + (1 + (1 if wr.is_fp8(hop_dcn)
                                         else 0)))
        else:
            # flat transports carry the leg wire end to end; the DCN
            # override has no hop to re-encode and must price as off
            for leg, wd in wires.items():
                legs[leg] = d * slab_bytes(cfg, d, leg=leg)
                a2a += chunks * (1 + (1 if wr.is_fp8(wd) else 0))

    # cross-check the two model sources against each other: the graph
    # legs must equal the HBM model's one-sided bytes times the
    # documented structural multipliers.  The hierarchical per-hop
    # variant derives each hop's side from path_costs independently —
    # the ICI hop from the config as-is, the DCN hop from the config
    # with the resolved DCN wire as its leg wire — so planner slabs and
    # the HBM model still cross-check per hop.
    if path == "hierarchical":
        cfg_dcn = (cfg.replace(wire_dtype=cfg.wire_dtype_dcn,
                               wire_dtype_combine=cfg.wire_dtype_dcn,
                               wire_dtype_dcn=None)
                   if cfg.wire_dtype_dcn is not None else cfg)
        cost_dcn = path_costs(cfg_dcn, "explicit", d_world=d)
        want = (cost.comm_bytes + cost_dcn.comm_bytes) / 2.0
    else:
        want = cost.comm_bytes / 2.0 * stages * bound_factor
    got = sum(legs.values())
    if abs(got - want) > 1e-6 * max(want, 1.0):
        raise AssertionError(
            f"analysis/planner byte models disagree for {path!r} at "
            f"d={d}: planner slabs give {got:.1f} B of graph wire "
            f"bytes, path_costs.comm_bytes implies {want:.1f} B — one "
            f"model moved without the other")
    return {
        "path": path, "chunks": chunks, "stages": stages, "legs": legs,
        "a2a_eqns": a2a, "gather_eqns": gather_eqns,
        "meta_bytes": meta_bytes, "psum_eqns": EXPECTED_PSUMS,
        "bound_factor": bound_factor,
        "model_comm_bytes": cost.comm_bytes,
    }


def chunked_pipeline_ms(chip_ms: float, dispatch_leg_ms: float,
                        combine_leg_ms: float, chunks: int) -> float:
    """Makespan of the chunked double-buffered EP schedule
    (``MoEConfig.a2a_chunks``) on the XLA transports — the
    overlap-adjusted cost the planner uses in place of the serial
    ``chip + dispatch + combine`` sum.

    ``dispatch_leg_ms`` / ``combine_leg_ms`` are the FULL chunked leg
    times (alpha already multiplied by ``chunks`` —
    :func:`a2a_transport_cost`); each chunk's share is ``leg / chunks``.
    Two-resource pipeline bound over ``chunks`` independent
    a2a -> FFN -> a2a chains:

      * compute-bound: the MXU runs continuously once chunk 0's
        dispatch lands, and the last chunk's combine trails it —
        ``chip + (dispatch + combine) / n``;
      * wire-bound: the wire runs continuously except for chunk 0's
        FFN fill — ``dispatch + combine + chip / n``.

    ``chunks=1`` reduces exactly to the serial makespan, so one formula
    prices both schedules."""
    if chunks < 1:
        raise ValueError(f"chunks={chunks} must be >= 1")
    e_total = dispatch_leg_ms + combine_leg_ms
    return max(chip_ms + e_total / chunks, e_total + chip_ms / chunks)


def candidate_table(cfg: MoEConfig, d_world: int = 1) -> str:
    """Markdown table of every path's modeled bytes at ``cfg`` — the
    BASELINE.md evidence table (VERDICT r4 next #2)."""
    paths = ["xla", "explicit", "gather", "ragged", "fused",
             "fused_combine"]
    lines = [
        f"| path | weights MB | acts MB | dispatch MB | comm MB | "
        f"combine MB | total MB | post-kernel MB |",
        "|---|---|---|---|---|---|---|---|",
    ]
    for p in paths:
        c = path_costs(cfg, p, d_world=d_world)
        mb = lambda b: f"{b / 2**20:.1f}"
        lines.append(
            f"| {p} | {mb(c.weight_bytes)} | {mb(c.activation_bytes)} | "
            f"{mb(c.dispatch_bytes)} | {mb(c.comm_bytes)} | "
            f"{mb(c.combine_bytes)} | {mb(c.total_bytes)} | "
            f"{mb(c.post_kernel_bytes)} |")
    return "\n".join(lines)


def main():
    import argparse

    from flashmoe_tpu.config import BENCH_CONFIGS

    ap = argparse.ArgumentParser()
    ap.add_argument("--config", default="reference",
                    choices=sorted(BENCH_CONFIGS.keys()))
    ap.add_argument("--d-world", type=int, default=1)
    args = ap.parse_args()
    cfg = BENCH_CONFIGS[args.config]
    print(f"# {args.config}: E={cfg.num_experts} k={cfg.expert_top_k} "
          f"H={cfg.hidden_size} I={cfg.intermediate_size} S={cfg.tokens} "
          f"d_world={args.d_world}")
    print(candidate_table(cfg, d_world=args.d_world))


if __name__ == "__main__":
    main()
