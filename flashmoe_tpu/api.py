"""Top-level API facade — parity with the reference's ``flashmoe.ops``.

Reference surface (``flashmoe/ops.py:18-71``, ``flashmoe/__init__.py``):
``run_moe(n_processes, processes_per_node, hostfile, config_path)`` and
``get_compiled_config()``.  Here ``run_moe`` launches worker processes over
the local devices, and ``get_compiled_config`` returns the active config
(the reference compiles it in; we specialize at jit time, so the "compiled"
config is the runtime's).
"""

from __future__ import annotations

import dataclasses

from flashmoe_tpu.config import MoEConfig
from flashmoe_tpu.runtime import bootstrap
from flashmoe_tpu.runtime.launcher import run_workers


def run_moe(n_processes: int = 1, processes_per_node: int | None = None,
            hostfile: str | None = None,
            config_path: str | None = None, *, bench: bool = False) -> int:
    """Launch the MoE workers (reference ``flashmoe.run_moe``).

    ``processes_per_node``/``hostfile`` are accepted for interface parity;
    multi-host TPU jobs are normally scheduler-launched (see
    :func:`flashmoe_tpu.runtime.launcher.slurm_command`).
    """
    del processes_per_node, hostfile  # scheduler-managed on TPU
    return run_workers(n_processes, config_path=config_path, bench=bench)


def get_compiled_config() -> dict:
    """The active configuration as a dict (reference
    ``get_compiled_config``, ``python_bindings.cu:194-217``)."""
    try:
        cfg = bootstrap.get_runtime().cfg
    except RuntimeError:
        cfg = MoEConfig()
    d = dataclasses.asdict(cfg)
    for k in ("dtype", "param_dtype", "accum_dtype"):
        d[k] = str(d[k].__name__ if hasattr(d[k], "__name__") else d[k])
    return d


def get_num_local_experts() -> int:
    """Reference ``get_num_local_experts`` (``python_bindings.cu:187``)."""
    return bootstrap.get_runtime().num_local_experts


def get_bookkeeping() -> dict:
    """Runtime state summary — the spiritual analogue of the reference's
    ``get_bookkeeping`` binding (``python_bindings.cu:180-184``, which
    exposes bookkeeping-derived state) extended to the full runtime view:
    mesh geometry, placement, process info.  Returns copies; mutating the
    result never touches the live Runtime."""
    rt = bootstrap.get_runtime()
    return {
        "mesh": dict(rt.mesh.shape),
        "groups": [list(g) for g in rt.placement.groups],
        "local_experts": {
            int(k): list(v) for k, v in rt.placement.local_experts.items()
        },
        "num_processes": rt.num_processes,
        "process_id": rt.process_id,
        "num_local_experts": rt.num_local_experts,
    }
