"""Chaos engineering for the fault-tolerance ladder.

The three recovery tiers (``docs/RESILIENCE.md``) only count if each rung
is *proven* to catch its fault class.  This package provides
deterministic, seeded fault injectors behind one :class:`FaultPlan` API,
plugging into two places:

  * **in-graph points** (:mod:`flashmoe_tpu.chaos.inject`): NaN expert
    outputs, router skew, gradient NaN/spikes — spliced into the traced
    computation, exercising tier 0 (expert masking) and tier 1 (update
    skipping);
  * **host-level hooks**: :func:`make_injector` returns a
    ``fail_injector(step)`` for :func:`flashmoe_tpu.runtime.resilient.
    resilient_train` (checkpoint corruption, path failures) and
    :func:`wrap_step` wraps a train step (stalls) — exercising tier 2
    (timeout + restore, intact-fallback restore, planner path fallback).

``python -m flashmoe_tpu.chaos`` runs the full drill matrix against a
small model and reports recovery outcome, loss-of-work, and telemetry
evidence per fault (:mod:`flashmoe_tpu.chaos.drill`).
"""

from __future__ import annotations

import dataclasses
import os
import time

from flashmoe_tpu.chaos import inject

#: the drill matrix: every fault class the ladder claims to survive
FAULTS = ("nan_expert", "nan_grad", "grad_spike", "slow_step",
          "corrupt_ckpt", "skewed_routing", "path_raise", "preempt",
          "device_loss", "skew_sustained", "slow_device",
          "dcn_latency", "dcn_jitter",
          "replica_crash", "handoff_corrupt", "handoff_timeout",
          "frontdoor_loss",
          "net_partition", "lease_split_brain", "replica_stall",
          "lease_torn_write")

#: which recovery tier is expected to absorb each fault.  The
#: ``controller:*`` tiers are the self-healing runtime controller
#: (docs/RESILIENCE.md "Self-healing controller"): the fault is not a
#: crash but a sustained PERFORMANCE/QUALITY regression, and recovery
#: means the controller repairs it mid-job — path morphing for
#: sustained routing skew, Decider re-placement for a degraded device.
EXPECTED_TIER = {
    "nan_expert": "tier0:expert_mask",
    "skewed_routing": "tier0:telemetry",
    "nan_grad": "tier1:skip_update",
    "grad_spike": "tier1:skip_update",
    "slow_step": "tier2:timeout_retry",
    "corrupt_ckpt": "tier2:fallback_restore",
    "path_raise": "tier2:planner_fallback",
    "preempt": "tier3:drain_resume",
    "device_loss": "tier3:elastic_refold",
    "skew_sustained": "controller:morph",
    "slow_device": "controller:replace",
    # DCN faults are SERVING faults: they never crash anything — they
    # stretch handoff transfers on the fabric's virtual clock, and the
    # recovery claim is observability: the measured-vs-priced monitor
    # (``fabric.handoff_drift``) must expose the degradation while the
    # per-request attribution stays exact
    "dcn_latency": "monitor:handoff_drift",
    "dcn_jitter": "monitor:handoff_drift",
    # the serving fault-tolerance ladder (docs/RESILIENCE.md
    # "Serving-side ladder"): a crashed decode replica's requests
    # MIGRATE via deterministic re-prefill; a corrupt or timed-out KV
    # handoff is caught by the transport's per-page CRC32 verify /
    # deadline and RETRIED with capped backoff; a dead front-door peer
    # fails its namespace leases over to the survivors
    "replica_crash": "fabric:migrate",
    "handoff_corrupt": "fabric:handoff_retry",
    "handoff_timeout": "fabric:handoff_retry",
    "frontdoor_loss": "fabric:frontdoor_failover",
    # cross-process faults (PR 19): a wire that drops a transfer
    # mid-stream is retried on a fresh connection; a zombie door
    # re-asserting a revoked lease is REFUSED by the store's epoch
    # fencing; a replica that hangs mid-step (not dead — the probe
    # still answers) is caught by the sub-step heartbeat watchdog and
    # migrated; a lease writer killed mid-append is rolled back to the
    # last intact CRC-framed record
    "net_partition": "fabric:partition_retry",
    "lease_split_brain": "fabric:lease_fence",
    "replica_stall": "fabric:heartbeat_migrate",
    "lease_torn_write": "fabric:lease_repair",
}


@dataclasses.dataclass
class FaultPlan:
    """One deterministic fault to inject.

    ``fault``: one of :data:`FAULTS`.
    ``step``:  the step index the fault fires at (host faults fire when
               the training loop reaches it; the in-graph gradient
               faults compare against the traced ``state.step``).
    ``expert``: target expert for nan_expert / skewed_routing; doubles
               as the target REPLICA for replica_crash (``expert %
               n_replicas``) and the dying front-door PEER for
               frontdoor_loss.
    ``scale``: gradient multiplier for grad_spike.
    ``bias``:  router logit bias for skewed_routing.
    ``sleep_s``: stall duration for slow_step (must exceed the
               ResilienceConfig step deadline to be detected) and the
               full-degradation stall for slow_device.
    ``once``:  host faults fire once then disarm (the transient-fault
               model); False = fire at every visit of ``step``.
    ``duration``: how many consecutive steps a SUSTAINED fault holds —
               ``slow_step`` stalls every step in ``[step, step +
               duration)`` (each visited step at most once under
               ``once``), ``slow_device`` degrades from ``step`` for
               ``duration`` steps, and the drill harness keeps
               ``skew_sustained`` armed that long.  Default 1 keeps
               every pre-existing single-shot drill byte-compatible.
               The self-healing controller's debounce window requires
               sustained faults: a one-step blip must never trigger a
               morph or re-placement.  For the DCN faults AND the
               handoff transport faults (handoff_corrupt /
               handoff_timeout / net_partition) the window is over
               TRANSFER index, not
               engine step; with ``once`` a faulted transfer's retry
               is clean (exactly one retry), with ``once=False`` every
               attempt fails until the retry budget gives up.
    ``latency_ms``: extra DCN delay added to every handoff transfer in
               the window (dcn_latency — a degraded inter-slice link).
    ``jitter_ms``: upper bound of the deterministic per-transfer jitter
               (dcn_jitter — crc32 of ``(seed, transfer index)`` maps
               each transfer to a fraction of this bound).
    ``seed``:  reserved for randomized plans; recorded for provenance
               (the dcn_jitter hash consumes it).
    """

    fault: str
    step: int = 3
    expert: int = 0
    scale: float = 1e4
    bias: float = 100.0
    sleep_s: float = 2.0
    once: bool = True
    duration: int = 1
    latency_ms: float = 0.0
    jitter_ms: float = 0.0
    seed: int = 0

    def __post_init__(self):
        if self.fault not in FAULTS:
            raise ValueError(
                f"unknown fault {self.fault!r}; known: {FAULTS}")
        if self.duration < 1:
            raise ValueError(f"duration must be >= 1, "
                             f"got {self.duration}")


def clear() -> None:
    """Disarm every in-graph point and forget reported path failures —
    call between drills so faults never leak across scenarios."""
    inject.disarm()
    from flashmoe_tpu.planner import select

    select.reset_path_failures()


def arm_plan(plan: FaultPlan) -> None:
    """Arm the plan's in-graph injection point (no-op for host faults).
    Arm BEFORE building/jitting the computation under test."""
    if plan.fault == "nan_expert":
        inject.arm("nan_expert", expert=plan.expert)
    elif plan.fault in ("skewed_routing", "skew_sustained"):
        # skew is armed at trace time and poisons every traced step: a
        # ``skew_sustained`` plan is the same injection, drilled long
        # enough (``duration``) to cross the controller's debounce
        # window and force a morph instead of mere telemetry
        inject.arm("skewed_routing", expert=plan.expert, bias=plan.bias)
    elif plan.fault == "nan_grad":
        inject.arm("nan_grad", step=plan.step)
    elif plan.fault == "grad_spike":
        inject.arm("grad_spike", step=plan.step, scale=plan.scale)


def _corrupt_latest_checkpoint(directory: str) -> str | None:
    """Flip bytes in the newest checkpoint's largest payload file.
    Returns the corrupted path (None when there is nothing to corrupt)."""
    from flashmoe_tpu.runtime import checkpoint as ckpt

    step = ckpt.latest_step(directory)
    if step is None:
        return None
    victim, size = None, -1
    for root, _dirs, files in os.walk(ckpt.step_dir(directory, step)):
        for f in files:
            p = os.path.join(root, f)
            s = os.path.getsize(p)
            if s > size:
                victim, size = p, s
    if victim is None:
        return None
    with open(victim, "r+b") as f:
        f.seek(max(0, size // 2))
        f.write(b"\xde\xad\xbe\xef")
    return victim


def make_injector(plan: FaultPlan, rcfg=None, preempt=None):
    """A ``fail_injector(step)`` callable for ``resilient_train`` that
    fires the plan's HOST-level fault (corrupt_ckpt / path_raise /
    preempt / device_loss).  In-graph and wrapper faults return a no-op
    injector so one code path installs any plan.

    ``preempt``: the run's :class:`flashmoe_tpu.runtime.preempt.
    PreemptionListener` — the ``preempt`` fault notifies it
    programmatically (a deterministic SIGTERM stand-in).
    ``device_loss`` keeps raising at ``plan.step`` until the in-job
    retry budget is spent, modelling a device that stays gone until the
    process is restarted on the survivors."""
    fired = {"n": 0}

    def injector(i: int):
        if plan.fault == "device_loss":
            # persistent until the retry budget forces a process-level
            # restart: ``once`` semantics would let restore-and-retry
            # absorb it in-job, which a lost device never allows
            budget = getattr(rcfg, "max_retries", 3) + 1
            if i == plan.step and fired["n"] < budget:
                fired["n"] += 1
                raise RuntimeError(
                    f"chaos: injected device loss at step {i} "
                    f"({fired['n']}/{budget})")
            return
        if i != plan.step or (plan.once and fired["n"]):
            return
        if plan.fault == "corrupt_ckpt":
            fired["n"] += 1
            directory = getattr(rcfg, "checkpoint_dir", None)
            if directory:
                _corrupt_latest_checkpoint(directory)
            raise RuntimeError(
                f"chaos: injected crash after corrupting newest "
                f"checkpoint in {directory!r} (step {i})")
        if plan.fault == "path_raise":
            fired["n"] += 1
            from flashmoe_tpu.planner.select import PathFailure

            raise PathFailure(
                "fused", f"chaos: injected path failure at step {i}")
        if plan.fault == "preempt":
            fired["n"] += 1
            if preempt is not None:
                # the step loop finishes THIS step, then drains: the
                # notice lands mid-step exactly like a real SIGTERM
                preempt.notify(source="chaos")

    return injector


def wrap_step(step_fn, plan: FaultPlan, deadline_s: float | None = None,
              load_share=None):
    """Wrap a train step with the plan's stall fault.

    ``slow_step``: the wrapped step sleeps ``plan.sleep_s`` at every
    step in ``[plan.step, plan.step + plan.duration)`` (each visited
    step at most once under ``plan.once``), which the resilient
    runner's wall-clock deadline converts into a detected StepFailure.

    ``slow_device``: models one DEGRADED (not dead) device gating the
    collective — the step slows by the share of expert work parked on
    that device: sleep = ``plan.sleep_s * load_share(step)``, sustained
    from ``plan.step`` for ``plan.duration`` steps.  ``load_share`` is
    the drill's probe of the live placement (e.g. ``controller.
    device_load_share(slow_dev) / rate``): once the self-healing
    controller re-places the hot experts off the slow device, the share
    — and the stall — collapses.  Defaults to a constant 1.0.

    Other faults pass through untouched."""
    if plan.fault == "slow_step":
        fired: set = set()

        def wrapped(state, batch):
            i = int(state.step)
            in_window = plan.step <= i < plan.step + plan.duration
            if in_window and not (plan.once and i in fired):
                fired.add(i)
                time.sleep(plan.sleep_s)
            return step_fn(state, batch)

        return wrapped
    if plan.fault == "slow_device":
        def wrapped(state, batch):
            i = int(state.step)
            if plan.step <= i < plan.step + plan.duration:
                share = float(load_share(i)) if load_share is not None \
                    else 1.0
                if share > 0:
                    time.sleep(plan.sleep_s * share)
            return step_fn(state, batch)

        return wrapped
    return step_fn


__all__ = ["FAULTS", "EXPECTED_TIER", "FaultPlan", "arm_plan", "clear",
           "inject", "make_injector", "wrap_step"]
