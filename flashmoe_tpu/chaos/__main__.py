"""Chaos drill CLI: ``python -m flashmoe_tpu.chaos``.

Runs the fault matrix (:data:`flashmoe_tpu.chaos.FAULTS`) against a
small model and reports, per fault: recovery outcome, the tier that
absorbed it, loss-of-work, and the telemetry evidence.  Exit code 0 iff
every drilled fault recovered — CI-able.

``--obs-dir`` exports the postmortem artifacts next to the report:
``decisions.jsonl`` (every structured decision the drills produced —
planner fallbacks, checkpoint fallbacks, skipped updates) and
``drill_results.jsonl`` (one result object per fault), the same
artifact convention as ``bench.py --obs-dir``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def _ensure_virtual_devices(n: int = 2) -> None:
    """The ``device_loss`` drill shrinks the world across a restart,
    which needs at least two devices.  On a plain CPU host, ask XLA for
    virtual ones.  jax is already imported by the package ``__init__``
    at this point, but XLA only reads the flag when a BACKEND first
    initializes — so setting the env here still works as long as
    nothing has called into jax yet (harmlessly ignored otherwise)."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count={n}".strip())


def main(argv=None) -> int:
    _ensure_virtual_devices()
    p = argparse.ArgumentParser(
        prog="python -m flashmoe_tpu.chaos",
        description="drill the fault-tolerance ladder (docs/RESILIENCE.md)")
    p.add_argument("--faults", default=None,
                   help="comma-separated subset (default: full matrix)")
    p.add_argument("--fault", action="append", default=None,
                   metavar="NAME",
                   help="drill a single fault (repeatable; composes "
                        "with --faults) — the CI fast path for smoking "
                        "one fault without the full slow matrix")
    p.add_argument("--steps", type=int, default=6,
                   help="training steps per drill (default 6)")
    p.add_argument("--checkpoint-every", type=int, default=2,
                   help="checkpoint interval (default 2)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--obs-dir", default=None,
                   help="export decisions.jsonl + drill_results.jsonl here")
    p.add_argument("--json", action="store_true",
                   help="print results as JSON instead of the table")
    args = p.parse_args(argv)

    from flashmoe_tpu.chaos import FAULTS
    from flashmoe_tpu.chaos.drill import run_drill

    faults = ([f.strip() for f in args.faults.split(",") if f.strip()]
              if args.faults else [])
    for f in args.fault or []:
        if f.strip() and f.strip() not in faults:
            faults.append(f.strip())
    if not args.faults and not args.fault:
        faults = list(FAULTS)
    if not faults:
        # '--faults ,' must not report "all recovered" over zero drills
        p.error(f"--faults selected no fault; known: {list(FAULTS)}")
    unknown = [f for f in faults if f not in FAULTS]
    if unknown:
        p.error(f"unknown fault(s) {unknown}; known: {list(FAULTS)}")

    results = [run_drill(f, num_steps=args.steps,
                         checkpoint_every=args.checkpoint_every,
                         seed=args.seed) for f in faults]

    if args.obs_dir:
        os.makedirs(args.obs_dir, exist_ok=True)
        with open(os.path.join(args.obs_dir, "decisions.jsonl"), "w") as f:
            for r in results:
                for d in r.decisions:
                    f.write(json.dumps(dict(d, fault=r.fault)) + "\n")
        with open(os.path.join(args.obs_dir,
                               "drill_results.jsonl"), "w") as f:
            for r in results:
                f.write(json.dumps(r.to_json()) + "\n")

    if args.json:
        print(json.dumps([r.to_json() for r in results], indent=2))
    else:
        w = max(len(r.fault) for r in results)
        print(f"{'fault':<{w}}  {'tier':<24} {'ok':<4} {'rerun':>5} "
              f"{'wall_s':>7}  evidence")
        for r in results:
            ev = ", ".join(r.evidence["decision_names"]) or "-"
            status = "PASS" if r.recovered else "FAIL"
            print(f"{r.fault:<{w}}  {r.expected_tier:<24} {status:<4} "
                  f"{r.steps_rerun:>5} {r.wall_s:>7.1f}  {ev}")
            if not r.recovered:
                print(f"{'':<{w}}    -> {r.reason}")
        n_ok = sum(r.recovered for r in results)
        print(f"\n{n_ok}/{len(results)} faults recovered at their "
              f"intended tier")
    return 0 if all(r.recovered for r in results) else 1


if __name__ == "__main__":
    sys.exit(main())
