"""Chaos drills: prove each fault class recovers at its intended tier.

One drill = one :class:`flashmoe_tpu.chaos.FaultPlan` run against a small
real training job under :func:`flashmoe_tpu.runtime.resilient.
resilient_train` with the full ladder armed (tier-0 expert masking,
tier-1 gradient guard, tier-2 verified checkpoints + path fallback).
The drill then interrogates the run the way an SRE would: did training
reach the last step, how many steps of work were re-executed, and does
the telemetry carry evidence that the *intended* tier absorbed the fault
(:data:`flashmoe_tpu.chaos.EXPECTED_TIER`)?

``python -m flashmoe_tpu.chaos`` runs the whole matrix.
"""

from __future__ import annotations

import dataclasses
import os
import tempfile
import time

import jax
import jax.numpy as jnp
import numpy as np

from flashmoe_tpu.chaos import (
    EXPECTED_TIER, FAULTS, FaultPlan, arm_plan, clear, inject,
    make_injector, wrap_step,
)
from flashmoe_tpu.config import MoEConfig
from flashmoe_tpu.parallel.mesh import make_mesh
from flashmoe_tpu.runtime.resilient import (
    ResilienceConfig, StepFailure, resilient_train,
)
from flashmoe_tpu.runtime.trainer import (
    GradGuardConfig, init_state, make_optimizer, make_train_step,
    state_shardings,
)
from flashmoe_tpu.utils.telemetry import Metrics, metrics as global_metrics


def drill_config(**overrides) -> MoEConfig:
    """The drill model: small enough to train on one CPU device in
    seconds, MoE enough (4 experts, top-2, capacity drops possible) that
    every tier-0 path is exercised.  The full ladder is armed."""
    base = dict(num_experts=4, expert_top_k=2, hidden_size=64,
                intermediate_size=128, sequence_len=32, num_layers=1,
                moe_frequency=1, vocab_size=256, num_heads=2,
                drop_tokens=True, capacity_factor=1.5, is_training=True,
                dtype=jnp.float32, param_dtype=jnp.float32,
                degrade_unhealthy_experts=True, collect_stats=True)
    base.update(overrides)
    return MoEConfig(**base)


def data_stream(cfg: MoEConfig, batch: int = 2, seed: int = 0):
    """Deterministic seeded batch stream (step-indexed keys, so two
    streams with one seed are bit-identical — the property the replay
    assertions lean on)."""
    i = 0
    while True:
        yield {"tokens": jax.random.randint(
            jax.random.PRNGKey(seed * 100003 + i),
            (batch, cfg.sequence_len + 1), 0, cfg.vocab_size)}
        i += 1


@dataclasses.dataclass
class DrillResult:
    fault: str
    expected_tier: str
    recovered: bool
    reason: str            # why recovered is False ("" when True)
    final_step: int
    steps_rerun: int       # loss-of-work: successful step executions
                           # beyond num_steps (replays after rewinds)
    wall_s: float
    evidence: dict         # telemetry proof the intended tier fired
    decisions: list        # structured decisions recorded during the run

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


def _stats_probe(cfg: MoEConfig, params, key=11):
    """One armed forward through the MoE layer, returning host stats —
    the tier-0 evidence reader (masked experts, imbalance, drops)."""
    from flashmoe_tpu.ops.moe import moe_layer
    from flashmoe_tpu.ops.stats import stats_to_host

    moe_params = params["layers"][0]["moe"]
    x = jax.random.normal(jax.random.PRNGKey(key),
                          (cfg.tokens, cfg.hidden_size), jnp.float32)
    out = moe_layer(moe_params, x.astype(cfg.dtype), cfg, use_pallas=False)
    return stats_to_host(out.stats), out


def _token_file(tmp: str, cfg: MoEConfig, seed: int,
                windows: int = 24) -> str:
    """A deterministic token shard for the supervised drills: a REAL
    TokenLoader (not a synthetic generator) is what makes the
    data-exactness claim end to end — its cursor rides the checkpoint
    manifest and must replay the identical stream after restart."""
    from flashmoe_tpu.runtime.data import write_token_file

    path = os.path.join(tmp, "tokens.bin")
    rng = np.random.default_rng(seed)
    write_token_file(path, rng.integers(
        0, cfg.vocab_size, size=windows * (cfg.sequence_len + 1),
        dtype=np.int32))
    return path


def _run_supervised_drill(fault: str, *, num_steps: int,
                          checkpoint_every: int, workdir: str | None,
                          seed: int, batch: int) -> DrillResult:
    """Drill the job-level (tier-3) faults through the supervisor:
    ``preempt`` (graceful drain + resume) and ``device_loss`` (restart
    re-folds parallelism onto the surviving devices)."""
    from flashmoe_tpu.runtime import checkpoint as ckpt_mod
    from flashmoe_tpu.runtime.data import TokenLoader
    from flashmoe_tpu.runtime.preempt import PreemptionListener
    from flashmoe_tpu.runtime.resilient import supervise

    plan = FaultPlan(fault, step=3, seed=seed)
    clear()
    tmp = workdir or tempfile.mkdtemp(prefix=f"chaos_{fault}_")
    ckpt_dir = os.path.join(tmp, "ckpt")
    pm_dir = os.path.join(tmp, "postmortem")
    cfg = drill_config()
    token_path = _token_file(tmp, cfg, seed)

    world0 = 2 if (fault == "device_loss" and len(jax.devices()) >= 2) \
        else 1
    injector_box: dict = {}

    def devices_fn():
        # device_loss: the first incarnation's world shrinks once the
        # fault has killed the process — the restart sees the survivors
        if fault == "device_loss" and injector_box.get("exhausted"):
            return jax.devices()[:1]
        return jax.devices()[:world0]

    rcfg = ResilienceConfig(checkpoint_dir=ckpt_dir,
                            checkpoint_every=checkpoint_every,
                            max_retries=3,
                            async_save=(fault == "preempt"))
    guard = GradGuardConfig(warmup_steps=2, spike_factor=10.0)
    preempt = PreemptionListener(grace_s=30.0)
    metrics = Metrics()
    base_injector = make_injector(plan, rcfg, preempt=preempt)

    def injector(i):
        try:
            base_injector(i)
        except Exception:
            # retry budget is max_retries; the (max_retries+1)-th raise
            # is the one that escalates to a process death
            if i == plan.step:
                injector_box["raises"] = injector_box.get("raises", 0) + 1
                if injector_box["raises"] > rcfg.max_retries:
                    injector_box["exhausted"] = True
            raise

    def data_factory(fcfg):
        return TokenLoader(token_path, batch, fcfg.sequence_len,
                           seed=seed, shuffle=True, native=False)

    g0 = len(global_metrics.decisions)
    t0 = time.perf_counter()
    error = None
    try:
        final, history = supervise(
            cfg, data_factory, num_steps, rcfg, guard=guard,
            metrics=metrics, preempt=preempt, devices_fn=devices_fn,
            fail_injector=injector, seed=seed, postmortem_dir=pm_dir)
        final_step = int(final.step)
    except Exception as e:  # noqa: BLE001 — a drill reports, never dies
        error, final_step, history = f"{type(e).__name__}: {e}", -1, []
    wall = time.perf_counter() - t0

    decisions = metrics.decisions + global_metrics.decisions[g0:]
    c = metrics.counters
    names = sorted({d["decision"] for d in decisions})
    evidence: dict = {
        "failures": c.get("failures", 0.0),
        "restores": c.get("restores", 0.0),
        "checkpoints": c.get("checkpoints", 0.0),
        "preempt_drains": c.get("preempt_drains", 0.0),
        "loader_restores": c.get("loader_restores", 0.0),
        "supervisor_restarts": c.get("supervisor_restarts", 0.0),
        "finite_history": bool(history) and all(
            np.isfinite(h["loss"]) for h in history if "loss" in h),
        "decision_names": names,
        "world0": world0,
        "worlds": [d.get("world") for d in decisions
                   if d["decision"] == "supervisor.resume"],
    }
    last = ckpt_mod.latest_step(ckpt_dir)
    evidence["final_ckpt_step"] = last
    evidence["loader_state_present"] = (
        last is not None
        and ckpt_mod.load_loader_state(ckpt_dir, last) is not None)
    from flashmoe_tpu.profiler import postmortem as pm

    bundles = pm.find_bundles(pm_dir)
    evidence["postmortem_bundles"] = bundles

    ok, why = True, []

    def need(cond, msg):
        nonlocal ok
        if not cond:
            ok = False
            why.append(msg)

    need(error is None, f"aborted: {error}")
    need(final_step == num_steps, f"ended at step {final_step}")
    need(evidence["finite_history"], "non-finite loss leaked")
    need("supervisor.resume" in names, "no supervisor.resume decision")
    need(evidence["loader_state_present"],
         "no loader state in the final manifest")
    steps_rerun = max(0, int(c.get("steps", 0)) - num_steps)
    if fault == "preempt":
        need(c.get("preempt_drains", 0) >= 1, "no graceful drain")
        need("preempt.drain" in names, "no preempt.drain decision")
        # zero lost steps: the drain checkpoints the exact step reached
        need(steps_rerun == 0,
             f"drain lost work: {steps_rerun} steps re-run")
        need(c.get("failures", 0) == 0, "drain path counted failures")
        # a graceful drain is not a death: no forensics bundle
        need(not bundles,
             f"graceful drain left postmortem bundle(s): {bundles}")
    else:  # device_loss
        need(c.get("supervisor_restarts", 0) >= 1,
             "process death did not reach the supervisor")
        need(c.get("restores", 0) >= 1, "no checkpoint restore")
        # the restart-forcing death must leave its forensics behind
        need(len(bundles) >= 1,
             "process death left no postmortem bundle")
        need("postmortem.saved" in names, "no postmortem.saved decision")
        if world0 >= 2:
            worlds = [w for w in evidence["worlds"] if w]
            need(worlds and min(worlds) < world0,
                 f"world never shrank below {world0} ({worlds})")
        # loss-of-work bound: every in-job retry replays at most one
        # checkpoint window, the restart replays at most one more
        bound = checkpoint_every * (rcfg.max_retries + 1)
        need(steps_rerun <= bound,
             f"loss of work {steps_rerun} exceeds bound {bound}")

    clear()
    return DrillResult(
        fault=fault, expected_tier=EXPECTED_TIER[fault], recovered=ok,
        reason="; ".join(why), final_step=final_step,
        steps_rerun=steps_rerun, wall_s=round(wall, 3),
        evidence=evidence, decisions=decisions)


def _run_controller_drill(fault: str, *, num_steps: int,
                          checkpoint_every: int, workdir: str | None,
                          seed: int, batch: int) -> DrillResult:
    """Drill the self-healing runtime controller (docs/RESILIENCE.md
    "Self-healing controller"): faults that are sustained PERFORMANCE /
    QUALITY regressions rather than crashes, which no crash-recovery
    tier can absorb — the controller must repair the job mid-flight.

    ``skew_sustained``: routing collapses onto one expert for the whole
    run (the same in-graph injection as ``skewed_routing``, held past
    the controller's debounce window).  The capacity path drowns in
    token drops; recovery = a ``controller.morph`` onto a dropless
    execution, after which the drop EMA decays back under the trigger.

    ``slow_device``: one device degrades to a fraction of its rate
    mid-job while the workload's hot expert sits on it (the wrap_step
    stall is priced from the controller's LIVE placement: ``sleep_s *
    device_load_share(slow)/rate``).  The controller runs its DEFAULT
    ``rates_fn`` — the production per-device throughput re-probe
    (``runtime/throughput.device_rates``; ISSUE 12 satellite) — with
    the drill's degraded rates armed at the ``probe_rates`` injection
    seam, the reading a genuinely slow chip would hand the probe (the
    host-sleep stall this drill injects is invisible to a real CPU
    probe).  Recovery = a ``controller.replace`` carrying the PROBED
    rates — the Decider's rate-proportional assignment moves the hot
    expert onto a fast device (replicating it onto a dead slot when
    that improves the makespan), the stall collapses, and the armed
    SLO watchdog records the step time returning under budget
    (``slo.recovered``)."""
    from flashmoe_tpu.profiler.slo import SLOConfig
    from flashmoe_tpu.runtime.controller import (
        ControllerConfig, RuntimeController,
    )

    clear()
    tmp = workdir or tempfile.mkdtemp(prefix=f"chaos_{fault}_")
    ckpt_dir = os.path.join(tmp, "ckpt")
    pm_dir = os.path.join(tmp, "postmortem")
    slow = fault == "slow_device"
    sleep_s = 0.4
    plan = FaultPlan(fault, step=(2 if slow else 0),
                     duration=num_steps, expert=0, bias=100.0,
                     sleep_s=sleep_s, seed=seed)
    if slow:
        # top-1 routing: the biased workload parks ALL load on expert 0
        # and leaves genuinely dead slots for the replication policy
        cfg = drill_config(num_experts=8, expert_top_k=1)
    else:
        cfg = drill_config()
    arm_plan(FaultPlan("skew_sustained", step=0, duration=num_steps,
                       expert=plan.expert, bias=plan.bias, seed=seed))

    n_dev = 4 if slow else 1
    rates = np.array([0.25, 1.0, 1.0, 1.0]) if slow else None
    if slow:
        # the controller keeps its DEFAULT rates_fn (the live
        # per-device re-probe); the drill degrades what the probe READS
        # via the chaos seam, so the production path — trigger ->
        # re-probe -> rate-proportional re-placement — is what recovers
        inject.arm("probe_rates", rates=tuple(float(r) for r in rates))
    ccfg = ControllerConfig(
        enable_morph=not slow, enable_replace=slow,
        debounce_steps=2, cooldown_steps=3, baseline_steps=2,
        morph_budget=1, replace_budget=1, ema_decay=0.5,
        slow_factor=1.5)
    metrics = Metrics()
    controller = RuntimeController(
        cfg, ccfg, metrics=metrics, n_devices=n_dev)

    mesh = make_mesh(cfg, dp=1, devices=jax.devices()[:1])
    guard = GradGuardConfig(warmup_steps=2, spike_factor=10.0)
    opt = make_optimizer(cfg, total_steps=num_steps)
    state = init_state(jax.random.PRNGKey(seed), cfg, opt, guard=guard)
    state = jax.device_put(state, state_shardings(state, cfg, mesh))

    def _rearm_hot_column():
        # the injected skew models CONTENT-based routing: tokens chase
        # the hot expert's FUNCTION, which a re-placement moves to a
        # new router column (gate_w columns permute with their FFN
        # weights).  The logit-bias injection point is column-anchored,
        # so the faithful sustained-skew simulation re-arms it at the
        # hot expert's current column before every re-trace.
        col = plan.expert
        for rec in controller.timeline:
            if rec.get("decision") == "controller.replace":
                col = list(rec["perm"]).index(col)
        inject.arm("skewed_routing", expert=col, bias=plan.bias)
        return col

    def rebuild(overrides):
        _rearm_hot_column()
        scfg = cfg.replace(**overrides) if overrides else cfg
        return make_train_step(scfg, mesh, opt, guard=guard)

    step_fn = rebuild({})
    slo = None
    if slow:
        # the slow device gates the step at sleep_s / rate; the budget
        # sits between the degraded and the re-placed step time, so the
        # watchdog narrates breach -> (replace) -> recovered
        slo = SLOConfig(step_ms=sleep_s * 1e3 * 0.6, consecutive=3)

        def load_share(i):
            # bottleneck model: the slow device's work share over its
            # degraded rate (1.0 when the hot expert sits on it)
            return controller.device_load_share(0) / (
                rates[0] / rates.max())

        wrapped = wrap_step(step_fn, plan, load_share=load_share)

        def rebuild_wrapped(overrides):
            return wrap_step(rebuild(overrides), plan,
                             load_share=load_share)
    else:
        wrapped, rebuild_wrapped = step_fn, rebuild

    rcfg = ResilienceConfig(checkpoint_dir=ckpt_dir,
                            checkpoint_every=checkpoint_every,
                            max_retries=3)
    g0 = len(global_metrics.decisions)
    t0 = time.perf_counter()
    error = None
    step_wall: list[float] = []

    def timed(fn):
        # host-side wall-clock wrapper AROUND the jitted step (never
        # traced): the drill's recovery verdict reads these timings
        def run(st, b):
            s0 = time.perf_counter()  # staticcheck: ok host wrapper around the jitted step, not traced code
            out = fn(st, b)
            jax.block_until_ready(out[0])
            step_wall.append(time.perf_counter() - s0)  # staticcheck: ok host wrapper around the jitted step, not traced code
            return out
        return run

    try:
        final, history = resilient_train(
            state, timed(wrapped), data_stream(cfg, batch, seed),
            num_steps, rcfg=rcfg, metrics=metrics, slo=slo,
            postmortem_dir=pm_dir, cfg=cfg, controller=controller,
            rebuild_step=lambda ov: timed(rebuild_wrapped(ov)))
        final_step = int(final.step)
    except Exception as e:  # noqa: BLE001 — a drill reports, never dies
        error, final_step, history = f"{type(e).__name__}: {e}", -1, []
    wall = time.perf_counter() - t0

    from flashmoe_tpu.profiler import postmortem as pm
    from flashmoe_tpu.runtime import checkpoint as ckpt_mod

    bundles = pm.find_bundles(pm_dir)
    decisions = metrics.decisions + global_metrics.decisions[g0:]
    names = sorted({d["decision"] for d in decisions})
    c = metrics.counters
    act_name = "controller.replace" if slow else "controller.morph"
    act = next((d for d in decisions if d["decision"] == act_name), None)
    last = ckpt_mod.latest_step(ckpt_dir)
    manifest_plan = (ckpt_mod.load_controller_state(ckpt_dir, last)
                     if last is not None else None)
    evidence: dict = {
        "failures": c.get("failures", 0.0),
        "decision_names": names,
        "action": {k: v for k, v in (act or {}).items()
                   if k not in ("perm",)},
        "drop_ema_end": controller.drop_ema,
        "imbalance_ema_end": controller.imbalance_ema,
        "morphs_used": controller.morphs_used,
        "replaces_used": controller.replaces_used,
        "overrides": {k: str(v)
                      for k, v in controller.cfg_overrides.items()},
        "manifest_plan": bool(manifest_plan),
        "postmortem_bundles": bundles,
    }

    ok, why = True, []

    def need(cond, msg):
        nonlocal ok
        if not cond:
            ok = False
            why.append(msg)

    need(error is None, f"aborted: {error}")
    need(final_step == num_steps, f"ended at step {final_step}")
    need(act is not None, f"no {act_name} decision")
    need(c.get("failures", 0) == 0,
         "controller fault escalated into step failures")
    need(not bundles,
         f"self-healed fault left postmortem bundle(s): {bundles}")
    need(manifest_plan is not None and bool(manifest_plan),
         "newest checkpoint manifest carries no controller plan")
    steps_rerun = max(0, int(c.get("steps", 0)) - num_steps)
    need(steps_rerun == 0,
         f"self-healing re-ran {steps_rerun} steps (must be zero lost "
         f"steps)")
    if act is not None:
        act_step = int(act.get("step", 0))
        if slow:
            perm = act.get("perm") or list(range(cfg.num_experts))
            need(perm != list(range(cfg.num_experts))
                 or act.get("replicas"),
                 "re-placement changed nothing (identity perm, no "
                 "replicas)")
            need(bool(act.get("replicas")),
                 "hot expert was not replicated onto a dead slot")
            # ISSUE 12 satellite: the re-placement must have consumed
            # the PROBED rates (the controller's default rates_fn
            # through the probe_rates chaos seam), not drill-injected
            # ones — the decision record carries what the probe read
            need(act.get("rates") == [float(r) for r in rates],
                 f"controller.replace did not carry the probed rates "
                 f"(got {act.get('rates')})")
            pre = [s for i, s in enumerate(step_wall)
                   if plan.step <= i < act_step]
            post = step_wall[act_step + 1:]  # skip the re-jit step
            evidence["pre_ms"] = round(1e3 * max(pre), 1) if pre else None
            evidence["post_ms"] = (round(1e3 * min(post), 1)
                                   if post else None)
            need(pre and post and min(post) < 0.5 * max(pre),
                 f"step time did not recover "
                 f"(pre {evidence['pre_ms']} ms -> "
                 f"post {evidence['post_ms']} ms)")
            need("slo.breach" in names, "SLO never saw the degradation")
            need("slo.recovered" in names,
                 "step time never returned under the SLO budget")
        else:
            need(act.get("dropless"),
                 "morph did not target a dropless execution")
            need(controller.drop_ema is not None
                 and controller.drop_ema < ccfg.drop_high,
                 f"drop EMA {controller.drop_ema} still above the "
                 f"trigger after the morph")

    clear()
    return DrillResult(
        fault=fault, expected_tier=EXPECTED_TIER[fault], recovered=ok,
        reason="; ".join(why), final_step=final_step,
        steps_rerun=steps_rerun, wall_s=round(wall, 3),
        evidence=evidence, decisions=decisions)


def _run_vclock_drill(fault: str, *, seed: int) -> DrillResult:
    """Drill the DCN faults (``dcn_latency`` / ``dcn_jitter``) against
    the serving fabric's measured-latency plane: a mocked 2-replica
    fabric steps on a :class:`~flashmoe_tpu.fabric.vclock.VirtualClock`
    with the plan armed, behind a
    :class:`~flashmoe_tpu.fabric.frontdoor.FrontDoor`.

    These faults never crash anything — no recovery tier fires.  The
    claim under drill is OBSERVABILITY (``monitor:handoff_drift``):
    every perturbed transfer must surface through the
    ``fabric.handoff_drift`` decisions with ``measured > modeled``,
    unperturbed transfers must keep reconciling with the priced
    verdict, the shared tracer must stay contiguous, and every
    request's critical-path attribution must still sum to its span
    within the 1% gate — delay injection may stretch latencies, never
    corrupt the accounting."""
    import os

    from flashmoe_tpu.fabric import FrontDoor, ServingFabric, VirtualClock
    from flashmoe_tpu.fabric.topo import ENV_MOCK_FABRIC
    from flashmoe_tpu.models.transformer import init_params
    from flashmoe_tpu.serving.engine import ServeConfig
    from flashmoe_tpu.serving.loadgen import build_requests, tiny_config

    # window over TRANSFER index: skip the first two handoffs so the
    # drill proves both arms (clean reconciliation AND visible drift)
    plan = FaultPlan(fault, step=2, duration=6, latency_ms=50.0,
                     jitter_ms=50.0, seed=seed)
    clear()
    cfg = tiny_config()
    params = init_params(jax.random.PRNGKey(seed), cfg)
    serve = ServeConfig(max_batch=2, page_size=8, num_pages=64,
                        max_pages_per_slot=4, ctx_bucket_pages=1,
                        prompt_bucket=8)
    reqs, arrivals = build_requests(
        6, vocab=cfg.vocab_size, prompt_len=8, max_new=4, seed=seed,
        arrival_every=1)
    metrics = Metrics()
    saved = os.environ.get(ENV_MOCK_FABRIC)
    os.environ[ENV_MOCK_FABRIC] = "2"
    t0 = time.perf_counter()
    error, door, fab = None, None, None
    outputs: dict = {}
    att: dict = {}
    trace_errors: list = []
    try:
        vc = VirtualClock(plan=plan)
        fab = ServingFabric(params, cfg, serve, metrics_obj=metrics,
                            vclock=vc)
        door = FrontDoor(fab)
        outputs = door.run(reqs, arrivals)
        att = door.attribution()
        trace_errors = door.validate()
    except Exception as e:  # noqa: BLE001 — a drill reports, never dies
        error = f"{type(e).__name__}: {e}"
    finally:
        if door is not None:
            door.close()
        if fab is not None:
            fab.close()
        if saved is None:
            os.environ.pop(ENV_MOCK_FABRIC, None)
        else:
            os.environ[ENV_MOCK_FABRIC] = saved
    wall = time.perf_counter() - t0

    decisions = list(metrics.decisions)
    drift = [d for d in decisions
             if d["decision"] == "fabric.handoff_drift"]
    perturbed = [d for d in drift if d["chaos_ms"] > 0]
    clean = [d for d in drift if d["chaos_ms"] == 0]
    sums_ok = [a["sum_ok"] for a in att.values()]
    evidence: dict = {
        "completed": len(outputs),
        "handoffs": len([d for d in decisions
                         if d["decision"] == "fabric.handoff"]),
        "drift_decisions": len(drift),
        "perturbed_transfers": len(perturbed),
        "clean_transfers": len(clean),
        "max_chaos_ms": (max(d["chaos_ms"] for d in perturbed)
                         if perturbed else 0.0),
        "clean_agree": [d["agree"] for d in clean],
        "attribution_requests": len(att),
        "attribution_sum_ok": sums_ok,
        "max_rel_err": (max(a["rel_err"] for a in att.values())
                        if att else None),
        "trace_errors": trace_errors,
        "decision_names": sorted({d["decision"] for d in decisions}),
    }

    ok, why = True, []

    def need(cond, msg):
        nonlocal ok
        if not cond:
            ok = False
            why.append(msg)

    need(error is None, f"aborted: {error}")
    need(len(outputs) == len(reqs),
         f"only {len(outputs)}/{len(reqs)} requests completed")
    need(len(drift) == evidence["handoffs"],
         "not every handoff produced a drift verdict")
    need(len(perturbed) >= 1, "injected DCN fault never surfaced in "
                              "fabric.handoff_drift")
    need(all(d["measured_dcn_ms"] > d["modeled_dcn_ms"]
             for d in perturbed),
         "a perturbed transfer measured no slower than priced")
    need(all(a is not False for a in evidence["clean_agree"]),
         "an UNperturbed transfer disagreed with the priced verdict")
    need(not trace_errors, f"tracer lost contiguity: {trace_errors[:3]}")
    need(att and all(sums_ok),
         "attribution no longer sums to the request span (1% gate)")

    clear()
    return DrillResult(
        fault=fault, expected_tier=EXPECTED_TIER[fault], recovered=ok,
        reason="; ".join(why), final_step=(fab.step_idx if fab else -1),
        steps_rerun=0, wall_s=round(wall, 3),
        evidence=evidence, decisions=decisions)


def _run_fabric_fault_drill(fault: str, *, seed: int) -> DrillResult:
    """Drill the serving fault-tolerance ladder (ISSUE 18): a mocked
    2-replica fabric behind a front door, with ONE of the serving
    faults armed —

    * ``replica_crash``    — a decode replica dies silently at a fabric
      step; the health probes detect it and every victim MIGRATES to a
      survivor via deterministic re-prefill (``fabric:migrate``);
    * ``handoff_corrupt``  — a KV transfer's bytes flip on the wire;
      the per-page CRC32 verify refuses them and the transport retries
      exactly once (``fabric:handoff_retry``);
    * ``handoff_timeout``  — a transfer stalls past the deadline; same
      retry tier, reason ``timeout``;
    * ``frontdoor_loss``   — a front-door PEER dies mid-run; its
      namespace leases fail over to the survivors with bumped epochs
      (``fabric:frontdoor_failover``);
    * ``net_partition``    — the tcp wire drops transfers MID-STREAM
      (partial bytes really cross a kernel socket and the receiver
      really discards them); the sender reconnects and retries
      (``fabric:partition_retry``);
    * ``lease_split_brain`` — the lease table lives in an EXTERNAL
      fcntl-locked store; after a failover the dead peer plays zombie
      and re-asserts a moved shard at its stale epoch — the store's
      fencing token REFUSES it, zero requests double-served
      (``fabric:lease_fence``);
    * ``replica_stall``    — a decode replica hangs MID-STEP (its
      health probe still answers); the sub-step heartbeat deadline
      catches it and the victims migrate (``fabric:heartbeat_migrate``);
    * ``lease_torn_write`` — a lease writer is killed mid-append; the
      store's CRC framing refuses the torn record and rolls back to
      the last intact epoch (``fabric:lease_repair``).

    Recovery must be INVISIBLE to the tokens: every request completes
    with a token stream bit-equal to an uninterrupted single-pool
    engine on the same trace, the shared tracer stays orphan-free
    through the transition, the post-failure fleet Perfetto document
    still validates, and retry/migration costs are reconciled through
    the virtual clock (the ``fabric.handoff_drift`` family)."""
    import os

    from flashmoe_tpu.fabric import (
        FrontDoor, FrontDoorCluster, HandoffTransport, HeartbeatConfig,
        LeaseStore, ServingFabric, StaleLeaseError, VirtualClock,
    )
    from flashmoe_tpu.fabric.topo import ENV_MOCK_FABRIC
    from flashmoe_tpu.models.transformer import init_params
    from flashmoe_tpu.serving.engine import ServeConfig, ServingEngine
    from flashmoe_tpu.serving.loadgen import build_requests, tiny_config

    clear()
    cfg = tiny_config()
    params = init_params(jax.random.PRNGKey(seed), cfg)
    serve = ServeConfig(max_batch=2, page_size=8, num_pages=64,
                        max_pages_per_slot=4, ctx_bucket_pages=1,
                        prompt_bucket=8)
    reqs, arrivals = build_requests(
        6, vocab=cfg.vocab_size, prompt_len=8, max_new=4, seed=seed,
        arrival_every=1)

    # the uninterrupted single-pool run the recovery must be bit-equal
    # to (same module-level jits, same seeded trace)
    eng = ServingEngine(params, cfg, serve, metrics_obj=Metrics())
    baseline = eng.run(reqs, arrivals)
    eng.close()

    metrics = Metrics()
    saved = os.environ.get(ENV_MOCK_FABRIC)
    os.environ[ENV_MOCK_FABRIC] = "2"
    t0 = time.perf_counter()
    error, fab, door, cluster, transport = None, None, None, None, None
    store, store_path = None, None
    zombie_attempts, zombie_refused = 0, 0
    torn_bytes, restored_epoch = 0, -1
    outputs: dict = {}
    att: dict = {}
    trace_errors: list = []
    fleet_doc: dict = {}
    try:
        vc = VirtualClock()
        if fault in ("lease_split_brain", "lease_torn_write"):
            fd, store_path = tempfile.mkstemp(
                prefix="flashmoe-drill-leases-", suffix=".bin")
            os.close(fd)
        if fault in ("handoff_corrupt", "handoff_timeout"):
            # window over TRANSFER index, first attempt only (once):
            # two faulted transfers, each retried exactly once
            transport = HandoffTransport(
                metrics_obj=metrics,
                plan=FaultPlan(fault, step=2, duration=2, seed=seed))
            fab = ServingFabric(params, cfg, serve, metrics_obj=metrics,
                                vclock=vc, transport=transport)
            door = FrontDoor(fab)
            outputs = door.run(reqs, arrivals)
        elif fault == "replica_crash":
            fab = ServingFabric(
                params, cfg, serve, metrics_obj=metrics, vclock=vc,
                fault_plan=FaultPlan(fault, step=3, expert=0,
                                     seed=seed))
            door = FrontDoor(fab)
            outputs = door.run(reqs, arrivals)
        elif fault == "frontdoor_loss":
            fab = ServingFabric(params, cfg, serve, metrics_obj=metrics,
                                vclock=vc)
            cluster = FrontDoorCluster(fab, n_doors=2, n_shards=8,
                                       metrics_obj=metrics)
            outputs = cluster.run(reqs, arrivals, fail_at=2,
                                  fail_peer=0)
        elif fault == "net_partition":
            # the REAL tcp wire: two transfers are cut mid-stream at
            # the kernel socket layer (partial bytes actually cross),
            # the receiver discards the torn frames, the sender
            # reconnects and retries on the capped-backoff ladder
            transport = HandoffTransport(
                metrics_obj=metrics, wire="tcp",
                plan=FaultPlan(fault, step=2, duration=2, seed=seed))
            fab = ServingFabric(params, cfg, serve, metrics_obj=metrics,
                                vclock=vc, transport=transport)
            door = FrontDoor(fab)
            outputs = door.run(reqs, arrivals)
        elif fault == "lease_split_brain":
            store = LeaseStore(store_path, metrics_obj=metrics)
            fab = ServingFabric(params, cfg, serve, metrics_obj=metrics,
                                vclock=vc)
            cluster = FrontDoorCluster(fab, n_doors=2, n_shards=8,
                                       metrics_obj=metrics, store=store)
            # the epochs the doomed peer believes it holds, BEFORE the
            # failover moves them
            stale = {s: ls.epoch for s, ls in store.leases().items()
                     if ls.owner == 0}
            outputs = cluster.run(reqs, arrivals, fail_at=2,
                                  fail_peer=0)
            # the zombie arm: the failed peer wakes back up and
            # re-asserts every shard it lost, using the fencing token
            # it believes is next — every write must be REFUSED
            for shard, epoch in sorted(stale.items()):
                zombie_attempts += 1
                try:
                    store.write_lease(shard, 0, epoch + 1,
                                      reason="zombie_reassert")
                except StaleLeaseError:
                    zombie_refused += 1
        elif fault == "replica_stall":
            # the victim hangs MID-STEP (after its admit heartbeat,
            # inside prefill); its probe still answers, so only the
            # sub-step heartbeat deadline can catch it
            fab = ServingFabric(
                params, cfg, serve, metrics_obj=metrics, vclock=vc,
                heartbeat=HeartbeatConfig(misses_to_stall=2),
                fault_plan=FaultPlan(fault, step=3, expert=0,
                                     seed=seed))
            door = FrontDoor(fab)
            outputs = door.run(reqs, arrivals)
        elif fault == "lease_torn_write":
            # seed a store, advance shard 3 to epoch 1, then kill the
            # writer mid-append of epoch 2 — the torn record must be
            # refused and the table rolled back to epoch 1
            store = LeaseStore(store_path, metrics_obj=metrics)
            store.init_leases({s: s % 2 for s in range(8)})
            store.write_lease(3, 1, 1, reason="pre_crash")
            store.write_lease(3, 1, 2, reason="crash_victim")
            torn_bytes = store.tear_last_record()
            fab = ServingFabric(params, cfg, serve, metrics_obj=metrics,
                                vclock=vc)
            # the cluster's first mutating write repairs the tail
            cluster = FrontDoorCluster(fab, n_doors=2, n_shards=8,
                                       metrics_obj=metrics, store=store)
            restored_epoch = store.leases()[3].epoch
            outputs = cluster.run(reqs, arrivals, fail_at=2,
                                  fail_peer=0)
        else:
            raise ValueError(f"not a fabric fault: {fault!r}")
        authority = cluster if cluster is not None else door
        trace_errors = authority.validate()
        fleet_doc = authority.fleet_trace_document()
        if door is not None:
            att = door.attribution()
    except Exception as e:  # noqa: BLE001 — a drill reports, never dies
        error = f"{type(e).__name__}: {e}"
    finally:
        if door is not None:
            door.close()
        if cluster is not None:
            cluster.close()
        if fab is not None:
            fab.close()
        if transport is not None:
            transport.close()
        if store_path is not None:
            try:
                os.unlink(store_path)
            except OSError:
                pass
        if saved is None:
            os.environ.pop(ENV_MOCK_FABRIC, None)
        else:
            os.environ[ENV_MOCK_FABRIC] = saved
    wall = time.perf_counter() - t0

    decisions = list(metrics.decisions)

    def named(name):
        return [d for d in decisions if d["decision"] == name]

    bit_equal = (sorted(outputs) == sorted(baseline)
                 and all(outputs[r] == baseline[r] for r in baseline))
    drift = named("fabric.handoff_drift")
    retried_drift = [d for d in drift if d.get("retry_ms", 0) > 0]
    sums_ok = [a["sum_ok"] for a in att.values()]
    evidence: dict = {
        "completed": len(outputs),
        "bit_equal_to_baseline": bit_equal,
        "handoffs": len(named("fabric.handoff")),
        "retries": len(named("fabric.handoff_retry")),
        "corrupt": len(named("fabric.handoff_corrupt")),
        "migrations": len(named("fabric.migrate")),
        "crashes": len(named("fabric.replica_crash")),
        "failovers": len(named("frontdoor.failover")),
        "partitions": len(named("fabric.partition")),
        "fences": len(named("frontdoor.fence")),
        "lease_repairs": len(named("frontdoor.lease_repair")),
        "stalls": len(named("fabric.heartbeat_stall")),
        "heartbeat_misses": len(named("fabric.heartbeat_miss")),
        "zombie_attempts": zombie_attempts,
        "zombie_refused": zombie_refused,
        "torn_bytes": torn_bytes,
        "restored_epoch": restored_epoch,
        "retried_drift": len(retried_drift),
        "trace_errors": trace_errors,
        "fleet_trace_events": len(fleet_doc.get("traceEvents", [])),
        "attribution_requests": len(att),
        "attribution_sum_ok": sums_ok,
        "decision_names": sorted({d["decision"] for d in decisions}),
    }

    ok, why = True, []

    def need(cond, msg):
        nonlocal ok
        if not cond:
            ok = False
            why.append(msg)

    need(error is None, f"aborted: {error}")
    need(len(outputs) == len(reqs),
         f"only {len(outputs)}/{len(reqs)} requests completed")
    need(bit_equal, "a recovered request's token stream diverged from "
                    "the uninterrupted single-pool run")
    need(not trace_errors,
         f"tracer lost contiguity across the failure: "
         f"{trace_errors[:3]}")
    need(evidence["fleet_trace_events"] > 0,
         "post-failure fleet Perfetto document is empty")
    if fault == "replica_crash":
        need(evidence["crashes"] == 1,
             "the crash was never detected")
        need(evidence["migrations"] >= 1,
             "no request migrated off the dead replica")
    elif fault in ("handoff_corrupt", "handoff_timeout"):
        retries = named("fabric.handoff_retry")
        need(len(retries) == 2,
             f"expected exactly one retry per faulted transfer "
             f"(2 total), saw {len(retries)}")
        want_reason = ("corrupt" if fault == "handoff_corrupt"
                       else "timeout")
        need(all(d["reason"] == want_reason for d in retries),
             f"retry reasons {[d['reason'] for d in retries]} != "
             f"{want_reason}")
        if fault == "handoff_corrupt":
            need(evidence["corrupt"] == 2,
                 "CRC verify never named the corrupted pages")
        need(len(retried_drift) == 2,
             "retry cost never reconciled through the vclock "
             "(fabric.handoff_drift retry_ms)")
        need(att and all(sums_ok),
             "attribution no longer sums to the request span")
    elif fault == "frontdoor_loss":
        fo = named("frontdoor.failover")
        need(len(fo) >= 1, "no lease failed over off the dead peer")
        need(all(d["epoch"] >= 1 for d in fo),
             "a failover did not bump its lease epoch")
        need(all(d["to_peer"] != 0 for d in fo),
             "a lease failed over TO the dead peer")
    elif fault == "net_partition":
        retries = named("fabric.handoff_retry")
        parts = named("fabric.partition")
        need(len(parts) == 2,
             f"expected 2 partitioned transfers, saw {len(parts)}")
        need(all(d["wire"] == "tcp" and d["injected"] for d in parts),
             "a partition verdict did not come off the tcp wire")
        need(all(d.get("dropped_bytes", 0) > 0 for d in parts),
             "no partial bytes actually crossed the socket before "
             "the cut")
        need(len(retries) == 2
             and all(d["reason"] == "reset" for d in retries),
             f"expected 2 retries with reason=reset, saw "
             f"{[d.get('reason') for d in retries]}")
        need(len(retried_drift) == 2,
             "retry cost never reconciled through the vclock "
             "(fabric.handoff_drift retry_ms)")
        need(att and all(sums_ok),
             "attribution no longer sums to the request span")
    elif fault == "lease_split_brain":
        fo = named("frontdoor.failover")
        fences = named("frontdoor.fence")
        need(len(fo) >= 1, "no lease failed over off the dead peer")
        need(zombie_attempts >= 1,
             "the zombie never re-asserted a moved shard")
        need(zombie_refused == zombie_attempts,
             f"split brain: {zombie_attempts - zombie_refused} zombie "
             f"stale-epoch writes were ACCEPTED")
        need(len(fences) == zombie_refused
             and all(d["refused"] for d in fences),
             "a refusal was not logged as a frontdoor.fence decision")
    elif fault == "replica_stall":
        stalls = named("fabric.heartbeat_stall")
        need(len(stalls) == 1,
             "the mid-step hang was never declared a stall")
        need(evidence["heartbeat_misses"] >= 2,
             "the watchdog skipped its hysteresis window")
        need(stalls and stalls[0]["detect_ms"] > 0,
             "stall detection latency was not priced")
        need(stalls and stalls[0]["step"] > 3,
             "stall declared at or before the hang step — the probe "
             "false-positived where only heartbeats can see")
        need(evidence["crashes"] == 1,
             "the stalled replica was never fenced off")
        need(evidence["migrations"] >= 1,
             "no request migrated off the stalled replica")
    elif fault == "lease_torn_write":
        reps = named("frontdoor.lease_repair")
        need(torn_bytes > 0, "the kill never tore any bytes")
        need(len(reps) >= 1,
             "the torn tail was never repaired "
             "(frontdoor.lease_repair)")
        need(restored_epoch == 1,
             f"rolled back to epoch {restored_epoch}, wanted the "
             f"last intact epoch 1")
        need(evidence["failovers"] >= 1,
             "failover on top of the repaired store never happened")

    clear()
    return DrillResult(
        fault=fault, expected_tier=EXPECTED_TIER[fault], recovered=ok,
        reason="; ".join(why), final_step=(fab.step_idx if fab else -1),
        steps_rerun=0, wall_s=round(wall, 3),
        evidence=evidence, decisions=decisions)


def run_drill(fault: str, *, num_steps: int = 6, checkpoint_every: int = 2,
              workdir: str | None = None, seed: int = 0,
              batch: int = 2) -> DrillResult:
    """Run one fault drill end to end; never raises for a failed drill —
    the result carries the diagnosis instead."""
    if fault in ("dcn_latency", "dcn_jitter"):
        # serving-plane faults: drilled against the fabric's virtual
        # clock, not the training loop (num_steps etc. do not apply)
        return _run_vclock_drill(fault, seed=seed)
    if fault in ("replica_crash", "handoff_corrupt", "handoff_timeout",
                 "frontdoor_loss", "net_partition", "lease_split_brain",
                 "replica_stall", "lease_torn_write"):
        # the serving fault-tolerance ladder: drilled against a mocked
        # 2-replica fabric, recovery judged by token bit-equality
        return _run_fabric_fault_drill(fault, seed=seed)
    if fault in ("preempt", "device_loss"):
        return _run_supervised_drill(
            fault, num_steps=num_steps, checkpoint_every=checkpoint_every,
            workdir=workdir, seed=seed, batch=batch)
    if fault in ("skew_sustained", "slow_device"):
        # the self-healing drills need room for debounce + cooldown +
        # post-action recovery evidence: at least 12 steps
        return _run_controller_drill(
            fault, num_steps=max(num_steps, 12),
            checkpoint_every=checkpoint_every, workdir=workdir,
            seed=seed, batch=batch)
    plan = FaultPlan(fault, step=3, seed=seed)
    if fault == "corrupt_ckpt":
        # corrupt the NEWEST checkpoint after two exist, so the fallback
        # restore has an intact older step to land on
        plan.step = 2 * checkpoint_every + 1
    clear()
    arm_plan(plan)

    tmp = workdir or tempfile.mkdtemp(prefix=f"chaos_{fault}_")
    ckpt_dir = os.path.join(tmp, "ckpt")
    pm_dir = os.path.join(tmp, "postmortem")
    cfg = drill_config()
    # the drill mesh is a single device: deterministic, CLI-runnable on
    # any host; the multi-device tiers are covered by tests/test_chaos.py
    mesh = make_mesh(cfg, dp=1, devices=jax.devices()[:1])
    guard = GradGuardConfig(warmup_steps=2, spike_factor=10.0)
    opt = make_optimizer(cfg, total_steps=num_steps)
    state = init_state(jax.random.PRNGKey(seed), cfg, opt, guard=guard)
    state = jax.device_put(state, state_shardings(state, cfg, mesh))
    step_fn = make_train_step(cfg, mesh, opt, guard=guard)

    timeout = None
    if fault == "slow_step":
        # calibrate the deadline against a real (compiled) step so the
        # drill never mistakes compile time for a stall: warm up on a
        # throwaway state (the jitted step donates its input)
        warm = init_state(jax.random.PRNGKey(seed + 1), cfg, opt,
                          guard=guard)
        warm = jax.device_put(warm, state_shardings(warm, cfg, mesh))
        warm_batch = next(data_stream(cfg, batch, seed + 7))
        jax.block_until_ready(step_fn(warm, warm_batch))
        t0 = time.perf_counter()
        warm2 = init_state(jax.random.PRNGKey(seed + 2), cfg, opt,
                           guard=guard)
        warm2 = jax.device_put(warm2, state_shardings(warm2, cfg, mesh))
        jax.block_until_ready(step_fn(warm2, warm_batch))
        warm_s = time.perf_counter() - t0
        timeout = max(2.0, 20 * warm_s)
        plan.sleep_s = 2.5 * timeout

    rcfg = ResilienceConfig(checkpoint_dir=ckpt_dir,
                            checkpoint_every=checkpoint_every,
                            step_timeout_s=timeout, max_retries=3)
    metrics = Metrics()
    injector = make_injector(plan, rcfg)
    wrapped = wrap_step(step_fn, plan)
    g0 = len(global_metrics.decisions)

    t0 = time.perf_counter()
    error = None
    try:
        final, history = resilient_train(
            state, wrapped, data_stream(cfg, batch, seed), num_steps,
            rcfg=rcfg, metrics=metrics, fail_injector=injector,
            postmortem_dir=pm_dir, cfg=cfg)
        final_step = int(final.step)
    except Exception as e:  # noqa: BLE001 — a drill reports, never dies
        error, final_step, history = f"{type(e).__name__}: {e}", -1, []
    wall = time.perf_counter() - t0

    from flashmoe_tpu.profiler import postmortem as pm

    bundles = pm.find_bundles(pm_dir)
    decisions = metrics.decisions + global_metrics.decisions[g0:]
    c = metrics.counters
    evidence: dict = {
        "failures": c.get("failures", 0.0),
        "restores": c.get("restores", 0.0),
        "grad_skips": c.get("grad_skips", 0.0),
        "checkpoints": c.get("checkpoints", 0.0),
        "path_fallbacks": c.get("path_fallbacks", 0.0),
        "finite_history": bool(history) and all(
            np.isfinite(h["loss"]) for h in history if "loss" in h),
        "decision_names": sorted({d["decision"] for d in decisions}),
        "postmortem_bundles": bundles,
    }

    # ---- per-fault verdict: did the INTENDED tier absorb it? ----
    ok, why = True, []

    def need(cond, msg):
        nonlocal ok
        if not cond:
            ok = False
            why.append(msg)

    need(error is None, f"aborted: {error}")
    need(final_step == num_steps, f"ended at step {final_step}")
    if fault in ("nan_expert", "skewed_routing"):
        probe_params = (final.params if error is None else
                        init_state(jax.random.PRNGKey(seed), cfg,
                                   opt).params)
        st, _ = _stats_probe(cfg, {"layers": [{"moe": probe_params[
            "layers"][0]["moe"]}]})
        evidence["probe"] = st
        need(evidence["finite_history"], "non-finite loss leaked")
        need(c.get("failures", 0) == 0,
             "fault escalated past tier 0 (step failures)")
        if fault == "nan_expert":
            need(st["masked_experts"] >= 1, "no masked expert in stats")
        else:
            need(st["imbalance"] > cfg.num_experts / 2
                 or st["dropped_fraction"] > 0,
                 "no skew visible in stats")
    elif fault in ("nan_grad", "grad_spike"):
        need(c.get("grad_skips", 0) >= 1, "no skipped update recorded")
        need(c.get("failures", 0) == 0,
             "fault escalated past tier 1 (step failures)")
        need(c.get("restores", 0) == 0, "needless checkpoint rewind")
        need(any(d["decision"] == "trainer.grad_skip" for d in decisions),
             "no trainer.grad_skip decision")
    elif fault == "slow_step":
        need(c.get("failures", 0) >= 1, "stall was not detected")
        need(c.get("restores", 0) >= 1, "no restore after timeout")
    elif fault == "corrupt_ckpt":
        need(any(d["decision"] == "checkpoint.fallback"
                 for d in decisions), "no checkpoint.fallback decision")
        need(c.get("restores", 0) >= 1, "no restore happened")
    elif fault == "path_raise":
        need(c.get("path_fallbacks", 0) >= 1, "PathFailure not handled")
        need(any(d["decision"] == "planner.fallback" for d in decisions),
             "no planner.fallback decision")

    steps_rerun = max(0, int(c.get("steps", 0)) - num_steps)
    # loss-of-work bound: a rewind replays at most the window since the
    # newest usable checkpoint — one interval, two when the newest was
    # the corrupted one (fallback lands one checkpoint further back)
    bound = checkpoint_every * (2 if fault == "corrupt_ckpt" else 1)
    retries = int(c.get("failures", 0))
    if fault not in ("nan_expert", "skewed_routing", "nan_grad",
                     "grad_spike"):
        need(steps_rerun <= bound * max(1, retries),
             f"loss of work {steps_rerun} exceeds bound "
             f"{bound * max(1, retries)}")
    else:
        need(steps_rerun == 0, "in-graph tier re-ran steps")
    # every in-job fault recovers below the process-death line: a
    # postmortem bundle here would mean recovery gave up (the forensics
    # loop of docs/OBSERVABILITY.md — bundles are for deaths only)
    need(not bundles,
         f"recovered fault left postmortem bundle(s): {bundles}")

    clear()
    return DrillResult(
        fault=fault, expected_tier=EXPECTED_TIER[fault], recovered=ok,
        reason="; ".join(why), final_step=final_step,
        steps_rerun=steps_rerun, wall_s=round(wall, 3),
        evidence=evidence, decisions=decisions)


def run_matrix(faults=FAULTS, **kw) -> list[DrillResult]:
    return [run_drill(f, **kw) for f in faults]
