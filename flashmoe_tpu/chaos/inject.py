"""In-graph fault injection points — the chaos harness's data plane.

A tiny, import-light registry (this module must be importable from the
hot-path ops without dragging the runtime in).  Injection points are
*armed* host-side before a computation is traced; the hook sites in
:mod:`flashmoe_tpu.ops.moe` / :mod:`flashmoe_tpu.ops.gate` /
:mod:`flashmoe_tpu.parallel.ep` / :mod:`flashmoe_tpu.runtime.trainer`
check :func:`is_armed` with a plain Python ``if`` — a trace-time check,
so a disarmed registry adds ZERO ops to any compiled graph, and an armed
one splices the fault into the jaxpr deterministically.

Because arming is a trace-time decision, computations jitted BEFORE a
point was armed keep their fault-free trace (jit caches by Python-level
closure state).  The drill harness (:mod:`flashmoe_tpu.chaos.drill`)
always arms before building its train step; tests that re-arm must
rebuild (or re-jit) the computation.

Points:

=================  ==========================================  =========
point              hook site                                   spec keys
=================  ==========================================  =========
``nan_expert``     capacity expert-output buffers [E, C, H]    expert
                   (ops/moe.py, parallel/ep.py)
``skewed_routing`` router logits (ops/gate.py router_xla;      expert,
                   armed drills force the XLA gate)            bias
``nan_grad``       trainer gradients at one step               step
``grad_spike``     trainer gradients at one step               step,
                                                               scale
``probe_rates``    per-device throughput probe                 rates
                   (runtime/throughput.py device_rates —
                   host-side, not in-graph: supplies the
                   reading a degraded chip WOULD produce,
                   so the slow_device drill exercises the
                   controller's production re-probe path)
=================  ==========================================  =========

Host-level faults (``slow_step``, ``corrupt_ckpt``, ``path_raise``,
``preempt``, ``device_loss``) do not live here — they ride
:func:`flashmoe_tpu.chaos.make_injector` /
:func:`flashmoe_tpu.chaos.wrap_step` instead (``probe_rates`` is the
one host-side point in this registry: the probe it poisons is itself a
host-side measurement consulted at a step boundary, so the arm/disarm
lifecycle — not wrap_step — is the right seam).
"""

from __future__ import annotations

import jax.numpy as jnp

_ARMED: dict[str, dict] = {}

POINTS = ("nan_expert", "skewed_routing", "nan_grad", "grad_spike",
          "probe_rates")


def arm(point: str, **spec) -> None:
    """Arm an in-graph injection point.  Idempotent; later arms replace
    the spec.  Remember to (re)build any jitted computation AFTER arming
    — jit caches the fault-free trace."""
    if point not in POINTS:
        raise ValueError(f"unknown injection point {point!r}; "
                         f"in-graph points: {POINTS}")
    _ARMED[point] = dict(spec)


def disarm(point: str | None = None) -> None:
    """Disarm one point, or everything when ``point`` is None."""
    if point is None:
        _ARMED.clear()
    else:
        _ARMED.pop(point, None)


def is_armed(point: str) -> bool:
    return point in _ARMED


def spec(point: str) -> dict:
    return dict(_ARMED.get(point, {}))


def trace_signature() -> tuple:
    """Hashable snapshot of the armed registry, for use as a STATIC
    argument of cached traces.  Arming is trace-time state, so any
    cache keyed only on (function, config) — ``jax.checkpoint``'s remat
    cache in :func:`flashmoe_tpu.models.transformer.forward` — would
    resurrect a stale fault-free (or fault-carrying) jaxpr when the
    registry changes between two builds of an EQUAL config.  Threading
    this signature through the static args makes the registry part of
    the cache key: () when disarmed (the zero-cost common case), a
    distinct tuple per armed spec otherwise."""
    return tuple(sorted(
        (point, tuple(sorted(sp.items()))) for point, sp in _ARMED.items()
    ))


# ----------------------------------------------------------------------
# Appliers — called from the hook sites only when is_armed() (trace time)
# ----------------------------------------------------------------------

def poison_expert(ybuf):
    """NaN one expert's slab of a capacity-format output [E, C, H]."""
    ybuf = jnp.asarray(ybuf)
    e = int(_ARMED["nan_expert"].get("expert", 0)) % ybuf.shape[0]
    return ybuf.at[e].set(jnp.asarray(jnp.nan, ybuf.dtype))


def poison_local_expert(yloc, axis: str, num_experts: int, *,
                        local_offset: int = 0,
                        local_total: int | None = None):
    """NaN the armed GLOBAL expert's rows of a pre-exchange expert-
    parallel buffer ``[nE, rows, H]`` inside a shard_map body over
    ``axis``: only the expert's owner rank poisons, at its local row —
    the same global-expert-id semantics as :func:`poison_expert`'s
    ``[E, C, H]`` site, but applied where the fault physically
    originates (the owner, BEFORE the return exchange), so the NaN
    crosses the transport — wire compression included — before any
    health mask sees it.

    The buffer may be a chunk of the owner's local experts (the chunked
    a2a pipeline, ``MoEConfig.a2a_chunks``): ``local_total`` is the
    owner's full local-expert count (default: the buffer's own leading
    dim — the whole-slab case) and ``local_offset`` the first local
    expert this buffer covers.  A chunk that does not contain the armed
    expert is returned untouched — all offsets are trace-time ints, so
    the decision is static per chunk."""
    import jax

    yloc = jnp.asarray(yloc)
    nrows = yloc.shape[0]
    total = local_total if local_total is not None else nrows
    e = int(_ARMED["nan_expert"].get("expert", 0)) % num_experts
    row = e % total - local_offset
    if row < 0 or row >= nrows:
        return yloc  # armed expert lives in another chunk
    mine = jax.lax.axis_index(axis) == e // total
    poisoned = yloc.at[row].set(jnp.asarray(jnp.nan, yloc.dtype))
    return jnp.where(mine, poisoned, yloc)


def poison_logits(logits):
    """Bias the router logits hard toward one expert: logits [S, E].
    An additive logit bias is input-independent — every token's top-1
    collapses onto the target expert (weight-level biasing would scale
    with ``sum(x)``, whose sign flips per token)."""
    s = _ARMED["skewed_routing"]
    logits = jnp.asarray(logits)
    e = int(s.get("expert", 0)) % logits.shape[-1]
    bias = float(s.get("bias", 100.0))
    return logits.at[:, e].add(jnp.asarray(bias, logits.dtype))


def poison_grads(grads, step):
    """Apply armed gradient faults at their target step (in-graph:
    ``step`` is the traced TrainState.step, compared with jnp.where)."""
    if "nan_grad" in _ARMED:
        at = jnp.asarray(int(_ARMED["nan_grad"].get("step", 0)), step.dtype)
        grads = _tree_where(step == at, jnp.nan, grads)
    if "grad_spike" in _ARMED:
        s = _ARMED["grad_spike"]
        at = jnp.asarray(int(s.get("step", 0)), step.dtype)
        scale = float(s.get("scale", 1e4))
        grads = _tree_scale_where(step == at, scale, grads)
    return grads


def _tree_where(cond, bad_value, tree):
    import jax

    return jax.tree_util.tree_map(
        lambda g: jnp.where(cond, jnp.asarray(bad_value, g.dtype), g)
        if hasattr(g, "dtype") and jnp.issubdtype(g.dtype, jnp.floating)
        else g,
        tree,
    )


def _tree_scale_where(cond, scale, tree):
    import jax

    return jax.tree_util.tree_map(
        lambda g: jnp.where(cond, g * jnp.asarray(scale, g.dtype), g)
        if hasattr(g, "dtype") and jnp.issubdtype(g.dtype, jnp.floating)
        else g,
        tree,
    )
