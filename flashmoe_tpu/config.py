"""Static configuration system for flashmoe-tpu.

The reference (osayamenja/FlashMoE) bakes its model/job parameters in at
*compile time*: ``csrc/flashmoe_config.json`` is converted to ``-D`` macros by
``setup.py:226-292`` / ``CMakeLists.txt:114-159`` and consumed into the
``ACC`` constexpr struct (``csrc/include/flashmoe/types.cuh:441-512``), which
derives ~40 compile-time constants (token count ``S``, expert capacity ``EC``,
padded capacity ``pEC``, tile counts, gate reduction mode, combine mode, ...).

On TPU we get the same "compile-time specialization" for free from JAX
tracing: a frozen, hashable dataclass passed as a static argument (or closed
over) specializes every ``jit``/Pallas compilation to the exact shapes, with
no rebuild step.  This module is therefore the TPU-native equivalent of the
whole JSON -> macro -> ``ACC`` pipeline, including the schema constraints of
``csrc/flashmoe_config.schema.json:34-63`` (divisibility requirements) and
the derived-quantity formulas of ``types.cuh:497-499``.
"""

from __future__ import annotations

import dataclasses
import json
import math
from typing import Any

import jax.numpy as jnp

# TPU-native tile geometry.  The MXU is a 128x128 systolic array and the VPU
# operates on (8, 128) vregs; 128 is the universal lane width.  The reference
# uses BLOCK_M=128 / BLOCK_N=64 CUDA tiles (types.cuh); on TPU the natural
# block is 128x128.
BLOCK_M = 128
BLOCK_N = 128
LANE = 128


class Activation:
    """Activation selector, mirroring ``hidden_act`` (0=relu / 1=gelu) in
    ``csrc/flashmoe_config.json`` with TPU-relevant extensions."""

    RELU = "relu"
    GELU = "gelu"
    SILU = "silu"  # used by Mixtral/DeepSeek family (gated FFN)


_DTYPE_MAP = {
    # reference torch_dtype codes: 0=f32 / 1=tf32 / 2=bf16 / 3=fp16
    # (csrc/flashmoe_config.schema.json).  tf32 has no TPU equivalent; the
    # closest MXU mode is bf16 inputs with f32 accumulation, which is what
    # "bf16" here means.  fp16 is not TPU-native; we map it to bf16.
    0: jnp.float32,
    1: jnp.bfloat16,
    2: jnp.bfloat16,
    3: jnp.bfloat16,
    "float32": jnp.float32,
    "f32": jnp.float32,
    "tf32": jnp.bfloat16,
    "bfloat16": jnp.bfloat16,
    "bf16": jnp.bfloat16,
    "float16": jnp.bfloat16,
    "fp16": jnp.bfloat16,
}


def _round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


@dataclasses.dataclass(frozen=True)
class MoEConfig:
    """Frozen model/job configuration.

    Field names follow ``csrc/flashmoe_config.json:1-17`` where a counterpart
    exists; everything derived mirrors ``ACC`` (``types.cuh:441-512``).
    Instances are hashable and therefore usable as ``jit`` static arguments.
    """

    # --- core MoE shape (reference names) ---
    num_experts: int = 8
    expert_top_k: int = 2
    hidden_size: int = 1024
    intermediate_size: int = 4096
    sequence_len: int = 128
    mini_batch: int = 1
    global_batch: int = 1
    capacity_factor: float = 1.25
    drop_tokens: bool = True
    is_training: bool = False
    hidden_act: str = Activation.GELU

    # --- full-model shape ---
    num_layers: int = 2
    moe_frequency: int = 1  # every Nth layer is MoE
    vocab_size: int = 32000

    # --- extensions beyond the reference (needed for a full framework) ---
    num_shared_experts: int = 0  # DeepSeekMoE-style always-on experts
    num_heads: int = 8
    num_kv_heads: int = 0  # 0 => = num_heads (MHA); <num_heads => GQA
    head_dim: int = 0  # 0 => hidden_size // num_heads
    gated_ffn: bool = False  # SwiGLU-style expert FFN (Mixtral/DeepSeek)
    router_jitter: float = 0.0
    aux_loss_coef: float = 0.01
    router_z_loss_coef: float = 0.0
    rope_theta: float = 10000.0

    # --- numerics ---
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    accum_dtype: Any = jnp.float32

    # --- parallelism (mesh axis sizes; 1 = off) ---
    dp: int = 1  # data parallel
    ep: int = 1  # expert parallel
    tp: int = 1  # tensor parallel
    sp: int = 1  # sequence/context parallel
    pp: int = 1  # pipeline parallel

    # distributed MoE transport when ep > 1: "collective" (XLA all-to-all,
    # the robust default), "fused" (in-kernel RDMA, the FlashDMoE path),
    # "ragged" (dropless ragged all-to-all), or "auto" — the analytical
    # planner (flashmoe_tpu/planner/) picks per (config, mesh,
    # generation): predicted-latency winner, measured-winner when
    # tuning-table / bench measurements cover the shape
    moe_backend: str = "collective"

    # Wire-dtype compression of the EP all-to-all payload
    # (flashmoe_tpu/ops/wire.py): tokens are quantized immediately
    # before each exchange and dequantized immediately after, so only
    # the wire sees the narrow dtype — every compute stage stays at
    # `dtype`.  `wire_dtype` covers the dispatch leg (tokens -> expert
    # owners), `wire_dtype_combine` the return leg (expert outputs back
    # to token owners — independent because it carries gate-weighted
    # results that often want to stay high-precision).  Values: "bf16"
    # (plain cast), "e4m3"/"e5m2" (per-token-row scaled fp8, f32 scales
    # ride as a sidecar).  Default None: OFF, the hot path is
    # bit-identical to a compression-free build (the collect_stats /
    # degrade_unhealthy_experts convention; asserted by
    # tests/test_wire.py).  XLA transports only — the fused RDMA kernel
    # moves raw slabs, so `moe_backend='fused'` rejects these knobs.
    wire_dtype: str | None = None
    wire_dtype_combine: str | None = None

    # Per-hop wire dtype for the CROSS-SLICE (DCN) stage of the
    # two-stage hierarchical all-to-all (parallel/ep.py
    # _hierarchical_a2a): when the ep axis spans DCN-connected slices,
    # the exchange decomposes into an intra-slice ICI hop and one
    # aggregated DCN message per slice pair — and the DCN hop, priced
    # ~5x slower per byte than ICI (topology._DCN_SPEC), can carry a
    # narrower wire than the in-slice hop.  Set (e.g. "e4m3") the DCN
    # stage of BOTH legs re-encodes at this dtype while the ICI stage
    # stays at the leg's own wire (`wire_dtype` / `wire_dtype_combine`,
    # raw when those are off).  Default None: INHERIT the leg wire —
    # the whole exchange encodes once and the traced graph is exactly
    # the single-dtype build (bit-identical; proven by the staticcheck
    # invariant engine).  Inert on flat (single-slice) exchanges — there
    # is no DCN hop to re-encode.  XLA transports only, like the other
    # wire knobs (the fused RDMA kernel moves raw slabs).
    wire_dtype_dcn: str | None = None

    # Wire dtype for the serving fabric's KV-page handoff
    # (flashmoe_tpu/fabric/handoff.py): when prefill and decode run in
    # separate pools, a finished prompt's KV run crosses DCN as whole
    # pages — this knob compresses that payload with the same per-row
    # codec as the a2a wires, one scale per (layer, page) block riding
    # a `_qscale` sidecar.  HOST-SIDE only: the codec runs between the
    # prefill jit and the decode-side page store, so no traced graph
    # changes and no collective moves (census-proven; registered in
    # staticcheck/registry.py with changes_graph=False).  Default None:
    # OFF, handed-off pages are the prefill jit's own arrays untouched
    # — a fabric drill is bit-equal to the single-pool engine
    # (tests/test_fabric.py's acceptance drill).
    kv_wire_dtype: str | None = None

    # Chunked double-buffered EP dispatch (Comet-style compute–
    # communication overlap, arXiv 2502.19811): split the [E, C, H]
    # exchange slab along the local-expert axis into this many chunks
    # and software-pipeline the XLA transports so chunk k's expert FFN
    # overlaps chunk k+1's all-to-all, on the dispatch AND combine legs
    # (parallel/ep.py / parallel/ragged_ep.py; priced by the planner,
    # which also picks the best count under moe_backend='auto').
    # Composes with the wire codec: each chunk encodes/decodes inside
    # the pipeline.  Must divide num_experts // ep (validated here; the
    # shard body re-validates against the actual mesh).  Default None:
    # OFF, the serial schedule — bit-identical to a pre-chunking build
    # (the collect_stats / wire_dtype convention, asserted by
    # tests/test_chunked.py).  The fused RDMA kernel ignores the knob:
    # its transport already overlaps in-kernel per-slab (docs/PERF.md).
    a2a_chunks: int | None = None

    # In-graph MoE observability (flashmoe_tpu/ops/stats.py): when True,
    # every MoE layer additionally returns a MoEStats tuple (per-expert
    # load histogram, dropped-token fraction, capacity utilization,
    # imbalance factor, router entropy, top-k confidence) on
    # MoEOutput.stats, and the transformer/trainer thread them into step
    # metrics and the flight recorder.  Default False: the hot path is
    # bit-identical to a stats-free build and the EP layers add no extra
    # collectives (asserted by tests/test_observe.py).
    collect_stats: bool = False

    # Tier-0 fault tolerance (flashmoe_tpu/ops/health.py): when True,
    # every MoE layer checks its per-expert FFN outputs for non-finite
    # values *inside the compiled graph*, zeroes a sick expert's
    # contribution, and renormalizes each token's surviving gate weights
    # (jnp.where only — jit/vmap-safe, no collectives).  A dead or
    # NaN-poisoned expert then degrades quality for its tokens instead of
    # poisoning the whole step.  Masked expert/assignment counts land in
    # MoEStats (masked_experts / masked_fraction) when collect_stats is
    # also set, so the flight recorder sees degradation.  Default False:
    # the hot path is bit-identical to a pre-fault-tolerance build
    # (asserted by tests/test_chaos.py).
    degrade_unhealthy_experts: bool = False

    # Phase-level profiling (flashmoe_tpu/profiler/): when True, the
    # MoE layer bodies fence each phase (gate, dispatch, a2a legs,
    # expert FFN, combine) with block_until_ready so a host-armed
    # PhaseTimeline measures real per-phase wall time on EAGER
    # executions — the xprof-free phase timeline the cost ledger joins.
    # Host-side only: fences block on concrete values and no-op on
    # tracers, so the traced graph is byte-identical with the knob on
    # or off (registered as a graph-neutral knob in the staticcheck
    # registry and proven by the invariant engine).  Default False:
    # the bodies contain no fence calls at all.
    profile_phases: bool = False

    # Static hot-expert replica routing map, written by the self-healing
    # runtime controller (flashmoe_tpu/runtime/controller.py) when it
    # re-places experts under sustained load skew: each (hot, slot) pair
    # splits the traffic of expert ``hot`` between its own slot and the
    # replica ``slot`` (whose FFN weights the controller overwrites with
    # a copy of ``hot``'s — the victim slot must be a ~dead expert, so
    # evicting it costs nothing).  Applied in-graph AFTER top-k
    # (ops/gate.py): tokens routed to ``hot`` alternate between the two
    # physical slots by token parity, so each token is processed by
    # exactly one value-identical replica and the combine merges
    # contributions unchanged — the hot expert's load (and its capacity
    # drops) split in half.  Default (): OFF, bit-identical to a
    # replica-free build (the collect_stats / wire_dtype convention;
    # registered in staticcheck/registry.py, proven by the invariant
    # engine).
    expert_replicas: tuple = ()

    # Serving-phase selector consumed by the analytical planner when
    # ``moe_backend='auto'`` (flashmoe_tpu/planner/select.py and the
    # serving engine, flashmoe_tpu/serving/): None prices the layer at
    # the training shape (B x S tokens per step — the default every
    # training job uses); "decode" prices it at DECODE token counts
    # (per-step tokens = the decode batch, each fanning out top_k
    # exchange rows — a different regime where per-message alphas
    # dominate and the training-shaped a2a schedules are simply wrong,
    # RaMP arXiv 2604.26039); "prefill" prices the full-sequence
    # inference forward (training shape, inference-mode feasibility).
    # Pure selector: the traced graph is identical for every value —
    # only WHICH path 'auto' resolves to changes (registered in
    # staticcheck/registry.py SELECTOR_FIELDS).
    serving_mode: str | None = None

    # Forced FFN schedule of the fused RDMA kernel
    # (parallel/fused.py:_fused_schedule): None = auto (the IO-aware
    # resolution — arrival-batched when the hidden slab fits VMEM,
    # per-source resident when its byte trade wins, row-windowed
    # ('rowwin') when it beats per-row-tile streaming, 'stream'
    # otherwise); or one of 'batched' / 'resident' / 'stream' /
    # 'rowwin' to pin the schedule.  A forced schedule still faces the
    # hard VMEM feasibility gate — the kernel raises a clear ValueError
    # rather than launching an infeasible geometry, and the planner
    # marks the matching fused[<schedule>] row infeasible with the
    # reason.  Pure selector: every value computes the same function
    # (bit-identity across schedules asserted by tests/test_fused.py);
    # only execution geometry changes (registered in
    # staticcheck/registry.py SELECTOR_FIELDS).
    fused_schedule: str | None = None

    # Quantized expert weight storage & compute (flashmoe_tpu/quant/):
    # "int8" or "e4m3" stores the MoE FFN expert weights (w_up /
    # w_gate / w_down) at 1 byte per element with per-output-channel
    # f32 scales, dequantized IN COMPUTE — every matmul still
    # accumulates f32, biases/router stay full-precision.  With
    # pre-quantized params (quant.quantize_state) the weights stream
    # from HBM and live in memory at the narrow width (the planner
    # prices exactly this: analysis.path_costs weight terms, the fused
    # rowwin K-window geometry at 1 B/elem); with ordinary params the
    # layers fake-quant in-graph (round-trip) — same numerics, no
    # storage savings.  Default None: OFF, no quant code runs and the
    # graph is bit-identical to a pre-quant build (the collect_stats /
    # wire_dtype convention; registered in staticcheck/registry.py,
    # proven by the invariant engine).  Inference-only: post-training
    # quantization has no gradient story (jnp.round kills them), so
    # is_training=True rejects the knob — train at full precision and
    # quantize the checkpoint.
    expert_quant: str | None = None

    # Inference-only: fuse the dispatch gather into the FFN kernel
    # (ops/expert.py:grouped_ffn_tokens — no [E, C, H] HBM buffer).
    # None = auto: follow the FLASHMOE_GATHER_FUSED env var, else stay on
    # the explicit-dispatch path, which is hardware-validated.  The gather
    # kernel is opt-in until a committed stage_bench row shows it winning
    # on real TPU (round-2 advisor finding; VERDICT r2 "do this" #2).
    gather_fused: bool | None = None

    def __post_init__(self):
        if self.num_experts < 1:
            raise ValueError("num_experts must be >= 1")
        if not (1 <= self.expert_top_k <= self.num_experts):
            raise ValueError("expert_top_k must be in [1, num_experts]")
        # schema.json:34-63: hidden/intermediate multipleOf 64, seq multipleOf 128.
        if self.hidden_size % 64:
            raise ValueError("hidden_size must be a multiple of 64")
        if self.intermediate_size % 64:
            raise ValueError("intermediate_size must be a multiple of 64")
        if self.num_experts > 1 and self.num_experts % self.ep:
            raise ValueError("num_experts must divide evenly over ep")
        if self.capacity_factor <= 0:
            raise ValueError("capacity_factor must be > 0")
        if self.moe_backend not in ("collective", "fused", "ragged",
                                    "auto"):
            raise ValueError(
                f"moe_backend {self.moe_backend!r} not in "
                f"('collective', 'fused', 'ragged', 'auto')"
            )
        if self.fused_schedule not in (None, "batched", "resident",
                                       "stream", "rowwin"):
            raise ValueError(
                f"fused_schedule {self.fused_schedule!r} not in "
                f"(None, 'batched', 'resident', 'stream', 'rowwin')"
            )
        # reject combinations the specialized transports cannot serve
        # rather than silently falling back to the collective path
        if self.moe_backend in ("fused", "ragged") and self.tp > 1:
            raise ValueError(
                f"moe_backend={self.moe_backend!r} does not compose with "
                f"tp>1; use moe_backend='collective'"
            )
        if self.moe_backend == "ragged" and self.num_shared_experts:
            raise ValueError(
                "moe_backend='ragged' does not support shared experts; "
                "use 'collective' or 'fused'"
            )
        # wire-dtype knobs: reject unsupported combinations at config
        # time (unknown name, fp8 on a jax build without float8, wire
        # wider than the compute dtype, fused backend) instead of
        # failing inside shard_map
        from flashmoe_tpu.ops import wire as _wire

        for knob, val in (("wire_dtype", self.wire_dtype),
                          ("wire_dtype_combine", self.wire_dtype_combine),
                          ("wire_dtype_dcn", self.wire_dtype_dcn),
                          ("kv_wire_dtype", self.kv_wire_dtype)):
            if val is None:
                continue
            wd = _wire.resolve(val)  # ValueError on unknown/unsupported
            if jnp.dtype(wd).itemsize > jnp.dtype(self.dtype).itemsize:
                raise ValueError(
                    f"{knob}={val!r} ({jnp.dtype(wd).itemsize} B) is wider "
                    f"than the compute dtype "
                    f"{jnp.dtype(self.dtype).name} "
                    f"({jnp.dtype(self.dtype).itemsize} B); a wire must "
                    f"compress, not inflate")
        # quantized expert storage: reject unsupported combinations at
        # config time (unknown name, e4m3 without float8 support,
        # training jobs, tensor-parallel experts) instead of failing
        # inside a layer trace
        if self.expert_quant is not None:
            from flashmoe_tpu.quant import core as _qcore

            _qcore.resolve(self.expert_quant)  # ValueError on unknown
            if self.is_training:
                raise ValueError(
                    "expert_quant is post-training (inference-only): "
                    "jnp.round has no useful gradient, so a quantized "
                    "training step would silently learn nothing — "
                    "train at full precision and quantize_state() the "
                    "checkpoint")
            if self.tp > 1:
                raise ValueError(
                    "expert_quant does not compose with tp>1 (the "
                    "Megatron intermediate split would shard w_up's "
                    "per-output-channel scales); use tp=1")
        # chunked a2a pipeline: reject impossible chunk counts at config
        # time (clear ValueError) instead of a shape error inside the
        # pipeline loop; the shard body re-checks against the actual
        # mesh width, which may differ from cfg.ep
        if self.a2a_chunks is not None:
            n = self.a2a_chunks
            if not isinstance(n, int) or n < 1:
                raise ValueError(
                    f"a2a_chunks={n!r} must be a positive int (or None "
                    f"for the serial schedule)")
            nlx = self.num_experts // max(self.ep, 1)
            if n > 1 and (nlx == 0 or nlx % n):
                raise ValueError(
                    f"a2a_chunks={n} must divide the local-expert axis "
                    f"(num_experts // ep = {nlx}); pick a divisor or "
                    f"leave a2a_chunks=None for the serial schedule")
        # replica routing map: reject malformed maps at config time so
        # the in-graph remap (ops/gate.py) only ever sees valid static
        # (hot, slot) pairs
        if self.expert_replicas:
            if not isinstance(self.expert_replicas, tuple):
                raise ValueError(
                    f"expert_replicas must be a tuple of (hot, slot) "
                    f"pairs, got {type(self.expert_replicas).__name__}")
            seen_slots: set = set()
            hots = set()
            for pair in self.expert_replicas:
                if (not isinstance(pair, tuple) or len(pair) != 2
                        or not all(isinstance(v, int) for v in pair)):
                    raise ValueError(
                        f"expert_replicas entries must be (hot, slot) "
                        f"int pairs, got {pair!r}")
                hot, slot = pair
                if hot == slot:
                    raise ValueError(
                        f"expert_replicas pair {pair} replicates an "
                        f"expert onto its own slot")
                for v in pair:
                    if not 0 <= v < self.num_experts:
                        raise ValueError(
                            f"expert_replicas id {v} out of range "
                            f"[0, {self.num_experts})")
                if slot in seen_slots:
                    raise ValueError(
                        f"expert_replicas slot {slot} used as a replica "
                        f"target twice")
                if hot in hots:
                    # the in-graph split is a token-parity half/half
                    # between ONE (hot, slot) pair; a second replica of
                    # the same expert would receive zero traffic — its
                    # evicted slot wasted silently
                    raise ValueError(
                        f"expert_replicas replicates expert {hot} "
                        f"twice; the parity split supports exactly one "
                        f"replica per hot expert")
                seen_slots.add(slot)
                hots.add(hot)
            if hots & seen_slots:
                raise ValueError(
                    f"expert_replicas chains a replica "
                    f"({sorted(hots & seen_slots)} appear as both hot "
                    f"expert and replica slot)")
        if self.serving_mode not in (None, "prefill", "decode"):
            raise ValueError(
                f"serving_mode {self.serving_mode!r} not in "
                f"(None, 'prefill', 'decode')")
        if ((self.wire_dtype or self.wire_dtype_combine
                or self.wire_dtype_dcn)
                and self.moe_backend == "fused"):
            raise ValueError(
                "wire-dtype compression rides the XLA transports; "
                "moe_backend='fused' RDMAs raw slabs in-kernel — use "
                "'collective', 'ragged', or 'auto'"
            )

    # ------------------------------------------------------------------
    # Derived quantities (ACC equivalents, types.cuh:441-512)
    # ------------------------------------------------------------------

    @property
    def tokens(self) -> int:
        """S = sequence_len * mini_batch (types.cuh:470)."""
        return self.sequence_len * self.mini_batch

    @property
    def padded_num_experts(self) -> int:
        """PX: experts padded to the lane width (types.cuh ``PX``)."""
        return _round_up(self.num_experts, LANE)

    def capacity_for(self, tokens: int) -> int:
        """EC (types.cuh:497-499): CF * TK * ceil(tokens/E) when dropping,
        else all tokens.  The floor of 8 keeps the capacity buffer aligned to
        the TPU sublane count.  Used for both the global token count and the
        EP layer's per-shard capacity."""
        if not self.drop_tokens:
            return tokens
        return max(
            8,
            int(
                math.ceil(
                    self.capacity_factor
                    * self.expert_top_k
                    * math.ceil(tokens / self.num_experts)
                )
            ),
        )

    @property
    def expert_capacity(self) -> int:
        """EC over the full (unsharded) token count."""
        return self.capacity_for(self.tokens)

    @property
    def padded_expert_capacity(self) -> int:
        """pEC: EC padded to the block size (types.cuh ``pEC``)."""
        return _round_up(self.expert_capacity, 8)

    @property
    def num_local_experts(self) -> int:
        """nLx under the (uniform) EP sharding."""
        return max(1, self.num_experts // self.ep)

    @property
    def resolved_num_kv_heads(self) -> int:
        return self.num_kv_heads or self.num_heads

    @property
    def resolved_head_dim(self) -> int:
        return self.head_dim or self.hidden_size // self.num_heads

    @property
    def moe_layer_indices(self) -> tuple[int, ...]:
        """Which transformer layers carry an MoE FFN (vs dense)."""
        if self.num_experts <= 1:
            return ()
        f = max(1, self.moe_frequency)
        return tuple(i for i in range(self.num_layers) if (i + 1) % f == 0)

    @property
    def param_count(self) -> int:
        """PC (types.cuh:491-492): Chinchilla-style dense parameter count used
        by the Decider's cost model for gradient-buffer sizing."""
        h, i, v, l = (
            self.hidden_size,
            self.intermediate_size,
            self.vocab_size,
            self.num_layers,
        )
        return v * h + l * (4 * h * h + 2 * h * i) + h * v

    # ------------------------------------------------------------------
    # IO
    # ------------------------------------------------------------------

    @classmethod
    def from_json(cls, path_or_dict) -> "MoEConfig":
        """Load from a reference-style ``flashmoe_config.json`` dict/file."""
        if isinstance(path_or_dict, (str,)):
            with open(path_or_dict) as f:
                raw = json.load(f)
        else:
            raw = dict(path_or_dict)
        act = raw.pop("hidden_act", 1)
        if isinstance(act, int):
            act = Activation.RELU if act == 0 else Activation.GELU
        dtype = _DTYPE_MAP[raw.pop("torch_dtype", 2)]
        known = {f.name for f in dataclasses.fields(cls)}
        kwargs = {k: v for k, v in raw.items() if k in known}
        for b in ("drop_tokens", "is_training"):
            if b in kwargs:
                kwargs[b] = bool(kwargs[b])
        return cls(hidden_act=act, dtype=dtype, **kwargs)

    def to_json(self) -> str:
        d = dataclasses.asdict(self)
        for k in ("dtype", "param_dtype", "accum_dtype"):
            d[k] = jnp.dtype(d[k]).name
        return json.dumps(d, indent=2)

    def replace(self, **kw) -> "MoEConfig":
        return dataclasses.replace(self, **kw)


# Benchmark configurations from BASELINE.json / BASELINE.md.
BENCH_CONFIGS = {
    # 1. correctness reference
    "tiny": MoEConfig(num_experts=8, expert_top_k=2, hidden_size=1024,
                      intermediate_size=4096, sequence_len=128),
    # 2. single-chip token-scaling bench (reference headline config uses
    #    E=64, H=2048, I=2048, S=8192; BASELINE.json asks d_model=4096, S=4096)
    "token_scaling": MoEConfig(num_experts=64, expert_top_k=2, hidden_size=4096,
                               intermediate_size=4096, sequence_len=4096,
                               capacity_factor=1.0),
    "reference": MoEConfig(num_experts=64, expert_top_k=2, hidden_size=2048,
                           intermediate_size=2048, sequence_len=8192,
                           capacity_factor=1.0),
    # 3. Mixtral-8x7B FFN dims, 8-chip EP
    "mixtral": MoEConfig(num_experts=8, expert_top_k=2, hidden_size=4096,
                         intermediate_size=14336, sequence_len=4096,
                         gated_ffn=True, hidden_act=Activation.SILU, ep=8),
    # 4. DeepSeekMoE-style
    "deepseek": MoEConfig(num_experts=64, expert_top_k=6, hidden_size=2048,
                          intermediate_size=1408, sequence_len=4096,
                          num_shared_experts=2, gated_ffn=True,
                          hidden_act=Activation.SILU, ep=8),
    # 5. 256-expert weak-scaling / payload-skew bench (BASELINE.json
    #    config #5, sized for v5p-256).  ep clamps to the devices actually
    #    present at bench time (bench.py main), so the same name runs
    #    single-chip for latency, on the virtual 8-device mesh for
    #    correctness (tests/test_presets.py), and at full scale when a
    #    v5p pod is reachable.  Per-rank tokens stay constant as ep grows
    #    — the weak-scaling axis of the reference's scaling_gpus_8 plot
    #    (/root/reference/README.md:46).
    "weak_scaling_256": MoEConfig(num_experts=256, expert_top_k=2,
                                  hidden_size=2048, intermediate_size=2048,
                                  sequence_len=8192, capacity_factor=1.0,
                                  ep=256),
}
