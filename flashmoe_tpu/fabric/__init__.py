"""Disaggregated serving fabric (ISSUE 16).

The single-engine serving stack (:mod:`flashmoe_tpu.serving`) decodes
on one device pool and computes prefill inline between decode steps.
This package is its production-scale composition: prefill and decode
run on SEPARATE Decider-priced pools (:mod:`flashmoe_tpu.serving.
pools`), finished prefill pages stream to the decode side through a
DCN-priced KV handoff codec (:mod:`flashmoe_tpu.fabric.handoff`), a
join-shortest-queue router with session affinity spreads requests over
N engine replicas (:mod:`flashmoe_tpu.fabric.router`), and the whole
thing is CI-able on a mocked topology (:mod:`flashmoe_tpu.fabric.topo`,
``FLASHMOE_MOCK_FABRIC`` — the serving twin of PR 12's
``FLASHMOE_MOCK_SLICES``).

The composition rule that keeps the fabric bit-replayable: every
replica is a full :class:`~flashmoe_tpu.serving.engine.ServingEngine`
sharing the MODULE-LEVEL jitted step functions, and the handoff wire
codec is exact when off — so a fabric drill with the handoff wire off
produces token streams bit-equal to the single-pool engine on the same
seeded trace (tests/test_fabric.py's acceptance drill).
"""

from flashmoe_tpu.fabric.engine import ServingFabric
from flashmoe_tpu.fabric.frontdoor import FrontDoor, FrontDoorCluster
from flashmoe_tpu.fabric.handoff import (
    KVHandoff, decode_kv_run, encode_kv_run,
)
from flashmoe_tpu.fabric.leasestore import (
    HeartbeatConfig, HeartbeatPublisher, HeartbeatWatchdog, LeaseStore,
    StaleLeaseError,
)
from flashmoe_tpu.fabric.router import ReplicaRouter
from flashmoe_tpu.fabric.topo import fabric_world
from flashmoe_tpu.fabric.transport import (
    HandoffTransport, HandoffTransportError, WIRE_MODES,
    wire_overhead_ms,
)
from flashmoe_tpu.fabric.vclock import VirtualClock

__all__ = [
    "FrontDoor",
    "FrontDoorCluster",
    "HandoffTransport",
    "HandoffTransportError",
    "HeartbeatConfig",
    "HeartbeatPublisher",
    "HeartbeatWatchdog",
    "KVHandoff",
    "LeaseStore",
    "ReplicaRouter",
    "ServingFabric",
    "StaleLeaseError",
    "VirtualClock",
    "WIRE_MODES",
    "decode_kv_run",
    "encode_kv_run",
    "fabric_world",
    "wire_overhead_ms",
]
