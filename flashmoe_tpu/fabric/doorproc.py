"""Front-door peer as a separate OS process (the cross-process drill).

``python -m flashmoe_tpu.fabric.doorproc --store PATH --peer 1
--telemetry OUT.jsonl`` runs one door peer against an EXTERNAL
:class:`~flashmoe_tpu.fabric.leasestore.LeaseStore` shared with the
parent process through the filesystem — nothing else is shared.  The
child:

* publishes monotonic ``door<peer>`` heartbeats into the store every
  iteration (the liveness the parent's watchdog could consume);
* caches the epochs of the shards it owns at startup and watches them:
  when another process advances an epoch (the parent's
  ``fail_door(peer)`` failing this door over), the child plays the
  ZOMBIE — it re-asserts the shard with the fencing token it believes
  is current (``cached_epoch + 1``).  The store must REFUSE the stale
  epoch (``frontdoor.fence`` decision, recorded in this process's own
  telemetry shard) — that refusal, crossing a real process boundary
  through fcntl locks, is the split-brain guard the drill proves;
* flushes its telemetry shard (decisions + beat records, JSONL) every
  iteration, so the parent can ``observe --merge`` the per-door shards
  even after killing the child with ``SIGKILL``.

Exit codes: ``3`` = fenced (the expected drill outcome), ``0`` = ran
all iterations unfenced, ``2`` = bad invocation.
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def _flush_telemetry(path: str, metrics, beats: list) -> None:
    with open(path, "w") as fh:
        for rec in (*beats, *metrics.decisions):
            fh.write(json.dumps(rec, default=str) + "\n")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m flashmoe_tpu.fabric.doorproc",
        description="one front-door peer in its own OS process, "
                    "sharing only the external lease store")
    ap.add_argument("--store", required=True,
                    help="path of the shared LeaseStore file")
    ap.add_argument("--peer", type=int, required=True,
                    help="this door's peer id")
    ap.add_argument("--telemetry", required=True,
                    help="this door's telemetry shard "
                         "(telemetry.door<peer>.jsonl)")
    ap.add_argument("--iterations", type=int, default=400)
    ap.add_argument("--interval", type=float, default=0.025,
                    help="seconds between heartbeat/refresh rounds")
    args = ap.parse_args(argv)

    from flashmoe_tpu.fabric.leasestore import LeaseStore, StaleLeaseError
    from flashmoe_tpu.utils.telemetry import Metrics

    metrics = Metrics()
    store = LeaseStore(args.store, metrics_obj=metrics, peer=args.peer)
    owned = {s: ls.epoch for s, ls in store.leases().items()
             if ls.owner == args.peer}
    beats: list = []
    key = f"door{args.peer}"
    for seq in range(1, args.iterations + 1):
        store.heartbeat(key, seq, ts_ms=time.monotonic() * 1e3,
                        phase="alive", step=seq)
        beats.append({"kind": "doorproc_beat", "peer": args.peer,
                      "seq": seq, "step": seq})
        table = store.leases()
        for shard, cached in sorted(owned.items()):
            cur = table.get(shard)
            if cur is None or cur.epoch <= cached:
                continue
            # someone moved our shard while we weren't looking — the
            # zombie arm: re-assert with the token we BELIEVE is next.
            # The store must refuse it (stale epoch) and that refusal
            # is this process's exit condition.
            try:
                store.write_lease(shard, args.peer, cached + 1,
                                  reason="zombie_reassert")
            except StaleLeaseError:
                _flush_telemetry(args.telemetry, metrics, beats)
                print(f"door{args.peer}: fenced off shard {shard} "
                      f"(stale epoch {cached + 1} vs {cur.epoch})",
                      file=sys.stderr)
                return 3
            # an accepted re-assert means nobody actually advanced
            # past us — adopt the new epoch
            owned[shard] = cached + 1
        _flush_telemetry(args.telemetry, metrics, beats)
        time.sleep(args.interval)
    _flush_telemetry(args.telemetry, metrics, beats)
    return 0


if __name__ == "__main__":
    sys.exit(main())
