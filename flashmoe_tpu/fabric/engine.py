"""The disaggregated serving fabric: N decode replicas behind a router,
fed by a prefill pool across the KV handoff wire.

One :class:`ServingFabric` composes the pieces the rest of the package
provides:

* **pools** — :func:`flashmoe_tpu.serving.pools.plan_serving_pools`
  splits the device world into Decider-formed prefill and decode groups
  (each with its own planner path and its own quant/wire config) when
  the world has >= 2 devices; a single-device world runs co-located,
  pool plan ``None``;
* **replicas** — ``replicas`` full :class:`~flashmoe_tpu.serving.
  engine.ServingEngine` instances (count from
  :func:`~flashmoe_tpu.fabric.topo.fabric_world`, i.e. the
  ``FLASHMOE_MOCK_FABRIC`` blocking on a mocked drill), sharing ONE
  metrics object so ``/metrics`` aggregates the fabric and the
  per-replica ``serve.rK.ttft_ms`` / ``.tpot_ms`` sketches split it;
* **handoff** — every replica's prefill runs through one
  :class:`~flashmoe_tpu.fabric.handoff.KVHandoff` (the engine's
  ``prefill_fn`` seam): the prompt is computed with the prefill pool's
  config and crosses to the replica as DCN-priced pages.  With
  ``kv_wire_dtype=None`` the crossing is exact, which is what makes the
  acceptance drill token-bit-equal to a single-pool engine;
* **router** — :class:`~flashmoe_tpu.fabric.router.ReplicaRouter`
  places each submitted request (JSQ + session affinity over the live
  ``/healthz`` snapshots); the runtime controller's replica-morph
  verdicts (:meth:`~flashmoe_tpu.runtime.controller.RuntimeController.
  maybe_morph_replicas`) drain/undrain the rotation with the PR 9
  debounce/cooldown/budget discipline.

Determinism: replicas share the module-level jits, the router breaks
ties on the lowest id, page pools are LIFO, and sampling keys on
``fold_in(PRNGKey(req.seed), delivered)`` — so a fabric drill replays
bit-identically and (wire off) matches the single-pool engine token for
token regardless of how requests land on replicas.
"""

from __future__ import annotations

import os
import tempfile

import jax

from flashmoe_tpu.config import MoEConfig
from flashmoe_tpu.fabric.handoff import KVHandoff
from flashmoe_tpu.fabric.router import ReplicaRouter
from flashmoe_tpu.fabric.topo import fabric_world
from flashmoe_tpu.serving.engine import ServeConfig, ServingEngine
from flashmoe_tpu.utils.telemetry import metrics as _global_metrics

# "break-even not priced yet" — distinct from None (priced, infeasible)
_SPEC_BE_UNSET = object()


class _ReplicaStallInjected(RuntimeError):
    """A ``replica_stall`` chaos plan hung the victim mid-step: its
    engine never returns from this step.  The fabric models the hung
    thread by catching this and never stepping the replica again —
    NOTHING announces the stall; only the heartbeat deadline can."""


class ServingFabric:
    """N-replica disaggregated serving driver.

    ``replicas=None`` resolves the count from :func:`fabric_world`
    (``FLASHMOE_MOCK_FABRIC`` on mocked drills, else 1).  ``serve``
    applies to every replica.  ``prefill_overrides`` /
    ``decode_overrides`` are per-pool ``MoEConfig.replace`` fields
    forwarded to :func:`plan_serving_pools` — the decode replicas run
    the decode pool's config (e.g. ``{"expert_quant": "int8"}`` loads
    the PR 14 int8 store per replica), the handoff prefills with the
    prefill pool's.  ``controller``: a
    :class:`~flashmoe_tpu.runtime.controller.RuntimeController` whose
    replica-morph trigger is armed (``enable_replica_morph=True``)
    observes every fabric step and drains/undrains the rotation."""

    def __init__(self, params, cfg: MoEConfig,
                 serve: ServeConfig | None = None, *,
                 replicas: int | None = None, decode_share: float = 0.5,
                 prefill_overrides: dict | None = None,
                 decode_overrides: dict | None = None,
                 metrics_obj=None, controller=None, recorder=None,
                 telemetry_port=None, affinity: bool = True,
                 vclock=None, tracer=None, transport=None,
                 fault_plan=None, heartbeat=None):
        """``vclock``: a :class:`~flashmoe_tpu.fabric.vclock.
        VirtualClock` the whole fabric steps on — one lane per replica,
        tick resolved from the pool plan's decode objective when unset;
        None (default) is the wall clock, byte-identical to the PR 15
        paths.  ``tracer``: a shared
        :class:`~flashmoe_tpu.telemetry_plane.tracing.RequestTracer`
        every replica reports into (the FrontDoor's trace authority —
        replicas step sequentially, so one listener is race-free).
        ``transport``: a :class:`~flashmoe_tpu.fabric.transport.
        HandoffTransport` the handoff sends every payload through —
        per-page CRC32 verify, timeout + bounded retry; None (default)
        keeps the PR 15 in-process wire.  ``fault_plan``: an armed
        :class:`~flashmoe_tpu.chaos.FaultPlan` with fault
        ``replica_crash`` — replica ``plan.expert % n_replicas`` dies
        silently at fabric step ``plan.step``; the crash DETECTOR
        (health probes at the top of every step) notices and migrates
        its requests, it is never told — or ``replica_stall``: the
        victim HANGS mid-step (its health probe still answers, so only
        the sub-step heartbeat deadline catches it).  ``heartbeat``: a
        :class:`~flashmoe_tpu.fabric.leasestore.HeartbeatConfig` —
        every replica publishes sub-step heartbeats into the external
        lease store and a watchdog declares a replica with pending work
        stalled after ``misses_to_stall`` beat-less fabric steps,
        triggering the same fence+evacuate+adopt migration a detected
        crash takes; None (default) publishes nothing — byte-identical
        to the PR 18 fabric."""
        if fault_plan is not None \
                and fault_plan.fault not in ("replica_crash",
                                             "replica_stall"):
            raise ValueError(
                f"ServingFabric only injects 'replica_crash' / "
                f"'replica_stall', got plan fault {fault_plan.fault!r}")
        if fault_plan is not None \
                and fault_plan.fault == "replica_stall" \
                and heartbeat is None:
            raise ValueError(
                "a replica_stall plan needs heartbeat= armed: a "
                "mid-step hang is only detectable through the sub-step "
                "heartbeat deadline (probes still answer)")
        self.fault_plan = fault_plan
        self.cfg = cfg
        self.serve = serve if serve is not None else ServeConfig()
        self.metrics = (metrics_obj if metrics_obj is not None
                        else _global_metrics)
        self.controller = controller
        self.vclock = vclock
        # fleet speculation trigger state: cumulative (drafted,
        # accepted) at the last controller observation, and the lazily
        # priced planner break-even (sentinel = not priced yet)
        self._spec_prev = (0, 0)
        self._spec_be = _SPEC_BE_UNSET

        devices = jax.devices()
        if replicas is None:
            replicas, _ = fabric_world(len(devices))
        self.n_replicas = int(replicas)
        if self.n_replicas < 1:
            raise ValueError(f"fabric needs >= 1 replica, got "
                             f"{self.n_replicas}")

        # ---- pool formation (>= 2 devices; else co-located) ----------
        self.pool_plan = None
        prefill_cfg = decode_cfg = cfg
        if len(devices) >= 2:
            from flashmoe_tpu.parallel.topology import (
                ici_adjacency, measured_worker_attrs,
            )
            from flashmoe_tpu.serving.pools import plan_serving_pools

            self.pool_plan = plan_serving_pools(
                ici_adjacency(devices),
                measured_worker_attrs(devices, cfg, probe=False), cfg,
                decode_share=decode_share,
                decode_tokens=self.serve.max_batch, devices=devices,
                prefill_overrides=prefill_overrides,
                decode_overrides=decode_overrides)
            prefill_cfg = self.pool_plan.prefill_cfg or cfg
            decode_cfg = self.pool_plan.decode_cfg or cfg
        elif prefill_overrides or decode_overrides:
            prefill_cfg = (cfg.replace(**prefill_overrides)
                           if prefill_overrides else cfg)
            decode_cfg = (cfg.replace(**decode_overrides)
                          if decode_overrides else cfg)
        self.prefill_cfg = prefill_cfg
        self.decode_cfg = decode_cfg

        # ---- the handoff link (prefill pool side) --------------------
        # prefill always computes full-precision math on the handed
        # params (the engine-side quant store is a DECODE-pool
        # property), so the handoff sees the same prefill the
        # single-pool engine would run
        decode_step_ms = (self.pool_plan.decode_ms
                          if self.pool_plan is not None else None)
        if self.vclock is not None:
            # one lane per replica; the decode tick is the pool plan's
            # per-step objective (what the priced verdict judges
            # against), so an unperturbed drill reconciles exactly
            self.vclock.ensure_lanes(self.n_replicas)
            if self.vclock.tick_ms is None:
                self.vclock.tick_ms = (decode_step_ms
                                       if decode_step_ms else 1.0)
        self.handoff = KVHandoff(
            params, prefill_cfg, self.serve.page_size,
            metrics_obj=self.metrics,
            decode_step_ms=decode_step_ms, vclock=self.vclock,
            transport=transport)

        # ---- sub-step heartbeats (external lease store) --------------
        self.heartbeat_cfg = heartbeat
        self.lease_store = None
        self.hb_watchdog = None
        self._own_store_path = None
        self._stalled: set[int] = set()  # hung mid-step (undetected
        #                                  until the heartbeat deadline)
        hb_fns: list = [None] * self.n_replicas
        if heartbeat is not None:
            from flashmoe_tpu.fabric.leasestore import (
                HeartbeatPublisher, HeartbeatWatchdog, LeaseStore,
            )

            store_path = heartbeat.store_path
            if store_path is None:
                fd, store_path = tempfile.mkstemp(
                    prefix="flashmoe-leases-", suffix=".bin")
                os.close(fd)
                self._own_store_path = store_path
            self.lease_store = LeaseStore(
                store_path, metrics_obj=self.metrics)
            tick = (self.vclock.tick_ms if self.vclock is not None
                    else (decode_step_ms or 0.0))
            self.hb_watchdog = HeartbeatWatchdog(
                self.lease_store,
                misses_to_stall=heartbeat.misses_to_stall,
                tick_ms=tick, metrics_obj=self.metrics)
            for i in range(self.n_replicas):
                pub = HeartbeatPublisher(
                    self.lease_store, i, clock=self.vclock,
                    step_fn=(lambda i=i: self.engines[i].step_idx))
                hb_fns[i] = self._wrap_heartbeat(i, pub)

        # ---- decode replicas -----------------------------------------
        pools_info = (self.pool_plan.snapshot()
                      if self.pool_plan is not None else None)
        self.engines = [
            ServingEngine(
                params, decode_cfg, self.serve,
                metrics_obj=self.metrics, recorder=recorder,
                replica_tag=f"r{i}", prefill_fn=self.handoff.prefill_fn(i),
                pools_info=pools_info, clock=self.vclock,
                tracer=tracer, heartbeat_fn=hb_fns[i])
            for i in range(self.n_replicas)
        ]
        # the router probes through the fabric's crash filter: a killed
        # replica's probe RAISES (the process is gone — there is no
        # polite snapshot), which is exactly what an external /healthz
        # probe of a dead host experiences
        self._killed: set[int] = set()   # dead (silently, undetected)
        self._crashed: set[int] = set()  # detected + evacuated
        self.migrated = 0
        self.router = ReplicaRouter(
            [self._probe_fn(i) for i in range(self.n_replicas)],
            metrics_obj=self.metrics, affinity=affinity)
        self._placement: dict = {}      # rid -> replica
        self.step_idx = 0

        self.telemetry = None
        if telemetry_port is not None:
            from flashmoe_tpu.telemetry_plane.server import maybe_server

            self.telemetry = maybe_server(
                telemetry_port, metrics_fn=lambda: self.metrics,
                health_fn=self._health_snapshot,
                vars_fn=self._vars_snapshot)

    # ---- live-plane snapshots ----------------------------------------

    def _health_snapshot(self) -> dict:
        """Fabric ``/healthz``: the aggregate load story plus each
        replica's own document."""
        reps = [e._health_snapshot() for e in self.engines]
        return {
            "steps": self.step_idx,
            "queue_depth": sum(r["queue_depth"] for r in reps),
            "active_requests": sum(r["active_requests"] for r in reps),
            "completed": sum(r["completed"] for r in reps),
            "evictions": sum(r["evictions"] for r in reps),
            "crashed": sorted(self._crashed),
            "stalled": sorted(self._stalled),
            "migrated": self.migrated,
            "router": self.router.snapshot(),
            "replicas": reps,
        }

    def _vars_snapshot(self) -> dict:
        """Fabric ``/vars``: pool plan, handoff link, router rotation,
        and every replica's resolved plans."""
        return {
            "replicas": self.n_replicas,
            "pools": (self.pool_plan.snapshot()
                      if self.pool_plan is not None else None),
            "handoff": self.handoff.snapshot(),
            "vclock": (self.vclock.snapshot()
                       if self.vclock is not None else None),
            "lease_store": (self.lease_store.snapshot()
                            if self.lease_store is not None else None),
            "watchdog": (self.hb_watchdog.snapshot()
                         if self.hb_watchdog is not None else None),
            "router": self.router.snapshot(),
            "engines": [e._vars_snapshot() for e in self.engines],
        }

    def close(self) -> None:
        if self.telemetry is not None:
            self.telemetry.stop()
            self.telemetry = None
        for e in self.engines:
            e.close()
        if self._own_store_path is not None:
            try:
                os.unlink(self._own_store_path)
            except OSError:
                pass
            self._own_store_path = None

    # ---- crash detection + request migration -------------------------

    def _wrap_heartbeat(self, i: int, publisher):
        """Replica ``i``'s heartbeat callable, with the
        ``replica_stall`` injection spliced in: at fabric step
        ``plan.step`` the victim hangs at the ``prefill`` phase
        boundary — it beat at ``admit``, then went silent mid-step,
        before any token sampled.  Everything already admitted/prefilled
        is the partial-step work the migration must reconcile."""
        def beat(phase: str) -> None:
            p = self.fault_plan
            if (p is not None and p.fault == "replica_stall"
                    and i == p.expert % self.n_replicas
                    and self.step_idx == p.step
                    and phase == "prefill"
                    and i not in self._stalled):
                raise _ReplicaStallInjected(
                    f"chaos: replica r{i} hung mid-step "
                    f"{self.step_idx} at phase {phase!r}")
            publisher(phase)
        return beat

    def _probe_fn(self, i: int):
        """Health probe for replica ``i`` as the router sees it: a
        killed replica RAISES (dead process, no snapshot)."""
        def probe() -> dict:
            if i in self._killed:
                raise RuntimeError(f"replica r{i} is dead")
            return self.engines[i]._health_snapshot()
        return probe

    def kill_replica(self, replica: int) -> None:
        """Kill replica ``replica`` SILENTLY — nothing is announced;
        the fabric's own health probes must detect the death at the
        top of the next step and migrate the victims.  (The chaos
        ``replica_crash`` drill calls this through ``fault_plan``.)"""
        r = int(replica)
        if not 0 <= r < self.n_replicas:
            raise ValueError(f"replica {r} out of range "
                             f"[0, {self.n_replicas})")
        if len(self._killed) + 1 >= self.n_replicas:
            raise RuntimeError(
                "refusing to kill the last live replica — there would "
                "be nowhere to migrate its requests")
        self._killed.add(r)

    def _maybe_inject_crash(self) -> None:
        p = self.fault_plan
        if p is None or p.fault != "replica_crash":
            return
        target = p.expert % self.n_replicas
        if self.step_idx == p.step and target not in self._killed:
            self.kill_replica(target)

    def _detect_crashes(self) -> None:
        """Probe every not-yet-evacuated replica; a raising probe is a
        detected death -> evacuate + migrate."""
        for i in range(self.n_replicas):
            if i in self._crashed:
                continue
            try:
                self.router.health_fns[i]()
            except Exception:
                self._on_replica_death(i)

    def _on_replica_death(self, dead: int) -> None:
        """One replica's death, end to end: pull it from the rotation,
        evacuate its work through the PR 10 eviction path (resumed
        prompts carry every delivered token; trace spans close), and
        re-route every victim onto the survivors — in-flight requests
        resume at the head of their new queue, still in admission
        order, so the deterministic re-prefill replays bit-equal."""
        self.router.mark_failed(dead)
        engine = self.engines[dead]
        inflight, queued = engine.evacuate()
        self._crashed.add(dead)
        self.metrics.count("fabric.replica_crashes")
        self.metrics.decision(
            "fabric.replica_crash", replica=dead, step=self.step_idx,
            in_flight=len(inflight), queued=len(queued),
            survivors=[i for i in range(self.n_replicas)
                       if i not in self._crashed and
                       i not in self._killed])
        front: dict[int, list] = {}
        for entry in inflight:            # admission order
            choice = self.router.route(entry.orig.rid)
            front.setdefault(choice, []).append(entry)
            self._emit_migrate(entry, dead, choice, resumed=True)
        for choice, entries in front.items():
            # adopt(front=True) prepends, so reversed() lands the
            # oldest-admitted request back at the very head
            for entry in reversed(entries):
                self.engines[choice].adopt(entry, front=True)
        for entry in queued:
            choice = self.router.route(entry.orig.rid)
            self.engines[choice].adopt(entry)
            self._emit_migrate(entry, dead, choice, resumed=False)

    def _emit_migrate(self, entry, dead: int, choice: int, *,
                      resumed: bool) -> None:
        self._placement[entry.orig.rid] = choice
        self.migrated += 1
        self.metrics.count("fabric.migrations")
        self.metrics.decision(
            "fabric.migrate", rid=entry.orig.rid, from_replica=dead,
            to_replica=choice, resumed=resumed,
            delivered=(len(entry.req.prompt)
                       - len(entry.orig.prompt)),
            remaining=entry.req.max_new_tokens)

    def _observe_heartbeats(self) -> None:
        """One watchdog sweep after the replicas stepped: a replica
        with pending work that advanced no heartbeat seq takes a miss;
        at the deadline it is declared stalled and takes the same
        fence+evacuate+adopt path a detected crash does — the sub-step
        arm of the recovery ladder."""
        if self.hb_watchdog is None:
            return
        live = [i for i in range(self.n_replicas)
                if i not in self._killed and i not in self._crashed]
        newly = self.hb_watchdog.observe(
            self.step_idx, live,
            pending=lambda r: self.engines[r].pending())
        for victim in newly:
            if self.vclock is not None:
                # evacuate on the victim's own (frozen) lane so the
                # eviction instants continue from where it hung and
                # the resumed-gap spans stay contiguous
                self.vclock.use_lane(victim)
            self._on_replica_death(victim)

    # ---- submission / drive ------------------------------------------

    def submit(self, req, arrival_step: int = 0, *,
               session=None) -> int:
        """Route ``req`` to a replica (JSQ + affinity) and enqueue it
        there.  Returns the chosen replica id."""
        choice = self.router.route(req.rid, session=session)
        self.engines[choice].submit(req, arrival_step)
        self._placement[req.rid] = choice
        return choice

    def pending(self) -> bool:
        return any(e.pending() for e in self.engines)

    def _spec_break_even(self):
        """Planner break-even acceptance for the fleet's verify depth,
        priced once and cached (the shape never changes mid-run).  None
        when the planner has no feasible decode path for this config —
        the controller then falls back to its configured floor."""
        if self._spec_be is _SPEC_BE_UNSET:
            try:
                from flashmoe_tpu.planner.model import \
                    speculate_break_even
                self._spec_be = speculate_break_even(
                    self.cfg,
                    verify_tokens=self.serve.speculate.draft_tokens)
            except Exception:
                self._spec_be = None
        return self._spec_be

    def _observe_spec(self) -> None:
        """Feed the controller the fleet's INSTANTANEOUS draft
        acceptance (this step's delta across replicas, not the
        cumulative rate — a run that started well must still morph when
        traffic turns adversarial) and execute a morph-off verdict on
        EVERY replica at once: a per-replica split would fork the
        measurement identity the planner's spec pricing assumes."""
        drafted = accepted = 0
        spec_on = False
        for e in self.engines:
            snap = e.spec_snapshot()
            drafted += snap["spec_drafted"]
            accepted += snap["spec_accepted"]
            spec_on = spec_on or snap["spec_on"]
        d = drafted - self._spec_prev[0]
        a = accepted - self._spec_prev[1]
        self._spec_prev = (drafted, accepted)
        self.controller.observe_spec(
            self.step_idx, (a / d) if d > 0 else None,
            break_even=self._spec_break_even())
        act = self.controller.maybe_morph_spec(
            self.step_idx, spec_on=spec_on)
        if act is not None:
            for e in self.engines:
                if e._spec is not None:
                    e.set_speculate(False, reason=act.reason)

    def step(self) -> dict:
        """One fabric iteration: inject/detect crashes, then every live
        replica with pending work steps once (decode steps overlap the
        handoff prefills its admissions triggered), then the controller
        observes queue pressure and may morph the rotation."""
        self._maybe_inject_crash()
        self._detect_crashes()
        recs = []
        for i, e in enumerate(self.engines):
            if i in self._killed or i in self._stalled:
                continue
            if e.pending():
                if self.vclock is not None:
                    # replica-local virtual time: the real fleet steps
                    # replicas in parallel, so each gets its own lane
                    self.vclock.use_lane(i)
                try:
                    recs.append(e.step())
                except _ReplicaStallInjected:
                    # the replica hung mid-step: its thread never
                    # returns, so it never steps again — and nothing
                    # announces it (the probe still answers).  Close
                    # its open step window at the hang instant so the
                    # trace authority's tracks stay contiguous; the
                    # WORK stays parked on the hung replica until the
                    # heartbeat deadline notices.
                    self._stalled.add(i)
                    if e.tracer is not None:
                        e.tracer.end_step()
        self._observe_heartbeats()
        self.step_idx += 1
        if self.controller is not None:
            depths = [e._health_snapshot() for e in self.engines]
            self.controller.observe_fabric(
                self.step_idx,
                [d["queue_depth"] + d["active_requests"]
                 for d in depths])
            act = self.controller.maybe_morph_replicas(
                self.step_idx, draining=self.router.draining())
            if act is not None:
                if act.kind == "drain":
                    self.router.drain(act.replica)
                else:
                    self.router.undrain(act.replica)
            if self.serve.speculate is not None:
                self._observe_spec()
        return {"kind": "fabric_step", "step": self.step_idx,
                "replica_steps": len(recs),
                "queue_depth": sum(len(e.queue) for e in self.engines),
                "active": sum(len(e._active()) for e in self.engines)}

    def run(self, requests=None, arrivals=None, *, sessions=None,
            until=None) -> dict:
        """Drive to completion; the fabric twin of
        :meth:`ServingEngine.run`.  ``sessions``: optional per-request
        affinity keys (parallel to ``requests``).  Returns the merged
        ``{rid: tokens}`` across replicas."""
        for idx, req in enumerate(requests or ()):
            self.submit(req,
                        int(arrivals[idx]) if arrivals else 0,
                        session=sessions[idx] if sessions else None)
        while self.pending() and not (until is not None and until()):
            if self.step_idx >= self.serve.max_steps:
                raise RuntimeError(
                    f"fabric exceeded max_steps={self.serve.max_steps} "
                    f"with work pending")
            self.step()
        out: dict = {}
        for e in self.engines:
            out.update(e.outputs)
        return out

    def summary(self) -> dict:
        """Merged drill summary: per-replica engine summaries plus the
        fabric's own counters."""
        out = {
            "replicas": self.n_replicas,
            "steps": self.step_idx,
            "handoffs": self.handoff.count,
            "handoff_bytes": self.handoff.bytes_moved,
            "routed": list(self.router.routed),
            "placement": dict(self._placement),
            "crashed": sorted(self._crashed),
            "stalled": sorted(self._stalled),
            "migrated": self.migrated,
            "engines": [e.summary() for e in self.engines],
        }
        if self.serve.speculate is not None:
            drafted = sum(e.spec_snapshot()["spec_drafted"]
                          for e in self.engines)
            accepted = sum(e.spec_snapshot()["spec_accepted"]
                           for e in self.engines)
            out["spec"] = {
                "spec_drafted": drafted,
                "spec_accepted": accepted,
                "accept_rate": (round(accepted / drafted, 6)
                                if drafted else 0.0),
                "spec_on": [bool(e._spec is not None)
                            for e in self.engines],
            }
        if self.hb_watchdog is not None:
            out["heartbeat"] = self.hb_watchdog.snapshot()
        if self.vclock is not None:
            out["handoff_ms_measured"] = round(
                self.handoff.measured_ms_total, 6)
            out["handoff_hidden_frac"] = (
                round(self.handoff.hidden_ms_total
                      / self.handoff.measured_ms_total, 6)
                if self.handoff.measured_ms_total > 0 else None)
            out["handoff_verdicts_agree"] = self.handoff.drift_agree
            out["handoff_verdicts_total"] = self.handoff.drift_total
        return out
