"""The fabric's single front door: one trace/session authority.

Before this module, every fabric client split the request/trace
namespace per replica UP FRONT (``loadgen.split_requests``) — each
engine traced its own shard and nobody owned the request's identity
across the prefill pool, the router, an eviction, or a drain-spill.
:class:`FrontDoor` closes ROADMAP item 1(c): it wraps a
:class:`~flashmoe_tpu.fabric.engine.ServingFabric` with

* **one** shared :class:`~flashmoe_tpu.telemetry_plane.tracing.
  RequestTracer` installed across every replica (they step
  sequentially on one host thread, so a single listener is race-free)
  on the fabric's clock (the
  :class:`~flashmoe_tpu.fabric.vclock.VirtualClock` when armed, wall
  otherwise) — a request's spans land on ONE track no matter which
  pools it crossed;
* **namespace ownership** — a rid submits through the front door at
  most once (a duplicate raises), and every submit is recorded as a
  ``frontdoor.submit`` decision carrying the router's placement;
* **the fleet export** — :meth:`export_fleet_trace` writes ONE
  ``validate_trace``-gated Perfetto document with a process track per
  pool and flow arrows linking each request's prefill-pool span to
  its decode-pool resume
  (:func:`~flashmoe_tpu.profiler.export.fleet_trace_document`);
* **attribution** — :meth:`attribution` decomposes every retired
  request's measured latency into critical-path components
  (:mod:`flashmoe_tpu.telemetry_plane.attribution`), feeding the
  per-component ``/metrics`` sketches.
"""

from __future__ import annotations

import time

from flashmoe_tpu.telemetry_plane.tracing import RequestTracer


class FrontDoor:
    """Trace/session authority over one fabric.  Construct AFTER the
    fabric (it arms the shared tracer on the fabric's replicas); call
    :meth:`close` (or close the fabric) when done so the span listener
    uninstalls."""

    def __init__(self, fabric, *, metrics_obj=None):
        self.fabric = fabric
        self.metrics = (metrics_obj if metrics_obj is not None
                        else fabric.metrics)
        clock = (fabric.vclock if fabric.vclock is not None
                 else time.monotonic)
        self.tracer = RequestTracer(metrics_obj=self.metrics,
                                    clock=clock)
        self.tracer.install()
        for e in fabric.engines:
            e.tracer = self.tracer
        self._seen: set = set()
        self.sessions: dict = {}

    # ---- namespace ----------------------------------------------------

    def submit(self, req, arrival_step: int = 0, *,
               session=None) -> int:
        """Submit one request through the front door: route it, record
        the placement, own its rid.  Returns the chosen replica."""
        if req.rid in self._seen:
            raise ValueError(
                f"rid {req.rid} already submitted through this front "
                f"door — the trace namespace is owned here, not split "
                f"per replica")
        self._seen.add(req.rid)
        choice = self.fabric.submit(req, arrival_step, session=session)
        if session is not None:
            self.sessions.setdefault(session, []).append(req.rid)
        self.metrics.count("frontdoor.submits")
        self.metrics.decision(
            "frontdoor.submit", rid=req.rid, session=session,
            replica=int(choice), arrival_step=int(arrival_step),
            submitted=len(self._seen))
        return choice

    def run(self, requests=None, arrivals=None, *, sessions=None,
            until=None) -> dict:
        """Submit ``requests`` through the front door and drive the
        fabric to completion (the :meth:`ServingFabric.run` twin)."""
        for idx, req in enumerate(requests or ()):
            self.submit(req,
                        int(arrivals[idx]) if arrivals else 0,
                        session=sessions[idx] if sessions else None)
        return self.fabric.run(until=until)

    # ---- trace views --------------------------------------------------

    def validate(self) -> list[str]:
        """The tracer's no-orphan / contiguity gate over the WHOLE
        fleet's requests (empty = clean)."""
        return self.tracer.validate()

    def fleet_trace_document(self) -> dict:
        from flashmoe_tpu.profiler.export import fleet_trace_document

        return fleet_trace_document(self.tracer, self.fabric._placement,
                                    replicas=self.fabric.n_replicas)

    def export_fleet_trace(self, path: str) -> dict:
        from flashmoe_tpu.profiler.export import write_fleet_trace

        return write_fleet_trace(self.tracer, self.fabric._placement,
                                 path, replicas=self.fabric.n_replicas)

    def export_jsonl(self, path: str) -> int:
        """The fleet's ``serve_trace_span`` records (one shard — the
        front door owns the namespace, so there is nothing to merge)."""
        return self.tracer.export_jsonl(path)

    # ---- attribution --------------------------------------------------

    def attribution(self, *, feed_metrics: bool = True) -> dict:
        """Per-request critical-path attribution for every retired
        request (``{rid: {components, dominant, sum_ok, ...}}``),
        spill-aware via the router's ``fabric.route`` decisions.  With
        ``feed_metrics`` (default) the per-component sketches land on
        the fabric's metrics object and each request emits a
        ``serve.attribution`` decision."""
        from flashmoe_tpu.telemetry_plane.attribution import (
            attribute_tracer, spilled_rids,
        )

        spilled = spilled_rids(
            r for r in self.metrics.decisions
            if r.get("decision") == "fabric.route")
        return attribute_tracer(
            self.tracer, spilled=spilled,
            metrics_obj=self.metrics if feed_metrics else None)

    def close(self) -> None:
        self.tracer.uninstall()
        for e in self.fabric.engines:
            if e.tracer is self.tracer:
                e.tracer = None
