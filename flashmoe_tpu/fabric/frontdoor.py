"""The fabric's single front door: one trace/session authority.

Before this module, every fabric client split the request/trace
namespace per replica UP FRONT (``loadgen.split_requests``) — each
engine traced its own shard and nobody owned the request's identity
across the prefill pool, the router, an eviction, or a drain-spill.
:class:`FrontDoor` closes ROADMAP item 1(c): it wraps a
:class:`~flashmoe_tpu.fabric.engine.ServingFabric` with

* **one** shared :class:`~flashmoe_tpu.telemetry_plane.tracing.
  RequestTracer` installed across every replica (they step
  sequentially on one host thread, so a single listener is race-free)
  on the fabric's clock (the
  :class:`~flashmoe_tpu.fabric.vclock.VirtualClock` when armed, wall
  otherwise) — a request's spans land on ONE track no matter which
  pools it crossed;
* **namespace ownership** — a rid submits through the front door at
  most once (a duplicate raises), and every submit is recorded as a
  ``frontdoor.submit`` decision carrying the router's placement;
* **the fleet export** — :meth:`export_fleet_trace` writes ONE
  ``validate_trace``-gated Perfetto document with a process track per
  pool and flow arrows linking each request's prefill-pool span to
  its decode-pool resume
  (:func:`~flashmoe_tpu.profiler.export.fleet_trace_document`);
* **attribution** — :meth:`attribution` decomposes every retired
  request's measured latency into critical-path components
  (:mod:`flashmoe_tpu.telemetry_plane.attribution`), feeding the
  per-component ``/metrics`` sketches.
"""

from __future__ import annotations

import json
import os
import time

from flashmoe_tpu.telemetry_plane.tracing import RequestTracer


class FrontDoor:
    """Trace/session authority over one fabric.  Construct AFTER the
    fabric (it arms the shared tracer on the fabric's replicas); call
    :meth:`close` (or close the fabric) when done so the span listener
    uninstalls."""

    def __init__(self, fabric, *, metrics_obj=None, brownout=None,
                 tracer=None, seen=None, peer=None):
        """``brownout``: a :class:`~flashmoe_tpu.runtime.controller.
        BrownoutConfig` arming hysteretic admission shedding — while a
        brownout episode is active, :meth:`submit` sheds (or degrades)
        new requests instead of feeding an overloaded fleet.
        ``tracer`` / ``seen`` / ``peer``: the
        :class:`FrontDoorCluster` seams — peers of a replicated door
        share ONE tracer and ONE rid namespace, each tagging its
        submits with its ``peer`` id; a standalone door (defaults)
        owns both."""
        self.fabric = fabric
        self.metrics = (metrics_obj if metrics_obj is not None
                        else fabric.metrics)
        self.peer = peer
        self._owns_tracer = tracer is None
        if tracer is None:
            clock = (fabric.vclock if fabric.vclock is not None
                     else time.monotonic)
            tracer = RequestTracer(metrics_obj=self.metrics,
                                   clock=clock)
            tracer.install()
        self.tracer = tracer
        for e in fabric.engines:
            e.tracer = self.tracer
        self._seen: set = seen if seen is not None else set()
        self.sessions: dict = {}
        # ---- brownout state (PR 9 discipline: debounce / cooldown /
        # budget around a hysteresis band) ----
        self.brownout = brownout
        self._bo_active = False
        self._bo_breach = 0
        self._bo_clear = 0
        self._bo_cooldown_until = -1
        self._bo_episodes = 0
        self._bo_last_retries = 0
        self.shed_rids: list = []
        self.degraded_rids: list = []

    # ---- namespace ----------------------------------------------------

    def submit(self, req, arrival_step: int = 0, *,
               session=None) -> int | None:
        """Submit one request through the front door: route it, record
        the placement, own its rid.  Returns the chosen replica — or
        ``None`` when an active brownout SHED the request (it never
        enters the fabric; the rid stays owned so a retry under the
        same rid still raises)."""
        if req.rid in self._seen:
            raise ValueError(
                f"rid {req.rid} already submitted through this front "
                f"door — the trace namespace is owned here, not split "
                f"per replica")
        self._seen.add(req.rid)
        if self._bo_active:
            bo = self.brownout
            depth = self._fleet_depth()
            if bo.mode == "shed":
                self.shed_rids.append(req.rid)
                self.metrics.count("frontdoor.sheds")
                self.metrics.decision(
                    "frontdoor.shed", rid=req.rid, peer=self.peer,
                    mode="reject", step=self.fabric.step_idx,
                    queue_depth=round(depth, 3),
                    episode=self._bo_episodes)
                return None
            capped = min(req.max_new_tokens, bo.degrade_max_new)
            if capped < req.max_new_tokens:
                import dataclasses as _dc

                req = _dc.replace(req, max_new_tokens=capped)
                self.degraded_rids.append(req.rid)
                self.metrics.count("frontdoor.degraded")
                self.metrics.decision(
                    "frontdoor.shed", rid=req.rid, peer=self.peer,
                    mode="degrade", step=self.fabric.step_idx,
                    queue_depth=round(depth, 3),
                    max_new_tokens=capped,
                    episode=self._bo_episodes)
        choice = self.fabric.submit(req, arrival_step, session=session)
        if session is not None:
            self.sessions.setdefault(session, []).append(req.rid)
        self.metrics.count("frontdoor.submits")
        self.metrics.decision(
            "frontdoor.submit", rid=req.rid, session=session,
            replica=int(choice), arrival_step=int(arrival_step),
            peer=self.peer, submitted=len(self._seen))
        return choice

    # ---- brownout (hysteretic admission control) ----------------------

    def _fleet_depth(self) -> float:
        """Mean (queue + active) depth per LIVE replica — crashed
        replicas neither hold work nor count toward capacity."""
        fab = self.fabric
        live = [e for i, e in enumerate(fab.engines)
                if i not in fab._killed and i not in fab._crashed]
        if not live:
            return 0.0
        return sum(len(e.queue) + len(e._active()) for e in live) \
            / len(live)

    def _retry_pressure(self) -> int:
        """Handoff-transport retries since the previous observation."""
        transport = getattr(self.fabric.handoff, "transport", None)
        if transport is None:
            return 0
        now = transport.retries_total
        delta = now - self._bo_last_retries
        self._bo_last_retries = now
        return delta

    def observe_brownout(self, step: int) -> None:
        """One admission-control observation (call once per fabric
        step; :meth:`run` does).  Enter/exit transitions are
        ``frontdoor.brownout`` decisions; both directions are debounced
        and entries respect the cooldown and the episode budget."""
        bo = self.brownout
        if bo is None:
            return
        depth = self._fleet_depth()
        retries = self._retry_pressure()
        breach = depth > bo.queue_high or retries >= bo.retry_high
        if self._bo_active:
            calm = depth < bo.queue_low and retries == 0
            self._bo_clear = self._bo_clear + 1 if calm else 0
            if self._bo_clear >= bo.debounce_steps:
                self._bo_active = False
                self._bo_clear = 0
                self._bo_cooldown_until = step + bo.cooldown_steps
                self.metrics.decision(
                    "frontdoor.brownout", state="exit", step=step,
                    peer=self.peer, queue_depth=round(depth, 3),
                    retries=retries, episode=self._bo_episodes,
                    cooldown_until=self._bo_cooldown_until)
            return
        in_cooldown = step < self._bo_cooldown_until
        budget_left = self._bo_episodes < bo.episode_budget
        self._bo_breach = (self._bo_breach + 1
                           if breach and not in_cooldown and budget_left
                           else 0)
        if self._bo_breach >= bo.debounce_steps:
            self._bo_active = True
            self._bo_breach = 0
            self._bo_episodes += 1
            self.metrics.count("frontdoor.brownouts")
            self.metrics.decision(
                "frontdoor.brownout", state="enter", step=step,
                peer=self.peer, queue_depth=round(depth, 3),
                retries=retries, mode=bo.mode,
                episode=self._bo_episodes,
                budget_left=bo.episode_budget - self._bo_episodes)

    def run(self, requests=None, arrivals=None, *, sessions=None,
            until=None) -> dict:
        """Submit ``requests`` through the front door and drive the
        fabric to completion (the :meth:`ServingFabric.run` twin).

        With :attr:`brownout` armed the drive is STAGED: each request
        submits only when the fabric reaches its arrival step, so the
        admission verdict sees the queue pressure that actually exists
        at arrival time (an upfront bulk submit would let every request
        through before the first observation)."""
        if self.brownout is None:
            for idx, req in enumerate(requests or ()):
                self.submit(req,
                            int(arrivals[idx]) if arrivals else 0,
                            session=sessions[idx] if sessions else None)
            return self.fabric.run(until=until)
        waiting = [(int(arrivals[idx]) if arrivals else 0, req,
                    sessions[idx] if sessions else None)
                   for idx, req in enumerate(requests or ())]
        i = 0
        while i < len(waiting) or self.fabric.pending():
            if until is not None and until():
                break
            step = self.fabric.step_idx
            while i < len(waiting) and waiting[i][0] <= step:
                arrival, req, session = waiting[i]
                self.submit(req, arrival, session=session)
                i += 1
            if step >= self.fabric.serve.max_steps:
                raise RuntimeError(
                    f"fabric exceeded max_steps="
                    f"{self.fabric.serve.max_steps} with work pending")
            self.fabric.step()
            self.observe_brownout(self.fabric.step_idx)
        out: dict = {}
        for e in self.fabric.engines:
            out.update(e.outputs)
        return out

    # ---- trace views --------------------------------------------------

    def validate(self) -> list[str]:
        """The tracer's no-orphan / contiguity gate over the WHOLE
        fleet's requests (empty = clean)."""
        return self.tracer.validate()

    def fleet_trace_document(self) -> dict:
        from flashmoe_tpu.profiler.export import fleet_trace_document

        return fleet_trace_document(self.tracer, self.fabric._placement,
                                    replicas=self.fabric.n_replicas)

    def export_fleet_trace(self, path: str) -> dict:
        from flashmoe_tpu.profiler.export import write_fleet_trace

        return write_fleet_trace(self.tracer, self.fabric._placement,
                                 path, replicas=self.fabric.n_replicas)

    def export_jsonl(self, path: str) -> int:
        """The fleet's ``serve_trace_span`` records (one shard — the
        front door owns the namespace, so there is nothing to merge)."""
        return self.tracer.export_jsonl(path)

    # ---- attribution --------------------------------------------------

    def attribution(self, *, feed_metrics: bool = True) -> dict:
        """Per-request critical-path attribution for every retired
        request (``{rid: {components, dominant, sum_ok, ...}}``),
        spill-aware via the router's ``fabric.route`` decisions.  With
        ``feed_metrics`` (default) the per-component sketches land on
        the fabric's metrics object and each request emits a
        ``serve.attribution`` decision."""
        from flashmoe_tpu.telemetry_plane.attribution import (
            attribute_tracer, spilled_rids,
        )

        spilled = spilled_rids(
            r for r in self.metrics.decisions
            if r.get("decision") == "fabric.route")
        return attribute_tracer(
            self.tracer, spilled=spilled,
            metrics_obj=self.metrics if feed_metrics else None)

    def brownout_snapshot(self) -> dict:
        """Live view of the admission controller."""
        return {
            "armed": self.brownout is not None,
            "active": self._bo_active,
            "episodes": self._bo_episodes,
            "shed": len(self.shed_rids),
            "degraded": len(self.degraded_rids),
        }

    def close(self) -> None:
        if not self._owns_tracer:
            return                      # the cluster owns the listener
        self.tracer.uninstall()
        for e in self.fabric.engines:
            if e.tracer is self.tracer:
                e.tracer = None


class FrontDoorCluster:
    """N replicated front-door peers over one fabric: the door itself
    is no longer a single process (ROADMAP item 1(d)).

    Ownership is **leased by namespace shard**: a request's rid (or
    session key) crc32-hashes to one of ``n_shards`` shards, and each
    shard's lease names the PEER that owns submissions for it plus an
    **epoch** number.  All peers share ONE
    :class:`~flashmoe_tpu.telemetry_plane.tracing.RequestTracer` and
    ONE rid namespace (the trace authority is the cluster, not a
    peer), so when :meth:`fail_door` kills a peer its shards fail over
    to the survivors — epochs bump, a ``frontdoor.failover`` decision
    per shard — and the post-failover fleet Perfetto document still
    validates with zero orphan spans: no request's identity was split
    across the transition.

    With ``store`` (a :class:`~flashmoe_tpu.fabric.leasestore.
    LeaseStore`) the lease table lives OUTSIDE the process: every
    owner read and every failover write goes through the fcntl-locked,
    CRC-framed, epoch-fenced file — peers in separate OS processes
    share it, a failover's epoch bumps fence off any zombie peer
    re-asserting its old leases, and a writer killed mid-append is
    rolled back to the last intact record.  ``store=None`` (default)
    keeps the in-memory table, byte-identical to the PR 18 cluster."""

    def __init__(self, fabric, n_doors: int = 2, *,
                 n_shards: int = 8, metrics_obj=None, store=None):
        if n_doors < 1:
            raise ValueError(f"cluster needs >= 1 door, got {n_doors}")
        if n_shards < n_doors:
            raise ValueError(
                f"n_shards ({n_shards}) must be >= n_doors "
                f"({n_doors}) so every peer owns a lease")
        self.fabric = fabric
        self.metrics = (metrics_obj if metrics_obj is not None
                        else fabric.metrics)
        clock = (fabric.vclock if fabric.vclock is not None
                 else time.monotonic)
        self.tracer = RequestTracer(metrics_obj=self.metrics,
                                    clock=clock)
        self.tracer.install()
        self._seen: set = set()
        self.doors = [
            FrontDoor(fabric, metrics_obj=self.metrics,
                      tracer=self.tracer, seen=self._seen, peer=i)
            for i in range(n_doors)
        ]
        self.n_shards = int(n_shards)
        self.store = store
        #: shard -> {"owner": peer id, "epoch": lease generation} (the
        #: in-memory table; with ``store`` the external file is the
        #: authority and this dict is unused)
        self.leases = {s: {"owner": s % n_doors, "epoch": 0}
                       for s in range(self.n_shards)}
        if store is not None:
            # only missing shards are seeded: a peer joining an
            # existing store adopts the live table, never resets it
            store.init_leases({s: s % n_doors
                               for s in range(self.n_shards)})
        self._dead: set = set()

    @property
    def n_doors(self) -> int:
        return len(self.doors)

    def shard_of(self, rid, session=None) -> int:
        import zlib

        key = session if session is not None else rid
        return zlib.crc32(str(key).encode()) % self.n_shards

    def _lease_table(self) -> dict:
        """The live lease table: the external store's last intact
        state when one is attached, else the in-memory dict."""
        if self.store is not None:
            return {s: {"owner": ls.owner, "epoch": ls.epoch}
                    for s, ls in self.store.leases().items()}
        return self.leases

    def owner_of(self, rid, session=None) -> int:
        return self._lease_table()[self.shard_of(rid, session)]["owner"]

    def submit(self, req, arrival_step: int = 0, *,
               session=None) -> int | None:
        """Submit through the peer whose lease owns the request's
        namespace shard."""
        owner = self.owner_of(req.rid, session)
        if owner in self._dead:
            raise RuntimeError(
                f"lease for shard {self.shard_of(req.rid, session)} "
                f"names dead peer {owner} — failover did not run")
        return self.doors[owner].submit(req, arrival_step,
                                        session=session)

    def fail_door(self, peer: int) -> int:
        """Kill peer ``peer``: every lease it held fails over to a
        survivor (crc32-deterministic choice, epoch bumped).  Returns
        the number of shards that moved."""
        p = int(peer)
        if not 0 <= p < self.n_doors:
            raise ValueError(f"peer {p} out of range "
                             f"[0, {self.n_doors})")
        if p in self._dead:
            return 0
        survivors = [i for i in range(self.n_doors)
                     if i not in self._dead and i != p]
        if not survivors:
            raise RuntimeError(
                "refusing to kill the last live front-door peer — "
                "the namespace would have no owner")
        self._dead.add(p)
        moved = 0
        table = self._lease_table()
        for shard in sorted(table):
            lease = table[shard]
            if lease["owner"] != p:
                continue
            new = survivors[shard % len(survivors)]
            epoch = lease["epoch"] + 1
            if self.store is not None:
                from flashmoe_tpu.fabric.leasestore import \
                    StaleLeaseError

                try:
                    self.store.write_lease(shard, new, epoch,
                                           reason="failover")
                except StaleLeaseError:
                    # a racing peer already moved this shard at a
                    # newer epoch — its failover stands, not ours
                    continue
            else:
                self.leases[shard]["owner"] = new
                self.leases[shard]["epoch"] = epoch
            moved += 1
            self.metrics.count("frontdoor.failovers")
            self.metrics.decision(
                "frontdoor.failover", shard=shard, from_peer=p,
                to_peer=new, epoch=epoch,
                survivors=list(survivors))
        return moved

    def run(self, requests=None, arrivals=None, *, sessions=None,
            fail_at=None, fail_peer: int = 0, until=None) -> dict:
        """Drive the fleet through the cluster, optionally killing
        peer ``fail_peer`` when the fabric reaches step ``fail_at`` —
        requests arriving after the failover submit through the new
        lease owners, on the SAME shared tracer/namespace."""
        waiting = [(int(arrivals[idx]) if arrivals else 0, req,
                    sessions[idx] if sessions else None)
                   for idx, req in enumerate(requests or ())]
        i = 0
        failed = False
        while i < len(waiting) or self.fabric.pending():
            if until is not None and until():
                break
            step = self.fabric.step_idx
            if fail_at is not None and not failed and step >= fail_at:
                self.fail_door(fail_peer)
                failed = True
            while i < len(waiting) and waiting[i][0] <= step:
                arrival, req, session = waiting[i]
                self.submit(req, arrival, session=session)
                i += 1
            if step >= self.fabric.serve.max_steps:
                raise RuntimeError(
                    f"fabric exceeded max_steps="
                    f"{self.fabric.serve.max_steps} with work pending")
            self.fabric.step()
        out: dict = {}
        for e in self.fabric.engines:
            out.update(e.outputs)
        return out

    # ---- trace views (the CLUSTER is the authority) -------------------

    def validate(self) -> list[str]:
        return self.tracer.validate()

    def fleet_trace_document(self) -> dict:
        from flashmoe_tpu.profiler.export import fleet_trace_document

        return fleet_trace_document(self.tracer, self.fabric._placement,
                                    replicas=self.fabric.n_replicas)

    def export_fleet_trace(self, path: str) -> dict:
        from flashmoe_tpu.profiler.export import write_fleet_trace

        return write_fleet_trace(self.tracer, self.fabric._placement,
                                 path, replicas=self.fabric.n_replicas)

    def export_door_shards(self, dirpath: str) -> dict:
        """Write one telemetry shard per LIVE door
        (``telemetry.door<i>.jsonl``): the trace records it is an
        authority for plus every decision it witnessed.  In a
        cross-process deployment each door writes its own shard;
        ``observe --merge`` re-joins them into one fleet view, deduping
        double-witnessed records — the externalized trace-authority
        story (zero orphan spans after the merge)."""
        recs = [*self.tracer.records(),
                *(dict(d) for d in self.metrics.decisions)]
        out = {}
        for i in range(self.n_doors):
            if i in self._dead:
                continue
            path = os.path.join(dirpath, f"telemetry.door{i}.jsonl")
            with open(path, "w") as fh:
                for r in recs:
                    fh.write(json.dumps(r, default=str) + "\n")
            out[f"door{i}"] = {"path": path, "records": len(recs)}
        return out

    def snapshot(self) -> dict:
        """Live ``/vars`` view of the lease table."""
        table = self._lease_table()
        return {
            "doors": self.n_doors,
            "dead": sorted(self._dead),
            "shards": self.n_shards,
            "external_store": (self.store.path
                               if self.store is not None else None),
            "leases": {s: dict(v) for s, v in table.items()},
            "max_epoch": max(v["epoch"] for v in table.values()),
        }

    def close(self) -> None:
        self.tracer.uninstall()
        for e in self.fabric.engines:
            if e.tracer is self.tracer:
                e.tracer = None
