"""KV-page handoff: prefill-pool -> decode-pool page streaming.

In a disaggregated fabric the prefill pool computes a prompt's KV run
and the decode pool owns the paged cache the tokens decode against —
the run crosses DCN as whole pages.  This module is that boundary:

* **codec** — :func:`encode_kv_run` / :func:`decode_kv_run` reuse the
  PR 12 per-hop wire codec (:mod:`flashmoe_tpu.ops.wire`) over page
  payloads: each (layer, page) block quantizes as ONE wire row, so the
  f32 scales ride a ``_qscale`` sidecar with one entry per page (the
  PR 14 expert-store convention applied to KV).  ``wire=None`` is the
  exact path — arrays pass through untouched, which is what makes the
  fabric acceptance drill bit-equal to the single-pool engine;
* **pricing** — every handoff is priced through
  :func:`flashmoe_tpu.planner.model.kv_handoff_ms` (page bytes at the
  wire row size over the ``_DCN_SPEC`` alpha/beta) and recorded as a
  ``fabric.handoff`` decision carrying the modeled DCN cost and
  whether it hides under the decode pool's per-step objective
  (Comet-grained transfer/compute overlap, arXiv 2502.19811);
* **streamer** — :class:`KVHandoff` is the engine-facing seam: it is
  the ``prefill_fn`` a decode replica's
  :class:`~flashmoe_tpu.serving.engine.ServingEngine` calls at
  admission, so the prefill compute runs "in the prefill pool" (the
  same module-level jit — bit-identical math) and only pages cross.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from flashmoe_tpu.config import MoEConfig
from flashmoe_tpu.ops import wire as wr
from flashmoe_tpu.utils.telemetry import metrics as _global_metrics
from flashmoe_tpu.utils.telemetry import trace_span


@dataclasses.dataclass(frozen=True)
class KVPagePayload:
    """One prefill run's wire form: K/V page payloads plus the per-page
    f32 ``_qscale`` sidecars (``None`` on exact/plain-cast wires).
    ``shape`` is the dense ``[L, N_kv, T, D]`` the decode side
    restores."""

    k: jax.Array
    v: jax.Array
    k_qscale: jax.Array | None
    v_qscale: jax.Array | None
    shape: tuple
    page_size: int
    wire: str                      # canonical name, 'off' = exact

    @property
    def pages(self) -> int:
        l, _, t, _ = self.shape
        return t // self.page_size

    @property
    def payload_bytes(self) -> int:
        n = int(self.k.nbytes) + int(self.v.nbytes)
        for s in (self.k_qscale, self.v_qscale):
            if s is not None:
                n += int(s.nbytes)
        return n


def _page_rows(seq_kv, page_size: int):
    """[L, N_kv, T, D] -> [L * n_pages, N_kv * page * D]: one wire row
    per (layer, page), the granularity the ``_qscale`` sidecar keys."""
    l, nkv, t, d = seq_kv.shape
    if t % page_size:
        raise ValueError(f"KV run of {t} positions does not fill whole "
                         f"pages of {page_size}")
    n = t // page_size
    rows = seq_kv.reshape(l, nkv, n, page_size, d)
    rows = rows.transpose(0, 2, 1, 3, 4)        # [L, n, N_kv, page, D]
    return rows.reshape(l * n, nkv * page_size * d)


def _unpage_rows(rows, shape, page_size: int, out_dtype):
    l, nkv, t, d = shape
    n = t // page_size
    seq = rows.reshape(l, n, nkv, page_size, d).transpose(0, 2, 1, 3, 4)
    return seq.reshape(l, nkv, t, d).astype(out_dtype)


def encode_kv_run(k_seq, v_seq, page_size: int,
                  wire_dtype) -> KVPagePayload:
    """Quantize one prefill run for the handoff wire.  ``wire_dtype``
    ``None`` is the EXACT path: the arrays ride untouched (no cast, no
    sidecar) — unshared requests stay bit-equal with the wire off."""
    shape = tuple(k_seq.shape)
    if wire_dtype is None:
        return KVPagePayload(k_seq, v_seq, None, None, shape,
                             int(page_size), "off")
    kp, ks = wr.encode(_page_rows(k_seq, page_size), wire_dtype)
    vp, vs = wr.encode(_page_rows(v_seq, page_size), wire_dtype)
    return KVPagePayload(kp, vp, ks, vs, shape, int(page_size),
                         wr.canonical_name(jnp.dtype(wire_dtype).name))


def decode_kv_run(payload: KVPagePayload, out_dtype):
    """Invert :func:`encode_kv_run` -> (k_seq, v_seq) at ``out_dtype``.
    The 'off' arm returns the arrays untouched (bit-exact)."""
    if payload.wire == "off":
        return payload.k, payload.v
    k = _unpage_rows(
        wr.decode(payload.k, payload.k_qscale, jnp.float32),
        payload.shape, payload.page_size, out_dtype)
    v = _unpage_rows(
        wr.decode(payload.v, payload.v_qscale, jnp.float32),
        payload.shape, payload.page_size, out_dtype)
    return k, v


class KVHandoff:
    """The prefill-pool side of the fabric: computes prefill with the
    engine's own module-level jit, streams the KV run through the page
    codec, and hands the decode replica exactly what its local prefill
    would have produced (bit-equal with the wire off).

    Bind one per fabric; :meth:`prefill_fn` closes over the target
    replica id so each engine's ``fabric.handoff`` decisions name their
    destination."""

    def __init__(self, params, cfg: MoEConfig, page_size: int, *,
                 wire=None, metrics_obj=None,
                 decode_step_ms: float | None = None, vclock=None,
                 transport=None):
        self.params = params
        self.cfg = cfg
        self.page_size = int(page_size)
        name = wire if wire is not None else cfg.kv_wire_dtype
        self.wire_dtype = wr.resolve(name)
        self.wire_name = wr.canonical_name(name)
        self.metrics = (metrics_obj if metrics_obj is not None
                        else _global_metrics)
        #: the decode pool's modeled per-step objective (ms) the handoff
        #: must hide under to overlap (PoolPlan.decode_ms); None = not
        #: priced, the overlap verdict is omitted
        self.decode_step_ms = decode_step_ms
        #: optional :class:`~flashmoe_tpu.fabric.vclock.VirtualClock`:
        #: every transfer ADVANCES it by the measured DCN cost (modeled
        #: + chaos), making the overlap verdict a measured quantity —
        #: reconciled against the priced one per transfer through the
        #: ``fabric.handoff_drift`` decision
        self.vclock = vclock
        #: optional :class:`~flashmoe_tpu.fabric.transport
        #: .HandoffTransport`: with it set the payload crosses a
        #: failable wire — per-page CRC32 verification, timeout +
        #: bounded retry — and the decode pool caches the RECEIVED
        #: bytes; retry cost rides into the vclock as ``extra_ms``.
        #: ``None`` keeps the PR 15 in-process path byte-identical.
        self.transport = transport
        self.count = 0
        self.bytes_moved = 0
        self.modeled_ms_total = 0.0
        self.measured_ms_total = 0.0
        self.hidden_ms_total = 0.0
        self.drift_agree = 0
        self.drift_total = 0

    def prefill_fn(self, replica: int):
        """The ``ServingEngine(prefill_fn=...)`` seam for one decode
        replica."""
        def fn(prompt_padded, true_len, *, rid=None):
            return self.prefill(prompt_padded, true_len,
                                replica=replica, rid=rid)
        return fn

    def prefill(self, prompt_padded, true_len: int, *,
                replica: int = 0, rid=None):
        """Prefill in the prefill pool, hand pages to ``replica``.
        Returns ``(logits, k_seq, v_seq)`` — the engine's prefill
        contract — where the KV run has crossed the handoff wire."""
        from flashmoe_tpu.planner.model import kv_handoff_ms
        from flashmoe_tpu.serving.engine import _prefill_padded

        logits, k_seq, v_seq = _prefill_padded(
            self.params, self.cfg, prompt_padded, jnp.int32(true_len))
        acct = None
        retry_ms = 0.0
        retries = 0
        with trace_span("serve.handoff"):
            payload = encode_kv_run(k_seq, v_seq, self.page_size,
                                    self.wire_dtype)
            ms = kv_handoff_ms(self.cfg, payload.pages, self.page_size,
                               wire=self.wire_dtype)
            if self.transport is not None:
                # the failable wire: per-page CRC verify + bounded
                # retry; what the decode pool caches is what crossed
                result = self.transport.send(payload, modeled_ms=ms,
                                             rid=rid, replica=replica)
                payload = result.payload
                retry_ms = result.retry_ms
                retries = result.retries
            k_out, v_out = decode_kv_run(payload, self.cfg.dtype)
            if self.vclock is not None:
                # advance virtual time INSIDE the serve.handoff span:
                # the request's own prefill span absorbs the DCN wait
                # (plus any retry retransmissions + backoff), so TTFT
                # is measured UNDER the delay the model priced
                acct = self.vclock.on_handoff(ms, rid=rid,
                                              replica=replica,
                                              extra_ms=retry_ms)
        self.count += 1
        self.bytes_moved += payload.payload_bytes
        self.modeled_ms_total += ms
        overlapped = (None if self.decode_step_ms is None
                      else bool(ms <= self.decode_step_ms))
        self.metrics.count("fabric.handoffs")
        self.metrics.sketch("fabric.handoff_ms", ms)
        self.metrics.decision(
            "fabric.handoff", rid=rid, replica=int(replica),
            pages=payload.pages, wire=self.wire_name,
            payload_kb=round(payload.payload_bytes / 1024, 3),
            modeled_dcn_ms=round(ms, 6),
            decode_step_ms=(round(self.decode_step_ms, 6)
                            if self.decode_step_ms is not None else None),
            overlapped=overlapped, retries=retries,
            retry_ms=round(retry_ms, 6))
        if acct is not None:
            self._reconcile(acct, ms, rid, replica, overlapped)
        return logits, k_out, v_out

    def _reconcile(self, acct: dict, modeled_ms: float, rid,
                   replica: int, overlapped_priced) -> None:
        """Measured-vs-priced verdict for one transfer: the virtual
        clock experienced ``acct`` (modeled + chaos, overlap budget
        consumed step-wise); the planner priced ``modeled_ms`` against
        the whole decode tick.  The drift family decision narrates
        agreement — chaos latency/jitter is exactly what pulls the two
        apart."""
        measured = acct["measured_ms"]
        hidden = acct["hidden_ms"]
        self.measured_ms_total += measured
        self.hidden_ms_total += hidden
        overlapped_measured = bool(acct["exposed_ms"] <= 1e-9)
        hf_measured = (hidden / measured) if measured > 0 else 1.0
        hf_priced = None
        if self.decode_step_ms is not None:
            hf_priced = (min(modeled_ms, self.decode_step_ms)
                         / modeled_ms if modeled_ms > 0 else 1.0)
        agree = (None if overlapped_priced is None
                 else bool(overlapped_measured == overlapped_priced))
        self.drift_total += 1
        if agree:
            self.drift_agree += 1
        self.metrics.sketch("fabric.handoff_drift_ms",
                            measured - modeled_ms)
        self.metrics.decision(
            "fabric.handoff_drift", rid=rid, replica=int(replica),
            modeled_dcn_ms=round(modeled_ms, 6),
            chaos_ms=acct["chaos_ms"],
            retry_ms=acct.get("retry_ms", 0.0),
            measured_dcn_ms=round(measured, 6),
            tick_ms=acct["tick_ms"],
            hidden_ms=round(hidden, 6),
            exposed_ms=acct["exposed_ms"],
            hidden_frac_measured=round(hf_measured, 6),
            hidden_frac_priced=(round(hf_priced, 6)
                                if hf_priced is not None else None),
            overlapped_priced=overlapped_priced,
            overlapped_measured=overlapped_measured, agree=agree)

    def snapshot(self) -> dict:
        """Live ``/vars`` view of the handoff link."""
        out = {
            "wire": self.wire_name,
            "handoffs": self.count,
            "bytes_moved": self.bytes_moved,
            "modeled_ms_total": round(self.modeled_ms_total, 6),
            "decode_step_ms": self.decode_step_ms,
        }
        if self.vclock is not None:
            out.update(
                measured_ms_total=round(self.measured_ms_total, 6),
                hidden_ms_total=round(self.hidden_ms_total, 6),
                hidden_fraction=(
                    round(self.hidden_ms_total / self.measured_ms_total,
                          6) if self.measured_ms_total > 0 else None),
                verdicts_agree=self.drift_agree,
                verdicts_total=self.drift_total)
        if self.transport is not None:
            out["transport"] = self.transport.snapshot()
        return out
