"""External fenced lease store: the cross-process front-door seam.

PR 18's :class:`~flashmoe_tpu.fabric.frontdoor.FrontDoorCluster` kept
its shard leases in a Python dict — correct while every peer lives in
one process, meaningless the moment they don't.  This module is the
externalized lease table (ROADMAP item 1 "cross-process door"): a
single file any number of OS processes share, with the three properties
a real lease service needs and the repo's existing integrity idioms
provide:

* **mutual exclusion** — every read-modify-write runs under an
  exclusive :func:`fcntl.flock` on the store file, so two doors racing
  a failover serialize at the kernel, not in Python;
* **torn-write recovery** — the store is an append-only log of
  CRC-framed full-table records (``<magic, body_len, body_crc32>`` +
  JSON body, the :mod:`flashmoe_tpu.utils.integrity` + checkpoint-
  manifest idiom).  A writer killed mid-append leaves a torn tail; the
  next reader's scan stops at the first frame whose CRC refuses, and
  the next WRITER truncates the garbage back to the last intact record
  (a ``frontdoor.lease_repair`` decision) — the store never serves a
  half-written epoch;
* **epoch fencing** — every lease write carries the epoch the writer
  believes it is advancing to.  A write at an epoch <= the stored one
  is REFUSED (``frontdoor.fence`` decision, ``StaleLeaseError``): a
  partitioned zombie door re-asserting its old leases after a failover
  cannot clobber the new owner — the fencing-token discipline of
  Chubby/ZooKeeper leases, drilled by the ``lease_split_brain`` chaos
  row.

The same table carries the decode replicas' **sub-step heartbeats**
(monotonic ``seq`` bumped at every engine-step phase boundary,
vclock-stamped when the fabric's virtual clock is armed), and
:class:`HeartbeatWatchdog` turns them into stall detection: a replica
whose seq stops advancing while it still holds work is declared
stalled after ``misses_to_stall`` consecutive missed observations
(deadline hysteresis — a slow-but-alive replica that beats every other
step never trips), triggering the PR 18 fence+evacuate+adopt migration
path mid-step, not at the step boundary.
"""

from __future__ import annotations

import dataclasses
import fcntl
import json
import os
import struct

from flashmoe_tpu.utils.integrity import crc32_bytes
from flashmoe_tpu.utils.telemetry import metrics as _global_metrics

#: record frame: magic + body length + body crc32 (little-endian)
_MAGIC = b"FML1"
_HDR = struct.Struct("<4sII")


class LeaseStoreError(RuntimeError):
    """The store file is unusable (not a torn tail — those recover)."""


class StaleLeaseError(LeaseStoreError):
    """A lease write was fenced off: its epoch is not newer than the
    stored one.  The writer holds a revoked lease and must stand
    down."""


def _frame(state: dict) -> bytes:
    body = json.dumps(state, sort_keys=True).encode()
    return _HDR.pack(_MAGIC, len(body), crc32_bytes(body)) + body


def _scan(blob: bytes) -> tuple[dict | None, int, int]:
    """Walk the record log.  Returns ``(last intact state, offset just
    past it, torn bytes beyond it)`` — a torn/corrupt tail never hides
    the intact history before it."""
    state, pos = None, 0
    n = len(blob)
    while pos + _HDR.size <= n:
        magic, blen, crc = _HDR.unpack_from(blob, pos)
        body_at = pos + _HDR.size
        if magic != _MAGIC or body_at + blen > n:
            break                       # torn header or truncated body
        body = blob[body_at:body_at + blen]
        if crc32_bytes(body) != crc:
            break                       # torn/corrupted body
        try:
            state = json.loads(body.decode())
        except ValueError:
            break
        pos = body_at + blen
    return state, pos, n - pos


@dataclasses.dataclass(frozen=True)
class Lease:
    """One shard's lease row."""

    shard: int
    owner: int
    epoch: int


class LeaseStore:
    """File-backed fenced lease + heartbeat table.

    ``path``: the store file (created empty on first use).
    ``n_shards``: the namespace shard count the lease table covers.
    ``peer``: this process's door/peer id, stamped on its fencing
    decisions so a merged fleet view names WHO was refused."""

    def __init__(self, path: str, *, n_shards: int = 8,
                 metrics_obj=None, peer=None):
        if n_shards < 1:
            raise ValueError(f"n_shards must be >= 1, got {n_shards}")
        self.path = str(path)
        self.n_shards = int(n_shards)
        self.peer = peer
        self.metrics = (metrics_obj if metrics_obj is not None
                        else _global_metrics)
        self.repairs = 0
        self.fenced = 0
        # touch the file so every later open can be "r+b"
        with open(self.path, "ab"):
            pass

    # ---- framing / locking -------------------------------------------

    def _load(self, fh) -> tuple[dict, int, int]:
        fh.seek(0)
        state, good_end, torn = _scan(fh.read())
        if state is None:
            state = {"leases": {}, "beats": {}}
        return state, good_end, torn

    def _repair(self, fh, good_end: int, torn: int,
                state: dict) -> None:
        """Roll a torn tail back to the last intact record — the
        recovery arm of the checkpoint-manifest idiom, drilled by
        ``lease_torn_write``."""
        fh.truncate(good_end)
        self.repairs += 1
        epochs = [v["epoch"] for v in state["leases"].values()]
        self.metrics.count("frontdoor.lease_repairs")
        self.metrics.decision(
            "frontdoor.lease_repair", peer=self.peer,
            torn_bytes=int(torn), restored_offset=int(good_end),
            restored_epoch=(max(epochs) if epochs else None))

    def _write(self, fh, state: dict) -> None:
        fh.seek(0, os.SEEK_END)
        fh.write(_frame(state))
        fh.flush()
        os.fsync(fh.fileno())

    def _mutate(self, fn):
        """One locked read-modify-write round: load the last intact
        state (repairing any torn tail first), apply ``fn`` (which may
        raise to refuse), append the new record."""
        with open(self.path, "r+b") as fh:
            fcntl.flock(fh.fileno(), fcntl.LOCK_EX)
            try:
                state, good_end, torn = self._load(fh)
                if torn:
                    self._repair(fh, good_end, torn, state)
                out = fn(state)
                self._write(fh, state)
                return out
            finally:
                fcntl.flock(fh.fileno(), fcntl.LOCK_UN)

    def read(self) -> dict:
        """The last intact table state (shared-lock snapshot; a torn
        tail is SKIPPED here and repaired by the next writer)."""
        with open(self.path, "rb") as fh:
            fcntl.flock(fh.fileno(), fcntl.LOCK_SH)
            try:
                state, _end, _torn = self._load(fh)
                return state
            finally:
                fcntl.flock(fh.fileno(), fcntl.LOCK_UN)

    # ---- leases (epoch-fenced) ---------------------------------------

    def init_leases(self, owners: dict[int, int]) -> None:
        """Seed the lease table at epoch 0 — only shards not already
        present are written, so a second process joining an existing
        store adopts the live table instead of resetting it."""
        def fn(state):
            for shard, owner in owners.items():
                state["leases"].setdefault(
                    str(int(shard)), {"owner": int(owner), "epoch": 0})
        self._mutate(fn)

    def leases(self) -> dict[int, Lease]:
        return {int(s): Lease(int(s), int(v["owner"]), int(v["epoch"]))
                for s, v in self.read()["leases"].items()}

    def write_lease(self, shard: int, owner: int, epoch: int, *,
                    reason: str | None = None) -> Lease:
        """Advance one shard's lease — REFUSED (``StaleLeaseError`` +
        ``frontdoor.fence`` decision) unless ``epoch`` is strictly newer
        than the stored one.  The refusal is the split-brain guard: a
        zombie peer re-asserting a revoked lease cannot take the shard
        back."""
        def fn(state):
            cur = state["leases"].get(str(int(shard)),
                                      {"owner": -1, "epoch": -1})
            if int(epoch) <= int(cur["epoch"]):
                self.fenced += 1
                self.metrics.count("frontdoor.fences")
                self.metrics.decision(
                    "frontdoor.fence", shard=int(shard),
                    peer=self.peer, claimant=int(owner),
                    stale_epoch=int(epoch),
                    current_epoch=int(cur["epoch"]),
                    current_owner=int(cur["owner"]),
                    refused=True, reason=reason)
                raise StaleLeaseError(
                    f"lease write for shard {shard} at epoch {epoch} "
                    f"refused: store holds epoch {cur['epoch']} "
                    f"(owner {cur['owner']}) — claimant {owner} is "
                    f"fenced off")
            state["leases"][str(int(shard))] = {
                "owner": int(owner), "epoch": int(epoch)}
            return Lease(int(shard), int(owner), int(epoch))
        return self._mutate(fn)

    # ---- heartbeats --------------------------------------------------

    def heartbeat(self, key, seq: int, *, ts_ms: float = 0.0,
                  phase: str | None = None,
                  step: int | None = None) -> bool:
        """Publish one monotonic heartbeat for ``key`` (a replica id or
        a door name).  A stale ``seq`` (<= the stored one) is dropped —
        heartbeats only ever advance.  Returns whether it landed."""
        def fn(state):
            cur = state["beats"].get(str(key))
            if cur is not None and int(seq) <= int(cur["seq"]):
                return False
            state["beats"][str(key)] = {
                "seq": int(seq), "ts_ms": round(float(ts_ms), 6),
                "phase": phase,
                "step": (int(step) if step is not None else None)}
            return True
        return self._mutate(fn)

    def beats(self) -> dict:
        return dict(self.read()["beats"])

    # ---- chaos / test seams ------------------------------------------

    def tear_last_record(self, keep_fraction: float = 0.5) -> int:
        """Simulate a writer killed mid-append (``kill -9`` during
        :meth:`_write`): truncate the newest record mid-body so its CRC
        can no longer verify.  Returns the bytes torn off.  The next
        reader must recover the PREVIOUS intact state — the
        ``lease_torn_write`` drill's injection."""
        with open(self.path, "r+b") as fh:
            fcntl.flock(fh.fileno(), fcntl.LOCK_EX)
            try:
                fh.seek(0)
                blob = fh.read()
                _state, good_end, _torn = _scan(blob)
                if good_end == 0:
                    return 0
                # find the start of the LAST intact record
                prev_end = 0
                pos = 0
                while pos < good_end:
                    _m, blen, _c = _HDR.unpack_from(blob, pos)
                    nxt = pos + _HDR.size + blen
                    if nxt >= good_end:
                        prev_end = pos
                        break
                    pos = nxt
                last_len = good_end - prev_end
                cut = prev_end + max(_HDR.size + 1,
                                     int(last_len * keep_fraction))
                cut = min(cut, good_end - 1)
                fh.truncate(cut)
                return len(blob) - cut
            finally:
                fcntl.flock(fh.fileno(), fcntl.LOCK_UN)

    def snapshot(self) -> dict:
        """Live ``/vars`` view."""
        state = self.read()
        epochs = [v["epoch"] for v in state["leases"].values()]
        return {
            "path": self.path,
            "shards": self.n_shards,
            "leases": state["leases"],
            "beats": state["beats"],
            "max_epoch": (max(epochs) if epochs else None),
            "repairs": self.repairs,
            "fenced": self.fenced,
        }


@dataclasses.dataclass(frozen=True)
class HeartbeatConfig:
    """Arms sub-step heartbeat publication + stall detection on a
    :class:`~flashmoe_tpu.fabric.engine.ServingFabric`.

    ``misses_to_stall``: consecutive fabric-step observations with no
    fresh heartbeat before a replica is declared stalled.  >= 2 is the
    deadline hysteresis: a slow-but-alive replica that publishes at
    least every other observation never false-positives (drilled by
    ``tests/test_leasestore.py``).  ``store_path``: where the lease
    store lives; ``None`` lets the fabric place it in a tempdir."""

    misses_to_stall: int = 2
    store_path: str | None = None

    def __post_init__(self):
        if self.misses_to_stall < 1:
            raise ValueError(
                f"misses_to_stall must be >= 1, "
                f"got {self.misses_to_stall}")


class HeartbeatPublisher:
    """The engine-side half: a callable ``(phase)`` the
    :class:`~flashmoe_tpu.serving.engine.ServingEngine` invokes at every
    step-phase boundary (enter/admit/prefill/sample/decode/end).  Each
    call bumps the replica's monotonic ``seq`` in the store, stamped
    with virtual time when the fabric's clock is armed — so the
    watchdog can see WHERE inside a step a replica froze."""

    def __init__(self, store: LeaseStore, replica: int, *,
                 clock=None, step_fn=None):
        self.store = store
        self.replica = int(replica)
        self._clock = clock
        self._step_fn = step_fn
        self.seq = 0

    def __call__(self, phase: str) -> None:
        self.seq += 1
        ts = (self._clock() * 1e3 if self._clock is not None else 0.0)
        self.store.heartbeat(
            self.replica, self.seq, ts_ms=ts, phase=phase,
            step=(self._step_fn() if self._step_fn is not None
                  else None))


class HeartbeatWatchdog:
    """The fabric-side half: one observation per fabric step.  A
    replica with pending work whose stored ``seq`` did not advance
    since the last observation takes a miss (``fabric.heartbeat_miss``
    decision); ``misses_to_stall`` consecutive misses declare it
    stalled (``fabric.heartbeat_stall`` — detection latency in ms of
    virtual decode time) and the fabric runs the fence+evacuate+adopt
    migration.  Any fresh beat resets the miss count — the hysteresis
    that keeps a merely slow replica out of the morgue."""

    def __init__(self, store: LeaseStore, *, misses_to_stall: int = 2,
                 tick_ms: float | None = None, metrics_obj=None):
        self.store = store
        self.misses_to_stall = int(misses_to_stall)
        self.tick_ms = tick_ms
        self.metrics = (metrics_obj if metrics_obj is not None
                        else _global_metrics)
        self._last_seq: dict[int, int] = {}
        self._misses: dict[int, int] = {}
        self.stalled_total = 0

    def observe(self, step: int, replicas, *, pending=None) -> list[int]:
        """One post-step sweep over ``replicas``.  ``pending(r)`` gates
        the miss accounting: an idle replica owes no heartbeat.
        Returns the replicas newly declared stalled this observation."""
        beats = self.store.beats()
        stalled: list[int] = []
        for r in replicas:
            r = int(r)
            row = beats.get(str(r))
            seq = int(row["seq"]) if row is not None else -1
            if seq > self._last_seq.get(r, -1):
                self._last_seq[r] = seq
                self._misses[r] = 0
                continue
            if pending is not None and not pending(r):
                continue                # idle: no beat owed
            self._misses[r] = self._misses.get(r, 0) + 1
            self.metrics.count("fabric.heartbeat_misses")
            self.metrics.decision(
                "fabric.heartbeat_miss", replica=r, step=int(step),
                misses=self._misses[r], last_seq=seq,
                last_phase=(row or {}).get("phase"),
                budget_left=self.misses_to_stall - self._misses[r])
            if self._misses[r] >= self.misses_to_stall:
                detect_ms = (self._misses[r] * float(self.tick_ms)
                             if self.tick_ms else 0.0)
                self.stalled_total += 1
                self.metrics.count("fabric.heartbeat_stalls")
                self.metrics.sketch("fabric.heartbeat_detect_ms",
                                    detect_ms)
                self.metrics.decision(
                    "fabric.heartbeat_stall", replica=r,
                    step=int(step), misses=self._misses[r],
                    last_seq=seq, last_phase=(row or {}).get("phase"),
                    last_step=(row or {}).get("step"),
                    detect_ms=round(detect_ms, 6))
                stalled.append(r)
                self._misses[r] = 0
        return stalled

    def snapshot(self) -> dict:
        return {
            "misses_to_stall": self.misses_to_stall,
            "tick_ms": self.tick_ms,
            "misses": dict(self._misses),
            "stalled_total": self.stalled_total,
        }
