"""Request router over N engine replicas.

Join-shortest-queue with session affinity: a request that names a
session (or, failing that, its rid) hashes to a preferred replica so a
conversation's KV pages keep landing where its earlier turns decoded;
the preference yields to load only when that replica is unhealthy or
draining.  Queue depth comes from each replica's ``/healthz`` snapshot
(``queue_depth + active_requests``), so the router sees exactly what an
external probe of the engine would see — there is no second bookkeeping
path to drift.

Every placement is recorded as a ``fabric.route`` decision; the runtime
controller morphs the rotation through :meth:`ReplicaRouter.drain` /
:meth:`ReplicaRouter.undrain` (PR 9 debounce/cooldown/budget discipline
lives in :class:`~flashmoe_tpu.runtime.controller.RuntimeController`,
not here — the router just executes the verdict).

Ties break on the lowest replica id, so a fabric drill replays
bit-identically: same trace, same health sequence, same placements.
"""

from __future__ import annotations

import zlib

from flashmoe_tpu.utils.telemetry import metrics as _global_metrics


class ReplicaRouter:
    """Pick a decode replica for each request.

    ``health_fns`` is one zero-arg callable per replica returning the
    engine's ``/healthz`` dict (:meth:`ServingEngine._health_snapshot`);
    a callable that raises marks its replica unhealthy for that
    placement only — health is re-probed per route, never cached."""

    def __init__(self, health_fns, *, metrics_obj=None, affinity=True):
        self.health_fns = list(health_fns)
        if not self.health_fns:
            raise ValueError("ReplicaRouter needs >= 1 replica")
        self.affinity = bool(affinity)
        self.metrics = (metrics_obj if metrics_obj is not None
                        else _global_metrics)
        self._draining: set[int] = set()
        self._failed: set[int] = set()
        self.routed = [0] * len(self.health_fns)

    @property
    def n_replicas(self) -> int:
        return len(self.health_fns)

    def drain(self, replica: int) -> None:
        """Take ``replica`` out of the rotation (in-flight work keeps
        decoding; only NEW placements avoid it)."""
        self._check(replica)
        self._draining.add(int(replica))

    def undrain(self, replica: int) -> None:
        """Return ``replica`` to the rotation."""
        self._check(replica)
        self._draining.discard(int(replica))

    def draining(self) -> tuple[int, ...]:
        return tuple(sorted(self._draining))

    def mark_failed(self, replica: int) -> None:
        """Declare ``replica`` DEAD: unlike a drain (which only steers
        new placements while in-flight work keeps decoding), a failed
        replica is excluded even from the everyone-is-draining fallback
        rotation — its requests must MIGRATE, there is nothing left to
        decode them.  The fabric calls this from its crash detector."""
        self._check(replica)
        self._failed.add(int(replica))
        self._draining.discard(int(replica))

    def failed(self) -> tuple[int, ...]:
        return tuple(sorted(self._failed))

    def _check(self, replica: int) -> None:
        if not 0 <= int(replica) < self.n_replicas:
            raise ValueError(f"replica {replica} out of range "
                             f"[0, {self.n_replicas})")

    def _preferred(self, rid, session) -> int | None:
        if not self.affinity:
            return None
        key = session if session is not None else rid
        if key is None:
            return None
        return zlib.crc32(str(key).encode()) % self.n_replicas

    def _load(self, replica: int):
        """(queue_depth + active_requests, healthy) via ``/healthz``."""
        try:
            h = self.health_fns[replica]()
        except Exception:
            return None, False
        depth = int(h.get("queue_depth", 0)) + int(
            h.get("active_requests", 0))
        return depth, bool(h.get("ok", True))

    def route(self, rid=None, *, session=None) -> int:
        """Place one request; returns the chosen replica id."""
        loads = [self._load(i) for i in range(self.n_replicas)]
        eligible = [i for i, (d, ok) in enumerate(loads)
                    if ok and i not in self._draining
                    and i not in self._failed]
        if not eligible:
            # every replica draining/unhealthy: fall back to the full
            # rotation rather than dropping the request on the floor —
            # but never to a FAILED replica, which cannot decode at all
            eligible = [i for i in range(self.n_replicas)
                        if i not in self._failed]
        if not eligible:
            raise RuntimeError(
                "every replica has failed — nothing left to route to")
        preferred = self._preferred(rid, session)
        if preferred in eligible:
            choice, why = preferred, "affinity"
        else:
            choice = min(eligible, key=lambda i: (loads[i][0], i))
            why = "jsq" if preferred is None else "jsq_spill"
        self.routed[choice] += 1
        self.metrics.count("fabric.routed")
        self.metrics.decision(
            "fabric.route", rid=rid, session=session,
            replica=int(choice), policy=why,
            preferred=preferred,
            queue_depths=[d for d, _ in loads],
            draining=list(self.draining()))
        return choice

    def snapshot(self) -> dict:
        """Live ``/vars`` view of the rotation."""
        return {
            "replicas": self.n_replicas,
            "affinity": self.affinity,
            "draining": list(self.draining()),
            "failed": list(self.failed()),
            "routed": list(self.routed),
        }
