"""Mocked fabric topologies: ``FLASHMOE_MOCK_FABRIC`` world blocking.

The serving twin of the PR 12 ``FLASHMOE_MOCK_SLICES`` mock
(:func:`flashmoe_tpu.parallel.topology._mock_slices`): partition the
device world into ``k`` equal contiguous replica blocks so multi-replica
fabric drills, the ``bench.py --fabric`` sweep and the router tests run
on the virtual CPU mesh without real multi-host serving.

The parse is hardened the same way: a malformed mock (non-integer,
non-positive, or a count that does not divide a multi-device world) is
a configuration error the drill must see at fabric construction — a
``ValueError`` naming the world size and the accepted format — never a
silent fall-back to a single replica.  The one relaxation vs the slice
mock: on a SINGLE-device world any replica count co-locates on that
device (replicas are full engines sharing the module-level jits, not
device partitions), so the 1/2/4-replica CI sweep runs on a bare CPU
host without forcing a virtual mesh.
"""

from __future__ import annotations

import os

#: the env var: a single positive replica count dividing the world size.
ENV_MOCK_FABRIC = "FLASHMOE_MOCK_FABRIC"


def _mock_fabric(n: int) -> int | None:
    """Parse ``FLASHMOE_MOCK_FABRIC`` against a world of ``n`` devices.

    Returns the replica count, or ``None`` when the mock is unset (or
    asks for a single replica — no blocking).  Mirrors
    :func:`flashmoe_tpu.parallel.topology._mock_slices`: malformed
    values raise a ``ValueError`` naming the world size and the
    accepted format."""
    raw = os.environ.get(ENV_MOCK_FABRIC)
    if raw is None or raw.strip() == "":
        return None
    try:
        replicas = int(raw)
    except ValueError:
        raise ValueError(
            f"{ENV_MOCK_FABRIC}={raw!r} is not an integer; the mock "
            f"format is a single positive replica count dividing the "
            f"world size ({n} devices), e.g. {ENV_MOCK_FABRIC}=2")
    if replicas < 1:
        raise ValueError(
            f"{ENV_MOCK_FABRIC}={replicas} must be >= 1 (a positive "
            f"replica count dividing the world size, {n} devices)")
    if replicas > 1 and n > 1 and n % replicas:
        raise ValueError(
            f"{ENV_MOCK_FABRIC}={replicas} does not divide the world "
            f"size ({n} devices); pick a divisor of {n} so every mocked "
            f"replica holds the same contiguous device block")
    return replicas if replicas > 1 else None


def fabric_world(n_devices: int | None = None) -> tuple[int, int]:
    """(replicas, devices_per_replica) for the current (or given)
    world: the ``FLASHMOE_MOCK_FABRIC`` blocking when set, else one
    replica owning every device.  The one resolution
    :class:`~flashmoe_tpu.fabric.engine.ServingFabric` and
    ``bench.py --fabric`` share, so a mis-typed mock fails both the
    same way."""
    if n_devices is None:
        import jax

        n_devices = len(jax.devices())
    n = int(n_devices)
    if n < 1:
        raise ValueError(f"fabric world needs >= 1 device, got {n}")
    replicas = _mock_fabric(n) or 1
    return replicas, max(1, n // replicas)
