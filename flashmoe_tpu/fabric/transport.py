"""Failable KV-handoff transport: real wire semantics, in-process.

PR 15's handoff was a codec round trip — the bytes and the DCN pricing
were real, the wire was not: nothing could be lost, corrupted, or late.
This module is the transport seam behind
:class:`~flashmoe_tpu.fabric.handoff.KVHandoff` that makes the handoff
*failable* (ROADMAP item 1(a)), with the failure semantics a real
inter-host transport has:

* **wire frames** — every transfer serializes each payload field
  (K/V pages plus their ``_qscale`` sidecars) to raw bytes and attaches
  a per-page CRC32 checksum sidecar
  (:func:`flashmoe_tpu.utils.integrity.crc32_pages` — the same CRC32
  helper the checkpoint manifests use), riding the frame the way the
  quant scales ride the page payload;
* **receiver verification** — the receive side recomputes every page
  checksum before the bytes are allowed anywhere near the paged cache;
  a mismatch is a ``fabric.handoff_corrupt`` decision naming the bad
  pages, never a silent garbage decode;
* **timeout + bounded retry** — a failed attempt (corrupt or timed
  out) retries after a capped exponential backoff, at most
  ``max_retries`` times, each retry recorded as a
  ``fabric.handoff_retry`` decision; the wasted wire time (the garbage
  attempt's modeled DCN cost, or the timeout window) plus the backoff
  is returned as ``retry_ms`` so the caller prices it through the
  virtual clock — retries are *experienced* by the request's TTFT,
  reconciled per transfer by the ``fabric.handoff_drift`` verdicts;
* **deterministic chaos** — an armed
  :class:`~flashmoe_tpu.chaos.FaultPlan` with fault
  ``handoff_corrupt`` / ``handoff_timeout`` perturbs the first attempt
  of every transfer in ``[plan.step, plan.step + plan.duration)``
  (TRANSFER index, like the DCN faults).  With ``plan.once`` (default)
  the retry is clean — exactly one retry per faulted transfer; with
  ``once=False`` every attempt fails and the bounded budget surfaces
  as a :class:`HandoffTransportError` (the give-up arm).

The byte path is exact: with no fault armed, ``send`` returns a
payload rebuilt from the received bytes that is bit-identical to the
sent one, so the fabric's token-bit-equality gates hold with the
transport on.
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp
import numpy as np

from flashmoe_tpu.fabric.handoff import KVPagePayload
from flashmoe_tpu.utils.integrity import crc32_pages
from flashmoe_tpu.utils.telemetry import metrics as _global_metrics

#: serving faults the transport knows how to inject (chaos matrix rows)
TRANSPORT_FAULTS = ("handoff_corrupt", "handoff_timeout")

#: the bytes a chaos corruption stamps mid-page (the checkpoint
#: tamper idiom — ``chaos._corrupt_latest_checkpoint`` flips the same)
_TAMPER = b"\xde\xad\xbe\xef"


class HandoffTransportError(RuntimeError):
    """A transfer exhausted its retry budget — the handoff failed for
    real and the caller must treat the prefill as undelivered."""


@dataclasses.dataclass(frozen=True)
class WireFrame:
    """One payload field on the wire: raw bytes + enough metadata to
    rebuild the array + the per-page CRC32 sidecar."""

    buf: bytes
    dtype: object                  # np.dtype (in-process frame)
    shape: tuple
    page_crcs: tuple

    def verify(self) -> list:
        """Indices of pages whose received bytes fail their checksum."""
        got = crc32_pages(self.buf, len(self.page_crcs))
        return [i for i, (w, g) in enumerate(zip(self.page_crcs, got))
                if w != g]


def _to_frame(arr, pages: int) -> WireFrame | None:
    if arr is None:
        return None
    host = np.asarray(arr)
    buf = host.tobytes()
    return WireFrame(buf, host.dtype, tuple(host.shape),
                     crc32_pages(buf, pages))


def _from_frame(frame: WireFrame | None):
    if frame is None:
        return None
    arr = np.frombuffer(frame.buf, dtype=frame.dtype)
    return jnp.asarray(arr.reshape(frame.shape))


def encode_frames(payload: KVPagePayload) -> dict:
    """Serialize one payload into wire frames, one per field, each with
    its per-page checksum sidecar."""
    n = max(1, payload.pages)
    return {
        "k": _to_frame(payload.k, n),
        "v": _to_frame(payload.v, n),
        "k_qscale": _to_frame(payload.k_qscale, n),
        "v_qscale": _to_frame(payload.v_qscale, n),
    }


def verify_frames(frames: dict) -> list:
    """Every ``(field, page)`` whose received bytes fail the sidecar
    checksum (empty = the transfer verified clean)."""
    bad = []
    for field, frame in frames.items():
        if frame is None:
            continue
        bad.extend((field, p) for p in frame.verify())
    return bad


def decode_frames(frames: dict, payload: KVPagePayload) -> KVPagePayload:
    """Rebuild the payload FROM THE RECEIVED BYTES (not the sender's
    arrays) — the wire is real: what the decode pool caches is what
    crossed, bit-identical only because the transfer verified."""
    return dataclasses.replace(
        payload,
        k=_from_frame(frames["k"]), v=_from_frame(frames["v"]),
        k_qscale=_from_frame(frames["k_qscale"]),
        v_qscale=_from_frame(frames["v_qscale"]))


def _tampered(frame: WireFrame) -> WireFrame:
    """Corrupt one frame's bytes mid-buffer (checksums kept — the
    RECEIVER must notice, that is the whole point)."""
    buf = frame.buf
    if not buf:
        return frame
    mid = max(0, len(buf) // 2 - len(_TAMPER))
    out = buf[:mid] + _TAMPER[:len(buf) - mid] + buf[mid + len(_TAMPER):]
    return dataclasses.replace(frame, buf=out[:len(buf)])


@dataclasses.dataclass(frozen=True)
class TransferResult:
    """What one :meth:`HandoffTransport.send` experienced."""

    payload: KVPagePayload         # rebuilt from the received bytes
    attempts: int
    retries: int
    corrupt_pages: int
    timeouts: int
    retry_ms: float                # wasted wire time + backoff, priced
                                   # through the vclock by the caller


class HandoffTransport:
    """In-process transport with wire failure semantics.

    ``max_retries``: retry budget per transfer (attempts beyond
    ``1 + max_retries`` raise :class:`HandoffTransportError`).
    ``timeout_ms``: the per-attempt deadline — an injected
    ``handoff_timeout`` attempt stalls for exactly this long before it
    is abandoned.  ``backoff_ms`` / ``backoff_cap_ms``: capped
    exponential backoff between attempts (``min(cap, base * 2**(n-1))``
    after the n-th failure).  ``plan``: an armed
    :class:`~flashmoe_tpu.chaos.FaultPlan` whose fault is one of
    :data:`TRANSPORT_FAULTS`.  ``tamper_fn``: test seam — a callable
    ``(transfer_index, attempt) -> bool`` that forces corruption on a
    given attempt (the CRC tamper drill)."""

    def __init__(self, *, metrics_obj=None, max_retries: int = 2,
                 timeout_ms: float = 50.0, backoff_ms: float = 5.0,
                 backoff_cap_ms: float = 40.0, plan=None,
                 tamper_fn=None):
        if plan is not None and plan.fault not in TRANSPORT_FAULTS:
            raise ValueError(
                f"HandoffTransport only injects {TRANSPORT_FAULTS}, "
                f"got plan fault {plan.fault!r}")
        if max_retries < 0:
            raise ValueError(f"max_retries must be >= 0, "
                             f"got {max_retries}")
        self.metrics = (metrics_obj if metrics_obj is not None
                        else _global_metrics)
        self.max_retries = int(max_retries)
        self.timeout_ms = float(timeout_ms)
        self.backoff_ms = float(backoff_ms)
        self.backoff_cap_ms = float(backoff_cap_ms)
        self.plan = plan
        self.tamper_fn = tamper_fn
        self.transfers = 0
        self.retries_total = 0
        self.corrupt_total = 0
        self.timeout_total = 0
        self.retry_ms_total = 0.0

    # ---- chaos --------------------------------------------------------

    def _fault(self, index: int, attempt: int) -> str | None:
        """Which fault (if any) hits this attempt.  Chaos fires on the
        first attempt of every transfer in the plan window; with
        ``plan.once`` (default) the retry is clean, else every attempt
        fails until the budget gives up."""
        if self.tamper_fn is not None \
                and self.tamper_fn(index, attempt):
            return "handoff_corrupt"
        p = self.plan
        if p is None:
            return None
        if not (p.step <= index < p.step + p.duration):
            return None
        if attempt > 1 and p.once:
            return None
        return p.fault

    def _backoff(self, failures: int) -> float:
        return min(self.backoff_cap_ms,
                   self.backoff_ms * (2.0 ** (failures - 1)))

    # ---- the wire -----------------------------------------------------

    def _transmit(self, frames: dict, *, tamper: bool) -> dict:
        """One attempt: the frames cross the (in-process) wire.  A
        tampered attempt corrupts the largest frame's bytes — the
        sidecar checksums ride untouched, so the receiver's verify
        catches it."""
        if not tamper:
            return frames
        victim, size = None, -1
        for field, frame in frames.items():
            if frame is not None and len(frame.buf) > size:
                victim, size = field, len(frame.buf)
        rx = dict(frames)
        if victim is not None:
            rx[victim] = _tampered(rx[victim])
        return rx

    def send(self, payload: KVPagePayload, *, modeled_ms: float = 0.0,
             rid=None, replica: int = 0) -> TransferResult:
        """Move one payload across the wire with verify + retry.
        Returns the payload rebuilt from the received (verified) bytes
        plus the transfer's failure accounting."""
        frames = encode_frames(payload)
        index = self.transfers
        self.transfers += 1
        attempts = 0
        retry_ms = 0.0
        corrupt_pages = 0
        timeouts = 0
        rx = frames
        while True:
            attempts += 1
            fault = self._fault(index, attempts)
            if fault == "handoff_timeout":
                # the attempt never completes: pay the full deadline,
                # back off, retransmit
                timeouts += 1
                self.timeout_total += 1
                back = self._backoff(attempts)
                retry_ms += self.timeout_ms + back
                self.metrics.count("fabric.handoff_retries")
                self.metrics.decision(
                    "fabric.handoff_retry", rid=rid,
                    replica=int(replica), transfer=index,
                    attempt=attempts, reason="timeout",
                    wasted_ms=round(self.timeout_ms, 6),
                    backoff_ms=round(back, 6),
                    budget_left=self.max_retries - (attempts - 1) - 1)
                self._check_budget(attempts, index, rid, replica,
                                   "timeout")
                continue
            rx = self._transmit(frames,
                                tamper=(fault == "handoff_corrupt"))
            bad = verify_frames(rx)
            if bad:
                # garbage crossed the wire: the bytes were paid for,
                # the checksum refused them at the receiver
                corrupt_pages += len(bad)
                self.corrupt_total += len(bad)
                self.metrics.count("fabric.handoff_corrupts")
                self.metrics.decision(
                    "fabric.handoff_corrupt", rid=rid,
                    replica=int(replica), transfer=index,
                    attempt=attempts, bad_pages=bad[:4],
                    bad_page_count=len(bad))
                back = self._backoff(attempts)
                retry_ms += float(modeled_ms) + back
                self.metrics.count("fabric.handoff_retries")
                self.metrics.decision(
                    "fabric.handoff_retry", rid=rid,
                    replica=int(replica), transfer=index,
                    attempt=attempts, reason="corrupt",
                    wasted_ms=round(float(modeled_ms), 6),
                    backoff_ms=round(back, 6),
                    budget_left=self.max_retries - (attempts - 1) - 1)
                self._check_budget(attempts, index, rid, replica,
                                   "corrupt")
                continue
            break
        retries = attempts - 1
        self.retries_total += retries
        self.retry_ms_total += retry_ms
        if retry_ms:
            self.metrics.sketch("fabric.handoff_retry_ms", retry_ms)
        return TransferResult(
            payload=decode_frames(rx, payload), attempts=attempts,
            retries=retries, corrupt_pages=corrupt_pages,
            timeouts=timeouts, retry_ms=retry_ms)

    def _check_budget(self, attempts: int, index: int, rid, replica,
                      reason: str) -> None:
        if attempts >= 1 + self.max_retries:
            raise HandoffTransportError(
                f"KV handoff transfer {index} (rid={rid}, replica="
                f"{replica}) failed after {attempts} attempts "
                f"({reason}); retry budget max_retries="
                f"{self.max_retries} exhausted")

    def snapshot(self) -> dict:
        """Live ``/vars`` view of the transport."""
        return {
            "transfers": self.transfers,
            "retries_total": self.retries_total,
            "corrupt_total": self.corrupt_total,
            "timeout_total": self.timeout_total,
            "retry_ms_total": round(self.retry_ms_total, 6),
            "max_retries": self.max_retries,
            "timeout_ms": self.timeout_ms,
            "fault": (self.plan.fault if self.plan is not None
                      else None),
        }
