"""Failable KV-handoff transport: real wire semantics, in-process.

PR 15's handoff was a codec round trip — the bytes and the DCN pricing
were real, the wire was not: nothing could be lost, corrupted, or late.
This module is the transport seam behind
:class:`~flashmoe_tpu.fabric.handoff.KVHandoff` that makes the handoff
*failable* (ROADMAP item 1(a)), with the failure semantics a real
inter-host transport has:

* **wire frames** — every transfer serializes each payload field
  (K/V pages plus their ``_qscale`` sidecars) to raw bytes and attaches
  a per-page CRC32 checksum sidecar
  (:func:`flashmoe_tpu.utils.integrity.crc32_pages` — the same CRC32
  helper the checkpoint manifests use), riding the frame the way the
  quant scales ride the page payload;
* **receiver verification** — the receive side recomputes every page
  checksum before the bytes are allowed anywhere near the paged cache;
  a mismatch is a ``fabric.handoff_corrupt`` decision naming the bad
  pages, never a silent garbage decode;
* **timeout + bounded retry** — a failed attempt (corrupt or timed
  out) retries after a capped exponential backoff, at most
  ``max_retries`` times, each retry recorded as a
  ``fabric.handoff_retry`` decision; the wasted wire time (the garbage
  attempt's modeled DCN cost, or the timeout window) plus the backoff
  is returned as ``retry_ms`` so the caller prices it through the
  virtual clock — retries are *experienced* by the request's TTFT,
  reconciled per transfer by the ``fabric.handoff_drift`` verdicts;
* **deterministic chaos** — an armed
  :class:`~flashmoe_tpu.chaos.FaultPlan` with fault
  ``handoff_corrupt`` / ``handoff_timeout`` perturbs the first attempt
  of every transfer in ``[plan.step, plan.step + plan.duration)``
  (TRANSFER index, like the DCN faults).  With ``plan.once`` (default)
  the retry is clean — exactly one retry per faulted transfer; with
  ``once=False`` every attempt fails and the bounded budget surfaces
  as a :class:`HandoffTransportError` (the give-up arm).

* **a real socket wire** — ``wire="tcp"`` moves every transfer across
  a localhost TCP connection (stdlib :mod:`socket`, length-prefixed
  frame protocol, a receiver thread that rebuilds frames FROM THE
  STREAM): connection reset, partial read, and recv timeout become
  *real* kernel failure modes that feed the same retry ladder as the
  injected faults, and the ``net_partition`` chaos fault drops a
  transfer mid-stream for real (partial bytes cross, the receiver
  discards them, the sender reconnects on retry).  The default
  ``wire="inproc"`` keeps the PR 18 byte-copy path untouched —
  byte-identical, zero threads, zero sockets.

The byte path is exact: with no fault armed, ``send`` returns a
payload rebuilt from the received bytes that is bit-identical to the
sent one, so the fabric's token-bit-equality gates hold with the
transport on — on either wire.
"""

from __future__ import annotations

import dataclasses
import json
import queue
import socket
import struct
import threading

import jax.numpy as jnp
import numpy as np

from flashmoe_tpu.fabric.handoff import KVPagePayload
from flashmoe_tpu.utils.integrity import crc32_pages
from flashmoe_tpu.utils.telemetry import metrics as _global_metrics

#: serving faults the transport knows how to inject (chaos matrix rows)
TRANSPORT_FAULTS = ("handoff_corrupt", "handoff_timeout",
                    "net_partition")

#: transport wire modes: in-process byte copy (default, byte-identical
#: to PR 18) vs a real localhost TCP socket pair
WIRE_MODES = ("inproc", "tcp")

#: modeled per-transfer cost of the tcp leg over inproc (connect
#: amortization + length-prefixed framing + syscall pair) — the
#: deterministic basis of the ``fabric_wire_overhead_ms`` sentry row
TCP_OVERHEAD_BASE_MS = 0.05
TCP_OVERHEAD_PER_KIB_MS = 0.0002


def wire_overhead_ms(payload_bytes: int, wire: str = "inproc") -> float:
    """Modeled extra latency of carrying one transfer on ``wire``
    versus the in-process copy (deterministic, for the perf sentry)."""
    if wire != "tcp":
        return 0.0
    return TCP_OVERHEAD_BASE_MS + (
        float(payload_bytes) / 1024.0) * TCP_OVERHEAD_PER_KIB_MS

#: the bytes a chaos corruption stamps mid-page (the checkpoint
#: tamper idiom — ``chaos._corrupt_latest_checkpoint`` flips the same)
_TAMPER = b"\xde\xad\xbe\xef"


class HandoffTransportError(RuntimeError):
    """A transfer exhausted its retry budget — the handoff failed for
    real and the caller must treat the prefill as undelivered."""


@dataclasses.dataclass(frozen=True)
class WireFrame:
    """One payload field on the wire: raw bytes + enough metadata to
    rebuild the array + the per-page CRC32 sidecar."""

    buf: bytes
    dtype: object                  # np.dtype (in-process frame)
    shape: tuple
    page_crcs: tuple

    def verify(self) -> list:
        """Indices of pages whose received bytes fail their checksum."""
        got = crc32_pages(self.buf, len(self.page_crcs))
        return [i for i, (w, g) in enumerate(zip(self.page_crcs, got))
                if w != g]


def _to_frame(arr, pages: int) -> WireFrame | None:
    if arr is None:
        return None
    host = np.asarray(arr)
    buf = host.tobytes()
    return WireFrame(buf, host.dtype, tuple(host.shape),
                     crc32_pages(buf, pages))


def _from_frame(frame: WireFrame | None):
    if frame is None:
        return None
    arr = np.frombuffer(frame.buf, dtype=frame.dtype)
    return jnp.asarray(arr.reshape(frame.shape))


def encode_frames(payload: KVPagePayload) -> dict:
    """Serialize one payload into wire frames, one per field, each with
    its per-page checksum sidecar."""
    n = max(1, payload.pages)
    return {
        "k": _to_frame(payload.k, n),
        "v": _to_frame(payload.v, n),
        "k_qscale": _to_frame(payload.k_qscale, n),
        "v_qscale": _to_frame(payload.v_qscale, n),
    }


def verify_frames(frames: dict) -> list:
    """Every ``(field, page)`` whose received bytes fail the sidecar
    checksum (empty = the transfer verified clean)."""
    bad = []
    for field, frame in frames.items():
        if frame is None:
            continue
        bad.extend((field, p) for p in frame.verify())
    return bad


def decode_frames(frames: dict, payload: KVPagePayload) -> KVPagePayload:
    """Rebuild the payload FROM THE RECEIVED BYTES (not the sender's
    arrays) — the wire is real: what the decode pool caches is what
    crossed, bit-identical only because the transfer verified."""
    return dataclasses.replace(
        payload,
        k=_from_frame(frames["k"]), v=_from_frame(frames["v"]),
        k_qscale=_from_frame(frames["k_qscale"]),
        v_qscale=_from_frame(frames["v_qscale"]))


def _tampered(frame: WireFrame) -> WireFrame:
    """Corrupt one frame's bytes mid-buffer (checksums kept — the
    RECEIVER must notice, that is the whole point)."""
    buf = frame.buf
    if not buf:
        return frame
    mid = max(0, len(buf) // 2 - len(_TAMPER))
    out = buf[:mid] + _TAMPER[:len(buf) - mid] + buf[mid + len(_TAMPER):]
    return dataclasses.replace(frame, buf=out[:len(buf)])


_LEN = struct.Struct("<I")
_WIRE_FIELDS = ("k", "v", "k_qscale", "v_qscale")


class _WireReset(OSError):
    """The kernel socket failed mid-attempt (reset / broken pipe /
    refused) — the attempt's bytes are gone; retry on a fresh
    connection."""


class _WireTimeout(OSError):
    """The receiver produced nothing inside the recv deadline."""


class _PartialTransfer(Exception):
    """The stream ended mid-transfer — the receiver drops the bytes."""


def _dtype_of(name: str):
    try:
        return np.dtype(name)
    except TypeError:
        # ml_dtypes names (bfloat16 / float8_*) are attributes, not
        # always registered dtype strings
        import ml_dtypes
        return np.dtype(getattr(ml_dtypes, name))


def _pack_frames(frames: dict) -> bytes:
    """Length-prefixed wire encoding of one transfer: a JSON header
    (field order, dtype, shape, per-page CRC sidecar, byte counts)
    followed by the raw frame buffers."""
    header, bufs = [], []
    for field in _WIRE_FIELDS:
        fr = frames.get(field)
        if fr is None:
            header.append(None)
            continue
        header.append({"field": field,
                       "dtype": np.dtype(fr.dtype).name,
                       "shape": list(fr.shape),
                       "page_crcs": list(fr.page_crcs),
                       "nbytes": len(fr.buf)})
        bufs.append(fr.buf)
    hjson = json.dumps(header).encode()
    return _LEN.pack(len(hjson)) + hjson + b"".join(bufs)


class _TcpWire:
    """The localhost TCP leg: one server socket, a receiver thread
    that rebuilds :class:`WireFrame` dicts from the byte stream, and a
    sender connection that reconnects after a reset.  Everything the
    receiver hands back came off the kernel socket — a transfer the
    stream truncates (``net_partition``, or a real peer death) is
    discarded at the first short read, never delivered."""

    def __init__(self, *, recv_timeout_s: float = 5.0):
        self.recv_timeout_s = float(recv_timeout_s)
        self._srv = socket.create_server(("127.0.0.1", 0))
        self._srv.settimeout(0.2)
        self.port = self._srv.getsockname()[1]
        self._rx: queue.Queue = queue.Queue()
        self._stop = False
        self._sock = None
        self.partial_drops = 0
        self._thread = threading.Thread(
            target=self._serve, name="flashmoe-kv-wire", daemon=True)
        self._thread.start()

    # ---- receiver thread ---------------------------------------------

    def _recv_exact(self, conn, n: int):
        chunks, got = [], 0
        while got < n:
            try:
                b = conn.recv(min(1 << 16, n - got))
            except socket.timeout:
                if self._stop:
                    return None
                continue
            except OSError:
                return None
            if not b:
                return None
            chunks.append(b)
            got += len(b)
        return b"".join(chunks)

    def _read_transfer(self, conn):
        """One transfer off the stream.  ``None`` = clean EOF before a
        transfer started; a short read mid-transfer raises
        :class:`_PartialTransfer` and the bytes are dropped."""
        raw = self._recv_exact(conn, _LEN.size)
        if raw is None:
            return None
        (hlen,) = _LEN.unpack(raw)
        hraw = self._recv_exact(conn, hlen)
        if hraw is None:
            raise _PartialTransfer
        frames = {f: None for f in _WIRE_FIELDS}
        for entry in json.loads(hraw.decode()):
            if entry is None:
                continue
            buf = self._recv_exact(conn, entry["nbytes"])
            if buf is None:
                raise _PartialTransfer
            frames[entry["field"]] = WireFrame(
                buf, _dtype_of(entry["dtype"]), tuple(entry["shape"]),
                tuple(entry["page_crcs"]))
        return frames

    def _serve(self):
        while not self._stop:
            try:
                conn, _addr = self._srv.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            conn.settimeout(0.2)
            with conn:
                while not self._stop:
                    try:
                        frames = self._read_transfer(conn)
                    except _PartialTransfer:
                        self.partial_drops += 1
                        break
                    except Exception:
                        break
                    if frames is None:
                        break
                    self._rx.put(frames)

    # ---- sender side --------------------------------------------------

    def _connect(self):
        if self._sock is None:
            self._sock = socket.create_connection(
                ("127.0.0.1", self.port), timeout=self.recv_timeout_s)
        return self._sock

    def _reset(self):
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def roundtrip(self, frames: dict) -> dict:
        """One attempt: the transfer crosses the kernel socket and the
        RECEIVER's rebuild comes back.  Raises :class:`_WireReset` on a
        send-side socket failure, :class:`_WireTimeout` when nothing
        arrives inside the deadline."""
        blob = _pack_frames(frames)
        try:
            self._connect().sendall(blob)
        except OSError as e:
            self._reset()
            raise _WireReset(str(e)) from e
        try:
            return self._rx.get(timeout=self.recv_timeout_s)
        except queue.Empty:
            self._reset()
            raise _WireTimeout(
                f"no transfer received within "
                f"{self.recv_timeout_s}s") from None

    def drop_mid_transfer(self, frames: dict,
                          fraction: float = 0.5) -> int:
        """``net_partition`` injection: push a partial transfer, then
        hard-close the connection.  The partial bytes REALLY cross the
        kernel socket and the receiver REALLY discards them at the
        short read — returns the bytes that never made it."""
        blob = _pack_frames(frames)
        cut = max(1, min(len(blob) - 1, int(len(blob) * fraction)))
        try:
            self._connect().sendall(blob[:cut])
        except OSError:
            pass
        self._reset()
        return len(blob) - cut

    def close(self):
        self._stop = True
        self._reset()
        try:
            self._srv.close()
        except OSError:
            pass
        self._thread.join(timeout=2.0)

    def snapshot(self) -> dict:
        return {"port": self.port,
                "partial_drops": self.partial_drops,
                "recv_timeout_s": self.recv_timeout_s}


@dataclasses.dataclass(frozen=True)
class TransferResult:
    """What one :meth:`HandoffTransport.send` experienced."""

    payload: KVPagePayload         # rebuilt from the received bytes
    attempts: int
    retries: int
    corrupt_pages: int
    timeouts: int
    retry_ms: float                # wasted wire time + backoff, priced
                                   # through the vclock by the caller


class HandoffTransport:
    """In-process transport with wire failure semantics.

    ``max_retries``: retry budget per transfer (attempts beyond
    ``1 + max_retries`` raise :class:`HandoffTransportError`).
    ``timeout_ms``: the per-attempt deadline — an injected
    ``handoff_timeout`` attempt stalls for exactly this long before it
    is abandoned.  ``backoff_ms`` / ``backoff_cap_ms``: capped
    exponential backoff between attempts (``min(cap, base * 2**(n-1))``
    after the n-th failure).  ``plan``: an armed
    :class:`~flashmoe_tpu.chaos.FaultPlan` whose fault is one of
    :data:`TRANSPORT_FAULTS`.  ``tamper_fn``: test seam — a callable
    ``(transfer_index, attempt) -> bool`` that forces corruption on a
    given attempt (the CRC tamper drill).  ``wire``: one of
    :data:`WIRE_MODES` — ``"tcp"`` carries every transfer over a real
    localhost socket (close the transport when done)."""

    def __init__(self, *, metrics_obj=None, max_retries: int = 2,
                 timeout_ms: float = 50.0, backoff_ms: float = 5.0,
                 backoff_cap_ms: float = 40.0, plan=None,
                 tamper_fn=None, wire: str = "inproc"):
        if plan is not None and plan.fault not in TRANSPORT_FAULTS:
            raise ValueError(
                f"HandoffTransport only injects {TRANSPORT_FAULTS}, "
                f"got plan fault {plan.fault!r}")
        if max_retries < 0:
            raise ValueError(f"max_retries must be >= 0, "
                             f"got {max_retries}")
        if wire not in WIRE_MODES:
            raise ValueError(f"wire must be one of {WIRE_MODES}, "
                             f"got {wire!r}")
        self.metrics = (metrics_obj if metrics_obj is not None
                        else _global_metrics)
        self.max_retries = int(max_retries)
        self.timeout_ms = float(timeout_ms)
        self.backoff_ms = float(backoff_ms)
        self.backoff_cap_ms = float(backoff_cap_ms)
        self.plan = plan
        self.tamper_fn = tamper_fn
        self.wire = wire
        self._wire = _TcpWire() if wire == "tcp" else None
        self.transfers = 0
        self.retries_total = 0
        self.corrupt_total = 0
        self.timeout_total = 0
        self.partition_total = 0
        self.reset_total = 0
        self.retry_ms_total = 0.0

    # ---- chaos --------------------------------------------------------

    def _fault(self, index: int, attempt: int) -> str | None:
        """Which fault (if any) hits this attempt.  Chaos fires on the
        first attempt of every transfer in the plan window; with
        ``plan.once`` (default) the retry is clean, else every attempt
        fails until the budget gives up."""
        if self.tamper_fn is not None \
                and self.tamper_fn(index, attempt):
            return "handoff_corrupt"
        p = self.plan
        if p is None:
            return None
        if not (p.step <= index < p.step + p.duration):
            return None
        if attempt > 1 and p.once:
            return None
        return p.fault

    def _backoff(self, failures: int) -> float:
        return min(self.backoff_cap_ms,
                   self.backoff_ms * (2.0 ** (failures - 1)))

    # ---- the wire -----------------------------------------------------

    def _transmit(self, frames: dict, *, tamper: bool) -> dict:
        """One attempt: the frames cross the wire — an in-process copy
        by default, the kernel socket under ``wire="tcp"``.  A tampered
        attempt corrupts the largest frame's bytes BEFORE they ship —
        the sidecar checksums ride untouched (in the tcp header), so
        the receiver's verify catches it."""
        tx = frames
        if tamper:
            victim, size = None, -1
            for field, frame in frames.items():
                if frame is not None and len(frame.buf) > size:
                    victim, size = field, len(frame.buf)
            tx = dict(frames)
            if victim is not None:
                tx[victim] = _tampered(tx[victim])
        if self._wire is None:
            return tx
        return self._wire.roundtrip(tx)

    def send(self, payload: KVPagePayload, *, modeled_ms: float = 0.0,
             rid=None, replica: int = 0) -> TransferResult:
        """Move one payload across the wire with verify + retry.
        Returns the payload rebuilt from the received (verified) bytes
        plus the transfer's failure accounting."""
        frames = encode_frames(payload)
        index = self.transfers
        self.transfers += 1
        attempts = 0
        retry_ms = 0.0
        corrupt_pages = 0
        timeouts = 0
        rx = frames
        while True:
            attempts += 1
            fault = self._fault(index, attempts)
            if fault == "handoff_timeout":
                # the attempt never completes: pay the full deadline,
                # back off, retransmit
                timeouts += 1
                self.timeout_total += 1
                back = self._backoff(attempts)
                retry_ms += self.timeout_ms + back
                self.metrics.count("fabric.handoff_retries")
                self.metrics.decision(
                    "fabric.handoff_retry", rid=rid,
                    replica=int(replica), transfer=index,
                    attempt=attempts, reason="timeout",
                    wasted_ms=round(self.timeout_ms, 6),
                    backoff_ms=round(back, 6),
                    budget_left=self.max_retries - (attempts - 1) - 1)
                self._check_budget(attempts, index, rid, replica,
                                   "timeout")
                continue
            if fault == "net_partition":
                # the wire drops mid-transfer: on tcp, partial bytes
                # REALLY cross and the receiver REALLY discards them;
                # inproc models the same drop.  Either way the
                # attempt's modeled wire time was wasted.
                dropped = (self._wire.drop_mid_transfer(frames)
                           if self._wire is not None else None)
                self.partition_total += 1
                back = self._backoff(attempts)
                retry_ms += float(modeled_ms) + back
                self.metrics.count("fabric.partitions")
                self.metrics.decision(
                    "fabric.partition", rid=rid, replica=int(replica),
                    transfer=index, attempt=attempts, wire=self.wire,
                    dropped_bytes=dropped, injected=True)
                self.metrics.count("fabric.handoff_retries")
                self.metrics.decision(
                    "fabric.handoff_retry", rid=rid,
                    replica=int(replica), transfer=index,
                    attempt=attempts, reason="reset",
                    wasted_ms=round(float(modeled_ms), 6),
                    backoff_ms=round(back, 6),
                    budget_left=self.max_retries - (attempts - 1) - 1)
                self._check_budget(attempts, index, rid, replica,
                                   "reset")
                continue
            try:
                rx = self._transmit(
                    frames, tamper=(fault == "handoff_corrupt"))
            except _WireReset as e:
                # a REAL kernel-socket failure (connection reset,
                # broken pipe, partial write) — the same ladder as the
                # injected partition
                self.reset_total += 1
                back = self._backoff(attempts)
                retry_ms += float(modeled_ms) + back
                self.metrics.count("fabric.partitions")
                self.metrics.decision(
                    "fabric.partition", rid=rid, replica=int(replica),
                    transfer=index, attempt=attempts, wire=self.wire,
                    dropped_bytes=None, injected=False,
                    error=str(e)[:80])
                self.metrics.count("fabric.handoff_retries")
                self.metrics.decision(
                    "fabric.handoff_retry", rid=rid,
                    replica=int(replica), transfer=index,
                    attempt=attempts, reason="reset",
                    wasted_ms=round(float(modeled_ms), 6),
                    backoff_ms=round(back, 6),
                    budget_left=self.max_retries - (attempts - 1) - 1)
                self._check_budget(attempts, index, rid, replica,
                                   "reset")
                continue
            except _WireTimeout:
                # a REAL recv deadline: the receiver produced nothing
                timeouts += 1
                self.timeout_total += 1
                back = self._backoff(attempts)
                retry_ms += self.timeout_ms + back
                self.metrics.count("fabric.handoff_retries")
                self.metrics.decision(
                    "fabric.handoff_retry", rid=rid,
                    replica=int(replica), transfer=index,
                    attempt=attempts, reason="timeout",
                    wasted_ms=round(self.timeout_ms, 6),
                    backoff_ms=round(back, 6),
                    budget_left=self.max_retries - (attempts - 1) - 1)
                self._check_budget(attempts, index, rid, replica,
                                   "timeout")
                continue
            bad = verify_frames(rx)
            if bad:
                # garbage crossed the wire: the bytes were paid for,
                # the checksum refused them at the receiver
                corrupt_pages += len(bad)
                self.corrupt_total += len(bad)
                self.metrics.count("fabric.handoff_corrupts")
                self.metrics.decision(
                    "fabric.handoff_corrupt", rid=rid,
                    replica=int(replica), transfer=index,
                    attempt=attempts, bad_pages=bad[:4],
                    bad_page_count=len(bad))
                back = self._backoff(attempts)
                retry_ms += float(modeled_ms) + back
                self.metrics.count("fabric.handoff_retries")
                self.metrics.decision(
                    "fabric.handoff_retry", rid=rid,
                    replica=int(replica), transfer=index,
                    attempt=attempts, reason="corrupt",
                    wasted_ms=round(float(modeled_ms), 6),
                    backoff_ms=round(back, 6),
                    budget_left=self.max_retries - (attempts - 1) - 1)
                self._check_budget(attempts, index, rid, replica,
                                   "corrupt")
                continue
            break
        retries = attempts - 1
        self.retries_total += retries
        self.retry_ms_total += retry_ms
        if retry_ms:
            self.metrics.sketch("fabric.handoff_retry_ms", retry_ms)
        return TransferResult(
            payload=decode_frames(rx, payload), attempts=attempts,
            retries=retries, corrupt_pages=corrupt_pages,
            timeouts=timeouts, retry_ms=retry_ms)

    def _check_budget(self, attempts: int, index: int, rid, replica,
                      reason: str) -> None:
        if attempts >= 1 + self.max_retries:
            raise HandoffTransportError(
                f"KV handoff transfer {index} (rid={rid}, replica="
                f"{replica}) failed after {attempts} attempts "
                f"({reason}); retry budget max_retries="
                f"{self.max_retries} exhausted")

    def close(self) -> None:
        """Tear down the wire (tcp: close sockets, join the receiver
        thread).  Safe to call twice; a no-op for ``inproc``."""
        if self._wire is not None:
            self._wire.close()
            self._wire = None

    def snapshot(self) -> dict:
        """Live ``/vars`` view of the transport."""
        return {
            "transfers": self.transfers,
            "retries_total": self.retries_total,
            "corrupt_total": self.corrupt_total,
            "timeout_total": self.timeout_total,
            "partition_total": self.partition_total,
            "reset_total": self.reset_total,
            "retry_ms_total": round(self.retry_ms_total, 6),
            "max_retries": self.max_retries,
            "timeout_ms": self.timeout_ms,
            "wire": self.wire,
            "wire_drops": (self._wire.partial_drops
                           if self._wire is not None else 0),
            "fault": (self.plan.fault if self.plan is not None
                      else None),
        }
