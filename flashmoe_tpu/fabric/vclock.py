"""Deterministic virtual clock for the serving fabric.

Every latency number the fabric used to report was either wall-clock
host time (real, but noisy and DCN-free: the handoff codec runs
in-process) or a priced model (`kv_handoff_ms`, deterministic but never
*experienced* by a request).  :class:`VirtualClock` closes the gap: the
fabric steps on virtual time, the handoff ADVANCES that time by its
modeled DCN cost (plus optional chaos latency/jitter from a
:class:`~flashmoe_tpu.chaos.FaultPlan`), and every TTFT/TPOT/step
measurement the engine takes through its ``clock`` seam is therefore
*measured under* the delay the model priced — so the overlap verdict
becomes a measured quantity (``fabric.handoff_drift`` reconciles it
against the priced one per transfer).

Semantics (per decode replica = per **lane**, because the fabric steps
its replicas sequentially on one host thread while the real fleet runs
them in parallel):

* each engine step costs one decode **tick** (``tick_ms``, resolved
  from ``PoolPlan.decode_ms`` by the fabric) of lane time;
* a handoff advances the active lane by its measured DCN cost
  *immediately* (inside the ``serve.handoff`` span, so the request's
  own prefill span absorbs the wait);
* at the end of the step the engine advances the lane by
  ``max(0, tick - handoff_ms_this_step)`` — total virtual step
  duration ``max(tick, handoffs)``, i.e. transfers overlap the decode
  tick and only the *exposed* remainder stretches the step.

Per-transfer accounting: with ``H`` the handoff time already spent
this step, a transfer of ``m`` ms hides ``min(m, max(0, tick - H))``
and exposes the rest.  With ``tick = PoolPlan.decode_ms``, no chaos
and one transfer per step this reproduces the priced verdict
``m <= decode_ms`` exactly — the reconciliation invariant
``tests/test_fabric.py`` gates.

``VirtualClock`` is callable and returns SECONDS (the
``time.monotonic`` protocol), so it drops into every existing clock
seam.  Determinism: no wall reads, no randomness — chaos jitter is a
crc32 hash of ``(plan.seed, transfer index)``.
"""

from __future__ import annotations

import zlib

#: faults a VirtualClock knows how to inject (chaos drill matrix rows)
DCN_FAULTS = ("dcn_latency", "dcn_jitter")


class VirtualClock:
    """Callable virtual clock with one lane per decode replica.

    ``tick_ms``: virtual cost of one engine step (``None`` = resolved
    later by the fabric from its pool plan, fallback 1.0).
    ``lanes``: replica count (grown on demand via :meth:`ensure_lanes`).
    ``plan``: an optional armed :class:`~flashmoe_tpu.chaos.FaultPlan`
    whose fault is one of :data:`DCN_FAULTS` — it perturbs transfers
    in ``[plan.step, plan.step + plan.duration)`` (transfer index, not
    engine step) by ``plan.latency_ms`` / a deterministic jitter in
    ``[0, plan.jitter_ms]``."""

    def __init__(self, *, tick_ms: float | None = None, lanes: int = 1,
                 plan=None):
        if plan is not None and plan.fault not in DCN_FAULTS:
            raise ValueError(
                f"VirtualClock only injects {DCN_FAULTS}, got plan "
                f"fault {plan.fault!r}")
        self.tick_ms = tick_ms
        self.plan = plan
        self._lane_s = [0.0] * max(1, int(lanes))
        self._step_handoff_ms = [0.0] * len(self._lane_s)
        self._active = 0
        self._handoffs = 0
        #: per-transfer measured accounting (what handoff_drift records)
        self.transfers: list[dict] = []

    # ---- lanes --------------------------------------------------------

    def ensure_lanes(self, n: int) -> None:
        while len(self._lane_s) < n:
            self._lane_s.append(0.0)
            self._step_handoff_ms.append(0.0)

    def use_lane(self, i: int) -> None:
        """Make lane ``i`` the active one — the fabric calls this
        before stepping replica ``i`` (single-threaded, so the shared
        tracer's timestamps read replica-local time)."""
        self.ensure_lanes(int(i) + 1)
        self._active = int(i)

    @property
    def active_lane(self) -> int:
        return self._active

    # ---- the time.monotonic protocol ---------------------------------

    def __call__(self) -> float:
        return self._lane_s[self._active]

    def now_ms(self) -> float:
        return self._lane_s[self._active] * 1e3

    def advance_ms(self, ms: float) -> None:
        self._lane_s[self._active] += float(ms) / 1e3

    # ---- chaos --------------------------------------------------------

    def _chaos_ms(self, index: int) -> float:
        p = self.plan
        if p is None:
            return 0.0
        if not (p.step <= index < p.step + p.duration):
            return 0.0
        if p.fault == "dcn_latency":
            return float(p.latency_ms)
        # dcn_jitter: deterministic fraction of jitter_ms per transfer
        frac = (zlib.crc32(f"{p.seed}:{index}".encode()) % 10007) / 10006.0
        return float(p.jitter_ms) * frac

    # ---- fabric hooks -------------------------------------------------

    def on_handoff(self, modeled_ms: float, *, rid=None,
                   replica=None, extra_ms: float = 0.0) -> dict:
        """One KV-page transfer lands on the active lane: advance by
        the measured cost (modeled + chaos + ``extra_ms``) and account
        how much of it hides under the remaining decode-tick budget.
        ``extra_ms`` is transport overhead the wire actually spent —
        retry retransmissions and backoff
        (:class:`~flashmoe_tpu.fabric.transport.HandoffTransport`) —
        so a retried handoff is *experienced* by the request's TTFT,
        not just counted.  Returns the per-transfer accounting dict
        (also kept in :attr:`transfers`)."""
        index = self._handoffs
        self._handoffs += 1
        chaos = self._chaos_ms(index)
        measured = float(modeled_ms) + chaos + float(extra_ms)
        tick = float(self.tick_ms) if self.tick_ms is not None else 0.0
        lane = self._active
        budget = max(0.0, tick - self._step_handoff_ms[lane])
        hidden = min(measured, budget)
        exposed = measured - hidden
        self._step_handoff_ms[lane] += measured
        self.advance_ms(measured)
        acct = {
            "index": index, "rid": rid,
            "replica": (int(replica) if replica is not None else None),
            "lane": lane,
            "modeled_ms": round(float(modeled_ms), 6),
            "chaos_ms": round(chaos, 6),
            "retry_ms": round(float(extra_ms), 6),
            "measured_ms": round(measured, 6),
            "hidden_ms": round(hidden, 6),
            "exposed_ms": round(exposed, 6),
            "tick_ms": round(tick, 6),
        }
        self.transfers.append(acct)
        return acct

    def complete_step(self) -> float:
        """The engine finished one step on the active lane: advance by
        the decode tick MINUS the handoff time the step already spent
        (never negative — a handoff-saturated step is stretched by its
        transfers, not double-billed).  Returns the idle advance."""
        tick = float(self.tick_ms) if self.tick_ms is not None else 0.0
        lane = self._active
        idle = max(0.0, tick - self._step_handoff_ms[lane])
        if idle:
            self.advance_ms(idle)
        self._step_handoff_ms[lane] = 0.0
        return idle

    # ---- rollups ------------------------------------------------------

    @property
    def measured_ms_total(self) -> float:
        return sum(t["measured_ms"] for t in self.transfers)

    @property
    def hidden_ms_total(self) -> float:
        return sum(t["hidden_ms"] for t in self.transfers)

    def hidden_fraction(self) -> float | None:
        """Fleet-wide measured hidden fraction (None = no transfers)."""
        total = self.measured_ms_total
        if total <= 0:
            return None if not self.transfers else 1.0
        return self.hidden_ms_total / total

    def snapshot(self) -> dict:
        """Live ``/vars`` view."""
        hf = self.hidden_fraction()
        return {
            "tick_ms": self.tick_ms,
            "lanes": len(self._lane_s),
            "lane_s": [round(s, 9) for s in self._lane_s],
            "transfers": len(self.transfers),
            "measured_ms_total": round(self.measured_ms_total, 6),
            "hidden_ms_total": round(self.hidden_ms_total, 6),
            "hidden_fraction": (round(hf, 6) if hf is not None else None),
            "fault": (self.plan.fault if self.plan is not None else None),
        }
