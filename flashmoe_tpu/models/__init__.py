"""Model family: dense-math reference oracle and the MoE transformer."""
