"""Autoregressive generation with a KV cache.

Inference support for the flagship transformer (the reference is
forward-only over random tensors; a complete framework serves models).
Decode runs as a ``lax.scan`` over steps with a static-shape KV cache —
one token per step through the same parameter tree as training, MoE layers
included (top-k routing per decoded token).
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

from flashmoe_tpu.config import MoEConfig
from flashmoe_tpu.models.transformer import _rope, rms_norm
from flashmoe_tpu.ops.attention import attention_xla
from flashmoe_tpu.ops.moe import moe_layer


class KVCache(NamedTuple):
    k: jax.Array  # [L, B, N_kv, T_max, D]
    v: jax.Array


def init_cache(cfg: MoEConfig, batch: int, max_len: int) -> KVCache:
    nkv, dh = cfg.resolved_num_kv_heads, cfg.resolved_head_dim
    shape = (cfg.num_layers, batch, nkv, max_len, dh)
    return KVCache(jnp.zeros(shape, cfg.dtype), jnp.zeros(shape, cfg.dtype))


def _decode_step(params, cfg: MoEConfig, x, cache: KVCache, pos):
    """One token through all layers. x: [B, 1, H]; pos: [] current index."""
    b = x.shape[0]
    nh, nkv, dh = cfg.num_heads, cfg.resolved_num_kv_heads, cfg.resolved_head_dim
    new_k, new_v = [], []
    for li, layer in enumerate(params["layers"]):
        h_in = rms_norm(x, layer["attn_norm"])
        q = (h_in @ layer["wq"].astype(x.dtype)).reshape(b, 1, nh, dh)
        k = (h_in @ layer["wk"].astype(x.dtype)).reshape(b, 1, nkv, dh)
        v = (h_in @ layer["wv"].astype(x.dtype)).reshape(b, 1, nkv, dh)
        positions = jnp.broadcast_to(pos[None, None], (b, 1))
        q, k = _rope(q, k, positions, cfg.rope_theta)

        ck = jax.lax.dynamic_update_slice(
            cache.k[li], k.transpose(0, 2, 1, 3), (0, 0, pos, 0)
        )
        cv = jax.lax.dynamic_update_slice(
            cache.v[li], v.transpose(0, 2, 1, 3), (0, 0, pos, 0)
        )
        new_k.append(ck)
        new_v.append(cv)

        kk, vv = ck, cv
        if nkv != nh:
            rep = nh // nkv
            kk = jnp.repeat(kk, rep, axis=1)
            vv = jnp.repeat(vv, rep, axis=1)
        qh = q.transpose(0, 2, 1, 3)  # [B, N, 1, D]
        t_max = kk.shape[2]
        logits = jnp.einsum(
            "bntd,bnsd->bnts", qh, kk, preferred_element_type=jnp.float32
        ) * (dh ** -0.5)
        mask = (jnp.arange(t_max) <= pos)[None, None, None, :]
        logits = jnp.where(mask, logits, -1e30)
        probs = jax.nn.softmax(logits, axis=-1).astype(x.dtype)
        ctx = jnp.einsum(
            "bnts,bnsd->bntd", probs, vv, preferred_element_type=jnp.float32
        ).transpose(0, 2, 1, 3).reshape(b, 1, nh * dh).astype(x.dtype)
        x = x + ctx @ layer["wo"].astype(x.dtype)

        f_in = rms_norm(x, layer["ffn_norm"])
        layer_cfg = cfg if li in cfg.moe_layer_indices else cfg.replace(
            num_experts=1, expert_top_k=1, num_shared_experts=0
        )
        o = moe_layer(
            layer["moe"], f_in.reshape(b, -1), layer_cfg, use_pallas=False
        )
        x = x + o.out.reshape(b, 1, -1).astype(x.dtype)

    cache = KVCache(jnp.stack(new_k), jnp.stack(new_v))
    h = rms_norm(x, params["final_norm"])
    logits = jnp.dot(
        h.astype(cfg.dtype), params["lm_head"].astype(cfg.dtype),
        preferred_element_type=jnp.float32,
    )[:, 0]  # [B, V]
    return logits, cache


@functools.partial(
    jax.jit, static_argnames=("cfg", "max_new_tokens", "temperature"),
)
def generate(params, prompt, cfg: MoEConfig, *, max_new_tokens: int = 32,
             temperature: float = 0.0, key=None):
    """Greedy (temperature=0) or sampled decoding.

    prompt: [B, T0] int32.  Returns [B, T0 + max_new_tokens].
    """
    b, t0 = prompt.shape
    max_len = t0 + max_new_tokens
    cache = init_cache(cfg, b, max_len)
    key = key if key is not None else jax.random.PRNGKey(0)

    # prefill one token at a time (simple, correct; batched prefill is an
    # optimization for later rounds)
    def prefill(i, carry):
        cache, _ = carry
        x = params["embed"].astype(cfg.dtype)[prompt[:, i]][:, None, :]
        logits, cache = _decode_step(params, cfg, x, cache, i)
        return cache, logits

    cache, logits = jax.lax.fori_loop(
        0, t0, prefill, (cache, jnp.zeros((b, cfg.vocab_size), jnp.float32))
    )

    def sample(logits, k):
        if temperature == 0.0:
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)
        return jax.random.categorical(
            k, logits / temperature, axis=-1
        ).astype(jnp.int32)

    def step(carry, i):
        cache, logits, key = carry
        key, sub = jax.random.split(key)
        tok = sample(logits, sub)
        x = params["embed"].astype(cfg.dtype)[tok][:, None, :]
        logits, cache = _decode_step(params, cfg, x, cache, t0 + i)
        return (cache, logits, key), tok

    (_, logits, _), toks = jax.lax.scan(
        step, (cache, logits, key), jnp.arange(max_new_tokens)
    )
    return jnp.concatenate([prompt, toks.T], axis=1)
