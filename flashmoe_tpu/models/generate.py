"""Autoregressive generation with a KV cache.

Inference support for the flagship transformer (the reference is
forward-only over random tensors; a complete framework serves models).
Decode runs as a ``lax.scan`` over steps with a static-shape KV cache —
one token per step through the same parameter tree as training, MoE layers
included (top-k routing per decoded token).

Prefill has two arms: the original one-token-at-a-time ``fori_loop``
(the fallback — exact drop semantics for capacity configs) and a batched
single-pass prefill (full-sequence forward with a causal mask writing
the whole cache in one shot — one kernel launch chain instead of T0).
``prefill='auto'`` picks batched for dropless configs, where the two
arms are logits-equal (asserted by tests/test_generate.py), and the
loop for ``drop_tokens=True`` configs, whose capacity competition is
per-step by construction.

Sampling supports greedy, temperature, top-k and nucleus (top-p)
truncation, plus per-request stop tokens — the retirement primitive the
continuous-batching engine (:mod:`flashmoe_tpu.serving.engine`) builds
on.  :func:`sample_tokens` is shared with that engine so the two
samplers cannot drift.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

from flashmoe_tpu.config import MoEConfig
from flashmoe_tpu.models.transformer import _rope, rms_norm
from flashmoe_tpu.ops.attention import attention_xla
from flashmoe_tpu.ops.moe import moe_layer


class KVCache(NamedTuple):
    k: jax.Array  # [L, B, N_kv, T_max, D]
    v: jax.Array


def init_cache(cfg: MoEConfig, batch: int, max_len: int) -> KVCache:
    nkv, dh = cfg.resolved_num_kv_heads, cfg.resolved_head_dim
    shape = (cfg.num_layers, batch, nkv, max_len, dh)
    return KVCache(jnp.zeros(shape, cfg.dtype), jnp.zeros(shape, cfg.dtype))


def _layer_cfg(cfg: MoEConfig, li: int) -> MoEConfig:
    return cfg if li in cfg.moe_layer_indices else cfg.replace(
        num_experts=1, expert_top_k=1, num_shared_experts=0
    )


def _decode_step(params, cfg: MoEConfig, x, cache: KVCache, pos):
    """One token through all layers. x: [B, 1, H]; pos: [] current index."""
    b = x.shape[0]
    nh, nkv, dh = cfg.num_heads, cfg.resolved_num_kv_heads, cfg.resolved_head_dim
    new_k, new_v = [], []
    for li, layer in enumerate(params["layers"]):
        h_in = rms_norm(x, layer["attn_norm"])
        q = (h_in @ layer["wq"].astype(x.dtype)).reshape(b, 1, nh, dh)
        k = (h_in @ layer["wk"].astype(x.dtype)).reshape(b, 1, nkv, dh)
        v = (h_in @ layer["wv"].astype(x.dtype)).reshape(b, 1, nkv, dh)
        positions = jnp.broadcast_to(pos[None, None], (b, 1))
        q, k = _rope(q, k, positions, cfg.rope_theta)

        ck = jax.lax.dynamic_update_slice(
            cache.k[li], k.transpose(0, 2, 1, 3), (0, 0, pos, 0)
        )
        cv = jax.lax.dynamic_update_slice(
            cache.v[li], v.transpose(0, 2, 1, 3), (0, 0, pos, 0)
        )
        new_k.append(ck)
        new_v.append(cv)

        kk, vv = ck, cv
        if nkv != nh:
            rep = nh // nkv
            kk = jnp.repeat(kk, rep, axis=1)
            vv = jnp.repeat(vv, rep, axis=1)
        qh = q.transpose(0, 2, 1, 3)  # [B, N, 1, D]
        t_max = kk.shape[2]
        logits = jnp.einsum(
            "bntd,bnsd->bnts", qh, kk, preferred_element_type=jnp.float32
        ) * (dh ** -0.5)
        mask = (jnp.arange(t_max) <= pos)[None, None, None, :]
        logits = jnp.where(mask, logits, -1e30)
        probs = jax.nn.softmax(logits, axis=-1).astype(x.dtype)
        ctx = jnp.einsum(
            "bnts,bnsd->bntd", probs, vv, preferred_element_type=jnp.float32
        ).transpose(0, 2, 1, 3).reshape(b, 1, nh * dh).astype(x.dtype)
        x = x + ctx @ layer["wo"].astype(x.dtype)

        f_in = rms_norm(x, layer["ffn_norm"])
        o = moe_layer(
            layer["moe"], f_in.reshape(b, -1), _layer_cfg(cfg, li),
            use_pallas=False
        )
        x = x + o.out.reshape(b, 1, -1).astype(x.dtype)

    cache = KVCache(jnp.stack(new_k), jnp.stack(new_v))
    h = rms_norm(x, params["final_norm"])
    logits = jnp.dot(
        h.astype(cfg.dtype), params["lm_head"].astype(cfg.dtype),
        preferred_element_type=jnp.float32,
    )[:, 0]  # [B, V]
    return logits, cache


def prefill_forward(params, cfg: MoEConfig, prompt, cache: KVCache):
    """Single-pass prefill core: the full prompt through every layer at
    once, causal-masked, writing the KV cache in one shot.

    prompt: [B, T0] int32.  Returns (x [B, T0, H] pre-final-norm hidden
    states, cache with positions [0, T0) filled).  Mirrors
    :func:`_decode_step`'s per-layer arithmetic with T0 query positions
    so the two prefill arms stay logits-equal on dropless configs
    (capacity configs compete for slots per call, so their drop
    pattern is step-count-dependent — use the loop arm there).
    Exposed separately from :func:`prefill_batched` because the serving
    engine prefills PADDED prompts and needs the hidden state at a
    dynamic true-length index, not the last row.
    """
    b, t0 = prompt.shape
    nh, nkv, dh = cfg.num_heads, cfg.resolved_num_kv_heads, cfg.resolved_head_dim
    x = params["embed"].astype(cfg.dtype)[prompt]  # [B, T0, H]
    positions = jnp.broadcast_to(jnp.arange(t0)[None, :], (b, t0))
    new_k, new_v = [], []
    for li, layer in enumerate(params["layers"]):
        h_in = rms_norm(x, layer["attn_norm"])
        q = (h_in @ layer["wq"].astype(x.dtype)).reshape(b, t0, nh, dh)
        k = (h_in @ layer["wk"].astype(x.dtype)).reshape(b, t0, nkv, dh)
        v = (h_in @ layer["wv"].astype(x.dtype)).reshape(b, t0, nkv, dh)
        q, k = _rope(q, k, positions, cfg.rope_theta)

        ck = jax.lax.dynamic_update_slice(
            cache.k[li], k.transpose(0, 2, 1, 3), (0, 0, 0, 0)
        )
        cv = jax.lax.dynamic_update_slice(
            cache.v[li], v.transpose(0, 2, 1, 3), (0, 0, 0, 0)
        )
        new_k.append(ck)
        new_v.append(cv)

        kk, vv = ck, cv
        if nkv != nh:
            rep = nh // nkv
            kk = jnp.repeat(kk, rep, axis=1)
            vv = jnp.repeat(vv, rep, axis=1)
        qh = q.transpose(0, 2, 1, 3)  # [B, N, T0, D]
        t_max = kk.shape[2]
        logits = jnp.einsum(
            "bntd,bnsd->bnts", qh, kk, preferred_element_type=jnp.float32
        ) * (dh ** -0.5)
        mask = (jnp.arange(t_max)[None, None, None, :]
                <= positions[:, None, :, None])
        logits = jnp.where(mask, logits, -1e30)
        probs = jax.nn.softmax(logits, axis=-1).astype(x.dtype)
        ctx = jnp.einsum(
            "bnts,bnsd->bntd", probs, vv, preferred_element_type=jnp.float32
        ).transpose(0, 2, 1, 3).reshape(b, t0, nh * dh).astype(x.dtype)
        x = x + ctx @ layer["wo"].astype(x.dtype)

        f_in = rms_norm(x, layer["ffn_norm"])
        o = moe_layer(
            layer["moe"], f_in.reshape(b * t0, -1), _layer_cfg(cfg, li),
            use_pallas=False
        )
        x = x + o.out.reshape(b, t0, -1).astype(x.dtype)

    return x, KVCache(jnp.stack(new_k), jnp.stack(new_v))


def lm_logits(params, cfg: MoEConfig, h):
    """Final-norm + lm_head on [B, 1, H] hidden states -> [B, V] f32
    (the exact tail :func:`_decode_step` applies, shared so every
    consumer produces bit-identical logits from the same hidden)."""
    h = rms_norm(h, params["final_norm"])
    return jnp.dot(
        h.astype(cfg.dtype), params["lm_head"].astype(cfg.dtype),
        preferred_element_type=jnp.float32,
    )[:, 0]  # [B, V]


def lm_logits_span(params, cfg: MoEConfig, h):
    """The multi-position twin of :func:`lm_logits`: final-norm +
    lm_head over a [B, T, H] hidden SPAN -> [B, T, V] f32.  The serving
    engine's speculative verify step (ISSUE 20) scores ``k+1`` drafted
    positions per slot in one forward and needs the lm head at every
    one of them; sharing the tail here keeps each column bit-identical
    to what :func:`lm_logits` produces from the same hidden row."""
    h = rms_norm(h, params["final_norm"])
    return jnp.dot(
        h.astype(cfg.dtype), params["lm_head"].astype(cfg.dtype),
        preferred_element_type=jnp.float32,
    )  # [B, T, V]


def prefill_batched(params, cfg: MoEConfig, prompt, cache: KVCache):
    """Single-pass prefill: :func:`prefill_forward` + the lm head on
    the LAST prompt position.  Returns (logits [B, V], filled cache)."""
    x, cache = prefill_forward(params, cfg, prompt, cache)
    return lm_logits(params, cfg, x[:, -1:]), cache


def prefill_loop(params, cfg: MoEConfig, prompt, cache: KVCache):
    """One-token-at-a-time prefill (the original arm): exact per-step
    capacity semantics, T0 sequential launches."""
    b, t0 = prompt.shape

    def body(i, carry):
        cache, _ = carry
        x = params["embed"].astype(cfg.dtype)[prompt[:, i]][:, None, :]
        logits, cache = _decode_step(params, cfg, x, cache, i)
        return cache, logits

    cache, logits = jax.lax.fori_loop(
        0, t0, body, (cache, jnp.zeros((b, cfg.vocab_size), jnp.float32))
    )
    return logits, cache


def sample_tokens(logits, key, *, temperature: float = 0.0,
                  top_k: int = 0, top_p: float = 1.0):
    """Sample next tokens from [B, V] f32 logits -> [B] int32.

    ``temperature=0`` is greedy (argmax; ``key`` unused).  ``top_k > 0``
    truncates to the k highest logits; ``top_p < 1`` applies nucleus
    truncation (smallest prefix of the sorted distribution whose mass
    reaches ``top_p`` — the top token always survives).  Truncations
    compose (top-k first, then top-p over the survivors).  Shared by
    :func:`generate` and the serving engine's per-request sampler, so
    the two can never drift."""
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    if not 0 < top_p <= 1.0:
        raise ValueError(f"top_p={top_p} must be in (0, 1]")
    if top_k < 0:
        raise ValueError(f"top_k={top_k} must be >= 0")
    logits = logits.astype(jnp.float32) / temperature
    neg = jnp.asarray(-1e30, logits.dtype)
    if top_k and top_k < logits.shape[-1]:
        kth = jnp.sort(logits, axis=-1)[:, -top_k][:, None]
        logits = jnp.where(logits < kth, neg, logits)
    if top_p < 1.0:
        sorted_desc = jnp.sort(logits, axis=-1)[:, ::-1]
        probs = jax.nn.softmax(sorted_desc, axis=-1)
        csum = jnp.cumsum(probs, axis=-1)
        # keep entries whose preceding mass is < top_p (the argmax has
        # preceding mass 0, so at least one entry always survives)
        keep = (csum - probs) < top_p
        thresh = jnp.min(
            jnp.where(keep, sorted_desc, jnp.inf), axis=-1, keepdims=True)
        logits = jnp.where(logits < thresh, neg, logits)
    return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)


@functools.partial(
    jax.jit, static_argnames=("cfg", "max_new_tokens", "temperature",
                              "top_k", "top_p", "stop_tokens",
                              "pad_token", "prefill"),
)
def generate(params, prompt, cfg: MoEConfig, *, max_new_tokens: int = 32,
             temperature: float = 0.0, top_k: int = 0, top_p: float = 1.0,
             stop_tokens: tuple = (), pad_token: int = 0, key=None,
             prefill: str = "auto"):
    """Greedy (temperature=0) or sampled decoding.

    prompt: [B, T0] int32.  Returns [B, T0 + max_new_tokens].

    ``stop_tokens``: static tuple of token ids that retire a row — the
    stop token itself is emitted, every later position is
    ``pad_token`` and the retired row's cache stops influencing its
    outputs (other rows are unaffected).  ``prefill``: 'batched' (one
    full-sequence pass), 'loop' (one token at a time), or 'auto'
    (batched for dropless configs, loop when ``drop_tokens`` — whose
    capacity competition is per-step by definition).
    """
    b, t0 = prompt.shape
    max_len = t0 + max_new_tokens
    cache = init_cache(cfg, b, max_len)
    key = key if key is not None else jax.random.PRNGKey(0)

    if prefill == "auto":
        prefill = "loop" if cfg.drop_tokens else "batched"
    if prefill not in ("batched", "loop"):
        raise ValueError(
            f"prefill={prefill!r} not in ('auto', 'batched', 'loop')")
    if prefill == "batched":
        logits, cache = prefill_batched(params, cfg, prompt, cache)
    else:
        logits, cache = prefill_loop(params, cfg, prompt, cache)

    stops = jnp.asarray(stop_tokens, jnp.int32) if stop_tokens else None

    def sample(logits, k):
        return sample_tokens(logits, k, temperature=temperature,
                             top_k=top_k, top_p=top_p)

    def step(carry, i):
        cache, logits, key, done = carry
        key, sub = jax.random.split(key)
        tok = sample(logits, sub)
        if stops is not None:
            tok = jnp.where(done, jnp.int32(pad_token), tok)
            done = done | jnp.isin(tok, stops)
        x = params["embed"].astype(cfg.dtype)[tok][:, None, :]
        logits, cache = _decode_step(params, cfg, x, cache, t0 + i)
        return (cache, logits, key, done), tok

    done0 = jnp.zeros((b,), bool)
    (_, logits, _, _), toks = jax.lax.scan(
        step, (cache, logits, key, done0), jnp.arange(max_new_tokens)
    )
    return jnp.concatenate([prompt, toks.T], axis=1)
