"""Model-family presets.

Configurations for the MoE families the benchmark matrix targets
(BASELINE.md) and the common public architectures a framework user
expects.  Each returns a full :class:`MoEConfig`; pass ``**overrides`` to
resize (e.g. fewer layers for a smoke run).
"""

from __future__ import annotations

import jax.numpy as jnp

from flashmoe_tpu.config import Activation, MoEConfig


def mixtral_8x7b(**overrides) -> MoEConfig:
    """Mixtral-8x7B: 8 experts, top-2, SwiGLU, GQA 32/8."""
    base = dict(
        num_experts=8, expert_top_k=2, hidden_size=4096,
        intermediate_size=14336, num_layers=32, moe_frequency=1,
        vocab_size=32000, num_heads=32, num_kv_heads=8,
        sequence_len=4096, gated_ffn=True, hidden_act=Activation.SILU,
        rope_theta=1e6, drop_tokens=False, dtype=jnp.bfloat16,
    )
    base.update(overrides)
    return MoEConfig(**base)


def deepseek_moe_16b(**overrides) -> MoEConfig:
    """DeepSeekMoE-16B: 64 routed + 2 shared experts, top-6, fine-grained."""
    base = dict(
        num_experts=64, expert_top_k=6, num_shared_experts=2,
        hidden_size=2048, intermediate_size=1408, num_layers=28,
        moe_frequency=1, vocab_size=102400, num_heads=16,
        sequence_len=4096, gated_ffn=True, hidden_act=Activation.SILU,
        drop_tokens=False, dtype=jnp.bfloat16,
    )
    base.update(overrides)
    return MoEConfig(**base)


def switch_base(**overrides) -> MoEConfig:
    """Switch-Transformer-Base flavour: top-1 routing, capacity + drops."""
    base = dict(
        num_experts=128, expert_top_k=1, hidden_size=768,
        intermediate_size=3072, num_layers=12, moe_frequency=2,
        vocab_size=32128, num_heads=12, sequence_len=512,
        capacity_factor=1.25, drop_tokens=True,
        hidden_act=Activation.RELU, dtype=jnp.bfloat16,
    )
    base.update(overrides)
    return MoEConfig(**base)


def flashmoe_reference(**overrides) -> MoEConfig:
    """The reference repo's benchmark config
    (``csrc/flashmoe_config.json``: E=64 top-2 H=2048 I=2048 S=8192)."""
    base = dict(
        num_experts=64, expert_top_k=2, hidden_size=2048,
        intermediate_size=2048, num_layers=2, moe_frequency=2,
        vocab_size=50257, num_heads=16, sequence_len=8192,
        capacity_factor=1.0, drop_tokens=True, dtype=jnp.bfloat16,
    )
    base.update(overrides)
    return MoEConfig(**base)


PRESETS = {
    "mixtral-8x7b": mixtral_8x7b,
    "deepseek-moe-16b": deepseek_moe_16b,
    "switch-base": switch_base,
    "flashmoe-reference": flashmoe_reference,
}
