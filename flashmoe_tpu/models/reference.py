"""Dense-math reference MoE — the numerical oracle.

The reference repo never finished its correctness oracle: ``rExpert``
(``csrc/correctness/correctness.cuh:19-46``) computes only the gate GEMM +
softmax + argmax.  This module is the complete oracle the CUDA code lacked:
an O(S * E) dense evaluation of the full MoE layer (gate -> softmax -> top-k
-> per-expert FFN -> weighted combine) in plain JAX, used by the test suite
to validate every optimized path (Pallas kernels, capacity-factor dispatch,
EP all-to-all) to tolerance.

It intentionally computes *every* expert for *every* token so routing,
capacity, permutation and communication cannot hide errors.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from flashmoe_tpu.config import Activation, MoEConfig


def activation_fn(name: str):
    return {
        Activation.RELU: jax.nn.relu,
        Activation.GELU: jax.nn.gelu,
        Activation.SILU: jax.nn.silu,
    }[name]


def init_moe_params(key, cfg: MoEConfig) -> dict:
    """Random MoE-layer parameters.

    Layout mirrors the reference worker's tensors (``flashmoe/worker.py:56-58``):
    ``gate_w [H, E]``, per-expert up/down projections (+ optional gate proj for
    SwiGLU), all stored stacked on a leading expert axis.
    """
    h, i, e = cfg.hidden_size, cfg.intermediate_size, cfg.num_experts
    ks = jax.random.split(key, 7)
    p = {
        "gate_w": jax.random.normal(ks[0], (h, e), cfg.param_dtype) / jnp.sqrt(h),
        "w_up": jax.random.normal(ks[1], (e, h, i), cfg.param_dtype) / jnp.sqrt(h),
        "b_up": jnp.zeros((e, i), cfg.param_dtype),
        "w_down": jax.random.normal(ks[2], (e, i, h), cfg.param_dtype) / jnp.sqrt(i),
        "b_down": jnp.zeros((e, h), cfg.param_dtype),
    }
    if cfg.gated_ffn:
        p["w_gate"] = (
            jax.random.normal(ks[3], (e, h, i), cfg.param_dtype) / jnp.sqrt(h)
        )
    if cfg.num_shared_experts:
        si = i * cfg.num_shared_experts
        p["shared_w_up"] = (
            jax.random.normal(ks[4], (h, si), cfg.param_dtype) / jnp.sqrt(h)
        )
        p["shared_w_down"] = (
            jax.random.normal(ks[5], (si, h), cfg.param_dtype) / jnp.sqrt(si)
        )
        if cfg.gated_ffn:
            p["shared_w_gate"] = (
                jax.random.normal(ks[6], (h, si), cfg.param_dtype) / jnp.sqrt(h)
            )
    return p


def reference_gate(x, gate_w, cfg: MoEConfig):
    """Gate: logits -> softmax over experts -> top-k.

    Returns (combine_weights [S, E], top_idx [S, K], router_probs [S, E],
    aux_loss).  ``combine_weights`` is the softmax prob masked to the top-k
    set and re-normalized to sum to 1 across the chosen experts — matching
    the reference's combine epilogue which divides by the accumulated
    combine-weight sum (``csrc/include/flashmoe/os/processor/processor.cuh``
    combine, and ``TPS`` weight accumulation in ``moe/gate.cuh:678-718``).
    """
    logits = jnp.dot(
        x.astype(cfg.accum_dtype),
        gate_w.astype(cfg.accum_dtype),
        preferred_element_type=cfg.accum_dtype,
    )
    probs = jax.nn.softmax(logits, axis=-1)
    top_p, top_idx = jax.lax.top_k(probs, cfg.expert_top_k)
    # mask to top-k, renormalize over the selected set
    denom = jnp.sum(top_p, axis=-1, keepdims=True)
    norm_top = top_p / jnp.maximum(denom, 1e-20)
    one_hot = jax.nn.one_hot(top_idx, cfg.num_experts, dtype=probs.dtype)
    combine_weights = jnp.einsum("sk,ske->se", norm_top, one_hot)

    # Switch-style load-balancing aux loss (gate.cuh:273-299 accumulates
    # mean-logit and mean-expert-count into gML/gMeC -> gL in training mode).
    density = jnp.mean(
        jnp.sum(one_hot, axis=1), axis=0
    )  # fraction routed per expert
    mean_probs = jnp.mean(probs, axis=0)
    aux_loss = cfg.num_experts * jnp.sum(density * mean_probs)
    return combine_weights, top_idx, probs, aux_loss


def expert_ffn(x, p, cfg: MoEConfig, e: int):
    """Single-expert FFN: up GEMM -> (+bias) -> act -> down GEMM -> (+bias),
    the same op chain as the fused ``fGET`` pipeline
    (``csrc/include/flashmoe/os/processor/processor.cuh:339-468``)."""
    act = activation_fn(cfg.hidden_act)
    up = jnp.dot(x, p["w_up"][e], preferred_element_type=cfg.accum_dtype)
    up = up + p["b_up"][e].astype(up.dtype)
    if cfg.gated_ffn:
        g = jnp.dot(x, p["w_gate"][e], preferred_element_type=cfg.accum_dtype)
        hidden = act(g) * up
    else:
        hidden = act(up)
    down = jnp.dot(
        hidden.astype(cfg.dtype),
        p["w_down"][e],
        preferred_element_type=cfg.accum_dtype,
    )
    return down + p["b_down"][e].astype(down.dtype)


def shared_expert_ffn(x, p, cfg: MoEConfig):
    act = activation_fn(cfg.hidden_act)
    up = jnp.dot(x, p["shared_w_up"], preferred_element_type=cfg.accum_dtype)
    if cfg.gated_ffn:
        g = jnp.dot(x, p["shared_w_gate"], preferred_element_type=cfg.accum_dtype)
        hidden = act(g) * up
    else:
        hidden = act(up)
    return jnp.dot(
        hidden.astype(cfg.dtype),
        p["shared_w_down"],
        preferred_element_type=cfg.accum_dtype,
    )


def reference_moe(params, x, cfg: MoEConfig):
    """Full dense-math MoE layer.

    x: [S, H] tokens.  Returns (output [S, H], aux_loss).  Every expert is
    evaluated on every token and combined through the dense combine-weight
    matrix, so there is no routing/capacity approximation to compare against.
    Note: with drop_tokens capacity limits, optimized paths may drop tokens
    the oracle keeps; tests account for that explicitly.
    """
    combine_weights, _, _, aux = reference_gate(x, params["gate_w"], cfg)
    xs = x.astype(cfg.dtype)
    all_out = jnp.stack(
        [expert_ffn(xs, params, cfg, e) for e in range(cfg.num_experts)], axis=0
    )  # [E, S, H]
    out = jnp.einsum(
        "se,esh->sh", combine_weights.astype(cfg.accum_dtype),
        all_out.astype(cfg.accum_dtype),
    )
    if cfg.num_shared_experts:
        out = out + shared_expert_ffn(xs, params, cfg).astype(out.dtype)
    return out.astype(cfg.dtype), aux
