"""FlashMoE-TPU transformer: the flagship MoE model family.

The reference is a kernel library, not a model — its Python worker feeds
random tensors through one MoE layer (``flashmoe/worker.py:56-67``), and the
full-model dimensions (num_layers, moe_frequency, vocab_size) exist only to
feed the Decider's cost model.  A complete framework needs the model around
the layer, so this module provides a modern MoE transformer (pre-norm,
RoPE, GQA attention, MoE FFN every ``moe_frequency``-th layer, optional
shared experts) in functional JAX style:

  * params are plain nested dicts (pytree), shardable with the
    PartitionSpecs from :mod:`flashmoe_tpu.parallel.mesh`;
  * :func:`forward` is jit-friendly (static config, no Python-level data
    dependence), uses the fused MoE layer per token shard;
  * :func:`loss_fn` / :func:`train_step` give the full training path
    (cross-entropy + load-balance aux + z-loss, optax-compatible grads)
    — the capability the reference models in its Decider (DP gradient
    allreduce pricing, ``os/decider/functions.cuh:28-32``) but never
    executes;
  * rematerialization via ``jax.checkpoint`` per block keeps HBM bounded.

Layer geometry follows cfg.moe_layer_indices (moe_frequency), mirroring the
reference's ``moe_frequency`` semantics.
"""

from __future__ import annotations

import functools
from typing import Any

import jax
import jax.numpy as jnp

from flashmoe_tpu.config import MoEConfig
from flashmoe_tpu.models.reference import init_moe_params
from flashmoe_tpu.ops.moe import dense_ffn, moe_layer
from flashmoe_tpu.parallel.ep import ep_moe_layer


# ----------------------------------------------------------------------
# Init
# ----------------------------------------------------------------------

def init_params(key, cfg: MoEConfig) -> dict:
    """Initialize the full transformer parameter tree."""
    h = cfg.hidden_size
    nh, nkv, dh = cfg.num_heads, cfg.resolved_num_kv_heads, cfg.resolved_head_dim
    keys = jax.random.split(key, cfg.num_layers + 3)

    def dense(k, shape, fan_in):
        return jax.random.normal(k, shape, cfg.param_dtype) / jnp.sqrt(fan_in)

    params: dict[str, Any] = {
        "embed": dense(keys[0], (cfg.vocab_size, h), 1.0) * 0.02 * jnp.sqrt(1.0),
        "final_norm": jnp.ones((h,), cfg.param_dtype),
        "lm_head": dense(keys[1], (h, cfg.vocab_size), h),
        "layers": [],
    }
    moe_set = set(cfg.moe_layer_indices)
    for li in range(cfg.num_layers):
        lk = jax.random.split(keys[2 + li], 6)
        layer = {
            "attn_norm": jnp.ones((h,), cfg.param_dtype),
            "ffn_norm": jnp.ones((h,), cfg.param_dtype),
            "wq": dense(lk[0], (h, nh * dh), h),
            "wk": dense(lk[1], (h, nkv * dh), h),
            "wv": dense(lk[2], (h, nkv * dh), h),
            "wo": dense(lk[3], (nh * dh, h), nh * dh),
        }
        if li in moe_set:
            layer["moe"] = init_moe_params(lk[4], cfg)
        else:
            layer["moe"] = init_moe_params(
                lk[4], cfg.replace(num_experts=1, expert_top_k=1,
                                   num_shared_experts=0)
            )
        params["layers"].append(layer)
    return params


# ----------------------------------------------------------------------
# Blocks
# ----------------------------------------------------------------------

def rms_norm(x, w, eps=1e-6):
    dt = x.dtype
    x = x.astype(jnp.float32)
    x = x * jax.lax.rsqrt(jnp.mean(jnp.square(x), axis=-1, keepdims=True) + eps)
    return (x * w.astype(jnp.float32)).astype(dt)


def _rope(q, k, positions, theta):
    """Rotary position embeddings. q/k: [B, T, N, D]."""
    d = q.shape[-1]
    half = d // 2
    freq = theta ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    angles = positions[..., None].astype(jnp.float32) * freq  # [B, T, half]
    cos = jnp.cos(angles)[:, :, None, :]
    sin = jnp.sin(angles)[:, :, None, :]

    def rot(x):
        x1, x2 = x[..., :half], x[..., half:]
        xf1, xf2 = x1.astype(jnp.float32), x2.astype(jnp.float32)
        return jnp.concatenate(
            [xf1 * cos - xf2 * sin, xf2 * cos + xf1 * sin], axis=-1
        ).astype(x.dtype)

    return rot(q), rot(k)


def attention(layer, x, cfg: MoEConfig, positions=None, mesh=None,
              use_pallas=None):
    """Causal self-attention with RoPE and GQA. x: [B, T, H].

    Backend selection: ring attention over the ``sp`` mesh axis for
    sequence-parallel configs, the flash Pallas kernel on TPU, plain XLA
    otherwise.
    """
    from flashmoe_tpu.ops.attention import attention_xla, flash_attention
    from flashmoe_tpu.parallel.ringattn import ring_attention

    b, t, h = x.shape
    nh, nkv, dh = cfg.num_heads, cfg.resolved_num_kv_heads, cfg.resolved_head_dim
    if positions is None:
        positions = jnp.broadcast_to(jnp.arange(t)[None, :], (b, t))

    q = (x @ layer["wq"].astype(x.dtype)).reshape(b, t, nh, dh)
    k = (x @ layer["wk"].astype(x.dtype)).reshape(b, t, nkv, dh)
    v = (x @ layer["wv"].astype(x.dtype)).reshape(b, t, nkv, dh)
    q, k = _rope(q, k, positions, cfg.rope_theta)

    if nkv != nh:  # GQA: repeat kv heads
        rep = nh // nkv
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)

    # [B, T, N, D] -> [B, N, T, D] for the attention kernels
    qh, kh, vh = (a.transpose(0, 2, 1, 3) for a in (q, k, v))
    if use_pallas is None:
        use_pallas = jax.default_backend() == "tpu"
    if mesh is not None and cfg.sp > 1:
        ctx = ring_attention(qh, kh, vh, mesh, causal=True)
    elif use_pallas and t % 128 == 0:
        ctx = flash_attention(qh, kh, vh, causal=True)
    else:
        ctx = attention_xla(qh, kh, vh, causal=True)
    ctx = ctx.transpose(0, 2, 1, 3).reshape(b, t, nh * dh).astype(x.dtype)
    return ctx @ layer["wo"].astype(x.dtype)


def _resolved_plan(cfg: MoEConfig, mesh) -> tuple[str, int | None]:
    """(moe_backend, a2a_chunks) with 'auto' resolved by the analytical
    planner (predicted-latency winner + chunked-pipeline sweep,
    measured override; decision recorded in telemetry).  The pricing
    regime follows ``cfg.serving_mode``: a decode-phase config
    (``serving_mode='decode'``, set by the serving engine) resolves a
    decode-priced plan — per-step tokens = the decode batch, not
    B x S — instead of the training-shaped sweep."""
    if cfg.moe_backend != "auto":
        return cfg.moe_backend, cfg.a2a_chunks
    from flashmoe_tpu.parallel.ep import resolve_moe_plan

    return resolve_moe_plan(cfg, mesh)


def _ffn(layer, x, cfg: MoEConfig, li: int, mesh, use_pallas):
    """FFN sub-block: MoE (possibly expert-parallel) or dense."""
    b, t, h = x.shape
    flat = x.reshape(b * t, h)
    layer_cfg = cfg if li in cfg.moe_layer_indices else cfg.replace(
        num_experts=1, expert_top_k=1, num_shared_experts=0
    )
    if mesh is not None and layer_cfg.num_experts > 1 and cfg.ep > 1:
        axes = ("dp", "ep") + (("sp",) if cfg.sp > 1 else ())
        backend, chunks = _resolved_plan(cfg, mesh)
        # the planner's chunked-pipeline pick rides the layer config
        # (parallel/ep.py reads cfg.a2a_chunks); explicit settings and
        # unservable picks pass through untouched
        from flashmoe_tpu.parallel.ep import apply_chunk_pick

        layer_cfg = apply_chunk_pick(layer_cfg, backend, chunks)
        if backend == "fused" and cfg.tp == 1:
            from flashmoe_tpu.parallel.fused import fused_ep_moe_layer

            # distinct collective_id per layer: each fused kernel in the
            # step needs its own barrier-semaphore identity
            # the fused layer IS a Pallas kernel — interpret it anywhere
            # but on real TPU, independent of the use_pallas preference
            o = fused_ep_moe_layer(layer["moe"], flat, layer_cfg, mesh,
                                   token_axes=axes,
                                   collective_id=7 + (li % 16),
                                   interpret=jax.default_backend() != "tpu")
        elif (backend == "ragged" and cfg.tp == 1
                and not layer_cfg.num_shared_experts):
            from flashmoe_tpu.parallel.ragged_ep import ragged_ep_moe_layer

            o = ragged_ep_moe_layer(layer["moe"], flat, layer_cfg, mesh,
                                    use_pallas=bool(use_pallas),
                                    interpret=bool(use_pallas)
                                    and jax.default_backend() != "tpu",
                                    token_axes=axes)
        else:
            o = ep_moe_layer(layer["moe"], flat, layer_cfg, mesh,
                             use_pallas=bool(use_pallas),
                             token_axes=axes)
    else:
        o = moe_layer(layer["moe"], flat, layer_cfg, use_pallas=use_pallas)
    return (o.out.reshape(b, t, h).astype(x.dtype),
            o.aux_loss + o.z_loss, o.stats)


def block(layer, x, cfg: MoEConfig, li: int, mesh=None, use_pallas=None,
          chaos_sig=()):
    """One pre-norm transformer block.  Returns (x, moe_losses,
    moe_stats) — stats is the layer's MoEStats when ``cfg.collect_stats``
    and this is an MoE layer, else None (an empty pytree leaf).

    ``chaos_sig`` is the chaos-injection registry snapshot
    (:func:`flashmoe_tpu.chaos.inject.trace_signature`), unused in the
    body but STATIC: ``jax.checkpoint`` caches block traces by
    (function, static args), and without the signature in the key a
    re-armed injection point silently reuses the previous arming
    state's jaxpr whenever two builds share an equal config (the chaos
    drills rebuild their step exactly to pick up new arming)."""
    a = attention(layer, rms_norm(x, layer["attn_norm"]), cfg, mesh=mesh,
                  use_pallas=use_pallas)
    x = x + a
    f, moe_loss, moe_stats = _ffn(layer, rms_norm(x, layer["ffn_norm"]),
                                  cfg, li, mesh, use_pallas)
    return x + f, moe_loss, moe_stats


# ----------------------------------------------------------------------
# Model forward / loss / train step
# ----------------------------------------------------------------------

def forward(params, tokens, cfg: MoEConfig, mesh=None, use_pallas=None):
    """tokens: [B, T] int32 -> logits [B, T, V]; also returns summed MoE
    aux losses.  With ``cfg.collect_stats`` a third element is returned:
    a tuple of per-MoE-layer :class:`flashmoe_tpu.ops.stats.MoEStats`
    (flag off keeps the two-tuple contract every existing caller uses)."""
    x = params["embed"].astype(cfg.dtype)[tokens]
    total_aux = jnp.zeros((), cfg.accum_dtype)
    layer_stats = []
    # per-block remat keeps HBM bounded; excluded exactly for the blocks
    # where the fused RDMA backend actually runs (same condition as _ffn's
    # fused branch — its kernel's side effects cannot be partially
    # evaluated under checkpoint, and its custom VJP already avoids
    # storing the exchange intermediates).  Non-MoE blocks keep remat.
    fused_active = (cfg.ep > 1 and cfg.tp == 1 and mesh is not None
                    and cfg.num_experts > 1
                    and _resolved_plan(cfg, mesh)[0] == "fused")
    blk_remat = jax.checkpoint(
        block, static_argnums=(2, 3, 4, 5, 6),
        policy=jax.checkpoint_policies.nothing_saveable,
    )
    from flashmoe_tpu.chaos import inject as chaos_inject

    chaos_sig = chaos_inject.trace_signature()
    moe_layers = set(cfg.moe_layer_indices)
    for li, layer in enumerate(params["layers"]):
        fused_block = fused_active and li in moe_layers
        blk = blk_remat if (cfg.is_training and not fused_block) else block
        x, moe_loss, moe_stats = blk(layer, x, cfg, li, mesh, use_pallas,
                                     chaos_sig)
        total_aux = total_aux + moe_loss
        if moe_stats is not None:
            layer_stats.append(moe_stats)
    x = rms_norm(x, params["final_norm"])
    logits = jnp.dot(
        x.astype(cfg.dtype), params["lm_head"].astype(cfg.dtype),
        preferred_element_type=jnp.float32,
    )
    if cfg.collect_stats:
        return logits, total_aux, tuple(layer_stats)
    return logits, total_aux


def loss_fn(params, batch, cfg: MoEConfig, mesh=None, use_pallas=None):
    """Next-token cross-entropy + MoE aux losses.

    batch: dict with "tokens" [B, T] (inputs are tokens[:, :-1], targets
    tokens[:, 1:]).
    """
    tokens = batch["tokens"]
    inp, tgt = tokens[:, :-1], tokens[:, 1:]
    if cfg.collect_stats:
        logits, aux, stats = forward(params, inp, cfg, mesh, use_pallas)
    else:
        logits, aux = forward(params, inp, cfg, mesh, use_pallas)
        stats = ()
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(logp, tgt[..., None], axis=-1)[..., 0]
    mask = batch.get("mask", jnp.ones_like(tgt, jnp.float32))
    ce = jnp.sum(nll * mask) / jnp.maximum(jnp.sum(mask), 1.0)
    metrics = {"ce": ce, "aux": aux}
    if cfg.collect_stats:
        # per-MoE-layer MoEStats, consumed by the trainer's flight
        # recorder; stays a pytree of arrays so it flows through jit
        metrics["moe_stats"] = stats
    return ce + aux, metrics


def sgd_train_step(params, batch, cfg: MoEConfig, lr=1e-3, mesh=None,
                   use_pallas=None):
    """Minimal fused train step (plain SGD) — used by the multi-chip
    dry-run; the full optimizer path lives in
    :mod:`flashmoe_tpu.runtime.trainer`."""
    (loss, metrics), grads = jax.value_and_grad(loss_fn, has_aux=True)(
        params, batch, cfg, mesh, use_pallas
    )
    params = jax.tree_util.tree_map(
        lambda p, g: (p - lr * g.astype(p.dtype)).astype(p.dtype)
        if jnp.issubdtype(p.dtype, jnp.floating) else p,
        params, grads,
    )
    return params, loss, metrics
