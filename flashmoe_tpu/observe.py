"""Flight-recorder analysis CLI: turn JSONL telemetry dumps into the
reports a perf postmortem starts from.

Input: any mix of JSONL files produced by this framework —

  * flight-recorder exports (``runtime.trainer.train(flight_path=...)``,
    records with ``step`` + optional per-layer ``moe`` stats),
  * telemetry decision logs (``Metrics.dump_decisions_jsonl`` — planner
    path selections and ``planner.drift`` comparisons),
  * bench.py output lines (``metric``/``value`` records with
    ``predicted_ms``/``prediction_error`` calibration fields),
  * metrics summaries (``Metrics.dump_jsonl`` — phase timers).

Output: an expert-load imbalance report (per-expert histogram), the
drop-rate timeline, a phase-time breakdown, and the planner drift report
(:func:`flashmoe_tpu.planner.drift.drift_report`).  ``--json`` emits one
machine-readable document instead of text.

Usage::

    python -m flashmoe_tpu.observe flight.jsonl [decisions.jsonl ...]
    python -m flashmoe_tpu.observe --json flight.jsonl
    python -m flashmoe_tpu.observe --ledger obs/ledger.jsonl
    python -m flashmoe_tpu.observe --serving obs/flight.jsonl obs/decisions.jsonl
    python -m flashmoe_tpu.observe --postmortem /path/to/bundles

``--ledger`` renders the per-phase predicted-vs-measured cost ledger
(:mod:`flashmoe_tpu.profiler.ledger` artifacts / ``planner.phase_drift``
decision dumps); ``--serving`` renders the serving-engine report
(TTFT/TPOT percentiles, queue depth, cache occupancy, the prefill-vs-
decode planner split — docs/SERVING.md); ``--postmortem`` renders a
triage report of the crash bundle(s) written by
:mod:`flashmoe_tpu.profiler.postmortem`.
"""

from __future__ import annotations

import argparse
import json
import sys


def load_jsonl(paths: list[str]) -> list[dict]:
    """All parseable JSON objects from the given files, in order.
    Unparseable lines (partial writes, comments) are skipped."""
    records: list[dict] = []
    for path in paths:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line or line.startswith("#"):
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if isinstance(rec, dict):
                    records.append(rec)
    return records


def _layer_stats(rec: dict) -> list[dict]:
    """Per-layer MoE stat dicts of one flight record (either the
    trainer's ``moe`` list or a bare top-level stats record)."""
    if isinstance(rec.get("moe"), list):
        return [m for m in rec["moe"] if isinstance(m, dict)]
    if isinstance(rec.get("expert_load"), list):
        return [rec]
    return []


def imbalance_report(flight: list[dict]) -> dict:
    """Aggregate expert-load histogram across steps and layers."""
    load: list[float] = []
    imb = []
    ent = []
    for rec in flight:
        for m in _layer_stats(rec):
            el = m.get("expert_load") or []
            if len(load) < len(el):
                load.extend([0.0] * (len(el) - len(load)))
            for i, v in enumerate(el):
                load[i] += float(v)
            if "imbalance" in m:
                imb.append(float(m["imbalance"]))
            if "router_entropy" in m:
                ent.append(float(m["router_entropy"]))
    total = sum(load)
    mean = total / len(load) if load else 0.0
    return {
        "experts": len(load),
        "expert_load": [round(v, 1) for v in load],
        "total_assignments": round(total, 1),
        "imbalance": round(max(load) / mean, 4) if mean > 0 else None,
        "mean_step_imbalance": round(sum(imb) / len(imb), 4) if imb
        else None,
        "mean_router_entropy": round(sum(ent) / len(ent), 4) if ent
        else None,
    }


def drop_report(flight: list[dict]) -> dict:
    """Drop-rate / capacity-utilization timeline and aggregates."""
    timeline = []
    for rec in flight:
        stats = _layer_stats(rec)
        drops = [float(m["dropped_fraction"]) for m in stats
                 if "dropped_fraction" in m]
        utils = [float(m["capacity_utilization"]) for m in stats
                 if "capacity_utilization" in m]
        if drops:
            timeline.append({
                "step": rec.get("step"),
                "dropped_fraction": round(sum(drops) / len(drops), 6),
                "capacity_utilization": round(sum(utils) / len(utils), 6)
                if utils else None,
            })
    dr = [t["dropped_fraction"] for t in timeline]
    return {
        "steps": len(timeline),
        "mean_dropped_fraction": round(sum(dr) / len(dr), 6) if dr
        else None,
        "max_dropped_fraction": round(max(dr), 6) if dr else None,
        "timeline": timeline,
    }


def degradation_report(flight: list[dict]) -> dict:
    """Tier-0 fault-tolerance timeline (docs/RESILIENCE.md): steps where
    the expert-health mask fired (``degrade_unhealthy_experts``), with
    masked-expert counts and masked assignment fractions."""
    timeline = []
    for rec in flight:
        stats = _layer_stats(rec)
        masked = [float(m["masked_experts"]) for m in stats
                  if m.get("masked_experts")]
        frac = [float(m["masked_fraction"]) for m in stats
                if "masked_fraction" in m]
        if masked:
            timeline.append({
                "step": rec.get("step"),
                "masked_experts": round(sum(masked), 2),
                "masked_fraction": round(sum(frac) / len(frac), 6)
                if frac else None,
            })
    return {
        "steps_with_masking": len(timeline),
        "max_masked_experts": max((t["masked_experts"] for t in timeline),
                                  default=0.0),
        "timeline": timeline,
    }


def wire_report(flight: list[dict]) -> dict:
    """Wire-compression health (ops/wire.py): the round-trip
    quantization-error proxy the EP layers attach to MoEStats when a
    ``wire_dtype`` is on.  Steps where the wire was active (error > 0),
    mean/max error — a rising error flags payload distributions the fp8
    wire no longer represents well."""
    errs = []
    dcn_errs = []
    for rec in flight:
        for m in _layer_stats(rec):
            e = m.get("wire_rtq_error")
            if isinstance(e, (int, float)) and e > 0:
                errs.append(float(e))
            e = m.get("wire_rtq_error_dcn")
            if isinstance(e, (int, float)) and e > 0:
                dcn_errs.append(float(e))
    return {
        "steps_with_wire": len(errs),
        "mean_rtq_error": round(sum(errs) / len(errs), 6) if errs
        else None,
        "max_rtq_error": round(max(errs), 6) if errs else None,
        # the cross-slice hop's own wire (wire_dtype_dcn), tracked
        # separately so an fp8 DCN hop's loss never hides in (or
        # inflates) the in-slice number
        "steps_with_dcn_wire": len(dcn_errs),
        "mean_dcn_rtq_error": (round(sum(dcn_errs) / len(dcn_errs), 6)
                               if dcn_errs else None),
        "max_dcn_rtq_error": round(max(dcn_errs), 6) if dcn_errs
        else None,
    }


def resilience_report(records: list[dict]) -> dict:
    """Fault-tolerance narrative from the decision stream
    (docs/RESILIENCE.md): how often each recovery rung fired, every
    drain with its remaining grace, and every supervised resume with
    the world it landed on — the loss-of-work story of the run."""
    by_name: dict[str, int] = {}
    drains = []
    resumes = []
    for rec in records:
        name = rec.get("decision")
        if not isinstance(name, str):
            continue
        by_name[name] = by_name.get(name, 0) + 1
        if name == "preempt.drain":
            drains.append({
                "step": rec.get("step"),
                "source": rec.get("source"),
                "remaining_grace_s": rec.get("remaining_grace_s"),
            })
        elif name == "supervisor.resume":
            resumes.append({
                "incarnation": rec.get("incarnation"),
                "step": rec.get("step"),
                "world": rec.get("world"),
                "ep": rec.get("ep"), "dp": rec.get("dp"),
            })
    interesting = ("trainer.grad_skip", "checkpoint.fallback",
                   "checkpoint.emergency_save", "checkpoint.async_error",
                   "planner.fallback", "preempt.notice", "preempt.drain",
                   "supervisor.resume", "slo.breach", "slo.recovered",
                   "postmortem.saved")
    return {
        "events": {k: by_name[k] for k in interesting if k in by_name},
        "drains": drains,
        "resumes": resumes,
        "worlds": sorted({r["world"] for r in resumes
                          if r.get("world") is not None}),
    }


def adaptation_report(records: list[dict]) -> dict:
    """The self-healing controller's story (docs/RESILIENCE.md
    "Self-healing controller"): every ``controller.*`` decision in
    timeline order, and — for each morph/re-placement — the mean MoE
    imbalance and dropped fraction over the flight-recorder steps
    BEFORE vs AFTER the action, so the report answers "did the repair
    actually repair" without replaying the run."""
    acts = [r for r in records
            if str(r.get("decision", "")).startswith("controller.")]
    flight = []
    for rec in records:
        ms = _layer_stats(rec)
        if ms and isinstance(rec.get("step"), (int, float)):
            flight.append((int(rec["step"]),
                           max(m.get("imbalance", 0.0) for m in ms),
                           max(m.get("dropped_fraction", 0.0)
                               for m in ms)))
    flight.sort()

    def window(step, after: bool, n: int = 5):
        rows = [(i, d) for s, i, d in flight
                if (s >= step if after else s < step)]
        rows = rows[:n] if after else rows[-n:]
        if not rows:
            return None
        return {"imbalance": round(sum(r[0] for r in rows)
                                   / len(rows), 3),
                "dropped_fraction": round(sum(r[1] for r in rows)
                                          / len(rows), 4)}

    timeline = []
    for a in acts:
        entry = {"decision": a.get("decision"), "step": a.get("step"),
                 "trigger": a.get("trigger")}
        if a["decision"] == "controller.morph":
            entry.update(backend=a.get("backend"),
                         dropless=a.get("dropless"),
                         overrides=a.get("overrides"),
                         reason=a.get("reason"))
        elif a["decision"] == "controller.replace":
            entry.update(replicas=a.get("replicas"),
                         rates=a.get("rates"),
                         device_share_before=a.get(
                             "device_share_before"))
        elif a["decision"] == "controller.demotion_reset":
            entry.update(dropped=a.get("dropped"),
                         world=a.get("world"))
        if a["decision"] in ("controller.morph", "controller.replace") \
                and isinstance(a.get("step"), (int, float)):
            entry["before"] = window(int(a["step"]), after=False)
            entry["after"] = window(int(a["step"]), after=True)
        timeline.append(entry)
    counts: dict[str, int] = {}
    for a in acts:
        counts[a["decision"]] = counts.get(a["decision"], 0) + 1
    return {"actions": counts, "timeline": timeline}


def phase_report(records: list[dict]) -> dict:
    """Mean of every ``*_ms`` field across records (flight ``step_ms``,
    bench leg timings) plus ``*_ms_p50`` phase timers from metrics
    summaries — the comm/compute phase breakdown."""
    sums: dict[str, float] = {}
    counts: dict[str, int] = {}
    # prediction fields are drift inputs, not phases — keep them out
    skip = {"predicted_ms", "xla_predicted_ms", "measured_ms"}
    for rec in records:
        for k, v in rec.items():
            if not isinstance(v, (int, float)) or isinstance(v, bool):
                continue
            if k in skip:
                continue
            if k.endswith("_ms") or k.endswith("_ms_p50"):
                sums[k] = sums.get(k, 0.0) + float(v)
                counts[k] = counts.get(k, 0) + 1
    return {k: round(sums[k] / counts[k], 4) for k in sorted(sums)}


def summarize(records: list[dict]) -> dict:
    """The full analysis document over a mixed record pile."""
    from flashmoe_tpu.planner.drift import drift_report

    flight = [r for r in records if _layer_stats(r) or "step" in r]
    return {
        "records": len(records),
        "flight_steps": len(flight),
        "imbalance": imbalance_report(flight),
        "drops": drop_report(flight),
        "degradation": degradation_report(flight),
        "wire": wire_report(flight),
        "resilience": resilience_report(records),
        "adaptation": adaptation_report(records),
        "phases": phase_report(records),
        "drift": drift_report(records),
        "decisions": sorted({r["decision"] for r in records
                             if isinstance(r.get("decision"), str)}),
    }


def ledger_report(records: list[dict]) -> dict:
    """The cost-ledger view: per-(path, chunks, wire) per-phase
    measured-vs-predicted drift, from ``ledger.jsonl`` rows
    (:func:`flashmoe_tpu.profiler.ledger.run_ledger_matrix`) and/or
    ``planner.phase_drift`` decision records — the per-phase answer to
    "which term of the cost model is lying".  Overlap cross-check rows
    (``record == "overlap"``) are summarized separately."""
    points: dict[tuple, dict] = {}
    overlaps = []
    for rec in records:
        if rec.get("record") == "overlap" or (
                "measured_fraction" in rec and "chunks" in rec):
            overlaps.append({
                "path": rec.get("point") or rec.get("path"),
                "d": rec.get("d"),
                "chunks": rec.get("chunks"), "wire": rec.get("wire"),
                "measured_fraction": rec.get("measured_fraction"),
                "predicted_fraction": rec.get("predicted_fraction"),
                "exceeded": rec.get("exceeded"),
            })
            continue
        phase = rec.get("phase")
        if not isinstance(phase, str) or "measured_ms" not in rec:
            continue
        # ledger.jsonl rows carry both the matrix point name ("flat")
        # and the planner path ("collective"); group/display by the
        # point name when present (decision records only have the path)
        key = (rec.get("point") or rec.get("path"),
               rec.get("chunks", 1), rec.get("wire", "off"))
        pt = points.setdefault(key, {
            "point": key[0], "path": rec.get("path"),
            "chunks": key[1], "wire": key[2], "phases": {}})
        pt["phases"][phase] = {
            "measured_ms": rec.get("measured_ms"),
            "predicted_ms": rec.get("predicted_ms"),
            "rel_error": rec.get("rel_error"),
            "exceeded": bool(rec.get("exceeded")),
        }
    phase_names = sorted({ph for pt in points.values()
                          for ph in pt["phases"]})
    n = sum(len(pt["phases"]) for pt in points.values())
    return {
        "n": n,
        "points": [points[k] for k in sorted(
            points, key=lambda k: (str(k[0]), k[1], str(k[2])))],
        "phases": phase_names,
        "exceeded": sum(1 for pt in points.values()
                        for p in pt["phases"].values() if p["exceeded"]),
        "overlap": overlaps,
    }


def render_ledger_text(led: dict) -> str:
    if not led["n"] and not led["overlap"]:
        return "no phase-ledger rows found (run `bench.py --profile` " \
               "or profiler.ledger.run_ledger_matrix first)"
    lines = []
    if led["n"]:
        lines += [f"cost ledger: {led['n']} phase comparisons over "
                  f"{len(led['points'])} config points, "
                  f"{led['exceeded']} over the drift threshold", ""]
        head = f"{'point':<34s}" + "".join(
            f"{ph.removeprefix('moe.'):>16s}" for ph in led["phases"])
        lines.append(head + "   (rel err, measured/predicted - 1)")
        for pt in led["points"]:
            label = (f"{pt.get('point') or pt['path']} "
                     f"c={pt['chunks']} wire={pt['wire']}")
            cells = []
            for ph in led["phases"]:
                p = pt["phases"].get(ph)
                if p is None:
                    cells.append(f"{'-':>16s}")
                else:
                    mark = "**" if p["exceeded"] else "  "
                    cells.append(f"{p['rel_error']:>+13.1%}{mark} ")
            lines.append(f"{label:<34s}" + "".join(cells))
    if led["overlap"]:
        lines.append("")
        lines.append("overlap cross-check (fenced serial phase sum / "
                     "jitted step):")
        for o in led["overlap"]:
            lines.append(
                f"  {o['path']} d={o['d']} chunks={o['chunks']} "
                f"wire={o['wire']}: measured {o['measured_fraction']} "
                f"vs bound {o['predicted_fraction']}"
                f"{'  ** DRIFTING' if o['exceeded'] else ''}")
    return "\n".join(lines)


def serving_report(records: list[dict]) -> dict:
    """The serving engine's story (``--serving``): per-step
    ``serve_step`` flight records (queue depth, active requests, cache
    occupancy, tokens emitted), per-request TTFT/TPOT from
    ``serve_request`` records / ``serve.retire`` decisions, the
    admission/eviction narrative, the decode-vs-prefill planner split
    (``serve.plan``), and serving SLO breaches (``slo.breach`` with
    target ttft/tpot)."""
    steps = [r for r in records if r.get("kind") == "serve_step"]
    req_recs = [r for r in records if r.get("kind") == "serve_request"]
    retires = [r for r in records
               if r.get("decision") == "serve.retire"]
    # the one serving percentile definition, shared with the bench
    # sweep's records so the two surfaces can never disagree on p99
    from flashmoe_tpu.serving.loadgen import pctl

    per_req = req_recs or retires
    ttfts = [float(r["ttft_ms"]) for r in per_req
             if isinstance(r.get("ttft_ms"), (int, float))]
    tpots = [float(r["tpot_ms"]) for r in per_req
             if isinstance(r.get("tpot_ms"), (int, float))]
    tokens = sum(int(r.get("tokens", 0)) for r in steps)
    wall_ms = sum(float(r.get("step_ms", 0.0)) for r in steps)
    qd = [int(r["queue_depth"]) for r in steps
          if isinstance(r.get("queue_depth"), (int, float))]
    occ = [float(r["cache_occupancy"]) for r in steps
           if isinstance(r.get("cache_occupancy"), (int, float))]
    act = [int(r["active"]) for r in steps
           if isinstance(r.get("active"), (int, float))]
    plan = next((r for r in reversed(records)
                 if r.get("decision") == "serve.plan"), None)
    slo = [r for r in records if r.get("decision") == "slo.breach"
           and r.get("target") in ("ttft", "tpot")]
    return {
        "steps": len(steps),
        "requests_completed": len({r.get("rid") for r in per_req}
                                  if per_req else ()),
        "tokens": tokens,
        "tokens_per_sec": round(tokens / (wall_ms / 1e3), 1)
        if wall_ms > 0 else None,
        "ttft_ms": {"mean": round(sum(ttfts) / len(ttfts), 3),
                    "p50": pctl(ttfts, 0.5), "p99": pctl(ttfts, 0.99),
                    "max": round(max(ttfts), 3)} if ttfts else None,
        "tpot_ms": {"mean": round(sum(tpots) / len(tpots), 3),
                    "p50": pctl(tpots, 0.5)} if tpots else None,
        "queue_depth": {"mean": round(sum(qd) / len(qd), 2),
                        "max": max(qd)} if qd else None,
        "active": {"mean": round(sum(act) / len(act), 2),
                   "max": max(act)} if act else None,
        "cache_occupancy": {"mean": round(sum(occ) / len(occ), 4),
                            "peak": round(max(occ), 4)} if occ else
        None,
        "admissions": sum(1 for r in records
                          if r.get("decision") == "serve.admit"),
        "evictions": sum(1 for r in records
                         if r.get("decision") == "serve.evict"),
        "plan": ({"prefill": [plan.get("prefill_backend"),
                              plan.get("prefill_chunks")],
                  "decode": [plan.get("decode_backend"),
                             plan.get("decode_chunks")],
                  "heterogeneous": plan.get("heterogeneous")}
                 if plan else None),
        "slo_breaches": {
            "ttft": sum(1 for r in slo if r["target"] == "ttft"),
            "tpot": sum(1 for r in slo if r["target"] == "tpot"),
        } if slo else None,
    }


def render_serving_text(rep: dict) -> str:
    if not rep["steps"] and not rep["requests_completed"]:
        return ("no serving records found (run `python -m "
                "flashmoe_tpu.serving --obs-dir ...` or the engine "
                "with a recorder first)")
    lines = [f"serving: {rep['requests_completed']} requests over "
             f"{rep['steps']} engine steps, {rep['tokens']} tokens"
             + (f" ({rep['tokens_per_sec']} tok/s)"
                if rep.get("tokens_per_sec") else "")]
    if rep.get("ttft_ms"):
        t = rep["ttft_ms"]
        lines.append(f"  TTFT ms: mean {t['mean']}  p50 {t['p50']}  "
                     f"p99 {t['p99']}  max {t['max']}")
    if rep.get("tpot_ms"):
        t = rep["tpot_ms"]
        lines.append(f"  TPOT ms: mean {t['mean']}  p50 {t['p50']}")
    if rep.get("queue_depth"):
        lines.append(f"  queue depth: mean {rep['queue_depth']['mean']}"
                     f"  max {rep['queue_depth']['max']}"
                     + (f"   active: mean {rep['active']['mean']} max "
                        f"{rep['active']['max']}" if rep.get("active")
                        else ""))
    if rep.get("cache_occupancy"):
        o = rep["cache_occupancy"]
        lines.append(f"  cache occupancy: mean {o['mean']}  peak "
                     f"{o['peak']}")
    lines.append(f"  admissions {rep['admissions']}  evictions "
                 f"{rep['evictions']}")
    plan = rep.get("plan")
    if plan:
        lines.append(
            f"  planner split: prefill {plan['prefill'][0]}"
            f"(c{plan['prefill'][1]}) vs decode {plan['decode'][0]}"
            f"(c{plan['decode'][1]})"
            + ("  [heterogeneous]" if plan.get("heterogeneous")
               else "  [same plan]"))
    if rep.get("slo_breaches"):
        b = rep["slo_breaches"]
        lines.append(f"  SLO breaches: ttft={b['ttft']} "
                     f"tpot={b['tpot']}")
    return "\n".join(lines)


def postmortem_report(bundle: dict) -> dict:
    """Triage view of one loaded postmortem bundle
    (:func:`flashmoe_tpu.profiler.postmortem.load_bundle`)."""
    man = bundle.get("manifest") or {}
    decisions = bundle.get("decisions") or []
    by_name: dict[str, int] = {}
    for d in decisions:
        name = d.get("decision")
        if isinstance(name, str):
            by_name[name] = by_name.get(name, 0) + 1
    tb = bundle.get("traceback") or ""
    cfg = bundle.get("config") or {}
    env = bundle.get("env") or {}
    planner = bundle.get("planner") or {}
    flight = bundle.get("flight") or []
    losses = [r.get("loss") for r in flight
              if isinstance(r.get("loss"), (int, float))]
    return {
        "path": bundle.get("path"),
        "error": man.get("error"),
        "step": man.get("step"),
        "files": man.get("files", []),
        "traceback_tail": tb.strip().splitlines()[-12:],
        "decision_counts": by_name,
        "last_decisions": decisions[-8:],
        "flight_records": len(flight),
        "last_losses": [round(v, 4) for v in losses[-5:]],
        "config": {k: cfg[k] for k in (
            "num_experts", "expert_top_k", "hidden_size",
            "intermediate_size", "moe_backend", "wire_dtype",
            "a2a_chunks", "ep", "dp") if k in cfg},
        "backend": env.get("backend"),
        "jax": env.get("jax"),
        "last_path_select": planner.get("last_path_select"),
        "extra": man.get("extra"),
    }


def render_postmortem_text(rep: dict) -> str:
    lines = [f"postmortem bundle: {rep['path']}",
             f"  error: {rep['error']}",
             f"  step:  {rep['step']}    files: "
             f"{', '.join(rep['files'])}"]
    if rep.get("extra"):
        lines.append(f"  extra: {rep['extra']}")
    if rep.get("config"):
        lines.append("  config: " + ", ".join(
            f"{k}={v}" for k, v in rep["config"].items()))
    if rep.get("backend") or rep.get("jax"):
        lines.append(f"  env: jax {rep['jax']} on {rep['backend']}")
    if rep["decision_counts"]:
        lines.append("  decisions: " + ", ".join(
            f"{k}={v}" for k, v in sorted(rep["decision_counts"].items())))
    if rep["flight_records"]:
        lines.append(f"  flight: {rep['flight_records']} records, last "
                     f"losses {rep['last_losses']}")
    sel = rep.get("last_path_select")
    if sel:
        lines.append(f"  last path select: {sel.get('backend') or sel}")
    if rep["traceback_tail"]:
        lines.append("  traceback (tail):")
        for tline in rep["traceback_tail"]:
            lines.append(f"    {tline}")
    return "\n".join(lines)


def _bar(value: float, peak: float, width: int = 40) -> str:
    n = int(round(width * value / peak)) if peak > 0 else 0
    return "#" * max(n, 1 if value > 0 else 0)


def render_text(s: dict) -> str:
    lines = [f"records: {s['records']}  flight steps: {s['flight_steps']}"]
    imb = s["imbalance"]
    if imb["experts"]:
        lines.append("")
        lines.append(f"expert load histogram ({imb['experts']} experts, "
                     f"{imb['total_assignments']:g} assignments, "
                     f"imbalance max/mean = {imb['imbalance']}):")
        peak = max(imb["expert_load"])
        for i, v in enumerate(imb["expert_load"]):
            lines.append(f"  e{i:<3d} {v:>10.1f} {_bar(v, peak)}")
        if imb["mean_router_entropy"] is not None:
            lines.append(f"  mean router entropy: "
                         f"{imb['mean_router_entropy']} nats")
    drops = s["drops"]
    if drops["steps"]:
        lines.append("")
        lines.append(f"drop rate: mean {drops['mean_dropped_fraction']} "
                     f"max {drops['max_dropped_fraction']} over "
                     f"{drops['steps']} steps")
        for t in drops["timeline"][-10:]:
            lines.append(f"  step {t['step']}: dropped "
                         f"{t['dropped_fraction']}  capacity util "
                         f"{t['capacity_utilization']}")
    deg = s.get("degradation", {})
    if deg.get("steps_with_masking"):
        lines.append("")
        lines.append(f"tier-0 degradation: expert-health mask fired on "
                     f"{deg['steps_with_masking']} steps (max "
                     f"{deg['max_masked_experts']:g} masked experts)")
        for t in deg["timeline"][-10:]:
            lines.append(f"  step {t['step']}: masked "
                         f"{t['masked_experts']:g} experts, fraction "
                         f"{t['masked_fraction']}")
    wire = s.get("wire", {})
    if wire.get("steps_with_wire"):
        lines.append("")
        lines.append(f"wire compression: active on "
                     f"{wire['steps_with_wire']} layer-steps, round-trip "
                     f"quantization error mean {wire['mean_rtq_error']} "
                     f"max {wire['max_rtq_error']}")
    res = s.get("resilience", {})
    if res.get("events"):
        lines.append("")
        lines.append("resilience events: " + ", ".join(
            f"{k}={v}" for k, v in res["events"].items()))
        for dr in res["drains"][-5:]:
            lines.append(
                f"  drain at step {dr['step']} ({dr['source']}), "
                f"{dr['remaining_grace_s']:.1f}s grace left"
                if isinstance(dr.get("remaining_grace_s"), float)
                else f"  drain at step {dr['step']} ({dr['source']})")
        for r in res["resumes"][-5:]:
            lines.append(f"  resume #{r['incarnation']} at step "
                         f"{r['step']}: world={r['world']} "
                         f"(ep={r['ep']} x dp={r['dp']})")
    adapt = s.get("adaptation", {})
    if adapt.get("actions"):
        lines.append("")
        lines.append("self-healing controller: " + ", ".join(
            f"{k.split('.', 1)[1]}={v}"
            for k, v in sorted(adapt["actions"].items())))
        for t in adapt["timeline"]:
            kind = str(t["decision"]).split(".", 1)[1]
            head = f"  step {t.get('step')}: {kind}"
            if kind == "morph":
                head += (f" -> {t.get('backend')}"
                         f"{' (dropless)' if t.get('dropless') else ''}")
            elif kind == "replace":
                reps = t.get("replicas") or []
                head += (f" (replicas {reps})" if reps
                         else " (permutation only)")
            elif kind == "demotion_reset":
                head += f" dropped={t.get('dropped')}"
            lines.append(head)
            b, a = t.get("before"), t.get("after")
            if b and a:
                lines.append(
                    f"    imbalance {b['imbalance']} -> "
                    f"{a['imbalance']}, dropped "
                    f"{b['dropped_fraction']} -> "
                    f"{a['dropped_fraction']}")
    if s["phases"]:
        lines.append("")
        lines.append("phase times (mean):")
        for k, v in s["phases"].items():
            lines.append(f"  {k:<32s} {v:>10.3f}")
    drift = s["drift"]
    if drift["n"]:
        lines.append("")
        lines.append(f"planner drift: {drift['n']} comparisons, "
                     f"{drift['exceeded']} over threshold")
        for key, b in drift["by_path"].items():
            lines.append(
                f"  {key:<24s} n={b['n']} mean|rel|="
                f"{b['mean_abs_rel_error']} worst={b['worst_rel_error']}"
                f"{'  ** DRIFTING' if b['exceeded'] else ''}")
    if s["decisions"]:
        lines.append("")
        lines.append("decision records: " + ", ".join(s["decisions"]))
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m flashmoe_tpu.observe",
        description="Summarize flight-recorder / telemetry JSONL dumps")
    ap.add_argument("files", nargs="*", help="JSONL files to analyze")
    ap.add_argument("--json", action="store_true",
                    help="emit one machine-readable JSON document")
    ap.add_argument("--ledger", action="store_true",
                    help="render the per-phase cost-ledger report "
                         "(ledger.jsonl / phase_drift decision files)")
    ap.add_argument("--serving", action="store_true",
                    help="render the serving report (engine "
                         "flight/decision dumps: TTFT/TPOT, queue "
                         "depth, cache occupancy, planner split)")
    ap.add_argument("--postmortem", metavar="DIR",
                    help="render a triage report of the crash postmortem "
                         "bundle(s) under DIR")
    args = ap.parse_args(argv)

    if args.postmortem:
        from flashmoe_tpu.profiler import postmortem as pm

        bundles = pm.find_bundles(args.postmortem)
        if not bundles:
            print(f"no postmortem bundles under {args.postmortem!r}",
                  file=sys.stderr)
            return 2
        reports = [postmortem_report(pm.load_bundle(b)) for b in bundles]
        if args.json:
            json.dump({"bundles": reports}, sys.stdout)
            print()
        else:
            print("\n\n".join(render_postmortem_text(r) for r in reports))
        return 0

    if not args.files:
        ap.error("JSONL files required (or use --postmortem DIR)")
    records = load_jsonl(args.files)
    if not records:
        print("no parseable records found", file=sys.stderr)
        return 2
    if args.ledger:
        led = ledger_report(records)
        if args.json:
            json.dump(led, sys.stdout)
            print()
        else:
            print(render_ledger_text(led))
        return 0 if led["n"] or led["overlap"] else 2
    if args.serving:
        rep = serving_report(records)
        if args.json:
            json.dump(rep, sys.stdout)
            print()
        else:
            print(render_serving_text(rep))
        return 0 if rep["steps"] or rep["requests_completed"] else 2
    s = summarize(records)
    if args.json:
        json.dump(s, sys.stdout)
        print()
    else:
        print(render_text(s))
    return 0


if __name__ == "__main__":
    sys.exit(main())
