"""Flight-recorder analysis CLI: turn JSONL telemetry dumps into the
reports a perf postmortem starts from.

Input: any mix of JSONL files produced by this framework —

  * flight-recorder exports (``runtime.trainer.train(flight_path=...)``,
    records with ``step`` + optional per-layer ``moe`` stats),
  * telemetry decision logs (``Metrics.dump_decisions_jsonl`` — planner
    path selections and ``planner.drift`` comparisons),
  * bench.py output lines (``metric``/``value`` records with
    ``predicted_ms``/``prediction_error`` calibration fields),
  * metrics summaries (``Metrics.dump_jsonl`` — phase timers).

Output: an expert-load imbalance report (per-expert histogram), the
drop-rate timeline, a phase-time breakdown, and the planner drift report
(:func:`flashmoe_tpu.planner.drift.drift_report`).  ``--json`` emits one
machine-readable document instead of text.

Usage::

    python -m flashmoe_tpu.observe flight.jsonl [decisions.jsonl ...]
    python -m flashmoe_tpu.observe --json flight.jsonl
    python -m flashmoe_tpu.observe --ledger obs/ledger.jsonl
    python -m flashmoe_tpu.observe --serving obs/flight.jsonl obs/decisions.jsonl
    python -m flashmoe_tpu.observe --postmortem /path/to/bundles
    python -m flashmoe_tpu.observe --trace 3 obs/trace.jsonl
    python -m flashmoe_tpu.observe --merge obs/telemetry.*.jsonl
    python -m flashmoe_tpu.observe --regression --ci [obs/history.jsonl]

``--ledger`` renders the per-phase predicted-vs-measured cost ledger
(:mod:`flashmoe_tpu.profiler.ledger` artifacts / ``planner.phase_drift``
decision dumps); ``--serving`` renders the serving-engine report
(TTFT/TPOT percentiles through the shared bounded-memory quantile
sketch, queue depth, cache occupancy, the prefill-vs-decode planner
split — docs/SERVING.md); ``--postmortem`` renders a triage report of
the crash bundle(s) written by
:mod:`flashmoe_tpu.profiler.postmortem`; ``--trace <rid>`` renders one
request's end-to-end timeline (eviction gaps included) from
``serve_trace_span`` records; ``--merge`` folds per-host telemetry
shards into one fleet view; ``--regression`` runs the perf sentry over
``obs/history.jsonl`` (``--ci`` exits rc 2 on a tolerance breach) —
docs/OBSERVABILITY.md "Live telemetry plane".
"""

from __future__ import annotations

import argparse
import json
import sys


def load_jsonl(paths: list[str]) -> list[dict]:
    """All parseable JSON objects from the given files, in order.
    Unparseable lines (partial writes, comments) are skipped."""
    records: list[dict] = []
    for path in paths:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line or line.startswith("#"):
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if isinstance(rec, dict):
                    records.append(rec)
    return records


def _layer_stats(rec: dict) -> list[dict]:
    """Per-layer MoE stat dicts of one flight record (either the
    trainer's ``moe`` list or a bare top-level stats record)."""
    if isinstance(rec.get("moe"), list):
        return [m for m in rec["moe"] if isinstance(m, dict)]
    if isinstance(rec.get("expert_load"), list):
        return [rec]
    return []


def imbalance_report(flight: list[dict]) -> dict:
    """Aggregate expert-load histogram across steps and layers."""
    load: list[float] = []
    imb = []
    ent = []
    for rec in flight:
        for m in _layer_stats(rec):
            el = m.get("expert_load") or []
            if len(load) < len(el):
                load.extend([0.0] * (len(el) - len(load)))
            for i, v in enumerate(el):
                load[i] += float(v)
            if "imbalance" in m:
                imb.append(float(m["imbalance"]))
            if "router_entropy" in m:
                ent.append(float(m["router_entropy"]))
    total = sum(load)
    mean = total / len(load) if load else 0.0
    return {
        "experts": len(load),
        "expert_load": [round(v, 1) for v in load],
        "total_assignments": round(total, 1),
        "imbalance": round(max(load) / mean, 4) if mean > 0 else None,
        "mean_step_imbalance": round(sum(imb) / len(imb), 4) if imb
        else None,
        "mean_router_entropy": round(sum(ent) / len(ent), 4) if ent
        else None,
    }


def drop_report(flight: list[dict]) -> dict:
    """Drop-rate / capacity-utilization timeline and aggregates."""
    timeline = []
    for rec in flight:
        stats = _layer_stats(rec)
        drops = [float(m["dropped_fraction"]) for m in stats
                 if "dropped_fraction" in m]
        utils = [float(m["capacity_utilization"]) for m in stats
                 if "capacity_utilization" in m]
        if drops:
            timeline.append({
                "step": rec.get("step"),
                "dropped_fraction": round(sum(drops) / len(drops), 6),
                "capacity_utilization": round(sum(utils) / len(utils), 6)
                if utils else None,
            })
    dr = [t["dropped_fraction"] for t in timeline]
    return {
        "steps": len(timeline),
        "mean_dropped_fraction": round(sum(dr) / len(dr), 6) if dr
        else None,
        "max_dropped_fraction": round(max(dr), 6) if dr else None,
        "timeline": timeline,
    }


def degradation_report(flight: list[dict]) -> dict:
    """Tier-0 fault-tolerance timeline (docs/RESILIENCE.md): steps where
    the expert-health mask fired (``degrade_unhealthy_experts``), with
    masked-expert counts and masked assignment fractions."""
    timeline = []
    for rec in flight:
        stats = _layer_stats(rec)
        masked = [float(m["masked_experts"]) for m in stats
                  if m.get("masked_experts")]
        frac = [float(m["masked_fraction"]) for m in stats
                if "masked_fraction" in m]
        if masked:
            timeline.append({
                "step": rec.get("step"),
                "masked_experts": round(sum(masked), 2),
                "masked_fraction": round(sum(frac) / len(frac), 6)
                if frac else None,
            })
    return {
        "steps_with_masking": len(timeline),
        "max_masked_experts": max((t["masked_experts"] for t in timeline),
                                  default=0.0),
        "timeline": timeline,
    }


def wire_report(flight: list[dict]) -> dict:
    """Wire-compression health (ops/wire.py): the round-trip
    quantization-error proxy the EP layers attach to MoEStats when a
    ``wire_dtype`` is on.  Steps where the wire was active (error > 0),
    mean/max error — a rising error flags payload distributions the fp8
    wire no longer represents well."""
    errs = []
    dcn_errs = []
    for rec in flight:
        for m in _layer_stats(rec):
            e = m.get("wire_rtq_error")
            if isinstance(e, (int, float)) and e > 0:
                errs.append(float(e))
            e = m.get("wire_rtq_error_dcn")
            if isinstance(e, (int, float)) and e > 0:
                dcn_errs.append(float(e))
    return {
        "steps_with_wire": len(errs),
        "mean_rtq_error": round(sum(errs) / len(errs), 6) if errs
        else None,
        "max_rtq_error": round(max(errs), 6) if errs else None,
        # the cross-slice hop's own wire (wire_dtype_dcn), tracked
        # separately so an fp8 DCN hop's loss never hides in (or
        # inflates) the in-slice number
        "steps_with_dcn_wire": len(dcn_errs),
        "mean_dcn_rtq_error": (round(sum(dcn_errs) / len(dcn_errs), 6)
                               if dcn_errs else None),
        "max_dcn_rtq_error": round(max(dcn_errs), 6) if dcn_errs
        else None,
    }


def quant_report(flight: list[dict]) -> dict:
    """Quantized-expert-store health (flashmoe_tpu/quant/): the
    weight-space round-trip error proxy the layers attach to MoEStats
    when ``MoEConfig.expert_quant`` is on.  Non-zero on fake-quant runs
    (the real quantization loss); pre-quantized states report ~0 here —
    their baked loss lives in the checkpoint's quant metadata block."""
    errs = []
    for rec in flight:
        for m in _layer_stats(rec):
            e = m.get("quant_error")
            if isinstance(e, (int, float)) and e > 0:
                errs.append(float(e))
    return {
        "steps_with_quant": len(errs),
        "mean_quant_error": round(sum(errs) / len(errs), 6) if errs
        else None,
        "max_quant_error": round(max(errs), 6) if errs else None,
    }


def resilience_report(records: list[dict]) -> dict:
    """Fault-tolerance narrative from the decision stream
    (docs/RESILIENCE.md): how often each recovery rung fired, every
    drain with its remaining grace, and every supervised resume with
    the world it landed on — the loss-of-work story of the run."""
    by_name: dict[str, int] = {}
    drains = []
    resumes = []
    for rec in records:
        name = rec.get("decision")
        if not isinstance(name, str):
            continue
        by_name[name] = by_name.get(name, 0) + 1
        if name == "preempt.drain":
            drains.append({
                "step": rec.get("step"),
                "source": rec.get("source"),
                "remaining_grace_s": rec.get("remaining_grace_s"),
            })
        elif name == "supervisor.resume":
            resumes.append({
                "incarnation": rec.get("incarnation"),
                "step": rec.get("step"),
                "world": rec.get("world"),
                "ep": rec.get("ep"), "dp": rec.get("dp"),
            })
    interesting = ("trainer.grad_skip", "checkpoint.fallback",
                   "checkpoint.emergency_save", "checkpoint.async_error",
                   "planner.fallback", "preempt.notice", "preempt.drain",
                   "supervisor.resume", "slo.breach", "slo.recovered",
                   "postmortem.saved")
    return {
        "events": {k: by_name[k] for k in interesting if k in by_name},
        "drains": drains,
        "resumes": resumes,
        "worlds": sorted({r["world"] for r in resumes
                          if r.get("world") is not None}),
    }


def adaptation_report(records: list[dict]) -> dict:
    """The self-healing controller's story (docs/RESILIENCE.md
    "Self-healing controller"): every ``controller.*`` decision in
    timeline order, and — for each morph/re-placement — the mean MoE
    imbalance and dropped fraction over the flight-recorder steps
    BEFORE vs AFTER the action, so the report answers "did the repair
    actually repair" without replaying the run."""
    acts = [r for r in records
            if str(r.get("decision", "")).startswith("controller.")]
    flight = []
    for rec in records:
        ms = _layer_stats(rec)
        if ms and isinstance(rec.get("step"), (int, float)):
            flight.append((int(rec["step"]),
                           max(m.get("imbalance", 0.0) for m in ms),
                           max(m.get("dropped_fraction", 0.0)
                               for m in ms)))
    flight.sort()

    def window(step, after: bool, n: int = 5):
        rows = [(i, d) for s, i, d in flight
                if (s >= step if after else s < step)]
        rows = rows[:n] if after else rows[-n:]
        if not rows:
            return None
        return {"imbalance": round(sum(r[0] for r in rows)
                                   / len(rows), 3),
                "dropped_fraction": round(sum(r[1] for r in rows)
                                          / len(rows), 4)}

    timeline = []
    for a in acts:
        entry = {"decision": a.get("decision"), "step": a.get("step"),
                 "trigger": a.get("trigger")}
        if a["decision"] == "controller.morph":
            entry.update(backend=a.get("backend"),
                         dropless=a.get("dropless"),
                         overrides=a.get("overrides"),
                         reason=a.get("reason"))
        elif a["decision"] == "controller.replace":
            entry.update(replicas=a.get("replicas"),
                         rates=a.get("rates"),
                         device_share_before=a.get(
                             "device_share_before"))
        elif a["decision"] == "controller.demotion_reset":
            entry.update(dropped=a.get("dropped"),
                         world=a.get("world"))
        if a["decision"] in ("controller.morph", "controller.replace") \
                and isinstance(a.get("step"), (int, float)):
            entry["before"] = window(int(a["step"]), after=False)
            entry["after"] = window(int(a["step"]), after=True)
        timeline.append(entry)
    counts: dict[str, int] = {}
    for a in acts:
        counts[a["decision"]] = counts.get(a["decision"], 0) + 1
    return {"actions": counts, "timeline": timeline}


def phase_report(records: list[dict]) -> dict:
    """Mean of every ``*_ms`` field across records (flight ``step_ms``,
    bench leg timings) plus ``*_ms_p50`` phase timers from metrics
    summaries — the comm/compute phase breakdown."""
    sums: dict[str, float] = {}
    counts: dict[str, int] = {}
    # prediction fields are drift inputs, not phases — keep them out
    skip = {"predicted_ms", "xla_predicted_ms", "measured_ms"}
    for rec in records:
        for k, v in rec.items():
            if not isinstance(v, (int, float)) or isinstance(v, bool):
                continue
            if k in skip:
                continue
            if k.endswith("_ms") or k.endswith("_ms_p50"):
                sums[k] = sums.get(k, 0.0) + float(v)
                counts[k] = counts.get(k, 0) + 1
    return {k: round(sums[k] / counts[k], 4) for k in sorted(sums)}


def summarize(records: list[dict]) -> dict:
    """The full analysis document over a mixed record pile."""
    from flashmoe_tpu.planner.drift import drift_report

    flight = [r for r in records if _layer_stats(r) or "step" in r]
    return {
        "records": len(records),
        "flight_steps": len(flight),
        "imbalance": imbalance_report(flight),
        "drops": drop_report(flight),
        "degradation": degradation_report(flight),
        "wire": wire_report(flight),
        "quant": quant_report(flight),
        "resilience": resilience_report(records),
        "adaptation": adaptation_report(records),
        "phases": phase_report(records),
        "drift": drift_report(records),
        "decisions": sorted({r["decision"] for r in records
                             if isinstance(r.get("decision"), str)}),
    }


def ledger_report(records: list[dict]) -> dict:
    """The cost-ledger view: per-(path, chunks, wire) per-phase
    measured-vs-predicted drift, from ``ledger.jsonl`` rows
    (:func:`flashmoe_tpu.profiler.ledger.run_ledger_matrix`) and/or
    ``planner.phase_drift`` decision records — the per-phase answer to
    "which term of the cost model is lying".  Overlap cross-check rows
    (``record == "overlap"``) are summarized separately."""
    points: dict[tuple, dict] = {}
    overlaps = []
    for rec in records:
        if rec.get("record") == "overlap" or (
                "measured_fraction" in rec and "chunks" in rec):
            overlaps.append({
                "path": rec.get("point") or rec.get("path"),
                "d": rec.get("d"),
                "chunks": rec.get("chunks"), "wire": rec.get("wire"),
                "measured_fraction": rec.get("measured_fraction"),
                "predicted_fraction": rec.get("predicted_fraction"),
                "exceeded": rec.get("exceeded"),
            })
            continue
        phase = rec.get("phase")
        if not isinstance(phase, str) or "measured_ms" not in rec:
            continue
        # ledger.jsonl rows carry both the matrix point name ("flat")
        # and the planner path ("collective"); group/display by the
        # point name when present (decision records only have the path)
        key = (rec.get("point") or rec.get("path"),
               rec.get("chunks", 1), rec.get("wire", "off"))
        pt = points.setdefault(key, {
            "point": key[0], "path": rec.get("path"),
            "chunks": key[1], "wire": key[2], "phases": {}})
        pt["phases"][phase] = {
            "measured_ms": rec.get("measured_ms"),
            "predicted_ms": rec.get("predicted_ms"),
            "rel_error": rec.get("rel_error"),
            "exceeded": bool(rec.get("exceeded")),
        }
    phase_names = sorted({ph for pt in points.values()
                          for ph in pt["phases"]})
    n = sum(len(pt["phases"]) for pt in points.values())
    return {
        "n": n,
        "points": [points[k] for k in sorted(
            points, key=lambda k: (str(k[0]), k[1], str(k[2])))],
        "phases": phase_names,
        "exceeded": sum(1 for pt in points.values()
                        for p in pt["phases"].values() if p["exceeded"]),
        "overlap": overlaps,
    }


def render_ledger_text(led: dict) -> str:
    if not led["n"] and not led["overlap"]:
        return "no phase-ledger rows found (run `bench.py --profile` " \
               "or profiler.ledger.run_ledger_matrix first)"
    lines = []
    if led["n"]:
        lines += [f"cost ledger: {led['n']} phase comparisons over "
                  f"{len(led['points'])} config points, "
                  f"{led['exceeded']} over the drift threshold", ""]
        head = f"{'point':<34s}" + "".join(
            f"{ph.removeprefix('moe.'):>16s}" for ph in led["phases"])
        lines.append(head + "   (rel err, measured/predicted - 1)")
        for pt in led["points"]:
            label = (f"{pt.get('point') or pt['path']} "
                     f"c={pt['chunks']} wire={pt['wire']}")
            cells = []
            for ph in led["phases"]:
                p = pt["phases"].get(ph)
                if p is None:
                    cells.append(f"{'-':>16s}")
                else:
                    mark = "**" if p["exceeded"] else "  "
                    cells.append(f"{p['rel_error']:>+13.1%}{mark} ")
            lines.append(f"{label:<34s}" + "".join(cells))
    if led["overlap"]:
        lines.append("")
        lines.append("overlap cross-check (fenced serial phase sum / "
                     "jitted step):")
        for o in led["overlap"]:
            lines.append(
                f"  {o['path']} d={o['d']} chunks={o['chunks']} "
                f"wire={o['wire']}: measured {o['measured_fraction']} "
                f"vs bound {o['predicted_fraction']}"
                f"{'  ** DRIFTING' if o['exceeded'] else ''}")
    return "\n".join(lines)


def serving_report(records: list[dict]) -> dict:
    """The serving engine's story (``--serving``): per-step
    ``serve_step`` flight records (queue depth, active requests, cache
    occupancy, tokens emitted), per-request TTFT/TPOT from
    ``serve_request`` records / ``serve.retire`` decisions, the
    admission/eviction narrative, the decode-vs-prefill planner split
    (``serve.plan``), and serving SLO breaches (``slo.breach`` with
    target ttft/tpot).

    Percentiles run through the shared bounded-memory quantile sketch
    (telemetry_plane/sketch.py) — the same definition the engine's live
    ``/metrics`` summaries use, nearest-rank exact below 64
    observations (= ``loadgen.pctl`` on every CI-sized drill) and
    O(1)-memory P² beyond, so a million-request dump aggregates in
    constant space instead of retaining full history."""
    from flashmoe_tpu.telemetry_plane.sketch import QuantileSketch

    steps = n_steps = 0
    tokens = 0
    wall_ms = 0.0
    tt, tp = QuantileSketch(), QuantileSketch()
    qd, occ, act = QuantileSketch(), QuantileSketch(), QuantileSketch()
    rids: set = set()
    seen_req_recs = False
    plan = None
    quant = None
    pools = None
    route_counts: dict = {}
    route_policies: dict = {}
    route_draining: list = []
    ho_n = 0
    ho_kb = 0.0
    ho_ms = 0.0
    ho_overlapped = ho_verdicts = 0
    ho_wire = None
    admissions = evictions = slo_ttft = slo_tpot = 0
    migrations: list = []
    crashes: list = []
    retries: list = []
    corrupts = 0
    sheds: dict = {}
    brownouts: dict = {}
    failovers: list = []
    partitions: list = []
    fences: list = []
    repairs: list = []
    stalls: list = []
    hb_misses = 0
    for r in records:
        kind, dec = r.get("kind"), r.get("decision")
        if kind == "serve_step":
            n_steps += 1
            tokens += int(r.get("tokens", 0))
            wall_ms += float(r.get("step_ms", 0.0))
            if isinstance(r.get("queue_depth"), (int, float)):
                qd.observe(r["queue_depth"])
            if isinstance(r.get("cache_occupancy"), (int, float)):
                occ.observe(r["cache_occupancy"])
            if isinstance(r.get("active"), (int, float)):
                act.observe(r["active"])
        elif kind == "serve_request" or (dec == "serve.retire"
                                         and not seen_req_recs):
            # serve_request flight records win; retire decisions are
            # the fallback when no flight dump is present (same values)
            if kind == "serve_request" and not seen_req_recs:
                seen_req_recs = True
                tt, tp = QuantileSketch(), QuantileSketch()
                rids = set()
            rids.add(r.get("rid"))
            if isinstance(r.get("ttft_ms"), (int, float)):
                tt.observe(r["ttft_ms"])
            if isinstance(r.get("tpot_ms"), (int, float)):
                tp.observe(r["tpot_ms"])
        if dec == "serve.plan":
            plan = r
        elif dec == "serve.quant":
            quant = r
        elif dec == "serve.pools":
            pools = r
        elif dec == "fabric.route":
            rep_id = r.get("replica")
            route_counts[rep_id] = route_counts.get(rep_id, 0) + 1
            pol = r.get("policy")
            route_policies[pol] = route_policies.get(pol, 0) + 1
            route_draining = r.get("draining") or []
        elif dec == "fabric.handoff":
            ho_n += 1
            ho_kb += float(r.get("payload_kb", 0.0))
            ho_ms += float(r.get("modeled_dcn_ms", 0.0))
            if r.get("overlapped") is not None:
                ho_verdicts += 1
                ho_overlapped += int(bool(r.get("overlapped")))
            ho_wire = r.get("wire", ho_wire)
        elif dec == "serve.admit":
            admissions += 1
        elif dec == "serve.evict":
            evictions += 1
        elif dec == "fabric.migrate":
            migrations.append(r)
        elif dec == "fabric.replica_crash":
            crashes.append(r)
        elif dec == "fabric.handoff_retry":
            retries.append(r)
        elif dec == "fabric.handoff_corrupt":
            corrupts += 1
        elif dec == "frontdoor.shed":
            mode = str(r.get("mode") or "reject")
            sheds[mode] = sheds.get(mode, 0) + 1
        elif dec == "frontdoor.brownout":
            st = str(r.get("state") or "?")
            brownouts[st] = brownouts.get(st, 0) + 1
        elif dec == "frontdoor.failover":
            failovers.append(r)
        elif dec == "fabric.partition":
            partitions.append(r)
        elif dec == "frontdoor.fence":
            fences.append(r)
        elif dec == "frontdoor.lease_repair":
            repairs.append(r)
        elif dec == "fabric.heartbeat_stall":
            stalls.append(r)
        elif dec == "fabric.heartbeat_miss":
            hb_misses += 1
        elif dec == "slo.breach":
            if r.get("target") == "ttft":
                slo_ttft += 1
            elif r.get("target") == "tpot":
                slo_tpot += 1
    steps = n_steps

    def rnd(v, nd=3):
        return round(v, nd) if v is not None else None

    slo = slo_ttft or slo_tpot
    return {
        "steps": steps,
        "requests_completed": len(rids),
        "tokens": tokens,
        "tokens_per_sec": round(tokens / (wall_ms / 1e3), 1)
        if wall_ms > 0 else None,
        "ttft_ms": {"mean": rnd(tt.mean), "p50": rnd(tt.quantile(0.5)),
                    "p99": rnd(tt.quantile(0.99)),
                    "max": rnd(tt.max)} if tt.n else None,
        "tpot_ms": {"mean": rnd(tp.mean),
                    "p50": rnd(tp.quantile(0.5))} if tp.n else None,
        "queue_depth": {"mean": rnd(qd.mean, 2),
                        "max": int(qd.max)} if qd.n else None,
        "active": {"mean": rnd(act.mean, 2),
                   "max": int(act.max)} if act.n else None,
        "cache_occupancy": {"mean": rnd(occ.mean, 4),
                            "peak": rnd(occ.max, 4)} if occ.n else None,
        "admissions": admissions,
        "evictions": evictions,
        "plan": ({"prefill": [plan.get("prefill_backend"),
                              plan.get("prefill_chunks")],
                  "decode": [plan.get("decode_backend"),
                             plan.get("decode_chunks")],
                  "heterogeneous": plan.get("heterogeneous")}
                 if plan else None),
        "slo_breaches": {"ttft": slo_ttft, "tpot": slo_tpot}
        if slo else None,
        # quantized expert storage: the HBM the narrow store freed,
        # expressed as the extra KV-cache pages that headroom buys on
        # this engine's page size (serve.quant decision)
        "quant": ({"expert_quant": quant.get("expert_quant"),
                   "freed_mb": quant.get("freed_mb"),
                   "extra_kv_pages": quant.get("extra_kv_pages"),
                   "num_pages": quant.get("num_pages")}
                  if quant else None),
        # disaggregated fabric: the Decider's prefill/decode pool split
        # (serve.pools), where the router placed requests
        # (fabric.route) and what the KV handoff link moved
        # (fabric.handoff)
        "pools": ({"prefill_devices": pools.get("prefill_devices"),
                   "decode_devices": pools.get("decode_devices"),
                   "prefill_ms": pools.get("prefill_ms"),
                   "decode_ms": pools.get("decode_ms"),
                   "prefill_mapping": pools.get("prefill_mapping"),
                   "decode_mapping": pools.get("decode_mapping"),
                   "decode_quant": pools.get("decode_quant"),
                   "kv_wire": pools.get("kv_wire")}
                  if pools else None),
        "fabric_route": ({
            "placements": {str(k): v for k, v
                           in sorted(route_counts.items())},
            "policies": dict(sorted(route_policies.items())),
            "draining": route_draining,
        } if route_counts else None),
        "fabric_handoff": ({
            "count": ho_n,
            "payload_kb": round(ho_kb, 3),
            "modeled_dcn_ms": round(ho_ms, 6),
            "overlapped_frac": (round(ho_overlapped / ho_verdicts, 3)
                                if ho_verdicts else None),
            "wire": ho_wire,
        } if ho_n else None),
        # the serving failure story (ISSUE 18/19): crash timeline,
        # migrations, retried handoffs, brownout shedding, front-door
        # failovers, wire partitions, lease fencing/repair and
        # heartbeat stalls — the section an incident review reads first
        "fabric_failures": _fabric_failures(
            crashes, migrations, retries, corrupts, sheds, brownouts,
            failovers, partitions, fences, repairs, stalls, hb_misses),
        # speculative decoding (ISSUE 20): acceptance economics and
        # controller spec-morphs, aggregated from the same records by
        # the flight-recorder consumer twin of the engine's counters
        "speculation": _speculation_section(records),
    }


def _speculation_section(records):
    """The ``--serving`` speculation section (None when the run never
    drafted and never morphed — a non-speculative dump stays
    byte-identical)."""
    from flashmoe_tpu.ops.stats import speculation_summary

    s = speculation_summary(records)
    if not (s["spec_drafted"] or s["steps_spec_on"]
            or s["spec_morphs"]):
        return None
    return s


def _fabric_failures(crashes, migrations, retries, corrupts, sheds,
                     brownouts, failovers, partitions=(), fences=(),
                     repairs=(), stalls=(), hb_misses=0):
    """Aggregate the serving fault-tolerance decisions into the
    ``--serving`` report's failure section (None when the run saw no
    failure activity — the common case stays quiet)."""
    if not (crashes or migrations or retries or corrupts
            or sheds or brownouts or failovers or partitions
            or fences or repairs or stalls or hb_misses):
        return None

    def hist(values):
        out: dict = {}
        for v in values:
            out[str(v)] = out.get(str(v), 0) + 1
        return dict(sorted(out.items()))

    mig_paths = hist(f"r{m.get('from_replica')}->r{m.get('to_replica')}"
                     for m in migrations)
    return {
        "crashes": [{"replica": c.get("replica"),
                     "step": c.get("step"),
                     "in_flight": c.get("in_flight"),
                     "queued": c.get("queued")} for c in crashes],
        "migrations": {
            "total": len(migrations),
            "resumed_mid_decode": sum(bool(m.get("resumed"))
                                      for m in migrations),
            "paths": mig_paths,
        },
        "handoff_retries": {
            "total": len(retries),
            "reasons": hist(r.get("reason") for r in retries),
            "wasted_ms": round(sum(float(r.get("wasted_ms", 0.0))
                                   for r in retries), 3),
            "backoff_ms_hist": hist(r.get("backoff_ms")
                                    for r in retries),
        },
        "corrupt_transfers": corrupts,
        "shed": dict(sorted(sheds.items())),
        "brownout_transitions": dict(sorted(brownouts.items())),
        "failovers": {
            "total": len(failovers),
            "max_epoch": max((int(f.get("epoch", 0))
                              for f in failovers), default=0),
            "paths": hist(f"p{f.get('from_peer')}->p{f.get('to_peer')}"
                          for f in failovers),
        },
        # the cross-process arms (ISSUE 19): socket-wire partition
        # windows, the lease store's refused stale-epoch writes (the
        # split-brain verdict) and torn-tail repairs, and the
        # sub-step heartbeat detections
        "partitions": ({
            "total": len(partitions),
            "injected": sum(bool(p.get("injected")) for p in partitions),
            "real_resets": sum(not p.get("injected")
                               for p in partitions),
            "dropped_kb": round(sum(float(p.get("dropped_bytes") or 0)
                                    for p in partitions) / 1024, 3),
            "windows": hist(f"t{p.get('transfer')}"
                            for p in partitions),
        } if partitions else None),
        "lease_fences": ({
            "total": len(fences),
            "refused": sum(bool(f.get("refused")) for f in fences),
            "split_brain_averted": all(f.get("refused")
                                       for f in fences),
            "stale_epochs": hist(f.get("stale_epoch") for f in fences),
            "claimants": hist(f"p{f.get('claimant')}" for f in fences),
        } if fences else None),
        "lease_repairs": ({
            "total": len(repairs),
            "torn_bytes": sum(int(r.get("torn_bytes") or 0)
                              for r in repairs),
            "restored_epochs": hist(r.get("restored_epoch")
                                    for r in repairs),
        } if repairs else None),
        "heartbeat": ({
            "stalls": [{"replica": s.get("replica"),
                        "step": s.get("step"),
                        "detect_ms": s.get("detect_ms")}
                       for s in stalls],
            "misses": hb_misses,
        } if (stalls or hb_misses) else None),
    }


def render_serving_text(rep: dict) -> str:
    if not rep["steps"] and not rep["requests_completed"]:
        return ("no serving records found (run `python -m "
                "flashmoe_tpu.serving --obs-dir ...` or the engine "
                "with a recorder first)")
    lines = [f"serving: {rep['requests_completed']} requests over "
             f"{rep['steps']} engine steps, {rep['tokens']} tokens"
             + (f" ({rep['tokens_per_sec']} tok/s)"
                if rep.get("tokens_per_sec") else "")]
    if rep.get("ttft_ms"):
        t = rep["ttft_ms"]
        lines.append(f"  TTFT ms: mean {t['mean']}  p50 {t['p50']}  "
                     f"p99 {t['p99']}  max {t['max']}")
    if rep.get("tpot_ms"):
        t = rep["tpot_ms"]
        lines.append(f"  TPOT ms: mean {t['mean']}  p50 {t['p50']}")
    if rep.get("queue_depth"):
        lines.append(f"  queue depth: mean {rep['queue_depth']['mean']}"
                     f"  max {rep['queue_depth']['max']}"
                     + (f"   active: mean {rep['active']['mean']} max "
                        f"{rep['active']['max']}" if rep.get("active")
                        else ""))
    if rep.get("cache_occupancy"):
        o = rep["cache_occupancy"]
        lines.append(f"  cache occupancy: mean {o['mean']}  peak "
                     f"{o['peak']}")
    lines.append(f"  admissions {rep['admissions']}  evictions "
                 f"{rep['evictions']}")
    plan = rep.get("plan")
    if plan:
        lines.append(
            f"  planner split: prefill {plan['prefill'][0]}"
            f"(c{plan['prefill'][1]}) vs decode {plan['decode'][0]}"
            f"(c{plan['decode'][1]})"
            + ("  [heterogeneous]" if plan.get("heterogeneous")
               else "  [same plan]"))
    if rep.get("quant"):
        q = rep["quant"]
        lines.append(
            f"  quantized experts: {q['expert_quant']} freed "
            f"{q['freed_mb']} MB of weight HBM = +{q['extra_kv_pages']} "
            f"KV pages of headroom (pool {q['num_pages']})")
    if rep.get("pools"):
        p = rep["pools"]
        det = ""
        if p.get("prefill_mapping"):
            det = (f"  [{p['prefill_mapping']} vs {p['decode_mapping']}"
                   + (f", decode quant {p['decode_quant']}"
                      if p.get("decode_quant") else "")
                   + (f", kv wire {p['kv_wire']}"
                      if p.get("kv_wire") else "") + "]")
        lines.append(
            f"  pools: prefill {len(p['prefill_devices'] or [])} dev "
            f"({p['prefill_ms']} ms) / decode "
            f"{len(p['decode_devices'] or [])} dev ({p['decode_ms']} ms)"
            + det)
    if rep.get("fabric_route"):
        fr = rep["fabric_route"]
        plc = " ".join(f"r{k}:{v}" for k, v in fr["placements"].items())
        pol = " ".join(f"{k}={v}" for k, v in fr["policies"].items())
        lines.append(f"  fabric router: {plc}  ({pol})"
                     + (f"  draining={fr['draining']}"
                        if fr.get("draining") else ""))
    if rep.get("fabric_handoff"):
        h = rep["fabric_handoff"]
        lines.append(
            f"  kv handoff: {h['count']} transfers, "
            f"{h['payload_kb']} KB, modeled DCN {h['modeled_dcn_ms']} ms"
            + (f", {h['overlapped_frac'] * 100:.0f}% hidden under "
               f"decode" if h.get("overlapped_frac") is not None
               else "")
            + (f"  [wire {h['wire']}]"
               if h.get("wire") not in (None, "off") else ""))
    if rep.get("slo_breaches"):
        b = rep["slo_breaches"]
        lines.append(f"  SLO breaches: ttft={b['ttft']} "
                     f"tpot={b['tpot']}")
    sp = rep.get("speculation")
    if sp:
        lines.append(
            f"  speculation: {sp['spec_accepted']}/{sp['spec_drafted']}"
            f" drafts accepted ({sp['accept_rate']:.1%}), "
            f"{sp['spec_tokens_per_step']:.2f} tokens/verify-step over "
            f"{sp['spec_steps']} verify steps"
            + (f"  [{sp['spec_morphs']} spec morph(s) — controller "
               f"switched speculation off]" if sp["spec_morphs"]
               else ""))
    ff = rep.get("fabric_failures")
    if ff:
        lines.append("  -- failures --")
        for c in ff["crashes"]:
            lines.append(
                f"  replica crash: r{c['replica']} at step {c['step']} "
                f"({c['in_flight']} in flight, {c['queued']} queued)")
        mg = ff["migrations"]
        if mg["total"]:
            paths = " ".join(f"{k}:{v}" for k, v
                             in mg["paths"].items())
            lines.append(
                f"  migrations: {mg['total']} "
                f"({mg['resumed_mid_decode']} resumed mid-decode)  "
                f"{paths}")
        hr = ff["handoff_retries"]
        if hr["total"]:
            reasons = " ".join(f"{k}={v}" for k, v
                               in hr["reasons"].items())
            backoff = " ".join(f"{k}ms:{v}" for k, v
                               in hr["backoff_ms_hist"].items())
            lines.append(
                f"  handoff retries: {hr['total']} ({reasons}), wasted "
                f"{hr['wasted_ms']} ms on the wire, backoff {backoff}")
        if ff.get("corrupt_transfers"):
            lines.append(f"  corrupt transfers: "
                         f"{ff['corrupt_transfers']} (CRC named the "
                         f"pages; all re-sent)")
        if ff.get("shed"):
            shed = " ".join(f"{k}={v}" for k, v in ff["shed"].items())
            lines.append(f"  brownout shed admissions: {shed}")
        if ff.get("brownout_transitions"):
            tr = " ".join(f"{k}={v}" for k, v
                          in ff["brownout_transitions"].items())
            lines.append(f"  brownout transitions: {tr}")
        fo = ff["failovers"]
        if fo["total"]:
            paths = " ".join(f"{k}:{v}" for k, v
                             in fo["paths"].items())
            lines.append(
                f"  front-door failovers: {fo['total']} leases moved "
                f"(max epoch {fo['max_epoch']})  {paths}")
        if ff.get("partitions"):
            pt = ff["partitions"]
            wins = " ".join(f"{k}:{v}" for k, v
                            in pt["windows"].items())
            lines.append(
                f"  wire partitions: {pt['total']} "
                f"({pt['injected']} injected, {pt['real_resets']} real "
                f"resets), {pt['dropped_kb']} KB torn mid-stream  "
                f"{wins}")
        if ff.get("lease_fences"):
            lf = ff["lease_fences"]
            who = " ".join(f"{k}:{v}" for k, v
                           in lf["claimants"].items())
            verdict = ("split brain AVERTED"
                       if lf["split_brain_averted"]
                       else "SPLIT BRAIN: a stale write was accepted")
            lines.append(
                f"  lease fences: {lf['refused']}/{lf['total']} "
                f"stale-epoch writes refused ({verdict})  {who}")
        if ff.get("lease_repairs"):
            lr = ff["lease_repairs"]
            eps = " ".join(f"e{k}:{v}" for k, v
                           in lr["restored_epochs"].items())
            lines.append(
                f"  lease repairs: {lr['total']} torn tails rolled "
                f"back ({lr['torn_bytes']} bytes refused)  "
                f"restored {eps}")
        if ff.get("heartbeat"):
            hb = ff["heartbeat"]
            for s in hb["stalls"]:
                lines.append(
                    f"  heartbeat stall: r{s['replica']} declared at "
                    f"step {s['step']} (detected in "
                    f"{s['detect_ms']} virtual ms)")
            if hb["misses"]:
                lines.append(f"  heartbeat misses observed: "
                             f"{hb['misses']}")
    return "\n".join(lines)


def trace_report(records: list[dict], rid: int) -> dict:
    """One request's end-to-end timeline (``--trace <rid>``) from the
    tracer's ``serve_trace_span`` JSONL records: every lifecycle span
    in timeline order, eviction gaps flagged, and the totals a latency
    investigation starts from (queue wait vs prefill vs decode-window
    time)."""
    raw = [r for r in records if r.get("kind") == "serve_trace_span"
           and r.get("rid") == rid]
    # merged fleet shards record the SAME span in more than one file
    # (the prefill pool and the decode pool both witness a handoff):
    # identical (name, ts, dur, step) rows collapse to one so the
    # timeline reads contiguous, not twice as long
    spans, seen = [], set()
    for s in raw:
        key = (s.get("name"), s.get("ts_ms"), s.get("dur_ms"),
               s.get("step"), s.get("resumed"))
        if key in seen:
            continue
        seen.add(key)
        spans.append(s)
    spans.sort(key=lambda s: s.get("ts_ms", 0.0))
    known = sorted({r.get("rid") for r in records
                    if r.get("kind") == "serve_trace_span"})
    by_phase: dict[str, float] = {}
    for s in spans:
        if s.get("name") != "serve.step":   # windows overlap the rest
            by_phase[s["name"]] = by_phase.get(s["name"], 0.0) \
                + float(s.get("dur_ms", 0.0))
    gaps = [s for s in spans if s.get("name") == "serve.queued"
            and s.get("resumed")]
    return {
        "rid": rid,
        "found": bool(spans),
        "spans_deduped": len(raw) - len(spans),
        "known_rids": known,
        "trace_id": spans[0].get("trace_id") if spans else None,
        "spans": spans,
        "evictions": int(spans[0].get("evictions", 0)) if spans else 0,
        "eviction_gap_ms": round(sum(float(s.get("dur_ms", 0.0))
                                     for s in gaps), 3),
        "phase_ms": {k: round(v, 3) for k, v in sorted(by_phase.items())},
        # max END over all spans: the last-STARTING span may end before
        # an earlier step window does
        "total_ms": round(max(s["ts_ms"] + s["dur_ms"] for s in spans)
                          - spans[0]["ts_ms"], 3) if spans else None,
    }


def render_trace_text(rep: dict) -> str:
    if not rep["found"]:
        known = ", ".join(str(r) for r in rep["known_rids"]) or "none"
        return (f"no trace spans for request {rep['rid']} (traced "
                f"requests: {known}) — run the drill with tracing on "
                f"(`python -m flashmoe_tpu.serving --trace ...`)")
    lines = [f"request {rep['rid']} trace {rep['trace_id']}: "
             f"{len(rep['spans'])} spans, {rep['total_ms']} ms end to "
             f"end, {rep['evictions']} eviction(s)"
             + (f" ({rep['eviction_gap_ms']} ms re-queued)"
                if rep["evictions"] else "")
             + (f" [{rep['spans_deduped']} shard-duplicate span(s) "
                f"collapsed]" if rep.get("spans_deduped") else "")]
    for k, v in rep["phase_ms"].items():
        lines.append(f"  {k:<24s} {v:>10.3f} ms total")
    lines.append("  timeline:")
    t0 = rep["spans"][0]["ts_ms"]
    for s in rep["spans"]:
        mark = " <- eviction gap" if (s["name"] == "serve.queued"
                                      and s.get("resumed")) else ""
        lines.append(
            f"    +{s['ts_ms'] - t0:>10.3f} ms  {s['name']:<16s} "
            f"{s['dur_ms']:>10.3f} ms  step={s.get('step')}{mark}")
    return "\n".join(lines)


def merge_report(paths: list[str]) -> dict:
    """Fleet view over per-host telemetry shards (``--merge``): each
    input file is one host's JSONL dump (``telemetry.<host>.jsonl`` —
    telemetry_plane/server.py:host_shard_path, or any flight/decision
    file); records are tagged with their host, counted per host, and
    the union is summarized once — the mocked multi-slice drills
    (PR 12) read as ONE job instead of n disjoint dumps."""
    import os as _os

    hosts: dict[str, dict] = {}
    merged: list[dict] = []
    for path in paths:
        base = _os.path.basename(path)
        host = base
        if base.startswith("telemetry.") and base.endswith(".jsonl"):
            host = base[len("telemetry."):-len(".jsonl")]
        recs = load_jsonl([path])
        info = hosts.setdefault(host, {"records": 0, "files": []})
        info["records"] += len(recs)
        info["files"].append(base)
        steps = [r.get("step") for r in recs
                 if isinstance(r.get("step"), (int, float))]
        if steps:
            info["steps"] = [int(min(steps)), int(max(steps))]
        for r in recs:
            merged.append(dict(r, host=host))
    # a KV handoff is witnessed by BOTH pools (the prefill side prices
    # it, the decode side admits its pages): when the shards come from
    # the two pools the same transfer shows up twice — collapse on the
    # transfer's identity so fleet counts read per-transfer, not
    # per-witness
    deduped, seen, dropped = [], set(), 0
    for r in merged:
        if r.get("decision") == "fabric.handoff":
            key = (r.get("rid"), r.get("replica"), r.get("pages"),
                   r.get("modeled_dcn_ms"))
            if key in seen:
                dropped += 1
                continue
            seen.add(key)
        deduped.append(r)
    return {
        "hosts": hosts,
        "records": len(deduped),
        "handoffs_deduped": dropped,
        "fleet": summarize(deduped),
    }


def render_merge_text(rep: dict) -> str:
    lines = [f"fleet view: {len(rep['hosts'])} host shard(s), "
             f"{rep['records']} records"
             + (f" ({rep['handoffs_deduped']} double-witnessed "
                f"handoff(s) collapsed)"
                if rep.get("handoffs_deduped") else "")]
    for host in sorted(rep["hosts"]):
        info = rep["hosts"][host]
        steps = info.get("steps")
        lines.append(f"  {host}: {info['records']} records"
                     + (f", steps {steps[0]}..{steps[1]}" if steps
                        else ""))
    lines.append("")
    lines.append(render_text(rep["fleet"]))
    return "\n".join(lines)


def render_attribution_text(rep: dict) -> str:
    """``--attribution``: the fleet's latency budget with names on it
    (:func:`flashmoe_tpu.telemetry_plane.attribution.
    attribution_report`)."""
    lines = [f"latency attribution: {rep['requests']} retired "
             f"request(s)"
             + (f", {len(rep['spilled'])} spilled off their preferred "
                f"replica" if rep["spilled"] else "")]
    if rep["sum_violations"]:
        lines.append(f"  ** {len(rep['sum_violations'])} request(s) "
                     f"FAILED the 1% sum gate: "
                     f"{rep['sum_violations'][:8]}")
    lines.append("  fleet totals (where the milliseconds went):")
    for comp, ms in rep["totals_ms"].items():
        share = rep["shares"].get(comp, 0.0)
        dom = rep["dominant_counts"].get(comp, 0)
        lines.append(
            f"    {comp:<14s} {ms:>10.3f} ms  {share:>6.1%}"
            + (f"  dominant in {dom}" if dom else ""))
    lines.append("  per request:")
    for rid, att in rep["per_request"].items():
        lines.append(
            f"    rid={rid:<6} span {att['span_ms']:>10.3f} ms  "
            f"dominant={att['dominant']}"
            + ("" if att["sum_ok"]
               else f"  ** sum off by {att['rel_err']:.1%}"))
    return "\n".join(lines)


def postmortem_report(bundle: dict) -> dict:
    """Triage view of one loaded postmortem bundle
    (:func:`flashmoe_tpu.profiler.postmortem.load_bundle`)."""
    man = bundle.get("manifest") or {}
    decisions = bundle.get("decisions") or []
    by_name: dict[str, int] = {}
    for d in decisions:
        name = d.get("decision")
        if isinstance(name, str):
            by_name[name] = by_name.get(name, 0) + 1
    tb = bundle.get("traceback") or ""
    cfg = bundle.get("config") or {}
    env = bundle.get("env") or {}
    planner = bundle.get("planner") or {}
    flight = bundle.get("flight") or []
    losses = [r.get("loss") for r in flight
              if isinstance(r.get("loss"), (int, float))]
    return {
        "path": bundle.get("path"),
        "error": man.get("error"),
        "step": man.get("step"),
        "files": man.get("files", []),
        "traceback_tail": tb.strip().splitlines()[-12:],
        "decision_counts": by_name,
        "last_decisions": decisions[-8:],
        "flight_records": len(flight),
        "last_losses": [round(v, 4) for v in losses[-5:]],
        "config": {k: cfg[k] for k in (
            "num_experts", "expert_top_k", "hidden_size",
            "intermediate_size", "moe_backend", "wire_dtype",
            "a2a_chunks", "ep", "dp") if k in cfg},
        "backend": env.get("backend"),
        "jax": env.get("jax"),
        "last_path_select": planner.get("last_path_select"),
        "extra": man.get("extra"),
    }


def render_postmortem_text(rep: dict) -> str:
    lines = [f"postmortem bundle: {rep['path']}",
             f"  error: {rep['error']}",
             f"  step:  {rep['step']}    files: "
             f"{', '.join(rep['files'])}"]
    if rep.get("extra"):
        lines.append(f"  extra: {rep['extra']}")
    if rep.get("config"):
        lines.append("  config: " + ", ".join(
            f"{k}={v}" for k, v in rep["config"].items()))
    if rep.get("backend") or rep.get("jax"):
        lines.append(f"  env: jax {rep['jax']} on {rep['backend']}")
    if rep["decision_counts"]:
        lines.append("  decisions: " + ", ".join(
            f"{k}={v}" for k, v in sorted(rep["decision_counts"].items())))
    if rep["flight_records"]:
        lines.append(f"  flight: {rep['flight_records']} records, last "
                     f"losses {rep['last_losses']}")
    sel = rep.get("last_path_select")
    if sel:
        lines.append(f"  last path select: {sel.get('backend') or sel}")
    if rep["traceback_tail"]:
        lines.append("  traceback (tail):")
        for tline in rep["traceback_tail"]:
            lines.append(f"    {tline}")
    return "\n".join(lines)


def _bar(value: float, peak: float, width: int = 40) -> str:
    n = int(round(width * value / peak)) if peak > 0 else 0
    return "#" * max(n, 1 if value > 0 else 0)


def render_text(s: dict) -> str:
    lines = [f"records: {s['records']}  flight steps: {s['flight_steps']}"]
    imb = s["imbalance"]
    if imb["experts"]:
        lines.append("")
        lines.append(f"expert load histogram ({imb['experts']} experts, "
                     f"{imb['total_assignments']:g} assignments, "
                     f"imbalance max/mean = {imb['imbalance']}):")
        peak = max(imb["expert_load"])
        for i, v in enumerate(imb["expert_load"]):
            lines.append(f"  e{i:<3d} {v:>10.1f} {_bar(v, peak)}")
        if imb["mean_router_entropy"] is not None:
            lines.append(f"  mean router entropy: "
                         f"{imb['mean_router_entropy']} nats")
    drops = s["drops"]
    if drops["steps"]:
        lines.append("")
        lines.append(f"drop rate: mean {drops['mean_dropped_fraction']} "
                     f"max {drops['max_dropped_fraction']} over "
                     f"{drops['steps']} steps")
        for t in drops["timeline"][-10:]:
            lines.append(f"  step {t['step']}: dropped "
                         f"{t['dropped_fraction']}  capacity util "
                         f"{t['capacity_utilization']}")
    deg = s.get("degradation", {})
    if deg.get("steps_with_masking"):
        lines.append("")
        lines.append(f"tier-0 degradation: expert-health mask fired on "
                     f"{deg['steps_with_masking']} steps (max "
                     f"{deg['max_masked_experts']:g} masked experts)")
        for t in deg["timeline"][-10:]:
            lines.append(f"  step {t['step']}: masked "
                         f"{t['masked_experts']:g} experts, fraction "
                         f"{t['masked_fraction']}")
    wire = s.get("wire", {})
    if wire.get("steps_with_wire"):
        lines.append("")
        lines.append(f"wire compression: active on "
                     f"{wire['steps_with_wire']} layer-steps, round-trip "
                     f"quantization error mean {wire['mean_rtq_error']} "
                     f"max {wire['max_rtq_error']}")
    quant = s.get("quant", {})
    if quant.get("steps_with_quant"):
        lines.append("")
        lines.append(f"quantized experts: active on "
                     f"{quant['steps_with_quant']} layer-steps, "
                     f"weight round-trip error mean "
                     f"{quant['mean_quant_error']} max "
                     f"{quant['max_quant_error']}")
    res = s.get("resilience", {})
    if res.get("events"):
        lines.append("")
        lines.append("resilience events: " + ", ".join(
            f"{k}={v}" for k, v in res["events"].items()))
        for dr in res["drains"][-5:]:
            lines.append(
                f"  drain at step {dr['step']} ({dr['source']}), "
                f"{dr['remaining_grace_s']:.1f}s grace left"
                if isinstance(dr.get("remaining_grace_s"), float)
                else f"  drain at step {dr['step']} ({dr['source']})")
        for r in res["resumes"][-5:]:
            lines.append(f"  resume #{r['incarnation']} at step "
                         f"{r['step']}: world={r['world']} "
                         f"(ep={r['ep']} x dp={r['dp']})")
    adapt = s.get("adaptation", {})
    if adapt.get("actions"):
        lines.append("")
        lines.append("self-healing controller: " + ", ".join(
            f"{k.split('.', 1)[1]}={v}"
            for k, v in sorted(adapt["actions"].items())))
        for t in adapt["timeline"]:
            kind = str(t["decision"]).split(".", 1)[1]
            head = f"  step {t.get('step')}: {kind}"
            if kind == "morph":
                head += (f" -> {t.get('backend')}"
                         f"{' (dropless)' if t.get('dropless') else ''}")
            elif kind == "replace":
                reps = t.get("replicas") or []
                head += (f" (replicas {reps})" if reps
                         else " (permutation only)")
            elif kind == "demotion_reset":
                head += f" dropped={t.get('dropped')}"
            lines.append(head)
            b, a = t.get("before"), t.get("after")
            if b and a:
                lines.append(
                    f"    imbalance {b['imbalance']} -> "
                    f"{a['imbalance']}, dropped "
                    f"{b['dropped_fraction']} -> "
                    f"{a['dropped_fraction']}")
    if s["phases"]:
        lines.append("")
        lines.append("phase times (mean):")
        for k, v in s["phases"].items():
            lines.append(f"  {k:<32s} {v:>10.3f}")
    drift = s["drift"]
    if drift["n"]:
        lines.append("")
        lines.append(f"planner drift: {drift['n']} comparisons, "
                     f"{drift['exceeded']} over threshold")
        for key, b in drift["by_path"].items():
            lines.append(
                f"  {key:<24s} n={b['n']} mean|rel|="
                f"{b['mean_abs_rel_error']} worst={b['worst_rel_error']}"
                f"{'  ** DRIFTING' if b['exceeded'] else ''}")
    if s["decisions"]:
        lines.append("")
        lines.append("decision records: " + ", ".join(s["decisions"]))
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m flashmoe_tpu.observe",
        description="Summarize flight-recorder / telemetry JSONL dumps")
    ap.add_argument("files", nargs="*", help="JSONL files to analyze")
    ap.add_argument("--json", action="store_true",
                    help="emit one machine-readable JSON document")
    ap.add_argument("--ledger", action="store_true",
                    help="render the per-phase cost-ledger report "
                         "(ledger.jsonl / phase_drift decision files)")
    ap.add_argument("--serving", action="store_true",
                    help="render the serving report (engine "
                         "flight/decision dumps: TTFT/TPOT, queue "
                         "depth, cache occupancy, planner split)")
    ap.add_argument("--postmortem", metavar="DIR",
                    help="render a triage report of the crash postmortem "
                         "bundle(s) under DIR")
    ap.add_argument("--trace", type=int, metavar="RID", default=None,
                    help="render one request's end-to-end timeline "
                         "(queue wait, prefill, decode, eviction gaps) "
                         "from serve_trace_span JSONL records")
    ap.add_argument("--merge", action="store_true",
                    help="fleet view: treat each input file as one "
                         "host's telemetry shard and summarize the "
                         "union (telemetry.<host>.jsonl); handoffs "
                         "witnessed by both pools collapse to one")
    ap.add_argument("--attribution", action="store_true",
                    help="per-request critical-path attribution from "
                         "serve_trace_span records: where each retired "
                         "request's latency went (queue wait, router "
                         "spill, prefill, handoff DCN, decode, "
                         "eviction gaps) and the fleet rollup")
    ap.add_argument("--regression", action="store_true",
                    help="perf sentry: compare the newest run in the "
                         "history file (default obs/history.jsonl) "
                         "against the rolling baseline")
    ap.add_argument("--ci", action="store_true",
                    help="with --regression: exit rc 2 when any metric "
                         "regressed (regress.detected decisions)")
    args = ap.parse_args(argv)

    modes = [m for m, on in (("--ledger", args.ledger),
                             ("--serving", args.serving),
                             ("--postmortem", bool(args.postmortem)),
                             ("--trace", args.trace is not None),
                             ("--merge", args.merge),
                             ("--attribution", args.attribution),
                             ("--regression", args.regression)) if on]
    if len(modes) > 1:
        ap.error(f"pick one mode: {' '.join(modes)}")
    if args.ci and not args.regression:
        ap.error("--ci only applies with --regression")

    if args.regression:
        from flashmoe_tpu.telemetry_plane import regression as reg

        path = args.files[0] if args.files else reg.DEFAULT_HISTORY
        runs = reg.load_history(path)
        if not runs:
            print(f"no run history at {path!r} (append one with "
                  f"`bench.py --regression` or "
                  f"regression.append_run)", file=sys.stderr)
            return 2
        report = reg.check_regression(runs)
        report["history"] = path
        if args.json:
            json.dump(report, sys.stdout)
            print()
        else:
            print(reg.render_text(report))
        if args.ci and report["regressions"]:
            return 2
        return 0

    if args.postmortem:
        from flashmoe_tpu.profiler import postmortem as pm

        bundles = pm.find_bundles(args.postmortem)
        if not bundles:
            print(f"no postmortem bundles under {args.postmortem!r}",
                  file=sys.stderr)
            return 2
        reports = [postmortem_report(pm.load_bundle(b)) for b in bundles]
        if args.json:
            json.dump({"bundles": reports}, sys.stdout)
            print()
        else:
            print("\n\n".join(render_postmortem_text(r) for r in reports))
        return 0

    if not args.files:
        ap.error("JSONL files required (or use --postmortem DIR / "
                 "--regression)")
    if args.merge:
        rep = merge_report(args.files)
        if args.json:
            json.dump(rep, sys.stdout)
            print()
        else:
            print(render_merge_text(rep))
        return 0 if rep["records"] else 2
    records = load_jsonl(args.files)
    if not records:
        print("no parseable records found", file=sys.stderr)
        return 2
    if args.trace is not None:
        rep = trace_report(records, args.trace)
        if args.json:
            json.dump(rep, sys.stdout)
            print()
        else:
            print(render_trace_text(rep))
        return 0 if rep["found"] else 2
    if args.attribution:
        from flashmoe_tpu.telemetry_plane.attribution import (
            attribution_report,
        )

        rep = attribution_report(records)
        if args.json:
            json.dump(rep, sys.stdout)
            print()
        else:
            print(render_attribution_text(rep))
        return 0 if rep["requests"] else 2
    if args.ledger:
        led = ledger_report(records)
        if args.json:
            json.dump(led, sys.stdout)
            print()
        else:
            print(render_ledger_text(led))
        return 0 if led["n"] or led["overlap"] else 2
    if args.serving:
        rep = serving_report(records)
        if args.json:
            json.dump(rep, sys.stdout)
            print()
        else:
            print(render_serving_text(rep))
        return 0 if rep["steps"] or rep["requests_completed"] else 2
    s = summarize(records)
    if args.json:
        json.dump(s, sys.stdout)
        print()
    else:
        print(render_text(s))
    return 0


if __name__ == "__main__":
    sys.exit(main())
