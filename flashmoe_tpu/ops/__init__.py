"""Core MoE ops: gate, dispatch/combine, grouped expert FFN, fused layer."""
