"""Attention kernels: Pallas flash attention + XLA reference.

The reference has no attention anywhere (SURVEY §2.6) — sequence length
only sizes its token batch.  A complete framework needs the full model, and
long-context support is first-class here: this module provides the
single-chip blockwise (flash) attention kernel whose online-softmax
accumulator is also the building block of the ring attention in
:mod:`flashmoe_tpu.parallel.ringattn` (same math, kv blocks arriving over
ICI instead of from HBM).

Layouts: q/k/v are [B, N, T, D] (batch, heads, time, head_dim); GQA is
handled by the caller repeating kv heads (cheap view under XLA).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def attention_xla(q, k, v, *, causal: bool = True, q_offset: int | jax.Array = 0,
                  kv_offset: int | jax.Array = 0, scale: float | None = None):
    """Plain XLA attention (oracle). q: [B, N, Tq, D], k/v: [B, N, Tk, D].

    ``q_offset``/``kv_offset`` are the global positions of the first row /
    column — needed when the caller holds sequence shards (ring/SP)."""
    d = q.shape[-1]
    scale = scale if scale is not None else d ** -0.5
    logits = jnp.einsum(
        "bntd,bnsd->bnts", q, k, preferred_element_type=jnp.float32
    ) * scale
    if causal:
        tq, tk = q.shape[2], k.shape[2]
        qi = jnp.arange(tq)[:, None] + q_offset
        ki = jnp.arange(tk)[None, :] + kv_offset
        logits = jnp.where((qi >= ki)[None, None], logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum(
        "bnts,bnsd->bntd", probs.astype(v.dtype), v,
        preferred_element_type=jnp.float32,
    ).astype(q.dtype)


# ----------------------------------------------------------------------
# Flash attention kernel
# ----------------------------------------------------------------------

def _flash_kernel(q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr, *,
                  scale, causal, block_q, block_k):
    """Grid: (B*N, Tq/block_q, Tk/block_k) — kv innermost, accumulating the
    online softmax in VMEM scratch."""
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    q_start = qi * block_q
    k_start = ki * block_k
    # skip fully-masked kv blocks (strictly above the diagonal); m/l scratch
    # is lane-width (bq, 128) holding broadcast copies to keep TPU layouts
    # happy, like the upstream flash kernels
    run = (
        k_start <= q_start + block_q - 1 if causal else jnp.bool_(True)
    )

    @pl.when(run)
    def _():
        q = q_ref[0].astype(jnp.float32)  # [bq, d]
        k = k_ref[0].astype(jnp.float32)  # [bk, d]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # [bq, bk]
        if causal:
            rows = jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0) + q_start
            cols = jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1) + k_start
            s = jnp.where(rows >= cols, s, NEG_INF)
        m_prev = m_scr[:, :1]                   # [bq, 1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)                  # [bq, bk]
        alpha = jnp.exp(m_prev - m_new)         # [bq, 1]
        l_new = l_scr[:, :1] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)

    @pl.when(ki == nk - 1)
    def _():
        o_ref[0] = (
            acc_scr[:] / jnp.maximum(l_scr[:, :1], 1e-30)
        ).astype(o_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=("causal", "block_q", "block_k", "interpret", "scale"),
)
def flash_attention(q, k, v, *, causal: bool = True, scale: float | None = None,
                    block_q: int = 128, block_k: int = 128,
                    interpret: bool = False):
    """Blockwise attention. q/k/v: [B, N, T, D] with T % block == 0."""
    b, n, tq, d = q.shape
    tk = k.shape[2]
    scale = scale if scale is not None else d ** -0.5
    bq = min(block_q, tq)
    bk = min(block_k, tk)
    if tq % bq or tk % bk:
        raise ValueError(f"T ({tq},{tk}) must divide blocks ({bq},{bk})")

    qf = q.reshape(b * n, tq, d)
    kf = k.reshape(b * n, tk, d)
    vf = v.reshape(b * n, tk, d)
    grid = (b * n, tq // bq, tk // bk)
    out = pl.pallas_call(
        functools.partial(
            _flash_kernel, scale=scale, causal=causal,
            block_q=bq, block_k=bk,
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda h, i, j: (h, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bk, d), lambda h, i, j: (h, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bk, d), lambda h, i, j: (h, j, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, bq, d), lambda h, i, j: (h, i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((b * n, tq, d), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((bq, 128), jnp.float32),
            pltpu.VMEM((bq, 128), jnp.float32),
            pltpu.VMEM((bq, d), jnp.float32),
        ],
        interpret=interpret,
    )(qf, kf, vf)
    return out.reshape(b, n, tq, d)
