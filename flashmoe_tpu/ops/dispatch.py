"""Token dispatch (permute-to-experts) and combine (weighted un-permute).

TPU-native re-design of the reference's packet layer
(``csrc/include/flashmoe/os/packet.cuh:20-286``): there, super-blocks of CUDA
blocks gather each expert's routed tokens out of the gate's ``tokenIds``
compaction and copy them into per-peer symmetric-heap cells, and the combine
stage (``processor.cuh`` ``combine``, ``:27-205``) scatter-adds weighted
expert outputs back to token order, dividing by the accumulated top-k weight
sum.

Under XLA we express the same movement as static-shape scatter/gather over a
capacity-padded ``[E, C, H]`` dispatch buffer (the reference's ``EC``/``pEC``
expert-capacity concept, ``types.cuh:497-499``):

  * positions within an expert come from a cumulative-sum rank over the
    (k-major, token-minor) flattening — identical priority order to GShard:
    all k=0 assignments beat k=1 assignments, ties broken by token index.
  * tokens whose position exceeds capacity are dropped iff
    ``cfg.drop_tokens`` (the reference's min(eC, EC) clamp,
    ``packet.cuh:99-206``); with ``drop_tokens=False`` capacity is S so
    nothing ever drops.
  * combine gathers each token's k expert outputs and forms the weighted sum
    (weights pre-normalized by the router), replacing the reference's
    nondeterministic atomicAdd combine with a deterministic gather — same
    math, reproducible accumulation order.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from flashmoe_tpu.config import MoEConfig


class DispatchPlan(NamedTuple):
    """Routing geometry for one token shard.

    expert_idx: [S, K] selected expert per (token, slot).
    position:   [S, K] slot within the expert's capacity buffer.
    valid:      [S, K] bool; False when dropped (over capacity).
    counts:     [E] number of selections per expert (pre-drop).
    tok_sorted: [S*K] token id per expert-sorted assignment (k-major
                priority order) — the sort is computed once here and
                reused by :func:`dispatch_indices`.
    """

    expert_idx: jax.Array
    position: jax.Array
    valid: jax.Array
    counts: jax.Array
    tok_sorted: jax.Array


def make_plan(expert_idx, cfg: MoEConfig, capacity: int) -> DispatchPlan:
    """Compute per-(token, k) capacity positions.

    expert_idx: [S, K] int32.  Sort-based ranking: ONE stable argsort over
    the [K*S] expert ids (k-major flattening, so priority order matches
    GShard: all k=0 assignments beat k=1, ties by token index) yields both
    the per-assignment rank (via the inverse permutation) and the
    expert-sorted token order that :func:`dispatch_indices` consumes.
    This replaces a [K*S, E] one-hot cumsum — O(S*K*E) integer traffic
    with a long-axis scan — with two O(S*K log S*K) sorts, the cheaper
    form on the VPU at MoE scale.
    """
    s, k = expert_idx.shape
    e = cfg.num_experts
    ef = expert_idx.T.reshape(-1)  # k-major flattening: index = kk*S + ss
    order = jnp.argsort(ef, stable=True)
    inv = jnp.argsort(order)  # rank of each assignment in the sorted run
    # counts from the sorted run boundaries — no [S*K, E] one-hot
    starts = jnp.searchsorted(ef[order], jnp.arange(e, dtype=ef.dtype),
                              side="left").astype(jnp.int32)
    ends = jnp.concatenate(
        [starts[1:], jnp.full((1,), s * k, jnp.int32)]
    )
    counts = ends - starts
    pos = (inv.astype(jnp.int32) - starts[ef]).reshape(k, s).T  # [S, K]
    tok_sorted = (order % s).astype(jnp.int32)
    # positions past capacity are ALWAYS invalid — with drop_tokens=False the
    # caller must size capacity >= max count (capacity_for does), so nothing
    # clamps; an undersized capacity then degrades to drops instead of
    # silently scattering into the next expert's buffer region.
    valid = pos < capacity
    return DispatchPlan(expert_idx, pos, valid, counts, tok_sorted)


def dispatch_indices(plan: DispatchPlan, cfg: MoEConfig, capacity: int):
    """Source-token index per expert-capacity slot.

    Returns ``(src_tok, present)``, both ``[E, capacity]``: ``src_tok`` is
    the token id feeding each slot (slots past an expert's count point at
    token 0 and are never read back by :func:`combine`), ``present`` marks
    populated slots.  Reads the expert-sorted token order computed once by
    :func:`make_plan`'s argsort: the c-th entry of expert e's sorted run
    is exactly the selection with position c.  This index plane is what
    the gather-fused FFN kernel consumes to build expert slabs from token
    rows on the fly — the analogue of the reference's super-blocks
    gathering from ``tokenIds`` (``packet.cuh:99-206``).
    """
    s, k = plan.expert_idx.shape
    tok_sorted = plan.tok_sorted
    offsets = jnp.cumsum(plan.counts) - plan.counts  # [E] exclusive
    slot = offsets[:, None] + jnp.arange(capacity, dtype=jnp.int32)[None, :]
    present = jnp.arange(capacity, dtype=jnp.int32)[None, :] < \
        plan.counts[:, None]
    src_tok = tok_sorted[jnp.clip(slot, 0, s * k - 1)]  # [E, C]
    src_tok = jnp.where(present, src_tok, 0)
    return src_tok, present


def dispatch(x, plan: DispatchPlan, cfg: MoEConfig, capacity: int):
    """Gather tokens into the per-expert capacity buffer.

    x: [S, H] -> [E, C, H].  Dropped/empty slots are zero (so the expert
    GEMM over them contributes nothing after combine masks them out).

    Formulated as sort + row-GATHER rather than a row-scatter: an H-wide
    scatter serializes on TPU, while the :func:`dispatch_indices` argsort
    followed by one [E*C]-row dynamic gather runs at HBM bandwidth.
    """
    src_tok, present = dispatch_indices(plan, cfg, capacity)
    buf = jnp.where(present[..., None], x[src_tok], 0)
    return buf.astype(x.dtype)


def sorted_return_maps(plan: DispatchPlan, combine_weights, cfg: MoEConfig,
                       capacity: int, rows_pad: int):
    """Token-sorted return placement for the in-kernel (fused) combine.

    The round-4 in-kernel combine scatter-accumulated returned rows one at
    a time (S*K sequential VPU adds — estimated as expensive as the whole
    layer, VERDICT r4 weak #3).  The restructure pre-sorts XLA-side: every
    occupied slab slot (token ``t``, top-k slot ``j``) is assigned the row
    ``t*k + j`` of a token-sorted return buffer, so the kernel's returning
    RDMAs land contributions in contiguous per-token runs and the combine
    becomes a fully vectorized segment-sum over ``k``-row segments — the
    deterministic TPU form of the reference's combine stage
    (``csrc/include/flashmoe/os/processor/processor.cuh:27-205``), with
    the atomicAdd replaced by disjoint pre-assigned rows.

    Returns ``(ret_pos, w_sorted)``:
      ret_pos  [E, capacity] i32 — sorted-buffer row for each slab slot
               (0 for slots that are empty/dropped; such slots are never
               sent, so the value is never consumed).
      w_sorted [rows_pad] f32 — renormalized combine weight per sorted
               row; 0.0 for rows whose (token, j) assignment was dropped
               and for the rows_pad padding tail.  Differentiable w.r.t.
               ``combine_weights`` (the scatter transposes to a gather),
               which is how router gradients flow on this path.
    """
    s, k = plan.expert_idx.shape
    e = cfg.num_experts
    w = jnp.where(plan.valid, combine_weights, 0.0).astype(jnp.float32)
    denom = jnp.sum(w, axis=-1, keepdims=True)
    w = w / jnp.maximum(denom, 1e-20)
    # sorted-buffer row of each (token, j) assignment
    pos = (jnp.arange(s, dtype=jnp.int32)[:, None] * k
           + jnp.arange(k, dtype=jnp.int32)[None, :])      # [S, K]
    flat_slot = jnp.where(
        plan.valid,
        plan.expert_idx * capacity + plan.position,
        e * capacity,                                      # trash slot
    ).reshape(-1)
    ret_pos = (
        jnp.zeros(e * capacity + 1, jnp.int32)
        .at[flat_slot].set(pos.reshape(-1))
    )[: e * capacity].reshape(e, capacity)
    w_sorted = (
        jnp.zeros(rows_pad, jnp.float32)
        .at[pos.reshape(-1)].set(jnp.where(plan.valid, w, 0.0).reshape(-1))
    )
    return ret_pos, w_sorted


def combine(expert_out, plan: DispatchPlan, combine_weights, cfg: MoEConfig,
            capacity: int):
    """Weighted un-permute: [E, C, H] -> [S, H].

    combine_weights: [S, K] normalized router weights.  Deterministic
    replacement for the reference's atomicAdd combine
    (``processor.cuh:27-205``).
    """
    e, c, h = expert_out.shape
    s, k = plan.expert_idx.shape
    flat = jnp.where(
        plan.valid,
        plan.expert_idx * capacity + plan.position,
        0,
    ).reshape(-1)
    gathered = expert_out.reshape(e * c, h)[flat].reshape(s, k, h)
    # dropped slots read flat index 0, which may be UNWRITTEN buffer memory
    # (the count-aware fused kernel skips empty tiles entirely) — zero the
    # values, not just the weights, or NaN garbage * 0.0 = NaN propagates
    gathered = jnp.where(plan.valid[..., None], gathered, 0)
    w = jnp.where(plan.valid, combine_weights, 0.0).astype(jnp.float32)
    # renormalize over surviving slots so dropped tokens keep unit weight
    # across their remaining experts (matches reference 1/sum(w) scaling).
    denom = jnp.sum(w, axis=-1, keepdims=True)
    w = w / jnp.maximum(denom, 1e-20)
    out = jnp.einsum(
        "skh,sk->sh", gathered.astype(jnp.float32), w,
        preferred_element_type=jnp.float32,
    )
    return out
