"""Grouped expert FFN: up-GEMM -> (+bias) -> activation -> down-GEMM -> (+bias).

TPU-native re-design of the reference's expert pipeline: there, the fused
kernel's processors run tile-level ``preGEMM``/``postGEMM`` Tasks through the
``fGET`` fused GEMM+bias+activation (``csrc/include/flashmoe/os/processor/
processor.cuh:339-468``), with an in-kernel scheduler feeding tiles as packets
arrive, and a standalone two-GEMM ``expert`` kernel used for throughput probes
(``csrc/include/flashmoe/moe/expert.cuh:194-372``).

On TPU the scheduler's job — keeping the matrix units fed while tiles stream
— is done by the Pallas grid pipeline: the grid is (row-tile, intermediate-
chunk); weights for each chunk are DMA'd HBM->VMEM by the pipeline while the
previous chunk computes on the MXU, and a float32 VMEM accumulator carries
the down-projection partial sums across chunks.  Group (=expert) selection is
data-dependent, handled megablox-style with a scalar-prefetched per-row-tile
group id that the BlockSpec index maps consume — so each row tile streams
exactly its own expert's weights, and skewed expert loads never waste MXU
steps on padding rows of other experts.

Two implementations with identical semantics:
  * :func:`expert_ffn_dense` — batched einsum over [E, C, H] (XLA path).
  * :func:`grouped_ffn`      — the Pallas kernel over row-sorted tokens.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from flashmoe_tpu.config import BLOCK_M, MoEConfig
from flashmoe_tpu.models.reference import activation_fn


# ----------------------------------------------------------------------
# XLA path: batched over the capacity buffer
# ----------------------------------------------------------------------

def expert_ffn_dense(xs, params, cfg: MoEConfig):
    """Batched per-expert FFN on the capacity buffer.

    xs: [E, C, H] -> [E, C, H].  XLA maps the batched matmuls straight onto
    the MXU; activation/bias fuse into the GEMM epilogues automatically.
    """
    act = activation_fn(cfg.hidden_act)
    up = jnp.einsum(
        "ech,ehi->eci", xs, params["w_up"].astype(xs.dtype),
        preferred_element_type=cfg.accum_dtype,
    ) + params["b_up"][:, None, :].astype(cfg.accum_dtype)
    if cfg.gated_ffn:
        g = jnp.einsum(
            "ech,ehi->eci", xs, params["w_gate"].astype(xs.dtype),
            preferred_element_type=cfg.accum_dtype,
        )
        hidden = act(g) * up
    else:
        hidden = act(up)
    down = jnp.einsum(
        "eci,eih->ech", hidden.astype(xs.dtype),
        params["w_down"].astype(xs.dtype),
        preferred_element_type=cfg.accum_dtype,
    ) + params["b_down"][:, None, :].astype(cfg.accum_dtype)
    return down.astype(xs.dtype)


# ----------------------------------------------------------------------
# Pallas grouped kernel
# ----------------------------------------------------------------------

def _ffn_kernel(gid_ref, x_ref, wup_ref, bup_ref, wdn_ref, bdn_ref, out_ref,
                acc_ref, *, act_name, gated):
    """One (row-tile, I-chunk) grid step.

    When ``gated`` the up-weight block holds [w_gate; w_up] stacked on a
    doubled chunk axis (see :func:`grouped_ffn`).
    """
    j = pl.program_id(1)
    nj = pl.num_programs(1)
    act = activation_fn(act_name)

    @pl.when(j == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    x = x_ref[:]
    if gated:
        half = wup_ref.shape[2] // 2
        g = jnp.dot(x, wup_ref[0, :, :half], preferred_element_type=jnp.float32)
        up = jnp.dot(x, wup_ref[0, :, half:], preferred_element_type=jnp.float32)
        up = up + bup_ref[0, 0, :].astype(jnp.float32)
        hidden = act(g) * up
    else:
        up = jnp.dot(x, wup_ref[0], preferred_element_type=jnp.float32)
        hidden = act(up + bup_ref[0, 0, :].astype(jnp.float32))
    acc_ref[:] += jnp.dot(
        hidden.astype(x.dtype), wdn_ref[0], preferred_element_type=jnp.float32
    )

    @pl.when(j == nj - 1)
    def _():
        out_ref[:] = (
            acc_ref[:] + bdn_ref[0, 0, :].astype(jnp.float32)
        ).astype(out_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("act_name", "gated", "block_m", "block_i",
                              "interpret"),
)
def grouped_ffn(x, tile_gid, w_up, b_up, w_down, b_down, w_gate=None, *,
                act_name: str, gated: bool = False, block_m: int = BLOCK_M,
                block_i: int = 512, interpret: bool = False):
    """Grouped FFN over row-sorted tokens.

    x:        [T, H] tokens, grouped so rows of one row-tile share an expert.
    tile_gid: [T // block_m] int32 expert id owning each row tile.
    w_up:     [E, H, I]; b_up: [E, I]; w_down: [E, I, H]; b_down: [E, H];
    w_gate:   [E, H, I] for SwiGLU-style experts.

    Returns [T, H].  The scalar-prefetched ``tile_gid`` drives the weight
    BlockSpec index maps, so each row tile DMAs only its own expert's weight
    chunks (megablox-style block-sparse grouped GEMM).
    """
    t, h = x.shape
    e, _, i = w_up.shape
    if t % block_m:
        raise ValueError(f"rows {t} must be a multiple of block_m={block_m}")
    bi = min(block_i, i)
    if i % bi:
        raise ValueError(f"intermediate {i} must be a multiple of {bi}")
    nt, nj = t // block_m, i // bi

    if gated:
        if w_gate is None:
            raise ValueError("gated_ffn requires w_gate")
        # interleave per-chunk: [E, H, 2*I] as chunk-major [gate_chunk|up_chunk]
        wg = w_gate.reshape(e, h, nj, bi)
        wu = w_up.reshape(e, h, nj, bi)
        w_up_eff = jnp.concatenate([wg, wu], axis=-1).reshape(e, h, nj * 2 * bi)
        up_block = (1, h, 2 * bi)
        up_map = lambda ti, j, gid: (gid[ti], 0, j)
    else:
        w_up_eff = w_up
        up_block = (1, h, bi)
        up_map = lambda ti, j, gid: (gid[ti], 0, j)

    # biases are lifted to [E, 1, dim] so their (1, dim) trailing block shape
    # satisfies the TPU (8, 128) tiling rule via the equal-dimension escape
    b_up3 = b_up.reshape(e, 1, i)
    b_down3 = b_down.reshape(e, 1, h)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(nt, nj),
        in_specs=[
            pl.BlockSpec((block_m, h), lambda ti, j, gid: (ti, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(up_block, up_map, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, bi), lambda ti, j, gid: (gid[ti], 0, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bi, h), lambda ti, j, gid: (gid[ti], j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, h), lambda ti, j, gid: (gid[ti], 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((block_m, h), lambda ti, j, gid: (ti, 0),
                               memory_space=pltpu.VMEM),
        scratch_shapes=[pltpu.VMEM((block_m, h), jnp.float32)],
    )
    flops = 2 * t * h * i * (3 if gated else 2)
    return pl.pallas_call(
        functools.partial(_ffn_kernel, act_name=act_name, gated=gated),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((t, h), x.dtype),
        cost_estimate=pl.CostEstimate(
            flops=flops,
            bytes_accessed=x.size * x.dtype.itemsize
            + w_up_eff.size * w_up_eff.dtype.itemsize
            + w_down.size * w_down.dtype.itemsize,
            transcendentals=t * i,
        ),
        interpret=interpret,
    )(tile_gid, x, w_up_eff, b_up3, w_down, b_down3)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def capacity_buffer_ffn_ad(xs, params, cfg: MoEConfig,
                           interpret: bool = False):
    """Differentiable wrapper over the grouped kernel on [E, C, H]:
    Pallas forward, backward recomputed through the batched XLA FFN
    (pallas_call has no autodiff rule)."""
    return capacity_buffer_ffn_pallas(xs, params, cfg, interpret=interpret)


def _cap_ffn_fwd(xs, params, cfg, interpret):
    return capacity_buffer_ffn_pallas(xs, params, cfg,
                                      interpret=interpret), (xs, params)


def _cap_ffn_bwd(cfg, interpret, res, ct):
    xs, params = res
    _, vjp_fn = jax.vjp(
        lambda xx, p: expert_ffn_dense(xx, p, cfg), xs, params
    )
    return vjp_fn(ct)


capacity_buffer_ffn_ad.defvjp(_cap_ffn_fwd, _cap_ffn_bwd)


def capacity_buffer_ffn_pallas(xs, params, cfg: MoEConfig, *,
                               interpret: bool = False):
    """Run the grouped kernel on an [E, C, H] capacity buffer.

    The capacity buffer is already expert-major, so tile group ids are just
    ``expert_of_tile = tile_index // (C / block_m)`` — no sort needed.  C is
    padded up to a block multiple; pad rows compute garbage that combine
    never reads.
    """
    e, c, h = xs.shape
    # Row tile sized to cover the whole per-expert capacity when it fits
    # (<= 512 rows): each expert's weights then stream through VMEM exactly
    # once.  Smaller capacities round up to the sublane multiple; larger
    # ones tile at 512 (weights re-fetched once per 512 rows).
    if c <= 512:
        bm = ((c + 7) // 8) * 8
    else:
        bm = next(b for b in (512, 256, 128) if c % b == 0) if any(
            c % b == 0 for b in (512, 256, 128)
        ) else 512
    cp = ((c + bm - 1) // bm) * bm
    if cp != c:
        xs = jnp.pad(xs, ((0, 0), (0, cp - c), (0, 0)))
    x = xs.reshape(e * cp, h)
    tiles_per_e = cp // bm
    tile_gid = (
        jnp.arange(e * tiles_per_e, dtype=jnp.int32) // tiles_per_e
    )
    # keep the chunked weight working set within VMEM alongside the row tile
    block_i = 512 if bm <= 256 else 256
    out = grouped_ffn(
        x, tile_gid, params["w_up"].astype(x.dtype),
        params["b_up"], params["w_down"].astype(x.dtype), params["b_down"],
        params.get("w_gate", None) if cfg.gated_ffn else None,
        act_name=cfg.hidden_act, gated=cfg.gated_ffn, block_m=bm,
        block_i=block_i, interpret=interpret,
    )
    return out.reshape(e, cp, h)[:, :c, :]
