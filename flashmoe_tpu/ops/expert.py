"""Grouped expert FFN: up-GEMM -> (+bias) -> activation -> down-GEMM -> (+bias).

TPU-native re-design of the reference's expert pipeline: there, the fused
kernel's processors run tile-level ``preGEMM``/``postGEMM`` Tasks through the
``fGET`` fused GEMM+bias+activation (``csrc/include/flashmoe/os/processor/
processor.cuh:339-468``), with an in-kernel scheduler feeding tiles as packets
arrive, and a standalone two-GEMM ``expert`` kernel used for throughput probes
(``csrc/include/flashmoe/moe/expert.cuh:194-372``).

On TPU the scheduler's job — keeping the matrix units fed while tiles stream
— is done by the Pallas grid pipeline: the grid is (row-tile, intermediate-
chunk); weights for each chunk are DMA'd HBM->VMEM by the pipeline while the
previous chunk computes on the MXU, and a float32 VMEM accumulator carries
the down-projection partial sums across chunks.  Group (=expert) selection is
data-dependent, handled megablox-style with a scalar-prefetched per-row-tile
group id that the BlockSpec index maps consume — so each row tile streams
exactly its own expert's weights, and skewed expert loads never waste MXU
steps on padding rows of other experts.

Two implementations with identical semantics:
  * :func:`expert_ffn_dense` — batched einsum over [E, C, H] (XLA path).
  * :func:`grouped_ffn`      — the Pallas kernel over row-sorted tokens.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from flashmoe_tpu.config import BLOCK_M, MoEConfig
from flashmoe_tpu.models.reference import activation_fn

# default intermediate-dimension chunk for the grouped kernels (VMEM
# working-set sizing); call sites share this instead of bare literals
DEFAULT_BLOCK_I = 512


# ----------------------------------------------------------------------
# XLA path: batched over the capacity buffer
# ----------------------------------------------------------------------

def expert_ffn_dense(xs, params, cfg: MoEConfig):
    """Batched per-expert FFN on the capacity buffer.

    xs: [E, C, H] -> [E, C, H].  XLA maps the batched matmuls straight onto
    the MXU; activation/bias fuse into the GEMM epilogues automatically.
    """
    act = activation_fn(cfg.hidden_act)
    up = jnp.einsum(
        "ech,ehi->eci", xs, params["w_up"].astype(xs.dtype),
        preferred_element_type=cfg.accum_dtype,
    ) + params["b_up"][:, None, :].astype(cfg.accum_dtype)
    if cfg.gated_ffn:
        g = jnp.einsum(
            "ech,ehi->eci", xs, params["w_gate"].astype(xs.dtype),
            preferred_element_type=cfg.accum_dtype,
        )
        hidden = act(g) * up
    else:
        hidden = act(up)
    down = jnp.einsum(
        "eci,eih->ech", hidden.astype(xs.dtype),
        params["w_down"].astype(xs.dtype),
        preferred_element_type=cfg.accum_dtype,
    ) + params["b_down"][:, None, :].astype(cfg.accum_dtype)
    return down.astype(xs.dtype)


# ----------------------------------------------------------------------
# Pallas grouped kernel
# ----------------------------------------------------------------------

def _ffn_kernel(gid_ref, x_ref, wup_ref, bup_ref, wdn_ref, bdn_ref, out_ref,
                acc_ref, *, act_name, gated):
    """One (row-tile, I-chunk) grid step.

    When ``gated`` the up-weight block holds [w_gate; w_up] stacked on a
    doubled chunk axis (see :func:`grouped_ffn`).
    """
    j = pl.program_id(1)
    nj = pl.num_programs(1)
    act = activation_fn(act_name)

    @pl.when(j == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    x = x_ref[:]
    if gated:
        half = wup_ref.shape[2] // 2
        g = jnp.dot(x, wup_ref[0, :, :half], preferred_element_type=jnp.float32)
        up = jnp.dot(x, wup_ref[0, :, half:], preferred_element_type=jnp.float32)
        up = up + bup_ref[0, 0, :].astype(jnp.float32)
        hidden = act(g) * up
    else:
        up = jnp.dot(x, wup_ref[0], preferred_element_type=jnp.float32)
        hidden = act(up + bup_ref[0, 0, :].astype(jnp.float32))
    acc_ref[:] += jnp.dot(
        hidden.astype(x.dtype), wdn_ref[0], preferred_element_type=jnp.float32
    )

    @pl.when(j == nj - 1)
    def _():
        out_ref[:] = (
            acc_ref[:] + bdn_ref[0, 0, :].astype(jnp.float32)
        ).astype(out_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("act_name", "gated", "block_m", "block_i",
                              "interpret"),
)
def grouped_ffn(x, tile_gid, w_up, b_up, w_down, b_down, w_gate=None, *,
                act_name: str, gated: bool = False, block_m: int = BLOCK_M,
                block_i: int = DEFAULT_BLOCK_I, interpret: bool = False):
    """Grouped FFN over row-sorted tokens.

    x:        [T, H] tokens, grouped so rows of one row-tile share an expert.
    tile_gid: [T // block_m] int32 expert id owning each row tile.
    w_up:     [E, H, I]; b_up: [E, I]; w_down: [E, I, H]; b_down: [E, H];
    w_gate:   [E, H, I] for SwiGLU-style experts.

    Returns [T, H].  The scalar-prefetched ``tile_gid`` drives the weight
    BlockSpec index maps, so each row tile DMAs only its own expert's weight
    chunks (megablox-style block-sparse grouped GEMM).
    """
    t, h = x.shape
    e, _, i = w_up.shape
    if t % block_m:
        raise ValueError(f"rows {t} must be a multiple of block_m={block_m}")
    bi = _auto_block(i, block_i)
    nt, nj = t // block_m, i // bi

    if gated:
        if w_gate is None:
            raise ValueError("gated_ffn requires w_gate")
        # interleave per-chunk: [E, H, 2*I] as chunk-major [gate_chunk|up_chunk]
        wg = w_gate.reshape(e, h, nj, bi)
        wu = w_up.reshape(e, h, nj, bi)
        w_up_eff = jnp.concatenate([wg, wu], axis=-1).reshape(e, h, nj * 2 * bi)
        up_block = (1, h, 2 * bi)
        up_map = lambda ti, j, gid: (gid[ti], 0, j)
    else:
        w_up_eff = w_up
        up_block = (1, h, bi)
        up_map = lambda ti, j, gid: (gid[ti], 0, j)

    # biases are lifted to [E, 1, dim] so their (1, dim) trailing block shape
    # satisfies the TPU (8, 128) tiling rule via the equal-dimension escape
    b_up3 = b_up.reshape(e, 1, i)
    b_down3 = b_down.reshape(e, 1, h)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(nt, nj),
        in_specs=[
            pl.BlockSpec((block_m, h), lambda ti, j, gid: (ti, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(up_block, up_map, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, bi), lambda ti, j, gid: (gid[ti], 0, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bi, h), lambda ti, j, gid: (gid[ti], j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, h), lambda ti, j, gid: (gid[ti], 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((block_m, h), lambda ti, j, gid: (ti, 0),
                               memory_space=pltpu.VMEM),
        scratch_shapes=[pltpu.VMEM((block_m, h), jnp.float32)],
    )
    flops = 2 * t * h * i * (3 if gated else 2)
    return pl.pallas_call(
        functools.partial(_ffn_kernel, act_name=act_name, gated=gated),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((t, h), x.dtype),
        cost_estimate=pl.CostEstimate(
            flops=flops,
            bytes_accessed=x.size * x.dtype.itemsize
            + w_up_eff.size * w_up_eff.dtype.itemsize
            + w_down.size * w_down.dtype.itemsize,
            transcendentals=t * i,
        ),
        interpret=interpret,
    )(tile_gid, x, w_up_eff, b_up3, w_down, b_down3)


# ----------------------------------------------------------------------
# Gather-fused grouped kernel: expert slabs built from token rows on the
# fly, never materializing the [E, C, H] dispatch buffer in HBM
# ----------------------------------------------------------------------

def _ffn_gather_kernel(gid_ref, tok_ref, x_ref, wup_ref, bup_ref, wdn_ref,
                       bdn_ref, out_ref, xtile, acc_ref, sems, *,
                       act_name, gated, block_m):
    """One (row-tile, I-chunk) grid step with in-kernel token gather.

    At each tile's first I-chunk the kernel issues per-row DMAs that pull
    the NEXT tile's token rows from ``x`` (HBM) into the alternate VMEM
    slab, then waits for the current tile's rows — the gather streams
    behind the previous tile's GEMMs exactly like the reference's packet
    stage building heap cells from ``tokenIds`` while processors compute
    (``packet.cuh:99-206``).
    """
    ti = pl.program_id(0)
    j = pl.program_id(1)
    nt = pl.num_programs(0)
    nj = pl.num_programs(1)
    act = activation_fn(act_name)

    def start_gather(tile, slot):
        def body(i, _):
            tok = tok_ref[tile * block_m + i]
            pltpu.make_async_copy(
                x_ref.at[pl.ds(tok, 1), :],
                xtile.at[slot, pl.ds(i, 1), :],
                sems.at[slot],
            ).start()
            return 0
        jax.lax.fori_loop(0, block_m, body, 0)

    def wait_gather(slot):
        # per-row waits mirror the per-row starts one-for-one, so the
        # semaphore balance is exact under either byte- or completion-
        # counting DMA semantics
        def body(i, _):
            pltpu.make_async_copy(
                x_ref.at[pl.ds(0, 1), :],
                xtile.at[slot, pl.ds(i, 1), :],
                sems.at[slot],
            ).wait()
            return 0
        jax.lax.fori_loop(0, block_m, body, 0)

    slot = jax.lax.rem(ti, 2)

    @pl.when(j == 0)
    def _():
        @pl.when(ti == 0)
        def _():
            start_gather(0, 0)

        @pl.when(ti + 1 < nt)
        def _():
            start_gather(ti + 1, jax.lax.rem(ti + 1, 2))

        wait_gather(slot)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    x = xtile[slot]
    if gated:
        half = wup_ref.shape[2] // 2
        g = jnp.dot(x, wup_ref[0, :, :half], preferred_element_type=jnp.float32)
        up = jnp.dot(x, wup_ref[0, :, half:], preferred_element_type=jnp.float32)
        up = up + bup_ref[0, 0, :].astype(jnp.float32)
        hidden = act(g) * up
    else:
        up = jnp.dot(x, wup_ref[0], preferred_element_type=jnp.float32)
        hidden = act(up + bup_ref[0, 0, :].astype(jnp.float32))
    acc_ref[:] += jnp.dot(
        hidden.astype(x.dtype), wdn_ref[0], preferred_element_type=jnp.float32
    )

    @pl.when(j == nj - 1)
    def _():
        out_ref[:] = (
            acc_ref[:] + bdn_ref[0, 0, :].astype(jnp.float32)
        ).astype(out_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("act_name", "gated", "block_m", "block_i",
                              "interpret"),
)
def grouped_ffn_tokens(x, src_tok, tile_gid, w_up, b_up, w_down, b_down,
                       w_gate=None, *, act_name: str, gated: bool = False,
                       block_m: int = BLOCK_M,
                       block_i: int = DEFAULT_BLOCK_I,
                       interpret: bool = False):
    """Grouped FFN reading token rows directly: the dispatch gather fused
    into the kernel (no [T, H] grouped buffer ever hits HBM).

    x:        [S, H] tokens in natural order (stays in HBM).
    src_tok:  [T] int32 source token per slab row (expert-grouped order).
    tile_gid: [T // block_m] int32 expert id owning each row tile.

    Returns [T, H] in slab order.  Rows whose slot is unpopulated compute
    on token 0's data; combine never reads them.  Forward-only: the
    training path keeps the explicit dispatch so the backward has its
    residuals (see :func:`grouped_ffn_ad`).
    """
    s, h = x.shape
    (t,) = src_tok.shape
    e, _, i = w_up.shape
    if t % block_m:
        raise ValueError(f"slab rows {t} must be a multiple of {block_m}")
    bi = _auto_block(i, block_i)
    nt, nj = t // block_m, i // bi

    if gated:
        if w_gate is None:
            raise ValueError("gated_ffn requires w_gate")
        wg = w_gate.reshape(e, h, nj, bi)
        wu = w_up.reshape(e, h, nj, bi)
        w_up_eff = jnp.concatenate([wg, wu], axis=-1).reshape(e, h, nj * 2 * bi)
        up_block = (1, h, 2 * bi)
    else:
        w_up_eff = w_up
        up_block = (1, h, bi)
    b_up3 = b_up.reshape(e, 1, i)
    b_down3 = b_down.reshape(e, 1, h)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(nt, nj),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),  # x: full [S, H] in HBM
            pl.BlockSpec(up_block, lambda ti, j, gid, tok: (gid[ti], 0, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, bi), lambda ti, j, gid, tok: (gid[ti], 0, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bi, h), lambda ti, j, gid, tok: (gid[ti], j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, h), lambda ti, j, gid, tok: (gid[ti], 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((block_m, h), lambda ti, j, gid, tok: (ti, 0),
                               memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((2, block_m, h), x.dtype),
            pltpu.VMEM((block_m, h), jnp.float32),
            pltpu.SemaphoreType.DMA((2,)),
        ],
    )
    flops = 2 * t * h * i * (3 if gated else 2)
    return pl.pallas_call(
        functools.partial(_ffn_gather_kernel, act_name=act_name, gated=gated,
                          block_m=block_m),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((t, h), x.dtype),
        cost_estimate=pl.CostEstimate(
            flops=flops,
            bytes_accessed=t * h * x.dtype.itemsize * 2
            + w_up_eff.size * w_up_eff.dtype.itemsize
            + w_down.size * w_down.dtype.itemsize,
            transcendentals=t * i,
        ),
        interpret=interpret,
    )(tile_gid, src_tok, x, w_up_eff, b_up3, w_down, b_down3)


@functools.partial(jax.custom_vjp, nondiff_argnums=(8, 9, 10, 11, 12))
def grouped_ffn_tokens_ad(x, src_tok, tile_gid, w_up, b_up, w_down, b_down,
                           w_gate, act_name, gated, block_m, block_i,
                           interpret):
    """Differentiable wrapper over :func:`grouped_ffn_tokens`.

    The forward is the cheap gather-fused kernel (no residuals written);
    under differentiation the backward re-gathers the slab rows and
    reuses the residual-saving grouped-FFN VJP, scattering dX back to
    token order.  Costs one extra forward recompute — only paid when
    someone actually differentiates through the inference path."""
    return grouped_ffn_tokens(
        x, src_tok, tile_gid, w_up, b_up, w_down, b_down, w_gate,
        act_name=act_name, gated=gated, block_m=block_m, block_i=block_i,
        interpret=interpret,
    )


def _gft_fwd(x, src_tok, tile_gid, w_up, b_up, w_down, b_down, w_gate,
             act_name, gated, block_m, block_i, interpret):
    y = grouped_ffn_tokens(
        x, src_tok, tile_gid, w_up, b_up, w_down, b_down, w_gate,
        act_name=act_name, gated=gated, block_m=block_m, block_i=block_i,
        interpret=interpret,
    )
    return y, (x, src_tok, tile_gid, w_up, b_up, w_down, b_down, w_gate)


def _gft_bwd(act_name, gated, block_m, block_i, interpret, res, dy):
    import numpy as np

    x, src_tok, tile_gid, w_up, b_up, w_down, b_down, w_gate = res
    xb = x[src_tok]
    if gated:
        def f(xb_, wu, bu, wd, bd, wg):
            return grouped_ffn_ad(xb_, tile_gid, wu, bu, wd, bd, wg,
                                  act_name, gated, block_m, block_i,
                                  interpret)
        _, vjp = jax.vjp(f, xb, w_up, b_up, w_down, b_down, w_gate)
        dxb, dwu, dbu, dwd, dbd, dwg = vjp(dy)
    else:
        def f(xb_, wu, bu, wd, bd):
            return grouped_ffn_ad(xb_, tile_gid, wu, bu, wd, bd, None,
                                  act_name, gated, block_m, block_i,
                                  interpret)
        _, vjp = jax.vjp(f, xb, w_up, b_up, w_down, b_down)
        dxb, dwu, dbu, dwd, dbd = vjp(dy)
        dwg = None
    dx = jnp.zeros(x.shape, jnp.float32).at[
        src_tok].add(dxb.astype(jnp.float32)).astype(x.dtype)
    ct_int = lambda a: np.zeros(a.shape, jax.dtypes.float0)
    return (dx, ct_int(src_tok), ct_int(tile_gid), dwu, dbu, dwd, dbd, dwg)


grouped_ffn_tokens_ad.defvjp(_gft_fwd, _gft_bwd)


def _capacity_tiling(c: int, cfg: MoEConfig | None = None
                     ) -> tuple[int, int, int]:
    """Shared row-tile selection for the capacity-buffer kernels: returns
    ``(block_m, padded_capacity, block_i)``.  Capacities <= 512 round up
    to the sublane multiple (each expert's weights stream through VMEM
    exactly once); larger ones tile at the largest dividing block.

    When a measured tuning entry matches (``flashmoe_tpu.tuning`` — the
    TPU analogue of the reference's per-arch trait table,
    ``arch.cuh:95-222``), its block sizes override the heuristic."""
    if c <= 512:
        bm = ((c + 7) // 8) * 8
    else:
        bm = next(b for b in (512, 256, 128) if c % b == 0) if any(
            c % b == 0 for b in (512, 256, 128)
        ) else 512
    block_i = 512 if bm <= 256 else 256
    if cfg is not None:
        from flashmoe_tpu import tuning

        t = tuning.lookup(
            "capacity_ffn", h=cfg.hidden_size, i=cfg.intermediate_size,
            dtype=jnp.dtype(cfg.dtype).name,
        )
        bm_t = t.get("block_m")
        # same ignore-if-not-dividing contract as the fused kernel's cm
        # override: a block measured at a large capacity must not inflate
        # a small runtime capacity's padding (tuning entries match on
        # (h, i, dtype) only)
        if bm_t and bm_t % 8 == 0 and c % bm_t == 0:
            bm = bm_t
        if t.get("block_i"):
            block_i = t["block_i"]  # _auto_block re-fits it to I below
    cp = ((c + bm - 1) // bm) * bm
    return bm, cp, block_i


def capacity_ffn_gather(x, plan, cfg: MoEConfig, capacity: int, params, *,
                        interpret: bool = False):
    """Capacity-path FFN with the dispatch gather fused into the kernel.

    Pads capacity to the row-tile size, derives per-slot source tokens
    from the plan, and runs the gather-fused kernel (differentiable via
    re-gather, :func:`grouped_ffn_tokens_ad`).  Returns ``([E, Cp, H],
    Cp)`` — combine must use the padded capacity so flat slot indices
    line up.
    """
    from flashmoe_tpu.ops import dispatch as dsp

    _, h = x.shape
    e = cfg.num_experts
    bm, cp, block_i = _capacity_tiling(capacity, cfg)
    src_tok, _ = dsp.dispatch_indices(plan, cfg, cp)
    tiles_per_e = cp // bm
    tile_gid = jnp.arange(e * tiles_per_e, dtype=jnp.int32) // tiles_per_e
    y = grouped_ffn_tokens_ad(
        x, src_tok.reshape(-1), tile_gid,
        params["w_up"].astype(x.dtype), params["b_up"],
        params["w_down"].astype(x.dtype), params["b_down"],
        params.get("w_gate", None) if cfg.gated_ffn else None,
        cfg.hidden_act, cfg.gated_ffn, bm, block_i, interpret,
    )
    return y.reshape(e, cp, h), cp


# ----------------------------------------------------------------------
# Grouped matmul / transposed grouped matmul — the backward kernels
# ----------------------------------------------------------------------

def _auto_block(dim: int, cap: int) -> int:
    """Largest chunk <= cap that divides dim (config validation keeps dims
    64-multiples, so this lands on an MXU-friendly size instead of
    rejecting e.g. H=768)."""
    for b in (512, 448, 384, 320, 256, 192, 128, 64, 32, 16, 8):
        if b <= cap and dim % b == 0:
            return b
    raise ValueError(f"dimension {dim} not a multiple of 8")

def _gmm_kernel(gid_ref, x_ref, w_ref, out_ref, acc_ref, *, transpose_w):
    """One (row-tile, K-chunk) grid step of out = x @ w[gid] (or @ w[gid]^T
    when ``transpose_w`` — the weight block is then [N, bk] and the
    contraction runs over its last dim, so no transposed weight copy is
    ever materialized in HBM)."""
    j = pl.program_id(1)
    nj = pl.num_programs(1)

    @pl.when(j == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    if transpose_w:
        acc_ref[:] += jax.lax.dot_general(
            x_ref[:], w_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
    else:
        acc_ref[:] += jnp.dot(
            x_ref[:], w_ref[0], preferred_element_type=jnp.float32
        )

    @pl.when(j == nj - 1)
    def _():
        out_ref[:] = acc_ref[:].astype(out_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("transpose_w", "block_m", "block_k",
                              "out_dtype", "interpret"),
)
def grouped_matmul(x, tile_gid, w, *, transpose_w: bool = False,
                   block_m: int = BLOCK_M, block_k: int = 512,
                   out_dtype=None, interpret: bool = False):
    """out[T, N] = x[T, K] @ w[gid(tile), K, N]   (transpose_w: w is
    [E, N, K] and contracts on its last dim).

    The grouped-GEMM primitive of the backward pass: dA and dX are grouped
    matmuls against the *forward* weight layouts with ``transpose_w=True``.
    """
    t, k = x.shape
    if transpose_w:
        e, n, kw = w.shape
    else:
        e, kw, n = w.shape
    if kw != k:
        raise ValueError(f"contraction mismatch: x K={k}, w K={kw}")
    if t % block_m:
        raise ValueError(f"rows {t} must be a multiple of {block_m}")
    bk = _auto_block(k, block_k)
    nt, nk = t // block_m, k // bk

    if transpose_w:
        w_spec = pl.BlockSpec((1, n, bk), lambda ti, j, gid: (gid[ti], 0, j),
                              memory_space=pltpu.VMEM)
    else:
        w_spec = pl.BlockSpec((1, bk, n), lambda ti, j, gid: (gid[ti], j, 0),
                              memory_space=pltpu.VMEM)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(nt, nk),
        in_specs=[
            pl.BlockSpec((block_m, bk), lambda ti, j, gid: (ti, j),
                         memory_space=pltpu.VMEM),
            w_spec,
        ],
        out_specs=pl.BlockSpec((block_m, n), lambda ti, j, gid: (ti, 0),
                               memory_space=pltpu.VMEM),
        scratch_shapes=[pltpu.VMEM((block_m, n), jnp.float32)],
    )
    return pl.pallas_call(
        functools.partial(_gmm_kernel, transpose_w=transpose_w),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((t, n), out_dtype or x.dtype),
        cost_estimate=pl.CostEstimate(
            flops=2 * t * k * n,
            bytes_accessed=x.size * x.dtype.itemsize
            + w.size * w.dtype.itemsize,
            transcendentals=0,
        ),
        interpret=interpret,
    )(tile_gid, x, w)


def _tgmm_kernel(gid_ref, x_ref, dy_ref, out_ref):
    """One (K-chunk, N-chunk, row-tile) step of dW[e] += x_tile^T @ dy_tile.

    Row tiles sweep fastest and ``tile_gid`` is nondecreasing (both the
    capacity and the ragged layouts are expert-major), so all tiles of one
    expert revisit the same output block consecutively — the accumulation
    lives in the block's VMEM copy and flushes once per expert."""
    t = pl.program_id(2)
    contrib = jax.lax.dot_general(
        x_ref[:], dy_ref[:], (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    first = jnp.logical_or(
        t == 0, gid_ref[jnp.maximum(t - 1, 0)] != gid_ref[t]
    )

    @pl.when(first)
    def _():
        out_ref[0] = contrib.astype(out_ref.dtype)

    @pl.when(jnp.logical_not(first))
    def _():
        out_ref[0] += contrib.astype(out_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("num_experts", "block_m", "block_k",
                              "block_n", "interpret"),
)
def tgmm(x, dy, tile_gid, num_experts: int, *, block_m: int = BLOCK_M,
         block_k: int = 512, block_n: int = 512,
         interpret: bool = False):
    """dW[E, K, N] = segment-sum over row tiles of x[T, K]^T @ dy[T, N].

    The weight-gradient kernel (megablox's transposed grouped GEMM):
    ``tile_gid`` MUST be nondecreasing.  Returns float32.
    """
    t, k = x.shape
    t2, n = dy.shape
    if t != t2:
        raise ValueError(f"row mismatch {t} vs {t2}")
    if t % block_m:
        raise ValueError(f"rows {t} must be a multiple of {block_m}")
    bk, bn = _auto_block(k, block_k), _auto_block(n, block_n)
    nt, nk, nn = t // block_m, k // bk, n // bn

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(nk, nn, nt),
        in_specs=[
            pl.BlockSpec((block_m, bk), lambda jk, jn, ti, gid: (ti, jk),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((block_m, bn), lambda jk, jn, ti, gid: (ti, jn),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(
            (1, bk, bn), lambda jk, jn, ti, gid: (gid[ti], jk, jn),
            memory_space=pltpu.VMEM,
        ),
    )
    out = pl.pallas_call(
        _tgmm_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((num_experts, k, n), jnp.float32),
        cost_estimate=pl.CostEstimate(
            flops=2 * t * k * n,
            bytes_accessed=(x.size + dy.size) * x.dtype.itemsize
            + num_experts * k * n * 4,
            transcendentals=0,
        ),
        interpret=interpret,
    )(tile_gid, x, dy)
    # experts absent from tile_gid (zero routed tokens on the ragged path)
    # have blocks the kernel never visited — UNINITIALIZED memory, not
    # zeros.  Select, don't multiply: NaN garbage * 0 would stay NaN.
    present = jnp.zeros((num_experts,), jnp.bool_).at[tile_gid].set(True)
    return jnp.where(present[:, None, None], out, 0.0)


def _segment_bias_grad(d, tile_gid, num_experts: int, block_m: int):
    """db[E, N] = per-expert row sum of d[T, N] (tiny; XLA einsum)."""
    nt = d.shape[0] // block_m
    per_tile = d.reshape(nt, block_m, -1).sum(axis=1)
    oh = jax.nn.one_hot(tile_gid, num_experts, dtype=per_tile.dtype)
    return jnp.einsum("tn,te->en", per_tile, oh)


# ----------------------------------------------------------------------
# Residual-saving forward + custom VJP: the fused backward path
# ----------------------------------------------------------------------

def _ffn_res_kernel(gid_ref, x_ref, wup_ref, bup_ref, wdn_ref, bdn_ref,
                    out_ref, u_out_ref, g_out_ref, acc_ref, *,
                    act_name, gated):
    """Same as :func:`_ffn_kernel` but additionally writes the
    pre-activation up (and gate) chunks — the residuals the backward needs,
    saved on the way through instead of recomputed."""
    j = pl.program_id(1)
    nj = pl.num_programs(1)
    act = activation_fn(act_name)

    @pl.when(j == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    x = x_ref[:]
    if gated:
        half = wup_ref.shape[2] // 2
        g = jnp.dot(x, wup_ref[0, :, :half],
                    preferred_element_type=jnp.float32)
        up = jnp.dot(x, wup_ref[0, :, half:],
                     preferred_element_type=jnp.float32)
        up = up + bup_ref[0, 0, :].astype(jnp.float32)
        g_out_ref[:] = g.astype(g_out_ref.dtype)
        u_out_ref[:] = up.astype(u_out_ref.dtype)
        hidden = act(g) * up
    else:
        up = jnp.dot(x, wup_ref[0], preferred_element_type=jnp.float32)
        up = up + bup_ref[0, 0, :].astype(jnp.float32)
        u_out_ref[:] = up.astype(u_out_ref.dtype)
        hidden = act(up)
    acc_ref[:] += jnp.dot(
        hidden.astype(x.dtype), wdn_ref[0], preferred_element_type=jnp.float32
    )

    @pl.when(j == nj - 1)
    def _():
        out_ref[:] = (
            acc_ref[:] + bdn_ref[0, 0, :].astype(jnp.float32)
        ).astype(out_ref.dtype)


def _grouped_ffn_res(x, tile_gid, w_up, b_up, w_down, b_down, w_gate, *,
                     act_name, gated, block_m, block_i, interpret):
    """Forward returning (y, u, g): u/g are the [T, I] pre-activation
    buffers (g is a zero-row placeholder when not gated)."""
    t, h = x.shape
    e, _, i = w_up.shape
    if t % block_m:
        raise ValueError(f"rows {t} must be a multiple of block_m={block_m}")
    bi = _auto_block(i, block_i)
    nt, nj = t // block_m, i // bi

    if gated:
        wg = w_gate.reshape(e, h, nj, bi)
        wu = w_up.reshape(e, h, nj, bi)
        w_up_eff = jnp.concatenate([wg, wu], axis=-1).reshape(
            e, h, nj * 2 * bi)
        up_block = (1, h, 2 * bi)
    else:
        w_up_eff = w_up
        up_block = (1, h, bi)
    b_up3 = b_up.reshape(e, 1, i)
    b_down3 = b_down.reshape(e, 1, h)

    g_spec = (
        pl.BlockSpec((block_m, bi), lambda ti, j, gid: (ti, j),
                     memory_space=pltpu.VMEM)
        if gated else
        # not gated: the kernel never writes the gate residual — collapse
        # it to one block so no [T, I] buffer is allocated for garbage
        pl.BlockSpec((block_m, bi), lambda ti, j, gid: (0, 0),
                     memory_space=pltpu.VMEM)
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(nt, nj),
        in_specs=[
            pl.BlockSpec((block_m, h), lambda ti, j, gid: (ti, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(up_block, lambda ti, j, gid: (gid[ti], 0, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, bi), lambda ti, j, gid: (gid[ti], 0, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, bi, h), lambda ti, j, gid: (gid[ti], j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, h), lambda ti, j, gid: (gid[ti], 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((block_m, h), lambda ti, j, gid: (ti, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((block_m, bi), lambda ti, j, gid: (ti, j),
                         memory_space=pltpu.VMEM),
            g_spec,
        ],
        scratch_shapes=[pltpu.VMEM((block_m, h), jnp.float32)],
    )
    y, u, g = pl.pallas_call(
        functools.partial(_ffn_res_kernel, act_name=act_name, gated=gated),
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((t, h), x.dtype),
            jax.ShapeDtypeStruct((t, i), x.dtype),
            jax.ShapeDtypeStruct((t, i) if gated else (block_m, bi),
                                 x.dtype),
        ],
        interpret=interpret,
    )(tile_gid, x, w_up_eff, b_up3, w_down, b_down3)
    return y, u, (g if gated else None)


@functools.partial(jax.custom_vjp, nondiff_argnums=(7, 8, 9, 10, 11))
def grouped_ffn_ad(x, tile_gid, w_up, b_up, w_down, b_down, w_gate,
                   act_name, gated, block_m, block_i, interpret):
    """Differentiable grouped FFN: Pallas forward AND Pallas backward.

    The backward's four large GEMMs run on kernels (dA and dX via
    :func:`grouped_matmul` ``transpose_w=True`` against the forward weight
    layouts; dW_up/dW_down via :func:`tgmm`), with pre-activations saved
    from the forward instead of recomputed — unlike the reference, which
    has no backward at all (SURVEY §2.6), and unlike round 1, which
    recomputed the whole forward through XLA."""
    return grouped_ffn(
        x, tile_gid, w_up, b_up, w_down, b_down, w_gate,
        act_name=act_name, gated=gated, block_m=block_m, block_i=block_i,
        interpret=interpret,
    )


def _gffn_fwd(x, tile_gid, w_up, b_up, w_down, b_down, w_gate,
              act_name, gated, block_m, block_i, interpret):
    y, u, g = _grouped_ffn_res(
        x, tile_gid, w_up, b_up, w_down, b_down, w_gate,
        act_name=act_name, gated=gated, block_m=block_m, block_i=block_i,
        interpret=interpret,
    )
    return y, (x, tile_gid, w_up, b_up, w_down, b_down, w_gate, u, g)


def ffn_backward_core(x, tile_gid, w_up, w_down, w_gate, u, g, dy, *,
                      act_name, gated, block_m, interpret):
    """Shared backward math over pre-activation residuals (u, g).

    All four large GEMMs run on the Pallas kernels: dHidden and dX via
    :func:`grouped_matmul` (transposed-weight contraction), dW via
    :func:`tgmm`.  Returns float32 (dx, d_wu, d_bu, d_wd, d_bd, d_wg) —
    d_wg is None when not gated.  Used by both the single-device grouped
    FFN VJP and the fused EP layer's VJP."""
    act = activation_fn(act_name)
    e = w_up.shape[0]
    dyc = dy.astype(x.dtype)

    # dHidden = dY @ w_down^T   [T, I]
    d_hidden = grouped_matmul(
        dyc, tile_gid, w_down, transpose_w=True, block_m=block_m,
        out_dtype=jnp.float32, interpret=interpret,
    )
    uf = u.astype(jnp.float32)
    if gated:
        gf = g.astype(jnp.float32)
        act_g, act_vjp = jax.vjp(act, gf)
        d_gate = act_vjp(d_hidden * uf)[0]
        d_up = d_hidden * act_g
        hidden = (act_g * uf).astype(x.dtype)
        dx = grouped_matmul(
            d_gate.astype(x.dtype), tile_gid, w_gate, transpose_w=True,
            block_m=block_m, out_dtype=jnp.float32, interpret=interpret,
        ) + grouped_matmul(
            d_up.astype(x.dtype), tile_gid, w_up, transpose_w=True,
            block_m=block_m, out_dtype=jnp.float32, interpret=interpret,
        )
        d_wg = tgmm(x, d_gate.astype(x.dtype), tile_gid, e,
                    block_m=block_m, interpret=interpret)
    else:
        act_u, act_vjp = jax.vjp(act, uf)
        d_up = act_vjp(d_hidden)[0]
        hidden = act_u.astype(x.dtype)
        dx = grouped_matmul(
            d_up.astype(x.dtype), tile_gid, w_up, transpose_w=True,
            block_m=block_m, out_dtype=jnp.float32, interpret=interpret,
        )
        d_wg = None
    d_wu = tgmm(x, d_up.astype(x.dtype), tile_gid, e,
                block_m=block_m, interpret=interpret)
    d_wd = tgmm(hidden, dyc, tile_gid, e,
                block_m=block_m, interpret=interpret)
    d_bu = _segment_bias_grad(d_up, tile_gid, e, block_m)
    d_bd = _segment_bias_grad(dy.astype(jnp.float32), tile_gid, e, block_m)
    return dx, d_wu, d_bu, d_wd, d_bd, d_wg


def _gffn_bwd(act_name, gated, block_m, block_i, interpret, res, dy):
    import numpy as np

    x, tile_gid, w_up, b_up, w_down, b_down, w_gate, u, g = res
    dx, d_wu, d_bu, d_wd, d_bd, d_wg = ffn_backward_core(
        x, tile_gid, w_up, w_down, w_gate, u, g, dy,
        act_name=act_name, gated=gated, block_m=block_m,
        interpret=interpret,
    )
    ct_wg = d_wg.astype(w_gate.dtype) if gated else None
    ct_gid = np.zeros(tile_gid.shape, jax.dtypes.float0)
    return (dx.astype(x.dtype), ct_gid, d_wu.astype(w_up.dtype),
            d_bu.astype(b_up.dtype), d_wd.astype(w_down.dtype),
            d_bd.astype(b_down.dtype), ct_wg)


grouped_ffn_ad.defvjp(_gffn_fwd, _gffn_bwd)


def capacity_buffer_ffn_ad(xs, params, cfg: MoEConfig,
                           interpret: bool = False):
    """Differentiable capacity-buffer FFN: the grouped Pallas kernel with
    its fused Pallas backward (:func:`grouped_ffn_ad`) under the same
    reshaping as :func:`capacity_buffer_ffn_pallas` — autodiff flows
    through the reshapes natively."""
    e, c, h = xs.shape
    bm, cp, block_i = _capacity_tiling(c, cfg)
    if cp != c:
        xs = jnp.pad(xs, ((0, 0), (0, cp - c), (0, 0)))
    x = xs.reshape(e * cp, h)
    tiles_per_e = cp // bm
    tile_gid = jnp.arange(e * tiles_per_e, dtype=jnp.int32) // tiles_per_e
    out = grouped_ffn_ad(
        x, tile_gid, params["w_up"].astype(x.dtype), params["b_up"],
        params["w_down"].astype(x.dtype), params["b_down"],
        params.get("w_gate", None) if cfg.gated_ffn else None,
        cfg.hidden_act, cfg.gated_ffn, bm, block_i, interpret,
    )
    return out.reshape(e, cp, h)[:, :c, :]


def capacity_buffer_ffn_pallas(xs, params, cfg: MoEConfig, *,
                               interpret: bool = False):
    """Run the grouped kernel on an [E, C, H] capacity buffer.

    The capacity buffer is already expert-major, so tile group ids are just
    ``expert_of_tile = tile_index // (C / block_m)`` — no sort needed.  C is
    padded up to a block multiple; pad rows compute garbage that combine
    never reads.
    """
    e, c, h = xs.shape
    bm, cp, block_i = _capacity_tiling(c, cfg)
    if cp != c:
        xs = jnp.pad(xs, ((0, 0), (0, cp - c), (0, 0)))
    x = xs.reshape(e * cp, h)
    tiles_per_e = cp // bm
    tile_gid = (
        jnp.arange(e * tiles_per_e, dtype=jnp.int32) // tiles_per_e
    )
    out = grouped_ffn(
        x, tile_gid, params["w_up"].astype(x.dtype),
        params["b_up"], params["w_down"].astype(x.dtype), params["b_down"],
        params.get("w_gate", None) if cfg.gated_ffn else None,
        act_name=cfg.hidden_act, gated=cfg.gated_ffn, block_m=bm,
        block_i=block_i, interpret=interpret,
    )
    return out.reshape(e, cp, h)[:, :c, :]
