"""Fused MoE gate (router): GEMM + softmax + top-k + expert counts.

TPU-native re-design of the reference's ``FusedGate``
(``csrc/include/flashmoe/moe/gate.cuh:93-720``), which fuses the gate GEMM
with an in-register online softmax, online top-k, and a CUB BlockScan token
compaction, using a block-ring over SMs when E exceeds one CUDA tile
(``gate.cuh:229-269, 321-390``).

On TPU none of that choreography is needed: one Pallas grid step owns a full
``[BLOCK_M, E_padded]`` logits tile in VMEM, so softmax and top-k are simple
vector ops after an MXU matmul — the "multi-block ring" collapses to a wider
lane dimension.  The kernel additionally accumulates the two statistics the
reference gathers for its aux loss (``gate.cuh:273-299``): per-expert
softmax-probability sums and per-expert top-k selection counts.

Two implementations with identical semantics:
  * :func:`router_xla` — plain jnp/lax, used as fallback and oracle.
  * :func:`router_pallas` — fused Pallas kernel (matmul + softmax + top-k +
    stats in one VMEM-resident pass).
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from flashmoe_tpu.config import BLOCK_M, LANE, MoEConfig


class RouterOutput(NamedTuple):
    """Routing decisions for one token shard.

    combine_weights: [S, K] normalized weights of the selected experts.
    expert_idx:      [S, K] int32 selected expert ids.
    expert_counts:   [E]    int32 number of (token, k) selections per expert.
    probs_mean:      [E]    mean softmax probability per expert (aux loss).
    aux_loss:        []     load-balancing loss (Switch-style).
    z_loss:          []     router z-loss (0 unless enabled).
    """

    combine_weights: jax.Array
    expert_idx: jax.Array
    expert_counts: jax.Array
    probs_mean: jax.Array
    aux_loss: jax.Array
    z_loss: jax.Array


def _finish(cfg: MoEConfig, top_p, top_idx, probs_sum, counts, zsum, s_tokens):
    """Shared epilogue: normalize top-k weights, form aux/z losses."""
    denom = jnp.sum(top_p, axis=-1, keepdims=True)
    combine_weights = (top_p / jnp.maximum(denom, 1e-20)).astype(cfg.accum_dtype)
    probs_mean = probs_sum / s_tokens
    density = counts.astype(cfg.accum_dtype) / (s_tokens * cfg.expert_top_k)
    # Switch-transformer load-balance loss: E * sum(density * mean_prob).
    aux = cfg.num_experts * jnp.sum(density * probs_mean) * cfg.expert_top_k
    z = (zsum / s_tokens) * cfg.router_z_loss_coef
    return RouterOutput(
        combine_weights=combine_weights,
        expert_idx=top_idx.astype(jnp.int32),
        expert_counts=counts.astype(jnp.int32),
        probs_mean=probs_mean,
        aux_loss=aux.astype(cfg.accum_dtype),
        z_loss=z.astype(cfg.accum_dtype),
    )


# ----------------------------------------------------------------------
# XLA reference path
# ----------------------------------------------------------------------

def router_xla(x, gate_w, cfg: MoEConfig) -> RouterOutput:
    """Router in plain XLA ops. x: [S, H], gate_w: [H, E]."""
    s = x.shape[0]
    logits = jnp.dot(
        x.astype(cfg.accum_dtype),
        gate_w.astype(cfg.accum_dtype),
        preferred_element_type=cfg.accum_dtype,
    )
    from flashmoe_tpu.chaos import inject as chaos_inject

    if chaos_inject.is_armed("skewed_routing"):  # trace-time check only
        logits = chaos_inject.poison_logits(logits)
    probs = jax.nn.softmax(logits, axis=-1)
    top_p, top_idx = jax.lax.top_k(probs, cfg.expert_top_k)
    counts = jnp.sum(
        jax.nn.one_hot(top_idx, cfg.num_experts, dtype=jnp.int32), axis=(0, 1)
    )
    zsum = jnp.sum(jnp.square(jax.nn.logsumexp(logits, axis=-1)))
    return _finish(cfg, top_p, top_idx, jnp.sum(probs, axis=0), counts, zsum, s)


# ----------------------------------------------------------------------
# Pallas fused kernel
# ----------------------------------------------------------------------

def _gate_kernel(x_ref, w_ref, top_p_ref, top_i_ref, stats_ref, *, k, e, px):
    """One grid step: route BLOCK_M tokens.

    stats_ref accumulates [3, PX]: row 0 = sum of softmax probs, row 1 =
    top-k selection counts, row 2 = z-loss partial (lane 0 only).
    """
    logits = jnp.dot(
        x_ref[:].astype(jnp.float32),
        w_ref[:].astype(jnp.float32),
        preferred_element_type=jnp.float32,
    )  # [BM, PX]
    bm = logits.shape[0]
    col = jax.lax.broadcasted_iota(jnp.int32, (bm, px), 1)
    neg = jnp.float32(-1e30)
    logits = jnp.where(col < e, logits, neg)

    # numerically-stable softmax over the (padded) expert axis
    m = jnp.max(logits, axis=-1, keepdims=True)
    ex = jnp.where(col < e, jnp.exp(logits - m), 0.0)
    se = jnp.sum(ex, axis=-1, keepdims=True)
    probs = ex / se

    # z-loss partial: logsumexp = m + log(se)  (kept 2D for TPU layouts)
    lse = m + jnp.log(se)
    zpart = jnp.sum(jnp.square(lse))

    # iterative top-k (K is small and static -> unrolled)
    p = probs
    sel_count = jnp.zeros((bm, px), jnp.float32)
    top_ps, top_is = [], []
    for _ in range(k):
        mx = jnp.max(p, axis=-1, keepdims=True)
        is_max = (p == mx) & (col < e)
        idx = jnp.min(jnp.where(is_max, col, px), axis=-1, keepdims=True)
        hit = col == idx
        top_ps.append(mx)
        top_is.append(idx)
        sel_count = sel_count + hit.astype(jnp.float32)
        p = jnp.where(hit, neg, p)
    top_p_ref[:] = jnp.concatenate(top_ps, axis=1)
    top_i_ref[:] = jnp.concatenate(top_is, axis=1)

    first = pl.program_id(0) == 0

    @pl.when(first)
    def _():
        stats_ref[:] = jnp.zeros_like(stats_ref)

    row = jax.lax.broadcasted_iota(jnp.int32, (8, px), 0)
    lane0 = jax.lax.broadcasted_iota(jnp.int32, (8, px), 1) == 0
    update = (
        jnp.where(row == 0, jnp.sum(probs, axis=0)[None, :], 0.0)
        + jnp.where(row == 1, jnp.sum(sel_count, axis=0)[None, :], 0.0)
        + jnp.where((row == 2) & lane0, zpart, 0.0)
    )
    stats_ref[:] = stats_ref[:] + update


@functools.partial(jax.jit, static_argnames=("cfg", "interpret"))
def router_pallas(x, gate_w, cfg: MoEConfig, interpret: bool = False
                  ) -> RouterOutput:
    """Fused gate on TPU. x: [S, H], gate_w: [H, E]. S must divide by 8."""
    s, h = x.shape
    e, k = cfg.num_experts, cfg.expert_top_k
    px = max(LANE, ((e + LANE - 1) // LANE) * LANE)
    if s % 8:
        raise ValueError(f"token count {s} must be a multiple of 8")
    # largest power-of-two row tile (<= BLOCK_M) dividing S, so any S % 8 == 0
    # token count works without padding
    bm = next(b for b in (128, 64, 32, 16, 8) if s % b == 0)
    w_pad = jnp.zeros((h, px), gate_w.dtype).at[:, :e].set(gate_w)

    grid = (s // bm,)
    top_p, top_i, stats = pl.pallas_call(
        functools.partial(_gate_kernel, k=k, e=e, px=px),
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, h), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((h, px), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((bm, k), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((bm, k), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((8, px), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((s, k), jnp.float32),
            jax.ShapeDtypeStruct((s, k), jnp.int32),
            jax.ShapeDtypeStruct((8, px), jnp.float32),
        ],
        interpret=interpret,
    )(x, w_pad)

    probs_sum = stats[0, :e]
    counts = stats[1, :e].astype(jnp.int32)
    zsum = stats[2, 0]
    return _finish(cfg, top_p, top_i, probs_sum, counts, zsum, s)


# ----------------------------------------------------------------------
# Two-pass expert-tiled gate: E beyond one VMEM tile
# ----------------------------------------------------------------------
#
# The reference handles E > one CUDA tile with a block-ring: phase 1
# passes an (max, sum) baton around SMs to form the global softmax
# normalizer, phase 2 rings the top-k (``gate.cuh:93-467``).  The TPU
# equivalent tiles the EXPERT axis across grid steps of one core:
#
#   pass 1 (grid nt x nj, experts inner): logits tile GEMM -> online
#     softmax running (m, se) in VMEM scratch + running top-k merged
#     tile-by-tile (the baton is just kernel-resident state); logits are
#     spilled to HBM so pass 2 need not redo the GEMM.
#   pass 2 (grid nj x nt, tokens inner): re-reads each logits tile with
#     the final (m, se) to accumulate the exact per-expert probability
#     sums / selection counts / z-loss the aux losses need (these are
#     sums over tokens of globally-normalized probs, so they cannot be
#     finalized inside pass 1's running rescale).

_ET = 512  # expert-tile width (lanes) of the two-pass gate


def _gate_pass1_kernel(x_ref, w_ref, *refs, k, e, et, spill):
    """``spill`` controls whether the logits tile is written to HBM for
    pass 2 (training/z-loss stats); inference skips the output entirely —
    at E=16k, S=8k that is a ~0.5 GB write per layer."""
    if spill:
        logits_ref, m_ref, se_ref, tv_ref, ti_ref = refs[:5]
        mrun, serun, topv, topi = refs[5:]
    else:
        logits_ref = None
        m_ref, se_ref, tv_ref, ti_ref = refs[:4]
        mrun, serun, topv, topi = refs[4:]
    j = pl.program_id(1)
    nj = pl.num_programs(1)
    bm = x_ref.shape[0]
    neg = jnp.float32(-1e30)

    @pl.when(j == 0)
    def _():
        mrun[:] = jnp.full_like(mrun, neg)
        serun[:] = jnp.zeros_like(serun)
        topv[:] = jnp.full_like(topv, neg)
        topi[:] = jnp.full_like(topi, -1)

    logits = jnp.dot(
        x_ref[:].astype(jnp.float32), w_ref[:].astype(jnp.float32),
        preferred_element_type=jnp.float32,
    )  # [bm, et]
    col = jax.lax.broadcasted_iota(jnp.int32, (bm, et), 1)
    gcol = col + j * et
    logits = jnp.where(gcol < e, logits, neg)
    if spill:
        logits_ref[:] = logits

    # online (max, sum) update with rescale — the softmax baton
    m_old = mrun[:, 0:1]
    mt = jnp.max(logits, axis=-1, keepdims=True)
    m_new = jnp.maximum(m_old, mt)
    ex = jnp.where(gcol < e, jnp.exp(logits - m_new), 0.0)
    se_new = (serun[:, 0:1] * jnp.exp(m_old - m_new)
              + jnp.sum(ex, axis=-1, keepdims=True))
    mrun[:] = jnp.broadcast_to(m_new, mrun.shape)
    serun[:] = jnp.broadcast_to(se_new, serun.shape)

    # tile top-k by logit (same order as by prob), then merge with the
    # carried top-k.  Expert ranges of carried vs tile candidates are
    # disjoint, so indices never collide.
    p = logits
    cand_v, cand_i = [], []
    for _ in range(k):
        mx = jnp.max(p, axis=-1, keepdims=True)
        is_mx = (p == mx) & (gcol < e)
        idx = jnp.min(jnp.where(is_mx, gcol, jnp.int32(2**30)),
                      axis=-1, keepdims=True)
        ok = idx < jnp.int32(2**30)
        cand_v.append(jnp.where(ok, mx, neg))
        cand_i.append(jnp.where(ok, idx, -1))
        p = jnp.where(gcol == idx, neg, p)

    lane = jax.lax.broadcasted_iota(jnp.int32, topv.shape, 1)
    cv, ci = topv[:], topi[:]
    for t in range(k):
        cv = jnp.where(lane == k + t, cand_v[t], cv)
        ci = jnp.where(lane == k + t, cand_i[t], ci)
    nv = jnp.full_like(cv, neg)
    ni = jnp.full_like(ci, -1)
    for t in range(k):
        mx = jnp.max(cv, axis=-1, keepdims=True)
        lsel = jnp.min(jnp.where(cv == mx, lane, jnp.int32(2**30)),
                       axis=-1, keepdims=True)
        hit = lane == lsel
        isel = jnp.max(jnp.where(hit, ci, -1), axis=-1, keepdims=True)
        nv = jnp.where(lane == t, mx, nv)
        ni = jnp.where(lane == t, isel, ni)
        cv = jnp.where(hit, neg, cv)
    topv[:] = nv
    topi[:] = ni

    @pl.when(j == nj - 1)
    def _():
        m_ref[:] = mrun[:]
        se_ref[:] = serun[:]
        tv_ref[:] = topv[:]
        ti_ref[:] = topi[:]


def _gate_pass2_kernel(logits_ref, m_ref, se_ref, ti_ref, stats_ref, *,
                       k, e, et):
    j = pl.program_id(0)
    ii = pl.program_id(1)
    bm = logits_ref.shape[0]

    @pl.when(ii == 0)
    def _():
        stats_ref[:] = jnp.zeros_like(stats_ref)

    m = m_ref[:, 0:1]
    se = se_ref[:, 0:1]
    col = jax.lax.broadcasted_iota(jnp.int32, (bm, et), 1)
    gcol = col + j * et
    probs = jnp.where(gcol < e,
                      jnp.exp(logits_ref[:] - m) / jnp.maximum(se, 1e-30),
                      0.0)
    sel = jnp.zeros((bm, et), jnp.float32)
    for t in range(k):
        sel = sel + (gcol == ti_ref[:, t:t + 1]).astype(jnp.float32)
    # z-loss partial once per token tile (tile j==0 carries it)
    lse = m + jnp.log(jnp.maximum(se, 1e-30))
    zpart = jnp.sum(jnp.square(lse)) * jnp.where(j == 0, 1.0, 0.0)
    row = jax.lax.broadcasted_iota(jnp.int32, (8, et), 0)
    lane0 = jax.lax.broadcasted_iota(jnp.int32, (8, et), 1) == 0
    stats_ref[:] = stats_ref[:] + (
        jnp.where(row == 0, jnp.sum(probs, axis=0)[None, :], 0.0)
        + jnp.where(row == 1, jnp.sum(sel, axis=0)[None, :], 0.0)
        + jnp.where((row == 2) & lane0, zpart, 0.0)
    )


def router_pallas_tiled(x, gate_w, cfg: MoEConfig, interpret: bool = False,
                        need_stats: bool | None = None) -> RouterOutput:
    """Two-pass fused gate for E beyond the single-tile VMEM budget.
    x: [S, H], gate_w: [H, E];  S % 8 == 0, E > _ET recommended.

    ``need_stats=None`` resolves OUTSIDE the jitted core (env vars read
    inside a jit bind at trace time and then stick in the cache):
    training / z-loss configs, ``cfg.collect_stats`` (the flight
    recorder's router-entropy signal wants real probability sums), and
    ``FLASHMOE_GATE_STATS=1`` get the stats pass; plain inference skips
    it (aux fields report zero)."""
    if need_stats is None:
        import os as _os

        need_stats = (cfg.is_training or cfg.router_z_loss_coef > 0
                      or cfg.collect_stats
                      or _os.environ.get("FLASHMOE_GATE_STATS") == "1")
    return _router_pallas_tiled_jit(x, gate_w, cfg, interpret,
                                    bool(need_stats))


@functools.partial(jax.jit,
                   static_argnames=("cfg", "interpret", "need_stats"))
def _router_pallas_tiled_jit(x, gate_w, cfg: MoEConfig, interpret: bool,
                             need_stats: bool) -> RouterOutput:
    s, h = x.shape
    e, k = cfg.num_experts, cfg.expert_top_k
    if s % 8:
        raise ValueError(f"token count {s} must be a multiple of 8")
    if 2 * k > LANE:
        # the carried+candidate top-k merge lives in lanes [0, 2k) of a
        # LANE-wide scratch; beyond that candidates would silently drop
        raise ValueError(f"top_k {k} exceeds the merge buffer ({LANE // 2})")
    et = _ET
    nj = (e + et - 1) // et
    px = nj * et
    bm = next(b for b in (128, 64, 32, 16, 8) if s % b == 0)
    nt = s // bm
    w_pad = jnp.zeros((h, px), gate_w.dtype).at[:, :e].set(gate_w)

    lane_spec = pl.BlockSpec((bm, LANE), lambda i, j: (i, 0),
                             memory_space=pltpu.VMEM)
    lane_shape = jax.ShapeDtypeStruct((s, LANE), jnp.float32)
    out_specs = [lane_spec] * 4
    out_shape = [lane_shape, lane_shape, lane_shape,
                 jax.ShapeDtypeStruct((s, LANE), jnp.int32)]
    if need_stats:
        out_specs = [pl.BlockSpec((bm, et), lambda i, j: (i, j),
                                  memory_space=pltpu.VMEM)] + out_specs
        out_shape = [jax.ShapeDtypeStruct((s, px), jnp.float32)] + out_shape
    res = pl.pallas_call(
        functools.partial(_gate_pass1_kernel, k=k, e=e, et=et,
                          spill=need_stats),
        grid=(nt, nj),
        in_specs=[
            pl.BlockSpec((bm, h), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((h, et), lambda i, j: (0, j),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[
            pltpu.VMEM((bm, LANE), jnp.float32),
            pltpu.VMEM((bm, LANE), jnp.float32),
            pltpu.VMEM((bm, LANE), jnp.float32),
            pltpu.VMEM((bm, LANE), jnp.int32),
        ],
        interpret=interpret,
    )(x, w_pad)
    if need_stats:
        logits, m, se, tv, ti = res
    else:
        m, se, tv, ti = res

    top_l = tv[:, :k]
    top_i = ti[:, :k].astype(jnp.int32)
    top_p = jnp.exp(top_l - m[:, 0:1]) / jnp.maximum(se[:, 0:1], 1e-30)

    if need_stats:
        stats = pl.pallas_call(
            functools.partial(_gate_pass2_kernel, k=k, e=e, et=et),
            grid=(nj, nt),
            in_specs=[
                pl.BlockSpec((bm, et), lambda j, i: (i, j),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((bm, LANE), lambda j, i: (i, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((bm, LANE), lambda j, i: (i, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((bm, LANE), lambda j, i: (i, 0),
                             memory_space=pltpu.VMEM),
            ],
            out_specs=pl.BlockSpec((8, et), lambda j, i: (0, j),
                                   memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct((8, px), jnp.float32),
            interpret=interpret,
        )(logits, m, se, ti)
        probs_sum = stats[0, :e]
        counts = stats[1, :e].astype(jnp.int32)
        zsum = stats[2, 0]
    else:
        # selection counts are cheap XLA-side; prob sums / z-loss are
        # training-only and reported as zero (aux_loss = 0 at inference —
        # under AD the custom_vjp still backs through router_xla)
        counts = jnp.zeros((e,), jnp.int32).at[top_i.reshape(-1)].add(1)
        probs_sum = jnp.zeros((e,), jnp.float32)
        zsum = jnp.float32(0.0)
    return _finish(cfg, top_p, top_i, probs_sum, counts, zsum, s)


# The kernel has no autodiff rule; under AD the fused router runs its
# forward and recomputes the backward through router_xla (identical math).
@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def _router_pallas_ad(x, gate_w, cfg: MoEConfig, interpret: bool):
    return router_pallas(x, gate_w, cfg, interpret=interpret)


def _router_fwd(x, gate_w, cfg, interpret):
    return router_pallas(x, gate_w, cfg, interpret=interpret), (x, gate_w)


def _router_bwd(cfg, interpret, res, ct):
    x, gate_w = res
    _, vjp_fn = jax.vjp(lambda xx, w: router_xla(xx, w, cfg), x, gate_w)
    return vjp_fn(ct)


_router_pallas_ad.defvjp(_router_fwd, _router_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def _router_tiled_ad(x, gate_w, cfg: MoEConfig, interpret: bool):
    return router_pallas_tiled(x, gate_w, cfg, interpret=interpret)


def _router_tiled_fwd(x, gate_w, cfg, interpret):
    return (router_pallas_tiled(x, gate_w, cfg, interpret=interpret),
            (x, gate_w))


_router_tiled_ad.defvjp(_router_tiled_fwd, _router_bwd)


def gate_vmem_bytes(s: int, h: int, e: int, dtype) -> int:
    """Static VMEM estimate of the fused gate's working set: the weight
    tile [H, PX], the token tile [BM, H], and ~4 [BM, PX]-sized f32
    intermediates (logits, exp, probs, selection mask)."""
    px = max(LANE, ((e + LANE - 1) // LANE) * LANE)
    bm = next(b for b in (128, 64, 32, 16, 8) if s % b == 0) if s % 8 == 0 \
        else 128
    item = jnp.dtype(dtype).itemsize
    return h * px * item + bm * h * item + 4 * bm * px * 4 + 8 * px * 4


# Single-tile gate ceiling: the kernel holds the full padded-E logits tile
# in VMEM, so it serves E up to a few thousand (h=2048 bf16: E <= ~4k).
# Past the budget the router switches to the two-pass expert-tiled kernel
# (:func:`router_pallas_tiled`) — the TPU form of the reference's
# multi-block ring (gate.cuh:93-467).
_GATE_VMEM_BUDGET = 12 * 2**20


def apply_replicas(out: RouterOutput, cfg: MoEConfig) -> RouterOutput:
    """Split hot-expert traffic across its replica slots
    (``cfg.expert_replicas``, written by the self-healing controller's
    re-placement action — :mod:`flashmoe_tpu.runtime.controller`).

    For each static (hot, slot) pair, tokens whose top-k selected
    ``hot`` are remapped to ``slot`` by token parity — a deterministic
    half/half split.  The controller guarantees ``slot``'s FFN weights
    are a value-identical copy of ``hot``'s, so every token is processed
    by exactly one replica with the same math and the combine merges
    contributions unchanged; only the *physical* load (and therefore
    capacity drops and per-device work) splits.  ``expert_counts`` is
    recomputed over the remapped slots so the dispatch plan, MoEStats
    load histogram, and the controller's own feedback all see physical
    slot load; ``aux_loss``/``probs_mean`` keep the router's logical
    view (computed pre-remap).  Empty map = identity (no ops added)."""
    if not cfg.expert_replicas:
        return out
    idx = out.expert_idx
    pos = jnp.arange(idx.shape[0], dtype=idx.dtype)[:, None]
    for hot, slot in cfg.expert_replicas:
        take = (idx == hot) & (pos % 2 == 1)
        idx = jnp.where(take, jnp.asarray(slot, idx.dtype), idx)
    counts = jnp.sum(
        jax.nn.one_hot(idx, cfg.num_experts, dtype=jnp.int32),
        axis=(0, 1))
    return out._replace(expert_idx=idx, expert_counts=counts)


def router(x, gate_w, cfg: MoEConfig, use_pallas: bool = True,
           interpret: bool = False) -> RouterOutput:
    """Dispatch to a fused kernel on TPU, XLA fallback elsewhere.
    Differentiable on all paths.  Large-E configs beyond the single-tile
    kernel's VMEM budget (:func:`gate_vmem_bytes`) use the two-pass
    expert-tiled kernel."""
    from flashmoe_tpu.chaos import inject as chaos_inject

    if chaos_inject.is_armed("skewed_routing") and use_pallas:
        # the skew fault biases router LOGITS (router_xla hook); the
        # fused gate kernels compute logits in-kernel, so chaos drills
        # route through the XLA gate while this point is armed
        return apply_replicas(router_xla(x, gate_w, cfg), cfg)
    on_tpu = interpret or jax.default_backend() == "tpu"
    s, h = x.shape
    if not (use_pallas and s % 8 == 0 and on_tpu):
        return apply_replicas(router_xla(x, gate_w, cfg), cfg)
    fits = gate_vmem_bytes(s, h, cfg.num_experts, x.dtype) \
        <= _GATE_VMEM_BUDGET
    if fits:
        return apply_replicas(_router_pallas_ad(x, gate_w, cfg, interpret),
                              cfg)
    if 2 * cfg.expert_top_k > LANE:
        # the tiled kernel's carried+candidate top-k merge holds 2k lanes;
        # beyond that use the XLA path instead of raising (advisor r4 #4)
        return apply_replicas(router_xla(x, gate_w, cfg), cfg)
    return apply_replicas(_router_tiled_ad(x, gate_w, cfg, interpret), cfg)
