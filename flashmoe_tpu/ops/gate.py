"""Fused MoE gate (router): GEMM + softmax + top-k + expert counts.

TPU-native re-design of the reference's ``FusedGate``
(``csrc/include/flashmoe/moe/gate.cuh:93-720``), which fuses the gate GEMM
with an in-register online softmax, online top-k, and a CUB BlockScan token
compaction, using a block-ring over SMs when E exceeds one CUDA tile
(``gate.cuh:229-269, 321-390``).

On TPU none of that choreography is needed: one Pallas grid step owns a full
``[BLOCK_M, E_padded]`` logits tile in VMEM, so softmax and top-k are simple
vector ops after an MXU matmul — the "multi-block ring" collapses to a wider
lane dimension.  The kernel additionally accumulates the two statistics the
reference gathers for its aux loss (``gate.cuh:273-299``): per-expert
softmax-probability sums and per-expert top-k selection counts.

Two implementations with identical semantics:
  * :func:`router_xla` — plain jnp/lax, used as fallback and oracle.
  * :func:`router_pallas` — fused Pallas kernel (matmul + softmax + top-k +
    stats in one VMEM-resident pass).
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from flashmoe_tpu.config import BLOCK_M, LANE, MoEConfig


class RouterOutput(NamedTuple):
    """Routing decisions for one token shard.

    combine_weights: [S, K] normalized weights of the selected experts.
    expert_idx:      [S, K] int32 selected expert ids.
    expert_counts:   [E]    int32 number of (token, k) selections per expert.
    probs_mean:      [E]    mean softmax probability per expert (aux loss).
    aux_loss:        []     load-balancing loss (Switch-style).
    z_loss:          []     router z-loss (0 unless enabled).
    """

    combine_weights: jax.Array
    expert_idx: jax.Array
    expert_counts: jax.Array
    probs_mean: jax.Array
    aux_loss: jax.Array
    z_loss: jax.Array


def _finish(cfg: MoEConfig, top_p, top_idx, probs_sum, counts, zsum, s_tokens):
    """Shared epilogue: normalize top-k weights, form aux/z losses."""
    denom = jnp.sum(top_p, axis=-1, keepdims=True)
    combine_weights = (top_p / jnp.maximum(denom, 1e-20)).astype(cfg.accum_dtype)
    probs_mean = probs_sum / s_tokens
    density = counts.astype(cfg.accum_dtype) / (s_tokens * cfg.expert_top_k)
    # Switch-transformer load-balance loss: E * sum(density * mean_prob).
    aux = cfg.num_experts * jnp.sum(density * probs_mean) * cfg.expert_top_k
    z = (zsum / s_tokens) * cfg.router_z_loss_coef
    return RouterOutput(
        combine_weights=combine_weights,
        expert_idx=top_idx.astype(jnp.int32),
        expert_counts=counts.astype(jnp.int32),
        probs_mean=probs_mean,
        aux_loss=aux.astype(cfg.accum_dtype),
        z_loss=z.astype(cfg.accum_dtype),
    )


# ----------------------------------------------------------------------
# XLA reference path
# ----------------------------------------------------------------------

def router_xla(x, gate_w, cfg: MoEConfig) -> RouterOutput:
    """Router in plain XLA ops. x: [S, H], gate_w: [H, E]."""
    s = x.shape[0]
    logits = jnp.dot(
        x.astype(cfg.accum_dtype),
        gate_w.astype(cfg.accum_dtype),
        preferred_element_type=cfg.accum_dtype,
    )
    probs = jax.nn.softmax(logits, axis=-1)
    top_p, top_idx = jax.lax.top_k(probs, cfg.expert_top_k)
    counts = jnp.sum(
        jax.nn.one_hot(top_idx, cfg.num_experts, dtype=jnp.int32), axis=(0, 1)
    )
    zsum = jnp.sum(jnp.square(jax.nn.logsumexp(logits, axis=-1)))
    return _finish(cfg, top_p, top_idx, jnp.sum(probs, axis=0), counts, zsum, s)


# ----------------------------------------------------------------------
# Pallas fused kernel
# ----------------------------------------------------------------------

def _gate_kernel(x_ref, w_ref, top_p_ref, top_i_ref, stats_ref, *, k, e, px):
    """One grid step: route BLOCK_M tokens.

    stats_ref accumulates [3, PX]: row 0 = sum of softmax probs, row 1 =
    top-k selection counts, row 2 = z-loss partial (lane 0 only).
    """
    logits = jnp.dot(
        x_ref[:].astype(jnp.float32),
        w_ref[:].astype(jnp.float32),
        preferred_element_type=jnp.float32,
    )  # [BM, PX]
    bm = logits.shape[0]
    col = jax.lax.broadcasted_iota(jnp.int32, (bm, px), 1)
    neg = jnp.float32(-1e30)
    logits = jnp.where(col < e, logits, neg)

    # numerically-stable softmax over the (padded) expert axis
    m = jnp.max(logits, axis=-1, keepdims=True)
    ex = jnp.where(col < e, jnp.exp(logits - m), 0.0)
    se = jnp.sum(ex, axis=-1, keepdims=True)
    probs = ex / se

    # z-loss partial: logsumexp = m + log(se)  (kept 2D for TPU layouts)
    lse = m + jnp.log(se)
    zpart = jnp.sum(jnp.square(lse))

    # iterative top-k (K is small and static -> unrolled)
    p = probs
    sel_count = jnp.zeros((bm, px), jnp.float32)
    top_ps, top_is = [], []
    for _ in range(k):
        mx = jnp.max(p, axis=-1, keepdims=True)
        is_max = (p == mx) & (col < e)
        idx = jnp.min(jnp.where(is_max, col, px), axis=-1, keepdims=True)
        hit = col == idx
        top_ps.append(mx)
        top_is.append(idx)
        sel_count = sel_count + hit.astype(jnp.float32)
        p = jnp.where(hit, neg, p)
    top_p_ref[:] = jnp.concatenate(top_ps, axis=1)
    top_i_ref[:] = jnp.concatenate(top_is, axis=1)

    first = pl.program_id(0) == 0

    @pl.when(first)
    def _():
        stats_ref[:] = jnp.zeros_like(stats_ref)

    row = jax.lax.broadcasted_iota(jnp.int32, (8, px), 0)
    lane0 = jax.lax.broadcasted_iota(jnp.int32, (8, px), 1) == 0
    update = (
        jnp.where(row == 0, jnp.sum(probs, axis=0)[None, :], 0.0)
        + jnp.where(row == 1, jnp.sum(sel_count, axis=0)[None, :], 0.0)
        + jnp.where((row == 2) & lane0, zpart, 0.0)
    )
    stats_ref[:] = stats_ref[:] + update


@functools.partial(jax.jit, static_argnames=("cfg", "interpret"))
def router_pallas(x, gate_w, cfg: MoEConfig, interpret: bool = False
                  ) -> RouterOutput:
    """Fused gate on TPU. x: [S, H], gate_w: [H, E]. S must divide by 8."""
    s, h = x.shape
    e, k = cfg.num_experts, cfg.expert_top_k
    px = max(LANE, ((e + LANE - 1) // LANE) * LANE)
    if s % 8:
        raise ValueError(f"token count {s} must be a multiple of 8")
    # largest power-of-two row tile (<= BLOCK_M) dividing S, so any S % 8 == 0
    # token count works without padding
    bm = next(b for b in (128, 64, 32, 16, 8) if s % b == 0)
    w_pad = jnp.zeros((h, px), gate_w.dtype).at[:, :e].set(gate_w)

    grid = (s // bm,)
    top_p, top_i, stats = pl.pallas_call(
        functools.partial(_gate_kernel, k=k, e=e, px=px),
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, h), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((h, px), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((bm, k), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((bm, k), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((8, px), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((s, k), jnp.float32),
            jax.ShapeDtypeStruct((s, k), jnp.int32),
            jax.ShapeDtypeStruct((8, px), jnp.float32),
        ],
        interpret=interpret,
    )(x, w_pad)

    probs_sum = stats[0, :e]
    counts = stats[1, :e].astype(jnp.int32)
    zsum = stats[2, 0]
    return _finish(cfg, top_p, top_i, probs_sum, counts, zsum, s)


# The kernel has no autodiff rule; under AD the fused router runs its
# forward and recomputes the backward through router_xla (identical math).
@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def _router_pallas_ad(x, gate_w, cfg: MoEConfig, interpret: bool):
    return router_pallas(x, gate_w, cfg, interpret=interpret)


def _router_fwd(x, gate_w, cfg, interpret):
    return router_pallas(x, gate_w, cfg, interpret=interpret), (x, gate_w)


def _router_bwd(cfg, interpret, res, ct):
    x, gate_w = res
    _, vjp_fn = jax.vjp(lambda xx, w: router_xla(xx, w, cfg), x, gate_w)
    return vjp_fn(ct)


_router_pallas_ad.defvjp(_router_fwd, _router_bwd)


def gate_vmem_bytes(s: int, h: int, e: int, dtype) -> int:
    """Static VMEM estimate of the fused gate's working set: the weight
    tile [H, PX], the token tile [BM, H], and ~4 [BM, PX]-sized f32
    intermediates (logits, exp, probs, selection mask)."""
    px = max(LANE, ((e + LANE - 1) // LANE) * LANE)
    bm = next(b for b in (128, 64, 32, 16, 8) if s % b == 0) if s % 8 == 0 \
        else 128
    item = jnp.dtype(dtype).itemsize
    return h * px * item + bm * h * item + 4 * bm * px * 4 + 8 * px * 4


# Single-tile gate ceiling: the kernel holds the full padded-E logits tile
# in VMEM, so it serves E up to a few thousand (h=2048 bf16: E <= ~4k).
# Past the budget the router falls back to router_xla — semantically
# identical, and XLA's own tiling IS the two-pass softmax/top-k the
# reference's multi-block ring implements by hand (gate.cuh:93-467).
_GATE_VMEM_BUDGET = 12 * 2**20


def router(x, gate_w, cfg: MoEConfig, use_pallas: bool = True,
           interpret: bool = False) -> RouterOutput:
    """Dispatch to the fused kernel on TPU, XLA fallback elsewhere.
    Differentiable on both paths.  Large-E configs beyond the single-tile
    kernel's VMEM budget (:func:`gate_vmem_bytes`) route to XLA."""
    on_tpu = interpret or jax.default_backend() == "tpu"
    s, h = x.shape
    fits = gate_vmem_bytes(s, h, cfg.num_experts, x.dtype) \
        <= _GATE_VMEM_BUDGET
    if use_pallas and s % 8 == 0 and on_tpu and fits:
        return _router_pallas_ad(x, gate_w, cfg, interpret)
    return router_xla(x, gate_w, cfg)
