"""Tier-0 fault tolerance: in-graph expert-health masking.

The reference has no failure story below the job level — a sick worker's
NaNs flow straight into the combine's atomicAdd and poison every token it
touched (SURVEY §5).  The framework-level answer so far
(:mod:`flashmoe_tpu.runtime.resilient`) aborts the whole step and rewinds
to a checkpoint, which turns one bad expert into a full-step loss of work.

This module is the cheapest rung of the fault-tolerance ladder: detect a
non-finite expert output *inside the compiled graph*, zero that expert's
contribution, and renormalize each affected token's surviving gate
weights.  A token whose experts are all sick degrades to a zero FFN delta
(the residual stream carries it through); every other token keeps an
exact MoE output over its healthy experts.  Everything is ``jnp.where``
arithmetic — jit/vmap-safe, differentiable, no collectives — and only in
the graph when ``MoEConfig.degrade_unhealthy_experts`` is set.

Consumers: :mod:`flashmoe_tpu.ops.moe` (capacity + dropless paths),
:mod:`flashmoe_tpu.parallel.ep`, :mod:`flashmoe_tpu.parallel.fused`, and
:mod:`flashmoe_tpu.parallel.ragged_ep` apply the mask just before their
combine; the masked counts thread into :class:`flashmoe_tpu.ops.stats.
MoEStats` so the flight recorder sees degradation.
"""

from __future__ import annotations

import jax.numpy as jnp


def expert_health_capacity(ybuf) -> jnp.ndarray:
    """[E] bool health of a capacity-format expert output [E, C, H].

    An expert is sick iff ANY of its rows carries a non-finite value —
    the conservative read: one NaN row means the expert's weights or
    transport are corrupt, and its other rows are not to be trusted.
    Unoccupied capacity slots are zero-filled by the dispatch, so they
    can never flag a healthy expert."""
    return jnp.all(jnp.isfinite(ybuf.astype(jnp.float32)), axis=(-2, -1))


def expert_health_tiles(y_rows, tile_gid, num_experts: int,
                        block_m: int) -> jnp.ndarray:
    """[E] bool health of a row-grouped buffer [T_pad, H] whose tiles map
    to experts via ``tile_gid`` [T_pad // block_m] (the ragged/grouped
    FFN layout).  Tail tiles past the populated segments clamp onto the
    last expert but hold zeros — finite, so they never flag it."""
    t = y_rows.shape[0] // block_m
    tile_ok = jnp.all(
        jnp.isfinite(y_rows.astype(jnp.float32)).reshape(t, -1), axis=-1
    )
    healthy = jnp.ones((num_experts,), jnp.int32)
    healthy = healthy.at[tile_gid].min(tile_ok.astype(jnp.int32))
    return healthy.astype(bool)


def expert_health_segments(y_rows, counts) -> jnp.ndarray:
    """[E] bool health of an expert-sorted ragged buffer [N, H] whose
    per-expert row counts are ``counts`` [E] (rows for expert e occupy
    the contiguous segment starting at ``cumsum(counts)[e-1]``).  Rows
    past the populated total are zero padding — finite, harmless even
    though their segment id clamps onto the last expert."""
    n = y_rows.shape[0]
    ends = jnp.cumsum(counts.astype(jnp.int32))
    row_gid = jnp.searchsorted(
        ends, jnp.arange(n, dtype=jnp.int32), side="right"
    ).astype(jnp.int32)
    row_gid = jnp.clip(row_gid, 0, counts.shape[0] - 1)
    row_ok = jnp.all(jnp.isfinite(y_rows.astype(jnp.float32)), axis=-1)
    healthy = jnp.ones((counts.shape[0],), jnp.int32)
    healthy = healthy.at[row_gid].min(row_ok.astype(jnp.int32))
    return healthy.astype(bool)


def sanitize(y):
    """Replace non-finite values with 0 — required before any weighted
    combine of masked outputs, because ``0.0 * nan = nan`` would undo the
    weight masking."""
    return jnp.where(jnp.isfinite(y.astype(jnp.float32)), y,
                     jnp.zeros((), y.dtype))


def mask_combine_weights(combine_weights, expert_idx, healthy, *,
                         renormalize: bool = False):
    """Zero each (token, k) weight whose expert is sick.

    ``renormalize=True`` additionally rescales each token's surviving
    weights to unit sum (needed for combines that do not renormalize
    internally, e.g. :func:`flashmoe_tpu.ops.ragged.ragged_combine`;
    the capacity :func:`flashmoe_tpu.ops.dispatch.combine` renormalizes
    over nonzero weights itself).  A token with no healthy expert keeps
    all-zero weights — its MoE output is exactly zero, never inf/nan.
    """
    keep = healthy[expert_idx]  # [S, K] bool
    w = jnp.where(keep, combine_weights, jnp.zeros((), combine_weights.dtype))
    if renormalize:
        # rescale survivors so each token keeps its ORIGINAL total
        # weight: ratio = sum(w) / sum(kept w).  With every expert
        # healthy the ratio is x/x = 1.0 exactly (IEEE), so the
        # all-healthy fast path stays bit-identical to the unmasked one.
        total = jnp.sum(combine_weights.astype(jnp.float32), axis=-1,
                        keepdims=True)
        kept = jnp.sum(w.astype(jnp.float32), axis=-1, keepdims=True)
        ratio = total / jnp.maximum(kept, 1e-20)
        w = (w.astype(jnp.float32) * ratio).astype(combine_weights.dtype)
    return w


def degradation_stats(healthy, expert_idx):
    """(masked_experts, masked_fraction) f32 scalars for MoEStats:
    the number of sick experts this shard masked, and the fraction of its
    (token, k) assignments whose contribution was zeroed."""
    masked_experts = jnp.sum((~healthy).astype(jnp.float32))
    masked = (~healthy[expert_idx]).astype(jnp.float32)
    return masked_experts, jnp.mean(masked)


def degrade_outputs(ybuf, combine_weights, expert_idx, healthy, *,
                    renormalize: bool = False):
    """The one tier-0 masking sequence every layer applies: sanitize the
    expert outputs, zero the sick experts' combine weights.  Returns
    (ybuf', combine_weights').  ``renormalize`` as in
    :func:`mask_combine_weights` — True for combines that do not
    renormalize internally (the ragged paths)."""
    return (sanitize(ybuf),
            mask_combine_weights(combine_weights, expert_idx, healthy,
                                 renormalize=renormalize))


def attach_degradation(stats, healthy, expert_idx, reduce_axes=None):
    """Fold this shard's degradation counters into a MoEStats tuple.
    With ``reduce_axes`` (inside a shard_map body) the masked-expert
    count psums and the assignment fraction pmeans across ranks — the
    same reduction contract as the rest of the stats."""
    from flashmoe_tpu.ops.stats import with_degradation

    me, mf = degradation_stats(healthy, expert_idx)
    if reduce_axes is not None:
        import jax

        me = jax.lax.psum(me, reduce_axes)
        mf = jax.lax.pmean(mf, reduce_axes)
    return with_degradation(stats, me, mf)
