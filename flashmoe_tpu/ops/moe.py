"""Single-device MoE layer: gate -> dispatch -> grouped FFN -> combine.

This is the TPU equivalent of one launch of the reference's fused kernel
``moe::forward`` (``csrc/include/flashmoe/moe/moe.cuh:71-144``) in the
single-PE case: the same four stages, expressed as a jit-compiled dataflow
that XLA fuses and schedules (the in-kernel OS/scheduler/subscriber machinery
of ``csrc/include/flashmoe/os/`` exists to do dynamic tile scheduling that
the XLA/Pallas pipeline provides natively).

The E==1 degenerate case routes to :func:`dense_ffn`, mirroring the
reference's ``fffn`` kernel fallback (``moe/fffn.cuh:24-167``,
``moe.cuh:174-177``).

The expert-parallel multi-device layer lives in
:mod:`flashmoe_tpu.parallel.ep` and reuses these stages around the
all-to-all.
"""

from __future__ import annotations

import os
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp

from flashmoe_tpu.config import BLOCK_M, MoEConfig
from flashmoe_tpu.models.reference import activation_fn, shared_expert_ffn
from flashmoe_tpu.ops import dispatch as dsp
from flashmoe_tpu.ops import expert as exp
from flashmoe_tpu.ops import ragged as rag
from flashmoe_tpu.ops.gate import router


def _gather_fused(cfg: MoEConfig) -> bool:
    """Whether inference routes through the gather-fused FFN kernel.

    Opt-in (config field, or FLASHMOE_GATHER_FUSED=1) until the kernel has a
    winning stage_bench row on real TPU; the explicit-dispatch path is the
    hardware-validated default (round-2 advisor finding)."""
    if cfg.gather_fused is not None:
        return cfg.gather_fused
    return os.environ.get("FLASHMOE_GATHER_FUSED") == "1"


class MoEOutput(NamedTuple):
    out: jnp.ndarray  # [S, H]
    aux_loss: jnp.ndarray
    z_loss: jnp.ndarray
    expert_counts: jnp.ndarray  # [E]
    # MoEStats (ops/stats.py) when cfg.collect_stats, else None — a None
    # leaf is an empty pytree node, so the default changes no existing
    # sharding spec or custom-VJP structure
    stats: Any = None


def dense_ffn(params, x, cfg: MoEConfig):
    """E==1 dense fallback (the reference's ``fffn`` path)."""
    act = activation_fn(cfg.hidden_act)
    up = jnp.dot(x, params["w_up"][0].astype(x.dtype),
                 preferred_element_type=cfg.accum_dtype)
    up = up + params["b_up"][0].astype(cfg.accum_dtype)
    if cfg.gated_ffn:
        g = jnp.dot(x, params["w_gate"][0].astype(x.dtype),
                    preferred_element_type=cfg.accum_dtype)
        hidden = act(g) * up
    else:
        hidden = act(up)
    down = jnp.dot(hidden.astype(x.dtype), params["w_down"][0].astype(x.dtype),
                   preferred_element_type=cfg.accum_dtype)
    down = down + params["b_down"][0].astype(cfg.accum_dtype)
    return down.astype(x.dtype)


def _moe_layer_impl(params, x, cfg: MoEConfig, use_pallas: bool,
                    capacity: int | None, interpret: bool) -> MoEOutput:
    # quantized expert storage (flashmoe_tpu/quant/): resolve the FFN
    # weights to their dequant-in-compute form — payloads dequantize,
    # full-precision params fake-quant in-graph.  Called
    # UNCONDITIONALLY: with the knob off it returns the dict untouched
    # (bit-identical graph, invariant-engine-proven) but REFUSES a
    # quantized state whose scales would otherwise be silently ignored
    # (code-review finding).
    from flashmoe_tpu import quant as qt

    qerr = (qt.weight_quant_error(params, cfg)
            if cfg.expert_quant is not None and cfg.collect_stats
            else None)
    params = qt.ffn_compute_params(params, cfg)
    r = router(x, params["gate_w"], cfg, use_pallas=use_pallas,
               interpret=interpret)
    s, h = x.shape
    dropless = use_pallas and not cfg.drop_tokens and capacity is None
    stats = None
    if cfg.collect_stats:
        # in-graph routing health (ops/stats.py): pure function of the
        # router outputs + the same capacity constant the dispatch clamps
        # against, so the layer's numerics cannot shift
        from flashmoe_tpu.ops.stats import moe_stats

        stats_cap = None if dropless else (
            capacity if capacity is not None else cfg.capacity_for(s))
        stats = moe_stats(r, cfg, stats_cap)
    degrade = cfg.degrade_unhealthy_experts
    combine_w = r.combine_weights
    if degrade:
        from flashmoe_tpu.ops import health as hlt
    if dropless:
        # dropless: ragged expert-sorted grouping + block-sparse grouped FFN
        # (S*K + E*block rows instead of the capacity path's E*S)
        bm = BLOCK_M if s >= BLOCK_M else max(8, ((s + 7) // 8) * 8)
        plan = rag.make_ragged_plan(r.expert_idx, cfg, bm)
        # identical weight/config tail for both kernel entries, so the
        # training and inference arms cannot drift numerically
        ffn_tail = (
            params["w_up"].astype(cfg.dtype), params["b_up"],
            params["w_down"].astype(cfg.dtype), params["b_down"],
            params.get("w_gate", None) if cfg.gated_ffn else None,
            cfg.hidden_act, cfg.gated_ffn, bm, exp.DEFAULT_BLOCK_I,
            interpret,
        )
        if not cfg.is_training and _gather_fused(cfg):
            # inference: gather fused into the kernel via the plan's
            # inverse map — no [T_pad, H] grouped buffer in HBM
            ybuf = exp.grouped_ffn_tokens_ad(
                x.astype(cfg.dtype), plan.src_tok, plan.tile_gid, *ffn_tail)
        else:
            xbuf = rag.ragged_dispatch(x.astype(cfg.dtype), plan, cfg, bm)
            ybuf = exp.grouped_ffn_ad(xbuf, plan.tile_gid, *ffn_tail)
        if degrade:
            # tier-0 (ops/health.py): ragged_combine does not
            # renormalize, so the mask renormalizes survivors itself
            healthy = hlt.expert_health_tiles(ybuf, plan.tile_gid,
                                              cfg.num_experts, bm)
            ybuf, combine_w = hlt.degrade_outputs(
                ybuf, combine_w, r.expert_idx, healthy, renormalize=True)
        out = rag.ragged_combine(ybuf, plan, combine_w, cfg)
    else:
        # capacity from the ACTUAL token count of this call, not the config's
        # nominal sequence length (callers pass batched shards of any size)
        cap = capacity if capacity is not None else cfg.capacity_for(s)
        plan = dsp.make_plan(r.expert_idx, cfg, cap)
        if use_pallas and not cfg.is_training and _gather_fused(cfg):
            # inference: gather fused into the kernel — the [E, C, H]
            # dispatch buffer never hits HBM (training keeps the explicit
            # dispatch so the fused backward has its residuals)
            ybuf, cap_p = exp.capacity_ffn_gather(
                x.astype(cfg.dtype), plan, cfg, cap, params,
                interpret=interpret)
        else:
            xbuf = dsp.dispatch(x.astype(cfg.dtype), plan, cfg, cap)
            if use_pallas:
                ybuf = exp.capacity_buffer_ffn_ad(xbuf, params, cfg,
                                                  interpret=interpret)
            else:
                ybuf = exp.expert_ffn_dense(xbuf, params, cfg)
            cap_p = cap
        from flashmoe_tpu.chaos import inject as chaos_inject

        if chaos_inject.is_armed("nan_expert"):  # trace-time check only
            ybuf = chaos_inject.poison_expert(ybuf)
        if degrade:
            # tier-0 (ops/health.py): dsp.combine renormalizes the
            # surviving weights itself
            healthy = hlt.expert_health_capacity(ybuf)
            ybuf, combine_w = hlt.degrade_outputs(ybuf, combine_w,
                                                  r.expert_idx, healthy)
        out = dsp.combine(ybuf, plan, combine_w, cfg, cap_p)
    if degrade and stats is not None:
        stats = hlt.attach_degradation(stats, healthy, r.expert_idx)
    if qerr is not None and stats is not None:
        from flashmoe_tpu.ops.stats import with_quant_error

        stats = with_quant_error(stats, qerr)
    if cfg.num_shared_experts:
        out = out + shared_expert_ffn(x.astype(cfg.dtype), params, cfg).astype(
            out.dtype
        )
    return MoEOutput(
        out.astype(cfg.dtype),
        r.aux_loss * cfg.aux_loss_coef,
        r.z_loss,
        r.expert_counts,
        stats,
    )


def moe_layer(params, x, cfg: MoEConfig, *, use_pallas: bool | None = None,
              capacity: int | None = None,
              interpret: bool = False) -> MoEOutput:
    """One MoE layer over a token shard x: [S, H].

    ``use_pallas`` selects the fused Pallas gate + grouped-FFN kernels;
    ``None`` (default) auto-selects: Pallas on TPU (or when ``interpret``),
    XLA elsewhere.  The XLA path is the oracle in tests.  Both paths are
    differentiable: the fused path composes per-component custom VJPs —
    the dominant FFN gradients run through the Pallas backward kernels
    (``grouped_matmul``/``tgmm`` with residuals saved in the forward,
    :mod:`flashmoe_tpu.ops.expert`), while the cheap gate/dispatch/combine
    stages differentiate through XLA.
    """
    if use_pallas is None:
        use_pallas = interpret or jax.default_backend() == "tpu"
    s, h = x.shape
    zero = jnp.zeros((), cfg.accum_dtype)
    if cfg.num_experts == 1:
        out = dense_ffn(params, x, cfg)
        return MoEOutput(out, zero, zero, jnp.full((1,), s, jnp.int32))
    return _moe_layer_impl(params, x, cfg, use_pallas, capacity, interpret)
