"""Dropless MoE: ragged token grouping + block-sparse grouped FFN.

The reference's dropless mode sets expert capacity to the full token count
(``EC = S`` when ``drop_tokens=0``, ``types.cuh:497-499``) and lets its
dynamic tile scheduler process only the ``routedTokens`` actually present
(``SignalPayload.routedTokens``, dispatch clamp at ``packet.cuh:99-206``) —
dense capacity buffers would waste memory and FLOPs, so tile-level dynamism
is the whole point of its in-kernel OS.

The TPU equivalent of that dynamism is *ragged grouping under static
shapes*: sort the (token, k) assignments by expert, pad each expert's
segment up to the row-tile size, and hand the result to the grouped Pallas
FFN kernel whose scalar-prefetched ``tile_gid`` already supports
data-dependent group ids (:func:`flashmoe_tpu.ops.expert.grouped_ffn`).
Pad rows cost at most ``E * (block_m - 1)`` extra rows — tile-level waste,
exactly like the reference's partially-filled final tile per expert — and
no token is ever dropped.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from flashmoe_tpu.config import MoEConfig


class RaggedPlan(NamedTuple):
    """Ragged grouping of (token, k) assignments by expert.

    position:    [S, K] destination row of each assignment in the sorted,
                 segment-padded buffer.
    tile_gid:    [T_pad // block_m] expert id per row tile (dynamic values,
                 static shape).
    counts:      [E] assignments per expert.
    num_rows:    [] total populated+padded rows (<= T_pad, dynamic).
    src_tok:     [T_pad] source token id per buffer row (pad rows point at
                 token 0; they are never read back by combine).
    present:     [T_pad] bool, True for populated rows.
    """

    position: jax.Array
    tile_gid: jax.Array
    counts: jax.Array
    num_rows: jax.Array
    src_tok: jax.Array
    present: jax.Array


def padded_total_rows(cfg: MoEConfig, s: int, block_m: int) -> int:
    """Static upper bound on the grouped buffer: every assignment plus up
    to block_m-1 pad rows per expert."""
    total = s * cfg.expert_top_k + cfg.num_experts * block_m
    return ((total + block_m - 1) // block_m) * block_m


def make_ragged_plan(expert_idx, cfg: MoEConfig, block_m: int) -> RaggedPlan:
    """Compute the expert-sorted, tile-padded layout. Pure integer work.

    One stable argsort powers everything: assignment positions (inverse
    permutation minus segment starts), the per-row source-token index
    plane (the inverse map, derived by locating each buffer row in its
    expert's padded segment — all gathers, no H-wide scatter), and the
    per-tile group ids."""
    s, k = expert_idx.shape
    e = cfg.num_experts
    flat_e = expert_idx.T.reshape(-1)  # k-major (matches capacity priority)
    n = flat_e.shape[0]

    order = jnp.argsort(flat_e, stable=True)
    sorted_e = flat_e[order]
    unpadded_starts = jnp.searchsorted(
        sorted_e, jnp.arange(e, dtype=flat_e.dtype), side="left"
    ).astype(jnp.int32)
    counts = jnp.concatenate(
        [unpadded_starts[1:], jnp.full((1,), n, jnp.int32)]
    ) - unpadded_starts
    padded = ((counts + block_m - 1) // block_m) * block_m
    seg_starts = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), jnp.cumsum(padded)[:-1]]
    )  # [E] padded segment starts

    sorted_pos = jnp.argsort(order).astype(jnp.int32)  # inverse permutation
    rank = sorted_pos - unpadded_starts[flat_e]
    position = (seg_starts[flat_e] + rank).reshape(k, s).T  # [S, K]

    t_pad = padded_total_rows(cfg, s, block_m)
    n_tiles = t_pad // block_m
    tile_starts = jnp.arange(n_tiles, dtype=jnp.int32) * block_m
    seg_ends = seg_starts + padded
    # tile t belongs to expert e iff seg_starts[e] <= t*block_m < seg_ends[e];
    # tail tiles past all segments clamp to the last expert (computed, unread)
    tile_gid = jnp.clip(
        jnp.searchsorted(seg_ends, tile_starts, side="right"), 0, e - 1
    ).astype(jnp.int32)

    # inverse map: which (token, k) assignment feeds each buffer row
    rows = jnp.arange(t_pad, dtype=jnp.int32)
    e_row = jnp.clip(
        jnp.searchsorted(seg_ends, rows, side="right"), 0, e - 1
    ).astype(jnp.int32)
    row_rank = rows - seg_starts[e_row]
    present = row_rank < counts[e_row]
    sorted_idx = unpadded_starts[e_row] + jnp.minimum(
        row_rank, jnp.maximum(counts[e_row] - 1, 0)
    )
    src_tok = jnp.where(
        present, (order[jnp.clip(sorted_idx, 0, n - 1)] % s).astype(
            jnp.int32), 0
    )
    return RaggedPlan(position, tile_gid, counts, seg_ends[-1], src_tok,
                      present)


def ragged_dispatch(x, plan: RaggedPlan, cfg: MoEConfig, block_m: int):
    """Gather tokens into the expert-sorted padded buffer: [T_pad, H].

    Row-gather via the plan's inverse map (``src_tok``).  Note: under
    differentiation the gather's VJP is an H-wide scatter-add back to
    token order, so the dropless TRAINING step still pays one scatter in
    the backward (a wash vs the old scatter-forward formulation); the
    real win is inference, which skips this buffer entirely via the
    gather-fused kernel."""
    buf = jnp.where(plan.present[:, None], x[plan.src_tok], 0)
    return buf.astype(x.dtype)


def ragged_combine(y, plan: RaggedPlan, combine_weights, cfg: MoEConfig):
    """Gather each token's K expert outputs and take the weighted sum."""
    s, k = plan.position.shape
    gathered = y[plan.position.reshape(-1)].reshape(s, k, -1)
    w = combine_weights.astype(jnp.float32)
    return jnp.einsum(
        "skh,sk->sh", gathered.astype(jnp.float32), w,
        preferred_element_type=jnp.float32,
    )
