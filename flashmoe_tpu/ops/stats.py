"""In-graph MoE routing statistics — the flight recorder's data plane.

The reference reads ``%globaltimer`` inside its kernels and wraps every
host phase in NVTX ranges (``csrc/include/flashmoe/telemetry.cuh``)
because distributed-MoE performance lives or dies on runtime state that
is invisible from outside: expert load imbalance, dropped tokens, and
capacity waste.  This module computes those quantities *inside the
compiled graph*, from values the layers already materialize (router
counts, combine weights, the capacity constant) — no extra HBM traffic
beyond a few scalar reductions, jit- and vmap-safe, and entirely absent
from the graph unless ``MoEConfig.collect_stats`` is set.

Consumers: ``ops/moe.py`` / ``parallel/ep.py`` / ``parallel/fused.py`` /
``parallel/ragged_ep.py`` attach a :class:`MoEStats` to their
``MoEOutput``; ``models/transformer.py`` threads per-layer stats into
the loss metrics; ``runtime/trainer.py`` lands them in the flight
recorder (:mod:`flashmoe_tpu.utils.telemetry`), which
``python -m flashmoe_tpu.observe`` summarizes offline.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp

from flashmoe_tpu.config import MoEConfig


class MoEStats(NamedTuple):
    """One MoE layer's routing health, all float32 so every field psums /
    pmeans uniformly across expert-parallel ranks.

    expert_load:          [E] pre-drop (token, k) selections per expert.
    dropped_fraction:     [] fraction of routed assignments dropped at
                          the capacity clamp (0 on dropless paths).
    capacity_utilization: [] kept rows / (E * capacity) buffer slots
                          (1.0 on dropless paths — no fixed buffer).
    imbalance:            [] max over experts / mean — 1.0 is perfectly
                          balanced, E is total collapse onto one expert.
    router_entropy:       [] entropy (nats) of the router's expert
                          distribution: the mean softmax probabilities
                          when the gate reports them, else the empirical
                          selection distribution.  ln(E) = uniform.
    topk_confidence:      [] mean normalized weight of each token's
                          top-1 expert (1.0 = the top expert takes all).
    masked_experts:       [] tier-0 degradation (ops/health.py): sick
                          (non-finite-output) experts masked this step —
                          per-rank contributions summed across ep ranks,
                          0.0 unless ``degrade_unhealthy_experts`` fired.
    masked_fraction:      [] fraction of (token, k) assignments whose
                          expert contribution was zeroed by the tier-0
                          mask (0.0 when every expert is healthy).
    wire_rtq_error:       [] round-trip quantization error of the EP
                          wire-dtype compression (ops/wire.py): mean
                          relative L1 error of encode+decode over the
                          dispatched payload, pmeaned across ranks.
                          0.0 when ``wire_dtype`` is off (or the layer
                          has no exchange).
    wire_rtq_error_dcn:   [] same proxy for the CROSS-SLICE hop's own
                          wire (``MoEConfig.wire_dtype_dcn``) on a
                          two-stage multi-slice exchange: how lossy the
                          fp8-across-DCN hop is on live traffic,
                          separately from the in-slice hop.  0.0 when
                          the DCN override is off or the exchange is
                          flat.
    quant_error:          [] round-trip error proxy of the quantized
                          expert weight store (flashmoe_tpu/quant/,
                          ``MoEConfig.expert_quant``): max over this
                          layer's FFN weight matrices of the store's
                          relative L1 round-trip error.  Real loss on
                          fake-quant runs; ~0 on pre-quantized states
                          (the baked loss lives in the state's quant
                          metadata).  0.0 when expert_quant is off.
    """

    expert_load: jnp.ndarray
    dropped_fraction: jnp.ndarray
    capacity_utilization: jnp.ndarray
    imbalance: jnp.ndarray
    router_entropy: jnp.ndarray
    topk_confidence: jnp.ndarray
    masked_experts: jnp.ndarray
    masked_fraction: jnp.ndarray
    wire_rtq_error: jnp.ndarray
    wire_rtq_error_dcn: jnp.ndarray
    quant_error: jnp.ndarray


def load_imbalance(expert_load) -> jnp.ndarray:
    """max/mean load factor of an [E] load vector (f32 scalar)."""
    load = expert_load.astype(jnp.float32)
    mean = jnp.mean(load, axis=-1)
    return jnp.max(load, axis=-1) / jnp.maximum(mean, 1e-9)


def dist_entropy(weights) -> jnp.ndarray:
    """Entropy (nats) of an unnormalized nonnegative [E] weight vector."""
    w = weights.astype(jnp.float32)
    p = w / jnp.maximum(jnp.sum(w, axis=-1, keepdims=True), 1e-9)
    return -jnp.sum(jnp.where(p > 0, p * jnp.log(jnp.maximum(p, 1e-30)),
                              0.0), axis=-1)


def router_entropy(probs_mean, expert_load) -> jnp.ndarray:
    """Entropy of the router's expert distribution.

    Prefers the gate's mean softmax probabilities; the inference-mode
    tiled gate reports ``probs_mean`` as zeros when its stats pass is
    skipped, in which case the empirical selection distribution stands
    in (a ``where``-select so the choice stays jit-safe)."""
    have_probs = jnp.sum(probs_mean.astype(jnp.float32), axis=-1) > 0
    return jnp.where(have_probs, dist_entropy(probs_mean),
                     dist_entropy(expert_load))


def drop_stats(expert_load, cfg: MoEConfig, capacity: int | None
               ) -> tuple[jnp.ndarray, jnp.ndarray]:
    """(dropped_fraction, capacity_utilization) of an [E] load vector
    against a per-expert ``capacity``; ``None`` = dropless path."""
    load = expert_load.astype(jnp.float32)
    total = jnp.maximum(jnp.sum(load, axis=-1), 1.0)
    if capacity is None:
        zero = jnp.zeros(total.shape, jnp.float32)
        return zero, jnp.ones(total.shape, jnp.float32)
    kept = jnp.sum(jnp.minimum(load, jnp.float32(capacity)), axis=-1)
    dropped = 1.0 - kept / total
    util = kept / jnp.float32(cfg.num_experts * capacity)
    return dropped, util


def moe_stats(router_out, cfg: MoEConfig, capacity: int | None
              ) -> MoEStats:
    """Stats for one token shard from its RouterOutput.

    ``capacity`` is the per-expert buffer size the dispatch will clamp
    against (the same constant :func:`flashmoe_tpu.ops.dispatch.make_plan`
    receives), or ``None`` on dropless paths.  Every output is a pure
    function of the router's existing outputs, so attaching stats can
    never perturb the layer's numerics.
    """
    load = router_out.expert_counts.astype(jnp.float32)
    dropped, util = drop_stats(load, cfg, capacity)
    # combine weights are sorted by the top-k extraction: slot 0 is each
    # token's strongest expert, pre-normalized over the k survivors
    conf = jnp.mean(router_out.combine_weights[..., 0].astype(jnp.float32),
                    axis=-1)
    zero = jnp.zeros(dropped.shape, jnp.float32)
    return MoEStats(
        expert_load=load,
        dropped_fraction=dropped,
        capacity_utilization=util,
        imbalance=load_imbalance(load),
        router_entropy=router_entropy(router_out.probs_mean, load),
        topk_confidence=conf,
        # tier-0 degradation counters: filled in by the layer via
        # with_degradation() after its health check runs (the check needs
        # the expert OUTPUTS, which do not exist yet at routing time)
        masked_experts=zero,
        masked_fraction=zero,
        # wire-compression error: filled in by the EP layers via
        # with_wire_error() once the dispatch payload exists (the
        # _dcn twin covers the cross-slice hop's own wire)
        wire_rtq_error=zero,
        wire_rtq_error_dcn=zero,
        # quantized-weight store error: filled in by the layers via
        # with_quant_error() when expert_quant is on
        quant_error=zero,
    )


def with_degradation(stats: MoEStats, masked_experts,
                     masked_fraction) -> MoEStats:
    """Attach tier-0 degradation counters (ops/health.py) to a stats
    tuple — a plain _replace, split out so layers read declaratively."""
    return stats._replace(
        masked_experts=jnp.asarray(masked_experts, jnp.float32),
        masked_fraction=jnp.asarray(masked_fraction, jnp.float32),
    )


def with_wire_error(stats: MoEStats, wire_rtq_error=None,
                    reduce_axes=None, *, dcn_error=None) -> MoEStats:
    """Attach the wire-compression round-trip error
    (:func:`flashmoe_tpu.ops.wire.roundtrip_error`) to a stats tuple.
    Inside a shard_map body pass ``reduce_axes`` to pmean the per-shard
    proxy across ranks (every rank holds the same token count).
    ``dcn_error`` carries the cross-slice hop's own proxy
    (``wire_rtq_error_dcn``, the ``wire_dtype_dcn`` hop); either side
    may be ``None`` to leave its field untouched."""
    import jax

    def _red(v):
        v = jnp.asarray(v, jnp.float32)
        return (jax.lax.pmean(v, reduce_axes)
                if reduce_axes is not None else v)

    fields = {}
    if wire_rtq_error is not None:
        fields["wire_rtq_error"] = _red(wire_rtq_error)
    if dcn_error is not None:
        fields["wire_rtq_error_dcn"] = _red(dcn_error)
    return stats._replace(**fields) if fields else stats


def with_quant_error(stats: MoEStats, quant_error,
                     reduce_axes=None) -> MoEStats:
    """Attach the quantized-weight round-trip error proxy
    (:func:`flashmoe_tpu.quant.state.weight_quant_error`) to a stats
    tuple.  Inside a shard_map body pass ``reduce_axes`` to pmean the
    per-shard proxy (each rank measures its own expert shard)."""
    import jax

    if quant_error is None:
        return stats
    v = jnp.asarray(quant_error, jnp.float32)
    if reduce_axes is not None:
        v = jax.lax.pmean(v, reduce_axes)
    return stats._replace(quant_error=v)


def reduce_stats(local: MoEStats, probs_mean, reduce_axes) -> MoEStats:
    """Cross-rank reduction of per-shard stats inside a shard_map body.

    The load histogram psums; ratio scalars pmean (every rank holds the
    same token count, so the mean of per-shard fractions is the exact
    global fraction); imbalance and entropy are recomputed from the
    GLOBAL load so a skew concentrated on one rank is never averaged
    away.  Only called when ``cfg.collect_stats`` — the stats-off graph
    contains none of these collectives."""
    import jax

    g_load = jax.lax.psum(local.expert_load, reduce_axes)
    g_probs = jax.lax.pmean(probs_mean.astype(jnp.float32), reduce_axes)
    return MoEStats(
        expert_load=g_load,
        dropped_fraction=jax.lax.pmean(local.dropped_fraction, reduce_axes),
        capacity_utilization=jax.lax.pmean(local.capacity_utilization,
                                           reduce_axes),
        imbalance=load_imbalance(g_load),
        router_entropy=router_entropy(g_probs, g_load),
        topk_confidence=jax.lax.pmean(local.topk_confidence, reduce_axes),
        # tier-0 degradation counters and the wire-error proxy pass
        # through untouched: they are zeros unless their feature flag is
        # on, and the layer reduces them itself in that case — reducing
        # constants here would add collectives to every stats-on graph
        # for nothing
        masked_experts=local.masked_experts,
        masked_fraction=local.masked_fraction,
        wire_rtq_error=local.wire_rtq_error,
        wire_rtq_error_dcn=local.wire_rtq_error_dcn,
        quant_error=local.quant_error,
    )


def stats_to_host(stats: MoEStats) -> dict:
    """One flight-recorder-ready dict (python floats/lists) per layer."""
    import jax
    import numpy as np

    # ONE bulk device->host transfer for the whole tuple — per-field
    # float() calls would each block on their own copy, inflating the
    # very step time the flight recorder is measuring
    host = jax.device_get(stats)
    return {
        "expert_load": np.asarray(host.expert_load,
                                  dtype=np.float64).tolist(),
        "dropped_fraction": float(host.dropped_fraction),
        "capacity_utilization": float(host.capacity_utilization),
        "imbalance": float(host.imbalance),
        "router_entropy": float(host.router_entropy),
        "topk_confidence": float(host.topk_confidence),
        "masked_experts": float(host.masked_experts),
        "masked_fraction": float(host.masked_fraction),
        "wire_rtq_error": float(host.wire_rtq_error),
        "wire_rtq_error_dcn": float(host.wire_rtq_error_dcn),
        "quant_error": float(host.quant_error),
    }


def speculation_summary(records) -> dict:
    """Aggregate speculative-decoding acceptance stats from flight records.

    Host-side consumer twin of ``serving.speculate.spec_stats_fields``:
    the engine folds per-slot counters into ``serve_request`` records and
    per-step ``spec_tokens``/``spec_on`` into step records; this reduces a
    recorder dump (or any iterable of such dicts) back into one summary the
    report surfaces (``observe.py``, loadgen sweeps) can print without
    re-deriving engine internals.
    """
    drafted = 0
    accepted = 0
    requests = 0
    spec_steps = 0
    steps_on = 0
    extra = 0
    morphs = 0
    for rec in records:
        kind = rec.get("kind")
        if kind == "serve_request" and "spec_drafted" in rec:
            requests += 1
            drafted += int(rec.get("spec_drafted") or 0)
            accepted += int(rec.get("spec_accepted") or 0)
        elif kind == "serve_step" and "spec_tokens" in rec:
            if rec.get("spec_on"):
                steps_on += 1
            n = int(rec.get("spec_tokens") or 0)
            if n > 0:
                spec_steps += 1
                extra += n
        elif "controller.spec_morph" in (rec.get("decision"),
                                         rec.get("name")):
            morphs += 1
    rate = (accepted / drafted) if drafted else 0.0
    per_step = 1.0 + extra / spec_steps if spec_steps else 1.0
    return {
        "spec_requests": requests,
        "spec_drafted": drafted,
        "spec_accepted": accepted,
        "accept_rate": round(rate, 6),
        "spec_tokens_per_step": round(per_step, 6),
        "spec_steps": spec_steps,
        "steps_spec_on": steps_on,
        "spec_morphs": morphs,
    }
