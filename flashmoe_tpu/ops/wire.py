"""Wire-dtype compression for the expert-parallel all-to-all payload.

The EP transports (:mod:`flashmoe_tpu.parallel.ep`,
:mod:`flashmoe_tpu.parallel.ragged_ep`) ship every routed token row at
the compute dtype, so the dispatch/combine exchanges — the term the
analytical model says dominates the collective path
(:mod:`flashmoe_tpu.analysis`) — move 2-4x more ICI/DCN bytes than the
tokens need.  This module is the codec those layers apply at the wire
boundary only: rows are quantized immediately before the exchange and
dequantized immediately after, so every compute stage (gate, dispatch
plan, expert FFN, combine) still runs at the compute dtype.

Two wire families, selected by ``MoEConfig.wire_dtype`` /
``MoEConfig.wire_dtype_combine`` (``None`` = off = bit-identical graphs,
the same convention as ``collect_stats`` / ``degrade_unhealthy_experts``):

``bf16``
    A plain dtype cast — halves f32 payloads, no sidecar.  Lossless for
    the ~8 mantissa bits a routed activation keeps anyway through a bf16
    matmul.
``e4m3`` / ``e5m2`` (``jnp.float8_e4m3fn`` / ``jnp.float8_e5m2``)
    Per-token-row symmetric scaling: each row is divided by
    ``amax(|row|) / finfo(fp8).max`` and cast to fp8; the f32 scale rides
    the exchange as a tiny sidecar array (4 bytes per row next to
    ``H * 1`` payload bytes).  e4m3 keeps 3 mantissa bits (better
    resolution, the default for activations); e5m2 keeps the wider
    exponent for combine-side outputs whose dynamic range survived a
    gate-weighted sum.

Numerical contracts (property-tested in ``tests/test_wire.py``):

* zero rows and zero elements survive the round trip exactly;
* scaling a row by ``c > 0`` scales the decoded row by exactly ``c``
  (the fp8 mantissa pattern is scale-invariant);
* a non-finite input row decodes to a non-finite row — NaN poisons the
  scale, Inf drives it to ``inf`` and the payload to ``0 * inf = NaN``
  — so the tier-0 health mask (:mod:`flashmoe_tpu.ops.health`) still
  trips on the far side of an fp8 wire.

Everything here is ``jnp.where``/cast arithmetic: jit-, vmap- and
shard_map-safe, no collectives, no Python-level data dependence.
"""

from __future__ import annotations

import jax.numpy as jnp

# Canonical wire names -> jnp dtypes.  fp8 types are resolved lazily via
# getattr so the module imports (and bf16 wires work) on jax builds that
# predate float8 support; requesting an fp8 wire there is a config-time
# ValueError, not a mid-trace crash.
_FP8_E4M3 = getattr(jnp, "float8_e4m3fn", None)
_FP8_E5M2 = getattr(jnp, "float8_e5m2", None)

_ALIASES = {
    "bf16": "bf16",
    "bfloat16": "bf16",
    "e4m3": "e4m3",
    "float8_e4m3fn": "e4m3",
    "fp8": "e4m3",          # the activation-friendly default fp8
    "e5m2": "e5m2",
    "float8_e5m2": "e5m2",
}

_DTYPES = {
    "bf16": jnp.bfloat16,
    "e4m3": _FP8_E4M3,
    "e5m2": _FP8_E5M2,
}

WIRE_NAMES = tuple(sorted(_ALIASES))


def canonical_name(name: str | None) -> str:
    """Canonical wire name ('bf16' / 'e4m3' / 'e5m2'), or 'off' for
    ``None`` — the spelling measurement keys and bench records use."""
    if name is None:
        return "off"
    key = _ALIASES.get(str(name).lower())
    if key is None:
        raise ValueError(
            f"unknown wire dtype {name!r}; supported: {WIRE_NAMES}")
    return key


def fp8_supported() -> bool:
    """Whether this jax build ships the float8 dtypes."""
    return _FP8_E4M3 is not None and _FP8_E5M2 is not None


def resolve(name: str | None):
    """Wire name -> jnp dtype, or ``None`` for ``None``/'off' (wire off).

    Raises ``ValueError`` for unknown names and for fp8 requests on a
    jax build without float8 dtypes — config validation calls this so
    unsupported wires fail at ``MoEConfig`` construction, never inside
    ``shard_map``."""
    if name is None:
        return None
    key = canonical_name(name)
    if key == "off":
        return None
    dt = _DTYPES[key]
    if dt is None:
        raise ValueError(
            f"wire dtype {name!r} needs float8 support this jax build "
            f"lacks; use wire_dtype='bf16' or None")
    return dt


def is_fp8(wire_dtype) -> bool:
    """True for the scaled fp8 wires (payload rides with a scale
    sidecar); False for plain-cast wires (bf16) and None."""
    if wire_dtype is None:
        return False
    return jnp.dtype(wire_dtype).itemsize == 1


def scale_bytes(wire_dtype) -> int:
    """Per-row sidecar bytes the wire adds next to the payload: 4 (one
    f32 scale) for fp8 wires, 0 otherwise.  The byte model
    (:mod:`flashmoe_tpu.analysis`) and the planner price this."""
    return 4 if is_fp8(wire_dtype) else 0


def payload_row_bytes(wire_dtype, h: int, compute_dtype) -> float:
    """Bytes of ONE token row's wire *payload* (scale sidecar excluded):
    ``H x wire itemsize``, or ``H x compute itemsize`` when the wire is
    off.  ``analysis.wire_row_bytes`` adds :func:`scale_bytes` on top;
    the collective census (:mod:`flashmoe_tpu.staticcheck.census`) needs
    the two terms separately because payload and sidecar ride separate
    ``all_to_all`` eqns in the lowered graph."""
    dt = compute_dtype if wire_dtype is None else wire_dtype
    return float(h * jnp.dtype(dt).itemsize)


def encode(x, wire_dtype):
    """Quantize ``x`` (``[..., H]``, rows on the last axis) for the wire.

    Returns ``(payload, scales)``: ``payload`` has ``x``'s shape at the
    wire dtype; ``scales`` is a ``[...]`` f32 array of per-row factors
    for fp8 wires, ``None`` for plain-cast wires (nothing extra to
    exchange).
    """
    if not is_fp8(wire_dtype):
        return x.astype(wire_dtype), None
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=-1, keepdims=True)
    fmax = jnp.float32(jnp.finfo(wire_dtype).max)
    # All-zero rows keep scale 1.0 (0/1 -> 0 exactly).  A NaN amax skips
    # the where's true-branch (NaN > 0 is False) but the payload cast
    # still carries the NaN elements; an Inf amax makes scale=inf and
    # payload 0/NaN, and the decode's 0 * inf = NaN marks the whole row
    # — either way non-finite rows stay non-finite across the wire.
    scale = jnp.where(amax > 0, amax / fmax, jnp.float32(1.0))
    payload = (xf / scale).astype(wire_dtype)
    return payload, scale[..., 0]


def decode(payload, scales, out_dtype):
    """Invert :func:`encode`: ``(payload, scales)`` -> ``[..., H]`` at
    ``out_dtype``.  ``scales=None`` is the plain-cast arm."""
    if scales is None:
        return payload.astype(out_dtype)
    return (payload.astype(jnp.float32)
            * scales[..., None].astype(jnp.float32)).astype(out_dtype)


def roundtrip(x, wire_dtype):
    """encode+decode without an exchange — what the far side would see."""
    payload, scales = encode(x, wire_dtype)
    return decode(payload, scales, x.dtype)


def roundtrip_error(x, wire_dtype) -> jnp.ndarray:
    """Mean relative L1 quantization error of the wire on ``x`` (f32
    scalar): ``sum|x - rt(x)| / (sum|x| + eps)``.  The in-graph proxy
    ``MoEStats.wire_rtq_error`` reports so the flight recorder sees how
    lossy the wire is on live traffic (0.0 when the wire is off)."""
    xf = x.astype(jnp.float32)
    rt = roundtrip(xf, wire_dtype).astype(jnp.float32)
    num = jnp.sum(jnp.abs(xf - rt))
    den = jnp.sum(jnp.abs(xf)) + jnp.float32(1e-9)
    return (num / den).astype(jnp.float32)
