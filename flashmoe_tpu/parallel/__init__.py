"""Parallelism: meshes, expert-parallel layers, placement, collectives."""
