"""Loader for the native (C++) runtime components.

Builds ``csrc/*.cpp`` into ``libflashmoe_native.so`` on demand (g++, cached
under ``csrc/build/``) and exposes the C ABI through ctypes.  Every native
entry point has a pure-Python fallback, so the framework works without a
toolchain; when the library is present the native path is preferred and
cross-validated by tests.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_CSRC = os.path.join(_ROOT, "csrc")
_BUILD = os.path.join(_CSRC, "build")
_LIB = os.path.join(_BUILD, "libflashmoe_native.so")
_ABI_VERSION = 1

_lock = threading.Lock()
_lib = None
_tried = False


def _sources():
    return sorted(
        os.path.join(_CSRC, f)
        for f in os.listdir(_CSRC)
        if f.endswith(".cpp")
    ) if os.path.isdir(_CSRC) else []


def build(force: bool = False) -> str | None:
    """Compile the native library; returns its path or None."""
    srcs = _sources()
    if not srcs:
        return None
    os.makedirs(_BUILD, exist_ok=True)
    if not force and os.path.exists(_LIB):
        newest = max(os.path.getmtime(s) for s in srcs)
        if os.path.getmtime(_LIB) >= newest:
            return _LIB
    cmd = ["g++", "-O2", "-shared", "-fPIC", "-std=c++17", "-o", _LIB, *srcs]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
    except (subprocess.CalledProcessError, FileNotFoundError,
            subprocess.TimeoutExpired):
        return None
    return _LIB


def load():
    """Load (building if needed) the native library; None if unavailable."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        path = build()
        if path is None:
            return None
        try:
            lib = ctypes.CDLL(path)
            if lib.flashmoe_native_abi_version() != _ABI_VERSION:
                return None
            lib.flashmoe_decide.restype = ctypes.c_int
            lib.flashmoe_decide.argtypes = [
                ctypes.c_int,
                np.ctypeslib.ndpointer(np.float64, flags="C"),
                np.ctypeslib.ndpointer(np.float64, flags="C"),
                np.ctypeslib.ndpointer(np.float64, flags="C"),
                np.ctypeslib.ndpointer(np.float64, flags="C"),
                ctypes.c_int, ctypes.c_double, ctypes.c_double,
                ctypes.c_double, ctypes.c_double, ctypes.c_int,
                np.ctypeslib.ndpointer(np.int32, flags="C"),
                np.ctypeslib.ndpointer(np.int32, flags="C"),
            ]
            _lib = lib
        except OSError:
            return None
        return _lib


def native_decide(alpha, beta, throughput, memory_gb, num_experts,
                  expert_mb, act_mb, grad_mb, gamma, is_training):
    """Run the C++ decider. Returns (group_ids [n], expert_counts [n]) or
    None when the native library is unavailable."""
    lib = load()
    if lib is None:
        return None
    n = alpha.shape[0]
    alpha = np.ascontiguousarray(alpha, np.float64)
    beta = np.ascontiguousarray(beta, np.float64)
    thr = np.ascontiguousarray(throughput, np.float64)
    mem = np.ascontiguousarray(memory_gb, np.float64)
    gid = np.zeros((n,), np.int32)
    cnt = np.zeros((n,), np.int32)
    rc = lib.flashmoe_decide(
        n, alpha, beta, thr, mem, int(num_experts), float(expert_mb),
        float(act_mb), float(grad_mb), float(gamma), int(bool(is_training)),
        gid, cnt,
    )
    if rc != 0:
        return None
    return gid, cnt
