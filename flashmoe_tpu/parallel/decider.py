"""Decider: topology-aware DP x EP group formation and expert placement.

Python re-design of the reference's host-side placement optimizer
(``csrc/include/flashmoe/os/decider/decider.cuh:34-329``), with the same
capability envelope:

  * **group formation** — partition the world into parallelism groups by
    greedy hierarchical merging over the alpha-beta adjacency matrix
    (Kruskal-flavored, union-find with path compression, candidate edges
    sorted by p2p transfer time; ``decider.cuh:29-30``).  A merge is
    accepted iff the merged group's objective does not exceed the max of
    its parts' (``os/decider/functions.cuh:34-45``).
  * **objective** — gamma * (compute/rate + eta * intra-group comm) + the
    inter-group gradient-allreduce time in training mode
    (``functions.cuh:20-26``), with the ring model ``2 (G-1) * bottleneck``
    priced from the ACTUAL worst external edge, maintained across merges
    in a priority queue (``decider.cuh:60, 86-158``); inference jobs use
    the no-allreduce specialization (``decider.cuh:177-268``).
  * **memory feasibility** — groups that cannot hold the full expert set
    must keep merging (``decider.cuh:50-55, 120-155``).
  * **expert assignment** — within a group, experts are partitioned across
    devices proportionally to processing rate over a cost-sorted multiset
    (``decider.cuh:273-329``).

On a homogeneous single-slice torus this collapses to one group with a
uniform round-robin placement (the reference's unused ``imposeStrategy``,
``bootstrap.cuh:35-52``) — the machinery earns its keep on multi-slice
(DCN-connected) or heterogeneous jobs, which is why it stays host-side
Python: it runs once at bootstrap, never on device.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from flashmoe_tpu.config import MoEConfig
from flashmoe_tpu.parallel.topology import Adjacency, WorkerAttr


# ----------------------------------------------------------------------
# Cost model (functions.cuh equivalents)
# ----------------------------------------------------------------------

@dataclasses.dataclass
class CostArgs:
    """Inputs to the group objective (the reference's ``ObjArgs``/``ARArgs``,
    ``os/decider/comps/args.cuh:17-89``)."""

    total_expert_cost_ms: float     # all experts, one device-unit of rate
    comm_mbytes: float              # per-step intra-group activation traffic
    grad_buffer_mb: float           # gradient buffer for the allreduce
    gamma: float = 1.0              # pipeline stages (num_layers/moe_freq)
    eta: float = 1.0                # comm weight


def ring_allreduce_ms(grad_mb: float, group_sizes, bottleneck_beta: float,
                      bottleneck_alpha: float = 0.0) -> float:
    """2(G-1)/G * buffer over the bottleneck inter-group edge (Sanders et
    al. ring model, as priced in ``functions.cuh:28-32``)."""
    g = len(group_sizes) if hasattr(group_sizes, "__len__") else group_sizes
    if g <= 1:
        return 0.0
    return 2.0 * (g - 1) * (
        bottleneck_alpha + (grad_mb / g) * bottleneck_beta
    )


def group_objective(members, rates, intra_comm_ms: float, args: CostArgs,
                    allreduce_ms: float = 0.0) -> float:
    """Objective of one group (``functions.cuh:20-26``): time to process all
    experts split across the group, plus weighted intra-group comm, plus the
    inter-group allreduce when training."""
    rate = sum(rates[m] for m in members)
    compute = args.total_expert_cost_ms / max(rate, 1e-9)
    return args.gamma * (compute + args.eta * intra_comm_ms) + allreduce_ms


# ----------------------------------------------------------------------
# Union-find
# ----------------------------------------------------------------------

class _DSU:
    def __init__(self, n):
        self.parent = list(range(n))

    def find(self, x):
        while self.parent[x] != x:
            self.parent[x] = self.parent[self.parent[x]]  # path halving
            x = self.parent[x]
        return x

    def union(self, a, b):
        ra, rb = self.find(a), self.find(b)
        if ra != rb:
            self.parent[rb] = ra
        return ra


# ----------------------------------------------------------------------
# Decider
# ----------------------------------------------------------------------

@dataclasses.dataclass
class Placement:
    """Result: parallelism groups + expert->device assignment.

    groups:        list of device-id lists (each an EP group; groups
                   replicate, i.e. are the DP dimension)
    expert_owner:  [E] device id owning each expert (within each group the
                   same logical assignment maps to that group's devices)
    local_experts: device id -> list of expert ids
    """

    groups: list
    expert_owner: dict
    local_experts: dict


def _intra_comm_ms(members, adj: Adjacency, mbytes: float) -> float:
    """Worst pairwise transfer inside the group — the dispatch/combine
    bottleneck edge.  The payload each peer exchanges shrinks as the group
    grows (the all-to-all slab is 1/|G| of the activations), mirroring the
    reference's ``evalP2PTime`` with ``p2pBuffer / numNodes``
    (``os/decider/comps/group.cuh``)."""
    n = max(len(members), 1)
    worst = 0.0
    for i in members:
        for j in members:
            if i != j:
                worst = max(worst, adj.transfer_ms(i, j, mbytes / n))
    return worst


def _placement_from_native(group_ids, counts, n: int, e: int) -> Placement:
    """Build a Placement from the C++ decider's (group_id, counts) arrays:
    expert ids are assigned contiguously per group in device order, matching
    the Python implementation."""
    import collections

    by_group = collections.defaultdict(list)
    for d in range(n):
        by_group[int(group_ids[d])].append(d)
    groups = [sorted(by_group[g]) for g in sorted(by_group)]
    expert_owner: dict[int, int] = {}
    local_experts: dict[int, list[int]] = {d: [] for d in range(n)}
    for gi, group in enumerate(groups):
        eid = 0
        for d in group:
            for _ in range(int(counts[d])):
                if gi == 0:
                    expert_owner[eid] = d
                local_experts[d].append(eid)
                eid += 1
    return Placement(groups, expert_owner, local_experts)


def decide(adj: Adjacency, workers: list[WorkerAttr], cfg: MoEConfig,
           expert_mb: float | None = None,
           native: str | bool = "auto",
           price_mode: str = "bottleneck") -> Placement:
    """Form DP x EP groups and assign experts (the reference's
    ``Decider<JobType>::operator()`` + ``assign``).

    Training mode prices the inter-group gradient allreduce with the
    ACTUAL bottleneck external edge, maintained in a max-heap across
    merges exactly as the reference's ``externalEdges`` priority queue
    (``decider.cuh:60, 86-130``): edges that become intra-group leave the
    pool, so the priced bottleneck improves as slow links are absorbed
    into groups — and, crucially, the allreduce term DIFFERS between the
    merged and unmerged sides of each comparison (fewer groups and a
    possibly different bottleneck edge), so it can decide merges.
    ``price_mode="max_beta"`` keeps the round-2 global-max-β model for
    comparison (tests show it groups worse).  Inference jobs
    (``cfg.is_training=False``) use the reference's specialization with
    no allreduce term at all (``decider.cuh:177-268``).

    ``native``: "auto" prefers the C++ implementation
    (:mod:`flashmoe_tpu.parallel._native`) when it builds/loads, True
    requires it, False forces pure Python.
    """
    import heapq

    n = adj.n
    e = cfg.num_experts
    import jax.numpy as jnp

    h, i_sz = cfg.hidden_size, cfg.intermediate_size
    bytes_per = jnp.dtype(cfg.param_dtype).itemsize
    expert_mb = expert_mb if expert_mb is not None else (
        2 * h * i_sz * bytes_per / 1e6
    )
    act_mb = cfg.tokens * h * bytes_per / 1e6
    grad_mb = cfg.param_count * bytes_per / 1e6 if cfg.is_training else 0.0

    rates = [w.throughput for w in workers]
    gamma = max(1, cfg.num_layers // max(1, cfg.moe_frequency))
    args = CostArgs(
        total_expert_cost_ms=e / max(min(rates), 1e-9),
        comm_mbytes=act_mb,
        grad_buffer_mb=grad_mb,
        gamma=gamma,
    )

    if native != False and price_mode == "bottleneck":  # noqa: E712
        from flashmoe_tpu.parallel import _native

        res = _native.native_decide(
            adj.alpha, adj.beta,
            np.array(rates, np.float64),
            np.array([w.memory_gb for w in workers], np.float64),
            e, expert_mb, act_mb, grad_mb, gamma, cfg.is_training,
        )
        if res is not None:
            return _placement_from_native(res[0], res[1], n, e)
        if native is True:
            raise RuntimeError("native decider unavailable (g++/build failed)")

    def can_hold_all(members) -> bool:
        cap = sum(workers[m].memory_gb for m in members) * 1024.0  # MB
        return cap >= e * expert_mb

    dsu = _DSU(n)
    members = {d: [d] for d in range(n)}
    training = cfg.is_training and grad_mb > 0

    def obj(mem, ar_ms) -> float:
        # memory-infeasible groups price at infinity, which is exactly the
        # reference's must-merge encoding (functions.cuh obj(): inf when
        # groupMemCapacity < totalExpertMemoryDemand; optimizingPolicy
        # accepts any merge between two infinite sides)
        if not can_hold_all(mem):
            return float("inf")
        intra = _intra_comm_ms(mem, adj, act_mb)
        return group_objective(mem, rates, intra, args, ar_ms)

    # --- inter-group allreduce bottleneck: max-heap of external edges ---
    # keyed by the edge's per-chunk gradient transfer time (the reference's
    # ARArgs::bottleneck); heapq is a min-heap, so negate.
    def bot_time(i, j):
        return adj.transfer_ms(i, j, grad_mb / max(n, 1))

    ext: list = []
    if training and price_mode == "bottleneck":
        ext = [(-bot_time(i, j), i, j)
               for i in range(n) for j in range(n) if i != j]
        heapq.heapify(ext)
    max_beta = float(np.max(adj.beta)) if n > 1 else 0.0

    def groups_now():
        return len({dsu.find(x) for x in range(n)})

    def ar_terms(ra, rb):
        """(ar_parts, ar_merged): the allreduce price before/after the
        hypothetical merge of roots ra+rb.  Pops permanently-intra edges;
        edges that the merge would internalize go to ``limbo`` and are
        re-pushed only if the merge is rejected (decider.cuh:96-158)."""
        g = groups_now()
        if not training:
            return 0.0, 0.0, []
        if price_mode == "max_beta":
            # legacy: same bottleneck both sides, only G differs
            return (ring_allreduce_ms(grad_mb, g, max_beta),
                    ring_allreduce_ms(grad_mb, max(g - 1, 1), max_beta),
                    [])
        limbo = []
        while ext:
            key, i, j = ext[0]
            fi, fj = dsu.find(i), dsu.find(j)
            if fi == fj:
                heapq.heappop(ext)          # intra forever: discard
                continue
            if {fi, fj} == {ra, rb}:
                limbo.append(heapq.heappop(ext))  # internal iff merged
                continue
            break
        # bottleneck for the CURRENT partition includes limbo edges.
        # Heap ORDER is fixed at the initial chunk grad_mb/n, but the
        # VALUE is repriced with the chunk of the current partition —
        # grad_mb/g now, grad_mb/(g-1) post-merge — mirroring the
        # reference's ARArgs::refresh, which re-derives bottleneckTime
        # from the live group count before every objective evaluation
        # (args.cuh:37, decider.cuh:96-158).  Without the refresh the
        # term is underpriced as merges shrink the partition (advisor
        # round-3 finding).
        cand = ext[:1] + limbo
        cur_bot = max(
            (adj.transfer_ms(i, j, grad_mb / g) for _, i, j in cand),
            default=0.0,
        )
        ar_parts = 2.0 * (g - 1) * cur_bot if g > 1 else 0.0
        post_bot = (adj.transfer_ms(ext[0][1], ext[0][2],
                                    grad_mb / (g - 1))
                    if ext and g - 1 > 1 else 0.0)
        ar_merged = 2.0 * (g - 2) * post_bot if g - 1 > 1 else 0.0
        return ar_parts, ar_merged, limbo

    # candidate edges sorted by p2p transfer time of one activation buffer
    edges = sorted(
        ((adj.transfer_ms(i, j, act_mb), i, j)
         for i in range(n) for j in range(i + 1, n)),
        key=lambda t: t[0],
    )

    for _, a, b in edges:
        ra, rb = dsu.find(a), dsu.find(b)
        if ra == rb:
            continue
        ga, gb = members[ra], members[rb]
        merged = ga + gb
        ar_parts, ar_merged, limbo = ar_terms(ra, rb)
        o1, o2 = obj(ga, ar_parts), obj(gb, ar_parts)
        om = obj(merged, ar_merged)
        both_inf = o1 == float("inf") and o2 == float("inf")
        if both_inf or om <= max(o1, o2):
            root = dsu.union(ra, rb)
            other = rb if root == ra else ra
            members[root] = merged
            del members[other]
            # limbo edges became intra-group: stay out of the pool
        else:
            for item in limbo:
                heapq.heappush(ext, item)

    # any still-infeasible group merges into its cheapest feasible neighbor
    changed = True
    while changed and len(members) > 1:
        changed = False
        for root, mem in list(members.items()):
            if not can_hold_all(mem):
                best, cost = None, float("inf")
                for r2, m2 in members.items():
                    if r2 == root:
                        continue
                    c = min(
                        adj.transfer_ms(x, y, act_mb)
                        for x in mem for y in m2
                    )
                    if c < cost:
                        best, cost = r2, c
                if best is not None:
                    merged = members[root] + members[best]
                    nr = dsu.union(root, best)
                    other = best if nr == root else root
                    members[nr] = merged
                    if other in members:
                        del members[other]
                    changed = True
                    break

    groups = sorted(members.values(), key=lambda g: sorted(g)[0])
    groups = [sorted(g) for g in groups]

    # --- expert assignment within each group (decider.cuh:273-329) ---
    expert_owner: dict[int, int] = {}
    local_experts: dict[int, list[int]] = {d: [] for d in range(n)}
    for group in groups:
        grates = np.array([rates[d] for d in group], dtype=np.float64)
        budgets = np.floor(e * grates / grates.sum()).astype(int)
        # distribute the remainder to the fastest devices
        rem = e - budgets.sum()
        order = np.argsort(-grates)
        for k in range(rem):
            budgets[order[k % len(group)]] += 1
        eid = 0
        for d_idx, d in enumerate(group):
            for _ in range(budgets[d_idx]):
                if group is groups[0]:
                    expert_owner[eid] = d
                local_experts[d].append(eid)
                eid += 1
    return Placement(groups, expert_owner, local_experts)


def uniform_placement(n_devices: int, cfg: MoEConfig) -> Placement:
    """Round-robin contiguous placement (the reference's ``imposeStrategy``,
    ``bootstrap.cuh:35-52``) — optimal on a homogeneous torus."""
    e = cfg.num_experts
    per = e // n_devices if e >= n_devices else 1
    local = {d: [] for d in range(n_devices)}
    owner = {}
    for eid in range(e):
        d = min(eid // max(per, 1), n_devices - 1)
        owner[eid] = d
        local[d].append(eid)
    return Placement([list(range(n_devices))], owner, local)
