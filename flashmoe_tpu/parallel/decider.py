"""Decider: topology-aware DP x EP group formation and expert placement.

Python re-design of the reference's host-side placement optimizer
(``csrc/include/flashmoe/os/decider/decider.cuh:34-329``), with the same
capability envelope:

  * **group formation** — partition the world into parallelism groups by
    greedy hierarchical merging over the alpha-beta adjacency matrix
    (Kruskal-flavored, union-find with path compression, candidate edges
    sorted by p2p transfer time; ``decider.cuh:29-30``).  A merge is
    accepted iff the merged group's objective does not exceed the max of
    its parts' (``os/decider/functions.cuh:34-45``).
  * **objective** — gamma * (compute/rate + eta * intra-group comm) + the
    inter-group gradient-allreduce time in training mode
    (``functions.cuh:20-26``), with the ring model ``2 (G-1) * bottleneck``
    priced from the ACTUAL worst external edge, maintained across merges
    in a priority queue (``decider.cuh:60, 86-158``); inference jobs use
    the no-allreduce specialization (``decider.cuh:177-268``).
  * **memory feasibility** — groups that cannot hold the full expert set
    must keep merging (``decider.cuh:50-55, 120-155``).
  * **expert assignment** — within a group, experts are partitioned across
    devices proportionally to processing rate over a cost-sorted multiset
    (``decider.cuh:273-329``).

On a homogeneous single-slice torus this collapses to one group with a
uniform round-robin placement (the reference's unused ``imposeStrategy``,
``bootstrap.cuh:35-52``) — the machinery earns its keep on multi-slice
(DCN-connected) or heterogeneous jobs, which is why it stays host-side
Python: it runs once at bootstrap, never on device.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from flashmoe_tpu.config import MoEConfig
from flashmoe_tpu.parallel.topology import Adjacency, WorkerAttr


# ----------------------------------------------------------------------
# Cost model (functions.cuh equivalents)
# ----------------------------------------------------------------------

@dataclasses.dataclass
class CostArgs:
    """Inputs to the group objective (the reference's ``ObjArgs``/``ARArgs``,
    ``os/decider/comps/args.cuh:17-89``)."""

    total_expert_cost_ms: float     # all experts, one device-unit of rate
    comm_mbytes: float              # per-step intra-group activation traffic
    grad_buffer_mb: float           # gradient buffer for the allreduce
    gamma: float = 1.0              # pipeline stages (num_layers/moe_freq)
    eta: float = 1.0                # comm weight


def ring_allreduce_ms(grad_mb: float, group_sizes, bottleneck_beta: float,
                      bottleneck_alpha: float = 0.0) -> float:
    """2(G-1)/G * buffer over the bottleneck inter-group edge (Sanders et
    al. ring model, as priced in ``functions.cuh:28-32``)."""
    g = len(group_sizes) if hasattr(group_sizes, "__len__") else group_sizes
    if g <= 1:
        return 0.0
    return 2.0 * (g - 1) * (
        bottleneck_alpha + (grad_mb / g) * bottleneck_beta
    )


def group_objective(members, rates, intra_comm_ms: float, args: CostArgs,
                    allreduce_ms: float = 0.0) -> float:
    """Objective of one group (``functions.cuh:20-26``): time to process all
    experts split across the group, plus weighted intra-group comm, plus the
    inter-group allreduce when training."""
    rate = sum(rates[m] for m in members)
    compute = args.total_expert_cost_ms / max(rate, 1e-9)
    return args.gamma * (compute + args.eta * intra_comm_ms) + allreduce_ms


# ----------------------------------------------------------------------
# Union-find
# ----------------------------------------------------------------------

class _DSU:
    def __init__(self, n):
        self.parent = list(range(n))

    def find(self, x):
        while self.parent[x] != x:
            self.parent[x] = self.parent[self.parent[x]]  # path halving
            x = self.parent[x]
        return x

    def union(self, a, b):
        ra, rb = self.find(a), self.find(b)
        if ra != rb:
            self.parent[rb] = ra
        return ra


# ----------------------------------------------------------------------
# Decider
# ----------------------------------------------------------------------

@dataclasses.dataclass
class Placement:
    """Result: parallelism groups + expert->device assignment.

    groups:        list of device-id lists (each an EP group; groups
                   replicate, i.e. are the DP dimension)
    expert_owner:  [E] device id owning each expert (within each group the
                   same logical assignment maps to that group's devices)
    local_experts: device id -> list of expert ids
    replicas:      hot-expert replication map.  From :func:`decide`:
                   expert id -> extra device ids also hosting a copy.
                   From :func:`rebalance_placement` (the equal-slot
                   runtime projection): hot SLOT -> victim SLOTs whose
                   ~dead experts are evicted to carry the copy (the
                   ``MoEConfig.expert_replicas`` encoding).  Empty when
                   no expert is replicated.
    """

    groups: list
    expert_owner: dict
    local_experts: dict
    replicas: dict = dataclasses.field(default_factory=dict)


def _intra_comm_ms(members, adj: Adjacency, mbytes: float) -> float:
    """Worst pairwise transfer inside the group — the dispatch/combine
    bottleneck edge.  The payload each peer exchanges shrinks as the group
    grows (the all-to-all slab is 1/|G| of the activations), mirroring the
    reference's ``evalP2PTime`` with ``p2pBuffer / numNodes``
    (``os/decider/comps/group.cuh``)."""
    n = max(len(members), 1)
    worst = 0.0
    for i in members:
        for j in members:
            if i != j:
                worst = max(worst, adj.transfer_ms(i, j, mbytes / n))
    return worst


def _placement_from_native(group_ids, counts, n: int, e: int) -> Placement:
    """Build a Placement from the C++ decider's (group_id, counts) arrays:
    expert ids are assigned contiguously per group in device order, matching
    the Python implementation."""
    import collections

    by_group = collections.defaultdict(list)
    for d in range(n):
        by_group[int(group_ids[d])].append(d)
    groups = [sorted(by_group[g]) for g in sorted(by_group)]
    expert_owner: dict[int, int] = {}
    local_experts: dict[int, list[int]] = {d: [] for d in range(n)}
    for gi, group in enumerate(groups):
        eid = 0
        for d in group:
            for _ in range(int(counts[d])):
                if gi == 0:
                    expert_owner[eid] = d
                local_experts[d].append(eid)
                eid += 1
    return Placement(groups, expert_owner, local_experts)


def assign_experts(group: list, rates, e: int,
                   expert_costs=None) -> dict:
    """Partition ``e`` experts across one group's devices proportionally
    to processing rate (``decider.cuh:273-329``).

    ``expert_costs=None`` keeps the contiguous rate-proportional budget
    split (uniform experts).  With per-expert costs — the controller's
    observed load histogram, the reference's cost-sorted multiset — the
    assignment is the greedy makespan heuristic over that multiset:
    experts sorted by cost descending (ties: lower id first), each
    placed on the device with the smallest projected finish time
    ``(assigned_cost + cost) / rate`` (ties: lower device id).  Both
    arms are fully deterministic: identical inputs yield the identical
    assignment (the stability property the runtime controller leans on
    — a re-plan from unchanged telemetry must be a no-op).

    Returns device id -> list of expert ids (sorted ascending).
    """
    out: dict[int, list[int]] = {d: [] for d in group}
    if expert_costs is None:
        grates = np.array([rates[d] for d in group], dtype=np.float64)
        budgets = np.floor(e * grates / grates.sum()).astype(int)
        # distribute the remainder to the fastest devices
        rem = e - budgets.sum()
        order = np.argsort(-grates, kind="stable")
        for k in range(rem):
            budgets[order[k % len(group)]] += 1
        eid = 0
        for d_idx, d in enumerate(group):
            for _ in range(budgets[d_idx]):
                out[d].append(eid)
                eid += 1
        return out
    costs = np.asarray(expert_costs, dtype=np.float64)
    if costs.shape != (e,):
        raise ValueError(
            f"expert_costs must have shape ({e},), got {costs.shape}")
    assigned = {d: 0.0 for d in group}
    # cost-sorted multiset, heaviest first; ties broken by expert id so
    # the order (and therefore the placement) is reproducible
    for eid in sorted(range(e), key=lambda i: (-costs[i], i)):
        d = min(group,
                key=lambda dd: ((assigned[dd] + costs[eid])
                                / max(rates[dd], 1e-9), dd))
        out[d].append(eid)
        assigned[d] += costs[eid]
    for d in group:
        out[d].sort()
    return out


def assign_experts_sliced(group: list, rates, e: int, slice_of,
                          expert_costs, pair: int = 2) -> dict:
    """Slice-aware cost-sorted assignment for a group whose devices
    span DCN-connected slices (ISSUE 13: the Decider output maps
    experts to SLICES, not just devices).

    Two levels, both deterministic:

    1. **experts -> slices.**  Each slice gets a rate-proportional
       expert budget (floor + remainder to the fastest slices, the
       :func:`assign_experts` uniform arm at slice granularity).
       Experts are then placed cost-sorted in PAIRS of ``pair``
       (default 2 = ``expert_top_k`` routing companions: the experts a
       token's top-k selection sends traffic to together): each pair
       lands whole on the slice with the smallest projected finish
       time ``(load + pair cost) / slice rate`` among slices with
       budget left.  Hot companions therefore co-locate inside one
       slice — a token routed to both crosses DCN at most once on
       dispatch and its combine rides the aggregated per-slice-pair
       message — while the pair-at-a-time greedy keeps the slices
       load-balanced (packing all hot experts on one slice would just
       move the bottleneck).
    2. **experts -> devices within a slice.**  The greedy makespan
       heuristic of :func:`assign_experts` over that slice's expert
       subset and its own devices.

    Returns device id -> sorted expert ids, the :func:`assign_experts`
    contract."""
    costs = np.asarray(expert_costs, dtype=np.float64)
    if costs.shape != (e,):
        raise ValueError(
            f"expert_costs must have shape ({e},), got {costs.shape}")
    by_slice: dict = {}
    for d in group:
        by_slice.setdefault(slice_of[d], []).append(d)
    sids = sorted(by_slice)
    if len(sids) < 2:
        return assign_experts(group, rates, e, expert_costs=expert_costs)
    srate = np.array([sum(rates[d] for d in by_slice[s]) for s in sids],
                     dtype=np.float64)
    budgets = np.floor(e * srate / srate.sum()).astype(int)
    rem = e - budgets.sum()
    order = np.argsort(-srate, kind="stable")
    for k in range(int(rem)):
        budgets[order[k % len(sids)]] += 1

    slice_experts: dict = {s: [] for s in sids}
    load = {s: 0.0 for s in sids}
    left = {s: int(budgets[i]) for i, s in enumerate(sids)}
    ranked = sorted(range(e), key=lambda i: (-costs[i], i))
    for lo in range(0, e, max(pair, 1)):
        chunk = ranked[lo:lo + max(pair, 1)]
        # slices that can hold the whole pair keep companions together;
        # the tail (budget fragmentation) falls back to any free slot
        fits = [s for s in sids if left[s] >= len(chunk)]
        cands = fits or [s for s in sids if left[s] > 0]
        csum = sum(costs[i] for i in chunk)
        tgt = min(cands,
                  key=lambda s: ((load[s] + csum) / max(srate[sids.index(s)], 1e-9), s))
        for eid in chunk:
            if left[tgt] <= 0:
                tgt = min((s for s in sids if left[s] > 0),
                          key=lambda s: ((load[s] + costs[eid])
                                         / max(srate[sids.index(s)],
                                               1e-9), s))
            slice_experts[tgt].append(eid)
            load[tgt] += costs[eid]
            left[tgt] -= 1

    out: dict[int, list[int]] = {d: [] for d in group}
    for s in sids:
        devs = sorted(by_slice[s])
        assigned = {d: 0.0 for d in devs}
        for eid in sorted(slice_experts[s],
                          key=lambda i: (-costs[i], i)):
            d = min(devs,
                    key=lambda dd: ((assigned[dd] + costs[eid])
                                    / max(rates[dd], 1e-9), dd))
            out[d].append(eid)
            assigned[d] += costs[eid]
    for d in group:
        out[d].sort()
    return out


def _replicate_hot(group: list, rates, per_device: dict, costs,
                   spare_slots: int) -> dict:
    """Replicate the costliest experts onto extra devices while spare
    memory slots remain AND each copy improves the group's projected
    makespan ``max(assigned/rate)``.  Returns expert -> extra device
    ids; ``per_device`` is extended in place."""
    replicas: dict[int, list[int]] = {}
    if spare_slots <= 0 or len(group) < 2:
        return replicas
    costs = np.asarray(costs, dtype=np.float64)
    assigned = {d: sum(costs[e] for e in per_device[d]) for d in group}
    # every copy must improve the makespan, so the loop terminates on
    # its own; the cap just bounds pathological memory-rich groups
    for _ in range(min(spare_slots, len(costs) * (len(group) - 1))):
        # the bottleneck device's costliest expert is the candidate
        bot = max(group, key=lambda d: (assigned[d] / max(rates[d], 1e-9),
                                        d))
        cands = [e for e in per_device[bot]
                 if e not in replicas or bot not in replicas[e]]
        if not cands:
            return replicas
        hot = max(cands, key=lambda e: (costs[e], -e))
        hosts = {d for d in group if hot in per_device[d]}
        free = [d for d in group if d not in hosts]
        if not free:
            return replicas
        # splitting the hot expert's cost evenly across its copies:
        # place the new copy where the post-split makespan is smallest
        n_copies = len(hosts) + 1
        share = costs[hot] / n_copies
        best, best_makespan = None, None
        for d in free:
            proj = dict(assigned)
            for h in hosts:
                proj[h] -= costs[hot] / len(hosts) - share
            proj[d] += share
            mk = max(proj[x] / max(rates[x], 1e-9) for x in group)
            if best_makespan is None or (mk, d) < (best_makespan, best):
                best, best_makespan = d, mk
        cur = max(assigned[x] / max(rates[x], 1e-9) for x in group)
        if best is None or best_makespan >= cur:
            return replicas  # no copy helps: capacity stays unspent
        for h in hosts:
            assigned[h] -= costs[hot] / len(hosts) - share
        assigned[best] += share
        per_device[best].append(hot)
        per_device[best].sort()
        replicas.setdefault(hot, []).append(best)
    return replicas


def decide(adj: Adjacency, workers: list[WorkerAttr], cfg: MoEConfig,
           expert_mb: float | None = None,
           native: str | bool = "auto",
           price_mode: str = "bottleneck",
           expert_costs=None, replicate: bool = False,
           slice_of=None) -> Placement:
    """Form DP x EP groups and assign experts (the reference's
    ``Decider<JobType>::operator()`` + ``assign``).

    Training mode prices the inter-group gradient allreduce with the
    ACTUAL bottleneck external edge, maintained in a max-heap across
    merges exactly as the reference's ``externalEdges`` priority queue
    (``decider.cuh:60, 86-130``): edges that become intra-group leave the
    pool, so the priced bottleneck improves as slow links are absorbed
    into groups — and, crucially, the allreduce term DIFFERS between the
    merged and unmerged sides of each comparison (fewer groups and a
    possibly different bottleneck edge), so it can decide merges.
    ``price_mode="max_beta"`` keeps the round-2 global-max-β model for
    comparison (tests show it groups worse).  Inference jobs
    (``cfg.is_training=False``) use the reference's specialization with
    no allreduce term at all (``decider.cuh:177-268``).

    ``native``: "auto" prefers the C++ implementation
    (:mod:`flashmoe_tpu.parallel._native`) when it builds/loads, True
    requires it, False forces pure Python.

    ``expert_costs``: observed per-expert processing cost ([E], any
    positive unit — the runtime controller feeds its load-histogram
    EMA).  Switches the within-group assignment from the contiguous
    uniform split to the reference's cost-sorted multiset
    (:func:`assign_experts`), so a hot expert lands with cheap
    neighbors and a slow device receives the cold tail.  ``replicate``
    additionally copies bottleneck experts onto extra devices while
    group memory capacity allows AND each copy improves the projected
    makespan (``Placement.replicas``).  Both are host-side only and
    force the pure-Python path (the C++ decider predates them).

    ``slice_of``: per-device slice membership (``topology.
    device_slice_ids``).  With ``expert_costs`` given, groups spanning
    more than one slice assign their experts through
    :func:`assign_experts_sliced` — hot top-k companion pairs
    co-locate inside a slice so the DCN hop carries the aggregated
    minimum (ISSUE 13).  Without costs the uniform split is
    slice-agnostic and nothing changes.
    """
    import heapq

    n = adj.n
    e = cfg.num_experts
    import jax.numpy as jnp

    h, i_sz = cfg.hidden_size, cfg.intermediate_size
    bytes_per = jnp.dtype(cfg.param_dtype).itemsize
    expert_mb = expert_mb if expert_mb is not None else (
        2 * h * i_sz * bytes_per / 1e6
    )
    act_mb = cfg.tokens * h * bytes_per / 1e6
    grad_mb = cfg.param_count * bytes_per / 1e6 if cfg.is_training else 0.0

    rates = [w.throughput for w in workers]
    gamma = max(1, cfg.num_layers // max(1, cfg.moe_frequency))
    args = CostArgs(
        total_expert_cost_ms=e / max(min(rates), 1e-9),
        comm_mbytes=act_mb,
        grad_buffer_mb=grad_mb,
        gamma=gamma,
    )

    if (native != False and price_mode == "bottleneck"  # noqa: E712
            and expert_costs is None and not replicate):
        from flashmoe_tpu.parallel import _native

        res = _native.native_decide(
            adj.alpha, adj.beta,
            np.array(rates, np.float64),
            np.array([w.memory_gb for w in workers], np.float64),
            e, expert_mb, act_mb, grad_mb, gamma, cfg.is_training,
        )
        if res is not None:
            return _placement_from_native(res[0], res[1], n, e)
        if native is True:
            raise RuntimeError("native decider unavailable (g++/build failed)")

    def can_hold_all(members) -> bool:
        cap = sum(workers[m].memory_gb for m in members) * 1024.0  # MB
        return cap >= e * expert_mb

    dsu = _DSU(n)
    members = {d: [d] for d in range(n)}
    training = cfg.is_training and grad_mb > 0

    def obj(mem, ar_ms) -> float:
        # memory-infeasible groups price at infinity, which is exactly the
        # reference's must-merge encoding (functions.cuh obj(): inf when
        # groupMemCapacity < totalExpertMemoryDemand; optimizingPolicy
        # accepts any merge between two infinite sides)
        if not can_hold_all(mem):
            return float("inf")
        intra = _intra_comm_ms(mem, adj, act_mb)
        return group_objective(mem, rates, intra, args, ar_ms)

    # --- inter-group allreduce bottleneck: max-heap of external edges ---
    # keyed by the edge's per-chunk gradient transfer time (the reference's
    # ARArgs::bottleneck); heapq is a min-heap, so negate.
    def bot_time(i, j):
        return adj.transfer_ms(i, j, grad_mb / max(n, 1))

    ext: list = []
    if training and price_mode == "bottleneck":
        ext = [(-bot_time(i, j), i, j)
               for i in range(n) for j in range(n) if i != j]
        heapq.heapify(ext)
    max_beta = float(np.max(adj.beta)) if n > 1 else 0.0

    def groups_now():
        return len({dsu.find(x) for x in range(n)})

    def ar_terms(ra, rb):
        """(ar_parts, ar_merged): the allreduce price before/after the
        hypothetical merge of roots ra+rb.  Pops permanently-intra edges;
        edges that the merge would internalize go to ``limbo`` and are
        re-pushed only if the merge is rejected (decider.cuh:96-158)."""
        g = groups_now()
        if not training:
            return 0.0, 0.0, []
        if price_mode == "max_beta":
            # legacy: same bottleneck both sides, only G differs
            return (ring_allreduce_ms(grad_mb, g, max_beta),
                    ring_allreduce_ms(grad_mb, max(g - 1, 1), max_beta),
                    [])
        limbo = []
        while ext:
            key, i, j = ext[0]
            fi, fj = dsu.find(i), dsu.find(j)
            if fi == fj:
                heapq.heappop(ext)          # intra forever: discard
                continue
            if {fi, fj} == {ra, rb}:
                limbo.append(heapq.heappop(ext))  # internal iff merged
                continue
            break
        # bottleneck for the CURRENT partition includes limbo edges.
        # Heap ORDER is fixed at the initial chunk grad_mb/n, but the
        # VALUE is repriced with the chunk of the current partition —
        # grad_mb/g now, grad_mb/(g-1) post-merge — mirroring the
        # reference's ARArgs::refresh, which re-derives bottleneckTime
        # from the live group count before every objective evaluation
        # (args.cuh:37, decider.cuh:96-158).  Without the refresh the
        # term is underpriced as merges shrink the partition (advisor
        # round-3 finding).
        cand = ext[:1] + limbo
        cur_bot = max(
            (adj.transfer_ms(i, j, grad_mb / g) for _, i, j in cand),
            default=0.0,
        )
        ar_parts = 2.0 * (g - 1) * cur_bot if g > 1 else 0.0
        post_bot = (adj.transfer_ms(ext[0][1], ext[0][2],
                                    grad_mb / (g - 1))
                    if ext and g - 1 > 1 else 0.0)
        ar_merged = 2.0 * (g - 2) * post_bot if g - 1 > 1 else 0.0
        return ar_parts, ar_merged, limbo

    # candidate edges sorted by p2p transfer time of one activation buffer
    edges = sorted(
        ((adj.transfer_ms(i, j, act_mb), i, j)
         for i in range(n) for j in range(i + 1, n)),
        key=lambda t: t[0],
    )

    for _, a, b in edges:
        ra, rb = dsu.find(a), dsu.find(b)
        if ra == rb:
            continue
        ga, gb = members[ra], members[rb]
        merged = ga + gb
        ar_parts, ar_merged, limbo = ar_terms(ra, rb)
        o1, o2 = obj(ga, ar_parts), obj(gb, ar_parts)
        om = obj(merged, ar_merged)
        both_inf = o1 == float("inf") and o2 == float("inf")
        if both_inf or om <= max(o1, o2):
            root = dsu.union(ra, rb)
            other = rb if root == ra else ra
            members[root] = merged
            del members[other]
            # limbo edges became intra-group: stay out of the pool
        else:
            for item in limbo:
                heapq.heappush(ext, item)

    # any still-infeasible group merges into its cheapest feasible neighbor
    changed = True
    while changed and len(members) > 1:
        changed = False
        for root, mem in list(members.items()):
            if not can_hold_all(mem):
                best, cost = None, float("inf")
                for r2, m2 in members.items():
                    if r2 == root:
                        continue
                    c = min(
                        adj.transfer_ms(x, y, act_mb)
                        for x in mem for y in m2
                    )
                    if c < cost:
                        best, cost = r2, c
                if best is not None:
                    merged = members[root] + members[best]
                    nr = dsu.union(root, best)
                    other = best if nr == root else root
                    members[nr] = merged
                    if other in members:
                        del members[other]
                    changed = True
                    break

    groups = sorted(members.values(), key=lambda g: sorted(g)[0])
    groups = [sorted(g) for g in groups]

    # --- expert assignment within each group (decider.cuh:273-329) ---
    expert_owner: dict[int, int] = {}
    local_experts: dict[int, list[int]] = {d: [] for d in range(n)}
    replicas: dict[int, list[int]] = {}
    for group in groups:
        spans_slices = (slice_of is not None
                        and len({slice_of[d] for d in group}) > 1)
        if expert_costs is not None and spans_slices:
            per_device = assign_experts_sliced(group, rates, e,
                                               slice_of, expert_costs,
                                               pair=cfg.expert_top_k)
        else:
            per_device = assign_experts(group, rates, e,
                                        expert_costs=expert_costs)
        if replicate and expert_costs is not None:
            cap_mb = sum(workers[d].memory_gb for d in group) * 1024.0
            spare = int(cap_mb // expert_mb) - e if expert_mb > 0 else 0
            reps = _replicate_hot(group, rates, per_device,
                                  expert_costs, spare)
            if group is groups[0]:
                replicas = reps
        for d in group:
            local_experts[d] = list(per_device[d])
            if group is groups[0]:
                for eid in per_device[d]:
                    if eid not in expert_owner:
                        expert_owner[eid] = d
    return Placement(groups, expert_owner, local_experts,
                     replicas=replicas)


def rebalance_placement(loads, n_devices: int, cfg: MoEConfig, *,
                        rates=None, replicate: bool = False,
                        cold_eps: float = 1e-3,
                        hot_min: float | None = None) -> Placement:
    """Equal-slot projection of :func:`decide`'s rate-proportional
    assignment for a RUNNING job: re-place the current physical expert
    slots across devices from their *observed* load histogram.

    The live EP layers shard experts uniformly (``num_experts // ep``
    contiguous slots per rank), so a mid-job re-placement cannot change
    per-device slot counts — only WHICH experts fill which slots.  This
    is the cost-sorted multiset of :func:`assign_experts` under that
    slot constraint: slots sorted by observed load descending (ties:
    lower slot id), each assigned to the device with the smallest
    projected finish time ``(load + l) / rate`` among devices with free
    slots.  Deterministic: identical (loads, rates) produce the
    identical placement.

    ``loads``: [E] observed per-slot load (the controller's MoEStats
    EMA).  ``rates``: per-device throughput (default uniform) — a slow
    device then receives the cold tail.  ``replicate``: while a ~dead
    slot exists (load share < ``cold_eps``), the hottest slot (share >
    ``hot_min``, default ``2/E``) is replicated onto it when splitting
    improves the projected makespan; the pair lands in
    ``Placement.replicas`` as {hot_slot: [victim_slot, ...]} — the
    :attr:`flashmoe_tpu.config.MoEConfig.expert_replicas` encoding
    (victim evicted, its slot overwritten with the hot expert's
    weights).

    Returns a single-group :class:`Placement` whose ``local_experts[d]``
    lists the OLD slot ids device ``d``'s new block holds — i.e. the
    permutation ``perm[new_slot] = old_slot`` read off block by block.
    """
    e = cfg.num_experts
    loads = np.asarray(loads, dtype=np.float64)
    if loads.shape != (e,):
        raise ValueError(f"loads must have shape ({e},), "
                         f"got {loads.shape}")
    if n_devices < 1 or e % n_devices:
        raise ValueError(
            f"n_devices={n_devices} must divide num_experts={e} "
            f"(the uniform EP shard's slot constraint)")
    nlx = e // n_devices
    rates = (np.ones(n_devices) if rates is None
             else np.asarray(rates, dtype=np.float64))
    if rates.shape != (n_devices,):
        raise ValueError(f"rates must have shape ({n_devices},), "
                         f"got {rates.shape}")

    assigned = [0.0] * n_devices
    slots_left = [nlx] * n_devices
    per_device: dict[int, list[int]] = {d: [] for d in range(n_devices)}
    for s in sorted(range(e), key=lambda i: (-loads[i], i)):
        free = [d for d in range(n_devices) if slots_left[d]]
        d = min(free, key=lambda dd: ((assigned[dd] + loads[s])
                                      / max(rates[dd], 1e-9), dd))
        per_device[d].append(s)
        assigned[d] += loads[s]
        slots_left[d] -= 1
    for d in per_device:
        per_device[d].sort()

    expert_owner = {s: d for d in per_device for s in per_device[d]}
    placement = Placement([list(range(n_devices))], expert_owner,
                          per_device)

    if replicate:
        total = float(loads.sum())
        if total > 0:
            share = loads / total
            hot_min = (2.0 / e) if hot_min is None else hot_min
            # new-slot index of each old slot under the permutation
            perm = [s for d in range(n_devices) for s in per_device[d]]
            new_of = {old: i for i, old in enumerate(perm)}
            hot = int(np.argmax(loads))
            dead = [s for s in range(e)
                    if share[s] < cold_eps and s != hot]
            if dead and share[hot] > hot_min:
                # split helps iff moving half the hot load onto some
                # dead slot's device lowers the bottleneck finish time;
                # pick the victim whose device benefits most
                dh = expert_owner[hot]
                before = max(assigned[d] / max(rates[d], 1e-9)
                             for d in range(n_devices))
                best, best_after = None, before
                for cold in dead:
                    dc = expert_owner[cold]
                    if dc == dh:
                        continue
                    proj = list(assigned)
                    proj[dh] -= loads[hot] / 2
                    proj[dc] += loads[hot] / 2
                    after = max(proj[d] / max(rates[d], 1e-9)
                                for d in range(n_devices))
                    if after < best_after:
                        best, best_after = cold, after
                if best is not None:
                    placement.replicas = {new_of[hot]: [new_of[best]]}
    return placement


def placement_permutation(placement: Placement) -> tuple:
    """``perm[new_slot] = old_slot`` for an equal-slot single-group
    placement (:func:`rebalance_placement`): device blocks concatenated
    in device order."""
    group = placement.groups[0]
    return tuple(s for d in group for s in placement.local_experts[d])


def uniform_placement(n_devices: int, cfg: MoEConfig) -> Placement:
    """Round-robin contiguous placement (the reference's ``imposeStrategy``,
    ``bootstrap.cuh:35-52``) — optimal on a homogeneous torus."""
    e = cfg.num_experts
    per = e // n_devices if e >= n_devices else 1
    local = {d: [] for d in range(n_devices)}
    owner = {}
    for eid in range(e):
        d = min(eid // max(per, 1), n_devices - 1)
        owner[eid] = d
        local[d].append(eid)
    return Placement([list(range(n_devices))], owner, local)
