"""Expert-parallel MoE layer: shard_map + all-to-all over the TPU mesh.

TPU-native re-design of the reference's distributed core: there, the gate's
``tokenIds`` compaction feeds ``packet::dispatch`` which writes each expert's
tokens straight into peer GPUs' symmetric-heap cells with NVSHMEM
put-with-signal (``csrc/include/flashmoe/os/packet.cuh:20-286``), expert FFNs
run as scheduled tiles, and results return by the same transport before a
scatter-add combine (``os/processor/processor.cuh:711-767``).

Here the same movement is an SPMD program over the ``ep`` mesh axis:

  1. every rank routes its local token shard (full-E routing decisions),
  2. scatters tokens into a capacity-padded ``[E, C_loc, H]`` buffer,
  3. ``jax.lax.all_to_all`` over ``ep`` exchanges expert-major slabs —
     XLA lowers this to ICI-optimal transfers (the analogue of the
     NVSHMEM heap cells being sliced per (peer, expert-slot, capacity),
     ``types.cuh:1014-1032``),
  4. local experts run the grouped FFN on ``[nLx, D*C_loc, H]``,
  5. the reverse all-to-all returns results and each rank combines its own
     tokens with deterministic weighted gathers.

Compute/communication overlap — the reference's headline trick — is XLA's
latency-hiding scheduler's job at this level (it overlaps the all-to-all
with surrounding compute); the fused Pallas path in
:mod:`flashmoe_tpu.parallel.fused` goes further with device-initiated
remote DMA inside the kernel.

Both exchanges optionally compress their payload to a narrow wire dtype
(``MoEConfig.wire_dtype`` / ``wire_dtype_combine`` —
:mod:`flashmoe_tpu.ops.wire`): rows quantize just before the a2a and
dequantize just after, halving (bf16) or quartering (fp8 + f32 per-row
scale sidecar) the ICI/DCN bytes while every compute stage stays at the
compute dtype.  Off by default; the wire-off graph is bit-identical.
On a multi-slice (two-stage) exchange, ``MoEConfig.wire_dtype_dcn``
additionally re-encodes the CROSS-SLICE hop at its own (narrower)
dtype — fp8 across DCN while the in-slice ICI hop stays bf16/f32 —
on both legs; default None inherits the leg wire (graph-identical to
the single-dtype build).

With ``MoEConfig.a2a_chunks = n`` the exchange additionally runs as a
chunked software pipeline (Comet, arXiv 2502.19811): the ``[D, nLx, C,
H]`` slab splits into ``n`` chunks along the local-expert axis and each
chunk runs its own dispatch-a2a -> expert-FFN -> combine-a2a chain.
The ``n`` chains are independent in the graph (unrolled, no carried
state), so XLA's latency-hiding scheduler can issue chunk ``k+1``'s
all-to-all while chunk ``k``'s GEMMs occupy the MXU — on both legs,
for the flat and the hierarchical exchange, with the wire codec
encoding/decoding per chunk inside the pipeline.  ``None`` (default)
keeps the serial single-slab schedule bit-identical to previous
builds; the planner prices the pipeline and picks ``n`` under
``moe_backend='auto'`` (:mod:`flashmoe_tpu.planner`).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from flashmoe_tpu.config import MoEConfig
from flashmoe_tpu.utils.compat import axis_size, shard_map
from flashmoe_tpu.models.reference import shared_expert_ffn
from flashmoe_tpu.ops import dispatch as dsp
from flashmoe_tpu.ops import expert as exp
from flashmoe_tpu.ops import stats as st
from flashmoe_tpu.ops import wire as wr
from flashmoe_tpu.ops.gate import router
from flashmoe_tpu.ops.moe import MoEOutput, dense_ffn
from flashmoe_tpu.profiler import spans as prof
from flashmoe_tpu.utils.telemetry import trace_span


#: reduction collectives one EP-layer forward traces to, knobs off: the
#: aux-loss pmean, the z-loss pmean, and the expert-count psum (pmean
#: lowers to psum + div).  A contract constant, not documentation:
#: ``analysis.comm_census`` expects exactly this many psum eqns and the
#: collective census (:mod:`flashmoe_tpu.staticcheck.census`) fails CI
#: when the traced graph disagrees — add a reduction, update this, and
#: the census diff shows the new collective was priced on purpose.
EXPECTED_PSUMS = 3


def local_capacity(cfg: MoEConfig, s_local: int) -> int:
    """Per-(rank, expert) capacity over a local token shard (EC formula of
    ``types.cuh:497-499`` applied shard-locally)."""
    return cfg.capacity_for(s_local)


def _hierarchical_a2a(t, axis: str, d: int, inner: int, *, reverse: bool):
    """Two-stage all-to-all over a (outer x inner) factorization of the ep
    axis — the multi-slice pattern: the inner stage rides ICI within a
    slice, the outer stage sends one aggregated message per slice pair
    over DCN instead of ``inner**2`` small ones (the ICI-vs-DCN duality of
    the reference's P2P-vs-IBGDA transports, ``bootstrap.cuh:442-446``).

    t: [D, ...] dest-major slabs (rank = outer * inner + inner_idx).
    Returns [D, ...] source-major, identical to a flat all_to_all.
    Composed from :func:`_hier_stage` (one definition of the group
    structure) so the per-hop wire path can never drift from it.
    """
    stages = ["inner", "outer"]
    if reverse:
        stages = stages[::-1]
    for stage in stages:
        t = _hier_stage(t, axis, d, inner, stage=stage)
    return t


def _hier_stage(t, axis: str, d: int, inner: int, *, stage: str):
    """ONE hop of the two-stage exchange on a ``[D, ...]`` dest-major
    array: ``stage='inner'`` is the within-slice ICI exchange,
    ``stage='outer'`` the cross-slice DCN exchange.  Composing
    inner-then-outer (or the reverse) reproduces
    :func:`_hierarchical_a2a` exactly; the split exists so the per-hop
    wire codec (``MoEConfig.wire_dtype_dcn``) can re-encode at the hop
    boundary."""
    outer = d // inner
    rest = t.shape[1:]
    t = t.reshape((outer, inner) + rest)
    if stage == "inner":
        ax = 1
        groups = [[o * inner + i for i in range(inner)]
                  for o in range(outer)]
    else:
        ax = 0
        groups = [[o * inner + j for o in range(outer)]
                  for j in range(inner)]
    t = jax.lax.all_to_all(
        t, axis, split_axis=ax, concat_axis=ax, tiled=False,
        axis_index_groups=groups,
    )
    return t.reshape((d,) + rest)


def _staged_wired(t, wire_dtype, axis: str, d: int, inner: int, *,
                  stage: str):
    """One hierarchical hop with its own wire: encode at ``wire_dtype``
    (None = raw), exchange payload (+fp8 scale sidecar) over that hop
    only, decode back to the compute dtype before the next hop."""
    if wire_dtype is None:
        return _hier_stage(t, axis, d, inner, stage=stage)
    payload, scales = wr.encode(t, wire_dtype)
    payload = _hier_stage(payload, axis, d, inner, stage=stage)
    if scales is not None:
        scales = _hier_stage(scales, axis, d, inner, stage=stage)
    return wr.decode(payload, scales, t.dtype)


def _exchange(t, axis: str, d: int, dcn_inner: int | None, *,
              reverse: bool):
    """One a2a hop of a ``[D, ...]`` dest-major array: the two-stage
    ICI+DCN decomposition when a slice blocking is known, the flat
    ``all_to_all`` otherwise.  Shape-generic so the wire codec's payload
    and scale sidecar ride the identical route."""
    if dcn_inner is not None and 1 < dcn_inner < d:
        return _hierarchical_a2a(t, axis, d, dcn_inner, reverse=reverse)
    return jax.lax.all_to_all(
        t, axis, split_axis=0, concat_axis=0, tiled=False,
    )


def _wired_exchange(t, wire_dtype, axis: str, d: int,
                    dcn_inner: int | None, *, reverse: bool,
                    wire_dcn=None):
    """Exchange ``t`` ([D, ..., H], rows on the last axis), quantized to
    ``wire_dtype`` for the wire only (``None`` = raw — the graph is then
    exactly the pre-compression one).  For fp8 wires the per-row f32
    scales ride the same (flat or hierarchical) route as the payload, so
    both hops of the two-stage exchange stay consistent.

    ``wire_dcn`` (resolved ``MoEConfig.wire_dtype_dcn``): a distinct
    wire for the CROSS-SLICE hop of the hierarchical exchange.  None
    inherits ``wire_dtype`` — one encode covers both hops and the graph
    is byte-identical to the single-dtype build (the default path
    below, unchanged).  Set (and a slice blocking active), each hop
    encodes independently: the ICI stage at the leg wire, the DCN stage
    at ``wire_dcn`` — so e.g. an fp8 DCN hop under a raw/bf16 in-slice
    hop.  Inert on the flat exchange (no DCN hop exists)."""
    hier = dcn_inner is not None and 1 < dcn_inner < d
    if wire_dcn is not None and hier:
        stages = [("inner", wire_dtype), ("outer", wire_dcn)]
        if reverse:
            stages = stages[::-1]
        for stage, wd in stages:
            t = _staged_wired(t, wd, axis, d, dcn_inner, stage=stage)
        return t
    if wire_dtype is None:
        return _exchange(t, axis, d, dcn_inner, reverse=reverse)
    payload, scales = wr.encode(t, wire_dtype)
    payload = _exchange(payload, axis, d, dcn_inner, reverse=reverse)
    if scales is not None:
        scales = _exchange(scales, axis, d, dcn_inner, reverse=reverse)
    return wr.decode(payload, scales, t.dtype)


def _ep_moe_shard(params, x, cfg: MoEConfig, *, axis: str, use_pallas: bool,
                  reduce_axes: tuple[str, ...] = ("ep",),
                  tp_axis: str | None = None,
                  dcn_inner: int | None = None,
                  interpret: bool = False,
                  skip_exchange: bool = False):
    """Per-rank body (runs inside shard_map over the ep axis).

    x: [S_loc, H] local tokens; params: expert weights sharded on axis 0
    (leading dim nLx), gate replicated.  With ``tp_axis``, each expert's
    intermediate dimension is additionally Megatron-split across tp ranks
    (column-parallel up/gate, row-parallel down, one psum per FFN).

    ``skip_exchange`` elides both all-to-alls while keeping every other
    stage and shape identical — the compute-only leg of the overlap-
    efficiency measurement (:mod:`flashmoe_tpu.parallel.overlap`); the
    result is numerically meaningless (tokens meet the wrong experts).
    """
    d = axis_size(axis)
    s_loc, h = x.shape
    e, nlx = cfg.num_experts, cfg.num_experts // d
    cap = local_capacity(cfg, s_loc)
    # quantized expert storage (flashmoe_tpu/quant/): resolve this
    # rank's FFN weight shard to its dequant-in-compute form before
    # any slicing/exchange logic sees it — payloads (and their _qscale
    # siblings, sharded P('ep') like everything else) dequantize here;
    # full-precision params fake-quant in-graph.  Called
    # UNCONDITIONALLY: off returns the dict untouched (bit-identical
    # graph) but a quantized state under a quant-off config is refused
    # instead of matmuling raw payloads (code-review finding).
    from flashmoe_tpu import quant as qt

    quant_err = (qt.weight_quant_error(params, cfg)
                 if cfg.expert_quant is not None and cfg.collect_stats
                 else None)
    params = qt.ffn_compute_params(params, cfg)
    wire_disp = wr.resolve(cfg.wire_dtype)
    wire_comb = wr.resolve(cfg.wire_dtype_combine)
    # the DCN-hop override only exists on a two-stage exchange; resolve
    # it to None otherwise so the flat transport traces the identical
    # graph whatever the knob says (it has no DCN hop to re-encode)
    hier_on = dcn_inner is not None and 1 < dcn_inner < d
    wire_dcn = wr.resolve(cfg.wire_dtype_dcn) if hier_on else None

    # phase spans mirror the reference's NVTX "Flashmoe" domain
    # (telemetry.cuh): named HLO scopes so xprof traces show gate /
    # dispatch / a2a / expert / combine as distinct phases.  Pure
    # metadata — no ops added, the stats-off graph is unchanged.  With
    # cfg.profile_phases the spans additionally fence (prof.fence:
    # block_until_ready on concrete eager values, a no-op on tracers),
    # so a host-armed PhaseTimeline measures real per-phase wall time
    # — the xprof-free phase timeline of flashmoe_tpu/profiler.
    with trace_span("moe.gate"):
        r = router(x, params["gate_w"], cfg, use_pallas=use_pallas,
                   interpret=interpret)
        if cfg.profile_phases:
            prof.fence(r)
    with trace_span("moe.dispatch"):
        plan = dsp.make_plan(r.expert_idx, cfg, cap)
        xbuf = dsp.dispatch(x.astype(cfg.dtype), plan, cfg, cap)  # [E, C, H]
        if cfg.profile_phases:
            prof.fence(xbuf)

    from flashmoe_tpu.chaos import inject as chaos_inject

    ffn_params = params
    if tp_axis is not None:
        # row-parallel down bias: each tp rank contributes 1/tp of it so
        # the psum reconstructs it exactly once
        tp = axis_size(tp_axis)
        ffn_params = dict(params, b_down=params["b_down"] / tp)

    def ffn(buf, p):
        """Expert FFN on a [nE, D*C, H] buffer with nE-leading params —
        one definition for the serial slab and every pipeline chunk."""
        if use_pallas:
            y = exp.capacity_buffer_ffn_ad(buf, p, cfg, interpret)
        else:
            y = exp.expert_ffn_dense(buf, p, cfg)
        if tp_axis is not None:
            y = jax.lax.psum(y, tp_axis)
        return y

    n_chunks = cfg.a2a_chunks or 1
    if n_chunks > 1 and nlx % n_chunks:
        raise ValueError(
            f"a2a_chunks={n_chunks} does not divide the local-expert "
            f"axis (num_experts={e} // ep={d} = {nlx}); pick a divisor "
            f"or leave a2a_chunks=None for the serial schedule")

    # exchange expert-major slabs: [E, C, H] -> [D, nLx, C, H] received
    wire_err = None
    dcn_err = None
    send = xbuf.reshape(d, nlx, cap, h)
    if cfg.collect_stats and wire_disp is not None:
        # round-trip error proxy on the payload actually shipped —
        # stats-gated, so the stats-off graph carries no extra pass
        wire_err = wr.roundtrip_error(send, wire_disp)
    if cfg.collect_stats and wire_dcn is not None:
        # per-hop proxy for the DCN stage's own wire (wire_dtype_dcn):
        # the same send payload quantized at the cross-slice dtype, so
        # the flight recorder sees each hop's loss separately
        dcn_err = wr.roundtrip_error(send, wire_dcn)

    if n_chunks > 1:
        # Chunked double-buffered pipeline (Comet, arXiv 2502.19811):
        # n independent dispatch-a2a -> FFN -> combine-a2a chains over
        # local-expert sub-slabs.  Unrolled on purpose — no carried
        # state between chunks, so the latency-hiding scheduler is free
        # to run chunk k+1's exchange under chunk k's GEMMs.  Per-chunk
        # trace spans make pipeline occupancy visible in xprof.
        ffn_keys = ("w_up", "w_gate", "b_up", "w_down", "b_down")
        comb_err = None
        nc = nlx // n_chunks
        ybacks = []
        for ck in range(n_chunks):
            lo = ck * nc
            with trace_span(f"moe.a2a_dispatch.{ck}"):
                send_k = send[:, lo:lo + nc]
                if skip_exchange:
                    recv_k = send_k
                else:
                    recv_k = _wired_exchange(send_k, wire_disp, axis, d,
                                             dcn_inner, reverse=False,
                                             wire_dcn=wire_dcn)
                if cfg.profile_phases:
                    prof.fence(recv_k)
            p_k = {kk: (v[lo:lo + nc] if kk in ffn_keys else v)
                   for kk, v in ffn_params.items()}
            with trace_span(f"moe.expert.{ck}"):
                ybuf_k = recv_k.transpose(1, 0, 2, 3).reshape(
                    nc, d * cap, h)
                yloc_k = ffn(ybuf_k, p_k)
                if cfg.profile_phases:
                    prof.fence(yloc_k)
            if chaos_inject.is_armed("nan_expert"):  # trace-time check
                # same pre-exchange poisoning as the serial branch; the
                # chunk covers local experts [lo, lo+nc) of this owner
                yloc_k = chaos_inject.poison_local_expert(
                    yloc_k, axis, e, local_offset=lo, local_total=nlx)
            with trace_span(f"moe.a2a_combine.{ck}"):
                ysend_k = yloc_k.reshape(nc, d, cap, h).transpose(
                    1, 0, 2, 3)
                if cfg.collect_stats and wire_comb is not None:
                    err_k = wr.roundtrip_error(ysend_k, wire_comb)
                    comb_err = (err_k if comb_err is None
                                else jnp.maximum(comb_err, err_k))
                if cfg.collect_stats and wire_dcn is not None:
                    errd_k = wr.roundtrip_error(ysend_k, wire_dcn)
                    dcn_err = (errd_k if dcn_err is None
                               else jnp.maximum(dcn_err, errd_k))
                if skip_exchange:
                    yback_k = ysend_k
                else:
                    yback_k = _wired_exchange(ysend_k, wire_comb, axis,
                                              d, dcn_inner, reverse=True,
                                              wire_dcn=wire_dcn)
                if cfg.profile_phases:
                    prof.fence(yback_k)
            ybacks.append(yback_k)
        # [D, nc, C, H] chunks -> [D, nLx, C, H] -> [E, C, H]: global
        # expert id = owner_rank * nLx + local index, so chunks stack
        # along the local-expert axis
        ybuf = jnp.concatenate(ybacks, axis=1).reshape(e, cap, h)
        if comb_err is not None:
            wire_err = (comb_err if wire_err is None
                        else jnp.maximum(wire_err, comb_err))
    else:
        with trace_span("moe.a2a_dispatch"):
            if skip_exchange:
                recv = send
            else:
                recv = _wired_exchange(send, wire_disp, axis, d,
                                       dcn_inner, reverse=False,
                                       wire_dcn=wire_dcn)
                # [D, nLx, C, H] — dim 0 now indexes source rank
            if cfg.profile_phases:
                prof.fence(recv)
        with trace_span("moe.expert"):
            ybuf_in = recv.transpose(1, 0, 2, 3).reshape(nlx, d * cap, h)
            yloc = ffn(ybuf_in, ffn_params)
            if cfg.profile_phases:
                prof.fence(yloc)

        if chaos_inject.is_armed("nan_expert"):  # trace-time check only
            # poison BEFORE the return exchange: the fault originates at
            # the sick expert's owner and must cross the transport —
            # wire compression included — before the health mask sees it
            # (the chaos drill's through-the-wire guarantee,
            # tests/test_chaos.py).  The armed spec names a GLOBAL
            # expert id, exactly as at the [E, C, H] hook site in
            # ops/moe.py.
            yloc = chaos_inject.poison_local_expert(yloc, axis, e)

        # reverse: [nLx, D*C, H] -> [D, nLx, C, H] -> a2a -> [E, C, H]
        with trace_span("moe.a2a_combine"):
            ysend = yloc.reshape(nlx, d, cap, h).transpose(1, 0, 2, 3)
            if cfg.collect_stats and wire_comb is not None:
                comb_err = wr.roundtrip_error(ysend, wire_comb)
                wire_err = (comb_err if wire_err is None
                            else jnp.maximum(wire_err, comb_err))
            if cfg.collect_stats and wire_dcn is not None:
                errd = wr.roundtrip_error(ysend, wire_dcn)
                dcn_err = (errd if dcn_err is None
                           else jnp.maximum(dcn_err, errd))
            if skip_exchange:
                yback = ysend
            else:
                yback = _wired_exchange(ysend, wire_comb, axis, d,
                                        dcn_inner, reverse=True,
                                        wire_dcn=wire_dcn)
                # [D, nLx, C, H] — dim 0 indexes expert-owner rank
            if cfg.profile_phases:
                prof.fence(yback)
        ybuf = yback.reshape(e, cap, h)

    healthy = None
    combine_w = r.combine_weights
    if cfg.degrade_unhealthy_experts:
        # tier-0 (ops/health.py): ybuf rows are THIS rank's tokens'
        # results per global expert, so each rank detects and masks its
        # own exposure to a sick expert locally — no extra collective
        from flashmoe_tpu.ops import health as hlt

        healthy = hlt.expert_health_capacity(ybuf)
        ybuf, combine_w = hlt.degrade_outputs(ybuf, combine_w,
                                              r.expert_idx, healthy)
    with trace_span("moe.combine"):
        out = dsp.combine(ybuf, plan, combine_w, cfg, cap)
        if cfg.num_shared_experts:
            out = out + shared_expert_ffn(
                x.astype(cfg.dtype), params, cfg
            ).astype(out.dtype)
        if cfg.profile_phases:
            prof.fence(out)

    aux = jax.lax.pmean(r.aux_loss, reduce_axes) * cfg.aux_loss_coef
    z = jax.lax.pmean(r.z_loss, reduce_axes)
    counts = jax.lax.psum(r.expert_counts, reduce_axes)
    stats = None
    if cfg.collect_stats:
        local = st.moe_stats(r, cfg, cap)
        stats = st.reduce_stats(local, r.probs_mean, reduce_axes)
        if healthy is not None:
            from flashmoe_tpu.ops import health as hlt

            stats = hlt.attach_degradation(stats, healthy, r.expert_idx,
                                           reduce_axes)
        if wire_err is not None or dcn_err is not None:
            stats = st.with_wire_error(stats, wire_err, reduce_axes,
                                       dcn_error=dcn_err)
        if quant_err is not None:
            stats = st.with_quant_error(stats, quant_err, reduce_axes)
    return MoEOutput(out.astype(cfg.dtype), aux, z, counts, stats)


def ep_moe_layer(params, x, cfg: MoEConfig, mesh: Mesh, *,
                 use_pallas: bool = False,
                 token_axes: tuple[str, ...] = ("ep",),
                 tp: bool | None = None,
                 dcn_inner: int | None = None,
                 interpret: bool = False,
                 skip_exchange: bool = False) -> MoEOutput:
    """Expert-parallel MoE layer over a global token batch.

    x: [S, H] global tokens, sharded over ``token_axes`` (e.g.
    ``('dp', 'ep')`` inside a data-parallel model — the all-to-all then
    runs within each dp group).  Expert params shard over 'ep' and are
    replicated across the other axes, except with ``tp`` (default: on when
    the mesh's tp axis > 1), where each expert's intermediate dimension is
    Megatron-split over 'tp' as well.

    ``dcn_inner``: ranks per slice when the ep axis spans slices — the
    all-to-all then runs as a two-stage (intra-slice, inter-slice)
    decomposition aggregating DCN traffic per slice pair.  Default
    (None): a bootstrapped runtime that detected a multislice blocking
    (``topology.slice_structure``) publishes it, the way it publishes the
    arrival-order schedule; pass ``0`` to force the flat exchange.
    """
    if dcn_inner is None:
        from flashmoe_tpu.runtime.bootstrap import current_dcn_inner

        dcn_inner = current_dcn_inner(mesh, mesh.shape.get("ep", 1))
    elif dcn_inner == 0:
        dcn_inner = None
    if cfg.num_experts == 1:
        return MoEOutput(
            dense_ffn(params, x, cfg),
            jnp.zeros((), cfg.accum_dtype), jnp.zeros((), cfg.accum_dtype),
            jnp.full((1,), x.shape[0], jnp.int32),
        )

    use_tp = tp if tp is not None else (
        "tp" in mesh.shape and mesh.shape["tp"] > 1
    )
    tp_specs = {
        "w_up": P("ep", None, "tp"),
        "w_gate": P("ep", None, "tp"),
        "b_up": P("ep", "tp"),
        "w_down": P("ep", "tp", None),
        "b_down": P("ep", None),
    }
    pspecs = {}
    for k in params:
        if k == "gate_w" or k.startswith("shared"):
            pspecs[k] = P()
        elif use_tp and k in tp_specs:
            pspecs[k] = tp_specs[k]
        else:
            pspecs[k] = P("ep")
    body = functools.partial(
        _ep_moe_shard, cfg=cfg, axis="ep", use_pallas=use_pallas,
        reduce_axes=token_axes, tp_axis="tp" if use_tp else None,
        dcn_inner=dcn_inner, interpret=interpret,
        skip_exchange=skip_exchange,
    )
    stats_specs = (st.MoEStats(*([P()] * len(st.MoEStats._fields)))
                   if cfg.collect_stats else None)
    fn = shard_map(
        body, mesh=mesh,
        in_specs=(pspecs, P(token_axes, None)),
        out_specs=MoEOutput(P(token_axes, None), P(), P(), P(),
                            stats_specs),
        check_vma=False,
    )
    return fn(params, x)


def resolve_moe_backend(cfg: MoEConfig, mesh: Mesh | None = None) -> str:
    """The concrete moe_backend this layer stack should run.

    Pass-through for explicit configs; ``moe_backend='auto'`` consults
    the analytical planner (:mod:`flashmoe_tpu.planner.select`) — the
    predicted per-path latency winner, overridden by measured entries
    when the tuning table or bench records cover this shape.  The
    decision and its full breakdown land in telemetry
    (``metrics.decision('planner.path_select', ...)``)."""
    from flashmoe_tpu.planner.select import resolve_moe_backend as _resolve

    return _resolve(cfg, mesh)


def resolve_moe_plan(cfg: MoEConfig, mesh: Mesh | None = None, *,
                     mode: str | None = None,
                     decode_tokens: int | None = None
                     ) -> tuple[str, int | None]:
    """(moe_backend, a2a_chunks) an ``moe_backend='auto'`` config should
    run: the planner's path winner plus its chunked-pipeline pick for
    the XLA transports (``None`` = serial).  Explicit configs pass
    through with their own ``cfg.a2a_chunks``.  ``mode`` selects the
    pricing regime (None reads ``cfg.serving_mode`` — a decode-phase
    config resolves a decode-priced plan; ``decode_tokens`` is the
    per-step decode batch)."""
    from flashmoe_tpu.planner.select import resolve_moe_plan as _resolve

    return _resolve(cfg, mesh, mode=mode, decode_tokens=decode_tokens)


def apply_chunk_pick(cfg: MoEConfig, backend: str,
                     chunks: int | None) -> MoEConfig:
    """Thread the planner's chunked-pipeline pick into a layer config
    (the shard bodies read ``cfg.a2a_chunks``).  An explicit
    ``cfg.a2a_chunks`` — or a backend/shape the pick cannot serve —
    passes through untouched; the one guard both call sites
    (``auto_ep_moe_layer``, the transformer's FFN block) must share."""
    if (chunks and chunks > 1 and cfg.a2a_chunks is None
            and backend in ("collective", "ragged")
            and cfg.num_experts // max(cfg.ep, 1) % chunks == 0):
        return cfg.replace(a2a_chunks=chunks)
    return cfg


def auto_ep_moe_layer(params, x, cfg: MoEConfig, mesh: Mesh, *,
                      use_pallas: bool = False,
                      token_axes: tuple[str, ...] = ("ep",),
                      interpret: bool = False,
                      collective_id: int = 7) -> MoEOutput:
    """Expert-parallel MoE layer on the planner-selected path.

    Same contract as :func:`ep_moe_layer`; the transport (collective /
    ragged / fused RDMA) — and the chunked-pipeline depth for the XLA
    transports — is chosen by :func:`resolve_moe_plan` for this
    (cfg, mesh) instead of being hard-coded by the caller."""
    backend, chunks = resolve_moe_plan(cfg, mesh)
    cfg = apply_chunk_pick(cfg, backend, chunks)
    try:
        if backend == "fused":
            from flashmoe_tpu.parallel.fused import fused_ep_moe_layer

            return fused_ep_moe_layer(params, x, cfg, mesh,
                                      token_axes=token_axes,
                                      collective_id=collective_id,
                                      interpret=interpret)
        if backend == "ragged":
            from flashmoe_tpu.parallel.ragged_ep import ragged_ep_moe_layer

            return ragged_ep_moe_layer(params, x, cfg, mesh,
                                       use_pallas=use_pallas,
                                       interpret=interpret,
                                       token_axes=token_axes)
    except Exception as e:  # noqa: BLE001 — tier-2 path fallback
        # a specialized transport failing at trace time demotes to the
        # collective baseline (and is remembered, so the next resolution
        # never retries it) instead of killing the step — the RaMP-style
        # runtime path polymorphism of docs/RESILIENCE.md
        from flashmoe_tpu.planner.select import report_path_failure

        report_path_failure(backend, f"{type(e).__name__}: {e}")
    return ep_moe_layer(params, x, cfg, mesh, use_pallas=use_pallas,
                        token_axes=token_axes, interpret=interpret)
