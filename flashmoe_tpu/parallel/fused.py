"""Fused expert-parallel MoE: device-initiated all-to-all inside the kernel,
overlapped with the expert FFN — the FlashDMoE headline capability on TPU.

The reference fuses dispatch -> expert GEMMs -> combine-return into one
persistent CUDA kernel in which NVSHMEM puts carry expert payloads between
GPUs while tile processors compute (``csrc/include/flashmoe/moe/moe.cuh:
71-144``; transport in ``os/packet.cuh:207-259`` and
``os/processor/processor.cuh:711-751``; the in-kernel actor scheduler in
``os/scheduler.cuh``/``subscriber.cuh`` exists to keep SMs busy while
payloads are in flight).

On TPU the same capability is a single Pallas kernel per rank, shard_mapped
over the ``ep`` mesh axis:

  * phase 0 — a cross-device barrier (each rank signals every peer), the
    analogue of the symmetric-heap readiness the reference gets from
    collective allocation (``bootstrap.cuh:347-362``);
  * phase 1 — every rank starts ALL its outbound slab RDMAs at once
    (``make_async_remote_copy``, non-blocking — the analogue of
    ``nvshmem_putmem_signal_nbi``), staggered by rank so the ICI links are
    used all-to-all rather than all-to-one;
  * phase 2 — one grid step per source rank, in ring arrival order: wait
    that source's recv semaphore (the data-carrying signal of the
    reference's ``SignalPayload``), run the local experts' up/act/down
    GEMM chain on the arrived slab with weights streamed HBM->VMEM, and
    immediately RDMA the results back to the source.  Compute on slab s
    overlaps the in-flight transfers of slabs s+1.. — payload-granularity
    overlap, which is the paper's core claim;
  * phase 2.5 — in-kernel combine: as owner ranks' result slabs land back,
    scatter-accumulate them (weighted) into the token-order output held in
    VMEM, so early-returning slabs buy combine progress instead of waiting
    for the whole kernel (the reference's combine tasks,
    ``os/processor/processor.cuh:27-205``).  Opt-in via
    ``FLASHMOE_FUSED_COMBINE=1`` until hardware-benchmarked, and falls
    back to the XLA combine when the accumulator/maps would not fit
    VMEM/SMEM (:func:`_fuse_combine_enabled`).
  * phase 3 — drain: wait all remaining send semaphores.

Gate/plan/dispatch-layout stay in XLA (bandwidth-trivial next to the FFN);
the kernel owns the communication-heavy middle plus the combine.
Capacity-format slabs keep every shape static.

Design decision — why the send slabs are built XLA-side rather than
gathered in-kernel (the reference gathers from ``tokenIds`` inside the
kernel, ``packet.cuh:99-206``): the reference hides per-row staging
latency behind hundreds of concurrently-resident SM blocks; a TPU kernel
is one sequential instruction stream, and this kernel's phase 1 issues
every outbound RDMA up front so remote compute can start.  An in-kernel
row gather there would pay per-row DMA-issue latency serially before any
send departs (~50-100 ns x S*K rows, with no compute to hide behind),
whereas the XLA dispatch builds the same slabs at full VPU/HBM bandwidth
and the RDMAs then stream straight from HBM with no VMEM bounce.  The
single-device path, whose gather IS overlappable with the grid's own
GEMMs, does fuse it (``ops/expert.py:grouped_ffn_tokens``).

Layouts (D = ep world, nLx = local experts, C = per-(rank, expert) capacity):
  x_send  [D, nLx, C, H]  on each source rank: slab d holds tokens routed
                          to rank d's local experts (dest-major).
  x_recv  [D, nLx, C, H]  on each dest rank: slab s is written remotely by
                          source rank s (source-major).
  y_recv  [D, nLx, C, H]  back on the source rank: slab d holds results
                          from owner rank d — exactly the [E, C, H] combine
                          layout after reshape.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import Mesh, PartitionSpec as P

from flashmoe_tpu.config import MoEConfig
from flashmoe_tpu.models.reference import activation_fn, shared_expert_ffn
from flashmoe_tpu.ops import dispatch as dsp
from flashmoe_tpu.ops.gate import router
from flashmoe_tpu.ops.moe import MoEOutput
from flashmoe_tpu.parallel.ep import local_capacity


def _fused_kernel(
    send_cnt, recv_cnt,                   # SMEM int32 [D, nLx] tile counts
    src_order,                            # SMEM int32 [D, D] processing order
    comb_idx,                             # SMEM [D*nLx, cap] (None = XLA combine)
    comb_w,                               # ANY [D*nLx, cap, 1] f32 weight columns
    x_send, w_up, b_up, w_down, b_down,   # inputs (ANY/VMEM)
    x_recv, y_recv, y_stage, out,         # outputs (out: VMEM f32 accumulator,
                                          #   None when combine stays in XLA)
    xs_vmem, wup_vmem, wdn_vmem, acc, yv, # VMEM scratch
    bup_vmem, bdn_vmem,                   # bias tiles
    yc_vmem, yw_vmem, wc_vmem,            # combine tiles (None w/o fusion):
                                          #   raw, f32-weighted, weight col
    copy_sems, send_x_sems, recv_x_sems, send_y_sems, recv_y_sems,
    *, axis, act_name, cm, bi, gated, fuse_combine,
):
    """One grid step = one source slab (ring order).

    Transfers are tile-granular and count-aware: both sides share the
    routed-count matrices (exchanged XLA-side), so only row tiles that
    actually hold tokens are sent, waited on, computed, and returned —
    the TPU form of the reference's ``routedTokens``-sized packets and
    zero-token noop signals (``packet.cuh:99-259``), with the noop made
    unnecessary because counts are pre-shared.

    With ``fuse_combine`` the weighted un-permute also runs in-kernel
    (the reference's combine stage, ``processor.cuh:27-205``): at step s
    the kernel scatter-accumulates the y tiles returned by owner
    ``my - s + 1`` — the owner whose return traffic lands during step
    s-1's compute — into the token-order VMEM accumulator ``out``, so
    return-path transfers overlap combine work instead of serializing
    behind the whole kernel (VERDICT r2 missing #1).
    """
    s = pl.program_id(0)
    d_world = pl.num_programs(0)
    my = jax.lax.axis_index(axis)
    nlx, cap, h = x_send.shape[1], x_send.shape[2], x_send.shape[3]
    d_static = x_send.shape[0]
    act = activation_fn(act_name)
    n_row_tiles = cap // cm
    n_i_chunks = w_down.shape[1] // bi

    def tiles_of(cnt):
        """Present row tiles for a (rank, expert) count."""
        return jax.lax.div(cnt + (cm - 1), cm)

    if fuse_combine:
        @pl.when(s == 0)
        def _():
            out[:] = jnp.zeros_like(out)

    # ---- phase 0/1 (first step only): barrier, then start every send ----
    @pl.when(s == 0)
    def _():
        barrier = pltpu.get_barrier_semaphore()

        def signal_peer(d, c):
            @pl.when(d != my)
            def _():
                pltpu.semaphore_signal(
                    barrier, inc=1, device_id=d,
                    device_id_type=pltpu.DeviceIdType.LOGICAL,
                )
            return c

        jax.lax.fori_loop(0, d_world, signal_peer, 0)
        pltpu.semaphore_wait(barrier, d_world - 1)

        def send(step, c):
            dst = jax.lax.rem(my + step + 1, d_world)

            def per_expert(e, c2):
                nt = tiles_of(send_cnt[dst, e])

                # fast path: full expert block in one DMA descriptor when
                # every tile is present (semaphore waits count bytes, so
                # the decomposition on the wait side need not match)
                @pl.when(nt == n_row_tiles)
                def _():
                    pltpu.make_async_remote_copy(
                        src_ref=x_send.at[dst, e],
                        dst_ref=x_recv.at[my, e],
                        send_sem=send_x_sems.at[dst],
                        recv_sem=recv_x_sems.at[my],
                        device_id=dst,
                        device_id_type=pltpu.DeviceIdType.LOGICAL,
                    ).start()

                @pl.when(nt < n_row_tiles)
                def _():
                    def per_tile(t, c3):
                        @pl.when(t < nt)
                        def _():
                            pltpu.make_async_remote_copy(
                                src_ref=x_send.at[dst, e,
                                                  pl.ds(t * cm, cm), :],
                                dst_ref=x_recv.at[my, e,
                                                  pl.ds(t * cm, cm), :],
                                send_sem=send_x_sems.at[dst],
                                recv_sem=recv_x_sems.at[my],
                                device_id=dst,
                                device_id_type=pltpu.DeviceIdType.LOGICAL,
                            ).start()
                        return c3

                    jax.lax.fori_loop(0, n_row_tiles, per_tile, 0)
                return c2

            jax.lax.fori_loop(0, nlx, per_expert, 0)
            return c

        jax.lax.fori_loop(0, d_world - 1, send, 0)
        # own slab: plain local copy (full; local bandwidth is cheap)
        own = pltpu.make_async_copy(
            x_send.at[my], x_recv.at[my], copy_sems.at[0]
        )
        own.start()
        own.wait()

    # ---- phase 2: process source slabs in expected-arrival order ----
    # ``src_order[my]`` is a permutation of sources starting with ``my``
    # (the own slab is local and ready immediately).  The default is ring
    # order (src_order[r, s] = (r+s) mod D), which IS arrival order on a
    # homogeneous ICI torus because phase 1 staggers sends by ring
    # distance.  On heterogeneous fabrics (multi-slice: some sources
    # behind a DCN hop) the caller passes
    # :func:`flashmoe_tpu.parallel.topology.arrival_order`, which sorts
    # sources by predicted alpha-beta arrival time — the static
    # equivalent of the reference subscriber consuming packets in
    # whatever order they land (``os/subscriber.cuh:333-451``); Mosaic
    # semaphores have no try-wait, so the order is bound at trace time
    # from the measured topology instead of polled at run time.
    # Correctness never depends on the order: every slab's recv
    # semaphore is awaited before use (see scripts/skew_sim.py for the
    # quantified cost of a mispredicted order).
    src = src_order[my, s]

    @pl.when(s != 0)
    def _():
        # wait for exactly the tiles this source sent (tile-sized waits
        # against the data-carrying recv semaphore)
        def per_expert(e, c):
            def per_tile(t, c2):
                @pl.when(t < tiles_of(recv_cnt[src, e]))
                def _():
                    pltpu.make_async_copy(
                        x_recv.at[src, e, pl.ds(t * cm, cm), :],
                        x_recv.at[src, e, pl.ds(t * cm, cm), :],
                        recv_x_sems.at[src],
                    ).wait()
                return c2

            return jax.lax.fori_loop(0, n_row_tiles, per_tile, c)

        jax.lax.fori_loop(0, nlx, per_expert, 0)

    def expert_body(e, _):
        # stream this expert's biases once
        bup_dma = pltpu.make_async_copy(
            b_up.at[pl.ds(e, 1), :], bup_vmem, copy_sems.at[0]
        )
        bdn_dma = pltpu.make_async_copy(
            b_down.at[pl.ds(e, 1), :], bdn_vmem, copy_sems.at[1]
        )
        bup_dma.start(); bdn_dma.start()
        bup_dma.wait(); bdn_dma.wait()

        # gated mode: w_up holds [gate_chunk | up_chunk] interleaved on a
        # doubled chunk axis (see fused_ep_moe_layer), so one DMA streams
        # both halves of the SwiGLU
        up_chunk = 2 * bi if gated else bi

        # weight-chunk DMA descriptors, double-buffered over two VMEM slots
        # (sems 2+slot / 4+slot): chunk j+1 streams HBM->VMEM while chunk j
        # runs on the MXU — the reference's multistage cp.async operand
        # pipeline (``mmaConfig.cuh:19-171``) expressed as slot-alternating
        # async copies.
        def wu_dma(j, slot):
            return pltpu.make_async_copy(
                w_up.at[e, :, pl.ds(j * up_chunk, up_chunk)],
                wup_vmem.at[slot], copy_sems.at[2 + slot],
            )

        def wd_dma(j, slot):
            return pltpu.make_async_copy(
                w_down.at[e, pl.ds(j * bi, bi), :],
                wdn_vmem.at[slot], copy_sems.at[4 + slot],
            )

        def row_tile_body(t, carry):
            xd = pltpu.make_async_copy(
                x_recv.at[src, e, pl.ds(t * cm, cm), :],
                xs_vmem, copy_sems.at[0],
            )
            xd.start()
            wu_dma(0, 0).start()
            wd_dma(0, 0).start()
            xd.wait()
            acc[:] = jnp.zeros_like(acc)

            def chunk_body(j, carry_c):
                slot = jax.lax.rem(j, 2)

                @pl.when(j + 1 < n_i_chunks)
                def _prefetch():
                    wu_dma(j + 1, 1 - slot).start()
                    wd_dma(j + 1, 1 - slot).start()

                wu_dma(j, slot).wait()
                if gated:
                    g = jnp.dot(
                        xs_vmem[:], wup_vmem[slot, :, :bi],
                        preferred_element_type=jnp.float32,
                    )
                    up = jnp.dot(
                        xs_vmem[:], wup_vmem[slot, :, bi:],
                        preferred_element_type=jnp.float32,
                    ) + bup_vmem[0, pl.ds(j * bi, bi)].astype(jnp.float32)
                    hidden = (act(g) * up).astype(xs_vmem.dtype)
                else:
                    up = jnp.dot(
                        xs_vmem[:], wup_vmem[slot],
                        preferred_element_type=jnp.float32,
                    ) + bup_vmem[0, pl.ds(j * bi, bi)].astype(jnp.float32)
                    hidden = act(up).astype(xs_vmem.dtype)
                wd_dma(j, slot).wait()
                acc[:] += jnp.dot(
                    hidden, wdn_vmem[slot],
                    preferred_element_type=jnp.float32,
                )
                return carry_c

            jax.lax.fori_loop(0, n_i_chunks, chunk_body, 0)
            yv[:] = (
                acc[:] + bdn_vmem[0].astype(jnp.float32)
            ).astype(yv.dtype)
            st = pltpu.make_async_copy(
                yv, y_stage.at[src, e, pl.ds(t * cm, cm), :], copy_sems.at[0]
            )
            st.start()
            st.wait()
            # return immediately: tile-granular send back to the source
            # (y_stage is indexed by src, so later steps never overwrite a
            # slab whose asynchronous return is still in flight)
            @pl.when(src != my)
            def _():
                pltpu.make_async_remote_copy(
                    src_ref=y_stage.at[src, e, pl.ds(t * cm, cm), :],
                    dst_ref=y_recv.at[my, e, pl.ds(t * cm, cm), :],
                    send_sem=send_y_sems.at[src],
                    recv_sem=recv_y_sems.at[my],
                    device_id=src,
                    device_id_type=pltpu.DeviceIdType.LOGICAL,
                ).start()
            return carry

        # only the row tiles this source actually routed here
        # (tiles_of(cnt) <= n_row_tiles by construction: counts are clamped
        # to cap and cap % cm == 0)
        jax.lax.fori_loop(0, tiles_of(recv_cnt[src, e]), row_tile_body, 0)
        return _

    jax.lax.fori_loop(0, nlx, expert_body, 0)

    @pl.when(src == my)
    def _():
        own = pltpu.make_async_copy(
            y_stage.at[src], y_recv.at[my], copy_sems.at[0]
        )
        own.start()
        own.wait()

    # ---- phase 2.5: in-kernel combine of returned slabs ----
    if fuse_combine:
        def wait_owner_tiles(o):
            """Consume ALL of owner o's return bytes before reading any
            tile: per-tile waits complete only once the cumulative byte
            count arrived, so reads below are safe even if the per-tile
            DMAs retire out of order."""
            def per_expert(e, c):
                def per_tile(t, c2):
                    @pl.when(t < tiles_of(send_cnt[o, e]))
                    def _():
                        pltpu.make_async_copy(
                            y_recv.at[o, e, pl.ds(t * cm, cm), :],
                            y_recv.at[o, e, pl.ds(t * cm, cm), :],
                            recv_y_sems.at[o],
                        ).wait()
                    return c2

                return jax.lax.fori_loop(0, n_row_tiles, per_tile, c)

            jax.lax.fori_loop(0, nlx, per_expert, 0)

        def combine_owner(o):
            """out[tok] += w * y for every populated slot of owner o's
            returned slab.  The combine weights are applied as ONE
            vectorized [cm, h] multiply per tile: comb_w is laid out
            [E, cap, 1] so the tile's weight column DMAs contiguously
            into a [cm, 1] scratch (no dynamic lane offsets, which
            Mosaic restricts).  The remaining per-row work is the
            scatter add alone — dynamic sublane indexing costs VPU
            cycles, not DMA issue latency (contrast the send-slab
            design note above)."""
            def per_expert(e, c):
                cnt = send_cnt[o, e]
                g = o * nlx + e

                def per_tile(t, c2):
                    yd = pltpu.make_async_copy(
                        y_recv.at[o, e, pl.ds(t * cm, cm), :],
                        yc_vmem, copy_sems.at[0],
                    )
                    wd = pltpu.make_async_copy(
                        comb_w.at[g, pl.ds(t * cm, cm), :],
                        wc_vmem, copy_sems.at[1],
                    )
                    yd.start(); wd.start()
                    yd.wait(); wd.wait()
                    yw_vmem[:] = yc_vmem[:].astype(jnp.float32) * wc_vmem[:]
                    rows = jnp.minimum(cm, cnt - t * cm)

                    def per_row(r, c3):
                        tok = comb_idx[g, t * cm + r]
                        out[pl.ds(tok, 1), :] += yw_vmem[pl.ds(r, 1), :]
                        return c3

                    return jax.lax.fori_loop(0, rows, per_row, c2)

                return jax.lax.fori_loop(0, tiles_of(cnt), per_tile, c)

            jax.lax.fori_loop(0, nlx, per_expert, 0)

        if d_static == 1:
            # single-rank world: the (local) own slab is ready right now
            combine_owner(my)
        else:
            # step s combines owner my-s+1, whose return for my tokens was
            # computed during global step s-1 (owner o processes source
            # my at its step (my-o) mod D) — ring-symmetric overlap; own
            # slab (o=my) combines at s=1, the last owner (my+1, computed
            # at global step D-1) in the drain step below.
            @pl.when(s >= 1)
            def _():
                o = jax.lax.rem(my + 1 - s + d_world, d_world)

                @pl.when(o != my)
                def _():
                    wait_owner_tiles(o)

                combine_owner(o)

            @pl.when(s == d_world - 1)
            def _():
                o_last = jax.lax.rem(my + 1, d_world)
                wait_owner_tiles(o_last)
                combine_owner(o_last)

    # ---- phase 3 (last step): drain all semaphores, tile-accounted ----
    @pl.when(s == d_world - 1)
    def _():
        def drain(d, c):
            @pl.when(d != my)
            def _():
                def per_expert(e, c2):
                    def per_tile(t, c3):
                        # x sends I started toward d
                        @pl.when(t < tiles_of(send_cnt[d, e]))
                        def _():
                            pltpu.make_async_copy(
                                x_send.at[d, e, pl.ds(t * cm, cm), :],
                                x_send.at[d, e, pl.ds(t * cm, cm), :],
                                send_x_sems.at[d],
                            ).wait()
                            # y tiles coming back from owner d (same
                            # predicate: they are the tiles I sent);
                            # with the in-kernel combine these waits
                            # were already consumed in phase 2.5
                            if not fuse_combine:
                                pltpu.make_async_copy(
                                    y_recv.at[d, e, pl.ds(t * cm, cm), :],
                                    y_recv.at[d, e, pl.ds(t * cm, cm), :],
                                    recv_y_sems.at[d],
                                ).wait()
                        # y sends I started toward source d
                        @pl.when(t < tiles_of(recv_cnt[d, e]))
                        def _():
                            pltpu.make_async_copy(
                                y_stage.at[d, e, pl.ds(t * cm, cm), :],
                                y_stage.at[d, e, pl.ds(t * cm, cm), :],
                                send_y_sems.at[d],
                            ).wait()
                        return c3

                    return jax.lax.fori_loop(0, n_row_tiles, per_tile, c2)

                jax.lax.fori_loop(0, nlx, per_expert, 0)
            return c

        jax.lax.fori_loop(0, d_world, drain, 0)


def _fused_shard(send_cnt, recv_cnt, src_order, x_send, w_up, b_up, w_down,
                 b_down, *,
                 cfg: MoEConfig, axis: str, interpret, collective_id: int,
                 detect_races: bool = False, w_gate=None,
                 comb_idx=None, comb_w=None, s_out: int | None = None):
    """Launch the fused kernel.  With ``comb_idx``/``comb_w``/``s_out`` the
    combine runs in-kernel and the call returns ``(out [s_out_pad, h] f32,
    y_recv)``; otherwise it returns ``y_recv`` for the XLA combine."""
    d_world, nlx, cap, h = x_send.shape
    i_dim = w_down.shape[1]
    gated = w_gate is not None
    fuse_combine = comb_idx is not None
    # largest row tile that divides the capacity (callers pad cap to a
    # 32-multiple, so an awkward capacity degrades the tile size instead of
    # being rejected)
    cm = next((t for t in (256, 128, 64, 32, 16, 8) if cap % t == 0), None)
    if cm is None:
        raise ValueError(f"capacity {cap} not a multiple of 8 rows")
    # the combine accumulator claims VMEM, so cap the streamed weight
    # chunk lower when it is resident (see _fuse_combine_enabled)
    bi_cap = 256 if fuse_combine else (512 if cm <= 128 else 256)
    # measured per-generation overrides (flashmoe_tpu.tuning; the
    # reference's arch trait table, arch.cuh:95-222) — applied only when
    # they still divide the shapes they claim to match
    from flashmoe_tpu import tuning

    tuned = tuning.lookup("fused_ep", h=h, i=i_dim,
                          dtype=jnp.dtype(x_send.dtype).name)
    if tuned.get("cm") and cap % tuned["cm"] == 0:
        cm = tuned["cm"]
    if tuned.get("bi_cap") and not fuse_combine:
        bi_cap = tuned["bi_cap"]
    bi = min(bi_cap, i_dim)
    if i_dim % bi:
        raise ValueError(f"intermediate {i_dim} not divisible by {bi}")
    if gated:
        # interleave per-chunk: [nlx, H, nj*2*bi] as [gate_chunk | up_chunk]
        nj = i_dim // bi
        wg = w_gate.reshape(nlx, h, nj, bi)
        wu = w_up.reshape(nlx, h, nj, bi)
        w_up = jnp.concatenate([wg, wu], axis=-1).reshape(
            nlx, h, nj * 2 * bi
        )

    unified = functools.partial(
        _fused_kernel, axis=axis, act_name=cfg.hidden_act, cm=cm, bi=bi,
        gated=gated, fuse_combine=fuse_combine,
    )
    out_shapes = [
        jax.ShapeDtypeStruct((d_world, nlx, cap, h), x_send.dtype),  # x_recv
        jax.ShapeDtypeStruct((d_world, nlx, cap, h), x_send.dtype),  # y_recv
        jax.ShapeDtypeStruct((d_world, nlx, cap, h), x_send.dtype),  # y_stage
    ]
    any_spec = pl.BlockSpec(memory_space=pl.ANY)
    smem_spec = pl.BlockSpec(memory_space=pltpu.SMEM)
    in_specs = [smem_spec, smem_spec, smem_spec]
    inputs = [send_cnt, recv_cnt, src_order]
    out_specs = [any_spec, any_spec, any_spec]
    if fuse_combine:
        s_pad = -(-s_out // 8) * 8
        # comb_idx feeds scalar indexing (SMEM); comb_w is applied as a
        # vectorized per-tile multiply — laid out [E, cap, 1] in HBM so
        # each tile's weight column DMAs contiguously into a [cm, 1]
        # scratch (no dynamic lane offsets)
        in_specs += [smem_spec, any_spec]
        inputs += [comb_idx,
                   comb_w.astype(jnp.float32).reshape(d_world * nlx,
                                                      cap, 1)]
        out_shapes.append(jax.ShapeDtypeStruct((s_pad, h), jnp.float32))
        # whole-array VMEM output: it IS the accumulator, revisited every
        # grid step and written back to HBM once at kernel end
        out_specs.append(pl.BlockSpec(memory_space=pltpu.VMEM))
    in_specs += [any_spec] * 5
    inputs += [x_send, w_up, b_up, w_down, b_down]

    if fuse_combine:
        def kernel(send_cnt, recv_cnt, src_order, comb_idx, comb_w,
                   x_send, w_up, b_up, w_down, b_down,
                   x_recv, y_recv, y_stage, out,
                   xs, wup, wdn, acc, yv, bup, bdn, yc, yw, wc, *sems):
            unified(send_cnt, recv_cnt, src_order, comb_idx, comb_w,
                    x_send, w_up, b_up, w_down, b_down,
                    x_recv, y_recv, y_stage, out,
                    xs, wup, wdn, acc, yv, bup, bdn, yc, yw, wc, *sems)
    else:
        def kernel(send_cnt, recv_cnt, src_order,
                   x_send, w_up, b_up, w_down, b_down,
                   x_recv, y_recv, y_stage,
                   xs, wup, wdn, acc, yv, bup, bdn, *sems):
            unified(send_cnt, recv_cnt, src_order, None, None,
                    x_send, w_up, b_up, w_down, b_down,
                    x_recv, y_recv, y_stage, None,
                    xs, wup, wdn, acc, yv, bup, bdn, None, None, None,
                    *sems)

    scratch = [
        pltpu.VMEM((cm, h), x_send.dtype),        # xs
        pltpu.VMEM((2, h, 2 * bi if gated else bi),
                   x_send.dtype),                 # w_up (+gate) 2 slots
        pltpu.VMEM((2, bi, h), x_send.dtype),     # w_down chunk 2 slots
        pltpu.VMEM((cm, h), jnp.float32),         # acc
        pltpu.VMEM((cm, h), x_send.dtype),        # y tile
        pltpu.VMEM((1, i_dim), b_up.dtype),       # bias up
        pltpu.VMEM((1, h), b_down.dtype),         # bias down
    ]
    if fuse_combine:
        scratch.append(pltpu.VMEM((cm, h), x_send.dtype))  # combine tile
        scratch.append(pltpu.VMEM((cm, h), jnp.float32))   # weighted tile
        scratch.append(pltpu.VMEM((cm, 1), jnp.float32))   # weight column
    scratch += [
        pltpu.SemaphoreType.DMA((6,)),            # local copy + wt sems
        pltpu.SemaphoreType.DMA((d_world,)),      # send x
        pltpu.SemaphoreType.DMA((d_world,)),      # recv x
        pltpu.SemaphoreType.DMA((d_world,)),      # send y
        pltpu.SemaphoreType.DMA((d_world,)),      # recv y
    ]
    interp = False
    if interpret:
        # the interpreter's vector-clock race detector is the framework's
        # lock-free-protocol sanitizer (the reference relies on manual
        # fence discipline with no tooling — SURVEY §5)
        interp = pltpu.InterpretParams(
            dma_execution_mode="eager", detect_races=detect_races,
        )
    results = pl.pallas_call(
        kernel,
        grid=(d_world,),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shapes,
        scratch_shapes=scratch,
        compiler_params=pltpu.CompilerParams(
            has_side_effects=True, collective_id=collective_id,
        ),
        interpret=interp,
    )(*inputs)
    if fuse_combine:
        _, y_recv, _, out = results
        return out, y_recv
    _, y_recv, _ = results
    return y_recv


# ----------------------------------------------------------------------
# Differentiable core: Pallas forward, Pallas-GEMM backward
# ----------------------------------------------------------------------
#
# The kernel's dataflow is  x_send --a2a--> x_recv --FFN--> y_stage
# --a2a--> y_recv.  ``all_to_all(split=concat=0)`` is its own transpose,
# so the VJP re-exchanges the cotangents/primals with XLA collectives
# (cheap next to the FFN FLOPs) and runs every large GEMM — the
# pre-activation recompute, dHidden/dX, and both dW — through the Pallas
# grouped kernels (:func:`flashmoe_tpu.ops.expert.ffn_backward_core`).
# Expert shards are disjoint across ep ranks, so dW needs no psum.

@functools.partial(jax.custom_vjp, nondiff_argnums=(9, 10, 11, 12, 13))
def _fused_core(send_cnt, recv_cnt, src_order, x_send, w_up, b_up, w_down,
                b_down, w_gate, cfg, axis, interpret, collective_id,
                detect_races):
    return _fused_shard(
        send_cnt, recv_cnt, src_order, x_send, w_up, b_up, w_down, b_down,
        cfg=cfg, axis=axis, interpret=interpret,
        collective_id=collective_id, detect_races=detect_races,
        w_gate=w_gate,
    )


def _fused_core_fwd(send_cnt, recv_cnt, src_order, x_send, w_up, b_up,
                    w_down, b_down, w_gate, cfg, axis, interpret,
                    collective_id, detect_races):
    y = _fused_core(send_cnt, recv_cnt, src_order, x_send, w_up, b_up,
                    w_down, b_down, w_gate, cfg, axis, interpret,
                    collective_id, detect_races)
    return y, (send_cnt, recv_cnt, src_order, x_send, w_up, b_up, w_down,
               b_down, w_gate)


def _ffn_bwd_from_dy(cfg, axis, interpret, res, dy):
    """Shared backward tail: slab cotangent ``dy`` (of y_recv) -> gradients
    of (x_send, w_up, b_up, w_down, b_down, w_gate) via XLA re-exchange +
    Pallas grouped-GEMM backward kernels."""
    from flashmoe_tpu.ops.expert import (
        _auto_block, ffn_backward_core, grouped_matmul,
    )

    x_send, w_up, b_up, w_down, b_down, w_gate = res
    d, nlx, cap, h = x_send.shape
    gated = w_gate is not None

    a2a = functools.partial(
        jax.lax.all_to_all, axis_name=axis, split_axis=0, concat_axis=0,
        tiled=False,
    )
    x_recv = a2a(x_send)       # recompute received slabs (fwd exchange)
    dy_stage = a2a(dy)         # transpose of the return exchange

    def to_rows(t):            # [D, nlx, cap, h] -> [nlx*D*cap, h]
        return t.transpose(1, 0, 2, 3).reshape(nlx * d * cap, h)

    def from_rows(r):
        return r.reshape(nlx, d, cap, h).transpose(1, 0, 2, 3)

    xr = to_rows(x_recv)
    dyr = to_rows(dy_stage)
    bm = _auto_block(cap, 256)
    tiles_per_e = (d * cap) // bm
    gid = jnp.arange(nlx * tiles_per_e, dtype=jnp.int32) // tiles_per_e

    # recompute pre-activations through the Pallas grouped matmul
    i_dim = w_up.shape[2]
    u = grouped_matmul(xr, gid, w_up, block_m=bm, out_dtype=jnp.float32,
                       interpret=interpret)
    u = (u.reshape(nlx, d * cap, i_dim)
         + b_up[:, None, :].astype(jnp.float32)).reshape(-1, i_dim)
    g = None
    if gated:
        g = grouped_matmul(xr, gid, w_gate, block_m=bm,
                           out_dtype=jnp.float32, interpret=interpret)

    dxr, d_wu, d_bu, d_wd, d_bd, d_wg = ffn_backward_core(
        xr, gid, w_up, w_down, w_gate, u, g, dyr,
        act_name=cfg.hidden_act, gated=gated, block_m=bm,
        interpret=interpret,
    )
    d_x_send = a2a(from_rows(dxr.astype(x_send.dtype)))
    return (d_x_send,
            d_wu.astype(w_up.dtype), d_bu.astype(b_up.dtype),
            d_wd.astype(w_down.dtype), d_bd.astype(b_down.dtype),
            d_wg.astype(w_gate.dtype) if gated else None)


def _fused_core_bwd(cfg, axis, interpret, collective_id, detect_races,
                    res, dy):
    import numpy as np

    (send_cnt, recv_cnt, src_order, x_send, w_up, b_up, w_down, b_down,
     w_gate) = res
    grads = _ffn_bwd_from_dy(
        cfg, axis, interpret,
        (x_send, w_up, b_up, w_down, b_down, w_gate), dy,
    )
    f0 = lambda a: np.zeros(a.shape, jax.dtypes.float0)
    return (f0(send_cnt), f0(recv_cnt), f0(src_order)) + grads


_fused_core.defvjp(_fused_core_fwd, _fused_core_bwd)


# ----------------------------------------------------------------------
# Combine-fused core: the kernel also owns the weighted un-permute
# ----------------------------------------------------------------------
#
# Dataflow:  x_send --a2a--> x_recv --FFN--> y_stage --a2a--> y_recv
#            --in-kernel combine-->  out[tok] = sum_slots w_slot * y_slot.
# The VJP peels the combine analytically (dy = w * dout[idx];
# d_comb_w = <dout[idx], y_recv>, masked to populated slots) and reuses
# the shared FFN backward.  comb_w stays a differentiable input so router
# gradients flow through dsp.combine_slot_maps' scatter transpose.

@functools.partial(jax.custom_vjp,
                   nondiff_argnums=(11, 12, 13, 14, 15, 16))
def _fused_combine_core(send_cnt, recv_cnt, src_order, comb_idx, comb_w,
                        x_send, w_up, b_up, w_down, b_down, w_gate,
                        cfg, axis, interpret, collective_id,
                        detect_races, s_out):
    out, _ = _fused_shard(
        send_cnt, recv_cnt, src_order, x_send, w_up, b_up, w_down, b_down,
        cfg=cfg, axis=axis, interpret=interpret,
        collective_id=collective_id, detect_races=detect_races,
        w_gate=w_gate, comb_idx=comb_idx, comb_w=comb_w, s_out=s_out,
    )
    return out


def _fused_combine_core_fwd(send_cnt, recv_cnt, src_order, comb_idx,
                            comb_w, x_send, w_up, b_up, w_down, b_down,
                            w_gate, cfg, axis, interpret, collective_id,
                            detect_races, s_out):
    out, y_recv = _fused_shard(
        send_cnt, recv_cnt, src_order, x_send, w_up, b_up, w_down, b_down,
        cfg=cfg, axis=axis, interpret=interpret,
        collective_id=collective_id, detect_races=detect_races,
        w_gate=w_gate, comb_idx=comb_idx, comb_w=comb_w, s_out=s_out,
    )
    return out, (send_cnt, recv_cnt, src_order, comb_idx, comb_w, x_send,
                 w_up, b_up, w_down, b_down, w_gate, y_recv)


def _fused_combine_core_bwd(cfg, axis, interpret, collective_id,
                            detect_races, s_out, res, dout):
    import numpy as np

    (send_cnt, recv_cnt, src_order, comb_idx, comb_w, x_send,
     w_up, b_up, w_down, b_down, w_gate, y_recv) = res
    d, nlx, cap, h = x_send.shape

    dout = dout.astype(jnp.float32)            # [s_pad, h]
    idx = comb_idx.reshape(d, nlx, cap)
    w = comb_w.reshape(d, nlx, cap)
    # combine transpose: dy[slot] = w_slot * dout[tok(slot)]
    dy = (w[..., None] * dout[idx]).astype(x_send.dtype)
    grads = _ffn_bwd_from_dy(
        cfg, axis, interpret,
        (x_send, w_up, b_up, w_down, b_down, w_gate), dy,
    )
    # d_comb_w[slot] = <dout[tok(slot)], y_recv[slot]>, only where the
    # slot is populated (empty slots hold unwritten garbage; their
    # cotangent is dropped by combine_slot_maps' trash-slot slice anyway,
    # but NaN garbage must not leak through 0*NaN)
    cnt = jnp.minimum(send_cnt, cap).astype(jnp.int32)  # [d, nlx]
    present = (
        jnp.arange(cap, dtype=jnp.int32)[None, None, :] < cnt[..., None]
    )
    d_w = jnp.where(
        present,
        jnp.einsum("denh,denh->den", dout[idx],
                   y_recv.astype(jnp.float32)),
        0.0,
    ).reshape(comb_w.shape)

    f0 = lambda a: np.zeros(a.shape, jax.dtypes.float0)
    return (f0(send_cnt), f0(recv_cnt), f0(src_order), f0(comb_idx),
            d_w) + grads


_fused_combine_core.defvjp(_fused_combine_core_fwd, _fused_combine_core_bwd)


def _fuse_combine_budget_ok(cfg: MoEConfig, s_loc: int, h: int, i_dim: int,
                            cap: int) -> bool:
    """Memory feasibility of the in-kernel combine: the token-order
    accumulator ``[s_pad, h] f32`` + streaming slabs must fit VMEM
    (``comb_w`` stays in HBM, streamed through a [cm, 1] scratch), and
    the index map ``comb_idx`` ([E, cap] i32) must fit SMEM — it is a
    whole-array scalar-memory input, and a VMEM-only estimate let large
    E x capacity configs sail into Mosaic compile failures instead of
    the XLA-combine fallback (advisor round-3 #1)."""
    s_pad = -(-s_loc // 8) * 8
    dt = jnp.dtype(cfg.dtype).itemsize
    cm = next((t for t in (256, 128, 64, 32, 16, 8) if cap % t == 0), 8)
    bi = min(256, i_dim)  # _fused_shard caps bi at 256 when fusing
    n_experts = cfg.num_experts
    acc_bytes = s_pad * h * 4
    weights = 2 * h * (2 * bi if cfg.gated_ffn else bi) * dt + 2 * bi * h * dt
    # xs, yv, yc tiles (model dtype) + acc, yw tiles (f32)
    tiles = cm * h * (3 * dt + 8)
    # conservative SMEM budget: the index map plus the count matrices must
    # stay well under the ~1 MiB scalar memory of current TPU cores
    smem_bytes = n_experts * cap * 4 + 2 * n_experts * 4
    return (acc_bytes + weights + tiles <= 15 * 2**20
            and smem_bytes <= 256 * 2**10)


def _fuse_combine_enabled(cfg: MoEConfig, s_loc: int, h: int, i_dim: int,
                          cap: int) -> bool:
    """Whether the weighted un-permute runs inside the RDMA kernel.

    OPT-IN (``FLASHMOE_FUSED_COMBINE=1``) until a hardware stage_bench
    row shows it beating the XLA combine: the scatter loop is S*K
    sequential per-row VPU accumulates (see ``combine_owner``), which on
    one TPU core may cost more than the return-path overlap it buys —
    the same measured-before-default policy applied to the gather-fused
    kernel in round 3.  Even when requested, memory-infeasible configs
    fall back to the XLA combine (same math, no return-path overlap)
    rather than failing Mosaic compilation.
    """
    if os.environ.get("FLASHMOE_FUSED_COMBINE") != "1":
        return False
    ok = _fuse_combine_budget_ok(cfg, s_loc, h, i_dim, cap)
    if not ok:
        import warnings
        warnings.warn(
            "FLASHMOE_FUSED_COMBINE=1 requested but the combine maps/"
            "accumulator exceed the SMEM/VMEM budget; using the XLA "
            "combine instead", stacklevel=2)
    return ok


def fused_ep_moe_layer(params, x, cfg: MoEConfig, mesh: Mesh, *,
                       interpret: bool = False,
                       use_pallas_gate: bool | None = None,
                       token_axes: tuple[str, ...] = ("ep",),
                       collective_id: int = 7,
                       detect_races: bool = False,
                       src_order=None) -> MoEOutput:
    """Expert-parallel MoE with the fused in-kernel all-to-all.

    Same contract as :func:`flashmoe_tpu.parallel.ep.ep_moe_layer`.  Gated
    (SwiGLU) experts stream through the kernel with chunk-interleaved
    gate|up weights; shared experts run XLA-side on the local token shard
    (they are replicated dense compute, not communication).

    ``src_order`` ([D, D] int32; row r = the order in which rank r
    processes source slabs, starting with r itself) overrides the default
    ring schedule — pass :func:`flashmoe_tpu.parallel.topology.
    arrival_order` on heterogeneous fabrics so slow-linked sources are
    processed last instead of stalling earlier slabs (the reference's
    arrival-order subscriber, ``os/subscriber.cuh:333-451``, bound
    statically from the measured topology).
    """

    d_world = mesh.shape["ep"]
    if src_order is None:
        # a bootstrapped runtime on a heterogeneous fabric publishes its
        # arrival-order schedule (gated on this mesh's device ordering
        # actually matching the table's rank indexing); everywhere else
        # the ring default stands
        from flashmoe_tpu.runtime.bootstrap import current_src_order

        src_order = current_src_order(mesh, d_world)
    if src_order is None:
        from flashmoe_tpu.parallel.topology import default_ring

        src_order = jnp.asarray(default_ring(d_world))
    else:
        if src_order.shape != (d_world, d_world):
            raise ValueError(
                f"src_order must be [{d_world}, {d_world}] (one "
                f"processing order per ep rank), got {src_order.shape}")
        # a row that is not an own-first permutation would make the kernel
        # process a slab whose recv semaphore was never awaited (step 0)
        # or wait on the never-signaled own slab — a silent race or a
        # hang; src_order normally comes concrete from arrival_order, so
        # check it at trace time when possible
        try:
            so = __import__("numpy").asarray(src_order)
        except Exception:  # traced value: caller owns the invariant
            so = None
        if so is not None:
            for r in range(d_world):
                if so[r, 0] != r or sorted(so[r]) != list(range(d_world)):
                    raise ValueError(
                        f"src_order row {r} must be a permutation of "
                        f"0..{d_world - 1} starting with {r}, got "
                        f"{so[r].tolist()}")
        src_order = jnp.asarray(src_order, jnp.int32)

    def body(params, x, src_order):
        d = jax.lax.axis_size("ep")
        s_loc, h = x.shape
        nlx = cfg.num_experts // d
        cap = local_capacity(cfg, s_loc)
        # pad the capacity buffer to a row-tile multiple (e.g. CF=1.25 can
        # give cap=320 -> padded 320, cap=40 -> 64); counts stay clamped to
        # the real cap, so padded rows are never transferred or computed
        cap_pad = -(-cap // 32) * 32

        use_gate_pallas = (
            use_pallas_gate
            if use_pallas_gate is not None
            else (interpret or jax.default_backend() == "tpu")
        )
        r = router(x, params["gate_w"], cfg, use_pallas=use_gate_pallas,
                   interpret=interpret)
        plan = dsp.make_plan(r.expert_idx, cfg, cap)
        xbuf = dsp.dispatch(x.astype(cfg.dtype), plan, cfg, cap)
        if cap_pad != cap:
            xbuf = jnp.pad(xbuf, ((0, 0), (0, cap_pad - cap), (0, 0)))
        x_send = xbuf.reshape(d, nlx, cap_pad, h)

        # routed-count matrices: what I send each (dest, expert) and what
        # each source sends my experts — shared knowledge on both ends, so
        # the kernel can skip absent tiles without noop signals
        send_cnt = jnp.minimum(plan.counts, cap).astype(jnp.int32).reshape(
            d, nlx
        )
        recv_cnt = jax.lax.all_to_all(
            send_cnt.reshape(d, 1, nlx), "ep", split_axis=0, concat_axis=0,
            tiled=False,
        ).reshape(d, nlx)

        w_args = (
            params["w_up"].astype(cfg.dtype), params["b_up"],
            params["w_down"].astype(cfg.dtype), params["b_down"],
            (params["w_gate"].astype(cfg.dtype)
             if cfg.gated_ffn else None),
        )
        i_dim = params["w_down"].shape[1]
        if _fuse_combine_enabled(cfg, s_loc, h, i_dim, cap_pad):
            comb_idx, comb_w = dsp.combine_slot_maps(
                plan, r.combine_weights, cfg, cap
            )
            if cap_pad != cap:
                comb_idx = jnp.pad(comb_idx, ((0, 0), (0, cap_pad - cap)))
                comb_w = jnp.pad(comb_w, ((0, 0), (0, cap_pad - cap)))
            out = _fused_combine_core(
                send_cnt, recv_cnt, src_order, comb_idx, comb_w, x_send,
                *w_args,
                cfg, "ep", interpret, collective_id, detect_races, s_loc,
            )[:s_loc]
        else:
            y_recv = _fused_core(
                send_cnt, recv_cnt, src_order, x_send, *w_args,
                cfg, "ep", interpret, collective_id, detect_races,
            )
            ybuf = y_recv.reshape(cfg.num_experts, cap_pad, h)
            out = dsp.combine(ybuf, plan, r.combine_weights, cfg, cap_pad)
        if cfg.num_shared_experts:
            out = out + shared_expert_ffn(
                x.astype(cfg.dtype), params, cfg
            ).astype(out.dtype)

        aux = jax.lax.pmean(r.aux_loss, token_axes) * cfg.aux_loss_coef
        z = jax.lax.pmean(r.z_loss, token_axes)
        counts = jax.lax.psum(r.expert_counts, token_axes)
        return MoEOutput(out.astype(cfg.dtype), aux, z, counts)

    pspecs = {k: P("ep") if k != "gate_w" and not k.startswith("shared")
              else P() for k in params}
    fn = jax.shard_map(
        body, mesh=mesh,
        in_specs=(pspecs, P(token_axes, None), P()),
        out_specs=MoEOutput(P(token_axes, None), P(), P(), P()),
        check_vma=False,
    )
    out = fn(params, x, src_order)
    if interpret and not isinstance(out.out, jax.core.Tracer):
        # Eager interpret mode runs the kernel's DMAs on io_callback
        # threads that can still be draining when the caller dispatches
        # the next computation; JAX's interpreter can deadlock against
        # them (observed: combine-test thread stuck in
        # interpret_pallas_call store while the next trace blocks).
        # Synchronize before handing results back — debug mode only, and
        # a no-op under jit where out is a Tracer.
        jax.block_until_ready(out.out)
    return out
