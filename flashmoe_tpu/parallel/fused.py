"""Fused expert-parallel MoE: device-initiated all-to-all inside the kernel,
overlapped with the expert FFN — the FlashDMoE headline capability on TPU.

The reference fuses dispatch -> expert GEMMs -> combine-return into one
persistent CUDA kernel in which NVSHMEM puts carry expert payloads between
GPUs while tile processors compute (``csrc/include/flashmoe/moe/moe.cuh:
71-144``; transport in ``os/packet.cuh:207-259`` and
``os/processor/processor.cuh:711-751``; the in-kernel actor scheduler in
``os/scheduler.cuh``/``subscriber.cuh`` exists to keep SMs busy while
payloads are in flight).

On TPU the same capability is a single Pallas kernel per rank, shard_mapped
over the ``ep`` mesh axis:

  * phase 0 — a cross-device barrier (each rank signals every peer), the
    analogue of the symmetric-heap readiness the reference gets from
    collective allocation (``bootstrap.cuh:347-362``);
  * phase 1 — every rank starts ALL its outbound slab RDMAs at once
    (``make_async_remote_copy``, non-blocking — the analogue of
    ``nvshmem_putmem_signal_nbi``), staggered by rank so the ICI links are
    used all-to-all rather than all-to-one;
  * phase 2 — one grid step per source rank, in ring arrival order: wait
    that source's recv semaphore (the data-carrying signal of the
    reference's ``SignalPayload``), run the local experts' up/act/down
    GEMM chain on arrived rows, and RDMA the results back to the source.
    Compute overlaps the in-flight transfers of later slabs —
    payload-granularity overlap, which is the paper's core claim.  FOUR
    FFN schedules (:func:`_fused_schedule`): per-source streaming,
    per-source weights-resident, the arrival-batched default at
    ep >= 3 — own slab computed at step 0 while remote slabs fly, all
    remote slabs computed expert-major at the final step so each weight
    byte streams twice total instead of once per source (the round-5
    cost model showed the per-source schedules' d x weight re-streaming
    dominates every other byte at multi-chip scale — see BASELINE.md) —
    and the row-windowed ``rowwin`` schedule for experts too wide for
    any weights-once residency (mixtral's i=14336): weights stream in
    VMEM-sized K-windows, window-major / row-minor, partial sums parked
    in an HBM f32 accumulator, bounding weight traffic at ~2 streams
    total at the cost of per-window activation re-streaming (ISSUE 12 /
    ROADMAP item 4; tiles picked by the IO-aware chooser
    :func:`_rowwin_tiles`, overridable by measured ``fused_tiles``
    tuning entries);
  * phase 2.5 — in-kernel combine: result rows return via RDMA directly
    into a TOKEN-SORTED buffer (each occupied slab slot is pre-assigned
    the row ``token*k + j`` XLA-side, :func:`flashmoe_tpu.ops.dispatch.
    sorted_return_maps`), so after the drain the combine is one fully
    vectorized pass of ``k``-row segment-sums — no per-row scatter (the
    round-4 implementation accumulated S*K rows one dynamic-slice add at
    a time, estimated as expensive as the whole layer; VERDICT r4 #3).
    The cost moved from the VPU to the DMA engine: per-ROW return copies
    instead of per-tile, ~cap row-DMA issues per (source, expert) that
    overlap the next slab's GEMMs.  This is the reference's combine
    stage (``os/processor/processor.cuh:27-205``) with the atomicAdd
    replaced by disjoint pre-assigned rows + deterministic segment-sum.
    Opt-in via ``FLASHMOE_FUSED_COMBINE=1`` until hardware-benchmarked
    (the open question is per-row RDMA issue/landing efficiency on real
    ICI), requires ep > 1 (at world 1 there is no communication to
    overlap and the per-row copies are pure overhead), and falls back to
    the XLA combine when the maps/tiles would not fit VMEM/SMEM
    (:func:`_fuse_combine_enabled`).
  * phase 3 — drain: wait all remaining send semaphores (row-granular on
    the return path when the combine is fused), then run the combine
    segment-sum if fused.

Gate/plan/dispatch-layout stay in XLA (bandwidth-trivial next to the FFN);
the kernel owns the communication-heavy middle plus the combine.
Capacity-format slabs keep every shape static.

Design decision — why the send slabs are built XLA-side rather than
gathered in-kernel (the reference gathers from ``tokenIds`` inside the
kernel, ``packet.cuh:99-206``): the reference hides per-row staging
latency behind hundreds of concurrently-resident SM blocks; a TPU kernel
is one sequential instruction stream, and this kernel's phase 1 issues
every outbound RDMA up front so remote compute can start.  An in-kernel
row gather there would pay per-row DMA-issue latency serially before any
send departs (~50-100 ns x S*K rows, with no compute to hide behind),
whereas the XLA dispatch builds the same slabs at full VPU/HBM bandwidth
and the RDMAs then stream straight from HBM with no VMEM bounce.  The
single-device path, whose gather IS overlappable with the grid's own
GEMMs, does fuse it (``ops/expert.py:grouped_ffn_tokens``).

Layouts (D = ep world, nLx = local experts, C = per-(rank, expert) capacity):
  x_send  [D, nLx, C, H]  on each source rank: slab d holds tokens routed
                          to rank d's local experts (dest-major).
  x_recv  [D, nLx, C, H]  on each dest rank: slab s is written remotely by
                          source rank s (source-major).
  y_recv  [D, nLx, C, H]  back on the source rank: slab d holds results
                          from owner rank d — exactly the [E, C, H] combine
                          layout after reshape.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import Mesh, PartitionSpec as P

from flashmoe_tpu.config import MoEConfig
from flashmoe_tpu.utils.compat import axis_size, shard_map
from flashmoe_tpu.models.reference import activation_fn, shared_expert_ffn
from flashmoe_tpu.ops import dispatch as dsp
from flashmoe_tpu.ops import stats as st
from flashmoe_tpu.ops.gate import router
from flashmoe_tpu.ops.moe import MoEOutput
from flashmoe_tpu.parallel.ep import local_capacity
from flashmoe_tpu.profiler import spans as prof
from flashmoe_tpu.utils.telemetry import trace_span


def _fused_kernel(
    send_cnt, recv_cnt,                   # SMEM int32 [D, nLx] tile counts
    src_order,                            # SMEM int32 [D, D] processing order
    recv_pos,                             # SMEM int32 [D, nLx, cap] sorted
                                          #   return rows (None = XLA combine)
    w_sorted,                             # ANY [rows_pad, 1] f32 weights
    x_send, w_up, b_up, w_down, b_down,   # inputs (ANY/VMEM)
    wup_sc, wdn_sc,                       # VMEM f32 per-output-channel
                                          #   scales of a quantized
                                          #   weight store ([nLx, I or
                                          #   2I] / [nLx, H]; None at
                                          #   full precision)
    x_recv, y_back, y_stage, out,         # outputs (y_back: the [D,nLx,C,H]
                                          #   slab y_recv, or the token-sorted
                                          #   [rows_pad, H] return buffer when
                                          #   fusing; out: [s_out_pad, H] f32,
                                          #   None when combine stays in XLA)
    acc_hbm,                              # [D, nLx, C, H] f32 HBM partial
                                          #   sums of the rowwin window
                                          #   loop (None otherwise)
    xs_vmem, wup_vmem, wdn_vmem, acc, yv, # VMEM scratch (wdn/acc/yv are
                                          #   [2,bi,h]/[cm,h]/[cm,h] when
                                          #   streaming, [2,i,bh]/[cm,bh]/
                                          #   [cm,bh] on the resident/
                                          #   batched schedules)
    bup_vmem, bdn_vmem,                   # bias tiles
    ys_vmem, ws_vmem, ov_vmem,            # combine chunk tiles (None w/o
                                          #   fusion): y rows, weight col,
                                          #   out rows
    hid_vmem,                             # [n_i_chunks, n_srcs*cap, bi]
                                          #   resident hidden (None when
                                          #   streaming)
    copy_sems, send_x_sems, recv_x_sems, send_y_sems, recv_y_sems,
    *, axis, act_name, cm, bi, gated, fuse_combine, k, cu,
    schedule, bh, quant=False,
):
    """One grid step = one source slab (ring order).

    Transfers are tile-granular and count-aware: both sides share the
    routed-count matrices (exchanged XLA-side), so only row tiles that
    actually hold tokens are sent, waited on, computed, and returned —
    the TPU form of the reference's ``routedTokens``-sized packets and
    zero-token noop signals (``packet.cuh:99-259``), with the noop made
    unnecessary because counts are pre-shared.

    With ``fuse_combine`` the weighted un-permute also runs in-kernel
    (the reference's combine stage, ``processor.cuh:27-205``): result
    rows are returned by per-ROW RDMA into the destination rank's
    token-sorted buffer ``y_back`` at the pre-assigned row
    ``recv_pos[src, e, slot]`` (= token*k + j on the source), so the
    final combine is ``n_chunks`` vectorized ``k``-row segment-sums with
    zero per-row VPU work.  ``k`` is the top-k width, ``cu`` the number
    of output rows per combine chunk (both static).
    """
    s = pl.program_id(0)
    d_world = pl.num_programs(0)
    my = jax.lax.axis_index(axis)
    nlx, cap, h = x_send.shape[1], x_send.shape[2], x_send.shape[3]
    act = activation_fn(act_name)
    n_row_tiles = cap // cm
    n_i_chunks = w_down.shape[1] // bi

    def tiles_of(cnt):
        """Present row tiles for a (rank, expert) count."""
        return jax.lax.div(cnt + (cm - 1), cm)

    # ---- phase 0/1 (first step only): barrier, then start every send ----
    @pl.when(s == 0)
    def _():
        barrier = pltpu.get_barrier_semaphore()

        def signal_peer(d, c):
            @pl.when(d != my)
            def _():
                pltpu.semaphore_signal(
                    barrier, inc=1, device_id=d,
                    device_id_type=pltpu.DeviceIdType.LOGICAL,
                )
            return c

        jax.lax.fori_loop(0, d_world, signal_peer, 0)
        pltpu.semaphore_wait(barrier, d_world - 1)

        def send(step, c):
            dst = jax.lax.rem(my + step + 1, d_world)

            def per_expert(e, c2):
                nt = tiles_of(send_cnt[dst, e])

                # fast path: full expert block in one DMA descriptor when
                # every tile is present (semaphore waits count bytes, so
                # the decomposition on the wait side need not match)
                @pl.when(nt == n_row_tiles)
                def _():
                    pltpu.make_async_remote_copy(
                        src_ref=x_send.at[dst, e],
                        dst_ref=x_recv.at[my, e],
                        send_sem=send_x_sems.at[dst],
                        recv_sem=recv_x_sems.at[my],
                        device_id=dst,
                        device_id_type=pltpu.DeviceIdType.LOGICAL,
                    ).start()

                @pl.when(nt < n_row_tiles)
                def _():
                    def per_tile(t, c3):
                        @pl.when(t < nt)
                        def _():
                            pltpu.make_async_remote_copy(
                                src_ref=x_send.at[dst, e,
                                                  pl.ds(t * cm, cm), :],
                                dst_ref=x_recv.at[my, e,
                                                  pl.ds(t * cm, cm), :],
                                send_sem=send_x_sems.at[dst],
                                recv_sem=recv_x_sems.at[my],
                                device_id=dst,
                                device_id_type=pltpu.DeviceIdType.LOGICAL,
                            ).start()
                        return c3

                    jax.lax.fori_loop(0, n_row_tiles, per_tile, 0)
                return c2

            jax.lax.fori_loop(0, nlx, per_expert, 0)
            return c

        jax.lax.fori_loop(0, d_world - 1, send, 0)
        # own slab: plain local copy (full; local bandwidth is cheap)
        own = pltpu.make_async_copy(
            x_send.at[my], x_recv.at[my], copy_sems.at[0]
        )
        own.start()
        own.wait()

    # ---- phase 2: process source slabs in expected-arrival order ----
    # ``src_order[my]`` is a permutation of sources starting with ``my``
    # (the own slab is local and ready immediately).  The default is ring
    # order (src_order[r, s] = (r+s) mod D), which IS arrival order on a
    # homogeneous ICI torus because phase 1 staggers sends by ring
    # distance.  On heterogeneous fabrics (multi-slice: some sources
    # behind a DCN hop) the caller passes
    # :func:`flashmoe_tpu.parallel.topology.arrival_order`, which sorts
    # sources by predicted alpha-beta arrival time — the static
    # equivalent of the reference subscriber consuming packets in
    # whatever order they land (``os/subscriber.cuh:333-451``); Mosaic
    # semaphores have no try-wait, so the order is bound at trace time
    # from the measured topology instead of polled at run time.
    # Correctness never depends on the order: every slab's recv
    # semaphore is awaited before use (see scripts/skew_sim.py for the
    # quantified cost of a mispredicted order).
    src = src_order[my, s]

    @pl.when(s != 0)
    def _():
        # wait for exactly the tiles this source sent (tile-sized waits
        # against the data-carrying recv semaphore)
        def per_expert(e, c):
            def per_tile(t, c2):
                @pl.when(t < tiles_of(recv_cnt[src, e]))
                def _():
                    pltpu.make_async_copy(
                        x_recv.at[src, e, pl.ds(t * cm, cm), :],
                        x_recv.at[src, e, pl.ds(t * cm, cm), :],
                        recv_x_sems.at[src],
                    ).wait()
                return c2

            return jax.lax.fori_loop(0, n_row_tiles, per_tile, c)

        jax.lax.fori_loop(0, nlx, per_expert, 0)

    def expert_body(e, _):
        # stream this expert's biases once
        bup_dma = pltpu.make_async_copy(
            b_up.at[pl.ds(e, 1), :], bup_vmem, copy_sems.at[0]
        )
        bdn_dma = pltpu.make_async_copy(
            b_down.at[pl.ds(e, 1), :], bdn_vmem, copy_sems.at[1]
        )
        bup_dma.start(); bdn_dma.start()
        bup_dma.wait(); bdn_dma.wait()

        # gated mode: w_up holds [gate_chunk | up_chunk] interleaved on a
        # doubled chunk axis (see fused_ep_moe_layer), so one DMA streams
        # both halves of the SwiGLU
        up_chunk = 2 * bi if gated else bi

        # weight-chunk DMA descriptors, double-buffered over two VMEM slots
        # (sems 2+slot / 4+slot): chunk j+1 streams HBM->VMEM while chunk j
        # runs on the MXU — the reference's multistage cp.async operand
        # pipeline (``mmaConfig.cuh:19-171``) expressed as slot-alternating
        # async copies.
        def wu_dma(j, slot):
            return pltpu.make_async_copy(
                w_up.at[e, :, pl.ds(j * up_chunk, up_chunk)],
                wup_vmem.at[slot], copy_sems.at[2 + slot],
            )

        def wd_dma(j, slot):
            return pltpu.make_async_copy(
                w_down.at[e, pl.ds(j * bi, bi), :],
                wdn_vmem.at[slot], copy_sems.at[4 + slot],
            )

        def send_back(sq, t):
            """Return tile t of source ``sq``'s finished rows —
            tile-granular into the slab buffer, or per-ROW into the
            token-sorted buffer when the combine is fused (rows of one
            token land disjointly: pos = token*k + j is unique per slot,
            so there are no write conflicts to order).  Issued
            immediately after the rows exist; y_stage is indexed by the
            source, so later steps never overwrite a slab whose
            asynchronous return is still in flight."""
            if not fuse_combine:
                @pl.when(sq != my)
                def _():
                    pltpu.make_async_remote_copy(
                        src_ref=y_stage.at[sq, e, pl.ds(t * cm, cm), :],
                        dst_ref=y_back.at[my, e, pl.ds(t * cm, cm), :],
                        send_sem=send_y_sems.at[sq],
                        recv_sem=recv_y_sems.at[my],
                        device_id=sq,
                        device_id_type=pltpu.DeviceIdType.LOGICAL,
                    ).start()
            else:
                rows_here = jnp.minimum(cm, recv_cnt[sq, e] - t * cm)

                @pl.when(sq != my)
                def _():
                    def ret_row(r, c3):
                        @pl.when(r < rows_here)
                        def _():
                            pos = recv_pos[sq, e, t * cm + r]
                            pltpu.make_async_remote_copy(
                                src_ref=y_stage.at[sq, e,
                                                   pl.ds(t * cm + r, 1), :],
                                dst_ref=y_back.at[pl.ds(pos, 1), :],
                                send_sem=send_y_sems.at[sq],
                                recv_sem=recv_y_sems.at[my],
                                device_id=sq,
                                device_id_type=pltpu.DeviceIdType.LOGICAL,
                            ).start()
                        return c3

                    jax.lax.fori_loop(0, cm, ret_row, 0)

                @pl.when(sq == my)
                def _():
                    def ret_row_local(r, c3):
                        @pl.when(r < rows_here)
                        def _():
                            pos = recv_pos[sq, e, t * cm + r]
                            pltpu.make_async_copy(
                                y_stage.at[sq, e, pl.ds(t * cm + r, 1), :],
                                y_back.at[pl.ds(pos, 1), :],
                                recv_y_sems.at[my],
                            ).start()
                        return c3

                    jax.lax.fori_loop(0, cm, ret_row_local, 0)

        def row_tile_body(t, carry):
            xd = pltpu.make_async_copy(
                x_recv.at[src, e, pl.ds(t * cm, cm), :],
                xs_vmem, copy_sems.at[0],
            )
            xd.start()
            wu_dma(0, 0).start()
            wd_dma(0, 0).start()
            xd.wait()
            acc[:] = jnp.zeros_like(acc)

            def chunk_body(j, carry_c):
                slot = jax.lax.rem(j, 2)

                @pl.when(j + 1 < n_i_chunks)
                def _prefetch():
                    wu_dma(j + 1, 1 - slot).start()
                    wd_dma(j + 1, 1 - slot).start()

                wu_dma(j, slot).wait()
                if gated:
                    g = jnp.dot(
                        xs_vmem[:], wup_vmem[slot, :, :bi],
                        preferred_element_type=jnp.float32,
                    )
                    up = jnp.dot(
                        xs_vmem[:], wup_vmem[slot, :, bi:],
                        preferred_element_type=jnp.float32,
                    ) + bup_vmem[0, pl.ds(j * bi, bi)].astype(jnp.float32)
                    hidden = (act(g) * up).astype(xs_vmem.dtype)
                else:
                    up = jnp.dot(
                        xs_vmem[:], wup_vmem[slot],
                        preferred_element_type=jnp.float32,
                    ) + bup_vmem[0, pl.ds(j * bi, bi)].astype(jnp.float32)
                    hidden = act(up).astype(xs_vmem.dtype)
                wd_dma(j, slot).wait()
                acc[:] += jnp.dot(
                    hidden, wdn_vmem[slot],
                    preferred_element_type=jnp.float32,
                )
                return carry_c

            jax.lax.fori_loop(0, n_i_chunks, chunk_body, 0)
            yv[:] = (
                acc[:] + bdn_vmem[0].astype(jnp.float32)
            ).astype(yv.dtype)
            st = pltpu.make_async_copy(
                yv, y_stage.at[src, e, pl.ds(t * cm, cm), :], copy_sems.at[0]
            )
            st.start()
            st.wait()
            send_back(src, t)
            return carry

        def resident_expert(first_q, n_srcs):
            """Weights-once two-pass schedule over the sources
            ``src_order[my, first_q : first_q + n_srcs]`` — each weight
            byte streams exactly once for ALL their rows (the reference's
            operand-pipeline reuse, ``mmaConfig.cuh:19-171``, applied
            across row tiles AND sources):

              pass 1  w_up chunk j resident (double-buffered) -> every
                      present row tile of every source streams through
                      it; activated hidden chunks land in the chunk-major
                      VMEM slab ``hid_vmem [n_i_chunks, n_srcs*cap, bi]``
                      (chunk-major so writes index a leading dim — Mosaic
                      restricts dynamic LANE offsets, not major-dim ones).
              pass 2  w_down COLUMN chunk c ([i, bh]) resident -> each
                      row tile contracts its resident hidden against it
                      chunk-by-chunk; output block written once, no
                      cross-chunk accumulator in HBM.

            Used two ways: per-source (``n_srcs=1``; kills the
            n_row_tiles x weight factor, VERDICT r4 weak #4) and
            arrival-batched over all remote sources at the final grid
            step (``n_srcs=d-1``; kills the per-source d x weight factor
            the round-5 cost model exposed — the schedule that makes the
            fused path competitive at multi-chip scale).  The trade: x
            re-streams once per i-chunk, and returns are issued per tile
            only after pass 2 (a tile's rows complete once every column
            chunk lands), so return overlap degrades to per-expert
            granularity — both priced in flashmoe_tpu/analysis.py."""
            n_h_chunks = h // bh

            def src_of(q):
                return src_order[my, first_q + q]

            def wdc_dma(c, slot):
                return pltpu.make_async_copy(
                    w_down.at[e, :, pl.ds(c * bh, bh)],
                    wdn_vmem.at[slot], copy_sems.at[4 + slot],
                )

            # ---- pass 1: up/act, weight-chunk outer, hidden resident ----
            wu_dma(0, 0).start()

            def up_chunk_body(j, carry_c):
                slot = jax.lax.rem(j, 2)

                @pl.when(j + 1 < n_i_chunks)
                def _prefetch():
                    wu_dma(j + 1, 1 - slot).start()

                wu_dma(j, slot).wait()

                def src_body(q, c1):
                    sq = src_of(q)
                    ntq = tiles_of(recv_cnt[sq, e])

                    def tile_body(t, c2):
                        @pl.when(t < ntq)
                        def _():
                            xd = pltpu.make_async_copy(
                                x_recv.at[sq, e, pl.ds(t * cm, cm), :],
                                xs_vmem, copy_sems.at[0],
                            )
                            xd.start()
                            xd.wait()
                            if gated:
                                g = jnp.dot(
                                    xs_vmem[:], wup_vmem[slot, :, :bi],
                                    preferred_element_type=jnp.float32,
                                )
                                up = jnp.dot(
                                    xs_vmem[:], wup_vmem[slot, :, bi:],
                                    preferred_element_type=jnp.float32,
                                ) + bup_vmem[0, pl.ds(j * bi, bi)].astype(
                                    jnp.float32)
                                hidden = (act(g) * up).astype(
                                    xs_vmem.dtype)
                            else:
                                up = jnp.dot(
                                    xs_vmem[:], wup_vmem[slot],
                                    preferred_element_type=jnp.float32,
                                ) + bup_vmem[0, pl.ds(j * bi, bi)].astype(
                                    jnp.float32)
                                hidden = act(up).astype(xs_vmem.dtype)
                            hid_vmem[j, pl.ds(q * cap + t * cm, cm), :] = \
                                hidden
                        return c2

                    return jax.lax.fori_loop(0, n_row_tiles, tile_body, c1)

                jax.lax.fori_loop(0, n_srcs, src_body, 0)
                return carry_c

            jax.lax.fori_loop(0, n_i_chunks, up_chunk_body, 0)

            # ---- pass 2: down proj, output-column chunks, wd once ----
            wdc_dma(0, 0).start()

            def col_body(c, carry_c):
                slot = jax.lax.rem(c, 2)

                @pl.when(c + 1 < n_h_chunks)
                def _prefetch():
                    wdc_dma(c + 1, 1 - slot).start()

                wdc_dma(c, slot).wait()

                def src_body(q, c1):
                    sq = src_of(q)
                    ntq = tiles_of(recv_cnt[sq, e])

                    def tile_body(t, c2):
                        @pl.when(t < ntq)
                        def _():
                            acc[:] = jnp.zeros_like(acc)

                            def contract(j, c3):
                                acc[:] += jnp.dot(
                                    hid_vmem[j,
                                             pl.ds(q * cap + t * cm, cm),
                                             :],
                                    wdn_vmem[slot, pl.ds(j * bi, bi), :],
                                    preferred_element_type=jnp.float32,
                                )
                                return c3

                            jax.lax.fori_loop(0, n_i_chunks, contract, 0)
                            yv[:] = (
                                acc[:]
                                + bdn_vmem[0, pl.ds(c * bh, bh)].astype(
                                    jnp.float32)
                            ).astype(yv.dtype)
                            st = pltpu.make_async_copy(
                                yv,
                                y_stage.at[sq, e, pl.ds(t * cm, cm),
                                           pl.ds(c * bh, bh)],
                                copy_sems.at[0],
                            )
                            st.start()
                            st.wait()
                        return c2

                    return jax.lax.fori_loop(0, n_row_tiles, tile_body, c1)

                jax.lax.fori_loop(0, n_srcs, src_body, 0)
                return carry_c

            jax.lax.fori_loop(0, n_h_chunks, col_body, 0)

            # ---- returns: every column chunk of a tile has landed ----
            def src_ret(q, c1):
                sq = src_of(q)
                ntq = tiles_of(recv_cnt[sq, e])

                def ret_tile(t, c2):
                    @pl.when(t < ntq)
                    def _():
                        send_back(sq, t)
                    return c2

                return jax.lax.fori_loop(0, n_row_tiles, ret_tile, c1)

            jax.lax.fori_loop(0, n_srcs, src_ret, 0)

        def rowwin_expert(first_q, n_srcs):
            """Row-windowed K-streamed schedule, WINDOW-major / row-minor
            (ISSUE 12 / ROADMAP item 4; SonicMoE's IO-aware stance,
            arXiv 2512.14080): the expert's weights stream along the
            intermediate dimension in ``bi``-wide VMEM windows (w_up
            columns + the matching w_down rows, double-buffered), and
            every present row tile of EVERY source in the pass flows
            through the resident window before the next is fetched —
            so each weight element streams once per pass, bounding
            weight traffic at ~2 streams total (own-slab pass at step 0
            + the arrival-batched remote pass at the final step)
            regardless of d or the row-tile count.  This is exactly the
            loop order BASELINE.md's round-5 caveat said naive
            row-windowing misses: a ROW-major window loop re-streams
            every window per row tile and degenerates to the stream
            schedule's bytes.

            The price is per-window activation re-streaming: each row
            tile re-reads its x tile per window and round-trips its f32
            partial sum through the HBM accumulator ``acc_hbm`` at every
            interior window boundary (the [cm, h] f32 state of ALL
            resident rows can never be VMEM-resident at the shapes this
            schedule exists for) — both priced in
            flashmoe_tpu/analysis.py.  The final window folds in the
            down bias, stages the finished tile, and issues its return
            immediately (per-TILE return granularity — finer than the
            batched schedule's per-expert returns)."""
            def src_of(q):
                return src_order[my, first_q + q]

            wu_dma(0, 0).start()
            wd_dma(0, 0).start()

            def win_body(j, carry_c):
                slot = jax.lax.rem(j, 2)

                @pl.when(j + 1 < n_i_chunks)
                def _prefetch():
                    wu_dma(j + 1, 1 - slot).start()
                    wd_dma(j + 1, 1 - slot).start()

                wu_dma(j, slot).wait()
                wd_dma(j, slot).wait()

                # quantized store (MoEConfig.expert_quant): the window
                # buffers hold int8/e4m3 payloads straight off HBM —
                # dequantize IN VMEM against the resident per-output-
                # channel f32 scales (w_up's channels are this window's
                # K columns; w_down's are the full H row), then compute
                # at the activation dtype exactly like the raw path.
                if quant:
                    up_cols = 2 * bi if gated else bi
                    wu_win = (
                        wup_vmem[slot].astype(jnp.float32)
                        * wup_sc[e, pl.ds(j * up_cols, up_cols)][None, :]
                    ).astype(xs_vmem.dtype)
                    wd_win = (
                        wdn_vmem[slot].astype(jnp.float32)
                        * wdn_sc[e, :][None, :]
                    ).astype(xs_vmem.dtype)
                else:
                    wu_win = wup_vmem[slot]
                    wd_win = wdn_vmem[slot]

                def src_body(q, c1):
                    sq = src_of(q)
                    ntq = tiles_of(recv_cnt[sq, e])

                    def tile_body(t, c2):
                        @pl.when(t < ntq)
                        def _():
                            xd = pltpu.make_async_copy(
                                x_recv.at[sq, e, pl.ds(t * cm, cm), :],
                                xs_vmem, copy_sems.at[0],
                            )
                            xd.start()

                            # resume this tile's partial sum (interior
                            # windows; window 0 starts from zero)
                            @pl.when(j > 0)
                            def _resume():
                                ad = pltpu.make_async_copy(
                                    acc_hbm.at[sq, e,
                                               pl.ds(t * cm, cm), :],
                                    acc, copy_sems.at[1],
                                )
                                ad.start()
                                ad.wait()

                            @pl.when(j == 0)
                            def _zero():
                                acc[:] = jnp.zeros_like(acc)

                            xd.wait()
                            if gated:
                                g = jnp.dot(
                                    xs_vmem[:], wu_win[:, :bi],
                                    preferred_element_type=jnp.float32,
                                )
                                up = jnp.dot(
                                    xs_vmem[:], wu_win[:, bi:],
                                    preferred_element_type=jnp.float32,
                                ) + bup_vmem[0, pl.ds(j * bi, bi)].astype(
                                    jnp.float32)
                                hidden = (act(g) * up).astype(
                                    xs_vmem.dtype)
                            else:
                                up = jnp.dot(
                                    xs_vmem[:], wu_win,
                                    preferred_element_type=jnp.float32,
                                ) + bup_vmem[0, pl.ds(j * bi, bi)].astype(
                                    jnp.float32)
                                hidden = act(up).astype(xs_vmem.dtype)
                            acc[:] += jnp.dot(
                                hidden, wd_win,
                                preferred_element_type=jnp.float32,
                            )

                            # interior windows park the partial sum in
                            # HBM; the last window finishes the tile and
                            # returns it immediately
                            @pl.when(j + 1 < n_i_chunks)
                            def _spill():
                                sd = pltpu.make_async_copy(
                                    acc,
                                    acc_hbm.at[sq, e,
                                               pl.ds(t * cm, cm), :],
                                    copy_sems.at[1],
                                )
                                sd.start()
                                sd.wait()

                            @pl.when(j + 1 == n_i_chunks)
                            def _finish():
                                yv[:] = (
                                    acc[:] + bdn_vmem[0].astype(
                                        jnp.float32)
                                ).astype(yv.dtype)
                                st2 = pltpu.make_async_copy(
                                    yv,
                                    y_stage.at[sq, e,
                                               pl.ds(t * cm, cm), :],
                                    copy_sems.at[0],
                                )
                                st2.start()
                                st2.wait()
                                send_back(sq, t)
                        return c2

                    return jax.lax.fori_loop(0, n_row_tiles, tile_body,
                                             c1)

                jax.lax.fori_loop(0, n_srcs, src_body, 0)
                return carry_c

            jax.lax.fori_loop(0, n_i_chunks, win_body, 0)

        def rows_present(first_q, n_srcs):
            """Total routed rows this expert holds across the sources —
            gates the weight streams so empty (source-set, expert) pairs
            never pay them (skewed-routing holes)."""
            def add(q, acc2):
                return acc2 + recv_cnt[src_order[my, first_q + q], e]

            return jax.lax.fori_loop(0, n_srcs, add, 0)

        # only the row tiles the step's source(s) actually routed here
        # (tiles_of(cnt) <= n_row_tiles by construction: counts are clamped
        # to cap and cap % cm == 0)
        if schedule in ("batched", "rowwin"):
            # own slab at step 0 (overlapping remote arrivals), every
            # remote source batched at the final step with weights
            # streamed once per pass (VMEM-resident hidden for batched,
            # K-windowed with the HBM accumulator for rowwin)
            pass_fn = (resident_expert if schedule == "batched"
                       else rowwin_expert)

            @pl.when((s == 0) & (rows_present(0, 1) > 0))
            def _own():
                pass_fn(0, 1)

            @pl.when((s == d_world - 1)
                     & (rows_present(1, d_world - 1) > 0))
            def _remote():
                pass_fn(1, d_world - 1)
        elif schedule == "resident":
            @pl.when(rows_present(s, 1) > 0)
            def _nonempty():
                resident_expert(s, 1)
        else:
            jax.lax.fori_loop(0, tiles_of(recv_cnt[src, e]), row_tile_body,
                              0)
        return _

    if schedule in ("batched", "rowwin"):
        # intermediate steps only consume arrivals (phase-2 waits above);
        # the expert loop runs at the endpoints
        @pl.when((s == 0) | (s == d_world - 1))
        def _():
            jax.lax.fori_loop(0, nlx, expert_body, 0)
    else:
        jax.lax.fori_loop(0, nlx, expert_body, 0)

    if not fuse_combine:
        @pl.when(src == my)
        def _():
            own = pltpu.make_async_copy(
                y_stage.at[src], y_back.at[my], copy_sems.at[0]
            )
            own.start()
            own.wait()

    # ---- phase 3 (last step): drain all semaphores, then (if fused)
    # ---- combine the fully-landed token-sorted returns
    @pl.when(s == d_world - 1)
    def _():
        if not fuse_combine:
            def drain(d, c):
                @pl.when(d != my)
                def _():
                    def per_expert(e, c2):
                        def per_tile(t, c3):
                            # x sends I started toward d
                            @pl.when(t < tiles_of(send_cnt[d, e]))
                            def _():
                                pltpu.make_async_copy(
                                    x_send.at[d, e, pl.ds(t * cm, cm), :],
                                    x_send.at[d, e, pl.ds(t * cm, cm), :],
                                    send_x_sems.at[d],
                                ).wait()
                                # y tiles coming back from owner d (same
                                # predicate: they are the tiles I sent)
                                pltpu.make_async_copy(
                                    y_back.at[d, e, pl.ds(t * cm, cm), :],
                                    y_back.at[d, e, pl.ds(t * cm, cm), :],
                                    recv_y_sems.at[d],
                                ).wait()
                            # y sends I started toward source d
                            @pl.when(t < tiles_of(recv_cnt[d, e]))
                            def _():
                                pltpu.make_async_copy(
                                    y_stage.at[d, e, pl.ds(t * cm, cm), :],
                                    y_stage.at[d, e, pl.ds(t * cm, cm), :],
                                    send_y_sems.at[d],
                                ).wait()
                            return c3

                        return jax.lax.fori_loop(0, n_row_tiles, per_tile,
                                                 c2)

                    jax.lax.fori_loop(0, nlx, per_expert, 0)
                return c

            jax.lax.fori_loop(0, d_world, drain, 0)
        else:
            # Row-granular accounting mirrors the row-granular sends: the
            # wait refs only meter bytes, so a [1, H] wait per present row
            # consumes exactly one returned row's worth.
            row_wait = y_stage.at[0, 0, pl.ds(0, 1), :]

            def drain(d, c):
                def per_expert(e, c2):
                    @pl.when(d != my)
                    def _():
                        def per_tile(t, c3):
                            # x sends I started toward d
                            @pl.when(t < tiles_of(send_cnt[d, e]))
                            def _():
                                pltpu.make_async_copy(
                                    x_send.at[d, e, pl.ds(t * cm, cm), :],
                                    x_send.at[d, e, pl.ds(t * cm, cm), :],
                                    send_x_sems.at[d],
                                ).wait()
                            return c3

                        jax.lax.fori_loop(0, n_row_tiles, per_tile, 0)

                        # y rows I sent toward source d
                        def per_row_sy(r, c3):
                            @pl.when(r < recv_cnt[d, e])
                            def _():
                                pltpu.make_async_copy(
                                    row_wait, row_wait, send_y_sems.at[d],
                                ).wait()
                            return c3

                        jax.lax.fori_loop(0, cap, per_row_sy, 0)

                    # y rows owner d returned into my sorted buffer (for
                    # d == my these were local copies on the same sem)
                    def per_row_ry(r, c3):
                        @pl.when(r < send_cnt[d, e])
                        def _():
                            pltpu.make_async_copy(
                                row_wait, row_wait, recv_y_sems.at[d],
                            ).wait()
                        return c3

                    jax.lax.fori_loop(0, cap, per_row_ry, 0)
                    return c2

                jax.lax.fori_loop(0, nlx, per_expert, 0)
                return c

            jax.lax.fori_loop(0, d_world, drain, 0)

            # every contribution has landed: one vectorized pass of
            # k-row segment-sums over the token-sorted buffer.  Rows
            # whose weight is 0 (dropped assignments, padding) may hold
            # unwritten garbage — `where` SELECTS before multiplying so
            # NaN/inf garbage cannot leak through 0 * NaN.
            cr = cu * k
            n_chunks = out.shape[0] // cu

            def combine_chunk(c, carry):
                yd = pltpu.make_async_copy(
                    y_back.at[pl.ds(c * cr, cr), :], ys_vmem,
                    copy_sems.at[0],
                )
                wd = pltpu.make_async_copy(
                    w_sorted.at[pl.ds(c * cr, cr), :], ws_vmem,
                    copy_sems.at[1],
                )
                yd.start(); wd.start()
                yd.wait(); wd.wait()
                yw = jnp.where(
                    ws_vmem[:] != 0.0, ys_vmem[:].astype(jnp.float32), 0.0
                ) * ws_vmem[:]
                ov_vmem[:] = yw.reshape(cu, k, h).sum(axis=1)
                st = pltpu.make_async_copy(
                    ov_vmem, out.at[pl.ds(c * cu, cu), :], copy_sems.at[0]
                )
                st.start()
                st.wait()
                return carry

            jax.lax.fori_loop(0, n_chunks, combine_chunk, 0)


def _resolve_tiles(cap: int, h: int, i_dim: int, dtype_name: str,
                   fuse_combine: bool) -> tuple[int, int]:
    """Resolve the kernel's (cm row tile, bi weight chunk), measured
    overrides included.  Both the VMEM budget gate and the launch call
    this, so a tuning entry can never re-size the kernel past the budget
    that approved it (advisor r4 #1)."""
    # largest row tile that divides the capacity (callers pad cap to a
    # 32-multiple, so an awkward capacity degrades the tile size instead
    # of being rejected)
    cm = next((t for t in (256, 128, 64, 32, 16, 8) if cap % t == 0), None)
    if cm is None:
        raise ValueError(f"capacity {cap} not a multiple of 8 rows")
    # the combine accumulator claims VMEM, so cap the streamed weight
    # chunk lower when it is resident (see _fuse_combine_enabled)
    bi_cap = 256 if fuse_combine else (512 if cm <= 128 else 256)
    # measured per-generation overrides (flashmoe_tpu.tuning; the
    # reference's arch trait table, arch.cuh:95-222) — applied only when
    # they still divide the shapes they claim to match
    from flashmoe_tpu import tuning

    tuned = tuning.lookup("fused_ep", h=h, i=i_dim, dtype=dtype_name)
    if tuned.get("cm") and cap % tuned["cm"] == 0:
        cm = tuned["cm"]
    if tuned.get("bi_cap") and not fuse_combine:
        bi_cap = tuned["bi_cap"]
    return cm, min(bi_cap, i_dim)


def _weights_resident_choice(cap: int, h: int, i_dim: int, dt_size: int,
                             gated: bool, cm: int, bi: int,
                             fuse_combine: bool, k: int,
                             tuned: dict) -> tuple[bool, int | None]:
    """Static decision: hold every weight byte in VMEM exactly once across
    row tiles (the resident two-pass schedule in the kernel) vs re-stream
    weights per row tile.  Returns ``(enabled, bh)`` with ``bh`` the
    output-column chunk width.

    Heuristic crossover: weight bytes saved, ``(n_row_tiles-1) * wu_mult
    * h * i`` (wu_mult = 3 for gated: gate+up+down matrices), must exceed
    the x bytes added by pass 1's per-chunk re-reads,
    ``(n_i_chunks-1) * cap * h`` — and the hidden slab ``cap * i`` plus
    both weight chunk pairs must fit the VMEM budget.  A measured
    ``weights_resident`` entry in the tuning table (the reference's arch
    trait table mechanism, ``arch.cuh:95-222``) overrides the heuristic;
    the VMEM feasibility check is never overridable."""
    n_row_tiles = cap // cm
    if n_row_tiles <= 1:
        return False, None
    n_i_chunks = i_dim // bi
    if "weights_resident" in tuned:
        if not tuned["weights_resident"]:
            return False, None
    else:
        wu_mult = 3 if gated else 2
        saved = (n_row_tiles - 1) * wu_mult * h * i_dim
        extra = (n_i_chunks - 1) * cap * h
        if saved <= extra:
            return False, None
    ok, bh = _resident_budget_ok(cap, h, i_dim, dt_size, gated, cm, bi,
                                 fuse_combine, k, hid_rows=cap)
    return (ok, bh) if ok else (False, None)


def _resident_budget_ok(cap, h, i_dim, dt_size, gated, cm, bi,
                        fuse_combine, k, *, hid_rows):
    """VMEM feasibility of a resident-style two-pass with ``hid_rows``
    rows of hidden resident.  Returns (ok, bh)."""
    n_i_chunks = i_dim // bi
    bh = next((b for b in (256, 128, 64, 32, 16, 8) if h % b == 0), None)
    if bh is None:
        return False, None
    hid = n_i_chunks * hid_rows * bi * dt_size
    wu2 = 2 * h * (2 * bi if gated else bi) * dt_size
    wdc2 = 2 * i_dim * bh * dt_size
    tiles = cm * h * dt_size + cm * bh * (4 + dt_size)  # xs + acc + yv
    chunk = (_combine_chunk_rows(k) * k * (h * dt_size + 4)
             + _combine_chunk_rows(k) * h * 4) if fuse_combine else 0
    if hid + wu2 + wdc2 + tiles + chunk > 15 * 2**20:
        return False, None
    return True, bh


#: K-window width candidates of the row-windowed schedule, widest first
#: (wider window = fewer activation re-streams; the IO-aware chooser
#: maximizes it under the VMEM budget)
_KW_CANDIDATES = (2048, 1024, 512, 256, 128, 64, 32, 16, 8)


def _rowwin_budget_ok(cap: int, h: int, i_dim: int, dt_size: int,
                      gated: bool, cm: int, kw: int, fuse_combine: bool,
                      k: int, *, w_dt: int | None = None,
                      sc_bytes: float = 0.0) -> bool:
    """VMEM feasibility of the row-windowed schedule at (cm row tile,
    kw K-window): the double-buffered window pair (w_up [h, kw] — or
    [h, 2*kw] gated — plus w_down [kw, h]) + one x row tile + the f32
    partial-sum accumulator tile + the full-width output tile.  The
    cross-window state lives in HBM (``acc_hbm``), so — unlike the
    weights-once schedules — NOTHING here scales with the capacity or
    the source count: this is the schedule that stays feasible when the
    expert is simply bigger than VMEM (mixtral's i=14336).

    ``w_dt``: bytes per WEIGHT element in the window buffers (default =
    ``dt_size``).  Quantized expert storage (``MoEConfig.expert_quant``,
    flashmoe_tpu/quant/) streams int8/e4m3 slabs and dequantizes in
    VMEM, so its windows budget at 1 B/elem — which is exactly why the
    chooser re-solves to wider K-windows under quant; ``sc_bytes``
    charges the resident f32 scale arrays that ride along."""
    wdt = dt_size if w_dt is None else w_dt
    wu2 = 2 * h * (2 * kw if gated else kw) * wdt
    wd2 = 2 * kw * h * wdt
    tiles = cm * h * dt_size + cm * h * 4 + cm * h * dt_size  # xs+acc+yv
    bias = i_dim * 4 + h * 4
    chunk = (_combine_chunk_rows(k) * k * (h * dt_size + 4)
             + _combine_chunk_rows(k) * h * 4) if fuse_combine else 0
    return wu2 + wd2 + tiles + bias + chunk + sc_bytes <= 15 * 2**20


def rowwin_tile_candidates(cap: int, h: int, i_dim: int, dt_size: int,
                           gated: bool, fuse_combine: bool,
                           k: int, *,
                           w_dt: int | None = None,
                           sc_bytes: float = 0.0
                           ) -> list[tuple[int, int]]:
    """Every VMEM-feasible (cm row tile, kw K-window) pair of the
    rowwin schedule at this shape — THE candidate grid shared by the
    IO-aware chooser (:func:`_rowwin_tiles`), ``bench.py --tiles`` and
    ``tune_sweep.py --stage tiles`` (via
    :func:`rowwin_sweep_candidates`), and the contract tests, so the
    measured sweeps can never silently drift from the pairs the
    chooser can actually pick.  ``w_dt``/``sc_bytes``: quantized-store
    weight width + scale residency (:func:`_rowwin_budget_ok`)."""
    return [
        (cm, kw)
        for cm in (256, 128, 64, 32, 16, 8) if cap % cm == 0
        for kw in _KW_CANDIDATES if i_dim % kw == 0
        and _rowwin_budget_ok(cap, h, i_dim, dt_size, gated, cm, kw,
                              fuse_combine, k, w_dt=w_dt,
                              sc_bytes=sc_bytes)
    ]


def rowwin_sweep_candidates(cap: int, h: int, i_dim: int, dt_size: int,
                            gated: bool, fuse_combine: bool,
                            k: int, *,
                            w_dt: int | None = None,
                            sc_bytes: float = 0.0
                            ) -> list[tuple[int, int]]:
    """The measurement subset of :func:`rowwin_tile_candidates` the
    tiles sweeps time: ONE candidate per feasible K-window, at its
    widest feasible row tile.  cm moves no modeled HBM bytes (the
    chooser always prefers the widest feasible cm for whatever kw it
    picks), so per-kw best-cm covers every pair the analytic chooser
    can select while keeping a hardware sweep to a handful of timed
    points instead of the full grid."""
    best_cm: dict[int, int] = {}
    for cm, kw in rowwin_tile_candidates(cap, h, i_dim, dt_size, gated,
                                         fuse_combine, k, w_dt=w_dt,
                                         sc_bytes=sc_bytes):
        best_cm[kw] = max(best_cm.get(kw, 0), cm)
    return sorted(((cm, kw) for kw, cm in best_cm.items()),
                  key=lambda t: -t[1])


def _rowwin_tiles(cap: int, h: int, i_dim: int, dt_size: int,
                  dtype_name: str | None, gated: bool,
                  fuse_combine: bool, k: int, *,
                  w_dt: int | None = None,
                  sc_bytes: float = 0.0) -> tuple[int | None,
                                                  int | None]:
    """IO-aware (row tile, K-window) chooser for the rowwin schedule:
    among VMEM-feasible (cm, kw) pairs, minimize the schedule's modeled
    HBM traffic (the SonicMoE stance, arXiv 2512.14080: optimize bytes,
    not FLOPs).  Weight bytes are tile-independent — window-major order
    streams each window exactly once per pass — so the objective is the
    activation term the window loop re-streams: per K-window every
    resident row re-reads its x tile (``n_win * h * dt``) and
    round-trips its f32 partial sum at every interior window boundary
    (``(n_win - 1) * h * 8``).  Traffic falls monotonically with kw, so
    the chooser takes the widest feasible window and spends the VMEM
    that remains on the largest row tile (cm moves no HBM bytes; bigger
    tiles mean fewer DMA issues and better MXU occupancy).

    A measured ``fused_tiles`` tuning entry
    (:mod:`flashmoe_tpu.tuning`; swept by ``scripts/tune_sweep.py
    --stage tiles`` / ``bench.py --tiles``) overrides the analytic pick
    when it still divides the shapes — the VMEM gate is never
    overridable.  Returns ``(cm, kw)``, or ``(None, None)`` when no
    pair fits the budget."""
    best = None  # (modeled activation bytes/row, -cm, cm, kw)
    for cm, kw in rowwin_tile_candidates(cap, h, i_dim, dt_size, gated,
                                         fuse_combine, k, w_dt=w_dt,
                                         sc_bytes=sc_bytes):
        n_win = i_dim // kw
        bytes_per_row = n_win * h * dt_size + (n_win - 1) * h * 8
        cand = (bytes_per_row, -cm, cm, kw)
        if best is None or cand < best:
            best = cand
    if best is None:
        return None, None
    cm, kw = best[2], best[3]
    if dtype_name is not None:
        from flashmoe_tpu import tuning

        tuned = tuning.lookup("fused_tiles", h=h, i=i_dim,
                              dtype=dtype_name)
        tcm, tkw = tuned.get("cm"), tuned.get("kw")
        if (tcm and tkw and cap % tcm == 0 and i_dim % tkw == 0
                and _rowwin_budget_ok(cap, h, i_dim, dt_size, gated,
                                      tcm, tkw, fuse_combine, k,
                                      w_dt=w_dt, sc_bytes=sc_bytes)):
            cm, kw = tcm, tkw
    return cm, kw


def _rowwin_choice(cap: int, h: int, i_dim: int, dt_size: int,
                   dtype_name: str | None, gated: bool, cm_stream: int,
                   fuse_combine: bool, k: int, d_world: int,
                   tuned: dict, *,
                   w_dt: int | None = None,
                   sc_bytes: float = 0.0) -> tuple[bool, int | None]:
    """Static stream-vs-rowwin decision (both are the fallbacks when no
    weights-once schedule fits VMEM).  Byte crossover, per local
    expert: weight streams saved by row-windowing — stream pays
    ``d_world * n_row_tiles`` streams, rowwin pays one pass for the own
    slab plus one for the batched remotes — must exceed the activation
    re-streaming the window loop adds (x re-reads + f32 partial-sum
    round-trips over the ~``d_world * cap`` resident rows).  A measured
    ``rowwin`` bit in the ``fused_ep`` tuning entry overrides the
    heuristic; ``FLASHMOE_FUSED_ROWWIN=0`` disables outright; the VMEM
    gate (the chooser finding any feasible pair) is never overridable.
    Rowwin IS a batched-pass schedule (own slab at step 0, all remotes
    in one pass at the final grid step), so the batched kill-switches —
    ``FLASHMOE_FUSED_BATCHED=0`` and a measured ``batched: false``
    entry — disable the auto choice too: a caller who asked for
    per-source arrival processing must get it (a ``rowwin: true`` entry
    or ``MoEConfig.fused_schedule='rowwin'`` still forces past them).
    Returns ``(enabled, kw)``."""
    cm, kw = _rowwin_tiles(cap, h, i_dim, dt_size, dtype_name, gated,
                           fuse_combine, k, w_dt=w_dt,
                           sc_bytes=sc_bytes)
    if cm is None:
        return False, None
    if os.environ.get("FLASHMOE_FUSED_ROWWIN") == "0":
        return False, None
    knob = tuned.get("rowwin")
    if knob is False:
        return False, None
    if knob is not True and (
            os.environ.get("FLASHMOE_FUSED_BATCHED") == "0"
            or tuned.get("batched") is False):
        return False, None
    if knob is not True:
        n_row_tiles = cap // cm_stream
        passes = 2 if d_world > 1 else 1
        streams_saved = d_world * n_row_tiles - passes
        wu_mult = 3 if gated else 2
        # weight streams saved are priced at the STORED width: under a
        # quantized store (w_dt=1) the byte trade rowwin wins shrinks,
        # while the activation re-streaming it pays does not
        saved = (streams_saved * wu_mult * h * i_dim
                 * (dt_size if w_dt is None else w_dt))
        n_win = i_dim // kw
        rows = d_world * cap
        extra = rows * h * ((n_win - 1) * dt_size + (n_win - 1) * 8)
        if saved <= extra:
            return False, None
    return True, kw


def _fused_schedule(cap: int, h: int, i_dim: int, dt_size: int,
                    gated: bool, cm: int, bi: int, fuse_combine: bool,
                    k: int, d_world: int,
                    tuned: dict, *, dtype_name: str | None = None,
                    forced: str | None = None,
                    w_dt: int | None = None,
                    sc_bytes: float = 0.0) -> tuple[str, int | None]:
    """Static FFN-schedule choice for the fused kernel:

      batched    own slab at step 0, ALL remote slabs expert-major at the
                 final step with weights streamed once -> 2x weight HBM
                 traffic instead of the per-source d x (the round-5 cost
                 model's headline finding; see BASELINE.md).  Default at
                 d >= 3 when the (d-1)*cap-row hidden slab fits VMEM —
                 at d=2 the two schedules move identical weight bytes
                 and per-source keeps finer overlap.
      resident   per-source two-pass (kills the n_row_tiles x factor,
                 VERDICT r4 weak #4) when its byte trade wins.
      rowwin     row-windowed K-dim streaming, window-major / row-minor
                 (ISSUE 12 / ROADMAP item 4): expert weights stream in
                 VMEM-sized K-windows and every resident row tile —
                 batched across ALL the pass's source slabs, like the
                 arrival-batched schedule — passes through a window
                 before the next is fetched, partial sums parked in an
                 HBM f32 accumulator.  ~2 weight streams total
                 regardless of d, at the cost of per-window activation
                 re-streaming — the schedule that serves wide experts
                 (mixtral i=14336) whose hidden slab can never be VMEM
                 resident.  Chosen over stream when its byte trade wins
                 (:func:`_rowwin_choice`).
      stream     per-row-tile weight streaming (the round-<=4 schedule).

    ``FLASHMOE_FUSED_BATCHED=0`` or a ``batched: false`` tuning entry
    disables the batched schedule; a ``batched: true`` entry forces it
    past the d>=3 heuristic (never past the VMEM gate).  ``rowwin``
    tuning bits / ``FLASHMOE_FUSED_ROWWIN=0`` gate rowwin the same way.

    ``forced`` (``MoEConfig.fused_schedule``) pins the schedule; a
    forced schedule still faces the hard VMEM gate — ValueError with
    the reason rather than an infeasible launch.  The second return
    value is the output-column chunk ``bh`` for batched/resident, the
    K-window ``kw`` for rowwin, None for stream."""
    if forced is not None:
        if forced == "stream":
            return "stream", None
        if forced in ("batched", "resident"):
            if forced == "batched" and d_world < 2:
                raise ValueError(
                    "fused_schedule='batched' needs an ep world of >= 2 "
                    "ranks (there is no remote batch at d_world=1)")
            hid_rows = ((d_world - 1) * cap if forced == "batched"
                        else cap)
            ok, bh = _resident_budget_ok(
                cap, h, i_dim, dt_size, gated, cm, bi, fuse_combine, k,
                hid_rows=hid_rows)
            if not ok:
                raise ValueError(
                    f"fused_schedule={forced!r} is VMEM-infeasible at "
                    f"this shape: the {hid_rows}-row hidden slab plus "
                    f"the double-buffered weight chunks exceed the "
                    f"budget (see BASELINE.md; 'rowwin' or 'stream' "
                    f"stay feasible)")
            return forced, bh
        if forced == "rowwin":
            cmr, kwr = _rowwin_tiles(cap, h, i_dim, dt_size, dtype_name,
                                     gated, fuse_combine, k, w_dt=w_dt,
                                     sc_bytes=sc_bytes)
            if cmr is None:
                raise ValueError(
                    "fused_schedule='rowwin' is VMEM-infeasible at this "
                    "shape: no (row tile, K-window) pair fits the "
                    "window double-buffer + accumulator budget")
            return "rowwin", kwr
        raise ValueError(f"unknown fused schedule {forced!r}")
    knob = tuned.get("batched")
    env_off = os.environ.get("FLASHMOE_FUSED_BATCHED") == "0"
    want_batched = (knob if knob is not None
                    else (d_world >= 3 and not env_off))
    if want_batched and d_world >= 2 and not env_off:
        ok, bh = _resident_budget_ok(
            cap, h, i_dim, dt_size, gated, cm, bi, fuse_combine, k,
            hid_rows=(d_world - 1) * cap)
        if ok:
            return "batched", bh
    resident, bh = _weights_resident_choice(
        cap, h, i_dim, dt_size, gated, cm, bi, fuse_combine, k, tuned)
    if resident:
        return "resident", bh
    rowwin, kw = _rowwin_choice(cap, h, i_dim, dt_size, dtype_name,
                                gated, cm, fuse_combine, k, d_world,
                                tuned, w_dt=w_dt, sc_bytes=sc_bytes)
    if rowwin:
        return "rowwin", kw
    return "stream", None


def schedule_table(cfg: MoEConfig, d_world: int, *,
                   fuse_combine: bool = False,
                   schedule: str | None = None) -> dict:
    """Public resolution of the fused kernel's execution geometry at
    ``(cfg, d_world)`` — THE single function behind the kernel launch,
    the byte model (``analysis._geom``), the planner's per-schedule
    feasibility rows, and the collective census, so no consumer can
    resolve a different geometry than the kernel actually runs (ISSUE
    12 satellite: the planner once imported the private helpers
    directly and could drift).

    ``schedule`` forces which schedule's geometry is REPORTED (the
    planner prices every schedule, not just the resolved one) without
    touching the resolution; None reports the resolved schedule's.
    ``cfg.fused_schedule`` is honored by the resolution; when the
    forced schedule is VMEM-infeasible the table falls back to the auto
    choice and records the reason under ``forced_infeasible`` (the
    LAUNCH path raises instead — see :func:`_fused_schedule`).

    Returns::

        schedule       the schedule the kernel would run
        priced         the schedule this table's geometry describes
                       (= ``schedule`` arg or the resolved one)
        feasible       {batched, resident, stream, rowwin}: hard VMEM
                       gates only (a schedule can be feasible yet not
                       chosen)
        cap, cap_raw   32-padded / raw per-(rank, expert) capacity
        cm, bi         row tile and weight-chunk width at ``priced``
                       (for rowwin, ``bi`` IS the K-window ``kw`` — the
                       IO-aware chooser's pick)
        kw             the K-window when ``priced == 'rowwin'``, None
                       otherwise
        n_row_tiles, n_i_chunks   derived loop extents (for rowwin,
                       ``n_i_chunks`` is the window count)
        s_loc, h, i, dt, gated    shared shape facts
        forced_infeasible         reason string, or None
    """
    from flashmoe_tpu import tuning

    s_loc = cfg.tokens // d_world
    h, i_dim = cfg.hidden_size, cfg.intermediate_size
    dt = jnp.dtype(cfg.dtype).itemsize
    name = jnp.dtype(cfg.dtype).name
    cap_raw = local_capacity(cfg, s_loc)
    cap = -(-cap_raw // 32) * 32
    cm, bi = _resolve_tiles(cap, h, i_dim, name, fuse_combine)
    gated = cfg.gated_ffn
    k = cfg.expert_top_k
    # quantized expert storage (MoEConfig.expert_quant): the rowwin
    # K-window streamer fetches int8/e4m3 slabs and dequantizes in
    # VMEM, so its window geometry re-solves at the QUANTIZED bytes
    # per element (wider feasible windows -> fewer HBM accumulator
    # round-trips), with the resident f32 scale arrays charged against
    # the budget.  The weights-once schedules boundary-dequantize
    # layer-side and keep pricing at the compute width.
    wdt, sc_bytes = _quant_geometry(cfg, d_world)
    tuned = tuning.lookup("fused_ep", h=h, i=i_dim, dtype=name)
    batched_ok = d_world >= 2 and _resident_budget_ok(
        cap, h, i_dim, dt, gated, cm, bi, fuse_combine, k,
        hid_rows=(d_world - 1) * cap)[0]
    resident_ok = cap // cm > 1 and _resident_budget_ok(
        cap, h, i_dim, dt, gated, cm, bi, fuse_combine, k,
        hid_rows=cap)[0]
    rw_cm, rw_kw = _rowwin_tiles(cap, h, i_dim, dt, name, gated,
                                 fuse_combine, k, w_dt=wdt,
                                 sc_bytes=sc_bytes)
    feasible = {"batched": batched_ok, "resident": resident_ok,
                "stream": True, "rowwin": rw_cm is not None}
    forced_infeasible = None
    try:
        resolved, _aux = _fused_schedule(
            cap, h, i_dim, dt, gated, cm, bi, fuse_combine, k, d_world,
            tuned, dtype_name=name, forced=cfg.fused_schedule,
            w_dt=wdt, sc_bytes=sc_bytes)
    except ValueError as e:
        forced_infeasible = str(e)
        resolved, _aux = _fused_schedule(
            cap, h, i_dim, dt, gated, cm, bi, fuse_combine, k, d_world,
            tuned, dtype_name=name, w_dt=wdt, sc_bytes=sc_bytes)
    priced = schedule if schedule is not None else resolved
    if priced not in feasible:
        raise ValueError(
            f"unknown fused schedule {priced!r}; choose from "
            f"{tuple(sorted(feasible))}")
    if priced == "rowwin" and rw_cm is not None:
        cm, bi = rw_cm, rw_kw
    return {
        "schedule": resolved, "priced": priced, "feasible": feasible,
        "cap": cap, "cap_raw": cap_raw, "cm": cm, "bi": bi,
        "kw": rw_kw if priced == "rowwin" else None,
        "n_row_tiles": cap // cm, "n_i_chunks": i_dim // bi,
        "s_loc": s_loc, "h": h, "i": i_dim, "dt": dt, "gated": gated,
        # bytes per weight element the ROWWIN streamer fetches (1 under
        # a quantized store, = dt otherwise); the weights-once
        # schedules stream boundary-dequantized compute-width weights
        "wdt": wdt if wdt is not None else dt,
        "forced_infeasible": forced_infeasible,
    }


def _quant_geometry(cfg: MoEConfig, d_world: int
                    ) -> tuple[int | None, float]:
    """(weight bytes/elem for the rowwin window buffers, resident
    scale-array VMEM bytes) under ``cfg.expert_quant`` — (None, 0.0)
    when quant is off, so every geometry resolution stays byte-
    identical to a pre-quant build."""
    if cfg.expert_quant is None:
        return None, 0.0
    from flashmoe_tpu.quant import core as qcore

    wdt = int(qcore.weight_itemsize(cfg.expert_quant, cfg.dtype))
    nlx = max(cfg.num_experts // max(d_world, 1), 1)
    chans = (2 if cfg.gated_ffn else 1) * cfg.intermediate_size \
        + cfg.hidden_size
    return wdt, float(nlx * chans * 4)


def schedule_metadata(cfg: MoEConfig, d_world: int, *,
                      fuse_combine: bool = False) -> dict:
    """Back-compat view of :func:`schedule_table`: ``{schedule,
    feasible, cap, cm, bi, n_row_tiles, n_i_chunks}`` — the keys PR-1
    consumers read.  New code should call :func:`schedule_table`, which
    adds the rowwin geometry and the forced-schedule surface."""
    t = schedule_table(cfg, d_world, fuse_combine=fuse_combine)
    return {k: t[k] for k in ("schedule", "feasible", "cap", "cm", "bi",
                              "n_row_tiles", "n_i_chunks")}


def _fused_shard(send_cnt, recv_cnt, src_order, x_send, w_up, b_up, w_down,
                 b_down, *,
                 cfg: MoEConfig, axis: str, interpret, collective_id: int,
                 detect_races: bool = False, w_gate=None,
                 recv_pos=None, w_sorted=None, cu: int | None = None,
                 wup_sc=None, wdn_sc=None, wg_sc=None):
    """Launch the fused kernel.  With ``recv_pos``/``w_sorted``/``cu`` the
    combine runs in-kernel and the call returns ``(out [s_out_pad, h] f32,
    y_sorted [rows_pad, h])``; otherwise it returns the slab ``y_recv``
    for the XLA combine.

    ``wup_sc``/``wdn_sc``/``wg_sc`` (``MoEConfig.expert_quant``): f32
    per-output-channel scales of a QUANTIZED weight store — ``w_up`` /
    ``w_down`` / ``w_gate`` then carry int8/e4m3 payloads.  When the
    resolved schedule is ``rowwin``, the K-window streamer fetches the
    quantized slabs and dequantizes in VMEM (geometry re-solved at 1
    B/elem); the weights-once schedules dequantize at this boundary
    instead (XLA-side — their VMEM residency is capacity-bound, not
    weight-width-bound) and launch exactly as at full precision."""
    d_world, nlx, cap, h = x_send.shape
    i_dim = w_down.shape[1]
    gated = w_gate is not None
    fuse_combine = recv_pos is not None
    k = cfg.expert_top_k
    quant = wup_sc is not None
    # one resolution of (cm, bi) shared with the combine budget gate, so
    # the VMEM estimate that approved the opt-in describes the kernel that
    # actually launches (advisor r4 #1)
    dt_name = jnp.dtype(x_send.dtype).name
    dt_size = jnp.dtype(x_send.dtype).itemsize
    cm, bi = _resolve_tiles(cap, h, i_dim, dt_name, fuse_combine)
    from flashmoe_tpu import tuning

    # per-K-GROUP scales always take the boundary-dequant path (the
    # in-kernel dequant is per-output-channel only), so their geometry
    # must budget at the COMPUTE width the kernel will actually stream
    grouped = quant and any(
        s is not None and s.shape[-2] != 1
        for s in (wup_sc, wdn_sc, wg_sc))
    w_dt, sc_bytes = (_quant_geometry(cfg, d_world)
                      if quant and not grouped else (None, 0.0))
    schedule, aux = _fused_schedule(
        cap, h, i_dim, dt_size, gated, cm, bi,
        fuse_combine, k, d_world,
        tuning.lookup("fused_ep", h=h, i=i_dim, dtype=dt_name),
        dtype_name=dt_name, forced=cfg.fused_schedule,
        w_dt=w_dt, sc_bytes=sc_bytes,
    )
    if quant and (schedule != "rowwin" or grouped):
        # weights-once schedules hold capacity-scaled hidden slabs, not
        # weight windows — dequantize at the boundary and launch the
        # unchanged full-precision kernel (the planner prices their
        # weight streams at the compute width for the same reason).
        # Per-K-GROUP scales take the same boundary path on rowwin too:
        # the in-kernel dequant is per-output-channel only.
        from flashmoe_tpu.quant import core as qcore

        w_up = qcore.dequantize_channelwise(w_up, wup_sc, cfg.dtype)
        w_down = qcore.dequantize_channelwise(w_down, wdn_sc, cfg.dtype)
        if gated:
            w_gate = qcore.dequantize_channelwise(w_gate, wg_sc,
                                                  cfg.dtype)
        quant = False
    bh = None
    if schedule == "rowwin":
        # the IO-aware chooser owns BOTH tiles on the rowwin schedule:
        # bi becomes the K-window width (aux == kw by construction), so
        # every bi-keyed mechanism below — the gated gate|up interleave,
        # the wu/wd window DMAs, the [2, bi, h] w_down slots — windows
        # the K dimension without a second code path
        cm, bi = _rowwin_tiles(cap, h, i_dim, dt_size, dt_name, gated,
                               fuse_combine, k, w_dt=w_dt,
                               sc_bytes=sc_bytes)
    else:
        bh = aux
    if i_dim % bi:
        raise ValueError(f"intermediate {i_dim} not divisible by {bi}")
    sc_args = None
    if gated:
        # interleave per-chunk: [nlx, H, nj*2*bi] as [gate_chunk | up_chunk]
        nj = i_dim // bi
        wg = w_gate.reshape(nlx, h, nj, bi)
        wu = w_up.reshape(nlx, h, nj, bi)
        w_up = jnp.concatenate([wg, wu], axis=-1).reshape(
            nlx, h, nj * 2 * bi
        )
        if quant:
            # scales interleave exactly like their payload columns
            sgp = wg_sc.reshape(nlx, nj, bi)
            sup = wup_sc.reshape(nlx, nj, bi)
            sc_args = (jnp.concatenate([sgp, sup], axis=-1).reshape(
                nlx, nj * 2 * bi).astype(jnp.float32),
                wdn_sc.reshape(nlx, h).astype(jnp.float32))
    elif quant:
        sc_args = (wup_sc.reshape(nlx, i_dim).astype(jnp.float32),
                   wdn_sc.reshape(nlx, h).astype(jnp.float32))

    unified = functools.partial(
        _fused_kernel, axis=axis, act_name=cfg.hidden_act, cm=cm, bi=bi,
        gated=gated, fuse_combine=fuse_combine, k=k, cu=cu,
        schedule=schedule, bh=bh, quant=quant,
    )
    out_shapes = [
        jax.ShapeDtypeStruct((d_world, nlx, cap, h), x_send.dtype),  # x_recv
    ]
    if fuse_combine:
        rows_pad = w_sorted.shape[0]
        if rows_pad % (cu * k):
            raise ValueError(
                f"sorted return rows {rows_pad} not a multiple of the "
                f"combine chunk {cu * k}")
        # token-sorted return buffer replaces the slab y_recv
        out_shapes.append(
            jax.ShapeDtypeStruct((rows_pad, h), x_send.dtype))
    else:
        out_shapes.append(
            jax.ShapeDtypeStruct((d_world, nlx, cap, h), x_send.dtype))
    out_shapes.append(
        jax.ShapeDtypeStruct((d_world, nlx, cap, h), x_send.dtype))  # y_stage
    any_spec = pl.BlockSpec(memory_space=pl.ANY)
    smem_spec = pl.BlockSpec(memory_space=pltpu.SMEM)
    in_specs = [smem_spec, smem_spec, smem_spec]
    inputs = [send_cnt, recv_cnt, src_order]
    out_specs = [any_spec, any_spec, any_spec]
    if fuse_combine:
        # recv_pos feeds scalar DMA addressing (SMEM); w_sorted streams
        # through a [cu*k, 1] scratch during the drain combine
        in_specs += [smem_spec, any_spec]
        inputs += [recv_pos, w_sorted.astype(jnp.float32)]
        out_shapes.append(
            jax.ShapeDtypeStruct((rows_pad // k, h), jnp.float32))  # out
        out_specs.append(any_spec)
    if schedule == "rowwin":
        # HBM f32 partial-sum accumulator of the window loop: scratch
        # that must persist across K-windows for EVERY resident row, so
        # it cannot live in VMEM (that infeasibility is the whole
        # reason this schedule exists) and Pallas scratch shapes are
        # VMEM/SMEM-only — it rides as an extra ANY-space output the
        # caller discards
        out_shapes.append(jax.ShapeDtypeStruct(
            (d_world, nlx, cap, h), jnp.float32))
        out_specs.append(any_spec)
    in_specs += [any_spec] * 5
    inputs += [x_send, w_up, b_up, w_down, b_down]
    if quant:
        # per-output-channel f32 scales: tiny ([nLx, I(+I)] + [nLx, H])
        # and read every window, so they live whole in VMEM
        in_specs += [pl.BlockSpec(memory_space=pltpu.VMEM)] * 2
        inputs += list(sc_args)

    # one generic wrapper splits the positional refs by the static layout
    # (inputs / outputs / scratch counts vary with fuse_combine and
    # weights_resident)
    def kernel(*refs):
        i0 = 0
        send_cnt_, recv_cnt_, src_order_ = refs[0:3]
        i0 = 3
        recv_pos_ = w_sorted_ = None
        if fuse_combine:
            recv_pos_, w_sorted_ = refs[3:5]
            i0 = 5
        xw = refs[i0:i0 + 5]
        i0 += 5
        wup_sc_ = wdn_sc_ = None
        if quant:
            wup_sc_, wdn_sc_ = refs[i0:i0 + 2]
            i0 += 2
        x_recv_, y_back_, y_stage_ = refs[i0:i0 + 3]
        i0 += 3
        out_ = None
        if fuse_combine:
            out_ = refs[i0]
            i0 += 1
        acc_hbm_ = None
        if schedule == "rowwin":
            acc_hbm_ = refs[i0]
            i0 += 1
        xs, wup, wdn, acc_, yv_, bup, bdn = refs[i0:i0 + 7]
        i0 += 7
        ys = ws = ov = hid = None
        if fuse_combine:
            ys, ws, ov = refs[i0:i0 + 3]
            i0 += 3
        if schedule in ("resident", "batched"):
            hid = refs[i0]
            i0 += 1
        unified(send_cnt_, recv_cnt_, src_order_, recv_pos_, w_sorted_,
                *xw, wup_sc_, wdn_sc_,
                x_recv_, y_back_, y_stage_, out_, acc_hbm_,
                xs, wup, wdn, acc_, yv_, bup, bdn, ys, ws, ov, hid,
                *refs[i0:])

    # streaming/rowwin schedules: wdn holds [bi, h] row chunks, acc/yv
    # full-width row tiles (for rowwin bi IS the K-window and the
    # cross-window acc state spills to the HBM accumulator above).
    # resident/batched schedules: wdn holds [i, bh] COLUMN chunks,
    # acc/yv are [cm, bh] output blocks, and the activated hidden
    # lives in the chunk-major hid slab (sized for one source per-source,
    # for all remote sources when batched).
    n_i_chunks = i_dim // bi
    two_pass = schedule in ("resident", "batched")
    scratch = [
        pltpu.VMEM((cm, h), x_send.dtype),        # xs
        # weight slots hold whatever streams from HBM: the compute
        # dtype at full precision, the int8/e4m3 payload under a
        # quantized store (w_up.dtype == x_send.dtype when quant off,
        # so the allocation is byte-identical to the pre-quant build)
        pltpu.VMEM((2, h, 2 * bi if gated else bi),
                   w_up.dtype),                   # w_up (+gate) 2 slots
        (pltpu.VMEM((2, i_dim, bh), w_down.dtype) if two_pass
         else pltpu.VMEM((2, bi, h), w_down.dtype)),  # w_down 2 slots
        pltpu.VMEM((cm, bh if two_pass else h),
                   jnp.float32),                  # acc
        pltpu.VMEM((cm, bh if two_pass else h),
                   x_send.dtype),                 # y tile / block
        pltpu.VMEM((1, i_dim), b_up.dtype),       # bias up
        pltpu.VMEM((1, h), b_down.dtype),         # bias down
    ]
    if fuse_combine:
        scratch.append(pltpu.VMEM((cu * k, h), x_send.dtype))  # y rows
        scratch.append(pltpu.VMEM((cu * k, 1), jnp.float32))   # weight col
        scratch.append(pltpu.VMEM((cu, h), jnp.float32))       # out rows
    if two_pass:
        hid_rows = (d_world - 1) * cap if schedule == "batched" else cap
        scratch.append(
            pltpu.VMEM((n_i_chunks, hid_rows, bi), x_send.dtype))  # hidden
    scratch += [
        pltpu.SemaphoreType.DMA((6,)),            # local copy + wt sems
        pltpu.SemaphoreType.DMA((d_world,)),      # send x
        pltpu.SemaphoreType.DMA((d_world,)),      # recv x
        pltpu.SemaphoreType.DMA((d_world,)),      # send y
        pltpu.SemaphoreType.DMA((d_world,)),      # recv y
    ]
    interp = False
    if interpret:
        # the interpreter's vector-clock race detector is the framework's
        # lock-free-protocol sanitizer (the reference relies on manual
        # fence discipline with no tooling — SURVEY §5).
        # FLASHMOE_INTERPRET_DMA=on_wait executes DMAs lazily at their
        # wait instead of on io_callback threads — slower-arrival
        # semantics, but immune to the interpreter's eager-thread
        # deadlocks (see fused_ep_moe_layer's interpret note).
        interp = pltpu.InterpretParams(
            dma_execution_mode=os.environ.get("FLASHMOE_INTERPRET_DMA",
                                              "eager"),
            detect_races=detect_races,
        )
    results = pl.pallas_call(
        kernel,
        grid=(d_world,),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shapes,
        scratch_shapes=scratch,
        compiler_params=pltpu.CompilerParams(
            has_side_effects=True, collective_id=collective_id,
        ),
        interpret=interp,
    )(*inputs)
    if schedule == "rowwin":
        results = results[:-1]  # drop the HBM accumulator scratch
    if fuse_combine:
        _, y_sorted, _, out = results
        return out, y_sorted
    _, y_recv, _ = results
    return y_recv


# ----------------------------------------------------------------------
# Differentiable core: Pallas forward, Pallas-GEMM backward
# ----------------------------------------------------------------------
#
# The kernel's dataflow is  x_send --a2a--> x_recv --FFN--> y_stage
# --a2a--> y_recv.  ``all_to_all(split=concat=0)`` is its own transpose,
# so the VJP re-exchanges the cotangents/primals with XLA collectives
# (cheap next to the FFN FLOPs) and runs every large GEMM — the
# pre-activation recompute, dHidden/dX, and both dW — through the Pallas
# grouped kernels (:func:`flashmoe_tpu.ops.expert.ffn_backward_core`).
# Expert shards are disjoint across ep ranks, so dW needs no psum.

@functools.partial(jax.custom_vjp, nondiff_argnums=(9, 10, 11, 12, 13))
def _fused_core(send_cnt, recv_cnt, src_order, x_send, w_up, b_up, w_down,
                b_down, w_gate, cfg, axis, interpret, collective_id,
                detect_races):
    return _fused_shard(
        send_cnt, recv_cnt, src_order, x_send, w_up, b_up, w_down, b_down,
        cfg=cfg, axis=axis, interpret=interpret,
        collective_id=collective_id, detect_races=detect_races,
        w_gate=w_gate,
    )


def _fused_core_fwd(send_cnt, recv_cnt, src_order, x_send, w_up, b_up,
                    w_down, b_down, w_gate, cfg, axis, interpret,
                    collective_id, detect_races):
    y = _fused_core(send_cnt, recv_cnt, src_order, x_send, w_up, b_up,
                    w_down, b_down, w_gate, cfg, axis, interpret,
                    collective_id, detect_races)
    return y, (send_cnt, recv_cnt, src_order, x_send, w_up, b_up, w_down,
               b_down, w_gate)


def _ffn_bwd_from_dy(cfg, axis, interpret, res, dy):
    """Shared backward tail: slab cotangent ``dy`` (of y_recv) -> gradients
    of (x_send, w_up, b_up, w_down, b_down, w_gate) via XLA re-exchange +
    Pallas grouped-GEMM backward kernels."""
    from flashmoe_tpu.ops.expert import (
        _auto_block, ffn_backward_core, grouped_matmul,
    )

    x_send, w_up, b_up, w_down, b_down, w_gate = res
    d, nlx, cap, h = x_send.shape
    gated = w_gate is not None

    a2a = functools.partial(
        jax.lax.all_to_all, axis_name=axis, split_axis=0, concat_axis=0,
        tiled=False,
    )
    x_recv = a2a(x_send)       # recompute received slabs (fwd exchange)
    dy_stage = a2a(dy)         # transpose of the return exchange

    def to_rows(t):            # [D, nlx, cap, h] -> [nlx*D*cap, h]
        return t.transpose(1, 0, 2, 3).reshape(nlx * d * cap, h)

    def from_rows(r):
        return r.reshape(nlx, d, cap, h).transpose(1, 0, 2, 3)

    xr = to_rows(x_recv)
    dyr = to_rows(dy_stage)
    bm = _auto_block(cap, 256)
    tiles_per_e = (d * cap) // bm
    gid = jnp.arange(nlx * tiles_per_e, dtype=jnp.int32) // tiles_per_e

    # recompute pre-activations through the Pallas grouped matmul
    i_dim = w_up.shape[2]
    u = grouped_matmul(xr, gid, w_up, block_m=bm, out_dtype=jnp.float32,
                       interpret=interpret)
    u = (u.reshape(nlx, d * cap, i_dim)
         + b_up[:, None, :].astype(jnp.float32)).reshape(-1, i_dim)
    g = None
    if gated:
        g = grouped_matmul(xr, gid, w_gate, block_m=bm,
                           out_dtype=jnp.float32, interpret=interpret)

    dxr, d_wu, d_bu, d_wd, d_bd, d_wg = ffn_backward_core(
        xr, gid, w_up, w_down, w_gate, u, g, dyr,
        act_name=cfg.hidden_act, gated=gated, block_m=bm,
        interpret=interpret,
    )
    d_x_send = a2a(from_rows(dxr.astype(x_send.dtype)))
    return (d_x_send,
            d_wu.astype(w_up.dtype), d_bu.astype(b_up.dtype),
            d_wd.astype(w_down.dtype), d_bd.astype(b_down.dtype),
            d_wg.astype(w_gate.dtype) if gated else None)


def _fused_core_bwd(cfg, axis, interpret, collective_id, detect_races,
                    res, dy):
    import numpy as np

    (send_cnt, recv_cnt, src_order, x_send, w_up, b_up, w_down, b_down,
     w_gate) = res
    grads = _ffn_bwd_from_dy(
        cfg, axis, interpret,
        (x_send, w_up, b_up, w_down, b_down, w_gate), dy,
    )
    f0 = lambda a: np.zeros(a.shape, jax.dtypes.float0)
    return (f0(send_cnt), f0(recv_cnt), f0(src_order)) + grads


_fused_core.defvjp(_fused_core_fwd, _fused_core_bwd)


# ----------------------------------------------------------------------
# Combine-fused core: the kernel also owns the weighted un-permute
# ----------------------------------------------------------------------
#
# Dataflow:  x_send --a2a--> x_recv --FFN--> y_stage --row RDMA to the
#            pre-assigned sorted rows--> y_sorted --k-row segment-sum-->
#            out[t] = sum_j w_sorted[t*k+j] * y_sorted[t*k+j].
# The VJP peels the combine analytically (each occupied slab slot's
# cotangent is dy[slot] = w_sorted[ret_pos[slot]] * dout[ret_pos[slot]
# // k]) and reuses the shared FFN backward.  w_sorted stays a
# differentiable input so router gradients flow through
# dsp.sorted_return_maps' scatter transpose; ret_pos (the source-side
# slot -> sorted-row map) rides along only for the backward.

@functools.partial(jax.custom_vjp,
                   nondiff_argnums=(12, 13, 14, 15, 16, 17))
def _fused_combine_core(send_cnt, recv_cnt, src_order, ret_pos, recv_pos,
                        w_sorted, x_send, w_up, b_up, w_down, b_down,
                        w_gate, cfg, axis, interpret, collective_id,
                        detect_races, cu):
    out, _ = _fused_shard(
        send_cnt, recv_cnt, src_order, x_send, w_up, b_up, w_down, b_down,
        cfg=cfg, axis=axis, interpret=interpret,
        collective_id=collective_id, detect_races=detect_races,
        w_gate=w_gate, recv_pos=recv_pos, w_sorted=w_sorted, cu=cu,
    )
    return out


def _fused_combine_core_fwd(send_cnt, recv_cnt, src_order, ret_pos,
                            recv_pos, w_sorted, x_send, w_up, b_up,
                            w_down, b_down, w_gate, cfg, axis, interpret,
                            collective_id, detect_races, cu):
    out, y_sorted = _fused_shard(
        send_cnt, recv_cnt, src_order, x_send, w_up, b_up, w_down, b_down,
        cfg=cfg, axis=axis, interpret=interpret,
        collective_id=collective_id, detect_races=detect_races,
        w_gate=w_gate, recv_pos=recv_pos, w_sorted=w_sorted, cu=cu,
    )
    return out, (send_cnt, recv_cnt, src_order, ret_pos, recv_pos,
                 w_sorted, x_send, w_up, b_up, w_down, b_down, w_gate,
                 y_sorted)


def _fused_combine_core_bwd(cfg, axis, interpret, collective_id,
                            detect_races, cu, res, dout):
    import numpy as np

    (send_cnt, recv_cnt, src_order, ret_pos, recv_pos, w_sorted, x_send,
     w_up, b_up, w_down, b_down, w_gate, y_sorted) = res
    d, nlx, cap, h = x_send.shape
    k = cfg.expert_top_k
    rows_pad = w_sorted.shape[0]

    dout = dout.astype(jnp.float32)            # [rows_pad // k, h]
    # combine transpose per slab slot: dy[slot] = w * dout[token], both
    # read through the slot's sorted row.  Unoccupied slots must be hard
    # zero (their y was never computed; their ret_pos is a placeholder).
    cnt = jnp.minimum(send_cnt, cap).astype(jnp.int32)  # [d, nlx]
    occupied = (
        jnp.arange(cap, dtype=jnp.int32)[None, None, :] < cnt[..., None]
    )
    w_slab = w_sorted[:, 0][ret_pos]           # [d, nlx, cap]
    dy = jnp.where(
        occupied[..., None],
        w_slab[..., None] * dout[ret_pos // k],
        0.0,
    ).astype(x_send.dtype)
    grads = _ffn_bwd_from_dy(
        cfg, axis, interpret,
        (x_send, w_up, b_up, w_down, b_down, w_gate), dy,
    )
    # d_w_sorted[r] = <dout[r // k], y_sorted[r]> on rows some occupied
    # slot returned into; other rows hold unwritten garbage whose
    # cotangent the sorted_return_maps scatter-transpose would drop, but
    # NaN garbage must not leak through intermediate arithmetic.
    occ_rows = (
        jnp.zeros(rows_pad + 1, jnp.bool_)
        .at[jnp.where(occupied, ret_pos, rows_pad).reshape(-1)].set(True)
    )[:rows_pad]
    tok_of_row = (
        jnp.arange(rows_pad, dtype=jnp.int32) // k
    )
    d_ws = jnp.where(
        occ_rows,
        jnp.einsum("rh,rh->r", dout[tok_of_row],
                   jnp.where(occ_rows[:, None],
                             y_sorted.astype(jnp.float32), 0.0)),
        0.0,
    )

    f0 = lambda a: np.zeros(a.shape, jax.dtypes.float0)
    return (f0(send_cnt), f0(recv_cnt), f0(src_order), f0(ret_pos),
            f0(recv_pos), d_ws[:, None]) + grads


_fused_combine_core.defvjp(_fused_combine_core_fwd, _fused_combine_core_bwd)


def _combine_chunk_rows(k: int) -> int:
    """Output rows per drain-combine chunk (static).  The chunk reads
    ``cu * k`` sorted y rows + writes ``cu`` output rows; shrink for wide
    top-k so the [cu*k, h] tile stays a modest VMEM slice."""
    return 128 if k <= 3 else 64


def _fuse_combine_budget_ok(cfg: MoEConfig, s_loc: int, h: int, i_dim: int,
                            cap: int) -> bool:
    """Memory feasibility of the in-kernel combine: the FFN streaming
    tiles + the drain combine chunks ([cu*k, h] y rows, [cu, h] f32 out
    rows) must fit VMEM, and the sorted-row map ``recv_pos`` ([E, cap]
    i32) must fit SMEM — it is a whole-array scalar-memory input, and a
    VMEM-only estimate let large E x capacity configs sail into Mosaic
    compile failures instead of the XLA-combine fallback (advisor
    round-3 #1).  The round-4 [s_pad, h] f32 VMEM accumulator is gone
    (the sorted-return restructure writes output chunks once), so the
    budget no longer scales with the local token count."""
    dt = jnp.dtype(cfg.dtype).itemsize
    # the same (cm, bi) resolution — tuning overrides included — that
    # _fused_shard will use for the launch (advisor r4 #1)
    cm, bi = _resolve_tiles(cap, h, i_dim, jnp.dtype(cfg.dtype).name, True)
    k = cfg.expert_top_k
    cu = _combine_chunk_rows(k)
    n_experts = cfg.num_experts
    weights = 2 * h * (2 * bi if cfg.gated_ffn else bi) * dt + 2 * bi * h * dt
    # xs, yv tiles (model dtype) + acc (f32)
    tiles = cm * h * (2 * dt + 4)
    # drain combine: y rows (dtype) + weight col + out rows (f32)
    chunk = cu * k * h * dt + cu * k * 4 + cu * h * 4
    # conservative SMEM budget: the sorted-row map plus the count matrices
    # must stay well under the ~1 MiB scalar memory of current TPU cores
    smem_bytes = n_experts * cap * 4 + 2 * n_experts * 4
    return (weights + tiles + chunk <= 15 * 2**20
            and smem_bytes <= 256 * 2**10)


def _fuse_combine_enabled(cfg: MoEConfig, s_loc: int, h: int, i_dim: int,
                          cap: int, d_world: int | None = None) -> bool:
    """Whether the weighted un-permute runs inside the RDMA kernel.

    OPT-IN (``FLASHMOE_FUSED_COMBINE=1``) until a hardware stage_bench
    row shows it beating the XLA combine: the sorted-return restructure
    (round 5) moved the cost from S*K sequential VPU row-adds to per-row
    return DMAs whose issue cost overlaps the FFN, but the DMA-engine
    behavior of thousands of [1, h] remote copies on real ICI is exactly
    the kind of question only a measurement answers — the same
    measured-before-default policy applied to the gather-fused kernel in
    round 3.  Requires a multi-rank ep world: at d_world == 1 there is no
    communication to overlap and the per-row copies are pure overhead
    over the XLA combine.  Even when requested, memory-infeasible configs
    fall back to the XLA combine (same math, no return-path overlap)
    rather than failing Mosaic compilation.
    """
    if os.environ.get("FLASHMOE_FUSED_COMBINE") != "1":
        return False
    if (d_world if d_world is not None else cfg.ep) <= 1:
        return False
    ok = _fuse_combine_budget_ok(cfg, s_loc, h, i_dim, cap)
    if not ok:
        import warnings
        warnings.warn(
            "FLASHMOE_FUSED_COMBINE=1 requested but the combine maps/"
            "chunks exceed the SMEM/VMEM budget; using the XLA "
            "combine instead", stacklevel=2)
    return ok


def fused_ep_moe_layer(params, x, cfg: MoEConfig, mesh: Mesh, *,
                       interpret: bool = False,
                       use_pallas_gate: bool | None = None,
                       token_axes: tuple[str, ...] = ("ep",),
                       collective_id: int = 7,
                       detect_races: bool = False,
                       src_order=None) -> MoEOutput:
    """Expert-parallel MoE with the fused in-kernel all-to-all.

    Same contract as :func:`flashmoe_tpu.parallel.ep.ep_moe_layer`.  Gated
    (SwiGLU) experts stream through the kernel with chunk-interleaved
    gate|up weights; shared experts run XLA-side on the local token shard
    (they are replicated dense compute, not communication).

    ``src_order`` ([D, D] int32; row r = the order in which rank r
    processes source slabs, starting with r itself) overrides the default
    ring schedule — pass :func:`flashmoe_tpu.parallel.topology.
    arrival_order` on heterogeneous fabrics so slow-linked sources are
    processed last instead of stalling earlier slabs (the reference's
    arrival-order subscriber, ``os/subscriber.cuh:333-451``, bound
    statically from the measured topology).
    """

    if cfg.wire_dtype or cfg.wire_dtype_combine:
        # config.py already rejects moe_backend='fused' + wire; this
        # guards DIRECT layer calls so a wire knob is never silently
        # ignored by the raw-slab RDMA transport
        raise ValueError(
            "fused_ep_moe_layer moves raw slabs in-kernel and cannot "
            "honor wire_dtype compression; use ep_moe_layer or "
            "ragged_ep_moe_layer")
    d_world = mesh.shape["ep"]
    if src_order is None:
        # a bootstrapped runtime on a heterogeneous fabric publishes its
        # arrival-order schedule (gated on this mesh's device ordering
        # actually matching the table's rank indexing); everywhere else
        # the ring default stands
        from flashmoe_tpu.runtime.bootstrap import current_src_order

        src_order = current_src_order(mesh, d_world)
    if src_order is None:
        from flashmoe_tpu.parallel.topology import default_ring

        src_order = jnp.asarray(default_ring(d_world))
    else:
        if src_order.shape != (d_world, d_world):
            raise ValueError(
                f"src_order must be [{d_world}, {d_world}] (one "
                f"processing order per ep rank), got {src_order.shape}")
        # a row that is not an own-first permutation would make the kernel
        # process a slab whose recv semaphore was never awaited (step 0)
        # or wait on the never-signaled own slab — a silent race or a
        # hang; src_order normally comes concrete from arrival_order, so
        # check it at trace time when possible
        try:
            so = __import__("numpy").asarray(src_order)
        except Exception:  # traced value: caller owns the invariant
            so = None
        if so is not None:
            for r in range(d_world):
                if so[r, 0] != r or sorted(so[r]) != list(range(d_world)):
                    raise ValueError(
                        f"src_order row {r} must be a permutation of "
                        f"0..{d_world - 1} starting with {r}, got "
                        f"{so[r].tolist()}")
        src_order = jnp.asarray(src_order, jnp.int32)

    def body(params, x, src_order):
        d = axis_size("ep")
        s_loc, h = x.shape
        nlx = cfg.num_experts // d
        cap = local_capacity(cfg, s_loc)
        # pad the capacity buffer to a row-tile multiple (e.g. CF=1.25 can
        # give cap=320 -> padded 320, cap=40 -> 64); counts stay clamped to
        # the real cap, so padded rows are never transferred or computed
        cap_pad = -(-cap // 32) * 32

        use_gate_pallas = (
            use_pallas_gate
            if use_pallas_gate is not None
            else (interpret or jax.default_backend() == "tpu")
        )
        # phase spans (telemetry.trace_span): the xprof counterpart of the
        # reference's NVTX "Flashmoe" domain — metadata only, no ops.
        # With cfg.profile_phases the spans also fence (prof.fence no-ops
        # on tracers) so the host phase timeline sees real durations.
        with trace_span("moe.gate"):
            r = router(x, params["gate_w"], cfg, use_pallas=use_gate_pallas,
                       interpret=interpret)
            if cfg.profile_phases:
                prof.fence(r)
        with trace_span("moe.dispatch"):
            plan = dsp.make_plan(r.expert_idx, cfg, cap)
            xbuf = dsp.dispatch(x.astype(cfg.dtype), plan, cfg, cap)
            if cap_pad != cap:
                xbuf = jnp.pad(xbuf, ((0, 0), (0, cap_pad - cap), (0, 0)))
            x_send = xbuf.reshape(d, nlx, cap_pad, h)
            if cfg.profile_phases:
                prof.fence(x_send)

        # routed-count matrices: what I send each (dest, expert) and what
        # each source sends my experts — shared knowledge on both ends, so
        # the kernel can skip absent tiles without noop signals
        send_cnt = jnp.minimum(plan.counts, cap).astype(jnp.int32).reshape(
            d, nlx
        )
        recv_cnt = jax.lax.all_to_all(
            send_cnt.reshape(d, 1, nlx), "ep", split_axis=0, concat_axis=0,
            tiled=False,
        ).reshape(d, nlx)

        quant_on = cfg.expert_quant is not None
        quant_err = None
        sc_kw = {}
        if quant_on:
            # quantized expert storage (flashmoe_tpu/quant/): the
            # kernel streams int8/e4m3 payloads (rowwin dequantizes in
            # VMEM; weights-once schedules dequantize at the
            # _fused_shard boundary).  Full-precision params quantize
            # in-graph first so the knob behaves identically whether
            # the state was stored quantized or not.  Inference-only
            # (config.py rejects is_training), so the custom-VJP
            # wrapper is bypassed below.
            from flashmoe_tpu import quant as qt

            if cfg.collect_stats:
                quant_err = qt.weight_quant_error(params, cfg)
            if not any(kk + qt.SCALE_SUFFIX in params
                       for kk in qt.QUANT_WEIGHT_KEYS):
                params = qt.quantize_ffn_params(params, cfg.expert_quant)
            w_args = (
                params["w_up"], params["b_up"],
                params["w_down"], params["b_down"],
                params.get("w_gate") if cfg.gated_ffn else None,
            )
            sc_kw = dict(
                wup_sc=params["w_up" + qt.SCALE_SUFFIX],
                wdn_sc=params["w_down" + qt.SCALE_SUFFIX],
                wg_sc=(params.get("w_gate" + qt.SCALE_SUFFIX)
                       if cfg.gated_ffn else None))
            if any(s is not None and s.shape[-2] != 1
                   for s in sc_kw.values()):
                # per-K-GROUP scales would boundary-dequantize here
                # while the planner prices the per-channel int8
                # streamer — a schedule/geometry the kernel never runs
                # (code-review finding).  Refuse instead of diverging.
                raise ValueError(
                    "the fused path supports per-OUTPUT-CHANNEL quant "
                    "scales only (quantize_state without group_size); "
                    "per-K-group states run on the collective/ragged "
                    "paths, or dequantize_state() + requantize "
                    "per-channel")
        else:
            # the same quant-off guard every layer path applies: a
            # quantized state must never astype raw payloads below
            from flashmoe_tpu.quant import ensure_unquantized

            ensure_unquantized(params)
            w_args = (
                params["w_up"].astype(cfg.dtype), params["b_up"],
                params["w_down"].astype(cfg.dtype), params["b_down"],
                (params["w_gate"].astype(cfg.dtype)
                 if cfg.gated_ffn else None),
            )
        i_dim = params["w_down"].shape[1]
        # tier-0 degradation needs the per-expert outputs BEFORE the
        # weighted combine, so the in-kernel (fused) combine is
        # incompatible with it — degrade forces the XLA combine branch
        # (same math, explicit ybuf).  A quantized store also keeps the
        # XLA combine: the sorted-return path has no quant arm.
        if (_fuse_combine_enabled(cfg, s_loc, h, i_dim, cap_pad, d)
                and not cfg.degrade_unhealthy_experts
                and not quant_on):
            kk = cfg.expert_top_k
            cu = _combine_chunk_rows(kk)
            rows_pad = -(-(s_loc * kk) // (cu * kk)) * (cu * kk)
            ret_pos, w_sorted = dsp.sorted_return_maps(
                plan, r.combine_weights, cfg, cap, rows_pad
            )
            if cap_pad != cap:
                ret_pos = jnp.pad(ret_pos, ((0, 0), (0, cap_pad - cap)))
            ret_pos = ret_pos.reshape(d, nlx, cap_pad)
            # each owner needs to know where its computed rows land in
            # every source's sorted buffer — the same exchange shape as
            # the count matrices
            recv_pos = jax.lax.all_to_all(
                ret_pos, "ep", split_axis=0, concat_axis=0, tiled=False,
            )
            with trace_span("moe.fused_kernel"):
                out = _fused_combine_core(
                    send_cnt, recv_cnt, src_order, ret_pos, recv_pos,
                    w_sorted[:, None], x_send, *w_args,
                    cfg, "ep", interpret, collective_id, detect_races, cu,
                )[:s_loc]
                if cfg.profile_phases:
                    prof.fence(out)
        else:
            with trace_span("moe.fused_kernel"):
                if quant_on:
                    # direct launch: the custom-VJP wrapper only exists
                    # for training, which config.py rejects under quant
                    y_recv = _fused_shard(
                        send_cnt, recv_cnt, src_order, x_send,
                        w_args[0], w_args[1], w_args[2], w_args[3],
                        cfg=cfg, axis="ep", interpret=interpret,
                        collective_id=collective_id,
                        detect_races=detect_races, w_gate=w_args[4],
                        **sc_kw)
                else:
                    y_recv = _fused_core(
                        send_cnt, recv_cnt, src_order, x_send, *w_args,
                        cfg, "ep", interpret, collective_id,
                        detect_races,
                    )
                if cfg.profile_phases:
                    prof.fence(y_recv)
            with trace_span("moe.combine"):
                ybuf = y_recv.reshape(cfg.num_experts, cap_pad, h)
                combine_w = r.combine_weights
                if cfg.degrade_unhealthy_experts:
                    # tier-0 (ops/health.py): same per-rank masking as the
                    # collective layer — ybuf rows are this rank's tokens'
                    # results per global expert
                    from flashmoe_tpu.ops import health as hlt

                    healthy = hlt.expert_health_capacity(ybuf)
                    ybuf, combine_w = hlt.degrade_outputs(
                        ybuf, combine_w, r.expert_idx, healthy)
                out = dsp.combine(ybuf, plan, combine_w, cfg, cap_pad)
                if cfg.profile_phases:
                    prof.fence(out)
        if cfg.num_shared_experts:
            out = out + shared_expert_ffn(
                x.astype(cfg.dtype), params, cfg
            ).astype(out.dtype)

        aux = jax.lax.pmean(r.aux_loss, token_axes) * cfg.aux_loss_coef
        z = jax.lax.pmean(r.z_loss, token_axes)
        counts = jax.lax.psum(r.expert_counts, token_axes)
        stats = None
        if cfg.collect_stats:
            # the fused kernel drops at the same capacity clamp (send_cnt
            # = min(counts, cap)), so the collective layer's stats math
            # applies verbatim
            local = st.moe_stats(r, cfg, cap)
            stats = st.reduce_stats(local, r.probs_mean, token_axes)
            if cfg.degrade_unhealthy_experts:
                from flashmoe_tpu.ops import health as hlt

                stats = hlt.attach_degradation(stats, healthy,
                                               r.expert_idx, token_axes)
            if quant_err is not None:
                stats = st.with_quant_error(stats, quant_err,
                                            token_axes)
        return MoEOutput(out.astype(cfg.dtype), aux, z, counts, stats)

    pspecs = {k: P("ep") if k != "gate_w" and not k.startswith("shared")
              else P() for k in params}
    stats_specs = (st.MoEStats(*([P()] * len(st.MoEStats._fields)))
                   if cfg.collect_stats else None)
    fn = shard_map(
        body, mesh=mesh,
        in_specs=(pspecs, P(token_axes, None), P()),
        out_specs=MoEOutput(P(token_axes, None), P(), P(), P(),
                            stats_specs),
        check_vma=False,
    )
    out = fn(params, x, src_order)
    if interpret and not isinstance(out.out, jax.core.Tracer):
        # Eager interpret mode runs the kernel's DMAs on io_callback
        # threads that can still be draining when the caller dispatches
        # the next computation; JAX's interpreter can deadlock against
        # them (observed: combine-test thread stuck in
        # interpret_pallas_call store while the next trace blocks).
        # Synchronize before handing results back — debug mode only, and
        # a no-op under jit where out is a Tracer.
        jax.block_until_ready(out.out)
    return out
