"""Device-mesh construction and sharding helpers.

The reference bootstraps its "mesh" dynamically: NVSHMEM init, pairwise
alpha-beta topology probing (``csrc/include/flashmoe/topo.cuh``), and the
Decider's DP x EP group formation (``os/decider/decider.cuh``).  On TPU the
interconnect geometry is a known torus exposed through
``jax.sharding.Mesh``; this module builds the standard
(dp, pp, ep, tp, sp) meshes and the canonical PartitionSpecs for MoE
parameters and activations.  Topology-aware *placement* (which expert on
which chip) remains a real decision for heterogeneous/multi-slice jobs and
lives in :mod:`flashmoe_tpu.parallel.decider`.
"""

from __future__ import annotations

import math
from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from flashmoe_tpu.config import MoEConfig

# Canonical mesh axis order: slowest-varying (DCN-adjacent) first.  dp and pp
# tolerate slow links; ep's all-to-all and tp's collectives want ICI
# neighbours, so they take the fastest-varying (innermost torus) axes.
AXES = ("dp", "pp", "ep", "tp", "sp")


def make_mesh(cfg: MoEConfig | None = None, *, dp=None, pp=None, ep=None,
              tp=None, sp=None, devices: Sequence | None = None) -> Mesh:
    """Build a Mesh over the available devices.

    Sizes default to the config's parallelism fields; any remaining factor
    of the device count folds into dp.
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    sizes = {
        "dp": dp if dp is not None else (cfg.dp if cfg else 1),
        "pp": pp if pp is not None else (cfg.pp if cfg else 1),
        "ep": ep if ep is not None else (cfg.ep if cfg else 1),
        "tp": tp if tp is not None else (cfg.tp if cfg else 1),
        "sp": sp if sp is not None else (cfg.sp if cfg else 1),
    }
    used = math.prod(sizes.values())
    if dp is None and n % used == 0:
        # dp not pinned by the caller: fold the leftover device factor in
        sizes["dp"] *= n // used
    elif n != used:
        raise ValueError(
            f"{n} devices don't match mesh {sizes}; pass devices= to "
            f"restrict, or leave dp unset to absorb the remainder"
        )
    shape = tuple(sizes[a] for a in AXES)
    arr = np.asarray(devices).reshape(shape)
    return Mesh(arr, AXES)


def moe_param_specs(cfg: MoEConfig) -> dict:
    """PartitionSpecs for MoE-layer parameters.

    Experts shard over ep; each expert's weight matrices shard over tp on
    the intermediate dimension (column-parallel up, row-parallel down —
    Megatron-style, so only one psum per FFN).
    """
    ep_ax = "ep" if cfg.ep > 1 else None
    tp_ax = "tp" if cfg.tp > 1 else None
    specs = {
        "gate_w": P(None, None),
        "w_up": P(ep_ax, None, tp_ax),
        "b_up": P(ep_ax, tp_ax),
        "w_down": P(ep_ax, tp_ax, None),
        "b_down": P(ep_ax, None),
    }
    if cfg.gated_ffn:
        specs["w_gate"] = P(ep_ax, None, tp_ax)
    if cfg.num_shared_experts:
        specs["shared_w_up"] = P(None, tp_ax)
        specs["shared_w_down"] = P(tp_ax, None)
        if cfg.gated_ffn:
            specs["shared_w_gate"] = P(None, tp_ax)
    return specs


def token_spec() -> P:
    """Activations: tokens shard over (dp, ep, sp) jointly, hidden replicated.

    Folding ep into the token axis is the GShard layout: each EP rank owns a
    distinct token shard, and the MoE all-to-all exchanges tokens *within*
    the ep axis.
    """
    return P(("dp", "ep", "sp"), None)


def shard_params(params, cfg: MoEConfig, mesh: Mesh):
    specs = moe_param_specs(cfg)
    return {
        k: jax.device_put(v, NamedSharding(mesh, specs[k]))
        for k, v in params.items()
    }


def transformer_param_specs(cfg: MoEConfig) -> dict:
    """PartitionSpecs for the full transformer parameter tree
    (:func:`flashmoe_tpu.models.transformer.init_params` layout).

    Attention projections are Megatron-style tp-split (columns for qkv,
    rows for the output projection); the LM head is column-parallel over
    the vocab; MoE experts shard over ep.
    """
    tp_ax = "tp" if cfg.tp > 1 else None
    layer = {
        "attn_norm": P(None),
        "ffn_norm": P(None),
        "wq": P(None, tp_ax),
        "wk": P(None, tp_ax),
        "wv": P(None, tp_ax),
        "wo": P(tp_ax, None),
        "moe": moe_param_specs(cfg),
    }
    dense_moe = moe_param_specs(
        cfg.replace(num_experts=1, expert_top_k=1, num_shared_experts=0, ep=1)
    )
    moe_set = set(cfg.moe_layer_indices)
    layers = [
        {**layer, "moe": layer["moe"] if li in moe_set else dense_moe}
        for li in range(cfg.num_layers)
    ]
    return {
        "embed": P(None, None),
        "final_norm": P(None),
        "lm_head": P(None, tp_ax),
        "layers": layers,
    }
