"""Overlap-efficiency measurement.

The reference's headline metric (``/root/reference/README.md:33-35``,
``plots/overlap_efficiency_8.png``) quantifies how much of the dispatch/
combine communication the fused kernel hides behind expert compute.  Here
the metric is defined operationally, on any ``ep`` mesh:

    overlap_efficiency = (t_compute_only + t_comm_only) / t_overlapped

  * ``t_overlapped``   — the full MoE layer on the measured path (fused
    Pallas RDMA kernel or the XLA-collective layer);
  * ``t_compute_only`` — the same layer with both all-to-alls elided
    (identical gate/dispatch/FFN/combine stages and shapes);
  * ``t_comm_only``    — the two all-to-alls alone on identically shaped
    slabs, with no FFN between them.

A value of 1.0 means fully serialized (no overlap); the upper bound
``(a+b)/max(a,b)`` (= 2.0 when legs are balanced) means one leg fully
hidden behind the other.  The same procedure runs on a real v5e-8 and on
the virtual 8-device CPU mesh (where it validates the harness, not the
hardware — XLA's CPU collectives are memcpys).

Timing uses chained in-jit iterations (two chain lengths, differenced)
because the tunneled TPU backend's ``block_until_ready`` does not
synchronize — see ``bench.py``.
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from flashmoe_tpu.config import MoEConfig
from flashmoe_tpu.utils.compat import axis_size, shard_map
from flashmoe_tpu.models.reference import init_moe_params
from flashmoe_tpu.parallel.ep import ep_moe_layer, local_capacity
from flashmoe_tpu.parallel.fused import fused_ep_moe_layer


def _comm_only(x, cfg: MoEConfig, mesh: Mesh, *, path: str = "collective"):
    """Both all-to-alls on path-shaped slabs, no compute between —
    capacity slabs for the collective/fused paths, routed-row slabs for
    the ragged path.  With ``cfg.a2a_chunks = n`` each leg runs as n
    smaller exchanges (the pipeline's wire schedule, per-message alpha
    included), so the comm leg measures what the chunked schedule
    actually pays."""
    n = cfg.a2a_chunks or 1

    def body(x):
        d = axis_size("ep")
        s_loc, h = x.shape
        if path == "ragged":
            # uniform-routing expectation: s_loc * k routed rows split
            # evenly over the d peers
            r = max(s_loc * cfg.expert_top_k // d, 1)
        else:
            r = (cfg.num_experts // d) * local_capacity(cfg, s_loc)
        rp = -(-r // n) * n  # rows per dest, padded to the chunk count
        src = (jnp.arange(d * rp, dtype=jnp.int32) % s_loc)
        slab = x[src].reshape(d, rp, h)
        outs = []
        for k in range(n):
            c = slab[:, k * (rp // n):(k + 1) * (rp // n)]
            c = jax.lax.all_to_all(
                c, "ep", split_axis=0, concat_axis=0, tiled=False
            )
            c = jax.lax.all_to_all(
                c, "ep", split_axis=0, concat_axis=0, tiled=False
            )
            outs.append(c)
        back = outs[0] if n == 1 else jnp.concatenate(outs, axis=1)
        # feed the payload back as the next chain input (data dependency —
        # nothing for XLA to dead-code-eliminate)
        return back.reshape(d * rp, h)[:s_loc]

    return shard_map(
        body, mesh=mesh, in_specs=P("ep", None), out_specs=P("ep", None),
        check_vma=False,
    )(x)


def _time_chained(fn, x, *, trials: int, chain: int):
    """Median seconds per application via two-chain-length differencing."""

    def chained(n):
        def run(x0):
            def step(c, _):
                return fn(c).astype(x0.dtype), None
            c, _ = jax.lax.scan(step, x0, None, length=n)
            return c.astype(jnp.float32).sum()
        return jax.jit(run)

    def median_time(f):
        float(f(x))  # compile + warm
        ts = []
        for _ in range(trials):
            t0 = time.perf_counter()
            float(f(x))
            ts.append(time.perf_counter() - t0)
        ts.sort()
        return ts[len(ts) // 2]

    t1 = median_time(chained(1))
    tn = median_time(chained(chain))
    return max(tn - t1, 1e-9) / (chain - 1)


def measure_overlap(cfg: MoEConfig, mesh: Mesh, *, path: str = "fused",
                    trials: int = 5, chain: int = 8,
                    interpret: bool = False, seed: int = 0,
                    a2a_chunks: int | None = None) -> dict:
    """Measure the three legs and the efficiency ratio on ``mesh``.

    ``path``: 'fused' (Pallas RDMA kernel), 'collective' (XLA layer) or
    'ragged' (dropless row exchanges).  ``a2a_chunks`` overrides
    ``cfg.a2a_chunks`` for the XLA transports — the chunked pipeline's
    measured efficiency is then directly comparable against
    :func:`chunked_overlap_bound`'s analytic one; the fused kernel
    ignores the knob (in-kernel per-slab overlap), so passing it with
    ``path='fused'`` is an error.
    Returns {t_overlapped_ms, t_compute_ms, t_comm_ms, overlap_efficiency}.
    """
    ep = mesh.shape["ep"]
    if cfg.num_experts % ep:
        raise ValueError(f"E={cfg.num_experts} not divisible by ep={ep}")
    if a2a_chunks is not None:
        if path == "fused":
            raise ValueError(
                "a2a_chunks applies to the XLA transports; the fused "
                "kernel overlaps in-kernel and ignores the knob")
        cfg = cfg.replace(a2a_chunks=None if a2a_chunks <= 1
                          else a2a_chunks)
    pk, xk = jax.random.split(jax.random.PRNGKey(seed))
    params = init_moe_params(pk, cfg)
    params = jax.tree_util.tree_map(lambda p: p.astype(cfg.dtype), params)
    x = jax.random.normal(xk, (cfg.tokens, cfg.hidden_size), cfg.dtype)

    if path not in ("fused", "collective", "ragged"):
        raise ValueError(f"unknown path {path!r}")
    if path == "ragged":
        from flashmoe_tpu.parallel.ragged_ep import ragged_ep_moe_layer

        layer = ragged_ep_moe_layer
    else:
        layer = ep_moe_layer

    def xla_layer(c, skip=False):
        return layer(params, c, cfg, mesh, use_pallas=interpret,
                     interpret=interpret, skip_exchange=skip).out

    if path == "fused":
        overlapped = lambda c: fused_ep_moe_layer(
            params, c, cfg, mesh, interpret=interpret).out
    else:
        overlapped = xla_layer
    compute_only = lambda c: xla_layer(c, skip=True)
    comm_path = "ragged" if path == "ragged" else "collective"
    comm_only = lambda c: _comm_only(c, cfg, mesh, path=comm_path)

    t_over = _time_chained(overlapped, x, trials=trials, chain=chain)
    t_comp = _time_chained(compute_only, x, trials=trials, chain=chain)
    t_comm = _time_chained(comm_only, x, trials=trials, chain=chain)
    return {
        "t_overlapped_ms": t_over * 1e3,
        "t_compute_ms": t_comp * 1e3,
        "t_comm_ms": t_comm * 1e3,
        "overlap_efficiency": (t_comp + t_comm) / t_over,
        "path": path,
        "ep": ep,
        "a2a_chunks": cfg.a2a_chunks or 1,
    }


def overlap_bound(cfg: MoEConfig, d: int, gen: str = "v5e", *,
                  links: int = 4, mxu_fraction: float = 1.0,
                  schedule: str | None = None,
                  fuse_combine: bool = False) -> dict:
    """Analytical expected overlap efficiency of the fused kernel's
    phase-1-all-sends schedule — the number a future hardware
    ``--overlap`` measurement is judged against instead of being read
    off in isolation (VERDICT r4 next #8; the reference's measured
    analogue is ``plots/overlap_efficiency_8.png``).

    Model (per rank, homogeneous ring of ``d`` ranks, uniform routing):

      C      FFN compute on the ``s_loc * k`` received rows at
             ``mxu_fraction`` of the generation's peak bf16 throughput
             (1.0 = roofline bound; pass the measured ``mxu_util`` for a
             calibrated expectation).
      t_x    egress serialization of phase 1: all (d-1)/d of the slab
             bytes leave at once over ``links`` ICI links
             (``topology._ICI_SPECS`` per-link GB/s).
      T      makespan, per FFN schedule (``_fused_schedule``):
             per_source — step 0 computes the own slab while remote
               slabs fly, step s>=1 waits slab s:
               T = max(C, t_x + C/d) + tail;
             batched / rowwin — the own slab (C/d) is the only compute
               that can hide arrivals; the remaining (d-1)/d of C runs
               after the last arrival (expert-major with VMEM-resident
               hidden for batched, K-windowed with the HBM accumulator
               for rowwin):
               T = max(C/d, t_x) + (d-1)/d * C + tail.
      tail   the last returns can only start after their compute
             finishes: per_source — the LAST SLAB's rows, t_x/(d-1);
             batched — the LAST EXPERT's rows (returns issue per expert
             after its pass 2), t_x/nlx, which is the coarser wait
             whenever nlx < d-1; rowwin — the last WINDOW finishes each
             row tile and returns it immediately, so only the final
             row tile's rows trail: t_x/(nlx * n_row_tiles), the
             finest return granularity of the batched-pass schedules
             (geometry from ``fused.schedule_table``).
      OE     (C + 2*t_x) / T  — the operational metric's numerator is
             the serialized sum of the compute-only leg and BOTH
             all-to-alls (x out, y back).

    ``schedule=None`` resolves the kernel's actual default for this
    (cfg, d) — pass ``fuse_combine`` matching the run (the combine's
    VMEM claim can flip the schedule gate) so the reported bound
    describes the code path that will run.  Latency (alpha) terms are
    dropped: at slab sizes of MBs they are <1% of the beta terms.
    Returns every intermediate so tests can assert the pieces, not just
    the ratio.
    """
    from flashmoe_tpu.parallel.topology import _ICI_SPECS, chip_spec

    if schedule is None:
        from flashmoe_tpu.analysis import _geom

        schedule = _geom(cfg, d, fuse_combine=fuse_combine)["schedule"]
    # ValueError naming the supported generations for anything outside
    # {v4, v5e, v5p, v6e} — the planner calls this with arbitrary gen
    # strings, so it must fail cleanly (ADVICE round 5)
    peak_tflops, _ = chip_spec(gen)
    bw_link = _ICI_SPECS[gen][1] * 1e9            # B/s one way per link
    dt = jnp.dtype(cfg.dtype).itemsize
    s_loc = cfg.tokens // d
    rows = s_loc * cfg.expert_top_k
    gemms = 3 if cfg.gated_ffn else 2
    flops = gemms * 2.0 * rows * cfg.hidden_size * cfg.intermediate_size
    c_s = flops / (peak_tflops * 1e12 * mxu_fraction)
    b_dir = (d - 1) / d * rows * cfg.hidden_size * dt
    t_x = b_dir / (links * bw_link)
    nlx = max(cfg.num_experts // d, 1)
    if schedule == "batched":
        tail = t_x / nlx
        t_over = max(c_s / d, t_x) + (d - 1) / d * c_s + tail
        compute_bound = c_s / d >= t_x
    elif schedule == "rowwin":
        # batched-pass makespan with per-row-tile return granularity:
        # the last K-window finishes (and returns) one row tile at a
        # time, so only the final tile's rows trail the compute
        from flashmoe_tpu.parallel.fused import schedule_table

        n_row_tiles = schedule_table(cfg, d, fuse_combine=fuse_combine,
                                     schedule="rowwin")["n_row_tiles"]
        tail = t_x / max(nlx * n_row_tiles, 1)
        t_over = max(c_s / d, t_x) + (d - 1) / d * c_s + tail
        compute_bound = c_s / d >= t_x
    else:
        tail = t_x / max(d - 1, 1)
        t_over = max(c_s, t_x + c_s / d) + tail
        compute_bound = c_s >= t_x + c_s / d
    oe = (c_s + 2 * t_x) / t_over
    return {
        "schedule": schedule,
        "compute_ms": c_s * 1e3,
        "t_x_ms": t_x * 1e3,
        "tail_ms": tail * 1e3,
        "t_overlapped_ms": t_over * 1e3,
        "overlap_efficiency_bound": oe,
        "compute_bound": compute_bound,
    }


def chunked_overlap_bound(cfg: MoEConfig, d: int, gen: str = "v5e",
                          chunks: int = 1, *, links: int = 4,
                          mxu_fraction: float = 1.0,
                          path: str = "collective") -> dict:
    """Analytical expected overlap efficiency of the chunked
    double-buffered XLA-transport pipeline (``MoEConfig.a2a_chunks``) —
    the number a ``bench.py --overlap`` measurement of the chunked
    schedule is judged against, the way :func:`overlap_bound` anchors
    the fused kernel's measurement.

    Model (per rank, uniform routing): FFN compute ``C`` on the
    ``s_loc * k`` routed rows at ``mxu_fraction`` of peak; per-leg wire
    serialization at the leg's wire row size with ``chunks`` messages
    per peer (alpha x chunks — ``analysis.a2a_transport_cost``'s
    chunking rule); makespan ``T`` from
    ``analysis.chunked_pipeline_ms``.  The efficiency mirrors the
    operational metric exactly:

        OE = (C + E(n)) / T(n)     (serial + both chunked legs over
                                    the pipelined makespan)

    so ``chunks=1`` gives exactly 1.0 (fully serialized) and the upper
    bound is ``measure_overlap``'s ``(a+b)/max(a,b)`` shape.  ``path``
    prices capacity slabs ('collective') or routed rows ('ragged').
    Returns every intermediate so tests can assert the pieces."""
    from flashmoe_tpu.analysis import chunked_pipeline_ms, wire_row_bytes
    from flashmoe_tpu.parallel.topology import _ICI_SPECS, chip_spec

    if chunks < 1:
        raise ValueError(f"chunks={chunks} must be >= 1")
    if path not in ("collective", "ragged"):
        raise ValueError(
            f"unknown chunked path {path!r}; the fused kernel has its "
            f"own bound (overlap_bound)")
    peak_tflops, _ = chip_spec(gen)   # ValueError on unknown gen
    a_us, gbps = _ICI_SPECS.get(gen, _ICI_SPECS["default"])
    a_ms = a_us / 1e3
    bw_ms = gbps * 1e6 * max(links, 1)            # B/ms, striped
    mxu_fraction = max(min(mxu_fraction, 1.0), 1e-6)
    s_loc = cfg.tokens // d
    rows = s_loc * cfg.expert_top_k
    gemms = 3 if cfg.gated_ffn else 2
    flops = gemms * 2.0 * rows * cfg.hidden_size * cfg.intermediate_size
    c_ms = flops / (peak_tflops * 1e9 * mxu_fraction)  # TFLOP/s -> /ms
    if path == "ragged":
        slab_rows = rows / d
    else:
        slab_rows = (cfg.num_experts // d) * local_capacity(cfg, s_loc)
    leg = lambda which: (d - 1) * (
        chunks * a_ms + slab_rows * wire_row_bytes(cfg, which) / bw_ms)
    e_d, e_c = leg("dispatch"), leg("combine")
    t = chunked_pipeline_ms(c_ms, e_d, e_c, chunks)
    serial = c_ms + e_d + e_c
    return {
        "chunks": chunks,
        "path": path,
        "compute_ms": c_ms,
        "leg_dispatch_ms": e_d,
        "leg_combine_ms": e_c,
        "serial_ms": serial,
        "t_overlapped_ms": t,
        "overlap_efficiency_bound": serial / t,
    }
