"""Pipeline parallelism: GPipe-style microbatch pipeline over the ``pp``
mesh axis.

The reference has no pipeline parallelism (SURVEY §2.6 — ``num_layers`` /
``moe_frequency`` only feed its Decider's stage-count constant γ).  A
complete framework needs the axis to be real, so this module implements the
schedule the Decider's γ models: contiguous layer stages, M microbatches,
a ``lax.scan`` over M + P - 1 ticks in which every stage processes one
in-flight microbatch and hands its activation to the successor via
``jax.lax.ppermute`` (ICI neighbour transfer; XLA overlaps it with the next
tick's compute).  Stage 0 owns the embedding, the last stage owns the final
norm + LM head and the loss.

Composition: tokens shard over ``dp`` — and over ``ep`` when the mesh has
one (each (dp, ep) slice runs its own pipeline, with ep doubling as data
parallelism for the non-MoE sub-blocks, the standard DP x PP x EP layout).
Inside a stage, MoE layers then run *expert-parallel*: expert weights
shard over ``ep`` within the stage and the dispatch/combine all-to-all
runs between that stage's ep peers (:func:`flashmoe_tpu.parallel.ep.
_ep_moe_shard`, already an in-shard_map body).  Stages must be
structurally uniform (same layer pattern), which holds when every layer is
MoE (``moe_frequency == 1``) or every layer dense.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from flashmoe_tpu.config import MoEConfig
from flashmoe_tpu.utils.compat import axis_size, shard_map
from flashmoe_tpu.models import transformer as tfm
from flashmoe_tpu.ops.moe import moe_layer
from flashmoe_tpu.parallel.ep import _ep_moe_shard


def stack_stage_params(params, cfg: MoEConfig, pp: int, interleave: int = 1):
    """Re-shape init_params output into per-stage stacked pytrees.

    Returns (stage_layers, io_params): ``stage_layers`` has every leaf
    stacked as [pp, interleave, layers_per_chunk, ...] — global chunk
    ``c = lap * pp + stage`` owns contiguous layers
    ``[c * lpc, (c + 1) * lpc)`` (the Megatron interleaved assignment);
    ``io_params`` carries embed / final_norm / lm_head (replicated; stage
    roles select what they use).
    """
    v = interleave
    if cfg.num_layers % (pp * v):
        raise ValueError(
            f"num_layers {cfg.num_layers} not divisible by "
            f"pp*interleave={pp * v}")
    lpc = cfg.num_layers // (pp * v)
    moe_set = set(cfg.moe_layer_indices)
    uniform = all(i in moe_set for i in range(cfg.num_layers)) or not moe_set
    if not uniform:
        raise ValueError(
            "pipeline stages need a uniform layer pattern "
            "(moe_frequency=1 or num_experts=1)"
        )
    layers = params["layers"]
    ordered = [
        layers[(l * pp + s) * lpc + i]
        for s in range(pp) for l in range(v) for i in range(lpc)
    ]
    stage_layers = jax.tree_util.tree_map(
        lambda *ls: jnp.stack(ls).reshape((pp, v, lpc) + ls[0].shape),
        *ordered,
    )
    io_params = {k: params[k] for k in ("embed", "final_norm", "lm_head")}
    return stage_layers, io_params


def _block_in_stage(layer, x, cfg: MoEConfig, li: int, use_ep: bool,
                    use_pallas: bool, interpret: bool):
    """One transformer block inside the pipeline's shard_map body.

    With ``use_ep`` the MoE sub-block runs expert-parallel over the
    ``ep`` axis via the in-shard_map EP body (expert weights arrive
    ep-sharded through the stage in_specs); ``use_pallas`` selects the
    fused Pallas gate/FFN kernels inside the stage (the production TPU
    path — round-2 verdict weak #3 flagged the hard-coded XLA body)."""
    a = tfm.attention(layer, tfm.rms_norm(x, layer["attn_norm"]), cfg)
    x = x + a
    xf = tfm.rms_norm(x, layer["ffn_norm"])
    b, t, h = xf.shape
    flat = xf.reshape(b * t, h)
    layer_cfg = cfg if li in cfg.moe_layer_indices else cfg.replace(
        num_experts=1, expert_top_k=1, num_shared_experts=0
    )
    if use_ep and layer_cfg.num_experts > 1:
        o = _ep_moe_shard(layer["moe"], flat, cfg=layer_cfg, axis="ep",
                          use_pallas=use_pallas, reduce_axes=("ep",),
                          interpret=interpret)
    else:
        o = moe_layer(layer["moe"], flat, layer_cfg, use_pallas=use_pallas,
                      interpret=interpret)
    return x + o.out.reshape(b, t, h).astype(x.dtype), o.aux_loss + o.z_loss


def _stage_apply(stage_layers, x, cfg: MoEConfig, lps: int,
                 use_ep: bool = False, remat: bool = True,
                 use_pallas: bool = False, interpret: bool = False):
    """Run this rank's ``lps`` layers on x: [B, T, H].

    Per-layer rematerialization bounds the pipeline's activation memory to
    one layer per in-flight microbatch — the memory profile 1F1B buys on
    imperative runtimes, obtained here by letting XLA recompute inside the
    GPipe schedule instead of hand-interleaving backward ticks."""
    aux = jnp.zeros((), cfg.accum_dtype)
    li0 = 0 if cfg.num_experts == 1 else cfg.moe_layer_indices[0]
    apply = functools.partial(_block_in_stage, cfg=cfg, li=li0,
                              use_ep=use_ep, use_pallas=use_pallas,
                              interpret=interpret)
    if remat:
        apply = jax.checkpoint(
            apply, policy=jax.checkpoint_policies.nothing_saveable,
        )
    for li in range(lps):
        layer = jax.tree_util.tree_map(lambda a: a[li], stage_layers)
        x, moe_loss = apply(layer, x)
        aux = aux + moe_loss
    return x, aux


def pipeline_loss(params, batch, cfg: MoEConfig, mesh: Mesh, *,
                  num_microbatches: int = 2, interleave: int = 1,
                  use_pallas: bool | None = None):
    """Pipelined loss over the pp axis. batch["tokens"]: [B, T+1] with
    B % (dp * num_microbatches) == 0.

    ``interleave`` > 1 runs the Megatron-style interleaved schedule: each
    stage owns ``interleave`` layer chunks (global chunk ``l * pp + s``),
    microbatches proceed in groups of ``pp``, and every activation
    arriving on the ring is consumed the same tick — no holding buffer.
    Bubble shrinks from ``(P-1)/(M+P-1)`` of a ``V``-deep stage to
    ``(P-1)/(V*M+P-1)`` of a chunk (wall-clock ratio
    ``(V*M+P-1) / (V*(M+P-1))``).  ``interleave=1`` is exactly GPipe.
    Requires ``M % P == 0`` when interleaving (group structure).
    """
    pp = mesh.shape["pp"]
    if pp <= 1:
        raise ValueError("pipeline_loss needs a pp>1 mesh")
    v = interleave
    if v < 1:
        raise ValueError(f"interleave must be >= 1, got {v}")
    if v > 1 and num_microbatches % pp:
        raise ValueError(
            f"interleaved schedule needs num_microbatches "
            f"({num_microbatches}) divisible by pp ({pp})")
    # Pallas kernels inside the stage body: default on for real TPU;
    # elsewhere (CPU mesh) requesting them means interpret mode, same
    # convention as models.transformer._ffn
    if use_pallas is None:
        use_pallas = jax.default_backend() == "tpu"
    interpret = bool(use_pallas) and jax.default_backend() != "tpu"
    ep = mesh.shape.get("ep", 1)
    use_ep = ep > 1 and cfg.num_experts > 1
    if use_ep and cfg.num_experts % ep:
        raise ValueError(f"E={cfg.num_experts} not divisible by ep={ep}")
    lpc = cfg.num_layers // (pp * v)
    stage_layers, io_params = stack_stage_params(params, cfg, pp,
                                                 interleave=v)

    # expert-weight leaves additionally shard their expert dim (axis 3 of
    # the [pp, v, lpc, E, ...] stack) over ep; everything else replicates
    # across ep within the stage
    _EP_KEYS = {"w_up", "w_down", "w_gate", "b_up", "b_down"}

    def _stage_spec(path, leaf):
        keys = {getattr(k, "key", None) for k in path}
        if use_ep and keys & {"moe"} and keys & _EP_KEYS:
            return P("pp", None, None, "ep")
        return P("pp")

    stage_specs = jax.tree_util.tree_map_with_path(_stage_spec, stage_layers)

    def body(stage_layers, io_params, tokens):
        # in_specs P("pp") leaves a leading singleton stage dim per rank
        stage_layers = jax.tree_util.tree_map(lambda a: a[0], stage_layers)
        s = jax.lax.axis_index("pp")
        p = axis_size("pp")
        m = num_microbatches
        b, t1 = tokens.shape
        bm = b // m
        tlen = t1 - 1
        inp = tokens[:, :-1].reshape(m, bm, tlen)
        tgt = tokens[:, 1:].reshape(m, bm, tlen)

        def tick(carry, t):
            act_in, loss_sum, aux_sum, cnt = carry
            # interleaved decomposition of this rank's local tick
            # u = t - s:  group g of p microbatches, lap l, offset r
            u = t - s
            active = (u >= 0) & (u < v * m)
            uc = jnp.clip(u, 0, v * m - 1)
            g = uc // (v * p)
            l = (uc % (v * p)) // p
            r = uc % p
            mb = jnp.clip(g * p + r, 0, m - 1)
            chunk = jax.tree_util.tree_map(
                lambda a: jax.lax.dynamic_index_in_dim(a, l, 0,
                                                       keepdims=False),
                stage_layers,
            )
            inject = io_params["embed"].astype(cfg.dtype)[inp[mb]]
            x = jnp.where((s == 0) & (l == 0), inject, act_in)
            y, aux = _stage_apply(chunk, x, cfg, lpc, use_ep=use_ep,
                                  use_pallas=use_pallas,
                                  interpret=interpret)
            # last stage, last lap: loss on the completed microbatch.
            # The vocab GEMM + log_softmax live under lax.cond, so the
            # (P*V-1)/(P*V) of ticks where this rank is not finishing a
            # microbatch skip them at runtime instead of computing
            # [bm, T, V] logits and masking (round-2 verdict weak #3) —
            # under SPMD all ranks share one program, so a runtime
            # conditional is the strongest possible skip.
            use = active & (s == p - 1) & (l == v - 1)

            def ce_branch(y_tg):
                yb, tg = y_tg
                hn = tfm.rms_norm(yb, io_params["final_norm"])
                logits = jnp.dot(
                    hn.astype(cfg.dtype),
                    io_params["lm_head"].astype(cfg.dtype),
                    preferred_element_type=jnp.float32,
                )
                logp = jax.nn.log_softmax(
                    logits.astype(jnp.float32), axis=-1
                )
                nll = -jnp.take_along_axis(
                    logp, tg[..., None], axis=-1
                )[..., 0]
                return jnp.mean(nll)

            mb_ce = jax.lax.cond(
                use, ce_branch, lambda _: jnp.zeros((), jnp.float32),
                (y, tgt[mb]),
            )
            loss_sum = loss_sum + mb_ce
            aux_sum = aux_sum + jnp.where(active, aux, 0.0)
            cnt = cnt + jnp.where(use, 1.0, 0.0)
            act_out = jax.lax.ppermute(
                y, "pp", [(i, (i + 1) % p) for i in range(p)]
            )
            return (act_out, loss_sum, aux_sum, cnt), None

        zero_act = jnp.zeros((bm, tlen, cfg.hidden_size), cfg.dtype)
        (_, loss_sum, aux_sum, cnt), _ = jax.lax.scan(
            tick, (zero_act, jnp.zeros((), jnp.float32),
                   jnp.zeros((), cfg.accum_dtype),
                   jnp.zeros((), jnp.float32)),
            jnp.arange(v * m + p - 1),
        )
        # only the last stage accumulated CE; broadcast it everywhere
        ce = jax.lax.psum(loss_sum, "pp") / jnp.maximum(
            jax.lax.psum(cnt, "pp"), 1.0
        )
        aux = jax.lax.psum(aux_sum, "pp") / m
        token_axes = ("dp", "ep") if use_ep else ("dp",)
        ce = jax.lax.pmean(ce, token_axes)
        aux = jax.lax.pmean(aux, token_axes)
        return ce + aux, ce, aux

    tok_spec = P(("dp", "ep"), None) if use_ep else P("dp", None)
    fn = shard_map(
        body, mesh=mesh,
        in_specs=(stage_specs, P(), tok_spec),
        out_specs=(P(), P(), P()),
        check_vma=False,
    )
    total, ce, aux = fn(stage_layers, io_params, batch["tokens"])
    return total, {"ce": ce, "aux": aux}
