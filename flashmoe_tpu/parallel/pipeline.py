"""Pipeline parallelism: GPipe-style microbatch pipeline over the ``pp``
mesh axis.

The reference has no pipeline parallelism (SURVEY §2.6 — ``num_layers`` /
``moe_frequency`` only feed its Decider's stage-count constant γ).  A
complete framework needs the axis to be real, so this module implements the
schedule the Decider's γ models: contiguous layer stages, M microbatches,
a ``lax.scan`` over M + P - 1 ticks in which every stage processes one
in-flight microbatch and hands its activation to the successor via
``jax.lax.ppermute`` (ICI neighbour transfer; XLA overlaps it with the next
tick's compute).  Stage 0 owns the embedding, the last stage owns the final
norm + LM head and the loss.

Composition: tokens shard over ``dp`` (each dp group runs its own
pipeline); experts are replicated within a stage in this schedule (ep/tp
composition with PP is a later-round optimization).  Stages must be
structurally uniform (same layer pattern), which holds when every layer is
MoE (``moe_frequency == 1``) or every layer dense.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from flashmoe_tpu.config import MoEConfig
from flashmoe_tpu.models import transformer as tfm
from flashmoe_tpu.ops.moe import moe_layer


def stack_stage_params(params, cfg: MoEConfig, pp: int):
    """Re-shape init_params output into per-stage stacked pytrees.

    Returns (stage_layers, io_params): ``stage_layers`` has every leaf
    stacked as [pp, layers_per_stage, ...]; ``io_params`` carries embed /
    final_norm / lm_head (replicated; stage roles select what they use).
    """
    if cfg.num_layers % pp:
        raise ValueError(f"num_layers {cfg.num_layers} not divisible by pp={pp}")
    lps = cfg.num_layers // pp
    moe_set = set(cfg.moe_layer_indices)
    uniform = all(i in moe_set for i in range(cfg.num_layers)) or not moe_set
    if not uniform:
        raise ValueError(
            "pipeline stages need a uniform layer pattern "
            "(moe_frequency=1 or num_experts=1)"
        )
    layers = params["layers"]
    stage_layers = jax.tree_util.tree_map(
        lambda *ls: jnp.stack(ls).reshape((pp, lps) + ls[0].shape), *layers
    )
    io_params = {k: params[k] for k in ("embed", "final_norm", "lm_head")}
    return stage_layers, io_params


def _stage_apply(stage_layers, x, cfg: MoEConfig, lps: int):
    """Run this rank's ``lps`` layers on x: [B, T, H]."""
    aux = jnp.zeros((), cfg.accum_dtype)
    for li in range(lps):
        layer = jax.tree_util.tree_map(lambda a: a[li], stage_layers)
        x, moe_loss = tfm.block(layer, x, cfg, 0 if cfg.num_experts == 1
                                else cfg.moe_layer_indices[0])
        aux = aux + moe_loss
    return x, aux


def pipeline_loss(params, batch, cfg: MoEConfig, mesh: Mesh, *,
                  num_microbatches: int = 2):
    """Pipelined loss over the pp axis. batch["tokens"]: [B, T+1] with
    B % (dp * num_microbatches) == 0."""
    pp = mesh.shape["pp"]
    if pp <= 1:
        raise ValueError("pipeline_loss needs a pp>1 mesh")
    lps = cfg.num_layers // pp
    stage_layers, io_params = stack_stage_params(params, cfg, pp)

    def body(stage_layers, io_params, tokens):
        # in_specs P("pp") leaves a leading singleton stage dim per rank
        stage_layers = jax.tree_util.tree_map(lambda a: a[0], stage_layers)
        s = jax.lax.axis_index("pp")
        p = jax.lax.axis_size("pp")
        m = num_microbatches
        b, t1 = tokens.shape
        bm = b // m
        tlen = t1 - 1
        inp = tokens[:, :-1].reshape(m, bm, tlen)
        tgt = tokens[:, 1:].reshape(m, bm, tlen)

        def tick(carry, t):
            act_in, loss_sum, aux_sum, cnt = carry
            mb = jnp.clip(t - s, 0, m - 1)
            active = (t - s >= 0) & (t - s < m)
            inject = io_params["embed"].astype(cfg.dtype)[inp[mb]]
            x = jnp.where(s == 0, inject, act_in)
            y, aux = _stage_apply(stage_layers, x, cfg, lps)
            # last stage: loss on the completed microbatch
            h = tfm.rms_norm(y, io_params["final_norm"])
            logits = jnp.dot(
                h.astype(cfg.dtype), io_params["lm_head"].astype(cfg.dtype),
                preferred_element_type=jnp.float32,
            )
            logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
            nll = -jnp.take_along_axis(
                logp, tgt[mb][..., None], axis=-1
            )[..., 0]
            is_last = s == p - 1
            use = active & is_last
            loss_sum = loss_sum + jnp.where(use, jnp.mean(nll), 0.0)
            aux_sum = aux_sum + jnp.where(active, aux, 0.0)
            cnt = cnt + jnp.where(use, 1.0, 0.0)
            act_out = jax.lax.ppermute(
                y, "pp", [(i, (i + 1) % p) for i in range(p)]
            )
            return (act_out, loss_sum, aux_sum, cnt), None

        zero_act = jnp.zeros((bm, tlen, cfg.hidden_size), cfg.dtype)
        (_, loss_sum, aux_sum, cnt), _ = jax.lax.scan(
            tick, (zero_act, jnp.zeros((), jnp.float32),
                   jnp.zeros((), cfg.accum_dtype),
                   jnp.zeros((), jnp.float32)),
            jnp.arange(m + p - 1),
        )
        # only the last stage accumulated CE; broadcast it everywhere
        ce = jax.lax.psum(loss_sum, "pp") / jnp.maximum(
            jax.lax.psum(cnt, "pp"), 1.0
        )
        aux = jax.lax.psum(aux_sum, "pp") / m
        ce = jax.lax.pmean(ce, "dp")
        aux = jax.lax.pmean(aux, "dp")
        return ce + aux, ce, aux

    fn = jax.shard_map(
        body, mesh=mesh,
        in_specs=(P("pp"), P(), P("dp", None)),
        out_specs=(P(), P(), P()),
        check_vma=False,
    )
    total, ce, aux = fn(stage_layers, io_params, batch["tokens"])
    return total, {"ce": ce, "aux": aux}
