"""Distributed dropless MoE: ragged all-to-all expert parallelism.

The capacity-based EP layer (:mod:`flashmoe_tpu.parallel.ep`) pads every
(rank, expert) slab to a fixed capacity — simple, static, but with
``drop_tokens=False`` it ships ``E x S_loc`` rows per rank regardless of
routing.  The reference ships exactly ``routedTokens`` per packet (the
dynamic size rides in the signal payload, ``types.cuh:299-334``) and its
receivers decode variable-size packets.  This module is that capability on
TPU: variable-size expert transfers under static *bounds* instead of static
*shapes*.

Per rank: assignments sort by global expert id (destination-major), so each
destination's rows are contiguous; counts exchange over the ``ep`` axis
establishes every pairwise transfer size; ``jax.lax.ragged_all_to_all``
moves exactly the routed rows (TPU path — XLA:CPU lacks the op, so tests
exercise the same layout logic through a dense-padded ``all_to_all``
fallback); arithmetic (no sort) regroups the received source-major rows
into tile-padded expert-major segments for the grouped Pallas FFN; the
whole dance then runs in reverse.

All shapes are static upper bounds; ``recv_bound`` defaults to the true
worst case (every token in the ep group routed to one rank).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from flashmoe_tpu.config import BLOCK_M, MoEConfig
from flashmoe_tpu.utils.compat import axis_size, shard_map
from flashmoe_tpu.ops import expert as exp
from flashmoe_tpu.ops import ragged as rag
from flashmoe_tpu.ops import stats as st
from flashmoe_tpu.ops import wire as wr
from flashmoe_tpu.ops.gate import router
from flashmoe_tpu.ops.moe import MoEOutput


def _row_exchange(arr, *, axis: str, d: int, exchange: str,
                  block_rows: int, out_bound: int,
                  send_offsets, send_sizes, remote_offsets,
                  recv_sizes, recv_offsets):
    """Move ragged row blocks of ``arr`` ([N, W], any W / dtype) between
    ranks.  Rank-local blocks start at ``send_offsets`` with
    ``send_sizes`` rows; block ``p`` lands at ``remote_offsets[p]`` of
    peer ``p``'s ``[out_bound, W]`` output, which locally holds
    ``recv_sizes`` rows per source starting at ``recv_offsets``
    (``recv_offsets`` being the local cumsum view ``remote_offsets``
    describes remotely).  One implementation for both transfer
    directions and for the payload AND the fp8 scale sidecar, so the
    two can never take different routes.

    ``exchange='ragged'`` is the TPU ``ragged_all_to_all``; ``'dense'``
    pads each block to ``block_rows`` rows and compacts after a dense
    ``all_to_all`` (CPU fallback — identical layout logic)."""
    w = arr.shape[1]
    if exchange == "ragged":
        return jax.lax.ragged_all_to_all(
            arr, jnp.zeros((out_bound, w), arr.dtype),
            send_offsets, send_sizes, remote_offsets, recv_sizes,
            axis_name=axis,
        )
    blocks = jnp.zeros((d, block_rows, w), arr.dtype)

    def fill(peer, blocks):
        rows = jax.lax.dynamic_slice(
            jnp.pad(arr, ((0, block_rows), (0, 0))),
            (send_offsets[peer], 0), (block_rows, w),
        )
        mask = (jnp.arange(block_rows) < send_sizes[peer])[:, None]
        return blocks.at[peer].set(jnp.where(mask, rows, 0))

    blocks = jax.lax.fori_loop(0, d, fill, blocks)
    got = jax.lax.all_to_all(
        blocks.reshape(d, 1, block_rows, w), axis, split_axis=0,
        concat_axis=0, tiled=False,
    ).reshape(d, block_rows, w)
    buf = jnp.zeros((out_bound, w), arr.dtype)

    def compact(peer, buf):
        rows = got[peer]
        idx = jnp.where(
            jnp.arange(block_rows) < recv_sizes[peer],
            recv_offsets[peer] + jnp.arange(block_rows),
            out_bound,  # dropped
        )
        return buf.at[idx].set(rows, mode="drop")

    return jax.lax.fori_loop(0, d, compact, buf)


def _wired_row_exchange(arr, wire_dtype, **kw):
    """:func:`_row_exchange` with the wire codec applied at the
    boundary: rows quantize to ``wire_dtype`` before the transfer and
    dequantize after; fp8 per-row scales ride an identical second
    exchange as a [N, 1] column.  ``wire_dtype=None`` is the raw path —
    the exact pre-compression graph."""
    if wire_dtype is None:
        return _row_exchange(arr, **kw)
    payload, scales = wr.encode(arr, wire_dtype)
    payload = _row_exchange(payload, **kw)
    if scales is None:
        return wr.decode(payload, None, arr.dtype)
    scales = _row_exchange(scales[:, None], **kw)
    return wr.decode(payload, scales[:, 0], arr.dtype)


def _ragged_ep_shard(params, x, cfg: MoEConfig, *, axis: str,
                     use_pallas: bool, interpret: bool, exchange: str,
                     block_m: int, reduce_axes):
    d = axis_size(axis)
    s_loc, h = x.shape
    e = cfg.num_experts
    nlx = e // d
    n_assign = s_loc * cfg.expert_top_k
    recv_bound = d * n_assign  # worst case: everyone routes to me
    wire_disp = wr.resolve(cfg.wire_dtype)
    wire_comb = wr.resolve(cfg.wire_dtype_combine)

    r = router(x, params["gate_w"], cfg, use_pallas=use_pallas,
               interpret=interpret)

    # ---- local expert-sorted layout (contiguous, unpadded: block "1") ----
    plan = rag.make_ragged_plan(r.expert_idx, cfg, 1)
    xs = rag.ragged_dispatch(x.astype(cfg.dtype), plan, cfg, 1)  # [nA+, H]
    xs = xs[:n_assign]  # block_m=1 upper bound equals exact total
    counts = plan.counts  # [E] rows per global expert
    cmat = counts.reshape(d, nlx)  # [dest, local expert]
    send_sizes = jnp.sum(cmat, axis=1).astype(jnp.int32)  # [D]
    input_offsets = (jnp.cumsum(send_sizes) - send_sizes).astype(jnp.int32)

    # ---- exchange sizes ----
    # all ranks' send matrices: S[s, d] = rows s sends to d
    all_send = jax.lax.all_gather(send_sizes, axis)  # [D, D]
    my = jax.lax.axis_index(axis)
    recv_sizes = all_send[:, my].astype(jnp.int32)  # [D] rows from each src
    recv_offsets = (jnp.cumsum(recv_sizes) - recv_sizes).astype(jnp.int32)
    # where my block starts on each destination = sum of earlier sources
    out_offsets = (
        jnp.cumsum(all_send, axis=0) - all_send
    )[my].astype(jnp.int32)  # [D]
    # per-(src, my local expert) counts, for regrouping
    recv_cmat = jax.lax.all_to_all(
        cmat.reshape(d, 1, nlx), axis, split_axis=0, concat_axis=0,
        tiled=False,
    ).reshape(d, nlx)

    # ---- forward data exchange: src-major ragged layout ----
    wire_err = None
    if cfg.collect_stats and wire_disp is not None:
        wire_err = wr.roundtrip_error(xs, wire_disp)
    x_recv = _wired_row_exchange(
        xs, wire_disp, axis=axis, d=d, exchange=exchange,
        block_rows=n_assign, out_bound=recv_bound,
        send_offsets=input_offsets, send_sizes=send_sizes,
        remote_offsets=out_offsets, recv_sizes=recv_sizes,
        recv_offsets=recv_offsets,
    )

    # ---- regroup src-major -> tile-padded expert-major (arithmetic) ----
    # per-expert totals and padded segment starts
    etot = jnp.sum(recv_cmat, axis=0)  # [nlx]
    epad = ((etot + block_m - 1) // block_m) * block_m
    eseg = (jnp.cumsum(epad) - epad).astype(jnp.int32)  # [nlx]
    pre = (jnp.cumsum(recv_cmat, axis=0) - recv_cmat)  # [D, nlx] rows before src s
    intra = (jnp.cumsum(recv_cmat, axis=1) - recv_cmat)  # [D, nlx] within-src starts

    rows = jnp.arange(recv_bound, dtype=jnp.int32)
    src_of = jnp.clip(
        jnp.searchsorted(
            (recv_offsets + recv_sizes).astype(jnp.int32), rows,
            side="right",
        ).astype(jnp.int32),
        0, d - 1,
    )
    w = rows - recv_offsets[src_of]  # offset within the src block
    cum_intra = jnp.cumsum(recv_cmat, axis=1)  # [D, nlx] ends
    e_of = jnp.sum(
        w[:, None] >= cum_intra[src_of], axis=1
    ).astype(jnp.int32)
    e_of = jnp.clip(e_of, 0, nlx - 1)
    i_of = w - intra[src_of, e_of]
    total_recv = jnp.sum(recv_sizes)

    # grouped buffer: per-expert tile padding can push targets past
    # recv_bound, so the buffer is recv_bound (tile-rounded) plus one tile
    # per expert, and the dropped-row sentinel is grouped_rows itself —
    # strictly out of range for the scatter's drop mode
    grouped_rows = (
        ((recv_bound + block_m - 1) // block_m) * block_m
        + nlx * block_m
    )
    target = jnp.where(
        rows < total_recv,
        eseg[e_of] + pre[src_of, e_of] + i_of,
        grouped_rows,  # out of range -> dropped
    )
    x_grp = jnp.zeros((grouped_rows, h), xs.dtype)
    x_grp = x_grp.at[target].set(x_recv, mode="drop")

    # tile group ids from padded segment ends
    n_tiles = grouped_rows // block_m
    tile_starts = jnp.arange(n_tiles, dtype=jnp.int32) * block_m
    seg_ends = eseg + epad
    tile_gid = jnp.clip(
        jnp.sum(tile_starts[:, None] >= seg_ends[None, :], axis=1),
        0, nlx - 1,
    ).astype(jnp.int32)

    # ---- expert FFN on the local shard of weights ----
    if use_pallas:
        # _ad variant: Pallas forward AND Pallas backward (grouped_matmul/
        # tgmm with saved residuals) — the dropless path trains through
        # the kernels too
        y_grp = exp.grouped_ffn_ad(
            x_grp, tile_gid,
            params["w_up"].astype(cfg.dtype), params["b_up"],
            params["w_down"].astype(cfg.dtype), params["b_down"],
            params.get("w_gate", None) if cfg.gated_ffn else None,
            cfg.hidden_act, cfg.gated_ffn, block_m,
            exp.DEFAULT_BLOCK_I, interpret,
        )
    else:
        # XLA fallback: per-row weight selection via one-hot (test path)
        sel = jax.nn.one_hot(
            jnp.repeat(tile_gid, block_m), nlx, dtype=x_grp.dtype
        )  # [rows, nlx]
        up_w = jnp.einsum("rn,nhi->rhi", sel, params["w_up"].astype(x_grp.dtype))
        up = jnp.einsum("rh,rhi->ri", x_grp, up_w) + sel @ params["b_up"].astype(x_grp.dtype)
        from flashmoe_tpu.models.reference import activation_fn
        act = activation_fn(cfg.hidden_act)
        if cfg.gated_ffn:
            g_w = jnp.einsum("rn,nhi->rhi", sel,
                             params["w_gate"].astype(x_grp.dtype))
            hid = act(jnp.einsum("rh,rhi->ri", x_grp, g_w)) * up
        else:
            hid = act(up)
        dn_w = jnp.einsum("rn,nih->rih", sel,
                          params["w_down"].astype(x_grp.dtype))
        y_grp = (jnp.einsum("ri,rih->rh", hid, dn_w)
                 + sel @ params["b_down"].astype(x_grp.dtype))

    # ---- return path: expert-major -> src-major -> ragged back ----
    y_src_major = y_grp[target.clip(0, grouped_rows - 1)]
    y_src_major = jnp.where(
        (rows < total_recv)[:, None], y_src_major, 0
    ).astype(xs.dtype)

    # returned rows must land where the source originally staged them:
    # on rank s that's s's input_offsets[my] = exclusive row-cumsum of
    # its send sizes — derivable from the gathered send matrix
    rev_out_offsets = (
        jnp.cumsum(all_send, axis=1) - all_send
    )[:, my].astype(jnp.int32)
    if cfg.collect_stats and wire_comb is not None:
        comb_err = wr.roundtrip_error(y_src_major, wire_comb)
        wire_err = (comb_err if wire_err is None
                    else jnp.maximum(wire_err, comb_err))
    ys = _wired_row_exchange(
        y_src_major, wire_comb, axis=axis, d=d, exchange=exchange,
        block_rows=n_assign, out_bound=n_assign,
        send_offsets=recv_offsets, send_sizes=recv_sizes,
        remote_offsets=rev_out_offsets, recv_sizes=send_sizes,
        recv_offsets=input_offsets,
    )

    # ---- combine in the original expert-sorted layout ----
    healthy = None
    combine_w = r.combine_weights
    if cfg.degrade_unhealthy_experts:
        # tier-0 (ops/health.py): ys is expert-sorted by GLOBAL expert
        # with per-expert row counts in plan.counts (block-1 layout:
        # padded == exact), so segment health maps rows -> experts; the
        # ragged combine does not renormalize, so the mask does
        from flashmoe_tpu.ops import health as hlt

        healthy = hlt.expert_health_segments(ys, plan.counts)
        ys, combine_w = hlt.degrade_outputs(
            ys, combine_w, r.expert_idx, healthy, renormalize=True)
    out = rag.ragged_combine(ys, plan, combine_w, cfg)

    aux = jax.lax.pmean(r.aux_loss, reduce_axes) * cfg.aux_loss_coef
    z = jax.lax.pmean(r.z_loss, reduce_axes)
    cnts = jax.lax.psum(r.expert_counts, reduce_axes)
    stats = None
    if cfg.collect_stats:
        # dropless: capacity=None reports zero drops / full utilization
        local = st.moe_stats(r, cfg, None)
        stats = st.reduce_stats(local, r.probs_mean, reduce_axes)
        if healthy is not None:
            from flashmoe_tpu.ops import health as hlt

            stats = hlt.attach_degradation(stats, healthy, r.expert_idx,
                                           reduce_axes)
        if wire_err is not None:
            stats = st.with_wire_error(stats, wire_err, reduce_axes)
    return MoEOutput(out.astype(cfg.dtype), aux, z, cnts, stats)


def ragged_ep_moe_layer(params, x, cfg: MoEConfig, mesh: Mesh, *,
                        use_pallas: bool = False, interpret: bool = False,
                        exchange: str | None = None,
                        block_m: int = BLOCK_M,
                        token_axes: tuple[str, ...] = ("ep",)) -> MoEOutput:
    """Dropless expert-parallel MoE over the ``ep`` axis.

    ``exchange``: "ragged" (TPU ``ragged_all_to_all``) or "dense" (padded
    ``all_to_all`` fallback — same layout logic, used on backends without
    the ragged op).  Default picks by backend.
    """
    if cfg.num_shared_experts:
        raise NotImplementedError("shared experts stay outside this layer")
    if exchange is None:
        exchange = "ragged" if jax.default_backend() == "tpu" else "dense"

    body = functools.partial(
        _ragged_ep_shard, cfg=cfg, axis="ep", use_pallas=use_pallas,
        interpret=interpret, exchange=exchange, block_m=block_m,
        reduce_axes=token_axes,
    )
    pspecs = {k: P("ep") if k != "gate_w" else P() for k in params}
    stats_specs = (st.MoEStats(*([P()] * len(st.MoEStats._fields)))
                   if cfg.collect_stats else None)
    fn = shard_map(
        body, mesh=mesh,
        in_specs=(pspecs, P(token_axes, None)),
        out_specs=MoEOutput(P(token_axes, None), P(), P(), P(),
                            stats_specs),
        check_vma=False,
    )
    return fn(params, x)
