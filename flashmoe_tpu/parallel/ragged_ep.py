"""Distributed dropless MoE: ragged all-to-all expert parallelism.

The capacity-based EP layer (:mod:`flashmoe_tpu.parallel.ep`) pads every
(rank, expert) slab to a fixed capacity — simple, static, but with
``drop_tokens=False`` it ships ``E x S_loc`` rows per rank regardless of
routing.  The reference ships exactly ``routedTokens`` per packet (the
dynamic size rides in the signal payload, ``types.cuh:299-334``) and its
receivers decode variable-size packets.  This module is that capability on
TPU: variable-size expert transfers under static *bounds* instead of static
*shapes*.

Per rank: assignments sort by global expert id (destination-major), so each
destination's rows are contiguous; counts exchange over the ``ep`` axis
establishes every pairwise transfer size; ``jax.lax.ragged_all_to_all``
moves exactly the routed rows (TPU path — XLA:CPU lacks the op, so tests
exercise the same layout logic through a dense-padded ``all_to_all``
fallback); arithmetic (no sort) regroups the received source-major rows
into tile-padded expert-major segments for the grouped Pallas FFN; the
whole dance then runs in reverse.

All shapes are static upper bounds; ``recv_bound`` defaults to the true
worst case (every token in the ep group routed to one rank).

With ``MoEConfig.a2a_chunks = n`` the exchanges run as a chunked
software pipeline mirroring :mod:`flashmoe_tpu.parallel.ep`: the
local-expert axis splits into ``n`` chunks, each with its own
row-exchange -> regroup -> grouped-FFN -> return-exchange chain over
the chunk's rows only (offsets/sizes derived per chunk from one
all-gathered count matrix).  The chains are independent in the graph,
so chunk ``k+1``'s ragged transfer can overlap chunk ``k``'s FFN.
``None`` (default) keeps the serial schedule bit-identical.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from flashmoe_tpu.config import BLOCK_M, MoEConfig
from flashmoe_tpu.utils.compat import axis_size, shard_map
from flashmoe_tpu.ops import expert as exp
from flashmoe_tpu.ops import ragged as rag
from flashmoe_tpu.ops import stats as st
from flashmoe_tpu.ops import wire as wr
from flashmoe_tpu.ops.gate import router
from flashmoe_tpu.ops.moe import MoEOutput
from flashmoe_tpu.profiler import spans as prof
from flashmoe_tpu.utils.telemetry import trace_span


#: metadata collectives the dense-arm layouts trade beyond the payload
#: exchanges — contract constants the collective census
#: (``analysis.comm_census`` / :mod:`flashmoe_tpu.staticcheck.census`)
#: reconciles against the traced graph: the serial schedule gathers the
#: [D] send sizes and all-to-alls the [D, nLx] count matrix; the chunked
#: schedule replaces both with ONE all_gather of the count matrix.
META_COLLECTIVES_SERIAL = {"all_gather": 1, "all_to_all": 1}
META_COLLECTIVES_CHUNKED = {"all_gather": 1, "all_to_all": 0}


def _row_exchange(arr, *, axis: str, d: int, exchange: str,
                  block_rows: int, out_bound: int,
                  send_offsets, send_sizes, remote_offsets,
                  recv_sizes, recv_offsets):
    """Move ragged row blocks of ``arr`` ([N, W], any W / dtype) between
    ranks.  Rank-local blocks start at ``send_offsets`` with
    ``send_sizes`` rows; block ``p`` lands at ``remote_offsets[p]`` of
    peer ``p``'s ``[out_bound, W]`` output, which locally holds
    ``recv_sizes`` rows per source starting at ``recv_offsets``
    (``recv_offsets`` being the local cumsum view ``remote_offsets``
    describes remotely).  One implementation for both transfer
    directions and for the payload AND the fp8 scale sidecar, so the
    two can never take different routes.

    ``exchange='ragged'`` is the TPU ``ragged_all_to_all``; ``'dense'``
    pads each block to ``block_rows`` rows and compacts after a dense
    ``all_to_all`` (CPU fallback — identical layout logic)."""
    w = arr.shape[1]
    if exchange == "ragged":
        return jax.lax.ragged_all_to_all(
            arr, jnp.zeros((out_bound, w), arr.dtype),
            send_offsets, send_sizes, remote_offsets, recv_sizes,
            axis_name=axis,
        )
    blocks = jnp.zeros((d, block_rows, w), arr.dtype)

    def fill(peer, blocks):
        rows = jax.lax.dynamic_slice(
            jnp.pad(arr, ((0, block_rows), (0, 0))),
            (send_offsets[peer], 0), (block_rows, w),
        )
        mask = (jnp.arange(block_rows) < send_sizes[peer])[:, None]
        return blocks.at[peer].set(jnp.where(mask, rows, 0))

    blocks = jax.lax.fori_loop(0, d, fill, blocks)
    got = jax.lax.all_to_all(
        blocks.reshape(d, 1, block_rows, w), axis, split_axis=0,
        concat_axis=0, tiled=False,
    ).reshape(d, block_rows, w)
    buf = jnp.zeros((out_bound, w), arr.dtype)

    def compact(peer, buf):
        rows = got[peer]
        idx = jnp.where(
            jnp.arange(block_rows) < recv_sizes[peer],
            recv_offsets[peer] + jnp.arange(block_rows),
            out_bound,  # dropped
        )
        return buf.at[idx].set(rows, mode="drop")

    return jax.lax.fori_loop(0, d, compact, buf)


def _wired_row_exchange(arr, wire_dtype, **kw):
    """:func:`_row_exchange` with the wire codec applied at the
    boundary: rows quantize to ``wire_dtype`` before the transfer and
    dequantize after; fp8 per-row scales ride an identical second
    exchange as a [N, 1] column.  ``wire_dtype=None`` is the raw path —
    the exact pre-compression graph."""
    if wire_dtype is None:
        return _row_exchange(arr, **kw)
    payload, scales = wr.encode(arr, wire_dtype)
    payload = _row_exchange(payload, **kw)
    if scales is None:
        return wr.decode(payload, None, arr.dtype)
    scales = _row_exchange(scales[:, None], **kw)
    return wr.decode(payload, scales[:, 0], arr.dtype)


def _pad_rows(arr, out_rows: int):
    """Shape ``arr`` ([N, W]) to exactly ``out_rows`` rows (pad with
    zeros / truncate) — the exchange-elided stand-in for a row transfer
    on the overlap measurement's compute-only leg (the result is
    numerically meaningless, the shapes and every other stage are
    exact)."""
    n = arr.shape[0]
    if n >= out_rows:
        return arr[:out_rows]
    return jnp.pad(arr, ((0, out_rows - n), (0, 0)))


def _regroup_maps(recv_cmat, recv_offsets, recv_sizes, recv_bound: int,
                  block_m: int):
    """Src-major -> tile-padded expert-major scatter targets for one
    (chunk of the) local-expert axis.

    ``recv_cmat`` [D, nE]: rows per (source, local expert in this
    chunk); ``recv_offsets``/``recv_sizes`` [D]: where each source's
    block sits in the chunk's src-major receive buffer.  Returns
    (target [recv_bound], grouped_rows, tile_gid) with the dropped-row
    sentinel at ``grouped_rows`` (strictly out of range for the
    scatter's drop mode)."""
    d, ne = recv_cmat.shape
    etot = jnp.sum(recv_cmat, axis=0)  # [nE]
    epad = ((etot + block_m - 1) // block_m) * block_m
    eseg = (jnp.cumsum(epad) - epad).astype(jnp.int32)  # [nE]
    pre = (jnp.cumsum(recv_cmat, axis=0) - recv_cmat)  # rows before src s
    intra = (jnp.cumsum(recv_cmat, axis=1) - recv_cmat)  # within-src starts

    rows = jnp.arange(recv_bound, dtype=jnp.int32)
    src_of = jnp.clip(
        jnp.searchsorted(
            (recv_offsets + recv_sizes).astype(jnp.int32), rows,
            side="right",
        ).astype(jnp.int32),
        0, d - 1,
    )
    w = rows - recv_offsets[src_of]  # offset within the src block
    cum_intra = jnp.cumsum(recv_cmat, axis=1)  # [D, nE] ends
    e_of = jnp.sum(
        w[:, None] >= cum_intra[src_of], axis=1
    ).astype(jnp.int32)
    e_of = jnp.clip(e_of, 0, ne - 1)
    i_of = w - intra[src_of, e_of]
    total_recv = jnp.sum(recv_sizes)

    # grouped buffer: per-expert tile padding can push targets past
    # recv_bound, so the buffer is recv_bound (tile-rounded) plus one tile
    # per expert, and the dropped-row sentinel is grouped_rows itself —
    # strictly out of range for the scatter's drop mode
    grouped_rows = (
        ((recv_bound + block_m - 1) // block_m) * block_m
        + ne * block_m
    )
    target = jnp.where(
        rows < total_recv,
        eseg[e_of] + pre[src_of, e_of] + i_of,
        grouped_rows,  # out of range -> dropped
    )
    # tile group ids from padded segment ends
    n_tiles = grouped_rows // block_m
    tile_starts = jnp.arange(n_tiles, dtype=jnp.int32) * block_m
    seg_ends = eseg + epad
    tile_gid = jnp.clip(
        jnp.sum(tile_starts[:, None] >= seg_ends[None, :], axis=1),
        0, ne - 1,
    ).astype(jnp.int32)
    return target, grouped_rows, tile_gid, total_recv


def _grouped_ffn(x_grp, tile_gid, weights, cfg: MoEConfig, *,
                 use_pallas: bool, interpret: bool, block_m: int):
    """Grouped expert FFN on a tile-padded expert-major buffer, with
    ``weights`` = (w_up, b_up, w_down, b_down, w_gate-or-None) covering
    exactly the experts ``tile_gid`` indexes (the full local shard, or
    one pipeline chunk's slice)."""
    w_up, b_up, w_down, b_down, w_gate = weights
    if use_pallas:
        # _ad variant: Pallas forward AND Pallas backward (grouped_matmul/
        # tgmm with saved residuals) — the dropless path trains through
        # the kernels too
        return exp.grouped_ffn_ad(
            x_grp, tile_gid,
            w_up.astype(cfg.dtype), b_up,
            w_down.astype(cfg.dtype), b_down,
            w_gate,
            cfg.hidden_act, cfg.gated_ffn, block_m,
            exp.DEFAULT_BLOCK_I, interpret,
        )
    # XLA fallback: per-row weight selection via one-hot (test path)
    ne = w_up.shape[0]
    sel = jax.nn.one_hot(
        jnp.repeat(tile_gid, block_m), ne, dtype=x_grp.dtype
    )  # [rows, nE]
    up_w = jnp.einsum("rn,nhi->rhi", sel, w_up.astype(x_grp.dtype))
    up = jnp.einsum("rh,rhi->ri", x_grp, up_w) + sel @ b_up.astype(x_grp.dtype)
    from flashmoe_tpu.models.reference import activation_fn
    act = activation_fn(cfg.hidden_act)
    if cfg.gated_ffn:
        g_w = jnp.einsum("rn,nhi->rhi", sel,
                         w_gate.astype(x_grp.dtype))
        hid = act(jnp.einsum("rh,rhi->ri", x_grp, g_w)) * up
    else:
        hid = act(up)
    dn_w = jnp.einsum("rn,nih->rih", sel,
                      w_down.astype(x_grp.dtype))
    return (jnp.einsum("ri,rih->rh", hid, dn_w)
            + sel @ b_down.astype(x_grp.dtype))


def _chunked_ragged_exchange(params, xs, cmat, input_offsets,
                             cfg: MoEConfig, *, axis: str, d: int,
                             nlx: int, n_chunks: int, h: int,
                             n_assign: int, recv_bound: int,
                             exchange: str, block_m: int,
                             use_pallas: bool, interpret: bool,
                             wire_disp, wire_comb, w_gate_p,
                             skip_exchange: bool):
    """Chunked double-buffered ragged EP: ``n_chunks`` independent
    row-exchange -> regroup -> grouped-FFN -> return-exchange chains,
    one per local-expert sub-range (the :mod:`flashmoe_tpu.parallel.ep`
    pipeline mirrored onto variable-size transfers).

    One ``all_gather`` of the [dest, local-expert] count matrix replaces
    the serial path's (send-size gather + count a2a): every chunk's
    send/recv offsets and sizes derive from it arithmetically, because a
    chunk's rows are contiguous within each destination block of the
    expert-sorted staging buffer ``xs``.  Returns (ys [n_assign, H] in
    the original expert-sorted layout — the disjoint per-chunk returns
    summed — and the stats-gated combine wire error, or None)."""
    nc = nlx // n_chunks
    my = jax.lax.axis_index(axis)
    # all ranks' count matrices: all_cmat[s, p, le] = rows s sends to
    # dest p for p's local expert le
    all_cmat = jax.lax.all_gather(cmat, axis)  # [D_src, D_dst, nLx]
    # exclusive prefixes along the local-expert axis: where a chunk
    # starts inside each (src, dest) block
    cmat_pre = (jnp.cumsum(cmat, axis=1) - cmat).astype(jnp.int32)
    all_pre = (jnp.cumsum(all_cmat, axis=2) - all_cmat).astype(jnp.int32)
    all_send = jnp.sum(all_cmat, axis=2)  # [D_src, D_dst] totals
    # rank s staged its block for dest p at excl-cumsum over dests
    dest_pre = (jnp.cumsum(all_send, axis=1)
                - all_send).astype(jnp.int32)  # [D_src, D_dst]
    recv_cmat = all_cmat[:, my, :]  # [D_src, nLx] rows sent to me

    ys = jnp.zeros((n_assign, h), xs.dtype)
    comb_err = None
    for ck in range(n_chunks):
        lo = ck * nc
        # -- per-chunk transfer geometry (all arithmetic, no collective)
        send_sizes_c = jnp.sum(
            cmat[:, lo:lo + nc], axis=1).astype(jnp.int32)  # [D]
        send_offsets_c = (input_offsets + cmat_pre[:, lo]).astype(
            jnp.int32)
        all_send_c = jnp.sum(all_cmat[:, :, lo:lo + nc], axis=2)
        recv_sizes_c = all_send_c[:, my].astype(jnp.int32)
        recv_offsets_c = (jnp.cumsum(recv_sizes_c)
                          - recv_sizes_c).astype(jnp.int32)
        out_offsets_c = (
            jnp.cumsum(all_send_c, axis=0) - all_send_c
        )[my].astype(jnp.int32)

        # -- forward rows for this chunk (read straight out of xs: the
        # chunk's rows are contiguous within each dest block)
        with trace_span(f"moe.a2a_dispatch.{ck}"):
            if skip_exchange:
                x_recv_c = _pad_rows(xs, recv_bound)
            else:
                x_recv_c = _wired_row_exchange(
                    xs, wire_disp, axis=axis, d=d, exchange=exchange,
                    block_rows=n_assign, out_bound=recv_bound,
                    send_offsets=send_offsets_c, send_sizes=send_sizes_c,
                    remote_offsets=out_offsets_c,
                    recv_sizes=recv_sizes_c,
                    recv_offsets=recv_offsets_c,
                )
            if cfg.profile_phases:
                prof.fence(x_recv_c)

        # -- regroup + FFN on the chunk's experts only
        rows = jnp.arange(recv_bound, dtype=jnp.int32)
        target, grouped_rows, tile_gid, total_recv = _regroup_maps(
            recv_cmat[:, lo:lo + nc], recv_offsets_c, recv_sizes_c,
            recv_bound, block_m)
        x_grp = jnp.zeros((grouped_rows, h), xs.dtype)
        x_grp = x_grp.at[target].set(x_recv_c, mode="drop")
        with trace_span(f"moe.expert.{ck}"):
            y_grp = _grouped_ffn(
                x_grp, tile_gid,
                (params["w_up"][lo:lo + nc], params["b_up"][lo:lo + nc],
                 params["w_down"][lo:lo + nc],
                 params["b_down"][lo:lo + nc],
                 None if w_gate_p is None else w_gate_p[lo:lo + nc]),
                cfg, use_pallas=use_pallas, interpret=interpret,
                block_m=block_m)
            if cfg.profile_phases:
                prof.fence(y_grp)

        # -- return: back to each source's original staging slots
        y_src_major = y_grp[target.clip(0, grouped_rows - 1)]
        y_src_major = jnp.where(
            (rows < total_recv)[:, None], y_src_major, 0
        ).astype(xs.dtype)
        # rank s staged its chunk-ck rows for me at its dest-block start
        # plus the chunk's intra-block prefix
        rev_out_offsets_c = (dest_pre[:, my]
                             + all_pre[:, my, lo]).astype(jnp.int32)
        if cfg.collect_stats and wire_comb is not None:
            err_k = wr.roundtrip_error(y_src_major, wire_comb)
            comb_err = (err_k if comb_err is None
                        else jnp.maximum(comb_err, err_k))
        with trace_span(f"moe.a2a_combine.{ck}"):
            if skip_exchange:
                ys_c = _pad_rows(y_src_major, n_assign)
            else:
                ys_c = _wired_row_exchange(
                    y_src_major, wire_comb, axis=axis, d=d,
                    exchange=exchange,
                    block_rows=n_assign, out_bound=n_assign,
                    send_offsets=recv_offsets_c, send_sizes=recv_sizes_c,
                    remote_offsets=rev_out_offsets_c,
                    recv_sizes=send_sizes_c,
                    recv_offsets=send_offsets_c,
                )
            if cfg.profile_phases:
                prof.fence(ys_c)
        # chunks return disjoint row ranges (zeros elsewhere): summing
        # reassembles the full expert-sorted ys
        ys = ys + ys_c
    return ys, comb_err


def _ragged_ep_shard(params, x, cfg: MoEConfig, *, axis: str,
                     use_pallas: bool, interpret: bool, exchange: str,
                     block_m: int, reduce_axes,
                     skip_exchange: bool = False):
    d = axis_size(axis)
    s_loc, h = x.shape
    e = cfg.num_experts
    nlx = e // d
    n_assign = s_loc * cfg.expert_top_k
    recv_bound = d * n_assign  # worst case: everyone routes to me
    # quantized expert storage (flashmoe_tpu/quant/): resolve the FFN
    # weight shard to its dequant-in-compute form up front — the
    # chunked pipeline's per-chunk weight slices then slice plain
    # compute arrays (no scale keys left downstream).  Called
    # UNCONDITIONALLY: off returns the dict untouched (bit-identical
    # graph) but a quantized state under a quant-off config is refused
    # instead of matmuling raw payloads (code-review finding).
    from flashmoe_tpu import quant as qt

    quant_err = (qt.weight_quant_error(params, cfg)
                 if cfg.expert_quant is not None and cfg.collect_stats
                 else None)
    params = qt.ffn_compute_params(params, cfg)
    wire_disp = wr.resolve(cfg.wire_dtype)
    wire_comb = wr.resolve(cfg.wire_dtype_combine)
    n_chunks = cfg.a2a_chunks or 1
    if n_chunks > 1 and nlx % n_chunks:
        raise ValueError(
            f"a2a_chunks={n_chunks} does not divide the local-expert "
            f"axis (num_experts={e} // ep={d} = {nlx}); pick a divisor "
            f"or leave a2a_chunks=None for the serial schedule")

    # phase spans mirror parallel/ep.py: named HLO scopes for xprof, and
    # — with cfg.profile_phases — fenced boundaries for the host-side
    # phase timeline (flashmoe_tpu/profiler; fences no-op on tracers,
    # so the traced graph is identical with the knob on or off)
    with trace_span("moe.gate"):
        r = router(x, params["gate_w"], cfg, use_pallas=use_pallas,
                   interpret=interpret)
        if cfg.profile_phases:
            prof.fence(r)

    # ---- local expert-sorted layout (contiguous, unpadded: block "1") ----
    with trace_span("moe.dispatch"):
        plan = rag.make_ragged_plan(r.expert_idx, cfg, 1)
        xs = rag.ragged_dispatch(x.astype(cfg.dtype), plan, cfg, 1)
        xs = xs[:n_assign]  # block_m=1 upper bound equals exact total
        if cfg.profile_phases:
            prof.fence(xs)
    counts = plan.counts  # [E] rows per global expert
    cmat = counts.reshape(d, nlx)  # [dest, local expert]
    send_sizes = jnp.sum(cmat, axis=1).astype(jnp.int32)  # [D]
    input_offsets = (jnp.cumsum(send_sizes) - send_sizes).astype(jnp.int32)

    wire_err = None
    if cfg.collect_stats and wire_disp is not None:
        wire_err = wr.roundtrip_error(xs, wire_disp)

    w_gate_p = params.get("w_gate", None) if cfg.gated_ffn else None

    if n_chunks > 1:
        ys, comb_err = _chunked_ragged_exchange(
            params, xs, cmat, input_offsets, cfg,
            axis=axis, d=d, nlx=nlx, n_chunks=n_chunks, h=h,
            n_assign=n_assign, recv_bound=recv_bound, exchange=exchange,
            block_m=block_m, use_pallas=use_pallas, interpret=interpret,
            wire_disp=wire_disp, wire_comb=wire_comb,
            w_gate_p=w_gate_p, skip_exchange=skip_exchange)
        if comb_err is not None:
            wire_err = (comb_err if wire_err is None
                        else jnp.maximum(wire_err, comb_err))
    else:
        with trace_span("moe.a2a_dispatch"):
            # ---- exchange sizes ----
            # all ranks' send matrices: S[s, d] = rows s sends to d
            all_send = jax.lax.all_gather(send_sizes, axis)  # [D, D]
            my = jax.lax.axis_index(axis)
            recv_sizes = all_send[:, my].astype(jnp.int32)  # [D] per src
            recv_offsets = (jnp.cumsum(recv_sizes)
                            - recv_sizes).astype(jnp.int32)
            # where my block starts on each destination = earlier sources
            out_offsets = (
                jnp.cumsum(all_send, axis=0) - all_send
            )[my].astype(jnp.int32)  # [D]
            # per-(src, my local expert) counts, for regrouping
            recv_cmat = jax.lax.all_to_all(
                cmat.reshape(d, 1, nlx), axis, split_axis=0,
                concat_axis=0, tiled=False,
            ).reshape(d, nlx)

            # ---- forward data exchange: src-major ragged layout ----
            if skip_exchange:
                x_recv = _pad_rows(xs, recv_bound)
            else:
                x_recv = _wired_row_exchange(
                    xs, wire_disp, axis=axis, d=d, exchange=exchange,
                    block_rows=n_assign, out_bound=recv_bound,
                    send_offsets=input_offsets, send_sizes=send_sizes,
                    remote_offsets=out_offsets, recv_sizes=recv_sizes,
                    recv_offsets=recv_offsets,
                )
            if cfg.profile_phases:
                prof.fence(x_recv)

        with trace_span("moe.expert"):
            # ---- regroup src-major -> tile-padded expert-major ----
            rows = jnp.arange(recv_bound, dtype=jnp.int32)
            target, grouped_rows, tile_gid, total_recv = _regroup_maps(
                recv_cmat, recv_offsets, recv_sizes, recv_bound, block_m)
            x_grp = jnp.zeros((grouped_rows, h), xs.dtype)
            x_grp = x_grp.at[target].set(x_recv, mode="drop")

            # ---- expert FFN on the local shard of weights ----
            y_grp = _grouped_ffn(
                x_grp, tile_gid,
                (params["w_up"], params["b_up"], params["w_down"],
                 params["b_down"], w_gate_p),
                cfg, use_pallas=use_pallas, interpret=interpret,
                block_m=block_m)
            if cfg.profile_phases:
                prof.fence(y_grp)

        with trace_span("moe.a2a_combine"):
            # ---- return path: expert-major -> src-major -> ragged back
            y_src_major = y_grp[target.clip(0, grouped_rows - 1)]
            y_src_major = jnp.where(
                (rows < total_recv)[:, None], y_src_major, 0
            ).astype(xs.dtype)

            # returned rows must land where the source originally staged
            # them: on rank s that's s's input_offsets[my] = exclusive
            # row-cumsum of its send sizes — from the gathered matrix
            rev_out_offsets = (
                jnp.cumsum(all_send, axis=1) - all_send
            )[:, my].astype(jnp.int32)
            if cfg.collect_stats and wire_comb is not None:
                comb_err = wr.roundtrip_error(y_src_major, wire_comb)
                wire_err = (comb_err if wire_err is None
                            else jnp.maximum(wire_err, comb_err))
            if skip_exchange:
                ys = _pad_rows(y_src_major, n_assign)
            else:
                ys = _wired_row_exchange(
                    y_src_major, wire_comb, axis=axis, d=d,
                    exchange=exchange,
                    block_rows=n_assign, out_bound=n_assign,
                    send_offsets=recv_offsets, send_sizes=recv_sizes,
                    remote_offsets=rev_out_offsets, recv_sizes=send_sizes,
                    recv_offsets=input_offsets,
                )
            if cfg.profile_phases:
                prof.fence(ys)

    # ---- combine in the original expert-sorted layout ----
    with trace_span("moe.combine"):
        healthy = None
        combine_w = r.combine_weights
        if cfg.degrade_unhealthy_experts:
            # tier-0 (ops/health.py): ys is expert-sorted by GLOBAL
            # expert with per-expert row counts in plan.counts (block-1
            # layout: padded == exact), so segment health maps rows ->
            # experts; the ragged combine does not renormalize, so the
            # mask does
            from flashmoe_tpu.ops import health as hlt

            healthy = hlt.expert_health_segments(ys, plan.counts)
            ys, combine_w = hlt.degrade_outputs(
                ys, combine_w, r.expert_idx, healthy, renormalize=True)
        out = rag.ragged_combine(ys, plan, combine_w, cfg)
        if cfg.profile_phases:
            prof.fence(out)

    aux = jax.lax.pmean(r.aux_loss, reduce_axes) * cfg.aux_loss_coef
    z = jax.lax.pmean(r.z_loss, reduce_axes)
    cnts = jax.lax.psum(r.expert_counts, reduce_axes)
    stats = None
    if cfg.collect_stats:
        # dropless: capacity=None reports zero drops / full utilization
        local = st.moe_stats(r, cfg, None)
        stats = st.reduce_stats(local, r.probs_mean, reduce_axes)
        if healthy is not None:
            from flashmoe_tpu.ops import health as hlt

            stats = hlt.attach_degradation(stats, healthy, r.expert_idx,
                                           reduce_axes)
        if wire_err is not None:
            stats = st.with_wire_error(stats, wire_err, reduce_axes)
        if quant_err is not None:
            stats = st.with_quant_error(stats, quant_err, reduce_axes)
    return MoEOutput(out.astype(cfg.dtype), aux, z, cnts, stats)


def decode_moe_rows(params, x, cfg: MoEConfig, *, axis: str = "ep",
                    exchange: str | None = None,
                    block_m: int = BLOCK_M) -> MoEOutput:
    """Run the ragged EP MoE on LOCAL batch rows from inside an
    ENCLOSING ``shard_map`` — the serving engine's EP-sharded decode
    step, where the caller already owns the mesh and this layer is one
    stage of a larger sharded body (attention + paged KV around it).

    ``params`` are the local expert shard (``gate_w`` replicated);
    ``x``: ``[b_local, H]`` decode rows.  Decode batches are
    token-count-tiny, so the XLA grouped path (no Pallas) is always the
    right arm here, exactly as in the unsharded decode step."""
    if cfg.num_shared_experts:
        raise NotImplementedError("shared experts stay outside this layer")
    if exchange is None:
        exchange = "ragged" if jax.default_backend() == "tpu" else "dense"
    return _ragged_ep_shard(
        params, x, cfg, axis=axis, use_pallas=False, interpret=False,
        exchange=exchange, block_m=block_m, reduce_axes=(axis,))


def ragged_ep_moe_layer(params, x, cfg: MoEConfig, mesh: Mesh, *,
                        use_pallas: bool = False, interpret: bool = False,
                        exchange: str | None = None,
                        block_m: int = BLOCK_M,
                        token_axes: tuple[str, ...] = ("ep",),
                        skip_exchange: bool = False) -> MoEOutput:
    """Dropless expert-parallel MoE over the ``ep`` axis.

    ``exchange``: "ragged" (TPU ``ragged_all_to_all``) or "dense" (padded
    ``all_to_all`` fallback — same layout logic, used on backends without
    the ragged op).  Default picks by backend.

    ``skip_exchange`` elides the row transfers (metadata collectives
    stay) while keeping every other stage and shape — the compute-only
    leg of the overlap measurement (:mod:`flashmoe_tpu.parallel.overlap`);
    the result is numerically meaningless.
    """
    if cfg.num_shared_experts:
        raise NotImplementedError("shared experts stay outside this layer")
    if exchange is None:
        exchange = "ragged" if jax.default_backend() == "tpu" else "dense"

    body = functools.partial(
        _ragged_ep_shard, cfg=cfg, axis="ep", use_pallas=use_pallas,
        interpret=interpret, exchange=exchange, block_m=block_m,
        reduce_axes=token_axes, skip_exchange=skip_exchange,
    )
    pspecs = {k: P("ep") if k != "gate_w" else P() for k in params}
    stats_specs = (st.MoEStats(*([P()] * len(st.MoEStats._fields)))
                   if cfg.collect_stats else None)
    fn = shard_map(
        body, mesh=mesh,
        in_specs=(pspecs, P(token_axes, None)),
        out_specs=MoEOutput(P(token_axes, None), P(), P(), P(),
                            stats_specs),
        check_vma=False,
    )
    return fn(params, x)
