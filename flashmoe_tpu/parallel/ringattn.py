"""Ring attention: sequence/context parallelism over the ``sp`` mesh axis.

The reference has no attention and no sequence parallelism (SURVEY §2.6 —
its only ring algorithm is an intra-GPU block-ring over the *expert*
dimension in the gate).  Long context is first-class in this framework, so
this module implements ring attention (Liu et al.) the TPU way: each sp
rank holds a sequence shard of q/k/v; kv shards rotate around the ring via
``jax.lax.ppermute`` (XLA lowers this to ICI neighbour transfers), and each
rank folds every arriving kv block into its queries' online-softmax
accumulator (the same (m, l, acc) recursion as the flash kernel in
:mod:`flashmoe_tpu.ops.attention`).  XLA overlaps the next ppermute with
the current block's compute automatically (async collective + latency-
hiding scheduler).

Causal masking works on global positions: rank r's queries start at
``r * T_loc``; the kv shard arriving at step s originated at rank
``(r - s) mod D``.  Blocks wholly above the diagonal are skipped via a
zero contribution (static control flow, no dynamic shapes).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
from flashmoe_tpu.utils.compat import axis_size, shard_map

from flashmoe_tpu.ops.attention import NEG_INF


def _block_attn(q, k, v, q_off, kv_off, scale, causal):
    """One (q-shard, kv-shard) partial: returns (m, l, o_unnormalized)."""
    s = jnp.einsum(
        "bntd,bnsd->bnts", q, k, preferred_element_type=jnp.float32
    ) * scale
    if causal:
        tq, tk = q.shape[2], k.shape[2]
        qi = jnp.arange(tq)[:, None] + q_off
        ki = jnp.arange(tk)[None, :] + kv_off
        s = jnp.where((qi >= ki)[None, None], s, NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)  # [B, N, Tq, 1]
    # fully-masked rows: exp(NEG_INF - NEG_INF) would give 1s; clamp m
    m_safe = jnp.maximum(m, NEG_INF / 2)
    p = jnp.exp(s - m_safe)
    p = jnp.where(s <= NEG_INF, 0.0, p)
    l = jnp.sum(p, axis=-1, keepdims=True)
    o = jnp.einsum(
        "bnts,bnsd->bntd", p.astype(v.dtype), v,
        preferred_element_type=jnp.float32,
    )
    return m_safe, l, o


def _ring_shard(q, k, v, *, axis, scale, causal):
    """Per-rank body. q/k/v: [B, N, T_loc, D] local shards."""
    d_world = axis_size(axis)
    my = jax.lax.axis_index(axis)
    t_loc = q.shape[2]
    q_off = my * t_loc

    m_run = jnp.full(q.shape[:3] + (1,), NEG_INF, jnp.float32)
    l_run = jnp.zeros(q.shape[:3] + (1,), jnp.float32)
    acc = jnp.zeros(q.shape, jnp.float32)

    def step(s, carry):
        m_run, l_run, acc, k_cur, v_cur = carry
        src = jax.lax.rem(my - s + d_world, d_world)
        kv_off = src * t_loc
        m_blk, l_blk, o_blk = _block_attn(
            q, k_cur, v_cur, q_off, kv_off, scale, causal
        )
        m_new = jnp.maximum(m_run, m_blk)
        a_run = jnp.exp(m_run - m_new)
        a_blk = jnp.exp(m_blk - m_new)
        l_new = l_run * a_run + l_blk * a_blk
        acc_new = acc * a_run + o_blk * a_blk
        # rotate kv to the next rank (ring: receive from my-1 direction)
        perm = [(i, (i + 1) % d_world) for i in range(d_world)]
        k_nxt = jax.lax.ppermute(k_cur, axis, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis, perm)
        return m_new, l_new, acc_new, k_nxt, v_nxt

    # static unroll over ring steps (D is a mesh constant) so XLA can
    # overlap each step's ppermute with the next block's compute
    carry = (m_run, l_run, acc, k, v)
    for s in range(d_world):
        carry = step(s, carry)
    m_run, l_run, acc, _, _ = carry
    return (acc / jnp.maximum(l_run, 1e-30)).astype(q.dtype)


def ring_attention(q, k, v, mesh: Mesh, *, axis: str = "sp",
                   causal: bool = True, scale: float | None = None):
    """Ring attention over the sequence axis.

    q/k/v: [B, N, T, D] global; T shards over ``axis``.  Returns [B, N, T, D].
    """
    dd = q.shape[-1]
    scale = scale if scale is not None else dd ** -0.5
    body = functools.partial(_ring_shard, axis=axis, scale=scale,
                             causal=causal)
    fn = shard_map(
        body, mesh=mesh,
        in_specs=(P(None, None, axis, None),) * 3,
        out_specs=P(None, None, axis, None),
        check_vma=False,
    )
    return fn(q, k, v)
